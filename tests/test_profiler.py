"""Continuous CPU profiler + trace export.

Ref model: library/ytprof/cpu_profiler.h (timer-driven stack sampling
into pprof) and library/tracing/jaeger/tracer.h (batched span flush to
an agent).
"""

import json
import threading
import time

import pytest

from ytsaurus_tpu.utils.profiler import (
    SamplingProfiler,
    TraceExporter,
    jsonl_sink,
)
from ytsaurus_tpu.utils.tracing import TraceContext, get_collector


def _busy_function_alpha(stop):
    while not stop.is_set():
        sum(i * i for i in range(500))


def test_sampler_finds_the_hot_function():
    stop = threading.Event()
    worker = threading.Thread(target=_busy_function_alpha, args=(stop,),
                              daemon=True)
    worker.start()
    profiler = SamplingProfiler(interval=0.005).start()
    time.sleep(0.8)
    profiler.stop()
    stop.set()
    worker.join(timeout=5)
    state = profiler.state()
    assert state["total_samples"] > 20
    flat = "\n".join(profiler.collapsed())
    assert "_busy_function_alpha" in flat
    hotspots = profiler.hotspots()
    assert hotspots and abs(sum(h["share"] for h in hotspots)) <= 1.01
    assert any("_busy_function_alpha" in h["frame"] or
               "<genexpr>" in h["frame"] for h in hotspots)


def test_sampler_reset_and_bounds():
    profiler = SamplingProfiler(interval=0.005, max_entries=3)
    for _ in range(10):
        profiler.sample_once()
    assert profiler.state()["distinct_stacks"] <= 3
    profiler.reset()
    assert profiler.state()["total_samples"] == 0


def test_trace_exporter_flushes_batches(tmp_path):
    collector = get_collector()
    collector.drain()                       # isolate from other tests
    path = str(tmp_path / "traces.jsonl")
    exporter = TraceExporter(jsonl_sink(path), flush_interval=60,
                             collector=collector)
    with TraceContext("op.parent") as parent:
        with parent.create_child("op.child"):
            time.sleep(0.01)
    n = exporter.flush_once()
    assert n == 2
    lines = [json.loads(line) for line in open(path)]
    names = {line["name"] for line in lines}
    assert names == {"op.parent", "op.child"}
    traces = {line["trace_id"] for line in lines}
    assert len(traces) == 1                 # one trace, two spans
    assert exporter.stats == {"batches": 1, "spans": 2}
    # Nothing new → no batch.
    assert exporter.flush_once() == 0


def test_trace_exporter_background_loop(tmp_path):
    collector = get_collector()
    collector.drain()
    path = str(tmp_path / "bg.jsonl")
    exporter = TraceExporter(jsonl_sink(path), flush_interval=0.1,
                             collector=collector)
    exporter.start()
    with TraceContext("bg.span"):
        pass
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and exporter.stats["spans"] < 1:
        time.sleep(0.05)
    exporter.stop()
    assert exporter.stats["spans"] >= 1
    assert any("bg.span" in line for line in open(path))
