"""Ecosystem engines over the core query stack (SURVEY §2.11 analogs):
ANSI/ClickHouse-flavored SQL (CHYT analog) translating onto the native QL
engine, served through the query tracker's engine registry."""
