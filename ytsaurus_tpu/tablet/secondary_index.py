"""Secondary indexes: index tables maintained on write, used by queries.

Ref mapping:
  secondary index objects + index tables   → create_secondary_index builds
  (library/query/secondary_index,            an index table keyed by
  server/master/table_server)                (index columns..., source key
                                             columns...) with an $empty
                                             payload column
  index maintenance on tablet writes       → index rows join the SAME 2PC
  (sorted_store_manager index updates)       transaction as the source
                                             write: stale entries deleted,
                                             fresh ones inserted, using the
                                             pre-write row images
  predicate rewrite                        → select_rows consults
  (secondary_index/schema.cpp rewriter)      WHERE-derived column intervals
                                             (query/pruning.py) and serves
                                             the scan from index + lookup
                                             when the index prefix is
                                             bounded

Design delta: the rewrite happens at coordination time, not in the IR —
the index produces the exact source-key set, the source rows are fetched
via the vectorized lookup path, and the ORIGINAL plan runs unchanged over
that small rowset (so every query feature works over indexed scans).
"""

from __future__ import annotations

from typing import Optional, Sequence

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.schema import TableSchema

INDEXES_ATTR = "secondary_indexes"
EMPTY_COLUMN = "$empty"


def index_schema(source_schema: TableSchema,
                 index_columns: Sequence[str]) -> TableSchema:
    """Index table schema: (index columns..., source keys...) -> $empty."""
    cols: list = []
    seen = set()
    for name in index_columns:
        col = source_schema.find(name)
        if col is None:
            raise YtError(f"No such column {name!r} to index",
                          code=EErrorCode.QueryTypeError)
        if col.type.value == "any":
            raise YtError(f"Cannot index `any` column {name!r}",
                          code=EErrorCode.QueryUnsupported)
        cols.append((name, col.type.value, "ascending"))
        seen.add(name)
    for col in source_schema.key_columns:
        if col.name not in seen:
            cols.append((col.name, col.type.value, "ascending"))
    cols.append((EMPTY_COLUMN, "int64"))
    return TableSchema.make(cols, unique_keys=True)


def index_descriptors(node) -> dict:
    return dict(node.attributes.get(INDEXES_ATTR) or {})


def index_key_row(desc: dict, source_key_names: Sequence[str],
                  row: dict) -> dict:
    out = {}
    for name in desc["columns"]:
        out[name] = row.get(name)
    for name in source_key_names:
        out[name] = row.get(name)
    return out


def index_key_tuple(desc: dict, source_key_names: Sequence[str],
                    row: dict) -> tuple:
    ordered = list(desc["columns"]) + [
        n for n in source_key_names if n not in set(desc["columns"])]
    return tuple(row.get(n) for n in ordered)


def create_secondary_index(client, table_path: str, index_path: str,
                           columns: Sequence[str]) -> None:
    """Create + backfill an index table and register it on the source
    (ref: secondary index creation; backfill replaces the reference's
    online index build for existing rows)."""
    node = client._table_node(table_path)
    schema = client._node_schema(node)
    if schema is None or not schema.is_sorted or \
            not node.attributes.get("dynamic"):
        raise YtError("Secondary indexes require a sorted dynamic table",
                      code=EErrorCode.QueryUnsupported)
    columns = list(columns)
    if not columns:
        raise YtError("Secondary index needs at least one column",
                      code=EErrorCode.QueryTypeError)
    ischema = index_schema(schema, columns)
    client.create("table", index_path, recursive=True,
                  attributes={"schema": ischema, "dynamic": True,
                              "index_source": table_path})
    client.mount_table(index_path)
    # Backfill from the current committed state.
    key_names = schema.key_column_names
    desc = {"columns": columns, "path": index_path}
    existing = client._select_rows_system(
        ", ".join(c.name for c in schema) + f" FROM [{table_path}]")
    if existing:
        client.insert_rows(index_path, [
            dict(index_key_row(desc, key_names, row), **{EMPTY_COLUMN: 0})
            for row in existing])
    indexes = index_descriptors(node)
    indexes[index_path] = {"columns": columns}
    client.set(table_path + "/@" + INDEXES_ATTR, indexes)


def drop_secondary_index(client, table_path: str, index_path: str,
                         remove_table: bool = True) -> None:
    node = client._table_node(table_path)
    indexes = index_descriptors(node)
    if index_path not in indexes:
        raise YtError(f"No index {index_path!r} on {table_path!r}",
                      code=EErrorCode.ResolveError)
    del indexes[index_path]
    client.set(table_path + "/@" + INDEXES_ATTR, indexes)
    if remove_table:
        client.unmount_table(index_path)
        client.remove(index_path)


def record_index_intent(client, tx, path, node, schema,
                        new_rows: Optional[list],
                        deleted_keys: Optional[list],
                        update: bool) -> None:
    """Record a source-table modification for deferred index maintenance.

    Index mutations are computed at COMMIT time from the NET change
    (pre-transaction committed image → final image): staging per-write
    would emit a delete and a write of the SAME index key at one commit
    timestamp when a transaction rewrites a row twice, which MVCC cannot
    order.  The reference gets the same effect from its ordered row locks;
    here the transaction carries intents and finalize computes the net.
    """
    if not index_descriptors(node):
        return
    intents = getattr(tx, "index_intents", None)
    if intents is None:
        intents = tx.index_intents = []
    intents.append((path, new_rows, deleted_keys, update))


def finalize_index_mutations(client, txm, tx) -> None:
    """Stage the NET index mutations for every intent recorded under this
    transaction.  Called once, right before commit."""
    intents = getattr(tx, "index_intents", None)
    if not intents:
        return
    tx.index_intents = []          # idempotent under retry
    # path → {normalized source key: (raw key, committed_row, final_row)}
    net: dict = {}
    for path, new_rows, deleted_keys, update in intents:
        node = client._table_node(path)
        schema = client._node_schema(node)
        key_names = schema.key_column_names
        norm = client._mounted_tablets(path)[0].normalize_key
        per_path = net.setdefault(path, {})
        if deleted_keys is not None:
            items = [(tuple(k), None) for k in deleted_keys]
        else:
            items = [(tuple(r.get(n) for n in key_names), dict(r))
                     for r in new_rows]
        keys = [k for k, _ in items]
        need_committed = [k for k in keys if norm(k) not in per_path]
        # System path: this runs on the WRITE commit path and must not
        # queue behind (or deadlock inside) user read admission.
        committed = client._lookup_rows_direct(path, need_committed) \
            if need_committed else []
        for k, row in zip(need_committed, committed):
            per_path[norm(k)] = (k, row, row)
        for k, new in items:
            raw, committed_row, image = per_path[norm(k)]
            if new is None:
                image = None
            elif update and image is not None:
                merged = dict(image)
                merged.update(new)
                image = merged
            else:
                image = new
            per_path[norm(k)] = (raw, committed_row, image)
    for path, per_path in net.items():
        node = client._table_node(path)
        schema = client._node_schema(node)
        key_names = schema.key_column_names
        for index_path, desc in index_descriptors(node).items():
            desc = dict(desc, path=index_path)
            index_tablets = client._mounted_tablets(index_path)
            norm = index_tablets[0].normalize_key
            to_delete: list[tuple] = []
            to_write: list[dict] = []
            for raw, old, final in per_path.values():
                if final is None:
                    if old is not None:
                        to_delete.append(
                            index_key_tuple(desc, key_names, old))
                    continue
                if old is not None:
                    old_key = index_key_tuple(desc, key_names, old)
                    new_key = index_key_tuple(desc, key_names, final)
                    # Normalized compare: str vs bytes images of one key.
                    if norm(old_key) != norm(new_key):
                        to_delete.append(old_key)
                to_write.append(
                    dict(index_key_row(desc, key_names, final),
                         **{EMPTY_COLUMN: 0}))
            if to_delete:
                for idx, part in client._route_rows(
                        index_path, index_tablets, to_delete).items():
                    txm.delete_rows(tx, index_tablets[idx], part)
            if to_write:
                for idx, part in client._route_rows(
                        index_path, index_tablets, to_write).items():
                    txm.write_rows(tx, index_tablets[idx], part)


def _bounded(iv) -> bool:
    from ytsaurus_tpu.query.pruning import _NEG_INF, _POS_INF
    return iv is not None and not (iv.lo is _NEG_INF and iv.hi is _POS_INF)


def pick_index(node, intervals: dict) -> Optional[dict]:
    """Choose an index whose FIRST column is bounded by the WHERE-derived
    intervals (the rewriter's applicability rule)."""
    for index_path, desc in index_descriptors(node).items():
        if _bounded(intervals.get(desc["columns"][0])):
            return dict(desc, path=index_path)
    return None


def _ql_literal(value) -> Optional[str]:
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        return repr(value)
    if isinstance(value, bytes):
        try:
            value = value.decode("utf-8")
        except UnicodeDecodeError:
            return None
    if isinstance(value, str):
        escaped = value.replace("\\", "\\\\").replace("'", "\\'")
        return f"'{escaped}'"
    return None


def _interval_predicate(column: str, iv) -> Optional[str]:
    from ytsaurus_tpu.query.pruning import _NEG_INF, _POS_INF
    parts = []
    if iv.lo is not _NEG_INF:
        lit = _ql_literal(iv.lo)
        if lit is None:
            return None
        parts.append(f"{column} {'>=' if iv.lo_incl else '>'} {lit}")
    if iv.hi is not _POS_INF:
        lit = _ql_literal(iv.hi)
        if lit is None:
            return None
        parts.append(f"{column} {'<=' if iv.hi_incl else '<'} {lit}")
    return " AND ".join(parts) if parts else None


def fetch_via_index(client, table_path: str, schema, desc: dict,
                    intervals: dict, timestamp) -> Optional[list[dict]]:
    """Index scan → source-key set → vectorized source lookup.  Returns
    None when the bound cannot be expressed (caller falls back to scan)."""
    key_names = schema.key_column_names
    first = desc["columns"][0]
    predicate = _interval_predicate(first, intervals[first])
    if predicate is None:
        return None
    index_cols = ", ".join(
        list(desc["columns"]) +
        [n for n in key_names if n not in set(desc["columns"])])
    # The index table is keyed by the indexed columns, so the bound lands
    # on its key prefix (range pruning); the caller's plan re-applies the
    # full WHERE over the fetched rows.
    # System path: fetch_via_index runs INSIDE an already-admitted
    # select — re-entering admission here could deadlock a saturated
    # pool (every slot holder waiting for one more slot).
    index_rows = client._select_rows_system(
        f"{index_cols} FROM [{desc['path']}] WHERE {predicate}",
        timestamp=timestamp)
    # Dedup: duplicated index entries (or several matching index rows per
    # source key) must not duplicate source rows.
    keys = list(dict.fromkeys(
        tuple(r[n] for n in key_names) for r in index_rows))
    if not keys:
        return []
    rows = client._lookup_rows_direct(table_path, keys,
                                      timestamp=timestamp)
    return [r for r in rows if r is not None]
