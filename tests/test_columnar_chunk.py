"""Columnar chunk tests (ref model: ytlib/columnar_chunk_format)."""

import numpy as np

from ytsaurus_tpu import EValueType, TableSchema
from ytsaurus_tpu.chunks import ColumnarChunk, concat_chunks, pad_capacity


def test_pad_capacity_buckets():
    assert pad_capacity(1) == 128
    assert pad_capacity(128) == 128
    assert pad_capacity(129) == 256
    assert pad_capacity(1000) == 1024


SCHEMA = TableSchema.make([
    ("k", "int64", "ascending"),
    ("v", "double"),
    ("s", "string"),
    ("b", "boolean"),
])


def test_from_rows_roundtrip():
    rows = [
        {"k": 1, "v": 1.5, "s": "foo", "b": True},
        {"k": 2, "v": None, "s": "bar", "b": False},
        {"k": 3, "v": -2.25, "s": None, "b": None},
    ]
    chunk = ColumnarChunk.from_rows(SCHEMA, rows)
    assert chunk.row_count == 3
    assert chunk.capacity == 128
    out = chunk.to_rows()
    assert out[0] == {"k": 1, "v": 1.5, "s": b"foo", "b": True}
    assert out[1]["v"] is None and out[1]["s"] == b"bar"
    assert out[2]["s"] is None and out[2]["b"] is None


def test_string_dictionary_order_preserving():
    rows = [{"k": i, "v": None, "s": s, "b": None}
            for i, s in enumerate(["zeta", "alpha", "midway", "alpha"])]
    chunk = ColumnarChunk.from_rows(SCHEMA, rows)
    col = chunk.column("s")
    codes = np.asarray(col.data[:4])
    # alpha < midway < zeta; equal strings share a code
    assert codes[1] == codes[3]
    assert codes[1] < codes[2] < codes[0]
    assert list(col.dictionary) == [b"alpha", b"midway", b"zeta"]


def test_tuple_rows_and_uint64():
    schema = TableSchema.make([("u", "uint64"), ("i", "int64")])
    big = 2**63 + 5
    chunk = ColumnarChunk.from_rows(schema, [(big, -7), (0, None)])
    rows = chunk.to_rows()
    assert rows[0]["u"] == big
    assert rows[0]["i"] == -7
    assert rows[1]["i"] is None


def test_concat_chunks_unifies_dictionaries():
    a = ColumnarChunk.from_rows(SCHEMA, [
        {"k": 1, "v": 1.0, "s": "bb", "b": True}])
    b = ColumnarChunk.from_rows(SCHEMA, [
        {"k": 2, "v": 2.0, "s": "aa", "b": False},
        {"k": 3, "v": 3.0, "s": "bb", "b": True}])
    merged = concat_chunks([a, b])
    assert merged.row_count == 3
    rows = merged.to_rows()
    assert [r["s"] for r in rows] == [b"bb", b"aa", b"bb"]
    col = merged.column("s")
    codes = np.asarray(col.data[:3])
    assert codes[0] == codes[2] and codes[1] < codes[0]


def test_slice_rows():
    rows = [{"k": i, "v": float(i), "s": str(i), "b": i % 2 == 0}
            for i in range(10)]
    chunk = ColumnarChunk.from_rows(SCHEMA, rows)
    part = chunk.slice_rows(3, 7)
    assert part.row_count == 4
    assert [r["k"] for r in part.to_rows()] == [3, 4, 5, 6]


def test_from_arrays_fast_path():
    schema = TableSchema.make([("x", "int64"), ("y", "double")])
    n = 1000
    chunk = ColumnarChunk.from_arrays(
        schema,
        {"x": np.arange(n), "y": np.linspace(0, 1, n)})
    assert chunk.row_count == n
    assert chunk.capacity == 1024
    assert np.asarray(chunk.column("x").data[:5]).tolist() == [0, 1, 2, 3, 4]


def test_any_column_roundtrip():
    schema = TableSchema.make([("k", "int64"), ("a", "any")])
    rows = [{"k": 1, "a": {"x": 1}}, {"k": 2, "a": [1, 2, 3]}, {"k": 3, "a": None}]
    chunk = ColumnarChunk.from_rows(schema, rows)
    out = chunk.to_rows()
    assert out[0]["a"] == {"x": 1}
    assert out[1]["a"] == [1, 2, 3]
    assert out[2]["a"] is None
    merged = concat_chunks([chunk, ColumnarChunk.from_rows(schema, [{"k": 4, "a": "s"}])])
    assert merged.to_rows()[3]["a"] == "s"


def test_concat_schema_mismatch_rejected():
    import pytest
    from ytsaurus_tpu import YtError
    a = ColumnarChunk.from_rows(TableSchema.make([("k", "int64")]), [(1,)])
    b = ColumnarChunk.from_rows(TableSchema.make([("k", "double")]), [(1.5,)])
    with pytest.raises(YtError):
        concat_chunks([a, b])


def test_strict_schema_rejects_unknown_columns():
    import pytest
    from ytsaurus_tpu import YtError
    schema = TableSchema.make([("k", "int64")])
    with pytest.raises(YtError):
        ColumnarChunk.from_rows(schema, [{"k": 1, "junk": 2}])
    loose = TableSchema.make([("k", "int64")], strict=False)
    chunk = ColumnarChunk.from_rows(loose, [{"k": 1, "junk": 2}])
    assert chunk.to_rows() == [{"k": 1}]
