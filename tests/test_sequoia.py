"""Sequoia groundwork: the resolve ground-table stays consistent with
the master tree through the mutation stream (ref sequoia_server +
sequoia_client ground tables)."""

import pytest

from ytsaurus_tpu.client import connect
from ytsaurus_tpu.cypress.sequoia import RESOLVE_PATH, SequoiaResolver


@pytest.fixture
def resolver(tmp_path):
    client = connect(str(tmp_path / "c"))
    client.create("map_node", "//pre/existing", recursive=True)
    return client, SequoiaResolver(client).enable()


def test_bootstrap_full_sync(resolver):
    client, seq = resolver
    hit = seq.resolve("//pre/existing")
    assert hit is not None
    assert hit["node_type"] == "map_node"
    assert seq.verify() == []


def test_mutations_maintain_resolve_table(resolver):
    client, seq = resolver
    client.create("document", "//a/b/c", recursive=True)
    assert seq.resolve("//a/b/c")["node_type"] == "document"
    # Recursive creates materialize ancestor records too.
    assert seq.resolve("//a")["node_type"] == "map_node"
    assert seq.resolve("//a/b")["node_type"] == "map_node"

    client.write_table("//a/t", [{"x": 1}])
    assert seq.resolve("//a/t")["node_type"] == "table"

    client.copy("//a", "//a2", recursive=True)
    assert seq.resolve("//a2/b/c") is not None
    client.move("//a2", "//a3")
    assert seq.resolve("//a2") is None
    assert seq.resolve("//a3/b/c") is not None

    client.remove("//a")
    assert seq.resolve("//a") is None
    assert seq.resolve("//a/b/c") is None
    assert seq.verify() == []


def test_resolve_matches_tree_ids(resolver):
    client, seq = resolver
    client.create("document", "//idcheck", recursive=True)
    node = client.cluster.master.tree.resolve("//idcheck")
    assert seq.resolve("//idcheck")["node_id"] == node.id


def test_verify_detects_and_full_sync_repairs(resolver):
    client, seq = resolver
    client.create("document", "//d/x", recursive=True)
    assert seq.verify() == []
    # Sabotage: drop one record behind the maintainer's back.
    client.delete_rows(RESOLVE_PATH, [("//d/x",)])
    assert "//d/x" in seq.verify()
    seq.full_sync()
    assert seq.verify() == []
    assert seq.resolve("//d/x") is not None


def test_resolve_excludes_own_subtree(resolver):
    client, seq = resolver
    # The resolve table does not mirror itself (no recursion).
    assert seq.resolve(RESOLVE_PATH) is None
    assert all(not p.startswith("//sys/sequoia") for p in seq.verify())


def test_set_creates_and_replaces_children(resolver):
    client, seq = resolver
    # set can CREATE a node outright...
    client.set("//brandnew", 5)
    assert seq.resolve("//brandnew") is not None
    # ...and replace a map_node's entire child set.
    client.create("document", "//m/old", recursive=True)
    client.set("//m", {"fresh": 1})
    assert seq.resolve("//m/old") is None
    assert seq.resolve("//m/fresh") is not None
    assert seq.verify() == []


def test_tx_abort_resyncs(resolver):
    client, seq = resolver
    tx = client.start_tx()
    client.create("document", "//txnode", recursive=True, tx=tx)
    assert seq.resolve("//txnode") is not None
    client.abort_tx(tx)
    assert seq.resolve("//txnode") is None      # no phantom node
    assert seq.verify() == []


def test_links_resolve_consistently(resolver):
    """Rows record the RAW node: a link row is the link itself (type
    'link'), so the incremental path, full_sync, and verify agree — and
    removing the TARGET never strands the link's row."""
    client, seq = resolver
    client.create("document", "//tgt", recursive=True)
    client.link("//tgt", "//lnk")
    link_id = client.cluster.master.tree.resolve(
        "//lnk", follow_links=False).id
    hit = seq.resolve("//lnk")
    assert hit == {"node_id": link_id, "node_type": "link"}
    assert seq.verify() == []
    seq.full_sync()
    assert seq.resolve("//lnk") == hit
    assert seq.verify() == []
    # Target removal: the link row stays valid (it records the link).
    client.remove("//tgt")
    assert seq.resolve("//lnk") == hit
    assert seq.verify() == []


def test_noncanonical_paths_share_one_row(resolver):
    client, seq = resolver
    client.create("document", "//x//y", recursive=True)
    assert seq.resolve("//x/y") is not None
    assert seq.verify() == []
    client.remove("//x//y")
    assert seq.resolve("//x/y") is None
    assert seq.verify() == []


def test_quoted_path_removal(resolver):
    client, seq = resolver
    client.create("map_node", "//data/it's", recursive=True)
    client.create("document", "//data/it's/leaf")
    assert seq.resolve("//data/it's/leaf") is not None
    client.remove("//data/it's")
    assert seq.resolve("//data/it's") is None
    assert seq.resolve("//data/it's/leaf") is None
    assert seq.verify() == []


def test_excluded_prefix_is_segment_aware(resolver):
    client, seq = resolver
    client.create("document", "//sys/sequoia_backup", recursive=True)
    assert seq.resolve("//sys/sequoia_backup") is not None
    assert seq.verify() == []


def test_under_mutation_load_stays_consistent(resolver):
    client, seq = resolver
    for i in range(40):
        client.create("document", f"//load/d{i}", recursive=True)
        if i % 3 == 0:
            client.set(f"//load/d{i}", {"v": i})
        if i % 7 == 0 and i:
            client.remove(f"//load/d{i - 1}")
    assert seq.verify() == []
    assert seq.resolve("//load/d2") is not None
    assert seq.resolve("//load/d6") is None       # removed at i=7


# -- slice 2: per-object records + cypress-proxy read path ---------------------


def test_read_path_serves_from_tables_only(resolver):
    """get/list/exists/attributes answered WITHOUT touching the master
    tree (cypress_proxy-style)."""
    client, seq = resolver
    client.create("map_node", "//app", recursive=True)
    client.create("document", "//app/config")
    client.set("//app/config", {"threads": 8, "name": "q"})
    client.set("//app/config/@owner", "alice")
    client.create("document", "//app/flag")
    client.set("//app/flag", 7)

    # The USER subtree must never be resolved through the master tree
    # during proxy reads (the ground tables' own paths legitimately are —
    # in the reference they live on the ground cluster).
    tree = client.cluster.master.tree
    real_try, real_resolve = tree.try_resolve, tree.resolve

    def _guard(path):
        assert not str(path).startswith("//app"), \
            "proxy read resolved a user path via the master tree"

    def guarded_try(path, *a, **k):
        _guard(path)
        return real_try(path, *a, **k)

    def guarded_resolve(path, *a, **k):
        _guard(path)
        return real_resolve(path, *a, **k)
    tree.try_resolve, tree.resolve = guarded_try, guarded_resolve
    try:
        assert seq.read_exists("//app/config")
        assert not seq.read_exists("//app/ghost")
        assert sorted(seq.read_list("//app")) == ["config", "flag"]
        assert seq.read_get("//app/config") == {"threads": 8, "name": "q"}
        assert seq.read_get("//app/flag") == 7
        assert seq.read_get("//app") == {
            "config": {"threads": 8, "name": "q"}, "flag": 7}
        assert seq.read_attribute("//app/config", "owner") == "alice"
    finally:
        tree.try_resolve, tree.resolve = real_try, real_resolve
    assert seq.verify() == []


def test_attribute_edits_refresh_node_records(resolver):
    client, seq = resolver
    client.create("document", "//rec", recursive=True)
    client.set("//rec/@color", "red")
    assert seq.read_attribute("//rec", "color") == "red"
    client.set("//rec/@color", "blue")
    assert seq.read_attribute("//rec", "color") == "blue"
    client.remove("//rec/@color")
    with pytest.raises(Exception):
        seq.read_attribute("//rec", "color")
    assert seq.verify() == []


def test_tx_abort_is_scoped_not_full_resync(resolver):
    """The abort resync touches only the aborted paths: full_sync must
    NOT run (abort-scoped undo replacing the slice-1 full resync)."""
    client, seq = resolver
    client.create("document", "//stable/keep", recursive=True)
    calls = {"n": 0}
    real_full_sync = seq.full_sync

    def counting_full_sync():
        calls["n"] += 1
        return real_full_sync()
    seq.full_sync = counting_full_sync
    tx = client.start_tx()
    client.create("document", "//txa/b", recursive=True, tx=tx)
    client.set("//stable/keep", {"v": 1}, tx=tx)
    client.abort_tx(tx)
    assert calls["n"] == 0                      # scoped, not full
    assert seq.resolve("//txa/b") is None
    assert seq.resolve("//txa") is None
    assert seq.read_get("//stable/keep") is None    # rolled-back value
    assert seq.verify() == []


def test_tx_commit_rolls_back_uncommitted_children_scoped(resolver):
    client, seq = resolver
    calls = {"n": 0}
    real_full_sync = seq.full_sync
    seq.full_sync = lambda: calls.__setitem__("n", calls["n"] + 1) or \
        real_full_sync()
    outer = client.start_tx()
    inner = client.start_tx(parent=outer)
    client.create("document", "//nested/child", recursive=True, tx=inner)
    client.commit_tx(outer)      # inner never committed → rolled back
    assert calls["n"] == 0
    assert seq.resolve("//nested/child") is None
    assert seq.verify() == []


def test_verify_detects_orphan_children_edge(resolver):
    """A stale children row (ghost edge) is a divergence full_sync must
    repair — verify() may not silently pass it."""
    from ytsaurus_tpu.cypress.sequoia import CHILDREN_PATH
    client, seq = resolver
    client.create("map_node", "//par", recursive=True)
    parent_id = seq.resolve("//par")["node_id"]
    assert seq.verify() == []
    client.insert_rows(CHILDREN_PATH, [{
        "parent_id": parent_id, "child_key": "ghost",
        "child_id": "deadbeef"}])
    assert seq.verify() != []
    seq.full_sync()
    assert seq.verify() == []
    assert seq.read_list("//par") == []


def test_multiprocess_randomized_workload_stays_consistent(tmp_path,
                                                           monkeypatch):
    """The slice-2 'Done' criterion over REAL processes: a remote client
    runs a randomized create/copy/remove/set/abort workload against a
    live cluster with Sequoia enabled; verify() (via orchid) proves the
    ground tables agree with the tree."""
    import random

    from ytsaurus_tpu.environment import LocalCluster
    from ytsaurus_tpu.remote_client import connect_remote
    from ytsaurus_tpu.rpc import Channel

    monkeypatch.setenv("YT_TPU_SEQUOIA", "1")
    with LocalCluster(str(tmp_path / "cl"), n_nodes=1) as cluster:
        client = connect_remote(cluster.primary_address)
        rng = random.Random(42)
        live: list[str] = []
        for step in range(60):
            roll = rng.random()
            if roll < 0.4 or not live:
                path = f"//w/n{step}"
                client.create("document", path, recursive=True)
                client.set(path, {"step": step})
                live.append(path)
            elif roll < 0.55:
                client.remove(live.pop(rng.randrange(len(live))),
                              force=True)
            elif roll < 0.7:
                src = rng.choice(live)
                dst = f"//w/copy{step}"
                client.copy(src, dst)
                live.append(dst)
            elif roll < 0.85:
                client.set(f"{rng.choice(live)}/@mark", step)
            else:
                tx = client.start_tx()
                path = f"//w/tx{step}"
                client.create("document", path, recursive=True, tx=tx)
                if rng.random() < 0.5:
                    client.abort_tx(tx)
                else:
                    client.commit_tx(tx)
                    live.append(path)
        ch = Channel(cluster.primary_address, timeout=60)
        body, _ = ch.call("orchid", "get", {"path": "/sequoia"})
        state = body["value"]
        assert state["enabled"] is True
        # Orchid reads serve CACHED verify state; the explicit
        # /sequoia/verify action runs the walk on demand, proving the
        # ground tables agree with the tree AFTER the workload.
        body, _ = ch.call("orchid", "get", {"path": "/sequoia/verify"})
        ch.close()
        assert body["value"]["divergent"] == []
        client.close()


def test_randomized_workload_with_aborts_stays_consistent(resolver):
    """The slice-2 'Done' criterion: create/copy/remove/set/abort chaos,
    then verify() proves all three ground tables agree with the tree."""
    import random
    client, seq = resolver
    rng = random.Random(20260730)
    live: list[str] = []
    for step in range(120):
        roll = rng.random()
        if roll < 0.35 or not live:
            path = f"//w/n{step}"
            client.create("document", path, recursive=True)
            client.set(path, {"step": step})
            live.append(path)
        elif roll < 0.5:
            victim = live.pop(rng.randrange(len(live)))
            client.remove(victim, force=True)
        elif roll < 0.65:
            src = rng.choice(live)
            dst = f"//w/copy{step}"
            client.copy(src, dst)
            live.append(dst)
        elif roll < 0.8:
            path = rng.choice(live)
            client.set(f"{path}/@mark", step)
        else:
            tx = client.start_tx()
            path = f"//w/tx{step}"
            client.create("document", path, recursive=True, tx=tx)
            if rng.random() < 0.5:
                client.abort_tx(tx)
            else:
                client.commit_tx(tx)
                live.append(path)
    assert seq.verify() == []