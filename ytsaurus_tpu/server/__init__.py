"""Server processes: the multi-host half of the cluster.

Ref mapping (design, not translation):
  data node chunk service (server/node/data_node/data_node_service.cpp
    PutBlocks/GetBlockSet)                        → services.DataNodeService
  journal chunks (quorum WAL storage,
    server/node/data_node/journal_chunk.h)        → services.DataNodeService
    journal_* methods
  node tracker heartbeats
    (server/master/node_tracker_server)           → services.NodeTrackerService
  proxy-hosted driver (server/http_proxy +
    client/driver/driver.cpp:121)                 → services.DriverService
  ytserver-all multiplexed binary
    (server/all/main.cpp)                         → daemon.py --role
  YTInstance local clusters
    (yt/python/yt/environment/yt_env.py:179)      → environment/local.py
"""
