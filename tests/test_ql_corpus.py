"""QL regression corpus — parameterized cases growing toward the
reference suite's scale (library/query/unittests/ql_query_ut.cpp ~600
cases; VERDICT r2 #10 asked for >= 300 total across the harness).

Every case runs the full parse -> typed IR -> XLA lowering -> execute
pipeline through tests/harness.evaluate.  Sections mirror the reference
suite's grouping: expression edge cases, null semantics per operator,
strings + string functions, scalar functions/casts, aggregates and
GROUP BY shapes, ORDER BY / LIMIT, and join shapes.
"""

import pytest

from tests.harness import evaluate

T = "//t"
D = "//d"

INT_COLS = [("k", "int64", "ascending"), ("v", "int64")]
ABC_COLS = [("k", "int64", "ascending"), ("a", "int64"), ("b", "int64")]
STR_COLS = [("k", "int64", "ascending"), ("s", "string")]
DBL_COLS = [("k", "int64", "ascending"), ("x", "double")]
U64_COLS = [("k", "int64", "ascending"), ("u", "uint64")]
BOOL_COLS = [("k", "int64", "ascending"), ("f", "boolean")]


def tbl(rows, cols=INT_COLS, path=T):
    return {path: (cols, rows)}


KV6 = tbl([(i, i * 10) for i in range(6)])
NULLS = tbl([(1, 10), (2, None), (3, 30), (4, None), (5, 50)])
AB = tbl([(1, 3, 2), (2, -7, 2), (3, 0, 0), (4, None, 5), (5, 8, None)],
         ABC_COLS)
STRS = tbl([(1, "apple"), (2, "Banana"), (3, "cherry"), (4, None),
            (5, ""), (6, "apple pie")], STR_COLS)
DBLS = tbl([(1, 1.5), (2, -2.5), (3, 0.0), (4, None), (5, 100.25)],
           DBL_COLS)
GRP = tbl([(1, 0, 1), (2, 1, 2), (3, 0, 3), (4, 1, 4), (5, 0, 5),
           (6, 2, None), (7, 2, None)],
          [("k", "int64", "ascending"), ("g", "int64"), ("v", "int64")])


def run(query, tables, expected, ordered=False):
    evaluate(query, tables, expected, ordered=ordered)


# ---------------------------------------------------------------------------
# A. arithmetic, unary, bitwise — C/C++ integer semantics
# ---------------------------------------------------------------------------

ARITH = [
    ("add", f"k + v AS r FROM [{T}]", tbl([(2, 3)]), [{"r": 5}]),
    ("sub", f"k - v AS r FROM [{T}]", tbl([(2, 5)]), [{"r": -3}]),
    ("mul", f"k * v AS r FROM [{T}]", tbl([(4, -6)]), [{"r": -24}]),
    ("div_exact", f"v / k AS r FROM [{T}]", tbl([(4, 12)]), [{"r": 3}]),
    ("div_trunc_pos", f"v / k AS r FROM [{T}]", tbl([(2, 7)]), [{"r": 3}]),
    ("div_trunc_neg", f"v / k AS r FROM [{T}]", tbl([(2, -7)]),
     [{"r": -3}]),
    ("div_trunc_neg_divisor", f"v / k AS r FROM [{T}]", tbl([(-2, 7)]),
     [{"r": -3}]),
    ("div_by_zero_null", f"v / k AS r FROM [{T}]", tbl([(0, 7)]),
     [{"r": None}]),
    ("mod_pos", f"v % k AS r FROM [{T}]", tbl([(3, 7)]), [{"r": 1}]),
    ("mod_neg_dividend", f"v % k AS r FROM [{T}]", tbl([(3, -7)]),
     [{"r": -1}]),
    ("mod_by_zero_null", f"v % k AS r FROM [{T}]", tbl([(0, 7)]),
     [{"r": None}]),
    ("precedence_mul_over_add", f"k + v * 2 AS r FROM [{T}]",
     tbl([(1, 10)]), [{"r": 21}]),
    ("parens_override", f"(k + v) * 2 AS r FROM [{T}]", tbl([(1, 10)]),
     [{"r": 22}]),
    ("unary_minus", f"-v AS r FROM [{T}]", tbl([(1, -5)]), [{"r": 5}]),
    ("unary_minus_expr", f"-(k + v) AS r FROM [{T}]", tbl([(1, 2)]),
     [{"r": -3}]),
    ("bitnot", f"~v AS r FROM [{T}]", tbl([(1, 0)]), [{"r": -1}]),
    ("bitand", f"v & 3 AS r FROM [{T}]", tbl([(1, 5)]), [{"r": 1}]),
    ("bitor", f"v | 2 AS r FROM [{T}]", tbl([(1, 5)]), [{"r": 7}]),
    ("bitxor", f"v ^ 1 AS r FROM [{T}]", tbl([(1, 5)]), [{"r": 4}]),
    ("shl", f"v << 4 AS r FROM [{T}]", tbl([(1, 3)]), [{"r": 48}]),
    ("shr", f"v >> 2 AS r FROM [{T}]", tbl([(1, 29)]), [{"r": 7}]),
    ("shr_negative_arithmetic", f"v >> 1 AS r FROM [{T}]", tbl([(1, -8)]),
     [{"r": -4}]),
    ("chained_sub_left_assoc", f"v - k - 1 AS r FROM [{T}]",
     tbl([(2, 10)]), [{"r": 7}]),
    ("double_add", f"x + 0.25 AS r FROM [{T}]", tbl([(1, 1.5)], DBL_COLS),
     [{"r": 1.75}]),
    ("double_div", f"x / 2 AS r FROM [{T}]", tbl([(1, 7.0)], DBL_COLS),
     [{"r": 3.5}]),
    ("double_neg", f"-x AS r FROM [{T}]", tbl([(1, -2.5)], DBL_COLS),
     [{"r": 2.5}]),
    ("int_double_promotion", f"k + x AS r FROM [{T}]",
     tbl([(2, 0.5)], DBL_COLS), [{"r": 2.5}]),
    ("literal_only_projection", f"1 + 2 AS r FROM [{T}]", tbl([(1, 0)]),
     [{"r": 3}]),
    ("null_plus_value_is_null", f"a + b AS r FROM [{T}]",
     tbl([(4, None, 5)], ABC_COLS), [{"r": None}]),
    ("null_mul_is_null", f"a * b AS r FROM [{T}]",
     tbl([(5, 8, None)], ABC_COLS), [{"r": None}]),
    ("null_div_is_null", f"a / b AS r FROM [{T}]",
     tbl([(4, None, 5)], ABC_COLS), [{"r": None}]),
    ("null_bitand_is_null", f"a & b AS r FROM [{T}]",
     tbl([(4, None, 5)], ABC_COLS), [{"r": None}]),
    ("null_shift_is_null", f"a << b AS r FROM [{T}]",
     tbl([(5, 8, None)], ABC_COLS), [{"r": None}]),
    ("mixed_null_and_value_rows", f"a + 1 AS r FROM [{T}]", AB,
     [{"r": 4}, {"r": -6}, {"r": 1}, {"r": None}, {"r": 9}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in ARITH],
                         ids=[c[0] for c in ARITH])
def test_arithmetic(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# B. comparisons + WHERE null semantics (NULL never matches, even NOT)
# ---------------------------------------------------------------------------

CMP = [
    ("lt", f"k FROM [{T}] WHERE v < 20", KV6, [{"k": 0}, {"k": 1}]),
    ("le", f"k FROM [{T}] WHERE v <= 20", KV6,
     [{"k": 0}, {"k": 1}, {"k": 2}]),
    ("gt", f"k FROM [{T}] WHERE v > 30", KV6, [{"k": 4}, {"k": 5}]),
    ("ge", f"k FROM [{T}] WHERE v >= 40", KV6, [{"k": 4}, {"k": 5}]),
    ("eq", f"k FROM [{T}] WHERE v = 30", KV6, [{"k": 3}]),
    ("ne", f"k FROM [{T}] WHERE v != 30", KV6,
     [{"k": 0}, {"k": 1}, {"k": 2}, {"k": 4}, {"k": 5}]),
    ("expr_both_sides", f"k FROM [{T}] WHERE k * 10 = v", KV6,
     [{"k": i} for i in range(6)]),
    ("null_eq_filters", f"k FROM [{T}] WHERE v = 10", NULLS, [{"k": 1}]),
    ("null_ne_filters_null_rows", f"k FROM [{T}] WHERE v != 10", NULLS,
     [{"k": 3}, {"k": 5}]),
    ("null_lt_filters", f"k FROM [{T}] WHERE v < 40", NULLS,
     [{"k": 1}, {"k": 3}]),
    ("not_pushes_through_null", f"k FROM [{T}] WHERE NOT (v < 40)", NULLS,
     [{"k": 5}]),
    ("is_null_predicate", f"k FROM [{T}] WHERE is_null(v)", NULLS,
     [{"k": 2}, {"k": 4}]),
    ("not_is_null", f"k FROM [{T}] WHERE NOT is_null(v)", NULLS,
     [{"k": 1}, {"k": 3}, {"k": 5}]),
    ("and_short_null", f"k FROM [{T}] WHERE v > 0 AND v < 40", NULLS,
     [{"k": 1}, {"k": 3}]),
    ("or_with_null_side", f"k FROM [{T}] WHERE v = 10 OR v = 50", NULLS,
     [{"k": 1}, {"k": 5}]),
    ("double_eq", f"k FROM [{T}] WHERE x = -2.5", DBLS, [{"k": 2}]),
    ("double_lt_zero", f"k FROM [{T}] WHERE x < 0.0", DBLS, [{"k": 2}]),
    ("bool_col_negated", f"k FROM [{T}] WHERE NOT f",
     tbl([(1, True), (2, False), (3, None)], BOOL_COLS), [{"k": 2}]),
    ("cmp_string_lt", f"k FROM [{T}] WHERE s < 'b'", STRS,
     # byte-wise: 'B' (0x42) < 'b' (0x62), so "Banana" matches too
     [{"k": 1}, {"k": 2}, {"k": 5}, {"k": 6}]),
    ("cmp_string_ge", f"k FROM [{T}] WHERE s >= 'cherry'", STRS,
     [{"k": 3}]),
    ("cmp_string_eq_empty", f"k FROM [{T}] WHERE s = ''", STRS,
     [{"k": 5}]),
    ("uint64_cmp", f"k FROM [{T}] WHERE u > 9000000000000000000",
     tbl([(1, 2**63 + 5), (2, 17)], U64_COLS), [{"k": 1}]),
    ("where_false_empty", f"k FROM [{T}] WHERE 1 = 2", KV6, []),
    ("where_true_all", f"k FROM [{T}] WHERE 1 = 1", KV6,
     [{"k": i} for i in range(6)]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in CMP],
                         ids=[c[0] for c in CMP])
def test_comparisons(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# C. IN / BETWEEN / LIKE / CASE / if / transform
# ---------------------------------------------------------------------------

COMB = [
    ("in_single", f"k FROM [{T}] WHERE k IN (3)", KV6, [{"k": 3}]),
    ("in_none_match", f"k FROM [{T}] WHERE k IN (77, 88)", KV6, []),
    ("in_expr_subject", f"k FROM [{T}] WHERE k % 3 IN (0)", KV6,
     [{"k": 0}, {"k": 3}]),
    ("not_in", f"k FROM [{T}] WHERE k NOT IN (0, 1, 2, 3)", KV6,
     [{"k": 4}, {"k": 5}]),
    ("in_null_subject_excluded", f"k FROM [{T}] WHERE v IN (10, 30)",
     NULLS, [{"k": 1}, {"k": 3}]),
    ("between_inclusive_ends", f"k FROM [{T}] WHERE k BETWEEN 1 AND 1",
     KV6, [{"k": 1}]),
    ("between_empty_range", f"k FROM [{T}] WHERE k BETWEEN 4 AND 2", KV6,
     []),
    ("between_on_expr", f"k FROM [{T}] WHERE v / 10 BETWEEN 2 AND 3",
     KV6, [{"k": 2}, {"k": 3}]),
    ("like_underscore", f"k FROM [{T}] WHERE s LIKE '_pple'", STRS,
     [{"k": 1}]),
    ("like_percent_middle", f"k FROM [{T}] WHERE s LIKE 'a%e'", STRS,
     [{"k": 1}, {"k": 6}]),
    ("like_exact_no_wildcards", f"k FROM [{T}] WHERE s LIKE 'cherry'",
     STRS, [{"k": 3}]),
    ("like_empty_pattern", f"k FROM [{T}] WHERE s LIKE ''", STRS,
     [{"k": 5}]),
    ("like_case_sensitive", f"k FROM [{T}] WHERE s LIKE 'banana'", STRS,
     []),
    ("ilike_case_insensitive", f"k FROM [{T}] WHERE s ILIKE 'banana'",
     STRS, [{"k": 2}]),
    ("like_null_subject", f"k FROM [{T}] WHERE s LIKE '%'", STRS,
     [{"k": 1}, {"k": 2}, {"k": 3}, {"k": 5}, {"k": 6}]),
    ("if_int_branches", f"if(v >= 30, 1, 0) AS r FROM [{T}]", KV6,
     [{"r": 0}, {"r": 0}, {"r": 0}, {"r": 1}, {"r": 1}, {"r": 1}]),
    ("if_nested", f"if(k < 2, 'lo', if(k < 4, 'mid', 'hi')) AS r "
     f"FROM [{T}]", tbl([(1, 0), (3, 0), (5, 0)]),
     [{"r": "lo"}, {"r": "mid"}, {"r": "hi"}]),
    ("if_null_condition_null_result",
     f"if(a > 0, 1, 0) AS r FROM [{T}]", tbl([(4, None, 5)], ABC_COLS),
     [{"r": None}]),
    ("if_null_function", f"if_null(a, 99) AS r FROM [{T}]", AB,
     [{"r": 3}, {"r": -7}, {"r": 0}, {"r": 99}, {"r": 8}]),
    ("if_null_passthrough", f"if_null(b, a) AS r FROM [{T}]",
     tbl([(5, 8, None)], ABC_COLS), [{"r": 8}]),
    ("case_no_else_null", f"CASE WHEN k = 1 THEN 7 END AS r FROM [{T}]",
     tbl([(1, 0), (2, 0)]), [{"r": 7}, {"r": None}]),
    ("case_first_match_wins",
     f"CASE WHEN k > 0 THEN 'a' WHEN k > 1 THEN 'b' END AS r FROM [{T}]",
     tbl([(2, 0)]), [{"r": "a"}]),
    ("case_operand_strings",
     f"CASE s WHEN 'apple' THEN 1 WHEN 'cherry' THEN 2 ELSE 0 END AS r "
     f"FROM [{T}]", STRS,
     # null operand: s = 'apple' is null, if() propagates -> null row
     [{"r": 1}, {"r": 0}, {"r": 2}, {"r": None}, {"r": 0}, {"r": 0}]),
    ("case_in_where",
     f"k FROM [{T}] WHERE CASE WHEN k < 3 THEN k ELSE 0 END = 2", KV6,
     [{"k": 2}]),
    ("transform_with_default", f"transform(k, (0, 1), (10, 11), -5) AS r "
     f"FROM [{T}]", tbl([(0, 0), (1, 0), (2, 0)]),
     [{"r": 10}, {"r": 11}, {"r": -5}]),
    ("transform_no_default_null",
     f"transform(k, (0, 1), (10, 11)) AS r FROM [{T}]",
     tbl([(0, 0), (9, 0)]), [{"r": 10}, {"r": None}]),
    ("transform_in_where",
     f"k FROM [{T}] WHERE transform(k, (1, 2), (10, 20), 0) = 20", KV6,
     [{"k": 2}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in COMB],
                         ids=[c[0] for c in COMB])
def test_conditionals(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# D. string functions
# ---------------------------------------------------------------------------

STRF = [
    ("length", f"length(s) AS r FROM [{T}]", STRS,
     [{"r": 5}, {"r": 6}, {"r": 6}, {"r": None}, {"r": 0}, {"r": 9}]),
    ("lower", f"lower(s) AS r FROM [{T}]", tbl([(1, "MiXeD")], STR_COLS),
     [{"r": "mixed"}]),
    ("upper", f"upper(s) AS r FROM [{T}]", tbl([(1, "MiXeD")], STR_COLS),
     [{"r": "MIXED"}]),
    ("lower_null", f"lower(s) AS r FROM [{T}]",
     tbl([(1, None)], STR_COLS), [{"r": None}]),
    ("concat_literal", f"concat(s, '!') AS r FROM [{T}]",
     tbl([(1, "hey")], STR_COLS), [{"r": "hey!"}]),
    ("concat_null_propagates", f"concat(s, '!') AS r FROM [{T}]",
     tbl([(1, None)], STR_COLS), [{"r": None}]),
    ("is_prefix_hit", f"k FROM [{T}] WHERE is_prefix('app', s)", STRS,
     [{"k": 1}, {"k": 6}]),
    ("is_prefix_empty_prefix", f"k FROM [{T}] WHERE is_prefix('', s)",
     STRS, [{"k": 1}, {"k": 2}, {"k": 3}, {"k": 5}, {"k": 6}]),
    ("is_substr_hit", f"k FROM [{T}] WHERE is_substr('err', s)", STRS,
     [{"k": 3}]),
    ("is_substr_space", f"k FROM [{T}] WHERE is_substr(' ', s)", STRS,
     [{"k": 6}]),
    ("length_in_where", f"k FROM [{T}] WHERE length(s) > 6", STRS,
     [{"k": 6}]),
    ("upper_in_group",
     f"upper(s) AS u, count(*) AS c FROM [{T}] GROUP BY upper(s) AS u",
     tbl([(1, "ab"), (2, "AB"), (3, "cd")], STR_COLS),
     [{"u": "AB", "c": 2}, {"u": "CD", "c": 1}]),
    ("concat_in_order_by",
     f"s FROM [{T}] ORDER BY concat(s, '') LIMIT 3",
     tbl([(1, "b"), (2, "a"), (3, "c")], STR_COLS),
     [{"s": "a"}, {"s": "b"}, {"s": "c"}]),
    ("string_min_max",
     f"min(s) AS lo, max(s) AS hi FROM [{T}] GROUP BY 1 AS one",
     tbl([(1, "pear"), (2, "fig"), (3, "plum")], STR_COLS),
     [{"lo": "fig", "hi": "plum"}]),
    ("farm_hash_deterministic",
     # farm_hash hashes the null marker too (non-null result), so every
     # row satisfies the self-equality
     f"k FROM [{T}] WHERE farm_hash(s) = farm_hash(s)", STRS,
     [{"k": i} for i in range(1, 7)]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in STRF],
                         ids=[c[0] for c in STRF])
def test_string_functions(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# E. numeric functions and casts
# ---------------------------------------------------------------------------

NUMF = [
    ("abs_int", f"abs(v) AS r FROM [{T}]", tbl([(1, -7)]), [{"r": 7}]),
    ("abs_double", f"abs(x) AS r FROM [{T}]", tbl([(1, -2.5)], DBL_COLS),
     [{"r": 2.5}]),
    ("abs_null", f"abs(a) AS r FROM [{T}]", tbl([(4, None, 5)], ABC_COLS),
     [{"r": None}]),
    ("ceil", f"ceil(x) AS r FROM [{T}]", tbl([(1, 1.2)], DBL_COLS),
     [{"r": 2.0}]),
    ("ceil_negative", f"ceil(x) AS r FROM [{T}]",
     tbl([(1, -1.2)], DBL_COLS), [{"r": -1.0}]),
    ("floor", f"floor(x) AS r FROM [{T}]", tbl([(1, 1.8)], DBL_COLS),
     [{"r": 1.0}]),
    ("floor_negative", f"floor(x) AS r FROM [{T}]",
     tbl([(1, -1.2)], DBL_COLS), [{"r": -2.0}]),
    ("sqrt", f"sqrt(x) AS r FROM [{T}]", tbl([(1, 6.25)], DBL_COLS),
     [{"r": 2.5}]),
    ("min_of_two", f"min_of(k, v) AS r FROM [{T}]", tbl([(5, 3)]),
     [{"r": 3}]),
    ("max_of_two", f"max_of(k, v) AS r FROM [{T}]", tbl([(5, 3)]),
     [{"r": 5}]),
    ("min_of_three", f"min_of(k, v, 0) AS r FROM [{T}]", tbl([(5, 3)]),
     [{"r": 0}]),
    ("max_of_doubles", f"max_of(x, 0.0) AS r FROM [{T}]",
     tbl([(1, -2.5)], DBL_COLS), [{"r": 0.0}]),
    ("int64_cast_from_double", f"int64(x) AS r FROM [{T}]",
     tbl([(1, 3.9)], DBL_COLS), [{"r": 3}]),
    ("double_cast_from_int", f"double(v) / 2 AS r FROM [{T}]",
     tbl([(1, 7)]), [{"r": 3.5}]),
    ("uint64_cast", f"uint64(v) AS r FROM [{T}]", tbl([(1, 7)]),
     [{"r": 7}]),
    ("int64_cast_of_uint", f"int64(u) AS r FROM [{T}]",
     tbl([(1, 7)], U64_COLS), [{"r": 7}]),
    ("boolean_cast", f"k FROM [{T}] WHERE boolean(v)",
     tbl([(1, 0), (2, 3)]), [{"k": 2}]),
    ("is_finite_true", f"k FROM [{T}] WHERE is_finite(x)", DBLS,
     [{"k": 1}, {"k": 2}, {"k": 3}, {"k": 5}]),
    ("is_finite_false_on_div0", f"k FROM [{T}] WHERE NOT is_finite(x / 0.0)",
     tbl([(1, 1.0)], DBL_COLS), [{"k": 1}]),
    ("is_nan_detects", f"k FROM [{T}] WHERE is_nan(x - x)",
     tbl([(1, 1.0)], DBL_COLS), []),
    ("sqrt_in_where", f"k FROM [{T}] WHERE sqrt(x) > 10.0", DBLS,
     [{"k": 5}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in NUMF],
                         ids=[c[0] for c in NUMF])
def test_numeric_functions(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# F. aggregates and GROUP BY shapes
# ---------------------------------------------------------------------------

AGG = [
    ("sum_per_group", f"g, sum(v) AS s FROM [{T}] GROUP BY g", GRP,
     [{"g": 0, "s": 9}, {"g": 1, "s": 6}, {"g": 2, "s": None}]),
    ("count_skips_nulls", f"g, count(v) AS c FROM [{T}] GROUP BY g", GRP,
     [{"g": 0, "c": 3}, {"g": 1, "c": 2}, {"g": 2, "c": 0}]),
    ("count_star_counts_rows", f"g, count(*) AS c FROM [{T}] GROUP BY g",
     GRP, [{"g": 0, "c": 3}, {"g": 1, "c": 2}, {"g": 2, "c": 2}]),
    ("min_max", f"g, min(v) AS lo, max(v) AS hi FROM [{T}] GROUP BY g",
     GRP, [{"g": 0, "lo": 1, "hi": 5}, {"g": 1, "lo": 2, "hi": 4},
           {"g": 2, "lo": None, "hi": None}]),
    ("avg_double_result", f"g, avg(v) AS a FROM [{T}] GROUP BY g", GRP,
     [{"g": 0, "a": 3.0}, {"g": 1, "a": 3.0}, {"g": 2, "a": None}]),
    ("first_any_member", f"g, first(g) AS f FROM [{T}] GROUP BY g", GRP,
     [{"g": 0, "f": 0}, {"g": 1, "f": 1}, {"g": 2, "f": 2}]),
    ("sum_of_expression", f"g, sum(v * v) AS s FROM [{T}] GROUP BY g",
     GRP, [{"g": 0, "s": 35}, {"g": 1, "s": 20}, {"g": 2, "s": None}]),
    ("group_by_two_keys",
     f"a, b, count(*) AS c FROM [{T}] GROUP BY a, b",
     tbl([(1, 1, 1), (2, 1, 1), (3, 1, 2), (4, 2, 1)], ABC_COLS),
     [{"a": 1, "b": 1, "c": 2}, {"a": 1, "b": 2, "c": 1},
      {"a": 2, "b": 1, "c": 1}]),
    ("group_key_expression_mod",
     f"k % 2 AS p, count(*) AS c FROM [{T}] GROUP BY k % 2 AS p", KV6,
     [{"p": 0, "c": 3}, {"p": 1, "c": 3}]),
    ("group_by_string_key",
     f"s, count(*) AS c FROM [{T}] GROUP BY s",
     tbl([(1, "x"), (2, "y"), (3, "x"), (4, None)], STR_COLS),
     [{"s": "x", "c": 2}, {"s": "y", "c": 1}, {"s": None, "c": 1}]),
    ("group_by_bool_key",
     f"f, count(*) AS c FROM [{T}] GROUP BY f",
     tbl([(1, True), (2, False), (3, True)], BOOL_COLS),
     [{"f": True, "c": 2}, {"f": False, "c": 1}]),
    ("having_on_count",
     f"g, count(*) AS c FROM [{T}] GROUP BY g HAVING count(*) > 2", GRP,
     [{"g": 0, "c": 3}]),
    ("having_on_min",
     f"g, min(v) AS lo FROM [{T}] GROUP BY g HAVING min(v) = 2", GRP,
     [{"g": 1, "lo": 2}]),
    ("having_filters_all",
     f"g, sum(v) AS s FROM [{T}] GROUP BY g HAVING sum(v) > 100", GRP,
     []),
    ("having_uses_ungrouped_agg",
     f"g FROM [{T}] GROUP BY g HAVING sum(v) >= 9", GRP, [{"g": 0}]),
    ("aggregate_only_no_keys",
     f"sum(v) AS s, count(*) AS c FROM [{T}] GROUP BY 1 AS one", GRP,
     [{"s": 15, "c": 7}]),
    ("avg_of_doubles", f"avg(x) AS a FROM [{T}] GROUP BY 1 AS one",
     tbl([(1, 1.0), (2, 2.0), (3, 6.0)], DBL_COLS), [{"a": 3.0}]),
    ("sum_uint64",
     f"sum(u) AS s FROM [{T}] GROUP BY 1 AS one",
     tbl([(1, 3), (2, 4)], U64_COLS), [{"s": 7}]),
    ("cardinality_exact_small",
     f"cardinality(v) AS c FROM [{T}] GROUP BY 1 AS one",
     tbl([(1, 5), (2, 5), (3, 7), (4, None)]), [{"c": 2}]),
    ("argmin_basic",
     f"argmin(k, v) AS r FROM [{T}] GROUP BY 1 AS one",
     tbl([(1, 30), (2, 10), (3, 20)]), [{"r": 2}]),
    ("argmax_basic",
     f"argmax(k, v) AS r FROM [{T}] GROUP BY 1 AS one",
     tbl([(1, 30), (2, 10), (3, 20)]), [{"r": 1}]),
    ("group_then_project_expression",
     f"g * 100 AS gg, sum(v) AS s FROM [{T}] GROUP BY g", GRP,
     [{"gg": 0, "s": 9}, {"gg": 100, "s": 6}, {"gg": 200, "s": None}]),
    ("group_by_if_expression",
     f"if(v < 3, 'small', 'big') AS b, count(*) AS c FROM [{T}] "
     f"WHERE v IS NOT NULL GROUP BY if(v < 3, 'small', 'big') AS b"
     .replace(" WHERE v IS NOT NULL", ""),
     tbl([(1, 1), (2, 2), (3, 3), (4, 4)]),
     [{"b": "small", "c": 2}, {"b": "big", "c": 2}]),
    ("where_then_group",
     f"g, count(*) AS c FROM [{T}] WHERE v > 1 GROUP BY g", GRP,
     [{"g": 0, "c": 2}, {"g": 1, "c": 2}]),
    ("group_order_limit",
     f"g, sum(v) AS s FROM [{T}] GROUP BY g ORDER BY g DESC LIMIT 2",
     GRP, [{"g": 2, "s": None}, {"g": 1, "s": 6}]),
    ("with_totals_row",
     f"g, sum(v) AS s FROM [{T}] GROUP BY g WITH TOTALS "
     f"ORDER BY g LIMIT 10",
     tbl([(1, 0, 1), (2, 0, 2), (3, 1, 4)],
         [("k", "int64", "ascending"), ("g", "int64"), ("v", "int64")]),
     [{"g": None, "s": 7}, {"g": 0, "s": 3}, {"g": 1, "s": 4}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in AGG],
                         ids=[c[0] for c in AGG])
def test_aggregates(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# G. ORDER BY / LIMIT / OFFSET (ordered comparisons)
# ---------------------------------------------------------------------------

ORDER = [
    ("asc", f"k FROM [{T}] ORDER BY v LIMIT 6", KV6,
     [{"k": i} for i in range(6)]),
    ("desc", f"k FROM [{T}] ORDER BY v DESC LIMIT 6", KV6,
     [{"k": i} for i in reversed(range(6))]),
    ("limit_caps", f"k FROM [{T}] ORDER BY k LIMIT 2", KV6,
     [{"k": 0}, {"k": 1}]),
    ("offset_skips", f"k FROM [{T}] ORDER BY k OFFSET 4 LIMIT 10", KV6,
     [{"k": 4}, {"k": 5}]),
    ("offset_past_end", f"k FROM [{T}] ORDER BY k OFFSET 99 LIMIT 5",
     KV6, []),
    ("limit_zero", f"k FROM [{T}] ORDER BY k LIMIT 0", KV6, []),
    ("multi_key_mixed",
     f"a, b FROM [{T}] ORDER BY a, b DESC LIMIT 10",
     tbl([(1, 1, 1), (2, 1, 3), (3, 0, 9), (4, 1, 2)], ABC_COLS),
     [{"a": 0, "b": 9}, {"a": 1, "b": 3}, {"a": 1, "b": 2},
      {"a": 1, "b": 1}]),
    ("order_by_string_desc",
     f"s FROM [{T}] ORDER BY s DESC LIMIT 3",
     tbl([(1, "b"), (2, "a"), (3, "c")], STR_COLS),
     [{"s": "c"}, {"s": "b"}, {"s": "a"}]),
    ("order_nulls_first_asc",
     f"v FROM [{T}] ORDER BY v LIMIT 3", NULLS,
     [{"v": None}, {"v": None}, {"v": 10}]),
    ("order_nulls_last_desc",
     f"v FROM [{T}] ORDER BY v DESC LIMIT 3", NULLS,
     [{"v": 50}, {"v": 30}, {"v": 10}]),
    ("order_by_unprojected_column",
     f"k FROM [{T}] ORDER BY v DESC LIMIT 2", NULLS,
     [{"k": 5}, {"k": 3}]),
    ("order_by_expression_abs",
     f"v FROM [{T}] ORDER BY abs(v - 25) LIMIT 2",
     tbl([(1, 10), (2, 24), (3, 50)]), [{"v": 24}, {"v": 10}]),
    ("order_stable_against_dup_keys",
     f"a, b FROM [{T}] ORDER BY a LIMIT 4",
     tbl([(1, 1, 4), (2, 1, 3), (3, 1, 2), (4, 0, 1)], ABC_COLS),
     None),
    ("order_doubles_negative",
     f"x FROM [{T}] ORDER BY x LIMIT 3", DBLS,
     [{"x": None}, {"x": -2.5}, {"x": 0.0}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in ORDER],
                         ids=[c[0] for c in ORDER])
def test_ordering(query, tables, expected):
    if expected is None:
        rows = evaluate(query, tables)
        assert [r["a"] for r in rows] == [0, 1, 1, 1]
        return
    run(query, tables, expected, ordered=True)


# ---------------------------------------------------------------------------
# H. join shapes
# ---------------------------------------------------------------------------

JT = {
    T: ([("k", "int64", "ascending"), ("g", "int64"), ("w", "int64")],
        [(1, 100, 1), (2, 200, 2), (3, 100, 3), (4, 300, 4),
         (5, None, 5)]),
    D: ([("g", "int64", "ascending"), ("name", "string"),
         ("rank", "int64")],
        [(100, "alpha", 1), (200, "beta", 2), (400, "gamma", 3)]),
}

JOINS = [
    ("inner_basic", f"k, name FROM [{T}] JOIN [{D}] USING g", JT,
     [{"k": 1, "name": "alpha"}, {"k": 2, "name": "beta"},
      {"k": 3, "name": "alpha"}]),
    ("inner_null_key_never_matches",
     f"k FROM [{T}] JOIN [{D}] USING g WHERE k = 5", JT, []),
    ("left_keeps_unmatched",
     f"k, name FROM [{T}] LEFT JOIN [{D}] USING g", JT,
     [{"k": 1, "name": "alpha"}, {"k": 2, "name": "beta"},
      {"k": 3, "name": "alpha"}, {"k": 4, "name": None},
      {"k": 5, "name": None}]),
    ("join_where_on_foreign",
     f"k FROM [{T}] JOIN [{D}] USING g WHERE rank = 1", JT,
     [{"k": 1}, {"k": 3}]),
    ("join_where_on_self",
     f"name FROM [{T}] JOIN [{D}] USING g WHERE w >= 2", JT,
     [{"name": "beta"}, {"name": "alpha"}]),
    ("join_project_both_sides",
     f"w + rank AS r FROM [{T}] JOIN [{D}] USING g", JT,
     [{"r": 2}, {"r": 4}, {"r": 4}]),
    ("join_group_on_foreign_key",
     f"name, sum(w) AS s FROM [{T}] JOIN [{D}] USING g GROUP BY name",
     JT, [{"name": "alpha", "s": 4}, {"name": "beta", "s": 2}]),
    ("join_order_by_foreign",
     f"k FROM [{T}] JOIN [{D}] USING g ORDER BY rank DESC, k LIMIT 3",
     JT, [{"k": 2}, {"k": 1}, {"k": 3}]),
    ("join_empty_foreign",
     f"k, name FROM [{T}] JOIN [{D}] USING g",
     {T: JT[T], D: (JT[D][0], [])}, []),
    ("left_join_empty_foreign",
     f"k, name FROM [{T}] LEFT JOIN [{D}] USING g",
     {T: JT[T], D: (JT[D][0], [])},
     [{"k": i, "name": None} for i in range(1, 6)]),
    ("join_empty_self",
     f"k, name FROM [{T}] JOIN [{D}] USING g",
     {T: (JT[T][0], []), D: JT[D]}, []),
    ("join_on_expression_scaled",
     f"k, d.name AS n FROM [{T}] JOIN [{D}] AS d ON g * 2 = d.g * 2",
     JT, [{"k": 1, "n": "alpha"}, {"k": 2, "n": "beta"},
          {"k": 3, "n": "alpha"}]),
    ("join_duplicate_foreign_fanout",
     f"k, x FROM [{T}] JOIN [{D}] USING g",
     {T: ([("k", "int64", "ascending"), ("g", "int64")], [(1, 7), (2, 8)]),
      D: ([("g", "int64", "ascending"), ("x", "int64")],
          [(7, 70), (7, 71), (9, 90)])},
     [{"k": 1, "x": 70}, {"k": 1, "x": 71}]),
    ("left_join_duplicate_foreign_fanout",
     f"k, x FROM [{T}] LEFT JOIN [{D}] USING g",
     {T: ([("k", "int64", "ascending"), ("g", "int64")], [(1, 7), (2, 8)]),
      D: ([("g", "int64", "ascending"), ("x", "int64")],
          [(7, 70), (7, 71), (9, 90)])},
     [{"k": 1, "x": 70}, {"k": 1, "x": 71}, {"k": 2, "x": None}]),
    ("string_key_join",
     f"k, r FROM [{T}] JOIN [{D}] ON s = t",
     {T: ([("k", "int64", "ascending"), ("s", "string")],
          [(1, "a"), (2, "b"), (3, None)]),
      D: ([("t", "string", "ascending"), ("r", "int64")],
          [("a", 10), ("c", 30)])},
     [{"k": 1, "r": 10}]),
    ("multi_key_join_both_match",
     f"k, val FROM [{T}] JOIN [{D}] ON a = c AND b = d",
     {T: ([("k", "int64", "ascending"), ("a", "int64"), ("b", "int64")],
          [(1, 1, 1), (2, 1, 2), (3, 2, 1)]),
      D: ([("c", "int64", "ascending"), ("d", "int64", "ascending"),
           ("val", "int64")],
          [(1, 1, 11), (1, 2, 12), (2, 2, 22)])},
     [{"k": 1, "val": 11}, {"k": 2, "val": 12}]),
    ("join_then_having",
     f"name, count(*) AS c FROM [{T}] JOIN [{D}] USING g GROUP BY name "
     f"HAVING count(*) > 1", JT, [{"name": "alpha", "c": 2}]),
    ("two_joins_chained",
     f"k, n1, n2 FROM [{T}] JOIN [//d1] ON g = g1 JOIN [//d2] ON w = g2",
     {T: ([("k", "int64", "ascending"), ("g", "int64"), ("w", "int64")],
          [(1, 10, 20), (2, 11, 21), (3, 10, 99)]),
      "//d1": ([("g1", "int64", "ascending"), ("n1", "int64")],
               [(10, 100), (11, 110)]),
      "//d2": ([("g2", "int64", "ascending"), ("n2", "int64")],
               [(20, 200), (21, 210)])},
     [{"k": 1, "n1": 100, "n2": 200}, {"k": 2, "n1": 110, "n2": 210}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in JOINS],
                         ids=[c[0] for c in JOINS])
def test_join_shapes(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# I. mixed pipelines (where + group + having + order + limit in one)
# ---------------------------------------------------------------------------

MIXED = [
    ("full_pipeline",
     f"g, sum(v) AS s FROM [{T}] WHERE v > 1 GROUP BY g "
     f"HAVING sum(v) >= 4 ORDER BY sum(v) DESC LIMIT 2", GRP,
     [{"g": 0, "s": 8}, {"g": 1, "s": 6}]),
    ("project_after_group_arith",
     f"g + 1 AS gg, sum(v) * 2 AS ss FROM [{T}] WHERE g < 2 GROUP BY g",
     GRP, [{"gg": 1, "ss": 18}, {"gg": 2, "ss": 12}]),
    ("distinct_via_group",
     f"v / 20 AS bucket FROM [{T}] GROUP BY v / 20 AS bucket", KV6,
     [{"bucket": 0}, {"bucket": 1}, {"bucket": 2}]),
    ("where_in_group_order",
     f"g, max(v) AS m FROM [{T}] WHERE v IN (1, 2, 3, 4) GROUP BY g "
     f"ORDER BY max(v) DESC LIMIT 10", GRP,
     [{"g": 1, "m": 4}, {"g": 0, "m": 3}]),
    ("expression_soup",
     f"if(k % 2 = 0, 'even', 'odd') AS par, count(*) AS c, "
     f"sum(v + 1) AS s FROM [{T}] "
     f"GROUP BY if(k % 2 = 0, 'even', 'odd') AS par", KV6,
     [{"par": "even", "c": 3, "s": 63}, {"par": "odd", "c": 3, "s": 93}]),
    ("limit_after_group_without_order",
     f"g FROM [{T}] GROUP BY g LIMIT 2", GRP, None),
    ("between_and_like_combo",
     f"k FROM [{T}] WHERE k BETWEEN 1 AND 6 AND s LIKE '%p%'", STRS,
     [{"k": 1}, {"k": 6}]),
    ("case_aggregated",
     f"sum(CASE WHEN v < 3 THEN 1 ELSE 0 END) AS small FROM [{T}] "
     f"GROUP BY 1 AS one",
     tbl([(1, 1), (2, 2), (3, 3), (4, 4)]), [{"small": 2}]),
    ("order_by_two_aggs",
     f"g, count(*) AS c, sum(v) AS s FROM [{T}] GROUP BY g "
     f"ORDER BY count(*) DESC, sum(v) LIMIT 10", GRP,
     # second key ascending: the null sum sorts FIRST among the ties
     [{"g": 0, "c": 3, "s": 9}, {"g": 2, "c": 2, "s": None},
      {"g": 1, "c": 2, "s": 6}]),
    ("left_join_group_counts_unmatched",
     f"name, count(*) AS c FROM [{T}] LEFT JOIN [{D}] USING g "
     f"GROUP BY name", JT,
     [{"name": "alpha", "c": 2}, {"name": "beta", "c": 1},
      {"name": None, "c": 2}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in MIXED],
                         ids=[c[0] for c in MIXED])
def test_mixed_pipelines(query, tables, expected):
    if expected is None:
        rows = evaluate(query, tables)
        assert len(rows) > 0
        return
    ordered = "ORDER BY" in query
    run(query, tables, expected, ordered=ordered)


# ---------------------------------------------------------------------------
# J. type-boundary, timestamp, and regression odds-and-ends
# ---------------------------------------------------------------------------

HOUR = 3600
DAY = 24 * HOUR

EDGE = [
    ("int64_min_passes_through", f"v FROM [{T}]",
     tbl([(1, -(2**63))]), [{"v": -(2**63)}]),
    ("int64_max_passes_through", f"v FROM [{T}]",
     tbl([(1, 2**63 - 1)]), [{"v": 2**63 - 1}]),
    ("uint64_max_passes_through", f"u FROM [{T}]",
     tbl([(1, 2**64 - 1)], U64_COLS), [{"u": 2**64 - 1}]),
    ("uint64_sum_wraps_mod_2_64",
     f"sum(u) AS s FROM [{T}] GROUP BY 1 AS one",
     tbl([(1, 2**63 + 1), (2, 2**63 + 2)], U64_COLS), [{"s": 3}]),
    ("uint64_group_key_high",
     f"u, count(*) AS c FROM [{T}] GROUP BY u",
     tbl([(1, 2**64 - 1), (2, 2**64 - 1), (3, 1)], U64_COLS),
     [{"u": 2**64 - 1, "c": 2}, {"u": 1, "c": 1}]),
    ("double_negative_zero_equals_zero",
     f"k FROM [{T}] WHERE x = 0.0", tbl([(1, -0.0), (2, 1.0)], DBL_COLS),
     [{"k": 1}]),
    ("double_scientific_literal",
     f"k FROM [{T}] WHERE x > 1e2", tbl([(1, 99.0), (2, 101.0)], DBL_COLS),
     [{"k": 2}]),
    ("negative_literal_in_in",
     f"k FROM [{T}] WHERE v IN (-10, 10)", tbl([(1, -10), (2, 5)]),
     [{"k": 1}]),
    ("ts_floor_hour",
     f"timestamp_floor_hour(v) AS r FROM [{T}]",
     tbl([(1, 5 * HOUR + 123)]), [{"r": 5 * HOUR}]),
    ("ts_floor_day",
     f"timestamp_floor_day(v) AS r FROM [{T}]",
     tbl([(1, 3 * DAY + 7 * HOUR)]), [{"r": 3 * DAY}]),
    ("ts_floor_in_where",
     f"k FROM [{T}] WHERE timestamp_floor_day(v) = 0",
     tbl([(1, DAY - 1), (2, DAY)]), [{"k": 1}]),
    ("ts_floor_group",
     f"timestamp_floor_hour(v) AS h, count(*) AS c FROM [{T}] "
     f"GROUP BY timestamp_floor_hour(v) AS h",
     tbl([(1, 10), (2, 20), (3, HOUR + 1)]),
     [{"h": 0, "c": 2}, {"h": HOUR, "c": 1}]),
    ("concat_three_nested",
     f"concat(concat(s, '-'), s) AS r FROM [{T}]",
     tbl([(1, "ab")], STR_COLS), [{"r": "ab-ab"}]),
    ("length_of_concat",
     f"length(concat(s, 'xy')) AS r FROM [{T}]",
     tbl([(1, "ab")], STR_COLS), [{"r": 4}]),
    ("upper_of_lower_roundtrip",
     f"upper(lower(s)) AS r FROM [{T}]", tbl([(1, "MiX")], STR_COLS),
     [{"r": "MIX"}]),
    ("cast_roundtrip_int_double_int",
     f"int64(double(v)) AS r FROM [{T}]", tbl([(1, 41)]), [{"r": 41}]),
    ("if_null_chain",
     f"if_null(if_null(a, b), 0) AS r FROM [{T}]",
     tbl([(1, None, None), (2, None, 5), (3, 7, 1)], ABC_COLS),
     [{"r": 0}, {"r": 5}, {"r": 7}]),
    ("abs_of_difference",
     f"abs(a - b) AS r FROM [{T}]", tbl([(1, 3, 9)], ABC_COLS),
     [{"r": 6}]),
    ("min_of_with_null_arg",
     # min_of/max_of skip null arguments (LEAST-like, not propagating)
     f"min_of(a, b) AS r FROM [{T}]", tbl([(4, None, 5)], ABC_COLS),
     [{"r": 5}]),
    ("where_on_projected_source_column",
     f"v AS w FROM [{T}] WHERE v > 30", KV6,
     [{"w": 40}, {"w": 50}]),
    ("duplicate_output_names_allowed",
     f"k AS a, k + 1 AS b FROM [{T}]", tbl([(1, 0)]),
     [{"a": 1, "b": 2}]),
    ("empty_table_scan", f"k FROM [{T}]", tbl([]), []),
    ("empty_table_group",
     f"sum(v) AS s, count(*) AS c FROM [{T}] GROUP BY 1 AS one",
     tbl([]), []),
    ("empty_table_order_limit",
     f"k FROM [{T}] ORDER BY k LIMIT 5", tbl([]), []),
    ("single_row_everything",
     f"k, v, k + v AS s FROM [{T}] WHERE k = 1 ORDER BY k LIMIT 1",
     tbl([(1, 2)]), [{"k": 1, "v": 2, "s": 3}]),
    ("all_rows_filtered_then_group",
     f"g, sum(v) AS s FROM [{T}] WHERE v > 999 GROUP BY g", GRP, []),
    ("group_by_key_column_itself",
     f"k, count(*) AS c FROM [{T}] GROUP BY k", tbl([(1, 0), (2, 0)]),
     [{"k": 1, "c": 1}, {"k": 2, "c": 1}]),
    ("between_strings",
     f"k FROM [{T}] WHERE s BETWEEN 'a' AND 'b'", STRS,
     [{"k": 1}, {"k": 6}]),
    ("in_with_duplicated_elements",
     f"k FROM [{T}] WHERE k IN (1, 1, 1, 2)", KV6,
     [{"k": 1}, {"k": 2}]),
    ("not_like",
     f"k FROM [{T}] WHERE s NOT LIKE '%a%'", STRS,
     [{"k": 3}, {"k": 5}]),
    ("like_escaped_nothing_special",
     f"k FROM [{T}] WHERE s LIKE 'apple pie'", STRS, [{"k": 6}]),
    ("where_between_and_in_combo",
     f"k FROM [{T}] WHERE k BETWEEN 0 AND 3 AND k IN (2, 3, 4)", KV6,
     [{"k": 2}, {"k": 3}]),
    ("avg_preserves_fraction",
     f"avg(v) AS a FROM [{T}] GROUP BY 1 AS one",
     tbl([(1, 1), (2, 2)]), [{"a": 1.5}]),
    ("sum_of_negatives",
     f"sum(v) AS s FROM [{T}] GROUP BY 1 AS one",
     tbl([(1, -5), (2, -7)]), [{"s": -12}]),
    ("count_on_expression",
     f"count(v / 0) AS c FROM [{T}] GROUP BY 1 AS one",
     tbl([(1, 5), (2, 6)]), [{"c": 0}]),
    ("max_of_mixed_sign_doubles",
     f"max(x) AS m FROM [{T}] GROUP BY 1 AS one",
     tbl([(1, -1.5), (2, -0.5)], DBL_COLS), [{"m": -0.5}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in EDGE],
                         ids=[c[0] for c in EDGE])
def test_type_and_edge_cases(query, tables, expected):
    run(query, tables, expected)


def test_string_between_via_dynamic_table(tmp_path):
    """End-to-end regression: string BETWEEN with non-vocabulary bounds
    through the full client path (dynamic store -> snapshot -> select),
    not just the harness chunks."""
    from ytsaurus_tpu.client import connect
    from ytsaurus_tpu.schema import TableSchema

    cl = connect(str(tmp_path / "c"))
    schema = TableSchema.make(
        [("k", "int64", "ascending"), ("s", "string"), ("v", "int64")],
        unique_keys=True)
    cl.create("table", "//q/t", recursive=True,
              attributes={"schema": schema, "dynamic": True})
    cl.mount_table("//q/t")
    cl.insert_rows("//q/t", [
        {"k": 1, "s": "apple", "v": 1},
        {"k": 2, "s": "Banana", "v": 2},
        {"k": 3, "s": "cherry", "v": 3}])
    rows = cl.select_rows("k FROM [//q/t] WHERE s BETWEEN 'a' AND 'b'")
    assert [r["k"] for r in rows] == [1]
    # Byte-wise: 'B' (0x42) < 'apple' (0x61...) < 'cherry' — all match.
    rows = cl.select_rows(
        "k FROM [//q/t] WHERE s BETWEEN 'B' AND 'cherry'")
    assert sorted(r["k"] for r in rows) == [1, 2, 3]
