"""Shard pruning: WHERE-derived column intervals vs chunk statistics.

Analog of the reference's range inference (library/query/base/key_trie.h +
CreateNewRangeInferrer): instead of building key ranges for tablet
coordination, the coordinator here prunes whole shards (chunks/tablets)
whose per-column min/max statistics cannot intersect the predicate.
Conservative: only top-level AND conjunctions of `col OP literal`,
BETWEEN and IN contribute; everything else keeps the shard.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ytsaurus_tpu.query import ir

_NEG_INF = object()
_POS_INF = object()


@dataclass
class Interval:
    lo: object = _NEG_INF
    hi: object = _POS_INF
    lo_incl: bool = True
    hi_incl: bool = True

    def intersect_point_set(self, values) -> "Interval":
        # IN (...) → widen to [min, max] of the set (conservative).
        lo = min(values)
        hi = max(values)
        return self.narrow(Interval(lo=lo, hi=hi))

    def narrow(self, other: "Interval") -> "Interval":
        lo, lo_incl = self.lo, self.lo_incl
        if other.lo is not _NEG_INF and (
                lo is _NEG_INF or _cmp(other.lo, lo) > 0 or
                (_cmp(other.lo, lo) == 0 and not other.lo_incl)):
            lo, lo_incl = other.lo, other.lo_incl
        hi, hi_incl = self.hi, self.hi_incl
        if other.hi is not _POS_INF and (
                hi is _POS_INF or _cmp(other.hi, hi) < 0 or
                (_cmp(other.hi, hi) == 0 and not other.hi_incl)):
            hi, hi_incl = other.hi, other.hi_incl
        return Interval(lo=lo, hi=hi, lo_incl=lo_incl, hi_incl=hi_incl)


def _cmp(a, b) -> int:
    a = _canon(a)
    b = _canon(b)
    return (a > b) - (a < b)


def _canon(v):
    if isinstance(v, str):
        return v.encode()
    if isinstance(v, bool):
        return int(v)
    return v


def extract_column_intervals(where: Optional[ir.TExpr]) -> dict[str, Interval]:
    """Per-column intervals implied by the predicate (conjunctions only)."""
    out: dict[str, Interval] = {}
    if where is None:
        return out

    def visit(e: ir.TExpr) -> None:
        if isinstance(e, ir.TBinary) and e.op == "and":
            visit(e.lhs)
            visit(e.rhs)
            return
        if isinstance(e, ir.TBinary) and e.op in ("=", "<", "<=", ">", ">="):
            ref, lit, op = _ref_literal(e)
            if ref is None:
                return
            iv = out.setdefault(ref, Interval())
            value = lit
            if op == "=":
                out[ref] = iv.narrow(Interval(lo=value, hi=value))
            elif op == "<":
                out[ref] = iv.narrow(Interval(hi=value, hi_incl=False))
            elif op == "<=":
                out[ref] = iv.narrow(Interval(hi=value))
            elif op == ">":
                out[ref] = iv.narrow(Interval(lo=value, lo_incl=False))
            elif op == ">=":
                out[ref] = iv.narrow(Interval(lo=value))
            return
        if isinstance(e, ir.TBetween) and not e.negated and \
                len(e.operands) == 1 and len(e.ranges) == 1 and \
                isinstance(e.operands[0], ir.TReference):
            (lower, upper) = e.ranges[0]
            # Null bounds admit null rows (null sorts first), which min/max
            # stats over non-null values cannot prune — no constraint.
            if len(lower) == 1 and len(upper) == 1 and \
                    lower[0] is not None and upper[0] is not None:
                name = e.operands[0].name
                iv = out.setdefault(name, Interval())
                out[name] = iv.narrow(Interval(lo=lower[0], hi=upper[0]))
            return
        if isinstance(e, ir.TIn) and len(e.operands) == 1 and \
                isinstance(e.operands[0], ir.TReference) and e.values:
            flat = [tup[0] for tup in e.values if tup[0] is not None]
            if flat and len(flat) == len(e.values):
                name = e.operands[0].name
                iv = out.setdefault(name, Interval())
                out[name] = iv.intersect_point_set(flat)
            return
        # Anything else (OR, functions, negations) → no constraint.

    visit(where)
    return out


def _ref_literal(e: ir.TBinary):
    """Normalize `ref OP literal` / `literal OP ref` to (ref, literal, op)."""
    flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}
    if isinstance(e.lhs, ir.TReference) and isinstance(e.rhs, ir.TLiteral) \
            and e.rhs.value is not None:
        return e.lhs.name, e.rhs.value, e.op
    if isinstance(e.rhs, ir.TReference) and isinstance(e.lhs, ir.TLiteral) \
            and e.lhs.value is not None:
        return e.rhs.name, e.lhs.value, flip[e.op]
    return None, None, None


def chunk_may_match(stats: dict, intervals: dict[str, Interval]) -> bool:
    """False only when a column's [min, max] provably misses its interval."""
    for name, interval in intervals.items():
        col = stats.get(name)
        if not col or col.get("min") is None or col.get("max") is None:
            continue
        cmin, cmax = _canon(col["min"]), _canon(col["max"])
        if interval.lo is not _NEG_INF:
            lo = _canon(interval.lo)
            # (Nulls never satisfy comparisons, so has_null cannot rescue a
            # shard whose non-null range misses the interval.)
            if cmax < lo or (cmax == lo and not interval.lo_incl):
                return False
        if interval.hi is not _POS_INF:
            hi = _canon(interval.hi)
            if cmin > hi or (cmin == hi and not interval.hi_incl):
                return False
    return True


def compute_column_stats(chunk) -> dict:
    """Host-side per-column min/max/has_null for pruning metadata.

    Since the stats moved into the chunk wire format (written once at
    serialize/seal time, read via `FsChunkStore.read_stats`), this is
    the BACKFILL path for already-decoded chunks — the implementation
    lives with the chunk layout in `chunks/columnar.py`."""
    from ytsaurus_tpu.chunks.columnar import chunk_column_stats
    return chunk_column_stats(chunk)
