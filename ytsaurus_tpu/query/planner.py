"""Cost-based join planning over chunk column stats (ISSUE 14).

The engine executed multiway joins exactly as declared: a left-to-right
binary cascade, each join paying its own exchange and its own host
syncs.  "Efficient Multiway Hash Join on Reconfigurable Hardware"
(arxiv 1905.13376) shows N-way joins fused into one partition pass beat
binary cascades on accelerator-shaped hardware — but fusing the wrong
ORDER fuses the wrong amount of data.  This module supplies the order:
a System-R-shaped greedy planner over REAL cardinalities that the chunk
layer already seals into metadata — `$row_count`, per-column min/max/
has_null, and (new) the 64-register distinct-count sketch
(`chunks/columnar.py::column_ndv_sketch`).

Decisions produced per query:

  join order       inner joins reorder most-selective-first (estimated
                   output cardinality via |R ⋈ S| = |R|·|S| /
                   max(ndv_R(k), ndv_S(k))), constrained by column
                   dependencies (a join whose key reads an earlier
                   join's pulled column cannot move before it) and by
                   LEFT-join barriers (outer joins pin their position —
                   reordering across one changes null-extension
                   semantics).
  side strategy    broadcast (small side replicates to every device —
                   no exchange) vs partition (co-partition both sides
                   by key hash), by foreign row count against
                   `CompileConfig.broadcast_join_rows`.  Broadcast
                   additionally requires unique foreign keys; the
                   execution layer verifies and falls back, and the
                   RESOLVED strategy folds into its cache key.
  semi-join ranges push the [min, max] of a selective INNER side's join
                   key down into the scan stage: the coordinator prunes
                   whole shards with it (`chunk_may_match`) and the
                   fused SPMD path masks rows BEFORE the first
                   exchange, so non-joining rows never ride all_to_all.

Compile-once contract (ISSUE 10/14): planner DECISIONS — order,
strategies, pushdown column sets — fold into every compiled-program
cache key (`JoinPlan.token()`; the reordered plan's fingerprint carries
the order).  Estimates and pushdown VALUES do not: estimates only rank
candidates, and pushdown bounds ride runtime bindings, so stats drift
that changes no decision changes no key (100% cache hit), while a
drift that flips a decision produces a NEW key (never a stale program).
"""

from __future__ import annotations

import threading
import weakref
from dataclasses import dataclass, replace as dc_replace
from typing import Mapping, Optional, Sequence

from ytsaurus_tpu.query import ir
from ytsaurus_tpu.utils import sanitizers

# Per-chunk stats memo: cost-based planning must not re-scan a chunk it
# already measured (the join-host memo discipline of distributed.py).
# Keyed by object identity with a liveness check; finalizers evict.
# guards: _stats_memo
_stats_lock = sanitizers.register_lock("planner._stats_lock")
_stats_memo: dict = {}
_STATS_MEMO_LIMIT = 512


def stats_for_chunk(chunk) -> dict:
    """chunk_column_stats(chunk), memoized per chunk identity — the
    backfill path when no sealed metadata stats are provided (engine
    entry points hold materialized chunks, not chunk ids)."""
    from ytsaurus_tpu.chunks.columnar import chunk_column_stats
    key = id(chunk)
    with _stats_lock:
        entry = _stats_memo.get(key)
        if entry is not None and entry[0]() is chunk:
            return entry[1]
    stats = chunk_column_stats(chunk)
    with _stats_lock:
        _stats_memo[key] = (weakref.ref(chunk), stats)
        while len(_stats_memo) > _STATS_MEMO_LIMIT:
            _stats_memo.pop(next(iter(_stats_memo)))
    return stats


def _stat_entry(stats: Optional[dict], name: str) -> Optional[dict]:
    if not stats:
        return None
    entry = stats.get(name)
    return entry if isinstance(entry, dict) else None


def _key_ndv(stats: Optional[dict], expr: ir.TExpr, rows: int) -> int:
    """NDV of a join-key expression: the sketch estimate for a bare
    column reference, else the conservative bound (row count)."""
    from ytsaurus_tpu.chunks.columnar import ndv_estimate
    if isinstance(expr, ir.TReference):
        entry = _stat_entry(stats, expr.name)
        if entry is not None and entry.get("ndv_sketch") is not None:
            est = ndv_estimate(entry.get("ndv_sketch"))
            if est > 0:
                return min(est, max(rows, 1))
    return max(rows, 1)


@dataclass(frozen=True)
class JoinDecision:
    """One join's planned execution."""
    index: int              # position in the ORIGINAL plan.joins tuple
    strategy: str           # "broadcast" | "partition"
    est_in: int             # estimated rows entering the join
    est_out: int            # estimated rows leaving it
    foreign_rows: int
    # INNER-side semi-join ranges pushed into the scan stage:
    # ((self_column, lo, hi), ...) — values are HOST data for shard
    # pruning; the fused path re-binds them as runtime bindings.
    pushdown: tuple = ()


@dataclass(frozen=True)
class JoinPlan:
    """The planner's answer for one query's join set, in execution
    order.  `token()` is the cache-key contribution: decisions only,
    never estimates or pushdown values (see module docstring)."""
    decisions: tuple

    @property
    def order(self) -> tuple:
        return tuple(d.index for d in self.decisions)

    def token(self) -> tuple:
        return tuple(
            (d.index, d.strategy,
             tuple(name for name, _lo, _hi in d.pushdown))
            for d in self.decisions)

    def pushdown_ranges(self) -> tuple:
        """Flat ((self_column, lo, hi), ...) across every decision."""
        out = []
        for d in self.decisions:
            out.extend(d.pushdown)
        return tuple(out)


def est_drift(est_rows, actual_rows) -> float:
    """Relative estimate error |actual - est| / max(actual, 1) — the
    one planner-feedback number the workload ledger (ISSUE 20's
    `join_est_error`), the mesh observatory, and EXPLAIN ANALYZE all
    agree on.  0.0 when no estimate was recorded (est <= 0): drift
    measures a WRONG estimate, not a missing one."""
    est = int(est_rows or 0)
    actual = int(actual_rows or 0)
    if est <= 0:
        return 0.0
    return round(abs(actual - est) / float(max(actual, 1)), 4)


def _base_columns(plan: ir.Query) -> set:
    """Self-table columns (plan.schema minus join-contributed names)."""
    joined = set()
    for join in plan.joins:
        for fname in join.foreign_columns:
            joined.add(f"{join.alias}.{fname}" if join.alias else fname)
    return {c.name for c in plan.schema if c.name not in joined}


def _join_outputs(join: ir.JoinClause) -> set:
    return {f"{join.alias}.{f}" if join.alias else f
            for f in join.foreign_columns}


def _join_inputs(join: ir.JoinClause) -> set:
    refs: set = set()
    for eq in join.self_equations:
        refs.update(ir.expr_references(eq))
    return refs


def _pushdown_for(join: ir.JoinClause, f_stats: Optional[dict],
                  base_columns: set) -> tuple:
    """Semi-join scan ranges a selective INNER side implies: only bare
    column = bare column equations qualify (range semantics need a raw
    self column, stats lookup needs a raw foreign column), and only
    bounded stats contribute (None bound = unprunable, PR 5 contract)."""
    if join.is_left or not f_stats:
        return ()
    out = []
    for self_eq, f_eq in zip(join.self_equations, join.foreign_equations):
        if not (isinstance(self_eq, ir.TReference)
                and isinstance(f_eq, ir.TReference)):
            continue
        if self_eq.name not in base_columns:
            continue
        entry = _stat_entry(f_stats, f_eq.name)
        if entry is None:
            continue
        lo, hi = entry.get("min"), entry.get("max")
        if lo is None or hi is None:
            continue
        out.append((self_eq.name, lo, hi))
    return tuple(out)


def plan_joins(plan: ir.Query, self_rows: int,
               foreign_stats: Mapping[str, Optional[dict]],
               self_stats: Optional[dict] = None) -> Optional[JoinPlan]:
    """Plan `plan.joins` (None when there is nothing to plan or the
    planner is configured off).

    `foreign_stats` maps foreign table path → merged column stats
    (sealed chunk metadata via merge_column_stats, or stats_for_chunk
    over a materialized chunk); missing/None entries degrade that side
    to conservative estimates.  `self_stats` (optional) sharpens the
    self-side NDV in the standard |R|·|S|/max(ndv_R, ndv_S) estimate.
    """
    from ytsaurus_tpu.config import compile_config
    if not plan.joins:
        return None
    cfg = compile_config()
    if not cfg.cost_join_planner:
        return None
    base = _base_columns(plan)
    broadcast_cap = cfg.broadcast_join_rows

    # LEFT joins are barriers: blocks of consecutive INNER joins reorder
    # internally; everything else keeps declared order.
    blocks: list = []          # list of lists of original indices
    for i, join in enumerate(plan.joins):
        if join.is_left:
            blocks.append([i])
        elif blocks and not plan.joins[blocks[-1][0]].is_left \
                and not plan.joins[blocks[-1][-1]].is_left:
            blocks[-1].append(i)
        else:
            blocks.append([i])

    def f_rows_of(join) -> Optional[int]:
        stats = foreign_stats.get(join.foreign_table)
        if stats and "$row_count" in stats:
            return int(stats["$row_count"])
        return None                 # unknown — not the same as empty

    def est_factor(join, est_in: int) -> float:
        """Estimated output multiplier of applying `join` to est_in
        rows: |out| / |in| = |S| / max(ndv_R(k), ndv_S(k)) per standard
        equi-join selectivity, multiplied across multi-column keys by
        taking the most selective single column (conservative)."""
        stats = foreign_stats.get(join.foreign_table)
        f_rows = f_rows_of(join)
        if f_rows is None:
            return 1.0              # no stats: neutral, keep declared rank
        if f_rows == 0:
            return 0.0 if not join.is_left else 1.0
        factor = float(f_rows)
        best = None
        for self_eq, f_eq in zip(join.self_equations,
                                 join.foreign_equations):
            ndv_f = _key_ndv(stats, f_eq, f_rows)
            ndv_s = _key_ndv(self_stats, self_eq, max(est_in, 1)) \
                if self_stats is not None else ndv_f
            cand = float(f_rows) / float(max(ndv_f, ndv_s, 1))
            best = cand if best is None else min(best, cand)
        if best is not None:
            factor = best
        if join.is_left:
            factor = max(factor, 1.0)
        return factor

    decisions: list = []
    est = max(self_rows, 1)
    for block in blocks:
        remaining = list(block)
        placed_outputs: set = set(base)
        for d in decisions:
            placed_outputs |= _join_outputs(plan.joins[d.index])
        while remaining:
            ready = [i for i in remaining
                     if _join_inputs(plan.joins[i]) <= placed_outputs]
            if not ready:
                # Unresolvable dependency inside the block (key reads a
                # column a LATER block pulls): keep declared order.
                ready = [remaining[0]]
            pick = min(ready,
                       key=lambda i: (est_factor(plan.joins[i], est), i))
            remaining.remove(pick)
            join = plan.joins[pick]
            f_rows = f_rows_of(join)
            factor = est_factor(join, est)
            est_out = max(int(est * factor), 1)
            if join.is_left:
                est_out = max(est_out, est)
            strategy = "broadcast" if f_rows is not None \
                and 0 < f_rows <= broadcast_cap else "partition"
            f_rows = f_rows if f_rows is not None else 0
            decisions.append(JoinDecision(
                index=pick, strategy=strategy, est_in=est,
                est_out=est_out, foreign_rows=f_rows,
                pushdown=_pushdown_for(
                    join, foreign_stats.get(join.foreign_table), base)))
            placed_outputs |= _join_outputs(join)
            est = est_out
    return JoinPlan(decisions=tuple(decisions))


def apply_order(plan: ir.Query, jplan: Optional[JoinPlan]) -> ir.Query:
    """The plan with joins permuted into execution order.  The permuted
    plan's fingerprint IS how the order reaches every compiled-program
    cache key (a stats-driven order flip can never serve a stale
    program)."""
    if jplan is None:
        return plan
    order = jplan.order
    if order == tuple(range(len(plan.joins))):
        return plan
    return dc_replace(plan,
                      joins=tuple(plan.joins[i] for i in order))


def plan_for_chunks(plan: ir.Query, self_rows: int,
                    foreign_chunks: Optional[Mapping] = None,
                    foreign_stats: Optional[Mapping] = None
                    ) -> Optional[JoinPlan]:
    """plan_joins with stats sourced from materialized foreign chunks
    (memoized per chunk) unless sealed-metadata stats are supplied."""
    if not plan.joins:
        return None
    stats: dict = dict(foreign_stats or {})
    for join in plan.joins:
        if join.foreign_table in stats:
            continue
        chunk = (foreign_chunks or {}).get(join.foreign_table)
        stats[join.foreign_table] = \
            stats_for_chunk(chunk) if chunk is not None else None
    return plan_joins(plan, self_rows, stats)


def reorder_for_chunks(plan: ir.Query, self_rows: int,
                       foreign_chunks: Optional[Mapping] = None
                       ) -> "tuple[ir.Query, Optional[JoinPlan]]":
    """(execution-ordered plan, JoinPlan) — the one-call form the
    evaluator's join cascade and the stitched SPMD paths use."""
    jplan = plan_for_chunks(plan, self_rows, foreign_chunks)
    return apply_order(plan, jplan), jplan


def pushdown_intervals(plan: ir.Query,
                       foreign_stats: Mapping[str, Optional[dict]]
                       ) -> dict:
    """Scan-stage shard-pruning intervals implied by selective INNER
    join sides: {self_column: pruning.Interval}.  The coordinator
    intersects these with the WHERE-derived intervals, so shards whose
    key range cannot join anything are never staged."""
    from ytsaurus_tpu.config import compile_config
    from ytsaurus_tpu.query.pruning import Interval
    if not compile_config().cost_join_planner:
        return {}
    base = _base_columns(plan)
    out: dict = {}
    for join in plan.joins:
        for name, lo, hi in _pushdown_for(
                join, foreign_stats.get(join.foreign_table), base):
            iv = out.get(name, Interval())
            out[name] = iv.narrow(Interval(lo=lo, hi=hi))
    return out
