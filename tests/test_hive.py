"""Hive mailboxes: exactly-once ordered cross-cell messaging.

Ref model: server/lib/hive/hive_manager.h — durable outboxes with
monotone seqnos, receiver-side dedupe, message application as an atomic
mutation on the receiving cell.
"""

import pytest

from ytsaurus_tpu import YtError
from ytsaurus_tpu.client import connect
from ytsaurus_tpu.cypress.hive import HiveManager


def counter_handler(client):
    """Message effects: append the payload value to //hive_log."""
    def handler(payload):
        log = list(client.get("//hive_log")) \
            if client.exists("//hive_log") else []
        ops = []
        if not client.exists("//hive_log"):
            ops.append(("create", {"path": "//hive_log",
                                   "type": "document"}))
        ops.append(("set", {"path": "//hive_log",
                            "value": log + [payload["value"]]}))
        return ops
    return handler


@pytest.fixture
def cells(tmp_path):
    a = connect(str(tmp_path / "a"))
    b = connect(str(tmp_path / "b"))
    ha = HiveManager(a, "cell-a")
    hb = HiveManager(b, "cell-b")
    hb.register_handler("append", counter_handler(b))
    return a, b, ha, hb


def test_ordered_exactly_once_delivery(cells):
    a, b, ha, hb = cells
    for v in (1, 2, 3):
        ha.post("cell-b", "append", {"value": v})
    assert ha.pending("cell-b") == 3
    assert ha.flush(hb) == 3
    assert b.get("//hive_log") == [1, 2, 3]
    # Redelivery is a no-op (dedupe by seqno), outbox trimmed.
    assert ha.flush(hb) == 0
    assert ha.pending("cell-b") == 0
    assert b.get("//hive_log") == [1, 2, 3]
    # Later messages continue the sequence.
    ha.post("cell-b", "append", {"value": 4})
    assert ha.flush(hb) == 1
    assert b.get("//hive_log") == [1, 2, 3, 4]


def test_gap_detection(cells):
    a, b, ha, hb = cells
    with pytest.raises(YtError):
        hb.apply("cell-a", {"seqno": 5, "type": "append",
                            "payload": {"value": 9}})


def test_survives_restart_without_double_apply(tmp_path):
    a = connect(str(tmp_path / "a"))
    b = connect(str(tmp_path / "b"))
    ha = HiveManager(a, "cell-a")
    hb = HiveManager(b, "cell-b")
    hb.register_handler("append", counter_handler(b))
    ha.post("cell-b", "append", {"value": 10})
    ha.post("cell-b", "append", {"value": 20})
    ha.flush(hb)
    # Both cells restart (WAL replay); the sender retries everything
    # still in its outbox — nothing may double-apply.
    a2 = connect(str(tmp_path / "a"), fresh=True)
    b2 = connect(str(tmp_path / "b"), fresh=True)
    ha2 = HiveManager(a2, "cell-a")
    hb2 = HiveManager(b2, "cell-b")
    hb2.register_handler("append", counter_handler(b2))
    assert ha2.flush(hb2) == 0
    assert b2.get("//hive_log") == [10, 20]
    ha2.post("cell-b", "append", {"value": 30})
    assert ha2.flush(hb2) == 1
    assert b2.get("//hive_log") == [10, 20, 30]


def test_atomic_application(cells):
    """A handler emitting an invalid op applies NOTHING — no ack bump,
    no partial effects (the batch mutation is all-or-nothing)."""
    a, b, ha, hb = cells

    def bad_handler(payload):
        return [("set", {"path": "//ok_part", "value": 1}),
                ("copy", {"src": "//x", "dst": "//y"})]   # not allowed

    hb.register_handler("bad", bad_handler)
    ha.post("cell-b", "bad", {})
    with pytest.raises(YtError):
        ha.flush(hb)
    assert not b.exists("//ok_part")
    assert hb.last_applied("cell-a") == 0
    # The message stays queued for a fixed handler.
    assert ha.pending("cell-b") == 1


def test_bidirectional_mailboxes(cells):
    a, b, ha, hb = cells
    ha.register_handler("append", counter_handler(a))
    hb.post("cell-a", "append", {"value": 100})
    assert hb.flush(ha) == 1
    assert a.get("//hive_log") == [100]
    # Inbox/outbox state is per-direction.
    assert hb.last_applied("cell-a") == 0
    assert ha.last_applied("cell-b") == 1


def test_batch_mid_failure_rolls_back(tmp_path):
    """A batch whose sub-op fails on RESOLUTION mid-way must leave no
    partial effects: earlier sub-ops roll back, no WAL record is written,
    and the master keeps serving (not poisoned)."""
    client = connect(str(tmp_path / "c"))
    master = client.cluster.master
    client.create("document", "//existing")
    with pytest.raises(YtError):
        master.commit_mutation("batch", ops=[
            {"op": "create", "args": {"path": "//fresh",
                                      "type": "document"}},
            {"op": "set", "args": {"path": "//fresh", "value": 7}},
            # Fails: create over an existing node.
            {"op": "create", "args": {"path": "//existing",
                                      "type": "document"}},
        ])
    # Earlier sub-ops rolled back.
    assert not client.exists("//fresh")
    # Master still serves mutations (atomic failure, not poison).
    client.create("document", "//after")
    assert client.exists("//after")
    # Replay agrees: no partial batch in the WAL.
    from ytsaurus_tpu.cypress.master import Master
    reloaded = Master(master.root_dir)
    assert reloaded.tree.try_resolve("//fresh") is None
    assert reloaded.tree.try_resolve("//after") is not None


def test_concurrent_posts_lose_no_message(tmp_path):
    """Racing posters must not lose a message or duplicate a seqno
    (outbox read-modify-write is serialized per manager)."""
    import threading
    client = connect(str(tmp_path / "c"))
    hive = HiveManager(client, "cell-x")
    n_threads, per_thread = 4, 25
    def poster(k):
        for i in range(per_thread):
            hive.post("cell-y", "append", {"value": (k, i)})
    threads = [threading.Thread(target=poster, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    state = client.get("//sys/hive/cell-x/outbox/cell-y")
    seqnos = [m["seqno"] for m in state["messages"]]
    assert len(seqnos) == n_threads * per_thread
    assert sorted(seqnos) == list(range(1, n_threads * per_thread + 1))


def test_batch_malformed_subop_rolls_back(tmp_path):
    """A sub-op raising a NON-YtError (malformed args) must also roll
    back — not leave earlier sub-ops applied with no WAL record."""
    client = connect(str(tmp_path / "c"))
    master = client.cluster.master
    with pytest.raises(KeyError):
        master.commit_mutation("batch", ops=[
            {"op": "create", "args": {"path": "//first",
                                      "type": "document"}},
            {"op": "create", "args": {"type": "document"}},   # no path
        ])
    assert not client.exists("//first")
    client.create("document", "//after")        # not poisoned


def test_batch_recursive_create_rolls_back_ancestors(tmp_path):
    """Rollback of a recursive create removes the TOPMOST materialized
    node, not just the leaf."""
    client = connect(str(tmp_path / "c"))
    master = client.cluster.master
    client.create("document", "//existing")
    with pytest.raises(YtError):
        master.commit_mutation("batch", ops=[
            {"op": "create", "args": {"path": "//x/y/z", "type": "document",
                                      "recursive": True}},
            {"op": "create", "args": {"path": "//existing",
                                      "type": "document"}},
        ])
    assert not client.exists("//x")
    from ytsaurus_tpu.cypress.master import Master
    reloaded = Master(master.root_dir)
    assert reloaded.tree.try_resolve("//x") is None
