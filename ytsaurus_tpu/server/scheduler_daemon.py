"""Scheduler / controller-agent daemon: operation control OUT of the
master process.

Ref: the reference runs schedulers (server/scheduler/) and controller
agents (server/controller_agent/) as processes separate from masters —
an operation storm must not contend with the metadata quorum's mutation
path, and controller crashes must not take masters down.  This daemon
realizes that split: it owns an OperationScheduler over a REMOTE thin
client, so every byte of operation state it needs to survive lives in
Cypress (//sys/operations documents + @snapshot chunks), and a freshly
restarted daemon revives its predecessor's orphaned operations from
there (ref revival from snapshots, master connector re-registration).

Only deterministic specs travel the wire (shell commands; Python
callables cannot cross a process boundary) — the same restriction
revival already imposes.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.rpc import Channel, RetryingChannel, RpcServer
from ytsaurus_tpu.rpc.server import Service, rpc_method
from ytsaurus_tpu.rpc.wire import wire_text as _text
from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("scheduler_daemon")


class OperationService(Service):
    """RPC surface of the operation daemon (ref scheduler's
    StartOperation/GetOperation/AbortOperation API)."""

    name = "operations"

    def __init__(self, scheduler):
        self.scheduler = scheduler

    @rpc_method()
    def start_operation(self, body, attachments):
        op_type = _text(body["type"])
        spec = dict(body.get("spec") or {})
        # Async by contract: controllers run minutes; the RPC returns
        # the id and callers poll (ref StartOperation semantics).
        op = self.scheduler.start_operation(op_type, spec, sync=False)
        return {"op_id": op.id}

    @rpc_method()
    def get_operation(self, body, attachments):
        op = self.scheduler.get_operation(_text(body["op_id"]))
        return {"id": op.id, "type": op.type, "state": op.state,
                "error": op.error, "result": op.result,
                "progress": op.progress}

    @rpc_method()
    def abort_operation(self, body, attachments):
        op = self.scheduler.abort_operation(_text(body["op_id"]))
        return {"id": op.id, "state": op.state}

    @rpc_method()
    def list_operations(self, body, attachments):
        return {"operations": [
            {"id": op.id, "type": op.type, "state": op.state}
            for op in self.scheduler.list_operations()]}


def run_scheduler(root: str, port: int, primary: str,
                  slots: int = 4) -> None:
    """Daemon entry: thin client to the masters, scheduler on top, RPC
    in front, revival of orphaned operations behind."""
    import os

    from ytsaurus_tpu.operations.scheduler import OperationScheduler
    from ytsaurus_tpu.remote_client import RemoteYtClient
    from ytsaurus_tpu.server.daemon import _write_port_file

    os.makedirs(root, exist_ok=True)
    client = RemoteYtClient(primary)
    scheduler = OperationScheduler(client, slots=slots)
    server = RpcServer([OperationService(scheduler)], port=port)
    server.start()
    _write_port_file(root, "scheduler", server.port)
    print(f"scheduler daemon serving on {server.address} -> {primary}",
          flush=True)

    def revive():
        # A predecessor's operations sit 'running' in Cypress with
        # per-stripe snapshots; re-run them (completed stripes skip).
        try:
            revived = scheduler.revive_operations()
            if revived:
                print(f"revived {len(revived)} orphaned operations",
                      flush=True)
        except YtError as exc:
            logger.warning("revival failed: %s", exc)

    threading.Thread(target=revive, daemon=True,
                     name="operation-revival").start()
    threading.Event().wait()


class SchedulerClient:
    """Thin client for the operation daemon: submit + poll.  Mirrors
    the YtClient run_* surface for command-based (wire-safe) specs."""

    def __init__(self, address: str, timeout: float = 60.0):
        self._channel = RetryingChannel(Channel(address, timeout=timeout))

    def close(self) -> None:
        self._channel.close()

    def start_operation(self, op_type: str, spec: dict) -> str:
        body, _ = self._channel.call(
            "operations", "start_operation",
            {"type": op_type, "spec": spec}, idempotent=False)
        return _text(body["op_id"])

    def get_operation(self, op_id: str) -> dict:
        body, _ = self._channel.call("operations", "get_operation",
                                     {"op_id": op_id})
        return {"id": _text(body["id"]), "type": _text(body["type"]),
                "state": _text(body["state"]),
                "error": body.get("error"),
                "result": body.get("result") or {},
                "progress": body.get("progress") or {}}

    def abort_operation(self, op_id: str) -> dict:
        body, _ = self._channel.call("operations", "abort_operation",
                                     {"op_id": op_id}, idempotent=False)
        return {"id": _text(body["id"]), "state": _text(body["state"])}

    def list_operations(self) -> "list[dict]":
        body, _ = self._channel.call("operations", "list_operations", {})
        return [{"id": _text(o["id"]), "type": _text(o["type"]),
                 "state": _text(o["state"])}
                for o in body.get("operations") or []]

    def wait_operation(self, op_id: str, timeout: float = 300.0,
                       poll: float = 0.2) -> dict:
        """Poll to a terminal state; raises the operation's error on
        failure (ref wait_for_operation)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                op = self.get_operation(op_id)
            except YtError as exc:
                if exc.code in (EErrorCode.TransportError,
                                EErrorCode.PeerUnavailable,
                                EErrorCode.RpcTimeout,
                                EErrorCode.NoSuchOperation):
                    # Daemon mid-restart: the operation revives from its
                    # Cypress record shortly; keep polling.  (An id that
                    # never existed times out instead of erroring — the
                    # price of restart transparency.)
                    time.sleep(poll)
                    continue
                raise
            if op["state"] == "completed":
                return op
            if op["state"] in ("failed", "aborted"):
                if op.get("error"):
                    raise YtError.from_dict(op["error"])
                raise YtError(f"operation {op_id} {op['state']}",
                              code=EErrorCode.OperationFailed)
            time.sleep(poll)
        raise YtError(f"operation {op_id} did not finish in {timeout}s",
                      code=EErrorCode.Timeout)

    # -- convenience run_* (command specs only) --------------------------------

    def run_sort(self, input_path: str, output_path: str,
                 sort_by: "Sequence[str] | str", **kw) -> str:
        return self.start_operation("sort", {
            "input_table_path": input_path,
            "output_table_path": output_path,
            "sort_by": [sort_by] if isinstance(sort_by, str)
            else list(sort_by), "raise_on_failure": False, **kw})

    def run_map(self, command: str, input_path: str, output_path: str,
                **kw) -> str:
        return self.start_operation("map", {
            "command": command, "input_table_path": input_path,
            "output_table_path": output_path,
            "raise_on_failure": False, **kw})

    def run_reduce(self, command: str, input_path: str, output_path: str,
                   reduce_by, **kw) -> str:
        return self.start_operation("reduce", {
            "command": command, "input_table_path": input_path,
            "output_table_path": output_path, "reduce_by": reduce_by,
            "raise_on_failure": False, **kw})

    def run_map_reduce(self, map_command: "Optional[str]",
                       reduce_command: str, input_path: str,
                       output_path: str, reduce_by, **kw) -> str:
        spec = {"reduce_command": reduce_command,
                "input_table_path": input_path,
                "output_table_path": output_path, "reduce_by": reduce_by,
                "raise_on_failure": False, **kw}
        if map_command:
            spec["map_command"] = map_command
        return self.start_operation("map_reduce", spec)
