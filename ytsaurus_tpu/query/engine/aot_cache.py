"""Persistent AOT compile-artifact cache (ISSUE 10 tentpole, piece c).

The disk tier of the compile ladder: memory LRU → THIS → fresh compile.
AOT-compiled executables (jax serialize_executable products of the
evaluator's `lower().compile()`) persist to a bounded directory keyed
by (plan shape fingerprint, capacity bucket, binding shapes/structure,
backend, jax version), so a rolling restart of query daemons
WARM-STARTS: the first query of each shape deserializes a ready
executable in milliseconds instead of cold-compiling it — the XLA
analog of the reference's on-disk LLVM image cache discipline
(engine_api/cg_cache.h keyed by llvm::FoldingSet fingerprint).

Safety posture is LOUD-BUT-SAFE: every artifact carries a versioned
JSON header that is refused loudly (warning log + `disk_errors`
sensor) on an aot-schema / jax-version / backend mismatch — the same
versioned-capture discipline as the workload log — and ANY load
failure (truncated file, pickle corruption, deserialize error) falls
back to a fresh compile; a query can never fail because the disk tier
rotted.  The directory is size-capped with oldest-mtime eviction
(loads touch mtime, so eviction is LRU-ish across processes sharing
the cache dir).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
from typing import Optional

import jax

from ytsaurus_tpu.utils.logging import get_logger
from ytsaurus_tpu.utils.profiling import Profiler
from ytsaurus_tpu.utils import sanitizers

logger = get_logger("AotCache")

# Bump when the on-disk artifact layout changes incompatibly: readers
# refuse mismatched headers loudly instead of unpickling garbage.
AOT_SCHEMA_VERSION = 1

_SUFFIX = ".aot"


def _backend() -> str:
    try:
        return jax.default_backend()
    except Exception:   # noqa: BLE001 — backend probe must never raise
        return "unknown"


class DiskCompileCache:
    """One process's view of an on-disk compile-artifact directory."""

    def __init__(self, config):
        self._dir = config.disk_cache_dir
        self._capacity_bytes = config.disk_cache_capacity_bytes
        self._min_seconds = config.disk_cache_min_compile_seconds
        # guards: bytes_n, files_n (gauge mirrors), eviction scans;
        # load/store file I/O itself is atomic-per-file (tmp+replace).
        # hot=False: this lock intentionally covers disk scans.
        self._lock = sanitizers.register_lock(
            "aot_cache.DiskCompileCache._lock", hot=False)
        self.hits_n = 0
        self.misses_n = 0
        self.errors_n = 0
        self.stores_n = 0
        self.evictions_n = 0
        prof = Profiler("/query/compile_cache")
        self._hits = prof.counter("disk_hits")
        self._misses = prof.counter("disk_misses")
        self._errors = prof.counter("disk_errors")
        self._bytes = prof.gauge("disk_bytes")
        self._files = prof.gauge("disk_files")
        self._refresh_gauges()

    # -- keying ----------------------------------------------------------------

    def _path(self, key: tuple) -> str:
        """Artifact path for one full compile-cache key.  The digest
        covers the key (fingerprint, capacity, binding shapes +
        structure — all plain ints/strings, stable across processes)
        plus backend and jax version, so an upgraded daemon simply sees
        a cold cache rather than refusing every file."""
        text = repr((key, _backend(), jax.__version__,
                     AOT_SCHEMA_VERSION))
        digest = hashlib.sha256(text.encode()).hexdigest()[:40]
        return os.path.join(self._dir, digest + _SUFFIX)

    # -- load ------------------------------------------------------------------

    def load(self, key: tuple):
        """Deserialize the executable for `key`, or None (counted as a
        disk miss / error).  Never raises."""
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                header_line = f.readline()
                header = json.loads(header_line or b"{}")
                problem = self._header_problem(header)
                if problem is not None:
                    logger.warning(
                        "refusing compile artifact %s: %s", path, problem)
                    self._count_error()
                    return None
                payload, in_tree, out_tree = pickle.loads(f.read())
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )
            fn = deserialize_and_load(payload, in_tree, out_tree)
        except FileNotFoundError:
            self._count_miss()
            return None
        except Exception as exc:   # noqa: BLE001 — loud-but-safe: a
            # rotted artifact (truncation, pickle/deserialize failure)
            # must fall back to a fresh compile, never fail the query.
            logger.warning("compile artifact %s unreadable (%r); "
                           "falling back to fresh compile", path, exc)
            self._count_error()
            return None
        try:
            os.utime(path)           # LRU touch for mtime eviction
        except OSError:
            pass
        with self._lock:
            self.hits_n += 1
        self._hits.increment()
        return fn

    def _header_problem(self, header: dict) -> Optional[str]:
        if not isinstance(header, dict):
            return "missing header"
        if header.get("aot_schema") != AOT_SCHEMA_VERSION:
            return (f"aot schema {header.get('aot_schema')!r}, this "
                    f"build speaks {AOT_SCHEMA_VERSION}")
        if header.get("jax") != jax.__version__:
            return (f"compiled under jax {header.get('jax')!r}, this "
                    f"process runs {jax.__version__}")
        if header.get("backend") != _backend():
            return (f"compiled for backend {header.get('backend')!r}, "
                    f"this process runs {_backend()!r}")
        return None

    # -- store -----------------------------------------------------------------

    def store(self, key: tuple, compiled, fingerprint: str,
              compile_seconds: float) -> bool:
        """Serialize one freshly AOT-compiled executable.  Best-effort:
        failures are counted + logged, never raised."""
        if compile_seconds < self._min_seconds:
            return False
        path = self._path(key)
        try:
            from jax.experimental.serialize_executable import serialize
            payload, in_tree, out_tree = serialize(compiled)
            header = json.dumps({
                "aot_schema": AOT_SCHEMA_VERSION,
                "jax": jax.__version__,
                "backend": _backend(),
                "fingerprint": fingerprint,
                "compile_seconds": round(compile_seconds, 6),
                "created_at": time.time(),
            }).encode() + b"\n"
            blob = pickle.dumps((payload, in_tree, out_tree))
            os.makedirs(self._dir, exist_ok=True)
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(header)
                f.write(blob)
            os.replace(tmp, path)
        except Exception as exc:   # noqa: BLE001 — persistence is an
            # optimization; a full disk or an unserializable executable
            # (callbacks, donated buffers) must not fail the query.
            logger.warning("cannot persist compile artifact %s: %r",
                           path, exc)
            self._count_error()
            return False
        with self._lock:
            self.stores_n += 1
            self._evict_locked()
        return True

    # -- bounds ----------------------------------------------------------------

    def _scan_locked(self) -> list[tuple[float, int, str]]:
        """(mtime, size, path) per artifact; unreadable entries skipped."""
        out = []
        try:
            names = os.listdir(self._dir)
        except OSError:
            return out
        for name in names:
            if not name.endswith(_SUFFIX):
                continue
            path = os.path.join(self._dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append((st.st_mtime, st.st_size, path))
        return out

    def _evict_locked(self) -> None:
        entries = self._scan_locked()
        total = sum(size for _mt, size, _p in entries)
        if self._capacity_bytes and total > self._capacity_bytes:
            for _mtime, size, path in sorted(entries):
                try:
                    os.remove(path)
                except OSError:
                    continue
                self.evictions_n += 1
                total -= size
                if total <= self._capacity_bytes:
                    break
            entries = self._scan_locked()
            total = sum(size for _mt, size, _p in entries)
        self._bytes.set(float(total))
        self._files.set(float(len(entries)))

    def _refresh_gauges(self) -> None:
        with self._lock:
            entries = self._scan_locked()
            self._bytes.set(float(sum(s for _m, s, _p in entries)))
            self._files.set(float(len(entries)))

    def _count_miss(self) -> None:
        with self._lock:
            self.misses_n += 1
        self._misses.increment()

    def _count_error(self) -> None:
        with self._lock:
            self.errors_n += 1
        self._errors.increment()

    # -- views -----------------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            entries = self._scan_locked()
            return {
                "dir": self._dir,
                "hits": self.hits_n,
                "misses": self.misses_n,
                "errors": self.errors_n,
                "stores": self.stores_n,
                "evictions": self.evictions_n,
                "files": len(entries),
                "bytes": sum(s for _m, s, _p in entries),
                "capacity_bytes": self._capacity_bytes,
            }


# -- globals -------------------------------------------------------------------

_cache: Optional[DiskCompileCache] = None
_cache_dir: Optional[str] = None
# guards: _cache, _cache_dir
_cache_lock = sanitizers.register_lock("aot_cache._cache_lock",
                                       hot=False)


def get_disk_cache() -> Optional[DiskCompileCache]:
    """The process disk tier, or None when CompileConfig.disk_cache_dir
    is unset (the default — tests and serving opt in explicitly)."""
    global _cache, _cache_dir
    from ytsaurus_tpu.config import compile_config
    cfg = compile_config()
    if not cfg.disk_cache_dir:
        return None
    with _cache_lock:
        if _cache is None or _cache_dir != cfg.disk_cache_dir:
            _cache = DiskCompileCache(cfg)
            _cache_dir = cfg.disk_cache_dir
        return _cache


def configure(cfg) -> None:
    """Rebind the global disk cache (called by config.set_compile_config;
    None restores the lazy default)."""
    global _cache, _cache_dir
    with _cache_lock:
        if cfg is None or not cfg.disk_cache_dir:
            _cache, _cache_dir = None, None
        else:
            _cache = DiskCompileCache(cfg)
            _cache_dir = cfg.disk_cache_dir
