"""Whole-plan SPMD execution: the entire distributed query as ONE program.

The stitched rungs of `coordinate_distributed` re-enter Python between
phases — `_finish_shuffled` runs a count program, blocks on a host read
to size the exchange quota, then runs the exchange program; the host
coordinator stitches N per-shard programs with Python glue.  Flare
(arxiv 1703.08219) and the JIT-in-databases survey (arxiv 2311.04692)
both locate the payoff of native compilation in the WHOLE-QUERY unit:
collapsing the interpretive glue between stages, not the operators.
This module is that collapse for the mesh: scan→filter→[partial
aggregate]→shuffle→aggregate/window→order/topk/project lowers as ONE
`jit(shard_map(...))` program over the `'shard'` axis, with
`with_sharding_constraint` pinning the inputs to the partition-rule
registry's placement and in-program collectives (all_to_all routing,
all_gather merge) replacing the Python-stitched exchanges.

Stage placement is driven by a partition-rule registry (the
`match_partition_rules` idiom of SNIPPETS.md [2]: stage-name regex →
PartitionSpec): `scan/<column>`, `filter`, `bottom/*`, `shuffle/*` and
`local/*` stages map onto `P('shard')`; `front`, `order`, `topk`,
`project`, `limit` are replicated (they run over the all_gathered
rowset on every device).  The registry digest folds into the program
cache key, so a placement change can never serve a stale executable.

The data-dependent decision the stitched path syncs for — the exchange
quota — moves from a per-query host read to a CACHED decision: the
fused program runs with a static pow2 quota, computes the true
transfer-matrix maximum on device, and returns it (with an overflow
flag) stacked WITH the result count — one final device→host transfer,
the only host sync in the whole plan.  On overflow the query re-runs
at the demanded quota (a fresh pow2 rung of the same compile-once
ladder) and the settled quota is memoized per plan shape, so steady
serving never syncs mid-plan and never overflows.  Unfusable plans
(joins, WITH TOTALS) and any in-program fault degrade to the stitched
ladder in `coordinate_distributed`.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import replace as dc_replace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ytsaurus_tpu.parallel.compat import shard_map

from ytsaurus_tpu.chunks.columnar import pad_capacity
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.parallel.mesh import SHARD_AXIS
from ytsaurus_tpu.parallel.shuffle import route_rows, transfer_counts
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.coordinator import split_plan
from ytsaurus_tpu.query.engine.lowering import prepare
from ytsaurus_tpu.query.parameterize import plan_fingerprint

# -- partition-rule registry ---------------------------------------------------

# Stage-name regex → PartitionSpec (the match_partition_rules idiom,
# SNIPPETS.md [2]).  Sharded stages run inside the shard_map body on the
# per-device slice; replicated stages run after the in-program
# all_gather (every device computes the same merge).  Rules are matched
# first-hit, so a custom registry can pin one stage or column family
# ("scan/l_.*") ahead of the defaults.
DEFAULT_PARTITION_RULES: "tuple[tuple[str, P], ...]" = (
    (r"^(scan|filter|bottom|shuffle|local)(/|$)", P(SHARD_AXIS)),
    (r"^(front|merge|order|topk|project|limit)(/|$)", P()),
)


def match_partition_rules(rules, name: str) -> P:
    """First rule whose regex matches `name` wins; no match is an error
    (an unplaceable stage must fail loudly, not silently replicate)."""
    for pattern, spec in rules:
        if re.search(pattern, name) is not None:
            return spec
    raise YtError(f"No partition rule matches stage {name!r}",
                  code=EErrorCode.QueryExecutionError)


def rules_fingerprint(rules) -> str:
    """Stable digest of a rule set — a cache-key axis, so editing the
    registry can never serve a program compiled under the old placement."""
    text = repr([(pattern, tuple(spec)) for pattern, spec in rules])
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def _validate_stages(rules, stages: "list[tuple[str, bool]]") -> None:
    """Check the registry places every stage where the fused program can
    execute it: (name, wants_sharded) pairs."""
    for name, want_sharded in stages:
        spec = match_partition_rules(rules, name)
        sharded = tuple(spec) == (SHARD_AXIS,)
        if sharded != want_sharded:
            where = "on the shard axis" if want_sharded else "replicated"
            raise YtError(
                f"partition rules place stage {name!r} as {tuple(spec)!r} "
                f"but the fused program runs it {where}",
                code=EErrorCode.QueryExecutionError)


# -- fusion gate ---------------------------------------------------------------


def can_fuse(plan: ir.Query) -> Optional[str]:
    """None when the whole plan lowers as one SPMD program; otherwise
    the reason it stays on the stitched ladder."""
    if plan.joins:
        return "join plans run the stitched broadcast/partitioned paths"
    if plan.group is not None and plan.group.totals:
        return "WITH TOTALS concatenates two materialized rowsets"
    return None


def _shape_of(plan: ir.Query) -> str:
    """Which fused shape serves this plan:

    exchange-states  GROUP BY without cardinality: partial aggregate
                     states per shard, then the states (not the rows)
                     ride the all_to_all — the in-program combiner.
    exchange-rows    cardinality GROUP BY / windowed plans: complete
                     groups (partitions) need the raw rows co-located.
    gather           everything else: bottom per shard, all_gather,
                     replicated front.
    """
    if plan.group is not None and not plan.group.totals:
        if any(a.function == "cardinality"
               for a in plan.group.aggregate_items):
            return "exchange-rows"
        return "exchange-states"
    if plan.window is not None and plan.window.partition_items:
        return "exchange-rows"
    return "gather"


# -- entry ---------------------------------------------------------------------


def run_whole_plan(evaluator, plan: ir.Query, table, stats=None,
                   rules=None):
    """Execute `plan` over a ShardedTable as ONE fused SPMD program.

    `evaluator` is the DistributedEvaluator owning the compile ladder
    (memory cache → AOT disk tier → fresh compile) and the quota memo.
    Raises YtError for unfusable plans or in-program faults — the
    caller's degradation ladder steps down to the stitched rungs.
    """
    reason = can_fuse(plan)
    if reason is not None:
        raise YtError(f"plan is not whole-plan fusable: {reason}",
                      code=EErrorCode.QueryUnsupported)
    rules = DEFAULT_PARTITION_RULES if rules is None else tuple(rules)
    shape = _shape_of(plan)
    if shape == "gather":
        chunk = _run_gather(evaluator, plan, table, rules)
    else:
        chunk = _run_exchange(evaluator, plan, table, rules, shape,
                              stats)
    if stats is not None:
        stats.whole_plan = 1
    return chunk


def _read_counts(final) -> "tuple[int, int, int]":
    """THE whole-plan host sync: ONE stacked device→host transfer
    carrying (result row count, overflow flag, max transfer cell).
    Gather-shape programs return a bare count (no exchange — overflow
    impossible)."""
    vals = np.asarray(final)
    if vals.ndim == 0:
        return int(vals), 0, 0
    return int(vals[0]), int(vals[1]), int(vals[2])


def _scan_shardings(rules, mesh, names: "list[str]"):
    """NamedShardings for the input planes per the registry ("scan/<col>"
    rules must keep scan columns on the shard axis — the planes ARE
    sharded)."""
    shardings = {}
    stages = []
    for name in names:
        stage = f"scan/{name}"
        stages.append((stage, True))
        shardings[name] = NamedSharding(mesh,
                                        match_partition_rules(rules, stage))
    _validate_stages(rules, stages)
    return shardings


def _constrain_inputs(mesh, shardings, columns: dict, row_valid):
    """`with_sharding_constraint` at the jit boundary: pins the scan
    planes to the registry's placement before the shard_map body (the
    GSPMD spelling of "this stage lives on the shard axis")."""
    out = {}
    for name, (data, valid) in columns.items():
        sh = shardings[name]
        out[name] = (jax.lax.with_sharding_constraint(data, sh),
                     jax.lax.with_sharding_constraint(valid, sh))
    rv = jax.lax.with_sharding_constraint(
        row_valid, NamedSharding(mesh, P(SHARD_AXIS)))
    return out, rv


def _gathered(planes_with_cols, shard_mask, out_cap: int):
    """In-program all_gather of a stage's output planes + mask."""
    gathered = {}
    for out_col, (d, v) in planes_with_cols:
        gathered[out_col.name] = (
            jax.lax.all_gather(d, SHARD_AXIS).reshape(-1),
            jax.lax.all_gather(v, SHARD_AXIS).reshape(-1))
    g_mask = jax.lax.all_gather(shard_mask, SHARD_AXIS).reshape(-1)
    return gathered, g_mask


# -- gather shape --------------------------------------------------------------


def _run_gather(evaluator, plan: ir.Query, table, rules):
    """bottom per shard → all_gather → replicated front, fused.  The
    same dataflow as the stitched gather rung, but compiled through the
    whole-plan ladder (AOT-serializable, registry-placed)."""
    from ytsaurus_tpu.parallel import distributed as dist
    dist._FP_GATHER.hit()
    mesh = table.mesh
    n = mesh.devices.size
    cap = table.capacity
    bottom, front = split_plan(plan)
    prepared_b = prepare(bottom, table.rep_chunk())
    inter_rep = dist._RepChunk(
        capacity=n * prepared_b.out_capacity,
        columns={c.name: dist._RepColumn(type=c.type, dictionary=c.vocab)
                 for c in prepared_b.output})
    prepared_f = prepare(front, inter_rep)
    names = [c.name for c in bottom.schema if c.name in table.columns]
    shardings = _scan_shardings(rules, mesh, names)
    stages = [("bottom", True), ("front", False)]
    if plan.order is not None:
        stages.append(("order", False))
    if plan.project is not None:
        stages.append(("project", False))
    _validate_stages(rules, stages)
    out_cap = prepared_b.out_capacity

    def build():
        def fused(columns, row_valid, b_bnd, f_bnd):
            planes, count = prepared_b.run(columns, row_valid, b_bnd)
            shard_mask = jnp.arange(out_cap) < count
            gathered, g_mask = _gathered(
                list(zip(prepared_b.output, planes)), shard_mask, out_cap)
            return prepared_f.run(gathered, g_mask, f_bnd)

        mapped = shard_map(
            fused, mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P()),
            out_specs=P(), check_vma=False)

        def program(columns, row_valid, b_bnd, f_bnd):
            columns, row_valid = _constrain_inputs(mesh, shardings,
                                                   columns, row_valid)
            return mapped(columns, row_valid, b_bnd, f_bnd)

        return program

    key = ("whole", "gather", plan_fingerprint(bottom),
           plan_fingerprint(front), n, cap,
           prepared_b.binding_shapes(), prepared_f.binding_shapes(),
           rules_fingerprint(rules))
    columns = {name: (table.columns[name].data, table.columns[name].valid)
               for name in names}
    out_planes, out_count = evaluator._dispatch_spmd(
        key, build, (columns, table.row_valid,
                     tuple(prepared_b.bindings),
                     tuple(prepared_f.bindings)))
    dist._note_host_sync()            # the final count read
    count, _over, _cell = _read_counts(out_count)
    return dist._assemble_chunk(prepared_f.output, out_planes, count)


# -- exchange shapes -----------------------------------------------------------


def _bind_route_keys(rep_columns, key_refs, where_expr):
    """Bind routing-key expressions (+ optional WHERE) against a
    namespace of _RepColumn-like carriers.  Returns (bind_ctx, where_b,
    key_b)."""
    from ytsaurus_tpu.query.engine.expr import BindContext, ColumnBinding, \
        ExprBinder
    bind_ctx = BindContext(columns={
        name: ColumnBinding(type=rc.type, vocab=rc.dictionary)
        for name, rc in rep_columns.items()})
    binder = ExprBinder(bind_ctx)
    where_b = binder.bind(where_expr) if where_expr is not None else None
    key_b = [binder.bind(expr) for expr in key_refs]
    return bind_ctx, where_b, key_b


def _dest_hash(key_b, ctx, mask, cap: int, n: int):
    """Destination device by canonical key hash (mirrors the stitched
    shuffle's routing so both paths co-locate identical key sets)."""
    from ytsaurus_tpu.query.engine.expr import _combine_u64, _mix_u64
    from ytsaurus_tpu.parallel.distributed import _canonical_hash_plane
    acc = jnp.full(cap, np.uint64(0x9E3779B97F4A7C15), dtype=jnp.uint64)
    for kb in key_b:
        data, valid = kb.emit(ctx)
        if data.dtype == jnp.bool_:
            data = data.astype(jnp.int8)
        h = _mix_u64(_canonical_hash_plane(data))
        h = jnp.where(valid, h, jnp.zeros_like(h))
        acc = _combine_u64(acc, h)
    pid = (acc % np.uint64(n)).astype(jnp.int32)
    return jnp.where(mask, pid, n)


def _initial_quota(memo: dict, memo_key, bound_cap: int, n: int,
                   headroom: float) -> "tuple[int, int]":
    """(starting quota, hard bound).  The bound is the per-source live
    capacity — a source cannot send more rows than it holds to one
    destination, so a program at the bound can never overflow."""
    bound = pad_capacity(bound_cap)
    start = memo.get(memo_key)
    if start is None:
        start = min(bound,
                    pad_capacity(max(64, int(bound_cap * headroom) // n)))
    return start, bound


def _settle_quota(memo: dict, memo_key, demand: int,
                  bound: int, headroom: float) -> None:
    """Memoize the demand-sized quota for the next query of this shape.
    Hysteresis: only shrink past a 4x gap (pow2 + headroom already give
    ~2x slack), so per-query demand jitter cannot thrash the compile
    cache with alternating quota rungs."""
    settled = min(bound, pad_capacity(max(int(demand * headroom), 64)))
    prev = memo.get(memo_key)
    if prev is None or settled > prev or settled * 4 <= prev:
        memo[memo_key] = settled


def _run_exchange(evaluator, plan: ir.Query, table, rules, shape: str,
                  stats):
    """The co-partitioned shapes, fused end to end:

    exchange-states  scan→filter→partial group (per shard) → all_to_all
                     of the GROUP STATES by key hash → merge group +
                     having (complete groups per device) → all_gather →
                     order/project/offset/limit.  The exchange moves
                     aggregate states, not rows — the in-program
                     combiner.
    exchange-rows    scan→filter → all_to_all of the surviving ROWS by
                     group/PARTITION BY hash → full local stage
                     (complete groups: cardinality; complete partitions:
                     window) → all_gather → front.

    One static pow2 quota sizes the exchange; the program returns the
    true transfer max + overflow flag WITH the count (one stacked final
    transfer).  Overflow re-runs at the demanded quota and memoizes it.
    """
    from ytsaurus_tpu.config import compile_config
    from ytsaurus_tpu.parallel import distributed as dist
    from ytsaurus_tpu.query.engine.expr import EmitContext

    dist._FP_ALL_TO_ALL.hit()
    mesh = table.mesh
    n = mesh.devices.size
    cap = table.capacity
    headroom = compile_config().whole_plan_headroom

    if shape == "exchange-states":
        bottom, front = split_plan(plan)
        prepared_s1 = prepare(bottom, table.rep_chunk())
        bound_cap = prepared_s1.out_capacity
        route_rep = {c.name: dist._RepColumn(type=c.type, dictionary=c.vocab)
                     for c in prepared_s1.output}
        route_names = [c.name for c in prepared_s1.output]
        # Routing keys: the group-key slots of the state rowset (bare
        # references — the bottom already evaluated the expressions).
        key_refs = [ir.TReference(type=item.expr.type, name=item.name)
                    for item in bottom.group.group_items]
        where_expr = None                 # consumed by the bottom
        local_plan = ir.FrontQuery(schema=front.schema, group=front.group,
                                   having=front.having)
        front_final = ir.FrontQuery(
            schema=local_plan.output_schema(), order=front.order,
            project=front.project, offset=front.offset, limit=front.limit)
        stage_names = [("bottom/group", True), ("shuffle/group", True),
                       ("local/group", True), ("front", False)]
    else:
        bottom = None
        prepared_s1 = None
        bound_cap = cap
        route_rep = {name: dist._RepColumn(type=col.type,
                                           dictionary=col.dictionary)
                     for name, col in table.columns.items()}
        route_names = [c.name for c in plan.schema
                       if c.name in table.columns]
        route_rep = {name: route_rep[name] for name in route_names}
        key_items = plan.window.partition_items \
            if plan.window is not None else plan.group.group_items
        key_refs = [item.expr for item in key_items]
        where_expr = plan.where
        local_plan = dc_replace(plan, order=None, project=None, offset=0,
                                limit=None)
        front_final = None                # built per quota below
        kind = "window" if plan.window is not None else "group"
        stage_names = [(f"shuffle/{kind}", True), (f"local/{kind}", True),
                       ("front", False)]
    if plan.order is not None:
        stage_names.append(("order", False))
    if plan.project is not None:
        stage_names.append(("project", False))
    _validate_stages(rules, stage_names)

    key_ctx, where_b, key_b = _bind_route_keys(route_rep, key_refs,
                                               where_expr)
    key_bindings = tuple(key_ctx.bindings)
    if shape == "exchange-states":
        columns = {name: (table.columns[name].data,
                          table.columns[name].valid)
                   for name in [c.name for c in bottom.schema
                                if c.name in table.columns]}
        scan_names = sorted(columns)
    else:
        columns = {name: (table.columns[name].data,
                          table.columns[name].valid)
                   for name in route_names}
        scan_names = route_names
    shardings = _scan_shardings(rules, mesh, scan_names)

    memo_key = (shape, plan_fingerprint(plan), n, bound_cap)
    quota, bound = _initial_quota(evaluator._quota_memo, memo_key,
                                  bound_cap, n, headroom)

    while True:
        recv_cap = n * quota
        local_rep = dist._RepChunk(
            capacity=recv_cap, columns=dict(route_rep))
        prepared_local = prepare(local_plan, local_rep)
        out_cap = prepared_local.out_capacity
        if shape == "exchange-states":
            final_plan = front_final
        else:
            final_plan = ir.FrontQuery(
                schema=local_plan.output_schema(), order=plan.order,
                project=plan.project, offset=plan.offset,
                limit=plan.limit)
        front_rep = dist._RepChunk(
            capacity=n * out_cap,
            columns={c.name: dist._RepColumn(type=c.type,
                                             dictionary=c.vocab)
                     for c in prepared_local.output})
        prepared_front = prepare(final_plan, front_rep)

        def build(quota=quota, prepared_local=prepared_local,
                  prepared_front=prepared_front, out_cap=out_cap):
            def fused(columns, row_valid, s1_bnd, key_bnd, l_bnd, f_bnd):
                if prepared_s1 is not None:
                    planes, cnt = prepared_s1.run(columns, row_valid,
                                                  s1_bnd)
                    routed = {c.name: plane for c, plane in
                              zip(prepared_s1.output, planes)}
                    mask = jnp.arange(bound_cap) < cnt
                else:
                    routed = {name: columns[name] for name in route_names}
                    mask = row_valid
                ctx = EmitContext(columns=routed, bindings=key_bnd,
                                  capacity=bound_cap)
                if where_b is not None:
                    d, v = where_b.emit(ctx)
                    mask = mask & v & d.astype(bool)
                pid = _dest_hash(key_b, ctx, mask, bound_cap, n)
                cell_counts = transfer_counts(pid, mask, n)
                recv, recv_mask = route_rows(routed, pid, n, quota,
                                             bound_cap)
                planes2, cnt2 = prepared_local.run(recv, recv_mask,
                                                   l_bnd)
                shard_mask = jnp.arange(out_cap) < cnt2
                gathered, g_mask = _gathered(
                    list(zip(prepared_local.output, planes2)),
                    shard_mask, out_cap)
                out_planes, out_count = prepared_front.run(gathered,
                                                           g_mask, f_bnd)
                # Replicated exchange telemetry riding the result: the
                # true transfer-matrix max (quota demand) + overflow.
                all_cells = jax.lax.all_gather(
                    cell_counts, SHARD_AXIS).reshape(-1)
                max_cell = all_cells.max().astype(jnp.int64)
                over = (max_cell > quota).astype(jnp.int64)
                final = jnp.stack(
                    [out_count.astype(jnp.int64), over, max_cell])
                return out_planes, final

            mapped = shard_map(
                fused, mesh=mesh,
                in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P(), P(),
                          P()),
                out_specs=P(), check_vma=False)

            def program(columns, row_valid, s1_bnd, key_bnd, l_bnd,
                        f_bnd):
                columns, row_valid = _constrain_inputs(
                    mesh, shardings, columns, row_valid)
                return mapped(columns, row_valid, s1_bnd, key_bnd,
                              l_bnd, f_bnd)

            return program

        key = ("whole", shape, plan_fingerprint(plan), n, cap, quota,
               bound_cap,
               prepared_s1.binding_shapes() if prepared_s1 is not None
               else None,
               tuple(key_ctx.structure),
               tuple((tuple(b.shape), str(b.dtype))
                     for b in key_bindings),
               prepared_local.binding_shapes(),
               prepared_front.binding_shapes(),
               rules_fingerprint(rules))
        args = (columns, table.row_valid,
                tuple(prepared_s1.bindings) if prepared_s1 is not None
                else (),
                key_bindings, tuple(prepared_local.bindings),
                tuple(prepared_front.bindings))
        out_planes, final = evaluator._dispatch_spmd(key, build, args)
        # Noted PER read: an overflow retry performs a real second
        # stacked transfer and the counter must say so (steady state
        # stays at exactly one).
        dist._note_host_sync()
        count, over, demand = _read_counts(final)
        if not over:
            break
        if quota >= bound:
            raise YtError(
                "whole-plan exchange overflowed at the maximal quota "
                f"(quota={quota}, demand={demand})",
                code=EErrorCode.QueryExecutionError)
        if stats is not None:
            stats.whole_plan_retries += 1
        quota = min(bound,
                    max(pad_capacity(max(int(demand * headroom), 1)),
                        quota * 2))
    _settle_quota(evaluator._quota_memo, memo_key, demand, bound,
                  headroom)
    return dist._assemble_chunk(prepared_front.output, out_planes, count)
