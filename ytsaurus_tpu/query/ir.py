"""Typed query plan IR.

Mirrors the reference plan IR (library/query/base/query.h: TExpression tree,
TGroupClause/TJoinClause/TOrderClause/TProjectClause, TQuery with the
bottom/front split) as immutable typed dataclasses.  CASE is desugared to
nested IF and LIKE to vocabulary-level predicates during building, so the IR
the lowering consumes stays small.

Every node is hashable; `fingerprint(query)` produces the stable key for the
compiled-executable cache — the analog of the reference's llvm::FoldingSet
fingerprint (library/query/engine/folding_profiler.cpp).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Optional

from ytsaurus_tpu.schema import EValueType, TableSchema


class TExpr:
    """Base of typed expressions; every node carries its result type."""
    type: EValueType


@dataclass(frozen=True)
class TLiteral(TExpr):
    type: EValueType
    value: object            # python scalar; bytes for strings; None for null


@dataclass(frozen=True)
class TReference(TExpr):
    type: EValueType
    name: str                # resolved name in the stage's row namespace


@dataclass(frozen=True)
class TFunction(TExpr):
    type: EValueType
    name: str
    args: tuple[TExpr, ...]


@dataclass(frozen=True)
class TUnary(TExpr):
    type: EValueType
    op: str
    operand: TExpr


@dataclass(frozen=True)
class TBinary(TExpr):
    type: EValueType
    op: str
    lhs: TExpr
    rhs: TExpr


@dataclass(frozen=True)
class TIn(TExpr):
    type: EValueType         # boolean
    operands: tuple[TExpr, ...]
    values: tuple[tuple, ...]


@dataclass(frozen=True)
class TBetween(TExpr):
    type: EValueType         # boolean
    operands: tuple[TExpr, ...]
    ranges: tuple[tuple, ...]
    negated: bool


@dataclass(frozen=True)
class TTransform(TExpr):
    type: EValueType
    operands: tuple[TExpr, ...]
    from_values: tuple[tuple, ...]
    to_values: tuple[object, ...]
    default: Optional[TExpr]


@dataclass(frozen=True)
class TStringPredicate(TExpr):
    """Vocabulary-level string predicate (LIKE / prefix / substring / regex).

    Evaluated host-side against the chunk dictionary, then gathered on device.
    `kind` in {like, prefix, substr, regex}; pattern is a bytes literal.
    """
    type: EValueType         # boolean
    operand: TExpr           # string-typed expr
    kind: str
    pattern: bytes
    case_insensitive: bool = False
    negated: bool = False


def expr_references(expr):
    """Yield every TReference name inside an expression tree."""
    import dataclasses as _dc
    if isinstance(expr, TReference):
        yield expr.name
        return
    if not isinstance(expr, TExpr):
        return
    for field in _dc.fields(expr):
        value = getattr(expr, field.name)
        if isinstance(value, TExpr):
            yield from expr_references(value)
        elif isinstance(value, (tuple, list)):
            for item in value:
                if isinstance(item, TExpr):
                    yield from expr_references(item)


def referenced_columns(query: "Query") -> "Optional[set[str]]":
    """Input-namespace columns the plan actually reads, or None when
    every schema column flows to the output (bare select: no projection
    and no grouping).  Used to prune planes before expensive data
    movement (e.g. the partitioned-join exchange)."""
    if query.project is None and query.group is None:
        return None
    refs: set[str] = set()

    def add(expr) -> None:
        if expr is not None:
            refs.update(expr_references(expr))

    add(query.where)
    if query.group is not None:
        for item in query.group.group_items:
            add(item.expr)
        for agg in query.group.aggregate_items:
            add(agg.argument)
            add(agg.by_argument)
    add(query.having)
    if query.window is not None:
        for item in query.window.partition_items:
            add(item.expr)
        for oi in query.window.order_items:
            add(oi.expr)
        for w in query.window.items:
            add(w.argument)
            add(w.default)
    if query.order is not None:
        for item in query.order.items:
            add(item.expr)
    if query.project is not None:
        for item in query.project.items:
            add(item.expr)
    for join in query.joins:
        for eq in join.self_equations:
            add(eq)
    return refs


@dataclass(frozen=True)
class NamedExpr:
    name: str
    expr: TExpr


@dataclass(frozen=True)
class AggregateItem:
    """One aggregate: `name` is its slot in the post-group namespace."""
    name: str
    function: str            # sum | min | max | avg | count | first | argmin...
    argument: Optional[TExpr]
    type: EValueType         # result type
    state_type: EValueType   # partial-state type (avg keeps (sum,count))
    by_argument: Optional[TExpr] = None   # argmin/argmax comparison key


@dataclass(frozen=True)
class GroupClause:
    group_items: tuple[NamedExpr, ...]
    aggregate_items: tuple[AggregateItem, ...]
    totals: bool = False


# Normalized frame: (start_kind, start_offset, end_kind, end_offset) with
# kind in {unbounded, offset, peer}; offsets are SIGNED row deltas relative
# to the current row (k PRECEDING → -k, k FOLLOWING → +k).  "peer" (end
# only) extends to the last row of the current ORDER-BY peer group — the
# SQL-standard default frame (RANGE UNBOUNDED PRECEDING .. CURRENT ROW):
# tied order keys share one value.  Explicit ROWS frames stay row-exact.
Frame = tuple[str, int, str, int]

WHOLE_PARTITION_FRAME: Frame = ("unbounded", 0, "unbounded", 0)
PEERS_FRAME: Frame = ("unbounded", 0, "peer", 0)


@dataclass(frozen=True)
class WindowItem:
    """One window function: `name` is its slot in the output namespace."""
    name: str
    function: str            # row_number | rank | dense_rank | lag | lead |
                             # first_value | last_value | sum | min | max |
                             # avg | count
    argument: Optional[TExpr]
    type: EValueType         # result type
    frame: Frame = WHOLE_PARTITION_FRAME
    offset: int = 1          # lag/lead row distance (>= 0)
    default: Optional[TExpr] = None   # lag/lead out-of-partition fill


@dataclass(frozen=True)
class WindowClause:
    """Window stage: ONE shared (partition, order) spec for every item
    (per-item frames vary).  Computed over the post-WHERE rowset in the
    input namespace; each item adds a column, no rows move."""
    partition_items: tuple[NamedExpr, ...]
    order_items: tuple["OrderItem", ...]
    items: tuple[WindowItem, ...]


@dataclass(frozen=True)
class OrderItem:
    expr: TExpr
    descending: bool


@dataclass(frozen=True)
class OrderClause:
    items: tuple[OrderItem, ...]


@dataclass(frozen=True)
class ProjectClause:
    items: tuple[NamedExpr, ...]


@dataclass(frozen=True)
class JoinClause:
    foreign_table: str
    foreign_schema: TableSchema
    alias: Optional[str]
    self_equations: tuple[TExpr, ...]      # evaluated in self namespace
    foreign_equations: tuple[TExpr, ...]   # evaluated in foreign namespace
    foreign_columns: tuple[str, ...]       # columns pulled from foreign table
    is_left: bool


@dataclass(frozen=True)
class Query:
    """A single-stage query plan (ref TQuery, base/query.h:532).

    Namespaces: `schema` names the input row namespace.  If `group` is set,
    having/order/project run in the post-group namespace (group item names +
    aggregate names); otherwise they run in the input namespace.
    """
    schema: TableSchema                    # input namespace (incl. join columns)
    source: Optional[str] = None           # table path (None = provided rowset)
    joins: tuple[JoinClause, ...] = ()
    where: Optional[TExpr] = None
    group: Optional[GroupClause] = None
    window: Optional[WindowClause] = None
    having: Optional[TExpr] = None
    order: Optional[OrderClause] = None
    project: Optional[ProjectClause] = None
    offset: int = 0
    limit: Optional[int] = None

    @property
    def is_ordered_scan(self) -> bool:
        return self.order is None and self.limit is not None

    def post_group_schema(self) -> TableSchema:
        assert self.group is not None
        cols = [(item.name, item.expr.type.value) for item in self.group.group_items]
        cols += [(agg.name, agg.type.value) for agg in self.group.aggregate_items]
        return TableSchema.make(cols)

    def output_schema(self) -> TableSchema:
        if self.project is not None:
            return TableSchema.make(
                [(item.name, item.expr.type.value) for item in self.project.items])
        if self.group is not None:
            return self.post_group_schema()
        cols = [(c.name, c.type.value) for c in self.schema.to_unsorted()]
        if self.window is not None:
            # Identity projection carries the window slots along so a
            # front stage can still reference them.
            cols += [(w.name, w.type.value) for w in self.window.items]
        return TableSchema.make(cols)


@dataclass(frozen=True)
class FrontQuery:
    """Coordinator-side merge query (ref TFrontQuery, base/query.h:559).

    Runs over the concatenation of bottom-query outputs: re-groups partial
    aggregate states, re-applies window/having/order/project/offset/limit.
    """
    schema: TableSchema                    # = bottom intermediate schema
    group: Optional[GroupClause] = None    # merge-combine aggregates
    window: Optional[WindowClause] = None  # recompute over the merged rowset
    having: Optional[TExpr] = None
    order: Optional[OrderClause] = None
    project: Optional[ProjectClause] = None
    offset: int = 0
    limit: Optional[int] = None

    def output_schema(self) -> TableSchema:
        if self.project is not None:
            return TableSchema.make(
                [(item.name, item.expr.type.value) for item in self.project.items])
        if self.group is not None:
            cols = [(i.name, i.expr.type.value) for i in self.group.group_items]
            cols += [(a.name, a.type.value) for a in self.group.aggregate_items]
            return TableSchema.make(cols)
        if self.window is not None:
            cols = [(c.name, c.type.value) for c in self.schema]
            cols += [(w.name, w.type.value) for w in self.window.items]
            return TableSchema.make(cols)
        return self.schema


def map_expr(expr, fn):
    """Bottom-up rewrite: apply `fn` to every node, recursing first.

    `fn(node)` returns a replacement node or the node itself.  Shared by the
    coordinator's avg-state substitution and the totals-plan key nulling —
    extend HERE when a new expression node type is added.
    """
    from dataclasses import replace as dc_replace

    if expr is None:
        return None
    e = expr
    if isinstance(e, TFunction):
        e = dc_replace(e, args=tuple(map_expr(a, fn) for a in e.args))
    elif isinstance(e, TUnary):
        e = dc_replace(e, operand=map_expr(e.operand, fn))
    elif isinstance(e, TBinary):
        e = dc_replace(e, lhs=map_expr(e.lhs, fn), rhs=map_expr(e.rhs, fn))
    elif isinstance(e, TIn):
        e = dc_replace(e, operands=tuple(map_expr(o, fn) for o in e.operands))
    elif isinstance(e, TBetween):
        e = dc_replace(e, operands=tuple(map_expr(o, fn) for o in e.operands))
    elif isinstance(e, TTransform):
        e = dc_replace(e, operands=tuple(map_expr(o, fn) for o in e.operands),
                       default=map_expr(e.default, fn))
    elif isinstance(e, TStringPredicate):
        e = dc_replace(e, operand=map_expr(e.operand, fn))
    return fn(e)


# --- fingerprinting -----------------------------------------------------------


# Literal types whose VALUES may be hoisted out of a parameterized
# fingerprint (query/parameterize.py): the lowering binds these values
# as runtime binding slots, so the traced program is value-independent.
# booleans and nulls are STATIC RESIDUE — the lexer keeps true/false/
# null as keywords (workload.normalize_query never hoists them), and
# their two-or-one-value domains cannot grow a shape spectrum anyway.
HOISTABLE_LITERAL_TYPES = frozenset(
    (EValueType.int64, EValueType.uint64, EValueType.double,
     EValueType.string))


def _repr_expr(e, omit_values: bool = False) -> str:
    # Deterministic structural serialization.  With omit_values=False
    # literal VALUES are included (the historical per-constant
    # fingerprint).  With omit_values=True (the parameterized shape
    # fingerprint — the analog of InferName(omitValues) feeding the
    # reference's llvm::FoldingSet profiler) hoistable literal values
    # collapse to `?`: the lowering passes them as runtime bindings, so
    # one compiled program serves every constant of the shape.  Counts
    # stay structural — IN-list membership loops, BETWEEN range lists
    # and TRANSFORM tables trace a fixed iteration count (IN bucketed
    # pow2 by the binder; the others exact).
    def rec(x):
        return _repr_expr(x, omit_values)

    if isinstance(e, TLiteral):
        if omit_values and not isinstance(e.type, EValueType):
            # Vector (parametric-type) literal: the query vector is a
            # runtime binding; the dim stays in the type spelling so one
            # program serves every query vector of that dim.
            return f"L({e.type.value},?)"
        if omit_values and e.type in HOISTABLE_LITERAL_TYPES:
            return f"L({e.type.value},?)"
        return f"L({e.type.value},{e.value!r})"
    if isinstance(e, TReference):
        return f"R({e.name})"
    if isinstance(e, TFunction):
        return f"F({e.name};{','.join(map(rec, e.args))})"
    if isinstance(e, TUnary):
        return f"U({e.op};{rec(e.operand)})"
    if isinstance(e, TBinary):
        return f"B({e.op};{rec(e.lhs)};{rec(e.rhs)})"
    if isinstance(e, TIn):
        if omit_values:
            from ytsaurus_tpu.chunks.columnar import next_pow2
            return (f"I({','.join(map(rec, e.operands))};"
                    f"#{next_pow2(len(e.values))})")
        return f"I({','.join(map(rec, e.operands))};{e.values!r})"
    if isinstance(e, TBetween):
        if omit_values:
            lens = tuple((len(lo), len(hi)) for lo, hi in e.ranges)
            return (f"W({','.join(map(rec, e.operands))};#{lens!r};"
                    f"{e.negated})")
        return f"W({','.join(map(rec, e.operands))};{e.ranges!r};{e.negated})"
    if isinstance(e, TTransform):
        if omit_values:
            widths = tuple(len(t) for t in e.from_values)
            return (f"T({','.join(map(rec, e.operands))};#{widths!r};"
                    f"{rec(e.default) if e.default else ''})")
        return (f"T({','.join(map(rec, e.operands))};{e.from_values!r};"
                f"{e.to_values!r};{rec(e.default) if e.default else ''})")
    if isinstance(e, TStringPredicate):
        pattern = "?" if omit_values else repr(e.pattern)
        return (f"S({e.kind};{rec(e.operand)};{pattern};"
                f"{e.case_insensitive};{e.negated})")
    if e is None:
        return "-"
    raise TypeError(f"Unknown expr node {type(e).__name__}")


def fingerprint(query: "Query | FrontQuery",
                omit_values: bool = False) -> str:
    """Stable plan fingerprint.  omit_values=True produces the
    PARAMETERIZED shape fingerprint: hoistable literal values and the
    exact OFFSET/LIMIT collapse (limits to their pow2 bucket — they
    shape the compiled program's top-k candidate count, so they are
    static residue that buckets instead of hoisting).  Callers should
    normally go through query/parameterize.plan_fingerprint, which
    consults CompileConfig."""
    def rec(e):
        return _repr_expr(e, omit_values)

    parts: list[str] = [type(query).__name__]
    parts.append(",".join(f"{c.name}:{c.type.value}" for c in query.schema))
    if isinstance(query, Query):
        parts.append(str(query.source))
        for j in query.joins:
            parts.append(
                f"J({j.foreign_table};{j.alias};{j.is_left};"
                f"{','.join(map(rec, j.self_equations))};"
                f"{','.join(map(rec, j.foreign_equations))};"
                f"{','.join(j.foreign_columns)})")
        parts.append(rec(query.where))
    if query.group:
        parts.append("G(" + ";".join(
            f"{i.name}={rec(i.expr)}" for i in query.group.group_items) + ")")
        parts.append("A(" + ";".join(
            f"{a.name}={a.function}({rec(a.argument) if a.argument else ''}"
            f";{rec(a.by_argument) if a.by_argument else ''})"
            for a in query.group.aggregate_items) + f";{query.group.totals})")
    if query.window:
        parts.append("WIN(" + ";".join(
            f"{i.name}={rec(i.expr)}"
            for i in query.window.partition_items) + "|" + ";".join(
            f"{rec(i.expr)}:{i.descending}"
            for i in query.window.order_items) + "|" + ";".join(
            f"{w.name}={w.function}({rec(w.argument) if w.argument else ''}"
            f";{w.frame};{w.offset};"
            f"{rec(w.default) if w.default else ''})"
            for w in query.window.items) + ")")
    parts.append(rec(query.having))
    if query.order:
        parts.append("O(" + ";".join(
            f"{rec(i.expr)}:{i.descending}" for i in query.order.items) + ")")
    if query.project:
        parts.append("P(" + ";".join(
            f"{i.name}={rec(i.expr)}" for i in query.project.items) + ")")
    if omit_values:
        from ytsaurus_tpu.chunks.columnar import next_pow2
        off_b = next_pow2(query.offset) if query.offset > 0 else 0
        lim_b = next_pow2(max(query.limit, 1)) \
            if query.limit is not None else None
        parts.append(f"{off_b}/{lim_b}")
    else:
        parts.append(f"{query.offset}/{query.limit}")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:24]
