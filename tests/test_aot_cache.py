"""Persistent AOT compile-artifact cache (ISSUE 10 tentpole, piece c):
cross-process warm start (compile in one process, disk-hit in a fresh
one), loud-but-safe fallback on corrupted artifacts, versioned-header
refusal on jax/schema mismatch, the size-capped mtime-LRU disk tier,
and the observability surfaces (sensors, /compile snapshot, EXPLAIN
ANALYZE's cause=disk_hit arm).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ytsaurus_tpu import config as yt_config
from ytsaurus_tpu.schema import TableSchema


@pytest.fixture(autouse=True)
def _fresh_configs():
    yield
    yt_config.set_compile_config(None)
    yt_config.set_workload_config(None)
    from ytsaurus_tpu.query.engine.evaluator import (
        get_compile_observatory,
    )
    get_compile_observatory().reset()


def _inputs(n=64):
    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    schema = TableSchema.make([("k", "int64"), ("v", "int64")])
    chunk = ColumnarChunk.from_arrays(schema, {
        "k": np.arange(n, dtype=np.int64),
        "v": np.arange(n, dtype=np.int64) * 2})
    return schema, chunk


def _plan(q, schema):
    from ytsaurus_tpu.query.builder import build_query
    return build_query(q, {"//t": schema})


def _use_disk(tmp_path, **kwargs):
    cfg = yt_config.CompileConfig(disk_cache_dir=str(tmp_path),
                                  **kwargs)
    yt_config.set_compile_config(cfg)
    return cfg


def test_warm_start_across_evaluators(tmp_path):
    """In-process restart analog: a FRESH evaluator over the same cache
    dir serves the shape from disk — zero fresh compiles."""
    from ytsaurus_tpu.query.engine.aot_cache import get_disk_cache
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    from ytsaurus_tpu.query.statistics import QueryStatistics
    _use_disk(tmp_path)
    schema, chunk = _inputs()
    s1 = QueryStatistics()
    Evaluator().run_plan(_plan("k FROM [//t] WHERE v < 10", schema),
                         chunk, stats=s1)
    assert s1.compile_count == 1 and s1.compile_disk_hit == 0
    assert get_disk_cache().snapshot()["files"] == 1
    # "Restart": fresh evaluator, fresh memory cache, same disk dir —
    # and a DIFFERENT constant of the same shape still disk-hits.
    s2 = QueryStatistics()
    out = Evaluator().run_plan(
        _plan("k FROM [//t] WHERE v < 6", schema), chunk, stats=s2)
    assert [r["k"] for r in out.to_rows()] == [0, 1, 2]
    assert s2.compile_disk_hit == 1
    assert s2.compile_count - s2.compile_disk_hit == 0, \
        "warm start must not fresh-compile"
    snap = get_disk_cache().snapshot()
    assert snap["hits"] == 1 and snap["errors"] == 0


def test_cross_process_persistence(tmp_path):
    """ISSUE 10 acceptance: compile in ONE process, start a fresh
    evaluator in ANOTHER on the same cache dir, assert disk hits and
    zero fresh compiles."""
    script = f"""
import numpy as np
from ytsaurus_tpu import config as yt_config
yt_config.set_compile_config(yt_config.CompileConfig(
    disk_cache_dir={str(tmp_path)!r}))
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.query.engine.evaluator import Evaluator
from ytsaurus_tpu.query.statistics import QueryStatistics
from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.schema import TableSchema
schema = TableSchema.make([("k", "int64"), ("v", "int64")])
chunk = ColumnarChunk.from_arrays(schema, {{
    "k": np.arange(64, dtype=np.int64),
    "v": np.arange(64, dtype=np.int64) * 2}})
stats = QueryStatistics()
rows = Evaluator().run_plan(
    build_query("k FROM [//t] WHERE v < 8", {{"//t": schema}}),
    chunk, stats=stats).to_rows()
print("CHILD", len(rows), stats.compile_count, stats.compile_disk_hit)
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    child = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("CHILD")][0].split()
    assert child[1:] == ["4", "1", "0"], child    # compiled fresh there
    # THIS process: fresh evaluator on the artifact the child wrote.
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    from ytsaurus_tpu.query.statistics import QueryStatistics
    _use_disk(tmp_path)
    schema, chunk = _inputs()
    stats = QueryStatistics()
    out = Evaluator().run_plan(
        _plan("k FROM [//t] WHERE v < 12", schema), chunk, stats=stats)
    assert [r["k"] for r in out.to_rows()] == [0, 1, 2, 3, 4, 5]
    assert stats.compile_disk_hit == 1
    assert stats.compile_count - stats.compile_disk_hit == 0


def test_corrupted_artifact_falls_back_and_counts_error(tmp_path):
    """Truncated artifact → fresh compile + disk_errors, never a query
    failure."""
    from ytsaurus_tpu.query.engine.aot_cache import get_disk_cache
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    from ytsaurus_tpu.query.statistics import QueryStatistics
    _use_disk(tmp_path)
    schema, chunk = _inputs()
    Evaluator().run_plan(_plan("k FROM [//t] WHERE v < 10", schema),
                         chunk)
    [artifact] = [p for p in os.listdir(tmp_path)
                  if p.endswith(".aot")]
    path = os.path.join(tmp_path, artifact)
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[: len(blob) // 3])      # truncate mid-pickle
    stats = QueryStatistics()
    out = Evaluator().run_plan(
        _plan("k FROM [//t] WHERE v < 10", schema), chunk, stats=stats)
    assert [r["k"] for r in out.to_rows()] == [0, 1, 2, 3, 4]
    assert stats.compile_disk_hit == 0
    assert stats.compile_count == 1          # fresh compile
    assert get_disk_cache().snapshot()["errors"] == 1


def test_version_mismatch_refused_loudly(tmp_path):
    """The versioned-header discipline: a jax-version (or schema)
    mismatch is REFUSED — counted as an error, fallback compiles."""
    from ytsaurus_tpu.query.engine.aot_cache import get_disk_cache
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    from ytsaurus_tpu.query.statistics import QueryStatistics
    _use_disk(tmp_path)
    schema, chunk = _inputs()
    Evaluator().run_plan(_plan("k FROM [//t] WHERE v < 10", schema),
                         chunk)
    [artifact] = [p for p in os.listdir(tmp_path)
                  if p.endswith(".aot")]
    path = os.path.join(tmp_path, artifact)
    with open(path, "rb") as f:
        header = json.loads(f.readline())
        rest = f.read()
    header["jax"] = "0.0.1-other"
    with open(path, "wb") as f:
        f.write(json.dumps(header).encode() + b"\n")
        f.write(rest)
    stats = QueryStatistics()
    out = Evaluator().run_plan(
        _plan("k FROM [//t] WHERE v < 10", schema), chunk, stats=stats)
    assert [r["k"] for r in out.to_rows()] == [0, 1, 2, 3, 4]
    assert stats.compile_count == 1 and stats.compile_disk_hit == 0
    assert get_disk_cache().snapshot()["errors"] == 1


def test_disk_tier_is_size_capped_with_mtime_lru(tmp_path):
    """Bounded disk tier: a byte cap evicts oldest-mtime artifacts."""
    from ytsaurus_tpu.query.engine.aot_cache import get_disk_cache
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    _use_disk(tmp_path)
    schema, chunk = _inputs()
    ev = Evaluator()
    ev.run_plan(_plan("k FROM [//t] WHERE v < 10", schema), chunk)
    one_size = get_disk_cache().snapshot()["bytes"]
    assert one_size > 0
    # Re-point at the same dir with a cap that holds ~1.5 artifacts.
    _use_disk(tmp_path, disk_cache_capacity_bytes=int(one_size * 1.5))
    for i, shape in enumerate(("v > %d", "v = %d", "v != %d")):
        ev.run_plan(_plan("k FROM [//t] WHERE " + shape % i, schema),
                    chunk)
    snap = get_disk_cache().snapshot()
    assert snap["evictions"] >= 2
    assert snap["bytes"] <= int(one_size * 1.5)
    assert snap["files"] >= 1


def test_min_compile_seconds_gates_persistence(tmp_path):
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    _use_disk(tmp_path, disk_cache_min_compile_seconds=3600.0)
    schema, chunk = _inputs()
    Evaluator().run_plan(_plan("k FROM [//t] WHERE v < 10", schema),
                         chunk)
    assert not [p for p in os.listdir(tmp_path) if p.endswith(".aot")]


def test_disk_sensors_and_compile_snapshot(tmp_path):
    """/compile carries the disk tier; the catalog sensors move."""
    from ytsaurus_tpu.query.engine.evaluator import (
        Evaluator,
        get_compile_observatory,
    )
    from ytsaurus_tpu.utils.profiling import get_registry
    obs = get_compile_observatory()
    obs.reset()
    _use_disk(tmp_path)
    schema, chunk = _inputs()
    Evaluator().run_plan(_plan("k FROM [//t] WHERE v < 10", schema),
                         chunk)
    Evaluator().run_plan(_plan("k FROM [//t] WHERE v < 4", schema),
                         chunk)
    snap = obs.snapshot()
    assert snap["disk"]["hits"] == 1
    assert snap["disk"]["files"] == 1
    assert snap["totals"]["disk_hits"] == 1
    [row] = snap["fingerprints"]
    assert row["disk_hits"] == 1 and row["compiles"] == 1
    registry = get_registry()
    with registry._lock:
        sensors = {name: s.get() for (name, _tags), s
                   in registry._sensors.items()
                   if name.startswith("/query/compile_cache/disk_")}
    assert sensors["/query/compile_cache/disk_hits"] >= 1
    assert sensors["/query/compile_cache/disk_bytes"] > 0
    assert sensors["/query/compile_cache/disk_files"] >= 1
    # EXPLAIN ANALYZE's cause arm (profile renderer).
    from ytsaurus_tpu.query.profile import format_profile_dict
    from ytsaurus_tpu.query.statistics import QueryStatistics
    stats = QueryStatistics()
    Evaluator().run_plan(_plan("k FROM [//t] WHERE v < 2", schema),
                         chunk, stats=stats)
    text = format_profile_dict({"statistics": stats.to_dict()})
    assert "disk_hit 1" in text


def test_compile_cache_top_renders_disk_tier(tmp_path, capsys):
    from ytsaurus_tpu.cli import _format_compile_top
    from ytsaurus_tpu.query.engine.evaluator import (
        Evaluator,
        get_compile_observatory,
    )
    obs = get_compile_observatory()
    obs.reset()
    _use_disk(tmp_path)
    schema, chunk = _inputs()
    Evaluator().run_plan(_plan("k FROM [//t] WHERE v < 10", schema),
                         chunk)
    Evaluator().run_plan(_plan("k FROM [//t] WHERE v < 4", schema),
                         chunk)
    out = _format_compile_top(obs.snapshot(), "compile_seconds", 10)
    assert "disk tier: 1 hits" in out
    assert "disk_hits" in out
