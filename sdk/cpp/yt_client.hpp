// C++ SDK: a native client for the ytsaurus_tpu HTTP proxy (/api/v4).
//
// Ref mapping: yt/cpp/mapreduce — the reference's high-level C++ client
// talks to clusters through the HTTP/RPC proxies; this SDK speaks the
// same driver-command surface over the HTTP proxy (every command in the
// driver registry is callable via Execute).  Parameters and results are
// JSON text: the SDK stays dependency-free (POSIX sockets only), and
// callers bring whatever JSON library they prefer.
#pragma once

#include <stdexcept>
#include <string>

namespace yt_tpu {

struct YtError : std::runtime_error {
    int http_status;
    YtError(int status, const std::string& body)
        : std::runtime_error("YT proxy error (HTTP " +
                             std::to_string(status) + "): " + body),
          http_status(status) {}
};

class Client {
public:
    Client(std::string host, int port, std::string user = "root");

    // POST /api/v4/<command> with a JSON parameter object; returns the
    // raw JSON response body.  Throws YtError on non-2xx.
    std::string Execute(const std::string& command,
                        const std::string& json_params) const;

    // Convenience verbs (thin wrappers over Execute).
    void Create(const std::string& type, const std::string& path,
                const std::string& attributes_json = "{}") const;
    bool Exists(const std::string& path) const;
    std::string Get(const std::string& path) const;
    void Set(const std::string& path, const std::string& value_json) const;
    void WriteTable(const std::string& path,
                    const std::string& rows_json) const;
    std::string ReadTable(const std::string& path) const;
    std::string SelectRows(const std::string& query) const;
    std::string ListCommands() const;   // GET /api/v4

private:
    std::string host_;
    int port_;
    std::string user_;

    std::string Request(const std::string& method, const std::string& path,
                        const std::string& body) const;
};

// Minimal JSON string escaping for building parameter objects.
std::string JsonQuote(const std::string& raw);

}  // namespace yt_tpu
