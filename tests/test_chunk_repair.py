"""Background chunk re-replication (VERDICT r2 #6).

Kill a node holding one of a chunk's two replicas; the master's chunk
replicator restores the replication factor within its scan interval with
NO read on the chunk's path (ref chunk_replicator.h Replicate jobs).
"""

import time

import pytest

from ytsaurus_tpu.remote_client import connect_remote
from ytsaurus_tpu.rpc import Channel


def _node_chunks(address: str) -> set[str]:
    ch = Channel(address, timeout=15)
    try:
        body, _ = ch.call("data_node", "list_chunks", {})
        return {c.decode() if isinstance(c, bytes) else c
                for c in body.get("chunk_ids", [])}
    finally:
        ch.close()


@pytest.mark.slow   # ~17s; tier-1 keeps replicator-healing coverage via
# test_scrub_quarantines_and_replicator_heals + test_replicator_scan_unit
def test_dead_node_chunks_re_replicate_without_reads(tmp_path):
    from ytsaurus_tpu.environment import LocalCluster

    with LocalCluster(str(tmp_path / "repair"), n_nodes=3) as cluster:
        client = connect_remote(cluster.primary_address)
        rows = [{"k": i, "v": float(i)} for i in range(500)]
        client.write_table("//repair/t", rows)

        # Locate every chunk's holders straight from the nodes.
        per_node = {a: _node_chunks(a) for a in cluster.node_addresses}
        all_chunks = set().union(*per_node.values())
        assert all_chunks, "no chunks written"
        # RF=2: every chunk is on exactly 2 of the 3 nodes.
        for cid in all_chunks:
            assert sum(cid in s for s in per_node.values()) == 2

        # Kill a node that holds at least one chunk.
        victim = next(i for i, a in enumerate(cluster.node_addresses)
                      if per_node[a])
        victim_addr = cluster.node_addresses[victim]
        lost = per_node[victim_addr]
        cluster.kill_node(victim)

        # No reads anywhere.  Within a few scan intervals every lost
        # chunk must be back at RF=2 across the surviving nodes.
        survivors = [a for a in cluster.node_addresses
                     if a != victim_addr]
        deadline = time.monotonic() + 60
        missing = set(lost)
        while time.monotonic() < deadline:
            counts = {cid: 0 for cid in lost}
            for addr in survivors:
                held = _node_chunks(addr)
                for cid in lost:
                    if cid in held:
                        counts[cid] += 1
            missing = {cid for cid, c in counts.items() if c < 2}
            if not missing:
                break
            time.sleep(0.25)
        assert not missing, \
            f"chunks still under-replicated after repair window: {missing}"

        # The data stayed readable afterwards (sanity, not the repair
        # mechanism).
        got = client.read_table("//repair/t")
        assert len(got) == 500
        client.close()


def test_scrub_quarantines_and_replicator_heals(tmp_path):
    """Durability loop end to end: flip bits in one replica's blob; the
    node's scrub detects the CRC break and quarantines the copy; the
    master's replicator restores RF=2 from the healthy holder; reads
    never see the corruption."""
    import glob
    import os

    from ytsaurus_tpu.environment import LocalCluster

    with LocalCluster(str(tmp_path / "scrub"), n_nodes=3) as cluster:
        client = connect_remote(cluster.primary_address)
        client.write_table("//s/t", [{"k": i} for i in range(300)])
        per_node = {a: _node_chunks(a) for a in cluster.node_addresses}
        cid = next(iter(set().union(*per_node.values())))
        holders = [a for a, s in per_node.items() if cid in s]
        assert len(holders) == 2
        victim = holders[0]
        node_index = cluster.node_addresses.index(victim)
        blob_paths = glob.glob(os.path.join(
            str(tmp_path / "scrub"), f"node{node_index}", "chunks",
            cid[:2], f"{cid}.chunk"))
        assert blob_paths, "chunk file not found on victim"
        with open(blob_paths[0], "r+b") as f:
            f.seek(max(os.path.getsize(blob_paths[0]) // 2, 16))
            f.write(b"\xde\xad\xbe\xef")
        ch = Channel(victim, timeout=60)
        try:
            body, _ = ch.call("data_node", "scrub_chunks", {})
            corrupt = [c.decode() if isinstance(c, bytes) else c
                       for c in body["corrupt"]]
            assert cid in corrupt
        finally:
            ch.close()
        # Quarantined: the victim stops advertising the chunk.
        assert cid not in _node_chunks(victim)
        # The replicator heals RF=2 with no read involved — possibly by
        # pushing a healthy copy BACK to the (still-alive) victim, whose
        # quarantined bytes stay aside for post-mortem.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sum(cid in _node_chunks(a)
                   for a in cluster.node_addresses) >= 2:
                break
            time.sleep(0.25)
        assert sum(cid in _node_chunks(a)
                   for a in cluster.node_addresses) >= 2
        # And the data stayed intact.
        assert len(client.read_table("//s/t")) == 300


def test_replicator_scan_unit(tmp_path):
    """Unit-level: scan_once computes targets from rendezvous placement
    and issues replicate_chunk only for missing target replicas."""
    from ytsaurus_tpu.server.chunk_replicator import ChunkReplicator
    from ytsaurus_tpu.server.remote_store import placement_rank

    calls = []

    class FakeNode:
        def __init__(self, address, chunks):
            self.address = address
            self.chunks = set(chunks)

        def call(self, service, method, body=None, attachments=(), **kw):
            if method == "list_chunks":
                return {"chunk_ids": sorted(self.chunks)}, []
            if method == "replicate_chunk":
                calls.append((self.address, body["chunk_id"],
                              body["target"]))
                return {}, []
            raise AssertionError(method)

    nodes = {f"n{i}": FakeNode(f"n{i}", []) for i in range(3)}
    targets = placement_rank("c1", sorted(nodes))[:2]
    # c1 present only on its first target → one replication to the other.
    nodes[targets[0]].chunks.add("c1")
    rep = ChunkReplicator(lambda: sorted(nodes), replication_factor=2)
    rep._channels = dict(nodes)
    issued = rep.scan_once()
    assert issued == 1
    assert calls == [(targets[0], "c1", targets[1])]
    # Fully-replicated chunk → no-op scan.
    calls.clear()
    nodes[targets[1]].chunks.add("c1")
    assert rep.scan_once() == 0 and calls == []


def test_erasure_repair_on_read_with_injected_location_loss(tmp_path):
    """ISSUE 2: an injected part loss forces the erasure read ladder
    through parity reconstruction, and repair-on-read rebuilds the lost
    part files in place (ref chunk_replicator.h Repair jobs)."""
    import os

    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    from ytsaurus_tpu.chunks.store import FsChunkStore
    from ytsaurus_tpu.schema import TableSchema
    from ytsaurus_tpu.utils import failpoints

    store = FsChunkStore(str(tmp_path / "store"))
    schema = TableSchema.make([("k", "int64"), ("v", "double")])
    chunk = ColumnarChunk.from_rows(
        schema, [(i, float(i * 3)) for i in range(400)])
    cid = store.write_chunk(chunk, erasure="rs_3_2")
    baseline = store.read_chunk(cid).to_rows()

    # Injected loss: the first part read "vanishes"; parity reconstructs
    # and the counters prove the site fired.
    before = failpoints.counters()["chunks.erasure.part_read"]["triggers"]
    with failpoints.active("chunks.erasure.part_read=error:times=1"):
        assert store.read_chunk(cid).to_rows() == baseline
    after = failpoints.counters()["chunks.erasure.part_read"]["triggers"]
    assert after == before + 1

    # Real location loss: delete two of five part files (rs_3_2 survives
    # any two); the read reconstructs AND rewrites them on disk.
    for i in (0, 3):
        os.unlink(store._part_path(cid, i))
    assert store.read_chunk(cid).to_rows() == baseline
    for i in (0, 3):
        assert os.path.exists(store._part_path(cid, i)), \
            f"repair-on-read did not restore part {i}"
    # The restored parts are byte-identical to a fresh encode.
    from ytsaurus_tpu.chunks.erasure import get_erasure_codec
    codec = get_erasure_codec("rs_3_2")
    fresh = codec.encode(store.get_blob(cid))
    for i in range(codec.total_parts):
        with open(store._part_path(cid, i), "rb") as f:
            assert f.read() == fresh[i]
