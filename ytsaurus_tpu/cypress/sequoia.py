"""Sequoia groundwork: Cypress resolution backed by a dynamic table.

Ref: yt/yt/server/master/sequoia_server/ + the ground tables under
yt/yt/ytlib/sequoia_client/ — the reference's escape from
all-metadata-in-one-master's-RAM: node records move into distributed
dynamic tables ("ground" tables, starting with path→node resolution),
so the metadata plane scales like any other table and masters become
coordinators over it.

This module realizes the first slice the reference built: the RESOLVE
table.  `//sys/sequoia/resolve` is an ordinary sorted dynamic table
(path → node id, type, revision) maintained TRANSACTIONALLY with the
master's mutation stream via a post-commit listener; `resolve()` serves
path lookups from the table — a point lookup instead of a tree walk —
and `verify()` proves table/tree agreement (the consistency invariant
Sequoia's migration hinges on).  Records store the RAW node at each
path — a link row carries the link's own id and type "link", so link
TRAVERSAL stays a resolver-layer concern and removing a link's target
never invalidates the link's row.  A transaction abort rolls the tree
back through undo entries invisible to the mutation stream, so aborts
trigger a full resync (metadata aborts are rare; the reference handles
this with Sequoia transactions, the next slice).

Scope honesty: node CONTENT still lives in the master tree; what rides
the table is resolution metadata.  That is exactly how the reference
staged it — resolve first, then per-object tables.
"""

from __future__ import annotations

from typing import Iterator, Optional

from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.schema import TableSchema

RESOLVE_PATH = "//sys/sequoia/resolve"

RESOLVE_SCHEMA = TableSchema.make([
    ("path", "string", "ascending"),
    ("node_id", "string"),
    ("node_type", "string"),
    ("revision", "int64"),
], unique_keys=True)

# Subtree whose mutations must NOT be mirrored (the resolve table's own
# home — mirroring it would recurse through its mount metadata).
_EXCLUDED_ROOT = "//sys/sequoia"


def _excluded(path: str) -> bool:
    return path == _EXCLUDED_ROOT or \
        path.startswith(_EXCLUDED_ROOT + "/")


def _text(value) -> str:
    return value.decode() if isinstance(value, bytes) else value


def _canon(path: str) -> "Optional[str]":
    """Canonical table key for a client-supplied path ('//a//b' and
    '//a/b' address the same node and must share one row)."""
    from ytsaurus_tpu.cypress.tree import parse_ypath
    try:
        tokens, attr = parse_ypath(path)
    except YtError:
        return None
    if attr is not None or not tokens:
        return None
    return "//" + "/".join(tokens)


class SequoiaResolver:
    """Maintains and serves the resolve table for one cluster."""

    def __init__(self, client):
        self.client = client
        self._revision = 0
        self._enabled = False
        # Host-side mirror of the table's key set: subtree drops become
        # an in-memory prefix scan + exact-key deletes, instead of a
        # table scan under the master mutation lock (and no path text is
        # ever spliced into QL).
        self._paths: set = set()

    # -- lifecycle -------------------------------------------------------------

    def enable(self) -> "SequoiaResolver":
        """Create + mount the resolve table, full-sync it from the tree,
        and subscribe to the mutation stream — atomically under the
        master mutation lock, so no mutation can slip between the sync
        walk and the subscription."""
        if not self.client.exists(RESOLVE_PATH):
            self.client.create("table", RESOLVE_PATH, recursive=True,
                               attributes={"schema": RESOLVE_SCHEMA,
                                           "dynamic": True})
            self.client.mount_table(RESOLVE_PATH)
        master = self.client.cluster.master
        with master.mutation_lock:
            self.full_sync()
            master.add_mutation_listener(self._on_mutation)
        self._enabled = True
        return self

    def _walk_tree(self) -> "Iterator[tuple[str, object]]":
        """(path, RAW node) for every non-excluded tree path — THE single
        walk shared by full_sync and verify.  Raw (no link following):
        a link row records the link itself, so target mutations never
        invalidate it and walks cannot loop through cyclic links."""
        tree = self.client.cluster.master.tree
        stack = [("/", tree.root)]
        while stack:
            path, node = stack.pop()
            for name, child in list(node.children.items()):
                child_path = f"//{name}" if path == "/" else \
                    f"{path}/{name}"
                if _excluded(child_path):
                    continue
                yield child_path, child
                stack.append((child_path, child))

    def full_sync(self) -> int:
        """Rebuild the table from the live tree (bootstrap, post-abort
        resync, or repair after a detected divergence)."""
        rows = [{"path": path, "node_id": node.id,
                 "node_type": node.type, "revision": self._revision}
                for path, node in self._walk_tree()]
        existing = self.client.select_rows(f"path FROM [{RESOLVE_PATH}]")
        if existing:
            self.client.delete_rows(
                RESOLVE_PATH, [(r["path"],) for r in existing])
        if rows:
            self.client.insert_rows(RESOLVE_PATH, rows)
        self._paths = {r["path"] for r in rows}
        return len(rows)

    # -- incremental maintenance ----------------------------------------------

    def _on_mutation(self, op: str, args: dict, result) -> None:
        try:
            self._apply_mutation(op, args)
        except YtError:
            # Upkeep must never block the mutation path; a miss degrades
            # to a stale entry that verify()/full_sync repairs.
            pass

    def _apply_mutation(self, op: str, args: dict) -> None:
        self._revision += 1
        if op == "create":
            self._upsert(args.get("path"))
        elif op == "remove":
            self._drop_subtree(args.get("path"))
        elif op == "set":
            path = args.get("path")
            if path and "/@" not in path:
                # A value set can CREATE the node, and a map_node set
                # replaces its whole child set: resync the subtree.
                self._drop_subtree(path)
                self._upsert_subtree(path)
        elif op in ("copy", "move"):
            if op == "move":
                self._drop_subtree(args.get("src"))
            self._upsert_subtree(args.get("dst"))
        elif op == "link":
            self._upsert(args.get("link"))
        elif op == "tx_abort":
            # The rollback edits the tree through undo entries the
            # mutation stream never sees; resync (aborted metadata txs
            # are rare — Sequoia transactions are the next slice).
            self.full_sync()
        elif op == "batch":
            for sub in args.get("ops") or []:
                self._apply_mutation(sub.get("op"), sub.get("args") or {})

    def _skip(self, path: "Optional[str]") -> bool:
        return not path or "/@" in path or _excluded(path)

    def _upsert(self, path: "Optional[str]") -> None:
        path = _canon(path) if path else None
        if self._skip(path):
            return
        node = self.client.cluster.master.tree.try_resolve(
            path, follow_links=False)
        if node is None:
            return
        self.client.insert_rows(RESOLVE_PATH, [{
            "path": path, "node_id": node.id, "node_type": node.type,
            "revision": self._revision}])
        self._paths.add(path)
        # Ancestors materialized by recursive creates get records too.
        parent = path.rsplit("/", 1)[0]
        if parent and parent != "/" and parent not in self._paths:
            self._upsert(parent)

    def _upsert_subtree(self, path: "Optional[str]") -> None:
        path = _canon(path) if path else None
        if self._skip(path):
            return
        # RAW node: recursion follows real children only (a link's
        # children are the target's business, recorded at its own path).
        node = self.client.cluster.master.tree.try_resolve(
            path, follow_links=False)
        if node is None:
            return
        self._upsert(path)
        for name in list(node.children):
            self._upsert_subtree(f"{path}/{name}")

    def _drop_subtree(self, path: "Optional[str]") -> None:
        path = _canon(path) if path else None
        if self._skip(path):
            return
        doomed = [p for p in self._paths
                  if p == path or p.startswith(path + "/")]
        if doomed:
            self.client.delete_rows(RESOLVE_PATH,
                                    [(p,) for p in doomed])
            self._paths.difference_update(doomed)

    # -- serving ---------------------------------------------------------------

    def resolve(self, path: str) -> "Optional[dict]":
        """Point lookup: {node_id, node_type} or None — the RAW node at
        the path (a link reports type "link"; traversal is the next
        resolver layer).  THE Sequoia win: resolution is a table read,
        not a masters-memory tree walk."""
        path = _canon(path)
        if path is None:
            return None
        (row,) = self.client.lookup_rows(RESOLVE_PATH, [(path,)])
        if row is None:
            return None
        return {"node_id": _text(row["node_id"]),
                "node_type": _text(row["node_type"])}

    def verify(self) -> "list[str]":
        """Table/tree agreement check over the FULL namespace; returns
        divergent paths (empty = consistent).  The Sequoia migration
        invariant, checkable any time because both sides coexist."""
        divergent: list[str] = []
        table_ids: dict[str, str] = {}
        for row in self.client.select_rows(
                f"path, node_id FROM [{RESOLVE_PATH}]"):
            table_ids[_text(row["path"])] = _text(row["node_id"])
        tree_paths = set()
        for path, node in self._walk_tree():
            tree_paths.add(path)
            if table_ids.get(path) != node.id:
                divergent.append(path)
        divergent.extend(p for p in table_ids if p not in tree_paths)
        return sorted(set(divergent))
