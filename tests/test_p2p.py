"""P2P hot-chunk distribution.

Ref model: server/node/data_node/p2p.h (TP2PDistributor) — a hammered
chunk seeds temporary copies onto peers so read load spreads; seeds
evict after the heat passes, and pre-existing replicas are never
evicted.
"""

import time

import pytest

from ytsaurus_tpu.chunks.store import FsChunkStore
from ytsaurus_tpu.rpc import Channel, RpcServer
from ytsaurus_tpu.server.p2p import P2PDistributor
from ytsaurus_tpu.server.services import DataNodeService


@pytest.fixture
def trio(tmp_path):
    """Three in-process data nodes with real RPC between them."""
    nodes = []
    for i in range(3):
        store = FsChunkStore(str(tmp_path / f"n{i}" / "chunks"))
        service = DataNodeService(store, str(tmp_path / f"n{i}" / "j"))
        server = RpcServer([service], port=0)
        server.start()
        nodes.append({"store": store, "service": service,
                      "server": server,
                      "address": f"127.0.0.1:{server.port}"})
    yield nodes
    for n in nodes:
        try:
            n["server"].stop()
        except Exception:       # noqa: BLE001 — a test may have stopped it
            pass


def _distributor(nodes, i, **kw):
    kw.setdefault("hot_threshold", 5)
    kw.setdefault("window", 0.4)
    kw.setdefault("cooldown", 0.5)
    kw.setdefault("fanout", 2)
    peers = [n["address"] for n in nodes]
    return P2PDistributor(nodes[i]["store"],
                          lambda: nodes[i]["address"],
                          lambda: peers, **kw)


def test_hot_chunk_seeds_to_peers_and_evicts(trio):
    src = trio[0]
    src["store"].put_blob("hot1", b"x" * 1024)
    p2p = _distributor(trio, 0)
    for _ in range(10):
        p2p.record_read("hot1")
    p2p.tick_once()
    assert trio[1]["store"].exists("hot1")
    assert trio[2]["store"].exists("hot1")
    assert p2p.stats["seeded_copies"] == 2
    # Heat passes: NO more record_read calls — the tick itself must
    # expire the stale window, or seeds would reheat forever.
    time.sleep(0.6)
    p2p.tick_once()
    assert not trio[1]["store"].exists("hot1")
    assert not trio[2]["store"].exists("hot1")
    assert src["store"].exists("hot1")             # the origin stays
    assert p2p.stats["evicted_copies"] == 2


def test_cold_chunks_not_seeded(trio):
    trio[0]["store"].put_blob("cold", b"y" * 64)
    p2p = _distributor(trio, 0)
    p2p.record_read("cold")
    p2p.tick_once()
    assert not trio[1]["store"].exists("cold")
    assert p2p.stats["seeded_copies"] == 0


def test_existing_holders_never_evicted(trio):
    """A peer that already held the chunk is not a seed target, so
    eviction can never delete a real replica."""
    trio[0]["store"].put_blob("shared", b"z" * 128)
    trio[1]["store"].put_blob("shared", b"z" * 128)   # real replica
    p2p = _distributor(trio, 0)
    for _ in range(10):
        p2p.record_read("shared")
    p2p.tick_once()
    assert trio[2]["store"].exists("shared")          # seeded here only
    with p2p._lock:
        entry = p2p._seeded["shared"]
    assert trio[1]["address"] not in entry["targets"]
    time.sleep(0.6)
    p2p.tick_once()
    assert trio[1]["store"].exists("shared")          # replica SURVIVES
    assert not trio[2]["store"].exists("shared")


def test_continued_heat_extends_seed_lease(trio):
    trio[0]["store"].put_blob("warm", b"w" * 64)
    p2p = _distributor(trio, 0)
    for _ in range(10):
        p2p.record_read("warm")
    p2p.tick_once()
    assert trio[1]["store"].exists("warm")
    time.sleep(0.6)
    for _ in range(10):
        p2p.record_read("warm")                        # still hot
    p2p.tick_once()
    assert trio[1]["store"].exists("warm")             # lease extended


def test_seeded_copy_serves_reads_when_origin_dies(trio):
    """The availability payoff: a seeded copy answers get_chunk after
    the origin is gone — exactly what the client's fallback path probes
    for."""
    trio[0]["store"].put_blob("payoff", b"p" * 256)
    p2p = _distributor(trio, 0, cooldown=60.0)
    for _ in range(10):
        p2p.record_read("payoff")
    p2p.tick_once()
    trio[0]["server"].stop()                           # origin dies
    holder = trio[1] if trio[1]["store"].exists("payoff") else trio[2]
    channel = Channel(holder["address"], timeout=10)
    try:
        _, attachments = channel.call("data_node", "get_chunk",
                                      {"chunk_id": "payoff"})
        assert attachments[0] == b"p" * 256
    finally:
        channel.close()
