"""LocalCluster: spin a REAL multi-process cluster on this machine.

The YTInstance pattern (ref yt/python/yt/environment/yt_env.py:179): spawn
actual daemon processes (1 primary + N data nodes) with generated state
dirs, wait for readiness (port files + driver ping + registered node
count), hand out client addresses, tear everything down.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.rpc import Channel, RetryingChannel


class LocalCluster:
    def __init__(self, root_dir: str, n_nodes: int = 2,
                 replication_factor: int = 2, http_proxy: bool = False,
                 n_masters: int = 1, lease_ttl: float = 4.0,
                 kafka_proxy: bool = False, n_clocks: int = 0,
                 scheduler: bool = False):
        self.root_dir = root_dir
        self.n_nodes = n_nodes
        self.n_masters = n_masters
        self.n_clocks = n_clocks
        self.with_scheduler = scheduler
        self.scheduler_address: "str | None" = None
        self.lease_ttl = lease_ttl
        self.replication_factor = replication_factor
        self.http_proxy = http_proxy
        if kafka_proxy and n_masters > 1:
            # The kafka listener lives inside master 0; after a failover
            # it would point at a dead process.  Until the proxy follows
            # the leader, refuse the combination rather than serve a
            # port that silently dies.
            raise ValueError("kafka_proxy requires n_masters == 1")
        self.kafka_proxy = kafka_proxy
        self.primary_address: str | None = None
        self.master_addresses: list[str] = []
        self.http_proxy_address: str | None = None
        self.kafka_address: str | None = None
        self.node_addresses: list[str] = []
        self.clock_addresses: list[str] = []
        self._procs: list[subprocess.Popen] = []

    # -- lifecycle -------------------------------------------------------------

    def start(self, timeout: float = 120.0) -> "LocalCluster":
        os.makedirs(self.root_dir, exist_ok=True)
        deadline = time.monotonic() + timeout
        election = self.n_masters > 1
        try:
            # Clock peers spawn FIRST and bind port 0 themselves (their
            # RPC surface answers NotClockLeader until the journal plane
            # exists): masters need the clock ADDRESSES at spawn, while
            # the clocks learn the (later) node addresses by polling a
            # journals file — no pre-allocated ports, no bind race.
            clock_procs = self._pending_clock_procs = []
            journals_path = os.path.join(self.root_dir, "journals.txt")
            for c in range(self.n_clocks):
                clock_root = os.path.join(self.root_dir, f"clock{c}")
                self._spawn(f"clock{c}", clock_root, [
                    "--role", "clock", "--root", clock_root,
                    "--journals-file", journals_path,
                    "--master-index", str(c),
                    "--lease-ttl", str(self.lease_ttl)])
                clock_procs.append(self._procs.pop())
            for c in range(self.n_clocks):
                clock_root = os.path.join(self.root_dir, f"clock{c}")
                port = self._wait_port(clock_root, "clock", deadline)
                self.clock_addresses.append(f"127.0.0.1:{port}")
            self._master_args: list[list[str]] = []
            for m in range(self.n_masters):
                name = "primary" if m == 0 else f"primary{m}"
                primary_root = os.path.join(self.root_dir, name)
                args = ["--role", "primary", "--root", primary_root,
                        "--replication-factor",
                        str(self.replication_factor),
                        "--journal-nodes", str(min(3, self.n_nodes))]
                if election:
                    args += ["--election", "--master-index", str(m),
                             "--lease-ttl", str(self.lease_ttl)]
                if self.n_clocks:
                    args += ["--clocks", ",".join(self.clock_addresses)]
                if self.kafka_proxy and m == 0:
                    args += ["--kafka"]
                self._master_args.append(args)
                self._spawn(name, primary_root, args)
            for m in range(self.n_masters):
                name = "primary" if m == 0 else f"primary{m}"
                primary_root = os.path.join(self.root_dir, name)
                port = self._wait_port(primary_root, "primary", deadline)
                self.master_addresses.append(f"127.0.0.1:{port}")
            self.primary_address = self.master_addresses[0]
            primaries = ",".join(self.master_addresses)
            for i in range(self.n_nodes):
                node_root = os.path.join(self.root_dir, f"node{i}")
                self._spawn(f"node{i}", node_root,
                            ["--role", "node", "--root", node_root,
                             "--primary", primaries])
            for i in range(self.n_nodes):
                node_root = os.path.join(self.root_dir, f"node{i}")
                port = self._wait_port(node_root, "node", deadline)
                self.node_addresses.append(f"127.0.0.1:{port}")
            if self.kafka_proxy:
                # The kafka listener comes up AFTER the primary's WAL
                # bootstrap, which itself waits for journal NODES to
                # register — so this wait must sit after the node spawn
                # loop, or startup deadlocks until the primary's
                # bootstrap timeout expires (~60s) and it falls back to
                # a local-only WAL.
                primary_root = os.path.join(self.root_dir, "primary")
                port = self._wait_port(primary_root, "kafka", deadline)
                self.kafka_address = f"127.0.0.1:{port}"
            if self.n_clocks:
                # Journal plane is up: hand its addresses to the waiting
                # clock daemons (atomic publish), and restore the
                # masters→nodes→clocks order the index helpers assume.
                tmp = journals_path + ".tmp"
                with open(tmp, "w") as f:
                    f.write(",".join(self.node_addresses))
                os.replace(tmp, journals_path)
                self._procs.extend(clock_procs)
                self._pending_clock_procs = []
            if self.with_scheduler:
                # The operation daemon (scheduler + controller agent
                # split out of the master process).
                sched_root = os.path.join(self.root_dir, "scheduler")
                self._spawn("scheduler", sched_root, [
                    "--role", "scheduler", "--root", sched_root,
                    "--primary", ",".join(self.master_addresses)])
                port = self._wait_port(sched_root, "scheduler", deadline)
                self.scheduler_address = f"127.0.0.1:{port}"
            self._wait_ready(deadline)
            if self.http_proxy:
                proxy_root = os.path.join(self.root_dir, "proxy")
                self._spawn("proxy", proxy_root,
                            ["--role", "proxy", "--root", proxy_root,
                             "--primary", self.primary_address])
                port = self._wait_port(proxy_root, "proxy", deadline)
                self.http_proxy_address = f"127.0.0.1:{port}"
        except BaseException:
            # A failed start must not leak daemon processes.
            self.stop()
            raise
        return self

    def _spawn(self, name: str, root: str, args: list[str]) -> None:
        os.makedirs(root, exist_ok=True)
        # Drop stale port files: a restart on the same root must not hand
        # out the previous incarnation's ports.
        for stale in ("primary.port", "node.port", "proxy.port",
                      "clock.port", "scheduler.port"):
            try:
                os.unlink(os.path.join(root, stale))
            except FileNotFoundError:
                pass
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"          # daemons never need the chip
        env.pop("XLA_FLAGS", None)
        with open(os.path.join(root, "daemon.log"), "ab") as log:
            proc = subprocess.Popen(
                [sys.executable, "-m", "ytsaurus_tpu.server.daemon", *args],
                stdout=log, stderr=subprocess.STDOUT, env=env,
                cwd=os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))))
        self._procs.append(proc)

    def _wait_port(self, root: str, role: str, deadline: float) -> int:
        path = os.path.join(root, f"{role}.port")
        while time.monotonic() < deadline:
            if os.path.exists(path):
                with open(path) as f:
                    return int(f.read().strip())
            self._check_daemons()
            time.sleep(0.1)
        raise YtError(f"{role} daemon did not bind a port "
                      f"(see {root}/daemon.log)")

    def _wait_ready(self, deadline: float) -> None:
        """Ready = some master is LEADER with every node registered and
        the driver answering (under election the leader may be any
        master)."""
        channels = {addr: RetryingChannel(Channel(addr, timeout=10),
                                          attempts=3, backoff=0.2)
                    for addr in (self.master_addresses or
                                 [self.primary_address])}
        try:
            while time.monotonic() < deadline:
                self._check_daemons()
                for addr, channel in channels.items():
                    try:
                        body, _ = channel.call("node_tracker",
                                               "list_nodes", {})
                        alive = body.get("alive", [])
                        if len(alive) < self.n_nodes:
                            continue
                        # Driver comes up after WAL recovery (on the
                        # leader only); ready means BOTH planes answer.
                        channel.call("driver", "ping", {})
                        return
                    except YtError:
                        continue
                time.sleep(0.2)
            raise YtError(
                f"cluster not ready: {self.n_nodes} nodes expected")
        finally:
            for channel in channels.values():
                channel.close()

    def _check_daemons(self) -> None:
        for proc in self._procs:
            rc = proc.poll()
            if rc is not None:
                raise YtError(f"daemon pid {proc.pid} exited rc={rc} during "
                              "startup (see its daemon.log)")

    def stop(self) -> None:
        # Clock procs not yet folded into _procs (startup failed before
        # the journal plane came up) must not leak.
        doomed = self._procs + list(getattr(self, "_pending_clock_procs",
                                            []) or [])
        for proc in doomed:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in doomed:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
        self._procs.clear()
        self._pending_clock_procs = []

    def restart_primary(self, timeout: float = 120.0,
                        index: int = 0) -> None:
        """Stop a master and bring it back on the same state root with
        the SAME flags (recovery-path fault injection: quorum WAL replay
        + snapshot load; under election it rejoins as a candidate).
        The address may change; read `primary_address` afterwards."""
        proc = self._procs[index]
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
        self._procs.pop(index)
        deadline = time.monotonic() + timeout
        name = "primary" if index == 0 else f"primary{index}"
        primary_root = os.path.join(self.root_dir, name)
        # Rebind the SAME port: data nodes heartbeat a fixed primary
        # address (stable daemon addresses, as in real deployments).
        old_port = (self.master_addresses[index] if self.master_addresses
                    else self.primary_address).rsplit(":", 1)[1]
        self._spawn(name, primary_root,
                    self._master_args[index] + ["--port", old_port])
        # _spawn appends; keep masters before nodes (kill_node contract).
        self._procs.insert(index, self._procs.pop())
        port = self._wait_port(primary_root, "primary", deadline)
        if self.master_addresses:
            self.master_addresses[index] = f"127.0.0.1:{port}"
        if index == 0:
            self.primary_address = f"127.0.0.1:{port}"
        self._wait_ready(deadline)

    def kill_node(self, index: int) -> None:
        """Hard-kill one data node (fault injection for replica fallback)."""
        # procs[0..n_masters-1] are masters; nodes follow in order.
        proc = self._procs[self.n_masters + index]
        proc.kill()
        proc.wait(timeout=10)

    # -- multi-master helpers --------------------------------------------------

    def _poll_leader(self, addresses, proc_offset: int, service: str,
                     method: str, is_leader, timeout: float,
                     what: str) -> int:
        """Shared leader poll for any role: index of the first peer whose
        `service.method` response satisfies is_leader(body)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for i, addr in enumerate(addresses):
                if self._procs[proc_offset + i].poll() is not None:
                    continue
                channel = Channel(addr, timeout=5)
                try:
                    body, _ = channel.call(service, method, {})
                    if is_leader(body):
                        return i
                except YtError:
                    continue
                finally:
                    channel.close()
            time.sleep(0.3)
        raise YtError(f"no {what} reported leadership in time")

    def leader_index(self, timeout: float = 30.0) -> int:
        """Index of the master currently reporting role=leader."""
        def is_leader(body):
            role = body.get("role")
            role = role.decode() if isinstance(role, bytes) else role
            return role == "leader"
        return self._poll_leader(self.master_addresses, 0, "master",
                                 "get_role", is_leader, timeout,
                                 "master")

    def kill_leader(self) -> int:
        """Hard-kill the current leader master; returns its index."""
        m = self.leader_index()
        proc = self._procs[m]
        proc.kill()
        proc.wait(timeout=10)
        return m

    # -- operation-daemon helpers ----------------------------------------------

    def _scheduler_proc_index(self) -> int:
        if not self.with_scheduler:
            raise YtError("cluster started without scheduler=True")
        return self.n_masters + self.n_nodes + self.n_clocks

    def kill_scheduler(self) -> None:
        """Hard-kill the operation daemon (kill -9 fault injection)."""
        proc = self._procs[self._scheduler_proc_index()]
        proc.kill()
        proc.wait(timeout=10)

    def restart_scheduler(self, timeout: float = 120.0) -> None:
        """Bring the operation daemon back on the same root: it revives
        orphaned operations from their Cypress records + snapshots."""
        index = self._scheduler_proc_index()
        self._procs.pop(index)
        sched_root = os.path.join(self.root_dir, "scheduler")
        self._spawn("scheduler", sched_root, [
            "--role", "scheduler", "--root", sched_root,
            "--primary", ",".join(self.master_addresses)])
        self._procs.insert(index, self._procs.pop())
        deadline = time.monotonic() + timeout
        port = self._wait_port(sched_root, "scheduler", deadline)
        self.scheduler_address = f"127.0.0.1:{port}"

    # -- clock-quorum helpers --------------------------------------------------

    def clock_leader_index(self, timeout: float = 30.0) -> int:
        """Index of the clock peer currently leading the quorum."""
        return self._poll_leader(
            self.clock_addresses, self.n_masters + self.n_nodes,
            "clock", "clock_state", lambda body: bool(body.get("leader")),
            timeout, "clock peer")

    def kill_clock_leader(self) -> int:
        """Hard-kill the current clock leader; returns its index."""
        c = self.clock_leader_index()
        proc = self._procs[self.n_masters + self.n_nodes + c]
        proc.kill()
        proc.wait(timeout=10)
        return c

    def __enter__(self) -> "LocalCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
