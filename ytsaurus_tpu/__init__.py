"""ytsaurus_tpu — a TPU-native distributed table store + query/compute framework.

A ground-up rebuild of the capabilities of ytsaurus/ytsaurus (reference layout in
SURVEY.md) designed for TPU hardware: columnar chunks staged into HBM, query plans
lowered to XLA (with Pallas kernels for the hash/sort hot loops), distribution via
jax.sharding meshes with ICI collectives (psum / all_to_all) instead of a TCP bus.

Layer map (mirrors SURVEY.md §1, re-architected TPU-first):
  - schema / rows / yson        — data model (ref: yt/yt/client/table_client)
  - chunks                      — columnar chunk format + store + HBM staging
                                  (ref: yt/yt/ytlib/columnar_chunk_format)
  - query                       — QL front end, plan IR, XLA lowering, evaluator
                                  (ref: yt/yt/library/query)
  - parallel                    — mesh / collectives / shuffle (ref: core/bus + rpc)
  - operations                  — MapReduce-style operations incl. Sort
                                  (ref: yt/yt/server/controller_agent/controllers)
  - tablet                      — dynamic tables: MVCC dynamic stores, lookup
                                  (ref: yt/yt/server/node/tablet_node)
  - cypress                     — metadata tree + transactions (ref: server/master)
"""

import jax as _jax

# Exact 64-bit integer and double semantics are load-bearing for a database
# engine (ref row model: client/table_client/unversioned_row.h uses i64/ui64/
# double).  JAX defaults to 32-bit; opt the whole framework into x64.
_jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from ytsaurus_tpu.errors import YtError, YtResponseError  # noqa: E402,F401
from ytsaurus_tpu.schema import (  # noqa: E402,F401
    ColumnSchema,
    EValueType,
    SortOrder,
    TableSchema,
)
