"""Replicated chunk store: N locations, read fallback, write-back repair.

Ref: the data-node/master replication pair (server/master/chunk_server/
chunk_replicator.h issuing Replicate/Repair jobs; replication_reader.cpp
falling back across replicas).  Collapsed to one process: a chunk writes to
`replication_factor` locations; reads try locations in order and, after a
successful read, re-replicate to locations that lost their copy (the
repair-on-read analog of the replicator's background jobs).  Erasure-coded
writes pass through to a single location (parity already provides
redundancy).
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Optional

from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.chunks.encoding import DEFAULT_CODEC
from ytsaurus_tpu.chunks.store import FsChunkStore, new_chunk_id
from ytsaurus_tpu.config import retry_policy
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils.logging import get_logger, log_event

import logging as _logging


def _is_missing(err: Exception) -> bool:
    """A clean 'this location has no such chunk' — NOT a dying disk."""
    return isinstance(err, YtError) and err.code == EErrorCode.NoSuchChunk


class ReplicatedChunkStore:
    """Drop-in FsChunkStore replacement spanning several directories."""

    def __init__(self, roots: list[str], replication_factor: int = 2,
                 codec: str = DEFAULT_CODEC,
                 blacklist_ttl: float = 15.0):
        if not roots:
            raise YtError("ReplicatedChunkStore needs at least one location")
        self.locations = [FsChunkStore(root, codec=codec) for root in roots]
        self.replication_factor = min(replication_factor, len(self.locations))
        self.codec = codec
        self.blacklist_ttl = blacklist_ttl
        # Location root → monotonic deadline until which reads skip it (a
        # location that just threw a disk-shaped error is probably still
        # broken; probing it on every read serializes the ladder on its
        # failure latency).  Ref: replication_reader.cpp banned peers.
        self._banned_until: dict[str, float] = {}
        self._ban_lock = threading.Lock()
        self._log = get_logger("ChunkReplicator")

    # -- location blacklist ----------------------------------------------------

    def _ban(self, store: FsChunkStore) -> None:
        if self.blacklist_ttl <= 0:
            return
        with self._ban_lock:
            self._banned_until[store.root] = \
                time.monotonic() + self.blacklist_ttl

    def _usable(self, stores: "list[FsChunkStore]") -> "list[FsChunkStore]":
        """Non-blacklisted locations — ALL of them when every location is
        banned (a desperation round beats a guaranteed failure)."""
        with self._ban_lock:
            now = time.monotonic()
            for root, until in list(self._banned_until.items()):
                if until <= now:
                    del self._banned_until[root]
            usable = [s for s in stores
                      if s.root not in self._banned_until]
        return usable or list(stores)

    # -- placement -------------------------------------------------------------

    def _placement(self, chunk_id: str) -> list[FsChunkStore]:
        """Deterministic location order per chunk (rendezvous hashing with a
        process-independent hash — python's hash() is salted per process and
        would make replicas drift across restarts)."""
        def rank(i: int) -> bytes:
            return hashlib.sha256(f"{chunk_id}:{i}".encode()).digest()
        ranked = sorted(range(len(self.locations)), key=rank)
        return [self.locations[i] for i in ranked]

    # -- FsChunkStore surface --------------------------------------------------

    def write_chunk(self, chunk: ColumnarChunk,
                    chunk_id: Optional[str] = None,
                    codec: Optional[str] = None,
                    erasure: Optional[str] = None) -> str:
        chunk_id = chunk_id or new_chunk_id()
        placement = self._placement(chunk_id)
        if erasure is not None:
            placement[0].write_chunk(chunk, chunk_id=chunk_id, codec=codec,
                                     erasure=erasure)
            return chunk_id
        written = 0
        errors = []
        for store in placement:
            if written >= self.replication_factor:
                break
            try:
                store.write_chunk(chunk, chunk_id=chunk_id, codec=codec)
                written += 1
            except OSError as e:          # location down/full
                errors.append(e)
                log_event(self._log, _logging.WARNING, "replica_write_failed",
                          chunk_id=chunk_id, location=store.root,
                          error=str(e))
        if written == 0:
            raise YtError(f"All locations failed writing chunk {chunk_id}",
                          code=EErrorCode.ChunkFormatError,
                          attributes={"errors": [str(e) for e in errors]})
        if written < self.replication_factor:
            log_event(self._log, _logging.WARNING, "chunk_under_replicated",
                      chunk_id=chunk_id, replicas=written,
                      target=self.replication_factor)
        return chunk_id

    def _read_with_ladder(self, chunk_id: str, probe):
        """Read ladder (ref replication_reader.cpp): rotate across the
        placement, blacklist locations that threw disk-shaped errors,
        and retry whole rounds with jittered exponential backoff — a
        transient fault (node restarting, injected failpoint) must not
        fail a read that ANY replica can still serve.  Per-location
        errors aggregate into the final YtError instead of only the
        last one surviving.  Returns (serving store, probe result,
        placement) — placement rides along so hot-path callers don't
        re-run the rendezvous hash."""
        from ytsaurus_tpu.utils.tracing import child_span
        policy = retry_policy("chunk_read")
        placement = self._placement(chunk_id)
        errors: dict[str, Exception] = {}
        with child_span("chunk.replicated_read",
                        chunk_id=chunk_id) as span:
            for attempt in range(policy.attempts):
                # The blacklist steers the FIRST round (skip known-bad
                # locations, serve from a healthy replica fast).  Later
                # rounds re-probe everything: when the only holder was
                # the banned location, honoring its ban would starve the
                # retry into a guaranteed failure.
                stores = self._usable(placement) if attempt == 0 \
                    else list(placement)
                for store in stores:
                    try:
                        result = probe(store)
                        span.add_tag("location", store.root)
                        span.add_tag("round", attempt)
                        span.add_tag("probes_failed", len(errors))
                        return store, result, placement
                    except (YtError, OSError) as e:   # missing OR dying
                        errors[store.root] = e
                        if not _is_missing(e):
                            self._ban(store)
                        continue
                if len(errors) == len(placement) and \
                        all(_is_missing(e) for e in errors.values()):
                    break   # cleanly absent everywhere: waiting cannot
                    # help
                if attempt + 1 < policy.attempts:
                    time.sleep(policy.delay(attempt))
            raise self._aggregate_read_error(chunk_id, placement, errors)

    def read_chunk(self, chunk_id: str) -> ColumnarChunk:
        store, chunk, placement = self._read_with_ladder(
            chunk_id, lambda s: s.read_chunk(chunk_id))
        import os
        is_erasure = os.path.exists(store._erasure_meta_path(chunk_id))
        if not is_erasure:
            # Erasure chunks carry their own redundancy; replicating
            # them in full would defeat the coding's storage savings.
            self._maybe_repair(chunk_id, chunk, placement)
        return chunk

    def _aggregate_read_error(self, chunk_id: str, placement,
                              errors: "dict[str, Exception]") -> YtError:
        inner = []
        for store in placement:
            err = errors.get(store.root)
            if err is None:
                continue
            if isinstance(err, YtError):
                err.attributes.setdefault("location", store.root)
                inner.append(err)
            else:
                inner.append(YtError(
                    f"location {store.root}: {err}",
                    code=EErrorCode.ChunkFormatError,
                    attributes={"location": store.root}))
        all_missing = bool(inner) and all(
            e.code == EErrorCode.NoSuchChunk for e in inner)
        code = EErrorCode.NoSuchChunk if all_missing or not inner \
            else next(e.code for e in inner
                      if e.code != EErrorCode.NoSuchChunk)
        return YtError(
            f"No location could serve chunk {chunk_id} "
            f"({len(inner)}/{len(placement)} failed)",
            code=code, inner_errors=inner)

    def _maybe_repair(self, chunk_id: str, chunk: ColumnarChunk,
                      placement: list[FsChunkStore]) -> None:
        """Top up to replication_factor TOTAL copies (counting copies on any
        location — a write that spilled past a failed location must not be
        re-replicated into over-replication when it recovers)."""
        holders = [s for s in placement if s.exists(chunk_id)]
        missing = self.replication_factor - len(holders)
        if missing <= 0:
            return
        for store in placement:
            if missing <= 0:
                break
            if store in holders:
                continue
            try:
                store.write_chunk(chunk, chunk_id=chunk_id)
                missing -= 1
                log_event(self._log, _logging.INFO, "replica_repaired",
                          chunk_id=chunk_id, location=store.root)
            except OSError:
                continue

    def read_meta(self, chunk_id: str) -> dict:
        # Same ladder as read_chunk: without the round-2 full-placement
        # re-probe, a ban on the sole holder would make meta reads
        # report an existing chunk as absent for the whole ban TTL.
        _, meta, _ = self._read_with_ladder(
            chunk_id, lambda s: s.read_meta(chunk_id))
        return meta

    def read_stats(self, chunk_id: str,
                   backfill_sketch: bool = False) -> dict:
        """Seal-time column stats through the replica read ladder (each
        location's FsChunkStore memoizes, incl. the pre-stats decode
        backfill)."""
        _, stats, _ = self._read_with_ladder(
            chunk_id,
            lambda s: s.read_stats(chunk_id,
                                   backfill_sketch=backfill_sketch))
        return stats

    def exists(self, chunk_id: str) -> bool:
        return any(store.exists(chunk_id) for store in self.locations)

    def remove_chunk(self, chunk_id: str) -> None:
        for store in self.locations:
            store.remove_chunk(chunk_id)

    def list_chunks(self) -> list[str]:
        out: set[str] = set()
        for store in self.locations:
            out.update(store.list_chunks())
        return sorted(out)
