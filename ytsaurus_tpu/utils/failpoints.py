"""Deterministic failpoint injection (ISSUE 2 tentpole).

Ref shape: the reference's testing fault hooks (library/named_value +
the `TDelayedExecutor`-based fault injection sprinkled through
integration tests) generalized into one registry, in the spirit of
FreeBSD/CockroachDB failpoints: every interesting I/O or execution
boundary names a **site** (`chunks.store.read`, `rpc.channel.send`,
...), and a **schedule** activated per process decides, deterministically
and reproducibly, which hits of which sites misbehave and how.

Modes
-----
  error       raise the site's registered error type (an OSError for disk
              sites, a transport-coded YtError for RPC sites, ...)
  delay       sleep `ms` milliseconds (straggler simulation)
  crash-once  raise InjectedCrash — a BaseException that deliberately
              pierces every `except Exception` boundary, so the process
              behaves as if it died at the site (operation docs stay
              'running', worker slots vanish).  Disarms after one shot.
  torn-write  write sites only: the payload is truncated mid-write and
              the write fails AFTER the torn bytes hit the tmp file —
              proving that tmp+rename publishing keeps torn bytes
              invisible to readers.

Schedules
---------
A spec is `site=mode[:k=v]...` entries joined by `;`:

    YT_FAILPOINTS="chunks.store.read=error:times=2;rpc.channel.send=delay:ms=5:p=0.5"

Per-rule knobs: `p` (trigger probability per eligible hit, decided by a
per-site RNG seeded from (seed, site) — same seed, same hit order, same
schedule), `1in` (every n-th eligible hit), `times` (max triggers;
crash-once defaults to 1), `after` (skip the first n hits), `ms` (delay
length).

Activation: the `YT_FAILPOINTS` / `YT_FAILPOINTS_SEED` environment (read
at import), `config.FailpointsConfig` via :func:`configure`, or the
:func:`active` context manager (what the pytest soak uses).  Per-site
hit/trigger counters are cumulative for the process and exported through
the monitoring endpoint (`/failpoints`, plus `/metrics` mirrors under
`failpoints_*`).

The disabled fast path is one module-global read per hit — no locks, no
dict lookups — so production code pays nothing for carrying the sites.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from typing import Callable, Optional

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils import sanitizers

MODES = ("error", "delay", "crash-once", "torn-write")


class InjectedCrash(BaseException):
    """Simulated process death at a failpoint.  Derives from
    BaseException ON PURPOSE: ordinary `except Exception` recovery code
    must NOT see it — a real crash doesn't run error handlers either."""


def _default_error(site_name: str) -> BaseException:
    return YtError(f"injected fault at failpoint {site_name!r}",
                   code=EErrorCode.Generic,
                   attributes={"failpoint": site_name})


class _Rule:
    """One parsed `site=mode:...` entry plus its runtime trigger state."""

    __slots__ = ("mode", "p", "one_in", "times", "after", "ms",
                 "hits", "triggered", "rng")

    def __init__(self, mode: str, p: float = 1.0, one_in: int = 0,
                 times: Optional[int] = None, after: int = 0,
                 ms: float = 10.0):
        if mode not in MODES:
            raise YtError(f"Unknown failpoint mode {mode!r} "
                          f"(expected one of {MODES})",
                          code=EErrorCode.InvalidConfig)
        self.mode = mode
        self.p = p
        self.one_in = one_in
        self.times = times if times is not None else \
            (1 if mode == "crash-once" else None)
        self.after = after
        self.ms = ms
        self.hits = 0
        self.triggered = 0
        self.rng: Optional[random.Random] = None   # bound at activation


class _State:
    """One activation: rules by site name + the seed that makes p-based
    decisions reproducible."""

    def __init__(self, rules: "dict[str, _Rule]", seed: int, spec: str):
        self.rules = rules
        self.seed = seed
        self.spec = spec
        for name, rule in rules.items():
            rule.rng = random.Random(f"{seed}:{name}")


# The ONE global read on the disabled fast path.
_STATE: Optional[_State] = None
# guards: _STATE, _SITES
_LOCK = sanitizers.register_lock("failpoints._LOCK", hot=False)
_SITES: "dict[str, FailpointSite]" = {}


class FailpointSite:
    """A named fault site.  `hit()` is the generic probe; write paths use
    `write_hit(blob)` so torn-write can mangle the payload."""

    __slots__ = ("name", "error_factory", "hits", "triggers",
                 "_prof_hits", "_prof_triggers")

    def __init__(self, name: str,
                 error: Optional[Callable[[str], BaseException]] = None):
        self.name = name
        self.error_factory = error or _default_error
        self.hits = 0        # cumulative, only counted while active
        self.triggers = 0
        self._prof_hits = None
        self._prof_triggers = None

    # -- trigger evaluation ----------------------------------------------------

    def fire(self, write: bool = False) -> "Optional[tuple[str, float]]":
        """Evaluate the schedule for one hit; returns (mode, param) when
        a fault should fire, None otherwise.  Does NOT raise or sleep —
        call sites needing custom handling (async server drop) use this
        directly; everything else goes through hit()/write_hit()."""
        state = _STATE
        if state is None:
            return None
        result = self._fire_locked(state, write)
        # Mirror on EVERY active hit (not just triggers), or /metrics
        # would show a site as dead while it accumulates toward `after`.
        self._ensure_sensors()
        self._prof_hits.set(self.hits)
        if result is not None:
            self._prof_triggers.increment()
        return result

    def _fire_locked(self, state: _State,
                     write: bool) -> "Optional[tuple[str, float]]":
        with _LOCK:
            self.hits += 1
            rule = state.rules.get(self.name)
            if rule is None:
                return None
            rule.hits += 1
            if rule.mode == "torn-write" and not write:
                return None          # torn-write only mangles write sites
            if rule.hits <= rule.after:
                return None
            if rule.times is not None and rule.triggered >= rule.times:
                return None
            if rule.one_in and (rule.hits - rule.after - 1) % rule.one_in:
                return None
            if rule.p < 1.0 and rule.rng.random() >= rule.p:
                return None
            rule.triggered += 1
            self.triggers += 1
        return rule.mode, rule.ms

    def _ensure_sensors(self) -> None:
        # Lazy: the profiling registry import stays off the fast path.
        # hits mirrors as a set-style gauge — a computed increment delta
        # would double-count under concurrent hits.
        if self._prof_triggers is None:
            from ytsaurus_tpu.utils.profiling import Profiler
            prof = Profiler("/failpoints").with_tags(site=self.name)
            self._prof_hits = prof.gauge("hits")
            self._prof_triggers = prof.counter("triggers")

    # -- probe APIs ------------------------------------------------------------

    def hit(self) -> None:
        """Generic probe: may sleep (delay), raise the site's error
        (error), or raise InjectedCrash (crash-once)."""
        # Failpoint sites ARE the statically-enforced I/O boundary list
        # (the coverage pass): the concurrency sanitizer reuses them as
        # its blocking-I/O probes — one global read when disabled.
        sanitizers.note_blocking("io", self.name)
        if _STATE is None:      # disabled fast path: one global read
            return
        act = self.fire()
        if act is None:
            return
        mode, ms = act
        if mode == "delay":
            time.sleep(ms / 1000.0)
        elif mode == "error":
            raise self.error_factory(self.name)
        elif mode == "crash-once":
            raise InjectedCrash(f"injected crash at failpoint {self.name}")

    def write_hit(self, blob: bytes) -> "tuple[bytes, bool]":
        """Write-site probe.  Returns (payload, torn): with torn=True the
        caller must write `payload` (a truncated prefix) to its STAGING
        location and then fail the write WITHOUT publishing — simulating
        a crash mid-write."""
        sanitizers.note_blocking("io", self.name)
        if _STATE is None:
            return blob, False
        act = self.fire(write=True)
        if act is None:
            return blob, False
        mode, ms = act
        if mode == "delay":
            time.sleep(ms / 1000.0)
            return blob, False
        if mode == "error":
            raise self.error_factory(self.name)
        if mode == "crash-once":
            raise InjectedCrash(f"injected crash at failpoint {self.name}")
        return blob[: max(len(blob) // 2, 1)], True   # torn-write


def register_site(name: str,
                  error: Optional[Callable[[str], BaseException]] = None
                  ) -> FailpointSite:
    """Get-or-create a site.  Module-import time registration keeps the
    full site list enumerable (the chaos soak asserts coverage over it)."""
    with _LOCK:
        site = _SITES.get(name)
        if site is None:
            site = _SITES[name] = FailpointSite(name, error=error)
        return site


def registered_sites() -> "list[str]":
    with _LOCK:
        return sorted(_SITES)


def counters() -> "dict[str, dict]":
    """Cumulative per-site counters (survive activation cycles)."""
    with _LOCK:
        return {name: {"hits": s.hits, "triggers": s.triggers}
                for name, s in sorted(_SITES.items())}


def reset_counters() -> None:
    with _LOCK:
        for site in _SITES.values():
            site.hits = 0
            site.triggers = 0


# -- spec parsing / activation -------------------------------------------------


def parse_spec(spec: str) -> "dict[str, _Rule]":
    """`site=mode[:k=v]...;site2=...` → rules by site name."""
    rules: dict[str, _Rule] = {}
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise YtError(f"Bad failpoint entry {entry!r} "
                          "(expected site=mode[:k=v]...)",
                          code=EErrorCode.InvalidConfig)
        name, _, rest = entry.partition("=")
        parts = rest.split(":")
        mode = parts[0].strip()
        kwargs: dict = {}
        for kv in parts[1:]:
            if not kv:
                continue
            key, _, value = kv.partition("=")
            key = key.strip()
            try:
                if key == "p":
                    kwargs["p"] = float(value)
                elif key == "1in":
                    kwargs["one_in"] = int(value)
                elif key == "times":
                    kwargs["times"] = int(value)
                elif key == "after":
                    kwargs["after"] = int(value)
                elif key == "ms":
                    kwargs["ms"] = float(value)
                else:
                    raise YtError(
                        f"Unknown failpoint knob {key!r} in {entry!r}",
                        code=EErrorCode.InvalidConfig)
            except ValueError as exc:
                raise YtError(f"Bad failpoint value {kv!r} in {entry!r}",
                              code=EErrorCode.InvalidConfig) from exc
        rules[name.strip()] = _Rule(mode, **kwargs)
    return rules


def activate(spec: str, seed: int = 0) -> None:
    """Replace the active schedule.  Unknown site names are allowed (the
    hosting module may not be imported yet); they simply never match."""
    global _STATE
    state = _State(parse_spec(spec), seed=seed, spec=spec)
    with _LOCK:
        _STATE = state if state.rules else None


def deactivate() -> None:
    global _STATE
    with _LOCK:
        _STATE = None


def is_active() -> bool:
    return _STATE is not None


def active_spec() -> Optional[str]:
    state = _STATE
    return state.spec if state is not None else None


@contextlib.contextmanager
def active(spec: str, seed: int = 0):
    """Scoped activation (the pytest-facing surface).  Nested use
    restores the previous schedule on exit."""
    global _STATE
    with _LOCK:
        prev = _STATE
    activate(spec, seed=seed)
    try:
        yield
    finally:
        with _LOCK:
            # analyze: allow(atomicity): scoped save/restore by design — prev IS the value to restore; concurrent activation scopes are a test-harness misuse, not a race this code defends against
            _STATE = prev


def schedule_snapshot() -> "dict[str, dict]":
    """Per-rule live state of the ACTIVE schedule (monitoring view)."""
    state = _STATE
    if state is None:
        return {}
    with _LOCK:
        return {name: {"mode": r.mode, "p": r.p, "one_in": r.one_in,
                       "times": r.times, "after": r.after, "ms": r.ms,
                       "hits": r.hits, "triggered": r.triggered}
                for name, r in state.rules.items()}


def configure(config) -> None:
    """Apply a config.FailpointsConfig (programmatic/config-file path;
    spawned daemons arm from the YT_FAILPOINTS environment instead)."""
    if config is None or not getattr(config, "spec", ""):
        return
    activate(config.spec, seed=int(getattr(config, "seed", 0)))


# Environment activation: the subprocess story (daemons spawned under a
# chaos harness inherit YT_FAILPOINTS and arm themselves on import).
_env_spec = os.environ.get("YT_FAILPOINTS", "")
if _env_spec:
    activate(_env_spec, seed=int(os.environ.get("YT_FAILPOINTS_SEED", "0")))
