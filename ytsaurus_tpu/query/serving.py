"""Query serving plane: admission control, deadline propagation, and
continuous micro-batching for lookups and selects (ISSUE 3 tentpole).

Ref shape: the reference serves interactive reads through a dedicated
query service with bounded in-flight windows and lookup sessions
(yt/yt/server/node/query_agent/query_service.cpp — TQueryService's
in-flight budget, TLookupSession batching concurrent reads against one
tablet).  On the XLA backbone the same idea doubles as inference-style
continuous batching: concurrent point lookups against one table coalesce
inside a flush window into one batched, order-preserving tablet read,
and the batched chunk probe buckets its key (needle) arrays to powers
of two, so gather shapes stay a bounded spectrum instead of one per
batch size — the bounded-shape discipline that keeps a JIT engine's
program cache from exploding (selects get the same guarantee from the
evaluator's capacity-bucketed compile cache) ("An Empirical Analysis of
Just-in-Time Compilation in Modern Databases", PAPERS.md).

Three pieces, one facade (`QueryGateway`, one per YtCluster):

  AdmissionController   per-pool weighted concurrency slots over a
                        bounded wait queue; overflow raises
                        `errors.ThrottledError` carrying a `retry_after`
                        hint derived from the observed slot drain rate.
  CancellationToken     deadline + cooperative cancellation, checked in
                        `coordinator.coordinate_and_execute`'s staging/
                        execution loop and in the evaluator, so a
                        timed-out query stops consuming device time
                        mid-plan instead of running to completion.
  LookupBatcher         continuous micro-batching of `lookup_rows`:
                        requests enqueue and a dedicated flusher thread
                        accumulates each arriving cohort (growth-stable
                        poll bounded by `flush_window_ms`), then runs
                        ONE batched read per (table, timestamp) with
                        parallel per-tablet fan-out, scattering rows
                        back in each caller's request order.

Serving metrics (queue depth, admitted/rejected/expired, batch size and
latency histograms) publish through `utils/profiling` under `/serving`,
so every daemon's monitoring `/metrics` endpoint exports them; the
`/serving` endpoint serves a structured snapshot.

Failpoint sites: `serving.admit` (admission decision; error mode injects
a ThrottledError) and `serving.batch_flush` (batched read execution).
"""

from __future__ import annotations

import threading
import time
import weakref
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional, Sequence

from ytsaurus_tpu.config import ServingConfig
from ytsaurus_tpu.cypress.security import current_user
from ytsaurus_tpu.errors import EErrorCode, ThrottledError, YtError
from ytsaurus_tpu.operations.fair_share import (
    PoolState as FairPoolState,
    compute_fair_shares,
)
from ytsaurus_tpu.query.accounting import get_accountant
from ytsaurus_tpu.utils import failpoints
from ytsaurus_tpu.utils.profiling import Profiler
from ytsaurus_tpu.utils.tracing import NULL_SPAN, child_span, current_trace
from ytsaurus_tpu.utils import sanitizers

_FP_ADMIT = failpoints.register_site(
    "serving.admit",
    error=lambda s: ThrottledError(
        f"injected admission rejection at {s}", retry_after=0.05))
_FP_BATCH_FLUSH = failpoints.register_site(
    "serving.batch_flush",
    error=lambda s: YtError(f"injected batch flush failure at {s}",
                            code=EErrorCode.TransportError))
_FP_BROWNOUT = failpoints.register_site(
    "serving.brownout",
    error=lambda s: YtError(f"injected brown-out degradation failure "
                            f"at {s}", code=EErrorCode.TransportError))

# Sub-millisecond latency buckets: point lookups sit well under the
# profiling default's 1ms floor.
_LATENCY_BOUNDS = (0.0002, 0.0005, 0.001, 0.002, 0.005, 0.01, 0.025,
                   0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0)
_BATCH_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096)


class CancellationToken:
    """Deadline + cooperative cancellation, threaded through execution.

    `check()` is the probe the coordinator/evaluator call between units
    of work; it raises `DeadlineExceeded` (terminal — never retried) or
    `Canceled`.  Tokens are cheap and thread-safe; `None` everywhere
    means "no deadline" so non-gateway callers pay nothing.

    The token also carries the admitted request's IDENTITY — (pool,
    user) — down through `coordinator.coordinate_and_execute`, the
    evaluator, and the tablet read path, so per-tenant resource
    accounting (query/accounting.py) can attribute what each layer
    consumed without a side channel."""

    __slots__ = ("deadline", "pool", "user", "_cancelled", "_reason",
                 "staleness_bound", "rung", "stale_served")

    def __init__(self, deadline: Optional[float] = None,
                 pool: Optional[str] = None,
                 user: Optional[str] = None):
        self.deadline = deadline          # time.monotonic() timestamp
        self.pool = pool
        self.user = user
        self._cancelled = False
        self._reason: Optional[str] = None
        # Brown-out ladder (ISSUE 17): when the gateway admits this
        # request under rung 1, `staleness_bound` carries the pool's
        # declared bound down to the tablet read path, and the read path
        # writes back the ACTUAL staleness it served (`stale_served`) so
        # every degraded response is tagged with what it got.
        self.staleness_bound: Optional[float] = None
        self.rung = 0
        self.stale_served = 0.0

    @classmethod
    def with_timeout(cls, timeout: Optional[float],
                     pool: Optional[str] = None,
                     user: Optional[str] = None) -> "CancellationToken":
        deadline = time.monotonic() + timeout \
            if timeout is not None and timeout > 0 else None
        return cls(deadline, pool=pool, user=user)

    def cancel(self, reason: str = "query cancelled") -> None:
        self._reason = reason
        self._cancelled = True

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    @property
    def expired(self) -> bool:
        return self.deadline is not None and \
            time.monotonic() >= self.deadline

    def remaining(self) -> Optional[float]:
        """Seconds until the deadline (>= 0), or None without one."""
        if self.deadline is None:
            return None
        return max(self.deadline - time.monotonic(), 0.0)

    def check(self) -> None:
        if self._cancelled:
            raise YtError(self._reason or "query cancelled",
                          code=EErrorCode.Canceled,
                          attributes={"pool": self.pool}
                          if self.pool else {})
        if self.expired:
            raise YtError(
                "query deadline exceeded",
                code=EErrorCode.DeadlineExceeded,
                attributes={"pool": self.pool} if self.pool else {})


class _PoolState:
    # Plain-int tallies back the per-gateway snapshot (the profiler
    # counters are PROCESS-wide: every gateway shares one registry
    # sensor per (name, pool) tag, which is right for /metrics but
    # wrong for one gateway's view).
    __slots__ = ("name", "weight", "min_share", "limit",
                 "staleness_bound", "fair_share", "in_flight", "waiting",
                 "admitted_n", "rejected_n", "expired_n", "yielded_n",
                 "degraded_n", "admitted", "rejected", "expired",
                 "queue_gauge", "in_flight_gauge", "fair_gauge",
                 "wait_hist", "cond")

    def __init__(self, name: str, config: ServingConfig,
                 profiler: Profiler, serving_profiler: Profiler):
        self.name = name
        self.in_flight = 0
        self.waiting = 0
        self.fair_share = 0.0        # share of config.slots in [0, 1]
        self.admitted_n = 0
        self.rejected_n = 0
        self.expired_n = 0
        self.yielded_n = 0           # admissions that yielded to a
        self.degraded_n = 0          # starving pool before running
        self.reconfigure(config)
        prof = profiler.with_tags(pool=name)
        self.admitted = prof.counter("admitted")
        self.rejected = prof.counter("rejected")
        self.expired = prof.counter("expired")
        self.in_flight_gauge = prof.gauge("in_flight")
        # Fair-share allocation in SLOTS (`serving_admission_fair_slots
        # {pool=}`): what `yt top --by pool` and the SLO bench read to
        # see a storming tenant squeezed back to its share.
        self.fair_gauge = prof.gauge("fair_slots")
        self.wait_hist = prof.histogram("admission_wait_seconds",
                                        bounds=_LATENCY_BOUNDS)
        # ISSUE 6 satellite: the per-pool backlog as a REAL routing
        # signal at the serving root (`serving_queue_depth{pool=}`) —
        # load-aware replica routing (ROADMAP 3) reads it off /metrics
        # instead of reaching into gateway internals.
        self.queue_gauge = serving_profiler.with_tags(
            pool=name).gauge("queue_depth")

    def reconfigure(self, config: ServingConfig) -> None:
        """Pull this pool's spec out of a (possibly freshly merged)
        ServingConfig — the dynamic-resize entry point."""
        pools = config.pools or {}
        self.weight = float(pools.get(self.name, 1.0))
        self.min_share = float(
            (config.min_shares or {}).get(self.name, 0.0))
        self.limit = (config.pool_limits or {}).get(self.name)
        bound = (config.staleness_bounds or {}).get(
            self.name, config.default_staleness_seconds)
        self.staleness_bound = float(bound) if bound else 0.0


# Bounded ring of brown-out rung transitions kept for /serving.
_MAX_TRANSITIONS = 64


class AdmissionController:
    """Fair-share admission over one shared slot budget (ISSUE 17).

    The static per-pool slot table collapsed into scalar progressive
    filling (operations/fair_share.py): every pool carries weight +
    min-share guarantees, `compute_fair_shares` water-fills the live
    demand (in-flight = running, queued waiters = pending), and a freed
    slot goes to the waiting pool FURTHEST below its fair share — a
    waiter of an over-share pool yields (is preempted in the queue) as
    long as an under-share pool starves.  Pools are DYNAMIC: created on
    first config mention, resized live via `apply_config` (the
    DynamicConfigManager subscription), so thousands of tenants can get
    weighted guarantees without a restart.

    A request whose pool already has `max_queue` waiters is rejected
    immediately with a `retry_after` hint estimated from the EWMA slot
    hold time and the backlog ahead of it.

    The controller also owns the BROWN-OUT ladder: the overload signal
    is estimated queue drain time (total waiters x hold EWMA / slots);
    rung 1 degrades reads to bounded-staleness snapshot-cache serves,
    rung 2 sheds new requests with retry_after.  Rungs escalate
    immediately and de-escalate one step at a time behind hysteresis +
    a minimum dwell, so the ladder cannot flap at a threshold."""

    def __init__(self, config: ServingConfig):
        self.config = config
        # One lock, MANY conditions: every pool parks its waiters on its
        # own condition (built over this same lock) so a release can
        # wake exactly the pool the freed slot belongs to instead of
        # broadcasting to every queued request in the process.
        self._lock = threading.RLock()
        # guards: _pools, _hold_ewma, _in_flight_total, _waiting_total, _shares_dirty, _rung, _rung_since, _transitions_log, config
        self._cond = sanitizers.register_condition(
            "serving.AdmissionController._cond",
            threading.Condition(self._lock))
        serving_profiler = Profiler("/serving")
        profiler = serving_profiler.with_prefix("/admission")
        self._profiler = profiler
        self._serving_profiler = serving_profiler
        self._pools: dict[str, _PoolState] = {}
        self._in_flight_total = 0
        self._waiting_total = 0
        self._shares_dirty = True
        for name in (config.pools or {config.default_pool: 1.0}):
            self._ensure_pool_locked(name)
        # EWMA of slot hold time, seeded pessimistically; feeds the
        # retry_after hint so clients back off proportionally to the
        # actual drain rate instead of a blind constant.  Exported as
        # `serving_hold_ewma_seconds` (ISSUE 6 satellite): the routing
        # signal was private to this object, and load-aware replica
        # routing needs it from /metrics.
        self._hold_ewma = 0.05
        self._hold_gauge = serving_profiler.gauge("hold_ewma_seconds")
        self._hold_gauge.set(self._hold_ewma)
        # Brown-out ladder state + sensors (/serving/brownout/*).
        bprof = serving_profiler.with_prefix("/brownout")
        self._rung = 0
        self._rung_since = time.monotonic()
        self._transitions_n = 0
        self._engaged_n = 0
        self._shed_n = 0
        self._transitions_log: list[dict] = []
        self._rung_gauge = bprof.gauge("rung")
        self._transitions_c = bprof.counter("transitions")
        self._degraded_c = bprof.counter("degraded_reads")
        self._shed_c = bprof.counter("shed")
        self._rung_gauge.set(0)

    # -- pools -----------------------------------------------------------------

    def _ensure_pool_locked(self, name: str) -> _PoolState:
        state = self._pools.get(name)
        if state is None:
            state = self._pools[name] = _PoolState(
                name, self.config, self._profiler,
                self._serving_profiler)
            # The pool's private wait queue shares the admission lock
            # (and the lock's sanitizer identity — it IS the same lock).
            state.cond = sanitizers.register_condition(
                "serving.AdmissionController._cond",
                threading.Condition(self._lock))
            self._shares_dirty = True
        return state

    def apply_config(self, config: ServingConfig) -> None:
        """Adopt a new ServingConfig live (DynamicConfigManager
        subscriber): resize the slot budget, re-weight existing pools,
        create newly declared ones.  Pools that vanished from the patch
        keep serving with default weight until their traffic drains —
        deleting live accounting identities mid-flight would orphan
        their in-flight releases."""
        with self._cond:
            self.config = config
            for name in (config.pools or {}):
                self._ensure_pool_locked(name)
            for state in self._pools.values():
                state.reconfigure(config)
            self._shares_dirty = True
            self._update_rung_locked()
            # Waiters re-evaluate against the new shares immediately —
            # a widened budget must not wait for the next release.
            # Config changes move shares arbitrarily, so this is the
            # one place a full broadcast is the right tool.
            for state in self._pools.values():
                state.cond.notify_all()

    def _resolve(self, pool: Optional[str]) -> _PoolState:
        return self._pools.get(pool or self.config.default_pool) or \
            self._pools[self.config.default_pool]

    # -- fair share ------------------------------------------------------------

    def _recompute_locked(self) -> None:
        slots = self.config.slots
        fair = [FairPoolState(name=s.name, weight=s.weight,
                              min_share_ratio=s.min_share,
                              max_running_jobs=s.limit,
                              running=s.in_flight, pending=s.waiting)
                for s in self._pools.values()]
        compute_fair_shares(fair, slots)
        for fp in fair:
            state = self._pools[fp.name]
            state.fair_share = fp.fair_share
            state.fair_gauge.set(fp.fair_share * slots)
        self._shares_dirty = False

    def _pick_locked(self) -> Optional[_PoolState]:
        """The waiting pool to serve next: lowest usage-to-fair-share
        ratio among pools with waiters and headroom (pick_pool
        semantics over the live admission counters)."""
        best = None
        best_ratio = None
        slots = self.config.slots
        for s in self._pools.values():
            if s.waiting <= 0 or s.fair_share <= 0:
                continue
            if s.limit is not None and s.in_flight >= s.limit:
                continue
            ratio = s.in_flight / (s.fair_share * slots)
            if best is None or ratio < best_ratio or \
                    (ratio == best_ratio and s.name < best.name):
                best, best_ratio = s, ratio
        return best

    def _may_run_locked(self, state: _PoolState) -> bool:
        if self._shares_dirty:
            self._recompute_locked()
        slots = self.config.slots
        if self._in_flight_total >= slots:
            return False
        if state.limit is not None and state.in_flight >= state.limit:
            return False
        if state.in_flight + 1 <= state.fair_share * slots + 1e-9:
            return True
        # Running would take the pool OVER its fair share: the slot
        # belongs to the starving pool furthest below its own — this
        # waiter yields (queue preemption).  When no pool is pickable
        # (all fair shares zero — degenerate configs) fall back to
        # first-come service so nobody livelocks.
        best = self._pick_locked()
        return best is None or best is state

    def _notify_waiters_locked(self) -> None:
        """Wake exactly the waiters the free capacity belongs to.

        A single shared condition made every release a thundering herd:
        O(total waiters) threads woke, re-ran the fair-share check, and
        re-slept.  A greedy tenant's thousand-deep queue turned that
        churn into CPU and GIL pressure the innocent neighbor pools
        felt as p99 — the herd itself was a noisy-neighbor channel.
        Each pool now parks on its own condition (over the one
        admission lock) and a freed slot wakes only the picked pool:
        O(pools) per release.  A woken waiter that can no longer run
        (shares shifted under it) re-aims the baton before re-sleeping,
        so a wakeup is never lost while a slot sits free."""
        if self._waiting_total <= 0:
            return
        if self._shares_dirty:
            self._recompute_locked()
        free = self.config.slots - self._in_flight_total
        if free <= 0:
            return
        best = self._pick_locked()
        if best is not None:
            best.cond.notify(min(free, best.waiting))
            return
        # Degenerate configs (every fair share zero): _may_run_locked
        # falls back to first-come service — wake one waiter per pool.
        for s in self._pools.values():
            if s.waiting > 0:
                s.cond.notify(1)

    # -- brown-out ladder ------------------------------------------------------

    def _pressure_locked(self) -> float:
        """Estimated seconds to drain the global backlog: waiters x
        EWMA hold / slots — queue depth and drain rate in one signal."""
        return self._waiting_total * self._hold_ewma / \
            max(self.config.slots, 1)

    def _update_rung_locked(self) -> None:
        cfg = self.config
        now = time.monotonic()
        if not cfg.brownout_enabled:
            self._set_rung_locked(0, now)
            return
        pressure = self._pressure_locked()
        target = 2 if pressure >= cfg.brownout_rung2_seconds else \
            1 if pressure >= cfg.brownout_rung1_seconds else 0
        if target > self._rung:
            self._set_rung_locked(target, now)      # escalate NOW
        elif self._rung > 0:
            threshold = (cfg.brownout_rung2_seconds if self._rung == 2
                         else cfg.brownout_rung1_seconds)
            if pressure < threshold * cfg.brownout_hysteresis and \
                    now - self._rung_since >= \
                    cfg.brownout_min_dwell_seconds:
                self._set_rung_locked(self._rung - 1, now)  # one step
        self._rung_gauge.set(self._rung)

    def _set_rung_locked(self, rung: int, now: float) -> None:
        if rung == self._rung:
            return
        if self._rung == 0 and rung > 0:
            self._engaged_n += 1
        self._transitions_n += 1
        self._transitions_c.increment()
        self._transitions_log.append({
            "at": time.time(), "from": self._rung, "to": rung,
            "pressure": round(self._pressure_locked(), 4)})
        del self._transitions_log[:-_MAX_TRANSITIONS]
        self._rung, self._rung_since = rung, now
        self._rung_gauge.set(rung)

    @property
    def rung(self) -> int:
        with self._cond:
            return self._rung

    def degradation(self, state: _PoolState) -> tuple[int,
                                                      Optional[float]]:
        """The degradation this ADMITTED request must apply: (active
        rung, the pool's staleness bound when rung >= 1 and the pool
        opted in).  Hits the `serving.brownout` failpoint whenever a
        degraded decision is being made."""
        with self._cond:
            rung = self._rung
            bound = state.staleness_bound
        if rung >= 1:
            _FP_BROWNOUT.hit()
            if bound and bound > 0:
                return rung, bound
        return rung, None

    def observe_degraded(self, state: _PoolState,
                         staleness: float) -> None:
        """Tally one response actually served degraded (tagged)."""
        with self._cond:
            state.degraded_n += 1
        self._degraded_c.increment()

    # -- admission -------------------------------------------------------------

    def _retry_after(self, state: _PoolState) -> float:
        backlog = state.waiting + state.in_flight
        fair_slots = max(state.fair_share * self.config.slots, 1.0)
        hint = self._hold_ewma * max(backlog, 1) / fair_slots
        return round(min(max(hint, 0.01), 5.0), 4)

    def admit(self, token: CancellationToken,
              pool: Optional[str] = None) -> _PoolState:
        _FP_ADMIT.hit()
        t0 = time.monotonic()
        with self._cond:
            state = self._resolve(pool)
            self._update_rung_locked()
            if self._rung >= 2:
                # Rung 2: the ladder's last step sheds NEW load at the
                # door so queued + in-flight work can drain.
                self._shed_n += 1
                self._shed_c.increment()
                state.rejected_n += 1
                state.rejected.increment()
                get_accountant().observe_throttle(state.name, token.user)
                raise ThrottledError(
                    f"serving brown-out rung 2: shedding load "
                    f"(pool {state.name!r})",
                    retry_after=self._retry_after(state),
                    attributes={"pool": state.name, "brownout_rung": 2})
            state.waiting += 1
            self._waiting_total += 1
            self._shares_dirty = True
            state.queue_gauge.set(state.waiting)
            yielded = False
            try:
                if not self._may_run_locked(state) and \
                        state.waiting > self.config.max_queue:
                    state.rejected_n += 1
                    state.rejected.increment()
                    get_accountant().observe_throttle(state.name,
                                                      token.user)
                    raise ThrottledError(
                        f"serving pool {state.name!r} is saturated "
                        f"(fair share "
                        f"{state.fair_share * self.config.slots:.1f} "
                        f"slots, {state.waiting - 1} queued)",
                        retry_after=self._retry_after(state),
                        attributes={"pool": state.name})
                while not self._may_run_locked(state):
                    if self._in_flight_total < self.config.slots and \
                            (state.limit is None or
                             state.in_flight < state.limit):
                        # A slot is FREE but belongs to a starving
                        # pool: this waiter is being queue-preempted.
                        # Pass the baton to that pool before sleeping —
                        # if this thread consumed the release's wakeup,
                        # the rightful waiter must not sleep through
                        # its free slot.
                        yielded = True
                        self._notify_waiters_locked()
                    if not state.cond.wait(timeout=token.remaining()):
                        # Deadline lapsed while queued: the request
                        # expires without ever consuming a slot.  Any
                        # wakeup racing the timeout is re-aimed so it
                        # doesn't die with this waiter.
                        state.expired_n += 1
                        state.expired.increment()
                        self._notify_waiters_locked()
                        raise YtError(
                            f"deadline exceeded while queued in serving "
                            f"pool {state.name!r}",
                            code=EErrorCode.DeadlineExceeded,
                            attributes={"pool": state.name})
                state.in_flight += 1
                self._in_flight_total += 1
                self._shares_dirty = True
                if self._in_flight_total < self.config.slots:
                    # Still free capacity after this admission (a grown
                    # budget, or a release that freed several at once):
                    # forward the baton — the release-time notify only
                    # aimed at ONE pool's waiters.
                    self._notify_waiters_locked()
            finally:
                state.waiting -= 1
                self._waiting_total -= 1
                self._shares_dirty = True
                state.queue_gauge.set(state.waiting)
            if yielded:
                state.yielded_n += 1
            state.admitted_n += 1
            state.admitted.increment()
            state.in_flight_gauge.set(state.in_flight)
        state.wait_hist.record(time.monotonic() - t0)
        return state

    def release(self, state: _PoolState, held_seconds: float) -> None:
        with self._cond:
            state.in_flight -= 1
            self._in_flight_total -= 1
            self._shares_dirty = True
            state.in_flight_gauge.set(state.in_flight)
            self._hold_ewma += 0.2 * (held_seconds - self._hold_ewma)
            self._hold_gauge.set(self._hold_ewma)
            self._update_rung_locked()
            # Targeted, NOT notify_all: wake only the pool the freed
            # slot belongs to (waiters of other pools stay parked on
            # their own conditions).
            self._notify_waiters_locked()

    def snapshot(self) -> dict:
        with self._cond:
            if self._shares_dirty:
                self._recompute_locked()
            # Rung re-evaluation on read: a gateway whose storm just
            # drained must DISENGAGE even if no new request arrives to
            # drive admit()/release() — monitoring scrapes are the
            # heartbeat that walks the ladder back down.
            self._update_rung_locked()
            slots = self.config.slots
            return {
                "slots": slots,
                "hold_ewma": round(self._hold_ewma, 6),
                "brownout": {
                    "rung": self._rung,
                    "pressure": round(self._pressure_locked(), 4),
                    "engaged": self._engaged_n,
                    "transitions": self._transitions_n,
                    "shed": self._shed_n,
                    "log": list(self._transitions_log),
                },
                "pools": {
                    name: {"weight": s.weight,
                           "min_share": s.min_share,
                           "limit": s.limit,
                           "fair_share": round(s.fair_share, 4),
                           "fair_slots": round(s.fair_share * slots, 2),
                           "staleness_bound": s.staleness_bound,
                           "in_flight": s.in_flight,
                           "waiting": s.waiting,
                           "demand": s.in_flight + s.waiting,
                           "admitted": s.admitted_n,
                           "rejected": s.rejected_n,
                           "expired": s.expired_n,
                           "yielded": s.yielded_n,
                           "degraded": s.degraded_n}
                    for name, s in sorted(self._pools.items())},
            }


class _PathContext:
    """Cached lookup context for one mounted table: tablet list, key
    normalization types, and normalized routing pivots — the per-request
    tree resolve + per-call pivot renormalization of the generic path
    (client._route_rows) is pure overhead at point-lookup rates.
    Freshness is an identity check: a remount replaces the cluster's
    tablet list object, which invalidates the context."""

    __slots__ = ("node_id", "tablets", "schema", "normalize",
                 "safe_pivots", "has_computed")

    def __init__(self, node_id, tablets, schema):
        from ytsaurus_tpu.tablet.dynamic_store import _null_safe
        self.node_id = node_id
        self.tablets = tablets
        self.schema = schema
        # THE key canonicalizer — one implementation (the tablet's,
        # which caches its key columns) so batched and direct lookups
        # can never disagree on result-map keys.
        self.normalize = tablets[0].normalize_key
        self.has_computed = any(c.expression for c in schema.key_columns)
        self.safe_pivots = [
            _null_safe(self.normalize(tuple(t.pivot_key)))
            for t in tablets[1:]]

    def route(self, nkeys) -> "dict[int, list]":
        """Normalized keys → owning tablet index (pivot bisect)."""
        import bisect

        from ytsaurus_tpu.tablet.dynamic_store import _null_safe
        if not self.safe_pivots:
            return {0: list(nkeys)}
        out: dict[int, list] = {}
        for nk in nkeys:
            idx = bisect.bisect_right(self.safe_pivots, _null_safe(nk))
            out.setdefault(idx, []).append(nk)
        return out


class _Batch:
    """One micro-batch: the key lists of every joined request plus the
    shared completion state.  Waiters block on `done` and scatter from
    `results` through their OWN normalized-key order, so one event wakes
    the whole cohort at once (per-entry futures would wake them one by
    one).

    `deadline` is the COHORT maximum (None once any member has no
    deadline): the flush runs on behalf of every member, so one
    short-deadline caller must not fail co-batched callers with budget
    left — members whose own deadline lapses time out individually in
    `lookup()`.  `pool` is the first member's pool (admission is one
    slot per flush; mixed-pool cohorts charge the pool that opened the
    batch).  `trace` captures the OPENING member's trace context so the
    flusher thread (which has no ambient context of its own) can parent
    its batch-flush span into that caller's trace."""

    __slots__ = ("key_lists", "users", "deadline", "pool", "user",
                 "client", "created", "done", "results", "error",
                 "trace")

    def __init__(self, token: CancellationToken, client):
        self.key_lists: list = []       # list[list[nkey]] per request
        self.users: list = []           # requesting user, per request
        self.deadline = token.deadline
        self.pool = token.pool
        self.user = token.user
        self.client = client
        self.created = time.monotonic()
        self.done = threading.Event()
        self.results: dict = {}
        self.error: Optional[BaseException] = None
        self.trace = current_trace()

    def join(self, token: CancellationToken) -> None:
        if self.deadline is not None:
            self.deadline = None if token.deadline is None \
                else max(self.deadline, token.deadline)

    def flush_token(self) -> CancellationToken:
        return CancellationToken(self.deadline, pool=self.pool,
                                 user=self.user)


class LookupBatcher:
    """Continuous micro-batching of point lookups (lookup sessions).

    Requests enqueue their normalized keys into the pending batch for
    their (table, timestamp) and block on the batch's completion event;
    a dedicated FLUSHER thread per gateway drains pending batches in a
    loop: it waits for work, lets the arriving cohort accumulate until
    the batch stops growing across one poll (bounded by
    `flush_window_ms`), then takes every pending batch and executes
    each as ONE admitted, batched read — keys deduplicated, padded to a
    power-of-two bucket, fanned out per tablet in parallel — and wakes
    the whole cohort with one event.  The explicit accumulation matters
    under the GIL: compute-bound requests barely overlap on their own,
    so without it every request would flush alone and amortize nothing.
    `max_batch_size` caps the keys per tablet read (bigger unions are
    read in slices inside the same flush).

    Responses are never lost, duplicated, or misordered regardless of
    how requests interleave: a batch resolves exactly once (rows or the
    flush's error) and each caller scatters from the shared result map
    through its OWN request-order key list."""

    # Growth-stability poll while a cohort accumulates; the sleep is
    # the yield that lets cohort threads actually enqueue.
    _POLL_SECONDS = 0.0002

    def __init__(self, config: ServingConfig, admission:
                 AdmissionController, executor: ThreadPoolExecutor):
        self.config = config
        self.admission = admission
        self._executor = executor
        # Flushes run on their own small pool, SEPARATE from the
        # per-tablet read executor: flushes submit reads to `executor`
        # and wait, so sharing one pool could fill every worker with
        # flushes waiting on reads that can never start.
        self._flush_executor = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="serving-flush")
        # guards: _batches, _contexts, _flusher, requests_n, batches_n, batched_keys_n
        self._cond = sanitizers.register_condition(
            "serving.LookupBatcher._cond")
        self._batches: "dict[tuple, _Batch]" = {}
        self._contexts: dict[str, _PathContext] = {}
        self._flusher: Optional[threading.Thread] = None
        # Instance tallies for snapshot(); profiler counters mirror
        # them process-wide for /metrics.
        self.requests_n = 0
        self.batches_n = 0
        self.batched_keys_n = 0
        prof = Profiler("/serving/lookup")
        self.requests = prof.counter("requests")
        self.batches = prof.counter("batches")
        self.batched_keys = prof.counter("batched_keys")
        self.batch_size_hist = prof.histogram("batch_size",
                                              bounds=_BATCH_BOUNDS)
        self.latency_hist = prof.histogram("latency_seconds",
                                           bounds=_LATENCY_BOUNDS)

    def _context(self, client, path: str) -> _PathContext:
        # The memo is shared by caller threads and the flusher: reads
        # and writes both go under the cond (the lock pass flagged the
        # bare-dict mutation; a clear() racing a get could hand a
        # half-installed context to a flush).
        with self._cond:
            ctx = self._contexts.get(path)
        if ctx is not None and \
                client.cluster.tablets.get(ctx.node_id) is ctx.tablets:
            return ctx
        tablets = client._mounted_tablets(path)
        client._require_sorted(tablets[0], path)
        node = client._table_node(path)
        ctx = _PathContext(node.id, tablets, tablets[0].schema)
        for tablet in tablets:
            # Shape-bucketing floor for the tablets' batched chunk
            # probes (tablet._pad_needles pow2 buckets).
            tablet.probe_bucket_min = self.config.min_bucket
        with self._cond:
            if len(self._contexts) > 256:
                self._contexts.clear()
            self._contexts[path] = ctx
        return ctx

    def lookup(self, client, path: str, keys: Sequence[tuple],
               timestamp: int, column_names, token: CancellationToken,
               pool: Optional[str] = None):
        with child_span("serving.lookup", table=path, keys=len(keys)):
            return self._lookup_traced(client, path, keys, timestamp,
                                       column_names, token, pool)

    def _lookup_traced(self, client, path: str, keys: Sequence[tuple],
                       timestamp: int, column_names,
                       token: CancellationToken,
                       pool: Optional[str] = None):
        t0 = time.monotonic()
        ctx = self._context(client, path)
        if ctx.has_computed:
            keys = client._fill_computed_keys(
                ctx.schema, [tuple(k) for k in keys])
        nkeys = [ctx.normalize(tuple(k)) for k in keys]
        bkey = (path, timestamp)
        with self._cond:
            # Tally under the cond with the enqueue (the lock pass
            # flagged the bare `+= 1`: two racing requests could lose
            # an increment and snapshot() would under-report).  The
            # profiler mirror increments HERE too, so the /metrics
            # sensor and snapshot() count the same events — a request
            # that fails context resolution above counts in neither.
            self.requests_n += 1
            batch = self._batches.get(bkey)
            if batch is None:
                batch = self._batches[bkey] = _Batch(token, client)
            else:
                batch.join(token)
            batch.key_lists.append(nkeys)
            batch.users.append(token.user)
            self.requests.increment()
            if self._flusher is None or not self._flusher.is_alive():
                self._flusher = threading.Thread(
                    target=self._flusher_loop, daemon=True,
                    name="serving-flusher")
                self._flusher.start()
            self._cond.notify()
        if not batch.done.wait(timeout=token.remaining()):
            raise YtError(
                "deadline exceeded waiting for the lookup batch",
                code=EErrorCode.DeadlineExceeded,
                attributes={"table": path})
        if batch.error is not None:
            raise batch.error
        results = batch.results
        out = []
        for nk in nkeys:
            row = results.get(nk)
            if row is not None:
                # Copy per caller: one merged row may serve several
                # concurrent requests, and callers may mutate.
                row = {name: row.get(name) for name in column_names} \
                    if column_names is not None else dict(row)
            out.append(row)
        self.latency_hist.record(time.monotonic() - t0)
        return out

    # -- the flusher thread ----------------------------------------------------

    # Idle flusher threads exit (lookup() restarts them on demand) so
    # processes juggling many short-lived clusters don't accumulate
    # parked threads.
    _IDLE_EXIT_SECONDS = 30.0

    def _flusher_loop(self) -> None:
        while True:
            with self._cond:
                while not self._batches:
                    if not self._cond.wait(
                            timeout=self._IDLE_EXIT_SECONDS) \
                            and not self._batches:
                        self._flusher = None
                        return
            self._accumulate()
            with self._cond:
                taken, self._batches = self._batches, {}
            for (path, timestamp), batch in taken.items():
                # Dispatch, don't run inline: a flush can park inside
                # admission when its pool is saturated, and an inline
                # flush would head-of-line-block every other table's
                # batches behind it.  (_flush relays any failure —
                # including InjectedCrash — to its cohort itself.)
                self._flush_executor.submit(self._flush, path,
                                            timestamp, batch)

    def _accumulate(self) -> None:
        """Let the arriving cohort join: poll until no pending batch
        grew across one interval, capped by flush_window_ms (and cut
        short once any batch holds max_batch_size keys)."""
        window = self.config.flush_window_ms / 1000.0
        if window <= 0:
            return
        deadline = time.monotonic() + window
        prev = -1
        while time.monotonic() < deadline:
            with self._cond:
                n = sum(len(b.key_lists) for b in self._batches.values())
                full = any(
                    sum(len(ks) for ks in b.key_lists) >=
                    self.config.max_batch_size
                    for b in self._batches.values())
            if n == prev or full:
                return
            prev = n
            time.sleep(self._POLL_SECONDS)

    # -- batch execution -------------------------------------------------------

    def _flush(self, path, timestamp, batch: _Batch) -> None:
        token = batch.flush_token()      # cohort-max deadline
        # Parent the flush span into the OPENING caller's trace (the
        # flusher thread has no ambient context): the cohort members see
        # one shared batch-flush child under the first joiner.
        parent = batch.trace
        span = parent.create_child("serving.batch_flush") \
            if parent is not None and parent.sampled else NULL_SPAN
        span.add_tag("table", path)
        span.add_tag("cohort", len(batch.key_lists))
        with span:
            try:
                with child_span("serving.admission", pool=batch.pool):
                    state = self.admission.admit(token, batch.pool)
            except BaseException as exc:
                self._fail(batch, exc)
                return
            t0 = time.monotonic()
            try:
                self._flush_admitted(path, timestamp, batch, token, span)
            except BaseException as exc:  # noqa: BLE001 — relayed to
                # waiters
                self._fail(batch, exc)
                if not isinstance(exc, Exception):
                    raise      # InjectedCrash still pierces this flush
            finally:
                self.admission.release(state, time.monotonic() - t0)

    def _flush_admitted(self, path, timestamp, batch: _Batch, token,
                        span) -> None:
        _FP_BATCH_FLUSH.hit()
        token.check()
        client = batch.client
        ctx = self._context(client, path)
        # Union of the batch's keys, deduplicated (two callers
        # asking for the same row share one read); normalized keys
        # ARE canonical keys, so they feed the tablets directly.
        union = dict.fromkeys(
            nk for ks in batch.key_lists for nk in ks)
        span.add_tag("keys", len(union))
        with self._cond:
            # Concurrent flushes race these tallies (4-worker flush
            # pool); the profiler counters already lock internally.
            self.batches_n += 1
            self.batched_keys_n += len(union)
        self.batches.increment()
        self.batched_keys.increment(len(union))
        self.batch_size_hist.record(len(union))
        results: dict[tuple, Optional[dict]] = {}
        pool = batch.pool or self.config.default_pool
        items = list(ctx.route(union).items())
        if len(items) > 1 and len(union) >= 32:
            # Parallel per-tablet fan-out (the sequential per-tablet
            # loop was the pre-gateway bottleneck, client.py:1136);
            # small batches stay inline — dispatch overhead would
            # exceed the read.  Each future carries an explicit
            # contextvars copy: executor threads have no ambient trace,
            # and the tablet-read spans must link under this flush.
            import contextvars as _cv
            futures = [
                self._executor.submit(_cv.copy_context().run,
                                      self._read_tablet,
                                      ctx.tablets, idx, part,
                                      timestamp, pool)
                for idx, part in items]
            for fut in futures:
                results.update(fut.result())
        else:
            for idx, part in items:
                results.update(self._read_tablet(
                    ctx.tablets, idx, part, timestamp, pool))
        # Fold into per-tenant accounting before waking the waiters:
        # the FLUSH is one `lookup_batches` unit under the cohort
        # opener's identity (it maps 1:1 onto the admission slot the
        # flush held — the per-pool reconciliation unit), while each
        # member request's keys/rows charge ITS OWN user, so a cohort
        # of mixed tenants doesn't bill everything to whoever opened
        # the batch window.
        accountant = get_accountant()
        accountant.observe_lookup_batch(pool, batch.user)
        for nkeys, user in zip(batch.key_lists, batch.users):
            distinct = dict.fromkeys(nkeys)
            accountant.observe_lookup(
                pool, user, keys=len(distinct),
                rows_found=sum(1 for nk in distinct
                               if results.get(nk) is not None))
        batch.results = results
        batch.done.set()

    def _read_tablet(self, tablets, idx: int, part: list,
                     timestamp: int, pool: Optional[str] = None) -> dict:
        """One tablet's slice of the batch, capped at max_batch_size
        keys per read; the tablet's batched chunk probe buckets its
        needle shapes to powers of two (min_bucket)."""
        out: dict = {}
        cap = self.config.max_batch_size
        for lo in range(0, len(part), cap):
            piece = part[lo:lo + cap]
            rows = tablets[idx].lookup_rows(piece, timestamp=timestamp,
                                            normalized=True, pool=pool)
            out.update(zip(piece, rows))
        return out

    @staticmethod
    def _fail(batch: _Batch, exc: BaseException) -> None:
        batch.error = exc
        batch.done.set()

    def snapshot(self) -> dict:
        return {"requests": self.requests_n,
                "batches": self.batches_n,
                "batched_keys": self.batched_keys_n}

# Live gateways of this process (the monitoring /serving endpoint).
_GATEWAYS: "weakref.WeakSet" = weakref.WeakSet()


class QueryGateway:
    """The serving-plane facade every query entry point routes through.

    `run_select(fn)` admits, mints a CancellationToken, calls
    `fn(token)`, and releases; `lookup_rows(...)` goes through the
    micro-batcher (which admits per batch flush).  One gateway per
    YtCluster so concurrent clients of one cluster share slots and
    coalesce lookups."""

    def __init__(self, config: Optional[ServingConfig] = None):
        self.config = config or ServingConfig()
        self.admission = AdmissionController(self.config)
        self._executor = ThreadPoolExecutor(
            max_workers=max(self.config.max_tablet_fanout, 1),
            thread_name_prefix="serving")
        self.batcher = LookupBatcher(self.config, self.admission,
                                     self._executor)
        from ytsaurus_tpu.query.vector import NearestBatcher
        self.nearest_batcher = NearestBatcher(self.config, self.admission)
        prof = Profiler("/serving")
        self.select_latency = prof.histogram("select_latency_seconds",
                                             bounds=_LATENCY_BOUNDS)
        self._stat_profiler = Profiler("/serving/query_stats")
        self._cache_gauge = prof.gauge("evaluator_cache_size")
        _GATEWAYS.add(self)

    @property
    def enabled(self) -> bool:
        return bool(self.config.enabled)

    def resolve_pool(self, pool: Optional[str]) -> str:
        """The admission-resolved pool name (None/unknown pools land on
        the default pool's slots) — the ONE identity admission counters,
        per-pool sensors, and accounting must share, or per-pool
        reconciliation splits between a requested and an admitted name."""
        return self.admission._resolve(pool).name

    def make_token(self, timeout: Optional[float],
                   pool: Optional[str] = None) -> CancellationToken:
        if timeout is None:
            timeout = self.config.default_timeout or None
        # Identity rides the token: the ADMISSION-RESOLVED pool plus the
        # ambient authenticated principal (RPC/HTTP entry points restore
        # it per request — cypress/security.authenticated_user).
        return CancellationToken.with_timeout(
            timeout, pool=self.resolve_pool(pool),
            user=current_user())

    # -- selects ---------------------------------------------------------------

    def run_select(self, fn: Callable[[Optional[CancellationToken]],
                                      object],
                   pool: Optional[str] = None,
                   timeout: Optional[float] = None):
        if not self.enabled:
            return fn(None)
        token = self.make_token(timeout, pool)
        # The admission wait is its own span: a query that queued 40ms
        # behind a saturated pool must show that 40ms as admission, not
        # as mystery execution time.  The wait is ALSO stamped as a tag
        # on the ambient root so ExecutionProfile.capture reads it with
        # a dict probe instead of scanning the span ring.
        t_admit = time.monotonic()
        with child_span("serving.admission",
                        pool=pool or self.config.default_pool):
            state = self.admission.admit(token, pool)
        root = current_trace()
        if root is not None:
            root.add_tag("admission_wait_s",
                         round(time.monotonic() - t_admit, 6))
        # Brown-out rung 1 (ISSUE 17): the pool's declared staleness
        # bound rides the token down to the tablet read path, which
        # serves the snapshot cache within the bound and writes back
        # what it actually served.  A failure INSIDE the degradation
        # decision (the `serving.brownout` failpoint's injection) falls
        # back to full-fidelity execution: broken brown-out machinery
        # must never take down a query that already holds a slot.
        try:
            rung, bound = self.admission.degradation(state)
        except YtError:
            rung, bound = 0, None
        if rung >= 1 and bound is not None:
            token.rung = rung
            token.staleness_bound = bound
        t0 = time.monotonic()
        try:
            return fn(token)
        finally:
            held = time.monotonic() - t0
            self.admission.release(state, held)
            self.select_latency.record(held)
            if token.rung >= 1:
                # Tag the degraded response where observability reads
                # it: the root span and the per-pool degraded tally.
                self.admission.observe_degraded(state, token.stale_served)
                if root is not None:
                    root.add_tag("brownout_rung", token.rung)
                    root.add_tag("stale_served_s",
                                 round(token.stale_served, 4))

    # -- lookups ---------------------------------------------------------------

    def lookup_rows(self, client, path: str, keys: Sequence[tuple],
                    timestamp: int, column_names=None,
                    pool: Optional[str] = None,
                    timeout: Optional[float] = None):
        token = self.make_token(timeout, pool)
        # Workload recorder fold (ISSUE 8): each admitted lookup is one
        # compact record (table + key tuples + outcome + wall) in the
        # bounded workload log — the replay harness re-runs them.
        from ytsaurus_tpu.query.workload import (
            get_workload_log,
            outcome_of,
        )
        t0 = time.monotonic()
        try:
            out = self.batcher.lookup(client, path, keys, timestamp,
                                      column_names, token, pool=pool)
        except YtError as err:
            get_workload_log().observe_lookup(
                path, keys, outcome=outcome_of(err),
                wall_time=time.monotonic() - t0, pool=token.pool,
                user=token.user)
            raise
        get_workload_log().observe_lookup(
            path, keys, outcome="ok", wall_time=time.monotonic() - t0,
            pool=token.pool, user=token.user)
        return out

    # -- vector search ---------------------------------------------------------

    def nearest_rows(self, client, path: str, column: str,
                     query_vector, k: int, metric: str = "l2",
                     timestamp: Optional[int] = None,
                     pool: Optional[str] = None,
                     timeout: Optional[float] = None):
        """Serve one NEAREST query through the vector micro-batcher:
        co-admitted queries on (path, column, metric, timestamp)
        coalesce into ONE batched distance matmul (query/vector.py)."""
        token = self.make_token(timeout, pool)
        if timestamp is None:
            from ytsaurus_tpu.tablet.timestamp import MAX_TIMESTAMP
            timestamp = MAX_TIMESTAMP
        return self.nearest_batcher.nearest(
            client, path, column, query_vector, k, metric, timestamp,
            token)

    # -- observability ---------------------------------------------------------

    def record_statistics(self, stats,
                          cache_size: Optional[int] = None) -> None:
        """Fold one query's TQueryStatistics into the cumulative serving
        counters (`serving_query_stats_* ` on /metrics).  Only numeric
        fields fold — capacity_buckets is a per-query set, not a
        counter."""
        for field, value in stats.to_dict().items():
            if isinstance(value, (int, float)) \
                    and not isinstance(value, bool) and value:
                self._stat_profiler.counter(field).increment(value)
        if cache_size is not None:
            self._cache_gauge.set(cache_size)

    def apply_config(self, config: ServingConfig) -> None:
        """Adopt a merged ServingConfig live: resize/re-weight admission
        pools, and let the batchers pick up the new windows (they read
        `self.config` per flush)."""
        self.config = config
        self.batcher.config = config
        self.nearest_batcher.config = config
        self.admission.apply_config(config)

    def attach_dynamic_config(self, manager) -> None:
        """Subscribe this gateway to a config.DynamicConfigManager whose
        merged config is (or carries) a ServingConfig — the dynamic
        pool create/resize path (ISSUE 17)."""
        def _on_update(cfg):
            serving = getattr(cfg, "serving", cfg)
            if isinstance(serving, ServingConfig):
                self.apply_config(serving)
        manager.subscribe(_on_update)

    def snapshot(self) -> dict:
        admission = self.admission.snapshot()
        return {"enabled": self.enabled,
                "admission": admission,
                "pools": admission["pools"],
                "lookup": self.batcher.snapshot(),
                "nearest": self.nearest_batcher.snapshot()}


def serving_snapshot() -> list:
    """Snapshots of every live gateway in this process (monitoring)."""
    return [g.snapshot() for g in list(_GATEWAYS)]
