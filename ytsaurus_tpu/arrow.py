"""Arrow interop: columnar chunks ↔ Arrow IPC streams.

Ref mapping (yt/yt/client/arrow):
  arrow_row_stream_encoder.h   → chunk_to_arrow / chunks_to_arrow_ipc
  arrow_row_stream_decoder     → arrow_ipc_to_rows / arrow_to_chunk
  dictionary-encoded string    → pa.DictionaryArray straight from the
  columns (the encoder's           int32 code plane + host vocabulary —
  dictionary batches)              the columnar planes ARE the arrow
                                   layout, so conversion is zero-copy for
                                   numeric planes

Design delta: the reference encodes row batches into arrow inside a stream
encoder; here the table already lives as device column planes + validity
masks, which map 1:1 onto arrow arrays (values + null bitmap), so the
conversion is a per-column buffer handoff, not a row walk.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.schema import EValueType, TableSchema, VectorType

_ARROW_TYPES = {
    EValueType.int64: "int64",
    EValueType.uint64: "uint64",
    EValueType.double: "float64",
    EValueType.boolean: "bool_",
}


def _pa():
    try:
        import pyarrow
        return pyarrow
    except ImportError as err:        # pragma: no cover - baked into image
        raise YtError("pyarrow is not available",
                      code=EErrorCode.QueryUnsupported) from err


def chunk_to_arrow(chunk) -> "pyarrow.Table":
    """One ColumnarChunk → pa.Table (numeric planes zero-copy via numpy;
    string columns as dictionary arrays over the host vocabulary)."""
    pa = _pa()
    n = chunk.row_count
    arrays, fields = [], []
    for col_schema in chunk.schema:
        name = col_schema.name
        col = chunk.columns[name]
        valid = np.asarray(col.valid[:n])
        mask = ~valid
        if isinstance(col_schema.type, VectorType):
            # (rows, dim) float32 plane → FixedSizeListArray(float32, dim):
            # the flat child buffer IS the plane, row-major.
            dim = col_schema.type.dim
            flat = np.ascontiguousarray(
                np.asarray(col.data[:n], dtype=np.float32)).reshape(-1)
            arr = pa.FixedSizeListArray.from_arrays(
                pa.array(flat, type=pa.float32()), dim)
            if mask.any():
                # from_arrays carries no validity — rebuild with nulls.
                arr = pa.array(
                    [None if mask[i] else
                     [float(x) for x in np.asarray(col.data[i])]
                     for i in range(n)],
                    type=pa.list_(pa.float32(), dim))
        elif col_schema.type in _ARROW_TYPES:
            data = np.asarray(col.data[:n])
            arr = pa.array(data, mask=mask,
                           type=getattr(pa, _ARROW_TYPES[col_schema.type])())
        elif col_schema.type is EValueType.string:
            codes = np.asarray(col.data[:n]).astype(np.int32)
            vocab = [bytes(v) for v in (col.dictionary if col.dictionary
                                        is not None else [])]
            # Null slots must carry a valid index for DictionaryArray.
            safe = np.where(mask, 0, codes) if len(vocab) else codes
            arr = pa.DictionaryArray.from_arrays(
                pa.array(safe, mask=mask, type=pa.int32()),
                pa.array(vocab, type=pa.binary()))
        elif col_schema.type is EValueType.any:
            values = [None if not valid[i] else (col.host_values or [])[i]
                      for i in range(n)]
            arr = pa.array([None if v is None else _any_to_arrow(v)
                            for v in values], type=pa.string())
        elif col_schema.type is EValueType.null:
            arr = pa.nulls(n)
        else:
            raise YtError(f"Cannot encode {col_schema.type} as arrow",
                          code=EErrorCode.QueryUnsupported)
        arrays.append(arr)
        fields.append(pa.field(name, arr.type))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def _any_to_arrow(value) -> str:
    from ytsaurus_tpu import yson
    return yson.dumps(value).decode("utf-8", "replace")


def chunks_to_arrow_ipc(chunks: Sequence) -> bytes:
    """Arrow IPC stream bytes (the read_table format='arrow' payload)."""
    pa = _pa()
    tables = [chunk_to_arrow(c) for c in chunks]
    table = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    return sink.getvalue().to_pybytes()


def arrow_ipc_to_rows(blob: bytes) -> list[dict]:
    """Arrow IPC stream → host rows (the write_table format='arrow' path).
    Binary/string columns come back as bytes, matching chunk decode."""
    pa = _pa()
    with pa.ipc.open_stream(blob) as reader:
        table = reader.read_all()
    rows: list[dict] = [dict() for _ in range(table.num_rows)]
    for name in table.column_names:
        column = table.column(name)
        for i, value in enumerate(column.to_pylist()):
            if isinstance(value, str):
                value = value.encode()
            rows[i][name] = value
    return rows


def arrow_schema_to_table_schema(arrow_schema) -> TableSchema:
    pa = _pa()
    cols = []
    for field in arrow_schema:
        t = field.type
        if pa.types.is_dictionary(t):
            t = t.value_type
        if pa.types.is_fixed_size_list(t) and \
                pa.types.is_floating(t.value_type):
            cols.append((field.name, f"vector<float, {t.list_size}>"))
            continue
        if pa.types.is_integer(t):
            ty = "uint64" if pa.types.is_unsigned_integer(t) else "int64"
        elif pa.types.is_floating(t):
            ty = "double"
        elif pa.types.is_boolean(t):
            ty = "boolean"
        elif pa.types.is_binary(t) or pa.types.is_string(t) or \
                pa.types.is_large_binary(t) or pa.types.is_large_string(t):
            ty = "string"
        else:
            raise YtError(f"Unsupported arrow type {t} for {field.name!r}",
                          code=EErrorCode.QueryUnsupported)
        cols.append((field.name, ty))
    return TableSchema.make(cols, strict=True)
