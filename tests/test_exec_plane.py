"""Distributed exec plane: command jobs run in exec-node slots on data
nodes (ref server/node/exec_node/ + job_proxy/user_job.cpp), reading
input chunks local-first, surviving node death via revival.
"""

import threading
import time

import pytest

from ytsaurus_tpu.environment import LocalCluster
from ytsaurus_tpu.remote_client import connect_remote
from ytsaurus_tpu.rpc import Channel


def _exec_stats(address: str) -> dict:
    channel = Channel(address, timeout=10)
    try:
        body, _ = channel.call("exec_node", "exec_stats", {})
        return body
    finally:
        channel.close()


def test_map_command_jobs_run_on_data_nodes(tmp_path):
    with LocalCluster(str(tmp_path / "c"), n_nodes=2) as cluster:
        client = connect_remote(cluster.primary_address)
        rows = [{"a": i, "b": i * 2} for i in range(400)]
        client.create("map_node", "//home", recursive=True)
        client.write_table("//home/in", rows)
        op = client.run_map("cat", "//home/in", "//home/out",
                            rows_per_job=100)
        assert op.result["rows"] == 400
        out = sorted(client.read_table("//home/out"),
                     key=lambda r: r["a"])
        assert out == rows
        # The jobs observably ran ON THE NODES, spread over both.
        stats = [_exec_stats(a) for a in cluster.node_addresses]
        started = [s["started_total"] for s in stats]
        assert sum(started) >= 4, stats
        assert sum(1 for s in started if s > 0) >= 2, stats


@pytest.mark.slow   # ~16s; tier-1 keeps node-death revival coverage via
# test_scheduler_daemon::test_kill9_mid_operation_revives_and_completes and
# exec-plane E2E via test_map_command_jobs_run_on_data_nodes.
def test_node_kill_mid_operation_revives_jobs(tmp_path):
    with LocalCluster(str(tmp_path / "c"), n_nodes=3,
                      replication_factor=2) as cluster:
        client = connect_remote(cluster.primary_address)
        rows = [{"a": i} for i in range(300)]
        client.create("map_node", "//home", recursive=True)
        client.write_table("//home/in", rows)

        result: dict = {}
        errors: list = []

        def run():
            try:
                op = client.run_map(
                    "sleep 2; cat", "//home/in", "//home/out",
                    rows_per_job=100)
                result.update(op.result)
            except Exception as exc:   # noqa: BLE001 - surface in assert
                errors.append(exc)

        thread = threading.Thread(target=run)
        thread.start()
        time.sleep(1.0)                 # jobs are sleeping on nodes now
        cluster.kill_node(2)            # one slot host dies mid-job
        thread.join(timeout=240)
        assert not thread.is_alive()
        assert not errors, errors
        assert result["rows"] == 300
        out = sorted(client.read_table("//home/out"),
                     key=lambda r: r["a"])
        assert out == rows
