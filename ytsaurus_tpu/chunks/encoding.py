"""Chunk wire format: columnar serialization with per-block checksums.

TPU-native chunk layout (mirrors the INTENT of ytlib/table_chunk_format —
per-column segments with type-specialized encodings — not its encoding):

  MAGIC 'YTC1' | varint meta_len | meta (binary YSON) | block bytes...

Meta: schema, row_count, codec name, per-column block descriptors
(offset/compressed size/raw size/checksum).  Encodings by logical type:
  int64/uint64  delta + zigzag varint (delta wins on sorted keys, harmless
                otherwise)
  double        raw 8-byte LE planes
  boolean       bit-packed
  string        int32 codes as varint + vocabulary block (length-prefixed)
  validity      bit-packed bitmap per column
Checksums are CRC-64 via the native library (ytsaurus_tpu.native).
"""

from __future__ import annotations

import struct
from dataclasses import replace
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ytsaurus_tpu import native, yson
from ytsaurus_tpu.chunks.columnar import Column, ColumnarChunk, pad_capacity
from ytsaurus_tpu.chunks.compression import get_codec
from ytsaurus_tpu.chunks.hunks import HunkRef
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.schema import (EValueType, TableSchema, VectorType,
                                 device_dtype)

from ytsaurus_tpu.utils.varint import (  # noqa: E402  (shared varint impl)
    encode_varint_u as _encode_varint_u,
    read_varint_u as _decode_varint_u,
)

MAGIC = b"YTC1"
DEFAULT_CODEC = "zlib_6"


def _encode_column(col: Column, ty: EValueType, n: int) -> tuple[bytes, bytes]:
    """Returns (data_block, aux_block) raw bytes; aux = vocab/host payload."""
    data = np.asarray(col.data[:n])
    aux = b""
    if isinstance(ty, VectorType):
        # Contiguous raw float32 LE (n, dim) plane — already fixed
        # width, so no per-row framing; dim rides in the schema.
        block = data.astype("<f4").tobytes()
    elif ty in (EValueType.int64, EValueType.uint64):
        block = native.varint_encode(
            native.delta_encode(data.astype(np.int64)))
    elif ty is EValueType.double:
        block = data.astype("<f8").tobytes()
    elif ty is EValueType.boolean:
        block = native.bitmap_pack(data.astype(np.uint8))
    elif ty is EValueType.string:
        block = native.varint_encode(
            native.delta_encode(data.astype(np.int64)))
        vocab = col.dictionary if col.dictionary is not None else \
            np.array([], dtype=object)
        # Tagged entries: 0 = inline bytes, 1 = hunk ref (id, length).
        parts = [_encode_varint_u(len(vocab))]
        for v in vocab:
            if isinstance(v, HunkRef):
                hid = v.hunk_id.encode()
                parts.append(b"\x01")
                parts.append(_encode_varint_u(len(hid)))
                parts.append(hid)
                parts.append(_encode_varint_u(v.length))
            else:
                parts.append(b"\x00")
                parts.append(_encode_varint_u(len(v)))
                parts.append(bytes(v))
        aux = b"".join(parts)
    elif ty is EValueType.any:
        block = b""
        values = (col.host_values or [])[:n]
        aux = yson.dumps([None if v is None else v for v in values],
                         binary=True)
    elif ty is EValueType.null:
        block = b""
    else:
        raise YtError(f"Cannot encode column type {ty.value}",
                      code=EErrorCode.ChunkFormatError)
    return block, aux


def _decode_column(ty: EValueType, data_block: bytes, aux_block: bytes,
                   valid: np.ndarray, n: int, cap: int,
                   format_version: int = 2) -> Column:
    dictionary = None
    host_values = None
    if isinstance(ty, VectorType):
        flat = np.frombuffer(data_block, dtype="<f4", count=n * ty.dim)
        plane = flat.reshape(n, ty.dim)
        if n and not np.isfinite(plane[valid[:n]]).all():
            raise YtError("Non-finite vector component in chunk block",
                          code=EErrorCode.ChunkFormatError)
    elif ty in (EValueType.int64, EValueType.uint64):
        values = native.delta_decode(native.varint_decode(data_block, n))
        plane = values.astype(device_dtype(ty))
    elif ty is EValueType.double:
        plane = np.frombuffer(data_block, dtype="<f8", count=n)
    elif ty is EValueType.boolean:
        plane = native.bitmap_unpack(data_block, n)
    elif ty is EValueType.string:
        values = native.delta_decode(native.varint_decode(data_block, n))
        plane = values.astype(np.int32)
        count, pos = _decode_varint_u(aux_block, 0)
        vocab = []
        for _ in range(count):
            if format_version >= 2:
                tag = aux_block[pos]
                pos += 1
            else:
                tag = 0                     # v1: untagged inline entries
            if tag == 0:
                length, pos = _decode_varint_u(aux_block, pos)
                vocab.append(aux_block[pos:pos + length])
                pos += length
            elif tag == 1:
                id_len, pos = _decode_varint_u(aux_block, pos)
                hid = aux_block[pos:pos + id_len].decode()
                pos += id_len
                length, pos = _decode_varint_u(aux_block, pos)
                vocab.append(HunkRef(hunk_id=hid, length=length))
            else:
                raise YtError(f"Bad vocab entry tag {tag}",
                              code=EErrorCode.ChunkFormatError)
        dictionary = np.empty(count, dtype=object)
        dictionary[:] = vocab
    elif ty is EValueType.any:
        # utf-8 decode so str payloads round-trip as str (bytes that are not
        # valid utf-8 stay bytes — the YSON wire format cannot distinguish).
        decoded = yson.loads(aux_block) if aux_block else []
        host_values = list(decoded) + [None] * (cap - n)
        plane = np.zeros(n, dtype=np.int8)
    elif ty is EValueType.null:
        plane = np.zeros(n, dtype=np.int8)
    else:
        raise YtError(f"Cannot decode column type {ty.value}",
                      code=EErrorCode.ChunkFormatError)
    full = np.zeros((cap,) + plane.shape[1:], dtype=plane.dtype)
    full[:n] = plane
    full_valid = np.zeros(cap, dtype=bool)
    full_valid[:n] = valid
    return Column(type=ty, data=jnp.asarray(full), valid=jnp.asarray(full_valid),
                  dictionary=dictionary, host_values=host_values)


def serialize_chunk(chunk: ColumnarChunk, codec: str = DEFAULT_CODEC,
                    hunk_store=None) -> bytes:
    """hunk_store: when given, string-column vocab entries whose column
    schema sets max_inline_hunk_size move out-of-row into content-addressed
    hunk blobs (ref hunks.h); their ids land in meta["hunk_chunk_ids"]."""
    compress, _ = get_codec(codec)
    n = chunk.row_count
    blocks: list[bytes] = []
    columns_meta = []
    hunk_chunk_ids: set[str] = set()
    offset = 0

    def add_block(raw: bytes) -> dict:
        nonlocal offset
        compressed = compress(raw)
        blocks.append(compressed)
        desc = {
            "offset": offset,
            "size": len(compressed),
            "raw_size": len(raw),
            "checksum": yson.YsonUint64(native.checksum(raw)),
        }
        offset += len(compressed)
        return desc

    for col_schema in chunk.schema:
        col = chunk.columns[col_schema.name]
        if hunk_store is not None and \
                col_schema.max_inline_hunk_size is not None and \
                col.dictionary is not None:
            from ytsaurus_tpu.chunks.hunks import hunkify_vocab
            vocab, ids = hunkify_vocab(hunk_store, col.dictionary,
                                       col_schema.max_inline_hunk_size)
            hunk_chunk_ids.update(ids)
            col = replace(col, dictionary=vocab)
        data_block, aux_block = _encode_column(col, col_schema.type, n)
        valid_block = native.bitmap_pack(
            np.asarray(col.valid[:n]).astype(np.uint8))
        columns_meta.append({
            "name": col_schema.name,
            "data": add_block(data_block),
            "aux": add_block(aux_block),
            "valid": add_block(valid_block),
        })

    from ytsaurus_tpu.chunks.columnar import chunk_column_stats
    meta = {
        # v2: tagged string-vocab entries (inline | hunk ref); v1 readable.
        "format_version": 2,
        "codec": codec,
        "row_count": n,
        "schema": chunk.schema.to_dict(),
        "columns": columns_meta,
        # Per-column min/max/has_null computed ONCE at seal time; scan
        # pruning and tablet snapshot-cache keying read them from the
        # meta header (no block decompress, no host recompute).
        "column_stats": chunk_column_stats(chunk),
    }
    if hunk_chunk_ids:
        meta["hunk_chunk_ids"] = sorted(hunk_chunk_ids)
    meta_blob = yson.dumps(meta, binary=True)
    return b"".join([MAGIC, _encode_varint_u(len(meta_blob)), meta_blob]
                    + blocks)


def read_chunk_meta(blob: bytes) -> dict:
    if blob[:4] != MAGIC:
        raise YtError("Bad chunk magic", code=EErrorCode.ChunkFormatError)
    meta_len, pos = _decode_varint_u(blob, 4)
    meta = yson.loads(blob[pos:pos + meta_len])
    meta["_data_start"] = pos + meta_len
    return meta


def deserialize_chunk(blob: bytes,
                      capacity: Optional[int] = None,
                      hunk_store=None) -> ColumnarChunk:
    meta = read_chunk_meta(blob)
    _, decompress = get_codec(meta["codec"])
    start = meta["_data_start"]
    n = meta["row_count"]
    cap = capacity or pad_capacity(max(n, 1))
    schema = TableSchema.from_dict(meta["schema"])

    def read_block(desc: dict) -> bytes:
        lo = start + desc["offset"]
        try:
            raw = decompress(bytes(blob[lo:lo + desc["size"]]))
        except Exception as e:
            raise YtError(f"Chunk block decompression failed: {e}",
                          code=EErrorCode.ChunkFormatError)
        if len(raw) != desc["raw_size"]:
            raise YtError("Chunk block size mismatch",
                          code=EErrorCode.ChunkFormatError)
        if native.checksum(raw) != int(desc["checksum"]):
            raise YtError("Chunk block checksum mismatch",
                          code=EErrorCode.ChunkFormatError)
        return raw

    has_hunks = bool(meta.get("hunk_chunk_ids"))
    columns: dict[str, Column] = {}
    try:
        for col_meta in meta["columns"]:
            name = col_meta["name"]
            col_schema = schema.get(name)
            valid = native.bitmap_unpack(read_block(col_meta["valid"]), n)
            column = _decode_column(
                col_schema.type, read_block(col_meta["data"]),
                read_block(col_meta["aux"]), valid, n, cap,
                format_version=int(meta.get("format_version", 1)))
            if has_hunks and column.dictionary is not None and \
                    any(isinstance(v, HunkRef) for v in column.dictionary):
                from ytsaurus_tpu.chunks.hunks import resolve_vocab
                column = replace(column, dictionary=resolve_vocab(
                    hunk_store, column.dictionary))
            columns[name] = column
    except (ValueError, IndexError, KeyError) as e:
        raise YtError(f"Chunk decode failed: {e}",
                      code=EErrorCode.ChunkFormatError)
    return ColumnarChunk(schema=schema, row_count=n, columns=columns)
