"""Columnar chunks: the device-resident unit of table data.

TPU-native analog of the reference's columnar chunk format
(yt/yt/ytlib/columnar_chunk_format — "format version 3" scan-oriented reader,
segment_readers.h) re-designed for XLA rather than translated:

  * A chunk is a struct-of-arrays: one fixed-width device plane per column plus
    a validity plane, padded to a static capacity (multiple of 128 lanes) so
    every kernel sees static shapes.  `row_count` may be smaller than capacity;
    rows beyond it are masked out by `row_valid`.
  * Strings are order-preserving dictionary-encoded per chunk: the device plane
    holds int32 ranks into a host-side sorted vocabulary.  Rank order == byte
    order, so ORDER BY / range predicates / GROUP BY on strings are pure integer
    ops on device.  Cross-chunk operations unify vocabularies host-side and
    remap codes with one device gather (see `unify_dictionaries`).
  * `any`-typed payloads stay host-side (list of YSON values); they ride along
    for projection but are opaque to device compute, like the reference's
    "any" columns are opaque blobs to its codegen.
"""

from __future__ import annotations

import hashlib
import weakref
from dataclasses import dataclass, replace
from typing import Any, Iterable, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils.invariants import check as _invariant_check
from ytsaurus_tpu.schema import (
    EValueType,
    TableSchema,
    VectorType,
    device_dtype,
)

LANE = 128  # last-dim tiling unit on TPU; capacities are multiples of this


def next_pow2(n: int, floor: int = 1) -> int:
    """THE pow2 bucketing primitive: smallest power-of-two multiple of
    `floor` that is >= n (floor itself for n <= floor).  Every bucketed
    shape in the tree — chunk capacities, lookup-probe needle arrays,
    vocabulary-table paddings, IN-list bindings, LIMIT fingerprint
    buckets — derives from this one implementation, so the compile-cache
    key spectrum is O(log max) everywhere by construction."""
    cap = max(floor, 1)
    while cap < n:
        cap *= 2
    return cap


def pad_capacity(n: int) -> int:
    """Round a row count up to a static capacity bucket.

    Buckets are powers of two (times LANE) so distinct data sizes collapse onto
    few compiled shapes — the XLA analog of the reference's LLVM code cache
    keyed by query fingerprint only (engine_api/cg_cache.h): we additionally
    key by capacity bucket, so bucketing bounds the number of recompiles.
    """
    return next_pow2(n, floor=LANE)


def _encode_strings(values: Sequence[Optional[bytes]]) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Order-preserving dictionary encode. Returns (codes, valid, vocab).

    Vectorized for high-cardinality columns (the round-1 "string cliff"):
    a fixed-width bytes array + ONE np.unique(return_inverse) replaces the
    per-value Python dict lookups — C-speed for ~1M-distinct columns (the
    sortedness of np.unique keeps code order == byte order, which the
    range/comparison lowering relies on)."""
    valid = np.array([v is not None for v in values], dtype=bool)
    if not valid.any():
        return (np.zeros(len(values), dtype=np.int32), valid,
                np.array([], dtype=object))
    # Object dtype (NOT numpy "S": fixed-width strips trailing NULs and
    # would corrupt arbitrary binary strings).
    packed = np.empty(len(values), dtype=object)
    packed[:] = [v if v is not None else b"" for v in values]
    vocab, codes = np.unique(packed, return_inverse=True)
    codes = codes.astype(np.int32)
    # b"" padding for nulls may introduce a phantom vocab entry; keep it
    # only if a VALID row actually holds the empty string.
    if len(vocab) and vocab[0] == b"" and not (
            valid & (codes == 0)).any():
        vocab = vocab[1:]
        codes = np.maximum(codes - 1, 0)
    return codes, valid, np.asarray(vocab, dtype=object)


def _to_bytes(v) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode("utf-8")
    raise YtError(f"Expected string value, got {type(v).__name__}")


@dataclass(frozen=True)
class Column:
    """One column plane: device data + validity + optional host vocabulary."""

    type: EValueType
    data: jax.Array                      # (capacity,) device_dtype(type)
    valid: jax.Array                     # (capacity,) bool
    dictionary: Optional[np.ndarray] = None   # host vocab for string columns
    host_values: Optional[list] = None        # payloads for `any` columns

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    def decode(self, row_count: int) -> list:
        """Materialize host values for the first `row_count` rows."""
        data = np.asarray(self.data[:row_count])
        valid = np.asarray(self.valid[:row_count])
        out: list = []
        for i in range(row_count):
            if not valid[i]:
                out.append(None)
            elif isinstance(self.type, VectorType):
                out.append([float(x) for x in data[i]])
            elif self.type is EValueType.string:
                out.append(bytes(self.dictionary[int(data[i])]))
            elif self.type is EValueType.any:
                out.append(self.host_values[i])
            elif self.type is EValueType.boolean:
                out.append(bool(data[i]))
            elif self.type is EValueType.double:
                out.append(float(data[i]))
            elif self.type is EValueType.null:
                out.append(None)
            else:
                out.append(int(data[i]))
        return out


@dataclass(frozen=True)
class ColumnarChunk:
    """An immutable columnar rowset with static device capacity."""

    schema: TableSchema
    row_count: int
    columns: dict[str, Column]
    # Sealed physical row order (ISSUE 19): column names whose ascending,
    # null-first, YT-comparator order the rows are already in (a prefix
    # guarantee: rows sorted by sorted_by[0], ties by sorted_by[1], ...).
    # Sealed at tablet flush/snapshot time where the MVCC merge emits key
    # order; ORDER BY lowering skips the packed-key sort when its spec is
    # covered.  Row-order-preserving transforms propagate it; anything
    # that reorders or merges rows must drop it (the default).
    sorted_by: tuple = ()

    @property
    def capacity(self) -> int:
        if not self.columns:
            return pad_capacity(max(self.row_count, 1))
        return next(iter(self.columns.values())).capacity

    @property
    def row_valid(self) -> jax.Array:
        cap = self.capacity
        return jnp.arange(cap) < self.row_count

    @property
    def nbytes(self) -> int:
        """Resident bytes of the column planes (capacity-padded) — the
        bytes-scanned unit per-tenant accounting charges.  `.nbytes` on
        a device array is metadata; nothing transfers."""
        total = 0
        for col in self.columns.values():
            total += int(getattr(col.data, "nbytes", 0))
            if col.valid is not None:
                total += int(getattr(col.valid, "nbytes", 0))
        return total

    def column(self, name: str) -> Column:
        col = self.columns.get(name)
        if col is None:
            raise YtError(f"No such column {name!r} in chunk",
                          code=EErrorCode.QueryTypeError)
        return col

    # --- construction ---------------------------------------------------------

    @staticmethod
    def from_rows(schema: TableSchema, rows: Sequence[Mapping[str, Any] | Sequence[Any]],
                  capacity: Optional[int] = None) -> "ColumnarChunk":
        n = len(rows)
        cap = capacity or pad_capacity(max(n, 1))
        if cap < n:
            raise YtError(f"Capacity {cap} < row count {n}")
        names = schema.column_names
        # Normalize to per-column host lists.
        name_set = set(names)
        per_col: dict[str, list] = {name: [] for name in names}
        for row in rows:
            if isinstance(row, Mapping):
                if schema.strict:
                    unknown = set(row) - name_set
                    if unknown:
                        raise YtError(
                            f"Unknown columns {sorted(unknown)} for strict schema",
                            code=EErrorCode.QueryTypeError)
                for name in names:
                    per_col[name].append(row.get(name))
            else:
                if len(row) != len(names):
                    raise YtError(
                        f"Row width {len(row)} != schema width {len(names)}")
                for name, v in zip(names, row):
                    per_col[name].append(v)
        columns: dict[str, Column] = {}
        for col_schema in schema:
            name = col_schema.name
            ty = col_schema.type
            values = per_col[name]
            if col_schema.required:
                for i, v in enumerate(values):
                    if v is None:
                        raise YtError(
                            f"Required column {name!r} is null in row {i}",
                            code=EErrorCode.QueryTypeError)
            columns[name] = _build_column(ty, values, cap, name=name)
        chunk = ColumnarChunk(schema=schema, row_count=n, columns=columns)
        _invariant_check("chunks", chunk)
        return chunk

    @staticmethod
    def from_arrays(schema: TableSchema, arrays: Mapping[str, np.ndarray],
                    row_count: Optional[int] = None,
                    valids: Optional[Mapping[str, np.ndarray]] = None,
                    dictionaries: Optional[Mapping[str, np.ndarray]] = None,
                    capacity: Optional[int] = None) -> "ColumnarChunk":
        """Fast path from numpy arrays (no per-value python loop)."""
        names = schema.column_names
        n = row_count if row_count is not None else len(next(iter(arrays.values())))
        cap = capacity or pad_capacity(max(n, 1))
        columns: dict[str, Column] = {}
        for col_schema in schema:
            name = col_schema.name
            ty = col_schema.type
            if ty is EValueType.any:
                raise YtError("from_arrays does not support `any` columns; "
                              "use from_rows", code=EErrorCode.QueryUnsupported)
            arr = np.asarray(arrays[name])
            if len(arr) != n:
                raise YtError(f"Column {name!r} length {len(arr)} != {n}")
            if isinstance(ty, VectorType):
                if arr.ndim != 2 or arr.shape[1] != ty.dim:
                    raise YtError(
                        f"Vector column {name!r} needs a (rows, {ty.dim}) "
                        f"array, got shape {arr.shape}",
                        code=EErrorCode.QueryTypeError)
                if not np.isfinite(arr).all():
                    raise YtError(
                        f"Non-finite component in vector column {name!r}",
                        code=EErrorCode.QueryTypeError)
                data = np.zeros((cap, ty.dim), dtype=np.float32)
                data[:n] = arr.astype(np.float32)
                valid = np.zeros(cap, dtype=bool)
                if valids is not None and name in valids:
                    valid[:n] = np.asarray(valids[name], dtype=bool)
                else:
                    valid[:n] = True
                columns[name] = Column(type=ty, data=jnp.asarray(data),
                                       valid=jnp.asarray(valid))
                continue
            vocab = None
            if ty is EValueType.string:
                if dictionaries is not None and name in dictionaries:
                    vocab = np.asarray(dictionaries[name], dtype=object)
                else:
                    # Raw string array: vectorized dictionary encode (the
                    # high-cardinality path; ONE np.unique, no per-value
                    # Python lookups).  "S"/"U" inputs are fixed-width
                    # already (numpy cannot represent trailing NULs there);
                    # object arrays unique losslessly over arbitrary bytes.
                    raw = arr
                    if raw.dtype.kind == "U":
                        raw = np.char.encode(raw, "utf-8")
                    if raw.dtype.kind == "O":
                        # None entries mark nulls; replace with b"" so
                        # np.unique can compare, masked out via validity.
                        none_mask = np.array(
                            [v is None for v in raw], dtype=bool)
                        if none_mask.any():
                            raw = raw.copy()
                            raw[none_mask] = b""
                            if valids is None or name not in valids:
                                v0 = np.ones(n, dtype=bool)
                                v0[none_mask] = False
                                valids = dict(valids or {})
                                valids[name] = v0
                    if raw.dtype.kind in ("S", "O"):
                        vocab_s, codes = np.unique(raw, return_inverse=True)
                        vocab = np.empty(len(vocab_s), dtype=object)
                        vocab[:] = [bytes(v) for v in vocab_s]
                        arr = codes.astype(np.int32)
                    else:
                        raise YtError(
                            f"String column {name!r} needs a dictionary "
                            "or a string-typed array")
            dt = device_dtype(ty)
            data = np.zeros(cap, dtype=dt)
            data[:n] = arr.astype(dt)
            valid = np.zeros(cap, dtype=bool)
            if valids is not None and name in valids:
                valid[:n] = np.asarray(valids[name], dtype=bool)
            else:
                valid[:n] = True
            columns[name] = Column(type=ty, data=jnp.asarray(data),
                                   valid=jnp.asarray(valid), dictionary=vocab)
        chunk = ColumnarChunk(schema=schema, row_count=n, columns=columns)
        _invariant_check("chunks", chunk)
        return chunk

    # --- materialization ------------------------------------------------------

    def to_rows(self) -> list[dict[str, Any]]:
        decoded = {name: col.decode(self.row_count)
                   for name, col in self.columns.items()}
        names = self.schema.column_names
        return [
            {name: decoded[name][i] for name in names}
            for i in range(self.row_count)
        ]

    def to_tuples(self) -> list[tuple]:
        decoded = [self.columns[name].decode(self.row_count)
                   for name in self.schema.column_names]
        return [tuple(col[i] for col in decoded) for i in range(self.row_count)]

    # --- transforms -----------------------------------------------------------

    def with_capacity(self, capacity: int) -> "ColumnarChunk":
        """Repad all planes to a new (>= row_count) capacity."""
        if capacity == self.capacity:
            return self
        if capacity < self.row_count:
            raise YtError("Cannot shrink chunk below its row count")
        columns = {}
        m = min(capacity, self.capacity)
        for name, col in self.columns.items():
            # (capacity,) + trailing dims: vector planes repad along axis 0.
            data = jnp.zeros((capacity,) + col.data.shape[1:],
                             dtype=col.data.dtype).at[:m].set(col.data[:m])
            valid = jnp.zeros(capacity, dtype=bool).at[:m].set(col.valid[:m])
            columns[name] = replace(col, data=data, valid=valid)
        return ColumnarChunk(schema=self.schema, row_count=self.row_count,
                             columns=columns, sorted_by=self.sorted_by)

    def slice_rows(self, start: int, end: int) -> "ColumnarChunk":
        start = max(0, start)
        end = min(self.row_count, end)
        n = max(0, end - start)
        cap = pad_capacity(max(n, 1))
        columns = {}
        for name, col in self.columns.items():
            trailing = col.data.shape[1:]
            data = jnp.zeros((cap,) + trailing, dtype=col.data.dtype).at[:n].set(
                jax.lax.dynamic_slice_in_dim(col.data, start, n) if n else
                jnp.zeros((0,) + trailing, dtype=col.data.dtype))
            valid = jnp.zeros(cap, dtype=bool).at[:n].set(
                jax.lax.dynamic_slice_in_dim(col.valid, start, n) if n else
                jnp.zeros(0, dtype=bool))
            host_values = None
            if col.host_values is not None:
                host_values = col.host_values[start:end]
            columns[name] = replace(col, data=data, valid=valid,
                                    host_values=host_values)
        return ColumnarChunk(schema=self.schema, row_count=n, columns=columns,
                             sorted_by=self.sorted_by)


def _plane_dtype(ty: EValueType) -> np.dtype:
    # `any` columns carry host payloads; their device plane is a placeholder.
    if ty is EValueType.any:
        return np.dtype(np.int8)
    return device_dtype(ty)


def _build_vector_plane(ty: VectorType, values: Sequence[Any],
                        cap: int, name: str = "") -> tuple[np.ndarray,
                                                           np.ndarray]:
    """Host rows → contiguous (cap, dim) float32 plane + validity.

    The WRITE-path hardening gate: ragged rows, wrong-dim rows and
    non-finite components are rejected loudly here — a NaN that slipped
    into a stored plane would silently poison every distance it ever
    participates in, so it must never seal."""
    dim = ty.dim
    n = len(values)
    data_np = np.zeros((cap, dim), dtype=np.float32)
    valid_np = np.zeros(cap, dtype=bool)
    label = f" in column {name!r}" if name else ""
    for i, v in enumerate(values):
        if v is None:
            continue
        try:
            arr = np.asarray(v, dtype=np.float32)
        except (TypeError, ValueError) as e:
            raise YtError(f"Bad vector value{label} at row {i}: {e}",
                          code=EErrorCode.QueryTypeError)
        if arr.ndim != 1:
            raise YtError(
                f"Ragged vector value{label} at row {i}: expected a flat "
                f"{dim}-component vector, got shape {arr.shape}",
                code=EErrorCode.QueryTypeError)
        if arr.shape[0] != dim:
            raise YtError(
                f"Vector dim mismatch{label} at row {i}: expected {dim} "
                f"components, got {arr.shape[0]}",
                code=EErrorCode.QueryTypeError)
        if not np.isfinite(arr).all():
            raise YtError(
                f"Non-finite vector component{label} at row {i}",
                code=EErrorCode.QueryTypeError)
        data_np[i] = arr
        valid_np[i] = True
    return data_np, valid_np


def _build_column(ty: EValueType, values: Sequence[Any], cap: int,
                  name: str = "") -> Column:
    n = len(values)
    if isinstance(ty, VectorType):
        data_np, valid_np = _build_vector_plane(ty, values, cap, name)
        return Column(type=ty, data=jnp.asarray(data_np),
                      valid=jnp.asarray(valid_np))
    dt = _plane_dtype(ty)
    valid_np = np.zeros(cap, dtype=bool)
    data_np = np.zeros(cap, dtype=dt)
    vocab = None
    host_values = None
    if ty is EValueType.string:
        encoded = [None if v is None else _to_bytes(v) for v in values]
        codes, valid, vocab = _encode_strings(encoded)
        data_np[:n] = codes
        valid_np[:n] = valid
    elif ty is EValueType.any:
        host_values = list(values) + [None] * (cap - n)
        valid_np[:n] = [v is not None for v in values]
    elif ty is EValueType.null:
        pass
    else:
        for i, v in enumerate(values):
            if v is None:
                continue
            valid_np[i] = True
            if ty is EValueType.boolean:
                data_np[i] = bool(v)
            elif ty is EValueType.double:
                data_np[i] = float(v)
            elif ty is EValueType.uint64:
                data_np[i] = np.uint64(v)
            else:
                data_np[i] = np.int64(v)
    return Column(type=ty, data=jnp.asarray(data_np), valid=jnp.asarray(valid_np),
                  dictionary=vocab, host_values=host_values)


# id(vocab) -> (weakref, digest).  Vocab arrays are immutable by
# convention (built sorted once at encode time, shared thereafter), so a
# content digest can be memoized per array identity; the weakref guards
# against id() reuse after collection (the _chunk_memo idiom).
_VOCAB_DIGEST_MEMO: dict = {}


def vocab_digest(vocab: np.ndarray) -> str:
    """Stable content digest of a sorted string vocabulary.  O(|vocab|)
    once per array, O(1) after — the identity check that lets
    `unify_dictionaries` and code-space predicate bindings recognize
    already-shared vocabs without a merge."""
    key = id(vocab)
    hit = _VOCAB_DIGEST_MEMO.get(key)
    if hit is not None and hit[0]() is vocab:
        return hit[1]
    h = hashlib.blake2b(digest_size=16)
    for v in vocab:
        b = v if isinstance(v, bytes) else _to_bytes(v)
        h.update(len(b).to_bytes(4, "little"))
        h.update(b)
    digest = h.hexdigest()
    if len(_VOCAB_DIGEST_MEMO) > 4096:
        for k in [k for k, (ref, _) in _VOCAB_DIGEST_MEMO.items()
                  if ref() is None]:
            del _VOCAB_DIGEST_MEMO[k]
    _VOCAB_DIGEST_MEMO[key] = (weakref.ref(vocab), digest)
    return digest


def unify_dictionaries(columns: Sequence[Column]) -> tuple[list[Column], np.ndarray]:
    """Re-encode string columns onto a shared sorted vocabulary.

    Returns the remapped columns and the unified vocab.  The remap is a single
    device gather per column (codes -> new codes), keeping order preservation.

    Fast path: when every string column already carries the SAME vocab
    (by identity, else by length + content digest) — the common
    post-compaction case — the columns return untouched: no host merge,
    no device gathers.
    """
    string_cols = [c for c in columns if c.type is EValueType.string]
    if string_cols and all(c.dictionary is not None for c in string_cols):
        first = string_cols[0].dictionary
        rest = [c.dictionary for c in string_cols[1:]]
        identical = all(v is first for v in rest)
        if not identical and all(len(v) == len(first) for v in rest):
            d0 = vocab_digest(first)
            identical = all(vocab_digest(v) == d0 for v in rest)
        if identical:
            return list(columns), np.asarray(first, dtype=object)
    vocabs = [c.dictionary for c in columns if c.dictionary is not None]
    # Vectorized union + remap (np.unique / searchsorted over object
    # arrays — lossless for arbitrary bytes): high-cardinality vocab
    # merges were the round-1 host cliff.
    if vocabs:
        merged = np.unique(np.concatenate(
            [np.asarray(v, dtype=object) for v in vocabs]))
    else:
        merged = np.array([], dtype=object)
    merged = np.asarray(merged, dtype=object)
    out = []
    for col in columns:
        if col.type is not EValueType.string:
            out.append(col)
            continue
        old_vocab = col.dictionary if col.dictionary is not None else np.array([], dtype=object)
        remap_np = np.searchsorted(
            merged, np.asarray(old_vocab, dtype=object)).astype(np.int32) \
            if len(old_vocab) else np.array([], dtype=np.int32)
        if len(remap_np) == 0:
            remap_np = np.zeros(1, dtype=np.int32)
        remap = jnp.asarray(remap_np)
        new_codes = remap[jnp.clip(col.data, 0, len(remap_np) - 1)]
        out.append(replace(col, data=new_codes.astype(jnp.int32), dictionary=merged))
    return out, merged


# Bound on string min/max stat values stored in chunk meta.  chunk_may_match
# treats a None bound as unprunable, so widening/dropping bounds is always
# safe — it only costs pruning power on pathological columns.
_STAT_STRING_CAP = 64

# --- distinct-count sketch ----------------------------------------------------
#
# A fixed 64-register hash-max sketch (the HLL register layout) per
# column, sealed into chunk meta next to min/max/has_null: the cost-based
# join planner (query/planner.py) reads NDV off chunk metadata instead of
# decoding data, and sketches MERGE across chunks by elementwise register
# max — so a table-level NDV is a fold over per-chunk meta, never a scan.
# 64 one-byte registers keep the meta payload bounded (the PR 5 hunk-
# externalization lesson: stats must never re-inline data-sized payloads).

NDV_SKETCH_SLOTS = 64
_NDV_SLOT_BITS = 6
_NDV_MAX_RANK = 58              # 64 - slot bits: ranks fit one byte


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 over a uint64 array (wrapping arithmetic)."""
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def _ndv_sketch_from_hashes(hashes: np.ndarray) -> bytes:
    """Fold uniform uint64 hashes into the 64-register sketch: low bits
    pick the register, the rank is 1 + trailing-zero count of the rest
    (the classic stochastic-averaging split)."""
    regs = np.zeros(NDV_SKETCH_SLOTS, dtype=np.uint8)
    if len(hashes):
        h = hashes.astype(np.uint64)
        slots = (h & np.uint64(NDV_SKETCH_SLOTS - 1)).astype(np.int64)
        rest = h >> np.uint64(_NDV_SLOT_BITS)
        with np.errstate(over="ignore"):
            lsb = rest & (~rest + np.uint64(1))
        # log2 of an exact power of two is exact in float64 up to 2^58.
        rank = np.where(rest == 0, _NDV_MAX_RANK,
                        1 + np.log2(np.maximum(lsb, 1).astype(np.float64))
                        ).astype(np.uint8)
        np.maximum.at(regs, slots, rank)
    return regs.tobytes()


def _hash_string_vocab(vocab: np.ndarray) -> np.ndarray:
    """Deterministic (cross-process stable) uint64 content hash per
    vocab entry, vectorized: one concatenated byte buffer, a wrapping
    polynomial fold per segment (`np.add.reduceat` over byte·p^pos),
    the length folded in, then splitmix.  Entries that are hunk refs
    hash their id (the same identity the store dedups by).  This runs
    on the chunk SEAL path — a per-entry digest loop would be
    O(distinct) interpreter-speed work on exactly the high-NDV columns
    the sketch exists for."""
    from ytsaurus_tpu.chunks.hunks import HunkRef
    n = len(vocab)
    if n == 0:
        return np.zeros(0, dtype=np.uint64)
    entries = [v.hunk_id.encode() if isinstance(v, HunkRef) else bytes(v)
               for v in vocab]
    lengths = np.fromiter((len(e) for e in entries), count=n,
                          dtype=np.int64)
    # One leading sentinel byte per entry keeps every reduceat segment
    # non-empty (reduceat over an empty segment would leak a neighbor's
    # byte) and distinguishes b"" from absent.
    data = np.frombuffer(b"\x01" + b"\x01".join(entries),
                         dtype=np.uint8).astype(np.uint64)
    seg_lengths = lengths + 1
    starts = np.zeros(n, dtype=np.int64)
    np.cumsum(seg_lengths[:-1], out=starts[1:])
    p = np.uint64(0x9E3779B97F4A7C15 | 1)
    with np.errstate(over="ignore"):
        powers = np.empty(int(seg_lengths.max()), dtype=np.uint64)
        powers[0] = 1
        np.cumprod(np.full(len(powers) - 1, p, dtype=np.uint64),
                   out=powers[1:])
        pos = np.arange(len(data), dtype=np.int64) - \
            np.repeat(starts, seg_lengths)
        h = np.add.reduceat(data * powers[pos], starts)
        h = h ^ (lengths.astype(np.uint64) *
                 np.uint64(0xBF58476D1CE4E5B9))
    return _splitmix64(h)


def column_ndv_sketch(col: Column, row_count: int) -> "bytes | None":
    """The column's distinct-count sketch over its valid values, or None
    for types with no meaningful NDV (any/null)."""
    if col.type in (EValueType.any, EValueType.null) or \
            isinstance(col.type, VectorType):
        return None
    n = row_count
    valid = np.asarray(col.valid[:n]) if n else np.zeros(0, dtype=bool)
    if not n or not valid.any():
        return _ndv_sketch_from_hashes(np.zeros(0, dtype=np.uint64))
    data = np.asarray(col.data[:n])[valid]
    if col.type is EValueType.string:
        vocab = col.dictionary if col.dictionary is not None \
            else np.array([], dtype=object)
        entry_hashes = _hash_string_vocab(vocab)
        if len(entry_hashes) == 0:
            hashes = np.zeros(0, dtype=np.uint64)
        else:
            hashes = entry_hashes[
                np.clip(data.astype(np.int64), 0, len(entry_hashes) - 1)]
    elif col.type is EValueType.double:
        canon = np.where(data == 0.0, 0.0, data)   # -0.0 == +0.0
        hashes = _splitmix64(canon.view(np.uint64))
    else:
        hashes = _splitmix64(data.astype(np.int64).view(np.uint64)
                             if col.type is not EValueType.uint64
                             else data.astype(np.uint64))
    return _ndv_sketch_from_hashes(hashes)


def _sketch_regs(sketch) -> "np.ndarray | None":
    """Registers from a sketch payload.  Binary YSON round-trips bytes
    that happen to be valid utf-8 as str — re-encoding restores the
    exact original bytes, so both spellings parse."""
    if sketch is None:
        return None
    if isinstance(sketch, str):
        sketch = sketch.encode("utf-8")
    regs = np.frombuffer(bytes(sketch), dtype=np.uint8)
    if len(regs) != NDV_SKETCH_SLOTS:
        return None                    # corrupt payload: unusable, not fatal
    return regs


def merge_ndv_sketches(sketches: "Iterable[bytes]") -> "bytes | None":
    """Elementwise register max — the sketch of the UNION of the inputs."""
    merged = None
    for s in sketches:
        regs = _sketch_regs(s)
        if regs is None:
            continue
        merged = regs.copy() if merged is None else np.maximum(merged, regs)
    return None if merged is None else merged.tobytes()


def ndv_estimate(sketch: "bytes | None") -> int:
    """Distinct-count estimate off the registers (HLL harmonic mean with
    the linear-counting small-range correction).  >= 1 for a non-empty
    sketch so selectivity divisions are always safe; 0 for no data."""
    regs = _sketch_regs(sketch)
    if regs is None:
        return 0
    regs = regs.astype(np.float64)
    if not regs.any():
        return 0
    m = float(NDV_SKETCH_SLOTS)
    est = 0.709 * m * m / np.sum(np.exp2(-regs))
    zeros = int((regs == 0).sum())
    if est <= 2.5 * m and zeros:
        est = m * np.log(m / zeros)
    return max(int(round(est)), 1)


def merge_column_stats(stats_list: "Sequence[dict]") -> dict:
    """Fold per-chunk column stats into table-level stats: min of mins,
    max of maxes (None = unbounded wins), has_null ORs, `$row_count`
    sums, sketches merge.  The planner's one-stop table cardinality
    view over chunk metadata."""
    def bound(v):
        # Binary YSON round-trips utf-8-clean bytes as str; normalize so
        # bounds from sealed meta and fresh host stats compare.
        return v.encode("utf-8") if isinstance(v, str) else v

    out: dict = {"$row_count": 0}
    for stats in stats_list:
        for name, entry in stats.items():
            if name == "$row_count":
                out["$row_count"] += int(entry)
                continue
            if not isinstance(entry, dict):
                continue
            if "vector_dim" in entry:
                # Vector columns fold exactly: counts and centroid SUMS
                # add, norm bounds min/max (None = no valid rows, the
                # other side wins), has_null ORs.
                cur = out.get(name)
                if cur is None:
                    out[name] = {**entry, "centroid_sum":
                                 list(entry.get("centroid_sum") or [])}
                    continue
                cur["has_null"] = bool(cur.get("has_null")) or \
                    bool(entry.get("has_null"))
                cur["count"] = int(cur.get("count", 0)) + \
                    int(entry.get("count", 0))
                a = cur.get("centroid_sum") or []
                b = entry.get("centroid_sum") or []
                cur["centroid_sum"] = [float(x) + float(y)
                                       for x, y in zip(a, b)] \
                    if a and b else list(a or b)
                for key, pick in (("norm_min", min), ("norm_max", max)):
                    x, y = cur.get(key), entry.get(key)
                    cur[key] = y if x is None else \
                        (x if y is None else pick(x, y))
                continue
            entry = {**entry, "min": bound(entry.get("min")),
                     "max": bound(entry.get("max"))}
            cur = out.get(name)
            if cur is None:
                cur = {"min": entry.get("min"), "max": entry.get("max"),
                       "has_null": bool(entry.get("has_null")),
                       "ndv_sketch": entry.get("ndv_sketch"),
                       "_empty": entry.get("min") is None
                       and entry.get("max") is None}
                out[name] = cur
                continue
            # A chunk with no valid rows (min AND max None) contributes
            # nothing to the bounds; a lone None bound (the string-cap
            # overflow) is genuinely unbounded and must win the merge.
            entry_empty = entry.get("min") is None and \
                entry.get("max") is None
            if not entry_empty:
                if cur.pop("_empty", False):
                    cur["min"], cur["max"] = entry.get("min"), \
                        entry.get("max")
                else:
                    for key, pick in (("min", min), ("max", max)):
                        a, b = cur.get(key), entry.get(key)
                        cur[key] = None if a is None or b is None \
                            else pick(a, b)
                cur["_empty"] = False
            cur["has_null"] = cur["has_null"] or bool(entry.get("has_null"))
            cur["ndv_sketch"] = merge_ndv_sketches(
                [cur.get("ndv_sketch"), entry.get("ndv_sketch")])
    for entry in out.values():
        if isinstance(entry, dict):
            entry.pop("_empty", None)
    return out


def _string_stat_upper(value: bytes) -> "bytes | None":
    """An upper bound for `value` no longer than the cap: the value itself
    when short, else the successor of its cap-length prefix (strictly
    greater than EVERY string starting with that prefix).  None when no
    bounded successor exists (prefix is all 0xFF)."""
    if len(value) <= _STAT_STRING_CAP:
        return value
    prefix = value[:_STAT_STRING_CAP].rstrip(b"\xff")
    if not prefix:
        return None
    return prefix[:-1] + bytes([prefix[-1] + 1])


def vector_column_stats(col: Column, row_count: int) -> dict:
    """Centroid + L2-norm stats for a vector column, sealed into chunk
    meta at flush time (the NDV-sketch pattern; the later ANN-pruning
    hook).  `centroid_sum` is the elementwise SUM over valid rows (not
    the mean) so the cross-chunk merge fold is an exact addition —
    readers divide by `count`.  `norm_min`/`norm_max` bracket the L2
    norms of valid rows: with a query norm they bound any chunk's best
    possible dot/cosine/L2 score via the triangle inequality."""
    n = row_count
    valid = np.asarray(col.valid[:n]) if n else np.zeros(0, dtype=bool)
    entry: dict = {"has_null": bool((~valid).any()) if n else True,
                   "vector_dim": int(col.type.dim), "count": 0,
                   "centroid_sum": [0.0] * int(col.type.dim),
                   "norm_min": None, "norm_max": None,
                   "ndv_sketch": None}
    if n and valid.any():
        data = np.asarray(col.data[:n])[valid].astype(np.float64)
        norms = np.sqrt((data * data).sum(axis=1))
        entry["count"] = int(valid.sum())
        entry["centroid_sum"] = [float(x) for x in data.sum(axis=0)]
        entry["norm_min"] = float(norms.min())
        entry["norm_max"] = float(norms.max())
    return entry


def chunk_column_stats(chunk: ColumnarChunk) -> dict:
    """Per-column min/max/has_null pruning statistics (+ `$row_count`).

    THE single implementation: embedded into chunk meta at serialize
    time (`chunks/encoding.py`), surfaced by `FsChunkStore.read_stats`,
    and re-exported as `query/pruning.compute_column_stats` for the
    host-side backfill of chunks written before stats persisted."""
    out: dict[str, dict] = {}
    n = chunk.row_count
    for name, col in chunk.columns.items():
        if col.type in (EValueType.any, EValueType.null):
            continue
        if isinstance(col.type, VectorType):
            out[name] = vector_column_stats(col, n)
            continue
        valid = np.asarray(col.valid[:n])
        entry: dict = {"has_null": bool((~valid).any()) if n else True,
                       "min": None, "max": None}
        if n and valid.any():
            data = np.asarray(col.data[:n])[valid]
            if col.type is EValueType.string:
                codes = data
                # Long payloads (hunk-bound blobs) must not ride into the
                # meta verbatim — a 2KB value would re-inline what the
                # hunk store just externalized.  min truncates to a prefix
                # (a prefix is ≤ the value, still a lower bound); max
                # needs a prefix SUCCESSOR to stay an upper bound.
                entry["min"] = bytes(
                    col.dictionary[int(codes.min())])[:_STAT_STRING_CAP]
                entry["max"] = _string_stat_upper(
                    bytes(col.dictionary[int(codes.max())]))
            elif col.type is EValueType.boolean:
                entry["min"] = bool(data.min())
                entry["max"] = bool(data.max())
            elif col.type is EValueType.double:
                entry["min"] = float(data.min())
                entry["max"] = float(data.max())
            else:
                entry["min"] = int(data.min())
                entry["max"] = int(data.max())
        # Bounded 64-byte distinct-count sketch (cost-based join
        # planning reads NDV off metadata; merges across chunks by
        # register max — merge_column_stats).
        entry["ndv_sketch"] = column_ndv_sketch(col, n)
        out[name] = entry
    # Not a column: per-chunk row count rides the stats so metadata-only
    # consumers (chunk merger sizing) never decode the chunk.  "$" can
    # never collide with a column name, and chunk_may_match looks
    # columns up by name so it skips this key.
    out["$row_count"] = n
    return out


def concat_chunks(chunks: Sequence[ColumnarChunk]) -> ColumnarChunk:
    """Concatenate chunks of identical schema into one (device concat + repad)."""
    if not chunks:
        raise YtError("concat_chunks: empty input")
    if len(chunks) == 1:
        return chunks[0]
    schema = chunks[0].schema
    for c in chunks[1:]:
        if c.schema != schema:
            raise YtError("concat_chunks: schema mismatch",
                          code=EErrorCode.ChunkFormatError)
    total = sum(c.row_count for c in chunks)
    cap = pad_capacity(max(total, 1))
    columns: dict[str, Column] = {}
    for col_schema in schema:
        name = col_schema.name
        cols = [c.column(name) for c in chunks]
        vocab = None
        if col_schema.type is EValueType.string:
            cols, vocab = unify_dictionaries(cols)
        data_parts, valid_parts = [], []
        for chunk, col in zip(chunks, cols):
            data_parts.append(col.data[: chunk.row_count])
            valid_parts.append(col.valid[: chunk.row_count])
        dt = _plane_dtype(col_schema.type)
        trailing = (col_schema.type.dim,) \
            if isinstance(col_schema.type, VectorType) else ()
        data = jnp.zeros((cap,) + trailing, dtype=dt).at[:total].set(
            jnp.concatenate(data_parts))
        valid = jnp.zeros(cap, dtype=bool).at[:total].set(jnp.concatenate(valid_parts))
        host_values = None
        if col_schema.type is EValueType.any:
            host_values = []
            for chunk, col in zip(chunks, cols):
                host_values.extend((col.host_values or [])[: chunk.row_count])
            host_values += [None] * (cap - total)
        columns[name] = Column(type=col_schema.type, data=data, valid=valid,
                               dictionary=vocab, host_values=host_values)
    return ColumnarChunk(schema=schema, row_count=total, columns=columns)
