"""Vectorized MVCC: columnar version resolution on the XLA backbone.

Ref: versioned_row_merger.h / versioned_chunk_reader — the reference
resolves visibility with a per-row k-way heap merge + per-column JIT'd
loops.  Here the whole versioned read path is ONE compiled pipeline over
static-capacity planes, the same discipline the query engine already
follows (SURVEY §7 / the compiled-query-pipeline argument in PAPERS.md):

  1. Every source (versioned snapshot chunk, dynamic store ingested to
     planes once per mutation generation) concatenates on device.
  2. One packed u32 sort orders versions by (key asc, timestamp desc) —
     the primitives are `ops/segments.py`'s packed key encoding + stable
     radix/network argsort shared with the window subsystem.
  3. Visibility is segmented-scan algebra over the sorted planes:
     timestamp filtering is a compare, tombstone bounding is a segmented
     running-OR, per-column newest-written fill is a segmented index-min
     + gather.  No Python touches a row.

Three entry points share the machinery (compiled once per
(versioned-schema, capacity-bucket), cached process-wide):

  visible_chunk      read_snapshot: versions → the select-input chunk
  sorted_versioned_chunk  flush: stores → one (key, -ts)-ordered chunk
  retained_chunk     major compaction: versions ≤ retention collapse to
                     one consolidated per-column base version per key

The Python merge loops in tablet/tablet.py (`_mvcc_select`,
`_drop_superseded`) remain as the reference oracles: property tests
assert bit-exact row parity between the two implementations.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ytsaurus_tpu.chunks.columnar import Column, ColumnarChunk, pad_capacity
from ytsaurus_tpu.ops.segments import (
    compact_mask,
    pack_key_planes_bits,
    segment_end_index,
    segment_scan,
    stable_argsort_u32,
)
from ytsaurus_tpu.schema import EValueType, TableSchema

# (kind, versioned-schema key, capacity) → jitted program.  Capacity
# buckets are powers of two (chunks/columnar.pad_capacity), so the cache
# stays bounded the same way the evaluator's compile cache does.
_PROGRAMS: dict = {}


def _schema_key(schema: TableSchema) -> tuple:
    return tuple(
        (c.name, c.type.value,
         c.sort_order.value if c.sort_order is not None else None)
        for c in schema)


def supports(schema: TableSchema) -> bool:
    """`any`-typed payloads live host-side (opaque to device compute);
    tablets carrying them keep the Python reference merge."""
    return not any(c.type is EValueType.any for c in schema)


def _comparable(data: jax.Array, valid: jax.Array) -> jax.Array:
    """Plane canonicalized for ordering/equality: invalid rows zeroed
    (null == null regardless of plane garbage) and -0.0 folded into +0.0
    so keys the host comparator calls equal land in one segment."""
    if data.dtype == jnp.bool_:
        data = data.astype(jnp.int8)
    if jnp.issubdtype(data.dtype, jnp.floating):
        data = data + 0.0
    return jnp.where(valid, data, jnp.zeros_like(data))


def _version_order(planes: dict, key_names: tuple, mask: jax.Array
                   ) -> jax.Array:
    """Stable permutation sorting versions by (key asc — nulls first —
    then timestamp desc), masked rows last.  Stability preserves the
    source concatenation order among duplicate (key, ts) versions, which
    is exactly the tie-break the Python reference's stable list sort
    applies."""
    items = [((~mask), jnp.ones_like(mask), False, 1)]
    for name in key_names:
        data, valid = planes[name]
        items.append((_comparable(data, valid), valid & mask, False, 64))
    ts_data, ts_valid = planes["$timestamp"]
    items.append((ts_data, ts_valid & mask, True, 64))
    words, bits = pack_key_planes_bits(items)
    return stable_argsort_u32(words, word_bits=bits)


def _key_starts(sorted_key_planes, s_mask: jax.Array) -> jax.Array:
    """Segment-start flags: row 0, any key change, masked transition."""
    change = s_mask != jnp.roll(s_mask, 1)
    for data, valid in sorted_key_planes:
        dz = _comparable(data, valid)
        change = change | (dz != jnp.roll(dz, 1)) | \
            (valid != jnp.roll(valid, 1))
    return change.at[0].set(True)


def _written_plane(s: dict, name: str) -> jax.Array:
    """Did each version STATE this column?  Mirrors tablet._written:
    an absent/null $w: flag means a whole-row write (legacy layout),
    only an explicit False means unwritten."""
    w_data, w_valid = s["$w:" + name]
    return jnp.where(w_valid, w_data, jnp.ones_like(w_data))


def _newest_written(s: dict, name: str, eligible: jax.Array,
                    starts: jax.Array, seg_end: jax.Array,
                    iota: jax.Array):
    """Per row: (data, valid) of its key's newest eligible version that
    wrote `name` — a segmented index-min over candidate rows + gather.
    Rows of one segment all read the same answer."""
    cap = iota.shape[0]
    data, valid = s[name]
    cand = eligible & _written_plane(s, name)
    cand_idx = jnp.where(cand, iota, jnp.full(cap, cap, dtype=jnp.int32))
    first_idx = segment_scan("min", cand_idx, starts)[seg_end]
    has = first_idx < cap
    idx = jnp.clip(first_idx, 0, cap - 1)
    return data[idx], has & valid[idx], has


def _build_visible(key_names: tuple, value_names: tuple, capacity: int):
    """read_snapshot program: versioned planes → visible-row planes (in
    key order, compacted to the front) + row count."""

    def run(planes, row_count, read_ts):
        iota = jnp.arange(capacity, dtype=jnp.int32)
        mask = iota < row_count
        perm = _version_order(planes, key_names, mask)
        s = {name: (d[perm], v[perm]) for name, (d, v) in planes.items()}
        s_mask = mask[perm]
        starts = _key_starts([s[k] for k in key_names], s_mask)
        seg_end = segment_end_index(starts)

        ts_data, _ = s["$timestamp"]
        tomb_data, tomb_valid = s["$tombstone"]
        tomb = tomb_data & tomb_valid
        eligible = s_mask & (ts_data <= read_ts)
        # Newest tombstone ≤ read_ts bounds the merge: a segmented
        # running-OR marks every version at/after (older than) it dead.
        dead = segment_scan(
            "max", (eligible & tomb).astype(jnp.int8), starts) > 0
        in_merge = eligible & ~dead
        # One output row per key with surviving writes; its planes are
        # gathered at the key's NEWEST surviving write (the leader).
        seen = segment_scan("sum", in_merge.astype(jnp.int32), starts)
        leader = in_merge & (seen == 1)

        out = {name: s[name] for name in key_names}
        for name in value_names:
            data, valid, _ = _newest_written(s, name, in_merge, starts,
                                             seg_end, iota)
            out[name] = (data, valid)
        order, count = compact_mask(leader)
        emitted = jnp.arange(capacity, dtype=jnp.int64) < count
        out = {name: (d[order], v[order] & emitted)
               for name, (d, v) in out.items()}
        return out, count

    return run


def _build_sorted(key_names: tuple, capacity: int):
    """flush program: one stable (key, -ts) sort, planes gathered."""

    def run(planes, row_count):
        iota = jnp.arange(capacity, dtype=jnp.int32)
        mask = iota < row_count
        perm = _version_order(planes, key_names, mask)
        return {name: (d[perm], v[perm])
                for name, (d, v) in planes.items()}

    return run


def _build_retained(key_names: tuple, value_names: tuple, capacity: int):
    """Major-compaction program (`_drop_superseded` semantics): versions
    newer than the retention timestamp pass through; versions at/below
    it collapse into ONE consolidated base version per key (per-column
    merged visible state at the retention cut), or nothing when that
    state is a delete."""

    def run(planes, row_count, retention_ts):
        iota = jnp.arange(capacity, dtype=jnp.int32)
        mask = iota < row_count
        perm = _version_order(planes, key_names, mask)
        s = {name: (d[perm], v[perm]) for name, (d, v) in planes.items()}
        s_mask = mask[perm]
        starts = _key_starts([s[k] for k in key_names], s_mask)
        seg_end = segment_end_index(starts)

        ts_data, ts_valid = s["$timestamp"]
        tomb_data, tomb_valid = s["$tombstone"]
        tomb = tomb_data & tomb_valid
        is_base = s_mask & (ts_data <= retention_ts)
        kept = s_mask & ~is_base
        dead = segment_scan(
            "max", (is_base & tomb).astype(jnp.int8), starts) > 0
        in_base = is_base & ~dead
        # The base versions sort after every kept version of their key
        # (lower timestamps), so the leader row — the newest surviving
        # base write — is where the consolidated version lands, already
        # in (key, -ts) output order.
        seen = segment_scan("sum", in_base.astype(jnp.int32), starts)
        leader = in_base & (seen == 1)

        out = {name: s[name] for name in key_names}
        out["$timestamp"] = (ts_data, ts_valid)   # leader keeps base_ts
        out["$tombstone"] = (jnp.where(leader, False, tomb_data),
                             tomb_valid | leader)
        for name in value_names:
            data, valid = s[name]
            base_d, base_v, _ = _newest_written(s, name, in_base, starts,
                                                seg_end, iota)
            out[name] = (jnp.where(leader, base_d, data),
                         jnp.where(leader, base_v, valid))
            w_data, w_valid = s["$w:" + name]
            # Consolidated versions STATE every column explicitly.
            out["$w:" + name] = (w_data | leader, w_valid | leader)
        emit = kept | leader
        order, count = compact_mask(emit)
        emitted = jnp.arange(capacity, dtype=jnp.int64) < count
        out = {name: (d[order], v[order] & emitted)
               for name, (d, v) in out.items()}
        return out, count

    return run


def _program(kind: str, merged: ColumnarChunk, key_names: tuple,
             value_names: tuple):
    key = (kind, _schema_key(merged.schema), merged.capacity)
    fn = _PROGRAMS.get(key)
    if fn is None:
        if kind == "visible":
            builder = _build_visible(key_names, value_names,
                                     merged.capacity)
        elif kind == "sorted":
            builder = _build_sorted(key_names, merged.capacity)
        else:
            builder = _build_retained(key_names, value_names,
                                      merged.capacity)
        fn = _PROGRAMS[key] = jax.jit(builder)
    return fn


def _planes(chunk: ColumnarChunk) -> dict:
    return {name: (col.data, col.valid)
            for name, col in chunk.columns.items()}


def _emit_chunk(schema: TableSchema, out_planes: dict, n: int,
                source: ColumnarChunk) -> ColumnarChunk:
    """Wrap program output planes into a chunk, shrunk to the tightest
    capacity bucket so downstream compile caches key on output size, not
    on how many superseded versions fed the merge."""
    columns = {}
    for c in schema:
        data, valid = out_planes[c.name]
        columns[c.name] = Column(
            type=c.type, data=data, valid=valid,
            dictionary=source.columns[c.name].dictionary)
    chunk = ColumnarChunk(schema=schema, row_count=n, columns=columns)
    tight = pad_capacity(max(n, 1))
    if tight < chunk.capacity:
        chunk = chunk.with_capacity(tight)
    return chunk


def visible_chunk(merged: ColumnarChunk, table_schema: TableSchema,
                  timestamp: int) -> ColumnarChunk:
    """MVCC merge at `timestamp` over a concatenated versioned chunk →
    the select-input ColumnarChunk (plain table schema, key order)."""
    key_names = tuple(table_schema.key_column_names)
    value_names = tuple(c.name for c in table_schema
                        if c.sort_order is None)
    fn = _program("visible", merged, key_names, value_names)
    out, count = fn(_planes(merged), np.int64(merged.row_count),
                    np.int64(timestamp))
    chunk = _emit_chunk(table_schema.to_unsorted(), out, int(count), merged)
    # The merge emits key order — seal it so ORDER BY <key prefix> over a
    # tablet snapshot skips the packed-key sort (ISSUE 19 layout sealing).
    return dataclasses.replace(chunk, sorted_by=key_names)


def sorted_versioned_chunk(merged: ColumnarChunk,
                           table_schema: TableSchema) -> ColumnarChunk:
    """Stable (key asc, ts desc) ordering of a versioned chunk — the
    flush sort, without materializing rows."""
    key_names = tuple(table_schema.key_column_names)
    fn = _program("sorted", merged, key_names, ())
    out = fn(_planes(merged), np.int64(merged.row_count))
    return _emit_chunk(merged.schema, out, merged.row_count, merged)


def retained_chunk(merged: ColumnarChunk, table_schema: TableSchema,
                   retention_timestamp: int) -> ColumnarChunk:
    """Major compaction over a concatenated versioned chunk: row-exact
    `_drop_superseded` on device.  row_count == 0 means every version
    was superseded by a delete — the caller drops the chunk."""
    key_names = tuple(table_schema.key_column_names)
    value_names = tuple(c.name for c in table_schema
                        if c.sort_order is None)
    fn = _program("retained", merged, key_names, value_names)
    out, count = fn(_planes(merged), np.int64(merged.row_count),
                    np.int64(retention_timestamp))
    return _emit_chunk(merged.schema, out, int(count), merged)
