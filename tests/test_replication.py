"""Table replication: async replicator, sync fanout, tracker, fallback.

Ref model: replicated dynamic tables (tablet_node/table_replicator.cpp),
sync-replica commit fanout (ytlib/api/native/transaction.cpp:737-830),
replicated_table_tracker mode flips, hedged replica fallback reads.
"""

import pytest

from ytsaurus_tpu import YtError
from ytsaurus_tpu.client import connect
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.tablet.replication import (
    ReplicatedTableTracker,
    TableReplicator,
)

SCHEMA = TableSchema.make([
    ("key", "int64", "ascending"), ("a", "string"), ("b", "int64")],
    unique_keys=True)


def make_table(client, path):
    client.create("table", path, recursive=True,
                  attributes={"schema": SCHEMA, "dynamic": True})
    client.mount_table(path)


@pytest.fixture
def upstream(tmp_path):
    return connect(str(tmp_path / "up"))


@pytest.fixture
def downstream_root(tmp_path):
    return str(tmp_path / "down")


def test_async_replication_roundtrip(upstream, downstream_root):
    down = connect(downstream_root)
    make_table(upstream, "//t")
    make_table(down, "//r")
    rid = upstream.create_table_replica(
        "//t", "//r", cluster_root=downstream_root, mode="async")
    upstream.insert_rows("//t", [{"key": 1, "a": "x", "b": 10},
                                 {"key": 2, "a": "y", "b": 20}])
    repl = TableReplicator(upstream)
    assert repl.lag("//t", rid) == 2
    assert repl.replicate_step("//t") == {rid: 2}
    assert repl.lag("//t", rid) == 0
    # The replicator's cached remote client shares the tablet state.
    rc = repl.replica_client(downstream_root)
    assert rc.lookup_rows("//r", [(1,), (2,)]) == [
        {"key": 1, "a": b"x", "b": 10},
        {"key": 2, "a": b"y", "b": 20}]
    # Idempotent: nothing new to pull.
    assert repl.replicate_step("//t") == {rid: 0}


def test_async_replication_partial_writes_and_deletes(upstream,
                                                      downstream_root):
    down = connect(downstream_root)
    make_table(upstream, "//t")
    make_table(down, "//r")
    rid = upstream.create_table_replica(
        "//t", "//r", cluster_root=downstream_root, mode="async")
    repl = TableReplicator(upstream)
    upstream.insert_rows("//t", [{"key": 1, "a": "x", "b": 1}])
    repl.replicate_step("//t")
    # Partial (update-mode) write replicates as a partial write.
    upstream.insert_rows("//t", [{"key": 1, "b": 2}], update=True)
    upstream.insert_rows("//t", [{"key": 3, "a": "z", "b": 3}])
    upstream.delete_rows("//t", [(3,)])
    repl.replicate_step("//t")
    rc = repl.replica_client(downstream_root)
    assert rc.lookup_rows("//r", [(1,)]) == [{"key": 1, "a": b"x", "b": 2}]
    assert rc.lookup_rows("//r", [(3,)]) == [None]
    assert repl.lag("//t", rid) == 0


def test_sync_replica_commit_fanout(upstream, downstream_root):
    down = connect(downstream_root)
    make_table(upstream, "//t")
    make_table(down, "//r")
    upstream.create_table_replica(
        "//t", "//r", cluster_root=downstream_root, mode="sync")
    upstream.insert_rows("//t", [{"key": 7, "a": "s", "b": 70}])
    # Visible on the replica immediately, no replicator pass needed.
    rc = upstream.table_replicator.replica_client(downstream_root)
    assert rc.lookup_rows("//r", [(7,)]) == [{"key": 7, "a": b"s", "b": 70}]
    upstream.delete_rows("//t", [(7,)])
    assert rc.lookup_rows("//r", [(7,)]) == [None]


def test_broken_sync_replica_fails_write(upstream, downstream_root):
    down = connect(downstream_root)
    make_table(upstream, "//t")
    make_table(down, "//r")
    upstream.create_table_replica(
        "//t", "//r", cluster_root=downstream_root, mode="sync")
    rc = upstream.table_replicator.replica_client(downstream_root)
    rc.unmount_table("//r")
    with pytest.raises(YtError):
        upstream.insert_rows("//t", [{"key": 1, "a": "x", "b": 1}])
    # Upstream must not have committed either (atomic fanout).
    upstream_rows = upstream.select_rows("key FROM [//t]")
    assert upstream_rows == []


def test_tracker_demotes_and_promotes(upstream, downstream_root):
    down = connect(downstream_root)
    make_table(upstream, "//t")
    make_table(down, "//r1")
    make_table(down, "//r2")
    rid1 = upstream.create_table_replica(
        "//t", "//r1", cluster_root=downstream_root, mode="sync")
    rid2 = upstream.create_table_replica(
        "//t", "//r2", cluster_root=downstream_root, mode="async")
    upstream.insert_rows("//t", [{"key": 1, "a": "x", "b": 1}])
    repl = TableReplicator(upstream)
    tracker = ReplicatedTableTracker(repl)
    # Break the sync replica: tracker must demote it and promote the
    # async one (after catching it up).
    rc = repl.replica_client(downstream_root)
    rc.unmount_table("//r1")
    result = tracker.step("//t")
    assert result["health"][rid1] is not None
    replicas = upstream.get_table_replicas("//t")
    assert replicas[rid1]["mode"] == "async"
    assert replicas[rid2]["mode"] == "sync"
    assert result["sync_count"] == 1
    # The promoted replica was caught up before the flip.
    assert rc.lookup_rows("//r2", [(1,)]) == [{"key": 1, "a": b"x", "b": 1}]


def test_lookup_replica_fallback(upstream, downstream_root):
    down = connect(downstream_root)
    make_table(upstream, "//t")
    make_table(down, "//r")
    upstream.create_table_replica(
        "//t", "//r", cluster_root=downstream_root, mode="async")
    upstream.insert_rows("//t", [{"key": 5, "a": "f", "b": 50}])
    upstream.table_replicator.replicate_step("//t")
    upstream.unmount_table("//t")
    with pytest.raises(YtError):
        upstream.lookup_rows("//t", [(5,)])
    assert upstream.lookup_rows("//t", [(5,)], replica_fallback=True) == [
        {"key": 5, "a": b"f", "b": 50}]


def test_lookup_replica_hedging_bounds_slow_replica(upstream,
                                                    downstream_root,
                                                    monkeypatch):
    """One slow replica must not serialize the fallback: the hedged race
    arms the next replica after lookup_hedging_delay, so wall-clock is
    bounded by ~delay + healthy-replica latency (VERDICT r2 #7)."""
    import time as _time

    down = connect(downstream_root)
    make_table(upstream, "//t")
    make_table(down, "//r_slow")
    make_table(down, "//r_fast")
    upstream.create_table_replica(
        "//t", "//r_slow", cluster_root=downstream_root, mode="async")
    upstream.create_table_replica(
        "//t", "//r_fast", cluster_root=downstream_root, mode="async")
    upstream.insert_rows("//t", [{"key": 7, "a": "h", "b": 70}])
    upstream.table_replicator.replicate_step("//t")
    upstream.unmount_table("//t")

    # Make whichever replica RANKS FIRST the slow one, so a sequential
    # fallback would necessarily eat the full slow latency.
    from ytsaurus_tpu.tablet import replication as repl
    descs = repl.replica_descriptors(upstream, "//t")
    ranked = sorted(descs.values(),
                    key=lambda i: (i.get("mode") != "sync",
                                   -int(i.get("last_replicated_ts", 0))))
    slow_path = ranked[0]["path"]
    slow_latency = 2.0
    rc = upstream.table_replicator.replica_client(downstream_root)
    real_lookup = rc.lookup_rows

    def flaky_lookup(path, keys, **kw):
        if path == slow_path:
            _time.sleep(slow_latency)
        return real_lookup(path, keys, **kw)

    monkeypatch.setattr(rc, "lookup_rows", flaky_lookup)
    upstream.lookup_hedging_delay = 0.05

    t0 = _time.monotonic()
    got = upstream.lookup_rows("//t", [(7,)], replica_fallback=True)
    elapsed = _time.monotonic() - t0
    assert got == [{"key": 7, "a": b"h", "b": 70}]
    # Bounded by the hedging delay + fast replica, far under slow_latency
    # (sequential fallback through the slow replica would take >= 2s when
    # the slow replica ranks first; hedged it costs at most ~delay).
    assert elapsed < slow_latency, f"hedging did not bound tail: {elapsed:.2f}s"


def test_sync_checkpoint_advances_under_caller_tx(upstream,
                                                  downstream_root):
    down = connect(downstream_root)
    make_table(upstream, "//t")
    make_table(down, "//r")
    rid = upstream.create_table_replica(
        "//t", "//r", cluster_root=downstream_root, mode="sync")
    tx = upstream.start_transaction()
    upstream.insert_rows("//t", [{"key": 1, "a": "x", "b": 1}], tx=tx)
    upstream.commit_transaction(tx)
    # Checkpoint advanced: demoting to async must not replay the write.
    repl = TableReplicator(upstream)
    assert repl.lag("//t", rid) == 0
    upstream.alter_table_replica("//t", rid, mode="async")
    assert repl.replicate_step("//t") == {rid: 0}


def test_same_cluster_replica(upstream):
    make_table(upstream, "//t")
    make_table(upstream, "//backup")
    rid = upstream.create_table_replica("//t", "//backup", mode="async")
    upstream.insert_rows("//t", [{"key": 1, "a": "x", "b": 1}])
    repl = TableReplicator(upstream)
    repl.replicate_step("//t")
    assert upstream.lookup_rows("//backup", [(1,)]) == [
        {"key": 1, "a": b"x", "b": 1}]
    assert repl.lag("//t", rid) == 0
