"""Query serving plane tests (ISSUE 3): admission control (weighted
slots, bounded queue, ThrottledError with retry_after), deadline
propagation and mid-plan cancellation, continuous lookup micro-batching
(correctness under concurrency: no lost/duplicated/misordered
responses), throttle-aware retry channels, serving metrics on /metrics,
and a seeded failpoint soak over the `serving.admit` /
`serving.batch_flush` sites."""

import threading
import time

import pytest

from ytsaurus_tpu.client import connect
from ytsaurus_tpu.config import ServingConfig
from ytsaurus_tpu.errors import (
    EErrorCode,
    ThrottledError,
    YtError,
    retry_after_hint,
)
from ytsaurus_tpu.query.serving import CancellationToken, QueryGateway
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.utils import failpoints

N_ROWS = 240


# Module-scoped: one shared cluster keeps the quick pass inside the
# tier-1 budget (tests only read //serve, and counter assertions use
# deltas).  The remount test re-mounts the same table, which is safe.
@pytest.fixture(scope="module")
def client(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("serving")
    c = connect(str(tmp_path / "cluster"))
    schema = TableSchema.make(
        [("k", "int64", "ascending"), ("v", "int64"), ("s", "string")],
        unique_keys=True)
    c.create("table", "//serve",
             attributes={"schema": schema, "dynamic": True,
                         "pivot_keys": [[80], [160]]}, recursive=True)
    c.mount_table("//serve")
    c.insert_rows("//serve", [{"k": i, "v": i * 7, "s": f"s{i}"}
                              for i in range(N_ROWS)])
    return c


# --- cancellation tokens ------------------------------------------------------


def test_token_deadline_and_cancel():
    token = CancellationToken.with_timeout(None)
    token.check()                          # no deadline: never raises
    assert token.remaining() is None

    token = CancellationToken.with_timeout(30.0, pool="prod")
    token.check()
    assert 0 < token.remaining() <= 30.0

    token = CancellationToken.with_timeout(1e-9)
    time.sleep(0.001)
    with pytest.raises(YtError) as err:
        token.check()
    assert err.value.code == EErrorCode.DeadlineExceeded

    token = CancellationToken.with_timeout(30.0)
    token.cancel("user abort")
    with pytest.raises(YtError) as err:
        token.check()
    assert err.value.code == EErrorCode.Canceled


# --- admission control --------------------------------------------------------


def _held_slot(gateway, pool=None):
    """Occupy one slot on a background thread; returns (release, thread)."""
    hold = threading.Event()
    entered = threading.Event()

    def busy(token):
        entered.set()
        hold.wait(5.0)
        return None

    thread = threading.Thread(
        target=lambda: gateway.run_select(busy, pool=pool), daemon=True)
    thread.start()
    assert entered.wait(5.0)
    return hold.set, thread


def test_admission_overflow_throttles_with_retry_after():
    gateway = QueryGateway(ServingConfig(slots=1, max_queue=0))
    release, thread = _held_slot(gateway)
    try:
        with pytest.raises(ThrottledError) as err:
            gateway.run_select(lambda token: None)
        assert err.value.code == EErrorCode.RequestThrottled
        assert err.value.retry_after > 0
        assert retry_after_hint(err.value) == err.value.retry_after
    finally:
        release()
        thread.join(timeout=5)
    snap = gateway.snapshot()["pools"]["default"]
    assert snap["rejected"] == 1


def test_admission_queue_waits_for_slot():
    gateway = QueryGateway(ServingConfig(slots=1, max_queue=4))
    release, thread = _held_slot(gateway)
    results = []
    waiter = threading.Thread(
        target=lambda: results.append(
            gateway.run_select(lambda token: "ran")), daemon=True)
    waiter.start()
    time.sleep(0.05)
    assert not results               # queued behind the held slot
    release()
    waiter.join(timeout=5)
    thread.join(timeout=5)
    assert results == ["ran"]
    assert gateway.snapshot()["pools"]["default"]["admitted"] == 2


def test_admission_deadline_expires_in_queue():
    gateway = QueryGateway(ServingConfig(slots=1, max_queue=4))
    release, thread = _held_slot(gateway)
    try:
        with pytest.raises(YtError) as err:
            gateway.run_select(lambda token: None, timeout=0.05)
        assert err.value.code == EErrorCode.DeadlineExceeded
    finally:
        release()
        thread.join(timeout=5)
    assert gateway.snapshot()["pools"]["default"]["expired"] == 1


def test_weighted_pools_and_unknown_pool_falls_back():
    config = ServingConfig(slots=8, pools={"default": 1.0, "heavy": 3.0})
    gateway = QueryGateway(config)
    pools = gateway.snapshot()["pools"]
    assert pools["heavy"]["weight"] == 3.0
    assert pools["default"]["weight"] == 1.0
    # Idle pools have no demand, so no fair share is reserved (work-
    # conserving: either pool may burst to all 8 slots while alone).
    assert pools["heavy"]["fair_slots"] == 0.0
    # Unknown pool name routes to default_pool instead of failing.
    assert gateway.run_select(lambda token: "ok", pool="nope") == "ok"
    assert gateway.snapshot()["pools"]["default"]["admitted"] == 1


def test_serving_config_validation():
    with pytest.raises(YtError):
        ServingConfig(pools={"default": -1.0})
    with pytest.raises(YtError):
        ServingConfig(pools={"a": 1.0}, default_pool="b")


def test_fair_share_conservation_and_isolation_under_storm():
    """8 threads storm two pools at once: the slot budget is never
    exceeded (conservation), the greedy pool's hard limit holds, every
    request completes, and the guaranteed pool's admission waits stay
    far below the greedy pool's (isolation)."""
    gateway = QueryGateway(ServingConfig(
        slots=3, max_queue=1000, default_pool="prod",
        pools={"prod": 3.0, "batch": 1.0}, pool_limits={"batch": 1}))
    lock = threading.Lock()
    running = {"total": 0, "prod": 0, "batch": 0,
               "max_total": 0, "max_batch": 0}
    waits = {"prod": [], "batch": []}

    def make_fn(pool):
        def fn(token):
            with lock:
                running["total"] += 1
                running[pool] += 1
                running["max_total"] = max(running["max_total"],
                                           running["total"])
                if pool == "batch":
                    running["max_batch"] = max(running["max_batch"],
                                               running["batch"])
            time.sleep(0.002)
            with lock:
                running["total"] -= 1
                running[pool] -= 1
        return fn

    def storm(pool, count):
        fn = make_fn(pool)
        for _ in range(count):
            t0 = time.monotonic()
            gateway.run_select(fn, pool=pool, timeout=30.0)
            with lock:
                waits[pool].append(time.monotonic() - t0)

    threads = [threading.Thread(target=storm, args=("prod", 30),
                                daemon=True) for _ in range(2)] + \
              [threading.Thread(target=storm, args=("batch", 30),
                                daemon=True) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert running["max_total"] <= 3            # conservation
    assert running["max_batch"] <= 1            # hard pool limit
    pools = gateway.snapshot()["pools"]
    assert pools["prod"]["admitted"] == 60      # nothing lost
    assert pools["batch"]["admitted"] == 180
    assert pools["prod"]["rejected"] == 0
    assert pools["batch"]["rejected"] == 0
    # Isolation: 6 batch threads fight over 1 slot while 2 prod threads
    # share 2 — prod's mean wall time must sit well below batch's.
    prod_mean = sum(waits["prod"]) / len(waits["prod"])
    batch_mean = sum(waits["batch"]) / len(waits["batch"])
    assert batch_mean > prod_mean * 2, (prod_mean, batch_mean)


def test_dynamic_pool_resize_admits_waiters_mid_traffic():
    """apply_config mid-traffic: a queued waiter must be admitted the
    moment the slot budget widens — without waiting for the held slot
    to release — and freshly declared pools appear live."""
    gateway = QueryGateway(ServingConfig(slots=1, max_queue=100))
    release, thread = _held_slot(gateway)
    results = []
    waiter = threading.Thread(
        target=lambda: results.append(
            gateway.run_select(lambda token: "ran")), daemon=True)
    waiter.start()
    time.sleep(0.05)
    assert not results                   # queued behind the held slot
    gateway.admission.apply_config(ServingConfig(
        slots=4, max_queue=100,
        pools={"default": 1.0, "fresh": 2.0}))
    waiter.join(timeout=5)
    assert results == ["ran"]            # admitted by the resize alone
    pools = gateway.snapshot()["pools"]
    assert pools["fresh"]["weight"] == 2.0
    release()
    thread.join(timeout=5)


# --- brown-out ladder ---------------------------------------------------------


def test_brownout_rung1_staleness_bound_and_disengage():
    """Rung 1 rides the pool's declared staleness bound down on the
    admitted token; once the queue drains, the snapshot heartbeat walks
    the ladder back to rung 0 and tallies one engagement."""
    gateway = QueryGateway(ServingConfig(
        slots=1, max_queue=10, brownout_rung1_seconds=1e-9,
        brownout_rung2_seconds=1e9, brownout_min_dwell_seconds=0.0,
        staleness_bounds={"default": 7.5}))
    release, thread = _held_slot(gateway)
    seen = []
    waiter = threading.Thread(
        target=lambda: seen.append(gateway.run_select(
            lambda token: (token.rung, token.staleness_bound))),
        daemon=True)
    waiter.start()
    time.sleep(0.05)                     # queued -> pressure > rung 1
    release()
    waiter.join(timeout=5)
    thread.join(timeout=5)
    assert seen == [(1, 7.5)]
    snap = gateway.snapshot()["admission"]["brownout"]
    assert snap["rung"] == 0             # heartbeat walked it back down
    assert snap["engaged"] == 1
    assert snap["transitions"] >= 2
    assert snap["log"][0]["to"] == 1


def test_brownout_rung2_sheds_new_load_with_retry_after():
    gateway = QueryGateway(ServingConfig(
        slots=1, max_queue=10, brownout_rung1_seconds=1e-9,
        brownout_rung2_seconds=1e-9, brownout_min_dwell_seconds=0.0))
    release, thread = _held_slot(gateway)
    waiter = threading.Thread(
        target=lambda: gateway.run_select(lambda token: None,
                                          timeout=10.0), daemon=True)
    waiter.start()
    time.sleep(0.05)                     # one waiter -> pressure > 0
    try:
        with pytest.raises(ThrottledError) as err:
            gateway.run_select(lambda token: None)
        assert err.value.retry_after > 0
        assert err.value.attributes["brownout_rung"] == 2
        assert gateway.snapshot()["admission"]["brownout"]["shed"] == 1
    finally:
        release()
        waiter.join(timeout=5)
        thread.join(timeout=5)


# --- lookup micro-batching ----------------------------------------------------


def test_batch_probe_covers_keys_evicted_mid_call(tmp_path):
    """Regression: a key that was a row-cache HIT when the batched
    chunk probe was computed can be EVICTED by the same call's own
    cache insertions; reaching it later must fall back to the per-key
    chunk read, not treat the (unprobed) batch result as 'no rows'."""
    from ytsaurus_tpu.chunks.store import FsChunkStore
    from ytsaurus_tpu.tablet.tablet import Tablet
    from ytsaurus_tpu.tablet.transactions import TransactionManager

    schema = TableSchema.make([("k", "int64", "ascending"),
                               ("v", "int64")], unique_keys=True)
    tablet = Tablet(schema, FsChunkStore(str(tmp_path / "chunks")))
    txm = TransactionManager()
    tx = txm.start()
    txm.write_rows(tx, tablet, [{"k": i, "v": i} for i in range(32)])
    txm.commit(tx)
    tablet.flush()                       # rows live in chunks
    tablet.row_cache_capacity = 4
    tablet.lookup_rows([(0,), (1,), (2,), (3,)])      # K=0 cached (LRU)
    rows = tablet.lookup_rows([(10,), (11,), (12,), (13,), (14,), (0,)])
    assert rows[-1] == {"k": 0, "v": 0}
    assert tablet.lookup_rows([(0,)]) == [{"k": 0, "v": 0}]


def test_pad_needles_pow2_buckets():
    from ytsaurus_tpu.tablet.tablet import _pad_needles
    assert _pad_needles([1, 2, 3], 8) == [1, 2, 3, 3, 3, 3, 3, 3]
    assert _pad_needles([1] * 8, 8) == [1] * 8
    assert len(_pad_needles(list(range(9)), 8)) == 16
    assert _pad_needles([5], 1) == [5]


def test_replica_fallback_surfaces_serving_errors(client):
    """A throttle / lapsed deadline is a serving-plane verdict, not
    primary unavailability: replica_fallback must surface it instead of
    hedging every replica."""
    with failpoints.active("serving.admit=error", seed=1):
        with pytest.raises(ThrottledError):
            client.lookup_rows("//serve", [(1,)], replica_fallback=True)


def test_lookup_duplicates_missing_and_column_filter(client):
    rows = client.lookup_rows(
        "//serve", [(3,), (9999,), (3,), (7,)], column_names=["v"])
    assert rows[0] == {"v": 21}
    assert rows[1] is None
    assert rows[2] == {"v": 21}
    assert rows[3] == {"v": 49}
    # Callers get private row copies (a shared batch result must not
    # leak mutations across requests).
    a = client.lookup_rows("//serve", [(5,)])[0]
    a["v"] = -1
    assert client.lookup_rows("//serve", [(5,)])[0]["v"] == 35


def test_concurrent_lookups_coalesce_and_stay_ordered(client):
    gateway = client.cluster.gateway
    before = gateway.snapshot()["lookup"]
    errors = []

    def worker(seed):
        try:
            for i in range(10):
                ks = [((seed * 31 + i * 7 + j) % N_ROWS,)
                      for j in range(1 + (seed + i) % 5)]
                rows = client.lookup_rows("//serve", ks)
                assert len(rows) == len(ks)
                for key, row in zip(ks, rows):
                    assert row["k"] == key[0] and row["v"] == key[0] * 7
        except Exception as exc:   # noqa: BLE001 — surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(s,), daemon=True)
               for s in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    after = gateway.snapshot()["lookup"]
    requests = after["requests"] - before["requests"]
    batches = after["batches"] - before["batches"]
    assert requests == 120
    # Coalescing happened: strictly fewer flushes than requests.
    assert 0 < batches < requests


def test_lookup_respects_remount(client):
    assert client.lookup_rows("//serve", [(1,)])[0]["v"] == 7
    client.unmount_table("//serve")
    client.mount_table("//serve")
    # The batcher's cached path context must notice the new tablets.
    assert client.lookup_rows("//serve", [(1,)])[0]["v"] == 7


def test_lookup_disabled_gateway_uses_direct_path(tmp_path):
    c = connect(str(tmp_path / "c2"))
    c.cluster.serving_config = ServingConfig(enabled=False)
    schema = TableSchema.make([("k", "int64", "ascending"),
                               ("v", "int64")], unique_keys=True)
    c.create("table", "//t", attributes={"schema": schema,
                                         "dynamic": True}, recursive=True)
    c.mount_table("//t")
    c.insert_rows("//t", [{"k": 1, "v": 10}])
    assert c.lookup_rows("//t", [(1,), (2,)]) == [{"k": 1, "v": 10}, None]
    assert c.cluster.gateway.snapshot()["lookup"]["requests"] == 0


# --- deadline propagation through execution -----------------------------------


class _CountingEvaluator:
    """Counts bottom-plan executions that actually ran (token passed)."""

    def __init__(self, inner):
        self.inner = inner
        self.executed = 0

    def run_plan(self, plan, chunk, foreign_chunks=None, stats=None,
                 token=None):
        out = self.inner.run_plan(plan, chunk, foreign_chunks,
                                  stats=stats, token=token)
        self.executed += 1
        return out


def test_deadline_aborts_before_remaining_shards():
    """Acceptance: a query past its deadline stops mid-plan — the
    remaining shards never execute (failpoint-injected delay makes the
    first shard consume the budget)."""
    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    from ytsaurus_tpu.query.builder import build_query
    from ytsaurus_tpu.query.coordinator import coordinate_and_execute
    from ytsaurus_tpu.query.engine.evaluator import Evaluator

    schema = TableSchema.make([("k", "int64"), ("v", "int64")])
    shards = [ColumnarChunk.from_rows(
        schema, [{"k": s * 10 + i, "v": i} for i in range(10)])
        for s in range(4)]
    plan = build_query("k, v FROM [//t] WHERE v >= 0", {"//t": schema})
    warm = Evaluator()
    coordinate_and_execute(plan, shards, evaluator=warm)   # compile once
    counting = _CountingEvaluator(warm)
    token = CancellationToken.with_timeout(0.15)
    with failpoints.active("query.shard_execute=delay:ms=120", seed=3):
        with pytest.raises(YtError) as err:
            coordinate_and_execute(plan, shards, evaluator=counting,
                                   token=token)
    assert err.value.code == EErrorCode.DeadlineExceeded
    assert counting.executed < len(shards)


def test_select_deadline_and_select_through_gateway(client):
    # Warm the compile cache so the timed run measures the deadline,
    # not XLA compilation.
    out = client.select_rows("k, v FROM [//serve] WHERE k < 5")
    assert len(out) == 5
    with failpoints.active("query.shard_execute=delay:ms=200", seed=1):
        t0 = time.monotonic()
        with pytest.raises(YtError) as err:
            client.select_rows("k, v FROM [//serve] WHERE k < 5",
                               timeout=0.08)
        elapsed = time.monotonic() - t0
    assert err.value.code == EErrorCode.DeadlineExceeded
    assert elapsed < 5.0          # aborted cooperatively, not run-out


def test_lookup_deadline_with_delayed_flush(client):
    client.lookup_rows("//serve", [(1,)])        # warm path context
    with failpoints.active("serving.batch_flush=delay:ms=400", seed=2):
        t0 = time.monotonic()
        with pytest.raises(YtError) as err:
            client.lookup_rows("//serve", [(2,)], timeout=0.1)
        elapsed = time.monotonic() - t0
    assert err.value.code == EErrorCode.DeadlineExceeded
    # Honored within tolerance: well before the injected 400ms delay
    # plus slack, and not before the deadline itself.
    assert 0.05 <= elapsed < 2.0


# --- throttle-aware retry channels --------------------------------------------


class _ScriptedChannel:
    """Stub channel: raises the scripted errors in order, then succeeds."""

    address = "stub:0"

    def __init__(self, errors):
        self.errors = list(errors)
        self.calls = 0

    def call(self, service, method, body=None, attachments=(),
             timeout=None, idempotent=True):
        self.calls += 1
        if self.errors:
            raise self.errors.pop(0)
        return {"ok": True}, []

    def close(self):
        pass


def test_retrying_channel_honors_retry_after():
    from ytsaurus_tpu.rpc.channel import RetryingChannel
    stub = _ScriptedChannel([ThrottledError(retry_after=0.12)])
    channel = RetryingChannel(stub)
    t0 = time.monotonic()
    body, _ = channel.call("svc", "m", idempotent=False)
    elapsed = time.monotonic() - t0
    assert body == {"ok": True}
    # Throttles retry even non-idempotent calls (never executed), and
    # the wait follows the hint, not the generic backoff curve.
    assert stub.calls == 2
    assert elapsed >= 0.1


def test_retrying_channel_deadline_exceeded_is_terminal():
    from ytsaurus_tpu.rpc.channel import RetryingChannel
    stub = _ScriptedChannel([
        YtError("deadline exceeded",
                code=EErrorCode.DeadlineExceeded)] * 5)
    channel = RetryingChannel(stub)
    with pytest.raises(YtError) as err:
        channel.call("svc", "m")
    assert err.value.code == EErrorCode.DeadlineExceeded
    assert stub.calls == 1


def test_retrying_channel_throttle_exhaustion_keeps_code():
    from ytsaurus_tpu.rpc.channel import RetryingChannel
    stub = _ScriptedChannel([ThrottledError(retry_after=0.001)] * 10)
    channel = RetryingChannel(stub, attempts=3, backoff=0.001)
    with pytest.raises(YtError) as err:
        channel.call("svc", "m")
    assert stub.calls == 3
    assert err.value.contains(EErrorCode.RequestThrottled)
    assert retry_after_hint(err.value) == 0.001


def test_retrying_channel_backoff_capped_by_deadline():
    """Regression (ISSUE 17): a throttle hinting a 30s wait against a
    0.2s caller deadline must sleep at most token.remaining() and then
    surface DeadlineExceeded promptly — never serve out the hint."""
    from ytsaurus_tpu.rpc.channel import RetryingChannel
    stub = _ScriptedChannel([ThrottledError(retry_after=30.0)] * 5)
    channel = RetryingChannel(stub, attempts=5)
    token = CancellationToken.with_timeout(0.2)
    t0 = time.monotonic()
    with pytest.raises(YtError) as err:
        channel.call("svc", "m", token=token)
    elapsed = time.monotonic() - t0
    assert err.value.code == EErrorCode.DeadlineExceeded
    assert stub.calls == 1               # one attempt, one capped sleep
    assert 0.15 <= elapsed < 2.0         # ~the deadline, not the hint


def test_retrying_channel_budget_exhaustion_fails_fast():
    from ytsaurus_tpu.rpc.channel import RetryingChannel, _RetryBudget
    stub = _ScriptedChannel([
        YtError("conn reset", code=EErrorCode.TransportError)] * 10)
    channel = RetryingChannel(stub, attempts=5, backoff=0.001)
    channel.retry_budget = _RetryBudget(1, 0.1)
    with pytest.raises(YtError) as err:
        channel.call("svc", "m")
    # One free failure + one budgeted retry, then the dry bucket fails
    # fast instead of serving out the remaining attempts.
    assert stub.calls == 2
    assert err.value.attributes["retry_budget_exhausted"] is True
    assert err.value.code == EErrorCode.PeerUnavailable
    snap = channel.retry_budget.snapshot()
    assert snap["spent"] == 1 and snap["exhausted"] == 1


def test_retry_budget_refills_on_success_only():
    from ytsaurus_tpu.rpc.channel import _RetryBudget
    budget = _RetryBudget(2, 0.5)
    assert budget.try_spend() and budget.try_spend()
    assert not budget.try_spend()        # dry
    budget.deposit()                     # one success: +0.5 token
    assert not budget.try_spend()        # still below a whole token
    budget.deposit()
    assert budget.try_spend()            # two successes buy one retry
    # Deposits cap at capacity.
    for _ in range(20):
        budget.deposit()
    assert budget.snapshot()["tokens"] == 2.0


# --- exec node admission ------------------------------------------------------


def test_exec_node_throttles_full_queue():
    from ytsaurus_tpu.server.exec_service import (
        MAX_PENDING_PER_SLOT,
        ExecNodeService,
    )
    service = ExecNodeService(store=None, slots=1)
    try:
        throttled = []
        for i in range(2 + MAX_PENDING_PER_SLOT * 2):
            try:
                service.start_job({"command": b"sleep 0.2"}, [b""])
            except ThrottledError as err:
                throttled.append(err)
        assert throttled, "queue never throttled"
        assert throttled[0].retry_after > 0
        stats = service.exec_stats({}, [])
        assert stats["throttled_total"] == len(throttled)
        assert stats["pending"] <= 1 + MAX_PENDING_PER_SLOT
    finally:
        service.close()


# --- http proxy error mapping -------------------------------------------------


class _FakeRequest:
    def __init__(self):
        self.status = None
        self.headers = {}
        self.body = b""
        import io
        self.wfile = io.BytesIO()

    def send_response(self, status):
        self.status = status

    def send_header(self, name, value):
        self.headers[name] = value

    def end_headers(self):
        pass


def test_http_proxy_maps_throttle_and_deadline():
    from ytsaurus_tpu.server.http_proxy import HttpProxy
    proxy = HttpProxy.__new__(HttpProxy)     # no sockets needed
    request = _FakeRequest()
    proxy._reply_error(request, ThrottledError(retry_after=0.25))
    assert request.status == 429
    assert request.headers["Retry-After"] == "0.250"
    request = _FakeRequest()
    proxy._reply_error(request, YtError(
        "deadline", code=EErrorCode.DeadlineExceeded))
    assert request.status == 504


# --- observability ------------------------------------------------------------


def test_serving_metrics_move_under_load(client):
    import json
    import urllib.request

    from ytsaurus_tpu.server.monitoring import MonitoringServer

    gateway = client.cluster.gateway
    before = gateway.snapshot()
    client.select_rows("sum(v) AS t FROM [//serve] GROUP BY k > 100")
    client.lookup_rows("//serve", [(1,), (2,), (3,)])
    after = gateway.snapshot()
    assert after["pools"]["default"]["admitted"] > \
        before["pools"]["default"]["admitted"]
    assert after["lookup"]["requests"] > before["lookup"]["requests"]

    server = MonitoringServer()
    server.start()
    try:
        base = f"http://{server.address}"
        metrics = urllib.request.urlopen(base + "/metrics",
                                         timeout=5).read().decode()
        # Admission counters, batching counters, query statistics
        # aggregates, and the evaluator cache gauge all export.
        assert "serving_admission_admitted" in metrics
        assert "serving_lookup_requests" in metrics
        assert "serving_lookup_batch_size_bucket" in metrics
        assert "serving_query_stats_rows_read" in metrics
        assert "serving_evaluator_cache_size" in metrics
        assert "serving_select_latency_seconds_bucket" in metrics
        snapshot = json.loads(urllib.request.urlopen(
            base + "/serving", timeout=5).read())
        assert any(g["pools"]["default"]["admitted"] > 0
                   for g in snapshot["gateways"])
    finally:
        server.stop()


# --- soak ---------------------------------------------------------------------


SOAK_THREADS = 8
SOAK_OPS = 18
QUICK_SOAK_THREADS = 6
QUICK_SOAK_OPS = 8


def _soak_round(client, spec, seed, accept_throttle,
                n_threads=SOAK_THREADS, n_ops=SOAK_OPS):
    """Mixed lookups/selects under a seeded failpoint schedule; returns
    (responses, throttles).  Asserts every successful response is
    correct and complete — nothing lost, duplicated, or misordered."""
    errors = []
    throttles = []
    responses = [0] * n_threads

    def worker(tid):
        try:
            for i in range(n_ops):
                try:
                    if i % 4 == 3:
                        rows = client.select_rows(
                            "k, v FROM [//serve] WHERE k < 10",
                            timeout=30.0)
                        assert len(rows) == 10
                    else:
                        width = 1 + (tid * n_ops + i) % 17
                        ks = [((tid * 97 + i * 13 + j) % N_ROWS,)
                              for j in range(width)]
                        rows = client.lookup_rows("//serve", ks,
                                                  timeout=30.0)
                        assert len(rows) == len(ks)
                        for key, row in zip(ks, rows):
                            assert row["k"] == key[0]
                            assert row["v"] == key[0] * 7
                    responses[tid] += 1
                except YtError as err:
                    if accept_throttle and err.contains(
                            EErrorCode.RequestThrottled):
                        # Throttles surface WITH their retry hint.
                        assert retry_after_hint(err) is not None
                        throttles.append(err)
                    else:
                        raise
        except Exception as exc:   # noqa: BLE001 — surfaced below
            errors.append(exc)

    with failpoints.active(spec, seed=seed):
        threads = [threading.Thread(target=worker, args=(t,),
                                    daemon=True)
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    assert not errors, errors
    # Every op is accounted for: a success or an accepted throttle.
    assert sum(responses) + len(throttles) == n_threads * n_ops
    return responses, throttles


def _soak(client, n_threads, n_ops):
    # Warm compiles so the soak exercises serving, not XLA.
    client.select_rows("k, v FROM [//serve] WHERE k < 10")
    client.lookup_rows("//serve", [(0,)])
    cache0 = client.cluster.evaluator.cache_size()

    # Delay schedule: admission and flushes straggle, nothing fails.
    _soak_round(client, "serving.admit=delay:ms=2:p=0.4;"
                        "serving.batch_flush=delay:ms=2:p=0.4",
                seed=7, accept_throttle=False,
                n_threads=n_threads, n_ops=n_ops)
    # Error schedule: every 6th admission throttles; callers see
    # ThrottledError with retry_after, everyone else is unaffected.
    _, throttles = _soak_round(
        client, "serving.admit=error:1in=6", seed=11,
        accept_throttle=True, n_threads=n_threads, n_ops=n_ops)
    assert throttles, "error schedule never throttled"

    # Compile-cache discipline: varied lookup batch sizes + repeated
    # selects must NOT mint new programs (bucketed shapes keep
    # compile_count flat across the soak).
    assert client.cluster.evaluator.cache_size() == cache0
    counters = failpoints.counters()
    assert counters["serving.admit"]["triggers"] > 0
    assert counters["serving.batch_flush"]["triggers"] > 0


def test_serving_soak_quick(client):
    """Tier-1 sibling of the full soak (same schedules, smaller mix)."""
    _soak(client, QUICK_SOAK_THREADS, QUICK_SOAK_OPS)


@pytest.mark.slow
def test_serving_soak_under_failpoints(client):
    _soak(client, SOAK_THREADS, SOAK_OPS)
