"""YSON parser: text and binary, one-pass recursive descent.

Ref: yt/yt/core/yson/parser.h / pull_parser.h.
"""

from __future__ import annotations

import struct

from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.utils.varint import read_varint_u
from ytsaurus_tpu.yson.types import YsonUint64, to_yson_type
from ytsaurus_tpu.yson.writer import zigzag_decode

_BARE = set(
    b"abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-%./")


class _Parser:
    def __init__(self, data: bytes, encoding: str | None = "utf-8"):
        self.data = data
        self.pos = 0
        self.encoding = encoding

    def error(self, message: str) -> YtError:
        ctx = self.data[max(0, self.pos - 15): self.pos + 15]
        return YtError(f"YSON parse error: {message} at byte {self.pos} "
                       f"(context {ctx!r})")

    # -- low level -------------------------------------------------------------

    def peek(self) -> int:
        self.skip_ws()
        if self.pos >= len(self.data):
            raise self.error("unexpected end of input")
        return self.data[self.pos]

    def skip_ws(self) -> None:
        while self.pos < len(self.data) and self.data[self.pos] in b" \t\r\n":
            self.pos += 1

    def expect(self, char: bytes) -> None:
        if self.peek() != char[0]:
            raise self.error(f"expected {char!r}")
        self.pos += 1

    def try_consume(self, char: bytes) -> bool:
        self.skip_ws()
        if self.pos < len(self.data) and self.data[self.pos] == char[0]:
            self.pos += 1
            return True
        return False

    def read_varint(self) -> int:
        try:
            value, self.pos = read_varint_u(self.data, self.pos)
        except ValueError:
            raise self.error("truncated varint")
        return value

    # -- values ----------------------------------------------------------------

    def parse_value(self):
        attributes = None
        if self.try_consume(b"<"):
            attributes = self._parse_map_body(b">")
        c = self.peek()
        value = None
        # Binary markers.
        if c == 0x01:
            self.pos += 1
            length = self.read_varint()
            raw = self.data[self.pos:self.pos + length]
            if len(raw) != length:
                raise self.error("truncated binary string")
            self.pos += length
            value = self._decode_string(raw)
        elif c == 0x02:
            self.pos += 1
            value = zigzag_decode(self.read_varint())
        elif c == 0x03:
            self.pos += 1
            value = struct.unpack("<d", self.data[self.pos:self.pos + 8])[0]
            self.pos += 8
        elif c == 0x04:
            self.pos += 1
            value = False
        elif c == 0x05:
            self.pos += 1
            value = True
        elif c == 0x06:
            self.pos += 1
            value = YsonUint64(self.read_varint())
        elif c == ord("#"):
            self.pos += 1
            value = None
        elif c == ord("{"):
            self.pos += 1
            value = self._parse_map_body(b"}")
        elif c == ord("["):
            self.pos += 1
            value = self._parse_list_body()
        elif c == ord('"'):
            value = self._parse_quoted_string()
        elif c == ord("%"):
            value = self._parse_special()
        elif chr(c).isdigit() or c in (ord("-"), ord("+")):
            value = self._parse_number()
        elif c in _BARE:
            value = self._parse_bare_string()
        else:
            raise self.error(f"unexpected byte {bytes([c])!r}")
        if attributes is not None:
            return to_yson_type(value, attributes)
        return value

    def _decode_string(self, raw: bytes):
        if self.encoding is None:
            return raw
        try:
            return raw.decode(self.encoding)
        except UnicodeDecodeError:
            return raw

    def _parse_map_body(self, closing: bytes) -> dict:
        result: dict = {}
        while not self.try_consume(closing):
            key = self.parse_value()
            if isinstance(key, bytes):
                key = key.decode("utf-8", "surrogateescape")
            if not isinstance(key, str):
                raise self.error(f"map key must be a string, got {key!r}")
            self.expect(b"=")
            result[key] = self.parse_value()
            if not self.try_consume(b";"):
                self.expect(closing)
                return result
        return result

    def _parse_list_body(self) -> list:
        result = []
        while not self.try_consume(b"]"):
            result.append(self.parse_value())
            if not self.try_consume(b";"):
                self.expect(b"]")
                return result
        return result

    def _parse_quoted_string(self):
        self.expect(b'"')
        out = bytearray()
        while True:
            if self.pos >= len(self.data):
                raise self.error("unterminated string")
            b = self.data[self.pos]
            self.pos += 1
            if b == ord('"'):
                break
            if b == ord("\\"):
                esc = self.data[self.pos]
                self.pos += 1
                mapping = {ord("n"): 10, ord("t"): 9, ord("r"): 13,
                           ord("\\"): 92, ord('"'): 34, ord("0"): 0}
                if esc in mapping:
                    out.append(mapping[esc])
                elif esc == ord("x"):
                    out.append(int(self.data[self.pos:self.pos + 2], 16))
                    self.pos += 2
                else:
                    out.append(esc)
            else:
                out.append(b)
        return self._decode_string(bytes(out))

    def _parse_bare_string(self):
        start = self.pos
        while self.pos < len(self.data) and self.data[self.pos] in _BARE:
            self.pos += 1
        return self._decode_string(self.data[start:self.pos])

    def _parse_special(self):
        for literal, value in ((b"%true", True), (b"%false", False),
                               (b"%nan", float("nan")), (b"%-inf", float("-inf")),
                               (b"%inf", float("inf"))):
            if self.data.startswith(literal, self.pos):
                self.pos += len(literal)
                return value
        raise self.error("unknown % literal")

    def _parse_number(self):
        start = self.pos
        if self.data[self.pos] in b"+-":
            self.pos += 1
        is_double = False
        while self.pos < len(self.data):
            b = self.data[self.pos]
            if chr(b).isdigit():
                self.pos += 1
            elif b in b".eE":
                is_double = True
                self.pos += 1
                if self.pos < len(self.data) and self.data[self.pos] in b"+-":
                    self.pos += 1
            else:
                break
        text = self.data[start:self.pos]
        if self.pos < len(self.data) and self.data[self.pos] in b"uU":
            self.pos += 1
            return YsonUint64(int(text))
        if is_double:
            return float(text)
        return int(text)


def loads(data: bytes | str, encoding: str | None = "utf-8",
          yson_type: str = "node"):
    """Parse one YSON value (or a list of values for yson_type='list_fragment')."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    parser = _Parser(data, encoding=encoding)
    try:
        if yson_type == "list_fragment":
            values = []
            parser.skip_ws()
            while parser.pos < len(parser.data):
                values.append(parser.parse_value())
                parser.try_consume(b";")
                parser.skip_ws()
            return values
        value = parser.parse_value()
        parser.skip_ws()
        if parser.pos != len(parser.data):
            raise parser.error("trailing data")
        return value
    except YtError:
        raise
    except (IndexError, ValueError, struct.error, OverflowError) as e:
        # Malformed input must surface as a parse error, not a raw exception.
        raise parser.error(f"malformed input ({type(e).__name__}: {e})")
