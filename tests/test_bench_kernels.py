"""Tier-1 smoke gate for the per-primitive kernel floors (ISSUE 19).

`bench.py --config kernels` times the ops/segments.py backbone
(segmented scans, scatter segment-reduce, radix ranks, packed sorts,
hash group order, lex join probe, mask compaction) and records rows/s
floors in tools/kernel_floors.json at 0.4x a measured run.  This test
replays the smoke-scale measurement inside the tier-1 pass so a backbone
regression (an engine falling off its fast path, a silently serialized
scatter) fails the build here, not rounds later in a macro bench.
"""

import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

_SMOKE_ROWS = 100_000


def test_kernel_floors_hold():
    import jax

    import bench

    platform = jax.devices()[0].platform
    floors = bench._load_kernel_floors()
    entry = floors.get(platform, {}).get(str(_SMOKE_ROWS))
    if not entry:
        pytest.skip(f"no recorded kernel floors for "
                    f"{platform}:{_SMOKE_ROWS}")
    results = bench.kernel_primitives(_SMOKE_ROWS, iters=3)
    # The floor file and the harness must agree on the primitive set —
    # a renamed or dropped primitive silently ungates otherwise.
    assert set(results) == set(entry), (
        sorted(results), sorted(entry))
    failures = {name: {"measured": round(rps, 1), "floor": entry[name]}
                for name, (rps, _) in results.items()
                if rps < entry[name]}
    assert not failures, f"kernel primitives under floor: {failures}"
