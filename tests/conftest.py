"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is unavailable in CI; all sharding/collective tests run on
a virtual 8-device CPU platform (xla_force_host_platform_device_count), per the
same strategy the reference uses for multi-node tests without a real cluster
(yt/python/yt/environment/yt_env.py local-mode clusters).

This must run before any JAX backend initializes.  The environment may have a
TPU plugin pre-registered by sitecustomize, so we switch platforms via
jax.config (which takes effect lazily at first backend use) rather than env.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
# Every test runs "sanitized": structural invariant checks at subsystem
# boundaries (utils/invariants.py — the debug-build assertion analog).
# Plain assignment, not setdefault: an inherited =0 from a profiling
# shell must not silently turn the sanitizer off for the whole suite.
os.environ["YT_TPU_INVARIANTS"] = "1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: minutes-long compile-heavy suites excluded from the tier-1 "
        "quick pass (ROADMAP.md runs -m 'not slow')")


@pytest.fixture(autouse=True)
def _failpoint_leak_guard():
    """Leak guard (ISSUE 2 satellite): a test that leaves a failpoint
    schedule active would inject faults into every later test — fail THAT
    test, loudly, and disarm before anything else runs."""
    yield
    from ytsaurus_tpu.utils import failpoints

    leaked = failpoints.active_spec()
    if leaked is not None:
        failpoints.deactivate()
        pytest.fail(f"test left failpoints active: {leaked!r}")


@pytest.fixture
def failpoints_active():
    """Scoped activation helper: `failpoints_active(spec, seed=7)` arms a
    schedule for the remainder of the test and guarantees disarm on
    teardown (even when the test body raises)."""
    from ytsaurus_tpu.utils import failpoints

    def arm(spec: str, seed: int = 0):
        failpoints.activate(spec, seed=seed)

    yield arm
    failpoints.deactivate()


@pytest.fixture(scope="session")
def mesh8():
    import numpy as np
    from jax.sharding import Mesh

    devices = jax.devices()
    assert len(devices) >= 8, f"expected >=8 virtual devices, got {len(devices)}"
    return Mesh(np.array(devices[:8]).reshape(8), ("shard",))
