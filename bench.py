"""Benchmarks for the BASELINE.md configs.

Prints ONE JSON line PER CONFIG: {"metric", "value", "unit", "vs_baseline"}
— ALWAYS, even on backend failure (the last verified on-chip capture from
BENCH_VERIFIED.json then, or 0.0 with the reason on stderr), so the
driver's parse never comes up empty and a late tunnel flap cannot zero a
round that HAS verified numbers.  Default --config=all runs every BASELINE
config, printing the headline (TPC-H Q1, config 1) last:

  q1      scan + filter + 8-aggregate GROUP BY (headline; default)
  groupby GROUP BY key over a sorted table (hash-aggregate path, config 2)
  topk    ORDER BY ... LIMIT K (config 3)
  q3      two-table JOIN + GROUP BY + top-K (TPC-H Q3, config 4)
  sort    device sort (single-chip stand-in for the 1B-row Sort, config 5)
  strings GROUP BY over a ~1M-distinct string column (hash-bucket path)
  window  running sum + rank OVER (PARTITION BY ... ORDER BY ...) over
          2M rows (segmented prefix-scan window subsystem)
  serving 64-client concurrent point lookups through the query gateway
          (continuous micro-batching, ISSUE 3) vs the pre-gateway
          sequential path; metric is the batched throughput, the
          speedup + p99s print on stderr
  scan    versioned MVCC snapshot read over a multi-chunk tablet with
          version churn (ISSUE 4): warm snapshot-cache select path is
          the metric; cold vectorized + pre-PR Python reference merge
          timings and speedups print on stderr
  trace_overhead  query flight recorder (ISSUE 5): asserts the untraced
          span-site fast path ≲1µs, reports sampled-mode tracing
          overhead on the select and warm-scan shapes; metric is the
          traced select throughput
  replay  workload recorder + replay harness (ISSUE 8): records a
          parameterized-query mix, exports/reloads it through the
          versioned capture format, then replays it open-loop against
          the live gateway; metric is the achieved replay throughput,
          p50/p99/p999 + steady-state compile-cache hit rate + slowest
          trace ids print on stderr
  serving_steady  compile-once serving (ISSUE 10): replays a skewed-
          literal parameterized mix three ways — pre-PR per-constant
          fingerprints (baseline), auto-parameterized + persistent AOT
          disk cache (asserts steady-state compile-cache hit rate
          >=99%), and a restart-warm-start leg in a SECOND process on
          the same artifact dir (asserts ~0 fresh compiles, disk hits
          only); metric is the parameterized replay throughput
  whole_plan  whole-plan fused SPMD execution (ISSUE 12): q1/groupby-
          class plans on the virtual 8-device CPU mesh, fused
          one-program lowering vs BOTH stitched rungs (shuffle +
          gather), asserting fused >=2x the best stitched rung and
          exactly one host sync per fused query; metric is the fused
          groupby-class throughput
  telemetry_overhead  cluster telemetry plane (ISSUE 6): asserts the
          per-site sensor-recording cost ≲1µs and the per-query
          accounting fold ≲20µs, then runs the serving lookup shape
          with the history sampler OFF vs ON at 100× the configured
          cadence and asserts the sampled throughput stays within 1%;
          metric is the sampled serving throughput
  tiering adaptive tiered execution (ISSUE 18): a burst of distinct
          cold query shapes inline-compiled vs interpreter-first with
          background promotion (cold p99 asserted >=10x lower, steady
          compiled share >=95%) plus a prewarmed-restart leg (0 inline
          compiles); metric is the interpreted cold-burst throughput
  all     run every config, one JSON line each (headline line printed last)

Row counts are scaled to the ACTUAL platform after backend probing: a CPU
fallback must never grind through TPU-sized inputs (round-1 failure mode:
rc=124 with zero output).  The iteration loop is additionally time-boxed by
--budget seconds (default 420, env BENCH_BUDGET) so a JSON line is emitted
within the driver timeout no matter what.

Baseline: the reference's LLVM-JIT evaluator on a modern x86 core sustains
roughly 5e7 rows/s on Q1-shaped scan+filter+group (order-of-magnitude from
vectorized-engine literature; the reference repo publishes no absolute
numbers — see BASELINE.md).  vs_baseline = ours / 5e7 for the query configs.

NOTE: under the axon tunnel, jax.block_until_ready does NOT synchronize —
timings force a real device→host read instead.

Usage: python bench.py [--config NAME] [--smoke] [--rows N] [--iters K]
                       [--budget SECONDS]
"""

import argparse
import json
import os
import sys
import time


BASELINE_ROWS_PER_SEC = 5.0e7

_DEADLINE = None   # wall-clock deadline for timed iterations (set in main)


def _iters_left(times, iters):
    """True while another timed iteration fits the budget."""
    if len(times) >= iters:
        return False
    if _DEADLINE is None or not times:
        return len(times) < iters          # always take at least one
    return time.monotonic() + max(times) < _DEADLINE


def _sync(x):
    """True synchronization: force a host read (see module note).  Slice
    ON DEVICE first so only one element crosses the tunnel — np.asarray of
    a whole result plane costs seconds at ~17 MB/s."""
    import numpy as np
    leaf = x
    while isinstance(leaf, (list, tuple)):
        leaf = leaf[0]
    if hasattr(leaf, "ravel"):
        leaf = leaf.ravel()[:1]
    np.asarray(leaf)


def _time_plan(query, tables, iters, evaluator=None):
    """Compile + time one plan over prepared chunks; returns best seconds."""
    import jax

    from ytsaurus_tpu.query.builder import build_query
    from ytsaurus_tpu.query.engine.lowering import prepare

    schemas = {path: chunk.schema for path, chunk in tables.items()}
    plan = build_query(query, schemas)
    chunk = tables[plan.source]
    prepared = prepare(plan, chunk)
    columns = {c.name: (chunk.columns[c.name].data,
                        chunk.columns[c.name].valid)
               for c in plan.schema}
    bindings = tuple(prepared.bindings)
    row_valid = chunk.row_valid
    fn = jax.jit(prepared.run)
    planes, count = fn(columns, row_valid, bindings)   # warm-up / compile
    _sync(planes)
    times = []
    while _iters_left(times, iters):
        t0 = time.perf_counter()
        planes, count = fn(columns, row_valid, bindings)
        _sync(planes)
        times.append(time.perf_counter() - t0)
    return min(times), int(count)


def bench_q1(n_rows, iters):
    from ytsaurus_tpu.models import tpch
    chunk = tpch.generate_lineitem_device(n_rows)
    best, groups = _time_plan(tpch.Q1, {"//tpch/lineitem": chunk}, iters)
    assert 1 <= groups <= 6
    return "tpch_q1_rows_per_sec", n_rows / best, best

def bench_groupby(n_rows, iters):
    from ytsaurus_tpu.models import tpch
    from ytsaurus_tpu.schema import TableSchema
    schema = TableSchema.make([("k", "int64", "ascending"), ("g", "int64"),
                               ("v", "int64")])
    chunk = tpch.device_chunk(schema, tpch.device_planes({
        "k": ("arange",), "g": ("randint", 0, 10_000),
        "v": ("randint", 0, 1000)}, n_rows), n_rows)
    best, _ = _time_plan(
        "g, sum(v) AS s, count(*) AS c FROM [//t] GROUP BY g",
        {"//t": chunk}, iters)
    return "groupby_rows_per_sec", n_rows / best, best

def bench_topk(n_rows, iters):
    from ytsaurus_tpu.models import tpch
    from ytsaurus_tpu.schema import TableSchema
    schema = TableSchema.make([("k", "int64"), ("v", "double")])
    chunk = tpch.device_chunk(schema, tpch.device_planes({
        "k": ("arange",), "v": ("uniform", 0.0, 1.0)}, n_rows), n_rows)
    best, count = _time_plan(
        "k, v FROM [//t] ORDER BY v DESC LIMIT 100", {"//t": chunk}, iters)
    assert count == 100
    return "topk_rows_per_sec", n_rows / best, best

def bench_q3(n_rows, iters):
    from ytsaurus_tpu.models import tpch
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    n_orders = max(n_rows // 4, 1)
    lineitem = tpch.generate_lineitem_device(n_rows, n_orders=n_orders)
    orders = tpch.generate_orders_device(n_orders)
    ev = Evaluator()
    from ytsaurus_tpu.query.builder import build_query
    plan = build_query(tpch.Q3, {"//tpch/lineitem": tpch.LINEITEM_SCHEMA,
                                 "//tpch/orders": tpch.ORDERS_SCHEMA})
    foreign = {"//tpch/orders": orders}
    out = ev.run_plan(plan, lineitem, foreign)      # warm-up (incl. join)
    assert out.row_count <= 10
    times = []
    while _iters_left(times, iters):
        t0 = time.perf_counter()
        out = ev.run_plan(plan, lineitem, foreign)
        _sync(out.columns[out.schema.column_names[0]].data)
        times.append(time.perf_counter() - t0)
    best = min(times)
    return "tpch_q3_rows_per_sec", n_rows / best, best

def bench_sort(n_rows, iters):
    from ytsaurus_tpu.models import tpch
    from ytsaurus_tpu.operations.sort_op import sort_chunk
    from ytsaurus_tpu.schema import TableSchema
    schema = TableSchema.make([("k", "int64"), ("p", "double")])
    spill_rows = int(os.environ.get("YT_TPU_SORT_SPILL_ROWS",
                                    128_000_000))
    if n_rows > spill_rows:
        return _bench_sort_spill(n_rows, iters, schema)
    chunk = tpch.device_chunk(schema, tpch.device_planes({
        "k": ("randint", 0, 1 << 60), "p": ("uniform", 0.0, 1.0)},
        n_rows), n_rows)
    out = sort_chunk(chunk, ["k"])                  # warm-up
    _sync(out.columns["k"].data)
    times = []
    while _iters_left(times, iters):
        t0 = time.perf_counter()
        out = sort_chunk(chunk, ["k"])
        _sync(out.columns["k"].data)
        times.append(time.perf_counter() - t0)
    return "sort_rows_per_sec", n_rows / min(times), min(times)


def _bench_sort_spill(n_rows, iters, schema):
    """BASELINE config 5 shape: input larger than HBM — external sort
    (range partition + host spill + per-range device sorts, ops/bigsort).
    Blocks generate lazily so peak device memory stays budget-bounded."""
    import numpy as np

    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    from ytsaurus_tpu.ops.bigsort import SpillStats, external_sort

    block_rows = 16_000_000
    budget = int(os.environ.get("YT_TPU_HBM_BUDGET", 8 << 30))

    def supplier(i, rows):
        def make():
            rng = np.random.default_rng(1000 + i)
            return ColumnarChunk.from_arrays(schema, {
                "k": rng.integers(0, 1 << 60, size=rows,
                                  dtype=np.int64),
                "p": rng.random(rows)})
        return make

    suppliers = []
    left, i = n_rows, 0
    while left > 0:
        rows = min(block_rows, left)
        suppliers.append(supplier(i, rows))
        left -= rows
        i += 1
    times = []
    while _iters_left(times, 1):       # spill passes are minutes: one run
        stats = SpillStats()
        t0 = time.perf_counter()
        total = 0
        prev_last = None
        for out in external_sort(suppliers, ["k"], budget_bytes=budget,
                                 stats=stats):
            # Touch the output (forces the device work) + verify global
            # order across range boundaries.
            n = out.row_count
            first = int(np.asarray(out.columns["k"].data[:1])[0])
            last = int(np.asarray(out.columns["k"].data[n - 1:n])[0])
            if prev_last is not None:
                assert first >= prev_last, "range order violated"
            prev_last = last
            total += n
        times.append(time.perf_counter() - t0)
        assert total == n_rows, (total, n_rows)
        print(f"# spill sort: {stats.ranges} ranges, "
              f"{stats.resplits} resplits, peak range "
              f"{stats.peak_range_rows} rows (budget "
              f"{stats.budget_rows})", file=sys.stderr)
    return "sort_rows_per_sec", n_rows / min(times), min(times)

def bench_strings(n_rows, iters):
    """GROUP BY over a high-cardinality (~n/10 distinct) string column."""
    import numpy as np
    from ytsaurus_tpu.models import tpch
    from ytsaurus_tpu.schema import TableSchema
    n_distinct = max(n_rows // 10, 1)
    schema = TableSchema.make([("k", "int64", "ascending"), ("s", "string"),
                               ("v", "int64")])
    # Codes on device; only the (host-side) vocabulary is materialized.
    vocab = np.empty(n_distinct, dtype=object)
    vocab[:] = [b"u%08d" % c for c in range(n_distinct)]
    chunk = tpch.device_chunk(schema, tpch.device_planes({
        "k": ("arange",), "s": ("randint", 0, n_distinct),
        "v": ("randint", 0, 1000)}, n_rows), n_rows,
        dictionaries={"s": vocab})
    best, groups = _time_plan(
        "s, sum(v) AS t FROM [//t] GROUP BY s", {"//t": chunk}, iters)
    assert groups <= n_distinct
    return "strings_groupby_rows_per_sec", n_rows / best, best


def bench_select(n_rows, iters):
    """Host-coordinated distributed select (coordinate_and_execute over
    8 shards): scan + filter + GROUP BY through the per-shard recovery
    ladder (ISSUE 2).  Also proves the DISABLED failpoint fast path adds
    no measurable overhead — the sites sit on this exact code path."""
    from ytsaurus_tpu.models import tpch
    from ytsaurus_tpu.query.builder import build_query
    from ytsaurus_tpu.query.coordinator import coordinate_and_execute
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    from ytsaurus_tpu.schema import TableSchema
    from ytsaurus_tpu.utils import failpoints

    # Fast-path micro-check: a disabled failpoint hit must be ~free
    # (one module-global read), or threading sites through every I/O
    # boundary would tax fault-free production.
    probe = failpoints.register_site("bench.overhead.probe")
    n_probe = 200_000
    t0 = time.perf_counter()
    for _ in range(n_probe):
        probe.hit()
    per_hit = (time.perf_counter() - t0) / n_probe
    print(f"# failpoints disabled fast path: {per_hit * 1e9:.0f} ns/hit",
          file=sys.stderr)
    assert per_hit < 5e-6, \
        f"disabled failpoint hit too slow: {per_hit * 1e9:.0f} ns"

    schema = TableSchema.make([("k", "int64", "ascending"), ("g", "int64"),
                               ("v", "int64")])
    chunk = tpch.device_chunk(schema, tpch.device_planes({
        "k": ("arange",), "g": ("randint", 0, 10_000),
        "v": ("randint", 0, 1000)}, n_rows), n_rows)
    n_shards = 8
    per = max(n_rows // n_shards, 1)
    shards = [chunk.slice_rows(i * per, min((i + 1) * per, n_rows))
              for i in range(n_shards) if i * per < n_rows]
    plan = build_query(
        "g, sum(v) AS s, count(*) AS c FROM [//t] WHERE v < 900 GROUP BY g",
        {"//t": schema})
    ev = Evaluator()
    out = coordinate_and_execute(plan, shards, evaluator=ev)   # warm-up
    _sync(out.columns[out.schema.column_names[0]].data)
    times = []
    while _iters_left(times, iters):
        t0 = time.perf_counter()
        out = coordinate_and_execute(plan, shards, evaluator=ev)
        _sync(out.columns[out.schema.column_names[0]].data)
        times.append(time.perf_counter() - t0)
    best = min(times)
    return "select_rows_per_sec", n_rows / best, best


def bench_window(n_rows, iters):
    """Window subsystem (ISSUE 1): running sum + rank over ~1k
    partitions — one packed u32 sort + segmented prefix scans
    (query/engine/window.py)."""
    from ytsaurus_tpu.models import tpch
    from ytsaurus_tpu.schema import TableSchema
    schema = TableSchema.make([("k", "int64", "ascending"),
                               ("g", "int64"), ("v", "int64")])
    chunk = tpch.device_chunk(schema, tpch.device_planes({
        "k": ("arange",), "g": ("randint", 0, 1000),
        "v": ("randint", 0, 1000)}, n_rows), n_rows)
    best, count = _time_plan(
        "k, sum(v) OVER (PARTITION BY g ORDER BY k) AS s, "
        "rank() OVER (PARTITION BY g ORDER BY k) AS r FROM [//t]",
        {"//t": chunk}, iters)
    assert count == n_rows
    return "window_rows_per_sec", n_rows / best, best


def bench_serving(n_rows, iters):
    """Query serving plane (ISSUE 3): 64 concurrent clients doing
    point lookups (8-key multi-gets) against one flushed 4-tablet
    dynamic table, batched (gateway micro-batching + vectorized batch
    probe + per-tablet fan-out) vs unbatched (the pre-gateway
    sequential path: one full-plane chunk mask PER KEY, tablets
    visited sequentially).  The table is larger than the tablet row
    caches, so per-key chunk-probe cost — the cost batching
    amortizes — dominates, as it does at serving scale.  The emitted
    metric is the BATCHED key throughput; the speedup and p99s go to
    stderr.  n_rows sizes the table."""
    import random
    import tempfile
    import threading

    from ytsaurus_tpu.client import connect
    from ytsaurus_tpu.schema import TableSchema

    n_clients = 64
    per_client = 8
    keys_per_op = 8
    client = connect(tempfile.mkdtemp(prefix="bench-serving-"))
    schema = TableSchema.make(
        [("k", "int64", "ascending"), ("v", "int64")], unique_keys=True)
    pivots = [[n_rows // 4], [n_rows // 2], [3 * n_rows // 4]]
    client.create("table", "//bench/serve",
                  attributes={"schema": schema, "dynamic": True,
                              "pivot_keys": pivots}, recursive=True)
    client.mount_table("//bench/serve")
    for lo in range(0, n_rows, 50_000):
        hi = min(lo + 50_000, n_rows)
        client.insert_rows("//bench/serve",
                           [{"k": i, "v": i * 3} for i in range(lo, hi)])
    # Flush to chunks: the steady serving state (memtable-only tables
    # are the post-restart exception, not the rule).
    client.freeze_table("//bench/serve")

    def run_mode(lookup_fn):
        latencies = []
        lock = threading.Lock()
        barrier = threading.Barrier(n_clients + 1)

        def worker(seed):
            rng = random.Random(seed)
            mine = []
            barrier.wait()
            for _ in range(per_client):
                keys = [(rng.randrange(n_rows),)
                        for _ in range(keys_per_op)]
                t0 = time.perf_counter()
                rows = lookup_fn("//bench/serve", keys)
                mine.append(time.perf_counter() - t0)
                assert rows[0]["v"] == keys[0][0] * 3
            with lock:
                latencies.extend(mine)

        threads = [threading.Thread(target=worker, args=(s,), daemon=True)
                   for s in range(n_clients)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        latencies.sort()
        p99 = latencies[int(len(latencies) * 0.99) - 1]
        total_keys = n_clients * per_client * keys_per_op
        return total_keys / elapsed, p99, elapsed

    # Warm both paths (tablet host planes) off the clock.
    client._lookup_rows_direct("//bench/serve", [(0,), (n_rows - 1,)])
    client.lookup_rows("//bench/serve", [(1,)])
    seq_tput, seq_p99, _ = run_mode(client._lookup_rows_direct)
    best_tput, best_p99, best_elapsed = 0.0, 0.0, 0.0
    times = []
    while _iters_left(times, iters):
        t0 = time.perf_counter()
        tput, p99, elapsed = run_mode(client.lookup_rows)
        times.append(time.perf_counter() - t0)
        if tput > best_tput:
            best_tput, best_p99, best_elapsed = tput, p99, elapsed
    snap = client.cluster.gateway.snapshot()["lookup"]
    print(f"# serving: batched {best_tput:.0f} keys/s "
          f"p99={best_p99*1e3:.2f}ms vs unbatched {seq_tput:.0f} keys/s "
          f"p99={seq_p99*1e3:.2f}ms "
          f"(speedup {best_tput / max(seq_tput, 1e-9):.2f}x, "
          f"{snap['requests']:.0f} requests in {snap['batches']:.0f} "
          "batches)", file=sys.stderr)
    return "serving_lookup_rows_per_sec", best_tput, best_elapsed


def bench_trace_overhead(n_rows, iters):
    """Query flight recorder (ISSUE 5): the UNTRACED span-site fast path
    must stay ≲1µs/site (one contextvar read + a singleton return —
    mirror of the failpoints fast-path assert: the query/operation planes
    thread ~20 sites through their hot paths, and fault-free untraced
    production must not pay for them), and sampled tracing must tax the
    select/scan pipelines only marginally.  The emitted metric is the
    TRACED select throughput; the per-site costs and the traced-vs-
    untraced deltas for the select and scan shapes go to stderr."""
    from ytsaurus_tpu import config as _config
    from ytsaurus_tpu.models import tpch
    from ytsaurus_tpu.query.builder import build_query
    from ytsaurus_tpu.query.coordinator import coordinate_and_execute
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    from ytsaurus_tpu.schema import TableSchema
    from ytsaurus_tpu.utils import tracing

    def per_site(site):
        """min-of-rounds mean: stable against scheduler noise."""
        n_round, best = 40_000, float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(n_round):
                with site("bench.trace.site"):
                    pass
            best = min(best, (time.perf_counter() - t0) / n_round)
        return best

    # (a) interior site with NO ambient trace — the path every span site
    # in an untraced query takes.
    null_cost = per_site(tracing.child_span)
    # (b) entry-point site with tracing DISABLED outright.
    _config.set_tracing_config(_config.TracingConfig(enabled=False))
    try:
        disabled_cost = per_site(tracing.start_query_span)
    finally:
        _config.set_tracing_config(None)
    # (c) reference: a live recorded span (allocation + collector add).
    def _recorded(name):
        return tracing.TraceContext(name)
    recorded_cost = per_site(_recorded)
    print(f"# trace sites: untraced child_span {null_cost * 1e9:.0f} "
          f"ns/site, disabled entry {disabled_cost * 1e9:.0f} ns/site, "
          f"recorded span {recorded_cost * 1e9:.0f} ns/site",
          file=sys.stderr)
    assert null_cost < 1.5e-6, \
        f"untraced span site too slow: {null_cost * 1e9:.0f} ns"
    assert disabled_cost < 1.5e-6, \
        f"disabled entry span site too slow: {disabled_cost * 1e9:.0f} ns"

    # Sampled-mode overhead, select shape: the bench_select pipeline
    # (8-shard coordinate_and_execute) untraced vs under a sampled root.
    schema = TableSchema.make([("k", "int64", "ascending"), ("g", "int64"),
                               ("v", "int64")])
    chunk = tpch.device_chunk(schema, tpch.device_planes({
        "k": ("arange",), "g": ("randint", 0, 10_000),
        "v": ("randint", 0, 1000)}, n_rows), n_rows)
    n_shards = 8
    per = max(n_rows // n_shards, 1)
    shards = [chunk.slice_rows(i * per, min((i + 1) * per, n_rows))
              for i in range(n_shards) if i * per < n_rows]
    plan = build_query(
        "g, sum(v) AS s, count(*) AS c FROM [//t] WHERE v < 900 GROUP BY g",
        {"//t": schema})
    ev = Evaluator()

    def timed_select(traced):
        out = coordinate_and_execute(plan, shards, evaluator=ev)  # warm
        _sync(out.columns[out.schema.column_names[0]].data)
        times = []
        while _iters_left(times, iters):
            t0 = time.perf_counter()
            if traced:
                with tracing.start_query_span("bench.trace.select"):
                    out = coordinate_and_execute(plan, shards,
                                                 evaluator=ev)
            else:
                out = coordinate_and_execute(plan, shards, evaluator=ev)
            _sync(out.columns[out.schema.column_names[0]].data)
            times.append(time.perf_counter() - t0)
        return min(times)

    plain = timed_select(traced=False)
    traced = timed_select(traced=True)

    # Sampled-mode overhead, scan shape: warm snapshot-cache tablet reads.
    import tempfile

    from ytsaurus_tpu.chunks.store import FsChunkStore
    from ytsaurus_tpu.tablet.tablet import Tablet
    tablet_schema = TableSchema.make(
        [("k", "int64", "ascending"), ("g", "int64"), ("v", "int64")],
        unique_keys=True)
    tablet = Tablet(tablet_schema,
                    FsChunkStore(tempfile.mkdtemp(prefix="bench-trace-")))
    for i in range(2048):
        tablet.write_row({"k": i, "g": i % 7, "v": i}, timestamp=100)
    tablet.read_snapshot()                        # prime the cache

    def timed_scan(do_trace):
        times = []
        while _iters_left(times, max(iters, 3)):
            t0 = time.perf_counter()
            for _ in range(100):
                if do_trace:
                    with tracing.start_query_span("bench.trace.scan"):
                        tablet.read_snapshot()
                else:
                    tablet.read_snapshot()
            times.append((time.perf_counter() - t0) / 100)
        return min(times)

    scan_plain = timed_scan(False)
    scan_traced = timed_scan(True)
    print(f"# sampled tracing overhead: select {plain * 1e3:.2f}ms -> "
          f"{traced * 1e3:.2f}ms "
          f"(+{(traced / plain - 1) * 100:.1f}%), warm scan "
          f"{scan_plain * 1e6:.0f}µs -> {scan_traced * 1e6:.0f}µs "
          f"(+{(scan_traced / scan_plain - 1) * 100:.1f}%)",
          file=sys.stderr)
    return "trace_overhead_rows_per_sec", n_rows / traced, traced


def bench_telemetry_overhead(n_rows, iters):
    """Cluster telemetry plane (ISSUE 6): the per-site recording cost
    (one counter increment / gauge set / histogram record — the unit
    every hot-path sensor pays) must stay ≲1µs, the per-query
    accounting fold (query/accounting.ResourceAccountant.fold: ~12
    counter adds under one lock) ≲20µs, and the sampler + accounting
    fold together must add ≤1% to the serving bench throughput.  The
    ≤1% claim is asserted as a deterministic decomposition — the
    sampler's whole cost is its duty cycle (sample_once walk time over
    the LIVE post-traffic registry / configured cadence) and the fold's
    is fold cost × the fold rate OBSERVED while the serving shape runs
    — because a direct A/B of a 16-thread throughput number on a noisy
    shared host cannot resolve 1% (round-to-round swings here are
    ±20%+); the A/B delta at 100× the configured cadence is still
    measured and printed for the record.  The emitted metric is the
    sampled serving key throughput."""
    import random
    import tempfile
    import threading

    from ytsaurus_tpu.client import connect
    from ytsaurus_tpu.query.accounting import ResourceAccountant
    from ytsaurus_tpu.schema import TableSchema
    from ytsaurus_tpu.utils.profiling import (
        MetricsHistory,
        Profiler,
        ProfilerRegistry,
        TelemetrySampler,
        get_registry,
    )
    from ytsaurus_tpu.utils.slo import SloTracker

    def per_site(fn, n_round=40_000, rounds=5):
        best = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            for _ in range(n_round):
                fn()
            best = min(best, (time.perf_counter() - t0) / n_round)
        return best

    reg = ProfilerRegistry()
    prof = Profiler("/bench/telemetry", registry=reg)
    counter, gauge = prof.counter("c"), prof.gauge("g")
    hist = prof.histogram("h")
    counter_cost = per_site(lambda: counter.increment())
    gauge_cost = per_site(lambda: gauge.set(1.25))
    hist_cost = per_site(lambda: hist.record(0.003))
    acct = ResourceAccountant(registry=reg)
    fold_cost = per_site(
        lambda: acct.fold("bench", "root", queries=1, rows_read=512,
                          bytes_read=16_384, compile_seconds=0.001,
                          execute_seconds=0.004, wall_seconds=0.005,
                          cache_hits=1),
        n_round=10_000)
    print(f"# telemetry sites: counter {counter_cost * 1e9:.0f} ns, "
          f"gauge {gauge_cost * 1e9:.0f} ns, histogram "
          f"{hist_cost * 1e9:.0f} ns, accounting fold "
          f"{fold_cost * 1e9:.0f} ns", file=sys.stderr)
    assert counter_cost < 1.5e-6, \
        f"counter record too slow: {counter_cost * 1e9:.0f} ns"
    assert gauge_cost < 1.5e-6, \
        f"gauge record too slow: {gauge_cost * 1e9:.0f} ns"
    assert hist_cost < 1.5e-6, \
        f"histogram record too slow: {hist_cost * 1e9:.0f} ns"
    assert fold_cost < 20e-6, \
        f"accounting fold too slow: {fold_cost * 1e9:.0f} ns"

    # Serving shape (scaled-down bench_serving): concurrent batched
    # multi-gets through the gateway, sampler OFF vs ON.
    n_clients, per_client, keys_per_op = 16, 64, 8
    client = connect(tempfile.mkdtemp(prefix="bench-telemetry-"))
    schema = TableSchema.make(
        [("k", "int64", "ascending"), ("v", "int64")], unique_keys=True)
    client.create("table", "//bench/telemetry",
                  attributes={"schema": schema, "dynamic": True,
                              "pivot_keys": [[n_rows // 2]]},
                  recursive=True)
    client.mount_table("//bench/telemetry")
    for lo in range(0, n_rows, 50_000):
        hi = min(lo + 50_000, n_rows)
        client.insert_rows("//bench/telemetry",
                           [{"k": i, "v": i * 3} for i in range(lo, hi)])
    client.freeze_table("//bench/telemetry")
    client.lookup_rows("//bench/telemetry", [(1,)])        # warm

    def run_round():
        barrier = threading.Barrier(n_clients + 1)

        def worker(seed):
            rng = random.Random(seed)
            barrier.wait()
            for _ in range(per_client):
                keys = [(rng.randrange(n_rows),)
                        for _ in range(keys_per_op)]
                rows = client.lookup_rows("//bench/telemetry", keys)
                assert rows[0]["v"] == keys[0][0] * 3
        threads = [threading.Thread(target=worker, args=(s,),
                                    daemon=True)
                   for s in range(n_clients)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        return n_clients * per_client * keys_per_op / elapsed, elapsed

    # The sampler walks the LIVE global registry (every sensor the
    # serving path above has created — the realistic per-tick cost),
    # with SLO evaluation hooked exactly as start_telemetry wires it.
    from ytsaurus_tpu.config import TelemetryConfig, telemetry_config
    from ytsaurus_tpu.query.accounting import get_accountant
    history = MetricsHistory(registry=get_registry())
    tracker = SloTracker(TelemetryConfig(), history=history)

    # A/B rounds (informational) + the observed accounting-fold rate;
    # one untimed round first warms every probe shape off the clock.
    run_round()
    rounds = min(max(iters or 0, 3), 7)
    best_off, best_on, best_on_elapsed = 0.0, 0.0, 0.0
    fold_rate = 0.0
    for _ in range(rounds):
        tput, _elapsed = run_round()
        best_off = max(best_off, tput)
        sampler = TelemetrySampler(history, period=0.1,
                                   hooks=[tracker.evaluate])
        sampler.start()
        folds0 = get_accountant().totals()["lookups"]
        try:
            tput, elapsed = run_round()
        finally:
            sampler.stop()
        fold_rate = max(fold_rate,
                        (get_accountant().totals()["lookups"] - folds0)
                        / elapsed)
        if tput > best_on:
            best_on, best_on_elapsed = tput, elapsed
    # Per-tick walk cost AFTER traffic: the registry now holds the full
    # serving sensor population and the rings are warm.
    walk_cost = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        history.sample_once()
        tracker.evaluate()
        walk_cost = min(walk_cost, time.perf_counter() - t0)

    period = telemetry_config().sample_period or 10.0
    sampler_share = walk_cost / period
    fold_share = fold_cost * fold_rate
    overhead = 1.0 - best_on / best_off if best_off else 0.0
    print(f"# sample_once+slo over the live registry: "
          f"{walk_cost * 1e6:.0f} µs/tick -> duty "
          f"{sampler_share * 100:.4f}% at the configured "
          f"{period:.0f}s cadence; accounting folds "
          f"{fold_rate:.0f}/s x {fold_cost * 1e9:.0f} ns -> "
          f"{fold_share * 100:.4f}% of one core", file=sys.stderr)
    print(f"# serving lookups: sampler off {best_off:.0f} keys/s, "
          f"on(100ms cadence) {best_on:.0f} keys/s "
          f"(A/B delta {overhead * 100:+.2f}%, informational: host "
          f"noise exceeds 1%)", file=sys.stderr)
    assert sampler_share + fold_share < 0.01, \
        f"telemetry costs {(sampler_share + fold_share) * 100:.3f}% " \
        f"> 1% (sampler duty {sampler_share * 100:.4f}%, accounting " \
        f"fold {fold_share * 100:.4f}%)"
    return "telemetry_overhead_rows_per_sec", best_on, best_on_elapsed


def bench_replay(n_rows, iters):
    """Workload recorder + replay harness (ISSUE 8): record a
    parameterized-query mix (3 shapes x skewed literal draws — the
    repeated-shape/varied-literal traffic ROADMAP 1 must compile once)
    against a flushed dynamic table, export the capture through the
    versioned workload-log schema, re-load it, and REPLAY it open-loop
    against the live gateway.  Reports p50/p99/p999, throttle/deadline
    counts, and the steady-state compile-cache hit rate (second half of
    the mix) — the measurement substrate the ROADMAP-1 ">=99% hit rate"
    acceptance will run on.  The emitted metric is the achieved replay
    query throughput; the latency/hit-rate detail and the slowest
    queries' trace ids go to stderr.  n_rows sizes the table."""
    import os as _os
    import random
    import tempfile

    from ytsaurus_tpu.client import connect
    from ytsaurus_tpu.query import workload as wl
    from ytsaurus_tpu.schema import TableSchema

    root = tempfile.mkdtemp(prefix="bench-replay-")
    client = connect(root)
    schema = TableSchema.make(
        [("k", "int64", "ascending"), ("g", "int64"), ("v", "int64")],
        unique_keys=True)
    client.create("table", "//bench/replay",
                  attributes={"schema": schema, "dynamic": True,
                              "pivot_keys": [[n_rows // 2]]},
                  recursive=True)
    client.mount_table("//bench/replay")
    for lo in range(0, n_rows, 50_000):
        hi = min(lo + 50_000, n_rows)
        client.insert_rows("//bench/replay",
                           [{"k": i, "g": i % 97, "v": i * 3}
                            for i in range(lo, hi)])
    client.freeze_table("//bench/replay")

    # Record phase: every select folds into the process workload log
    # (fresh — configure(None) rebinds it) via the normal client path.
    wl.configure(None)
    shapes = [
        "k, v FROM [//bench/replay] WHERE k = {}",
        "g, sum(v) AS s FROM [//bench/replay] WHERE v < {} GROUP BY g",
        "k, v FROM [//bench/replay] WHERE k > {} ORDER BY k LIMIT 10",
    ]
    rng = random.Random(7)
    distinct = [rng.randrange(n_rows) for _ in range(16)]
    n_queries = 240
    for i in range(n_queries):
        client.select_rows(shapes[i % len(shapes)].format(
            distinct[rng.randrange(4) if rng.random() < 0.5
                     else rng.randrange(len(distinct))]))
    capture_path = _os.path.join(root, "capture.json")
    written = wl.get_workload_log().export_capture(capture_path)
    records = wl.load_capture(capture_path)   # versioned-schema check
    assert written == len(records) == n_queries, (written, len(records))

    best = None
    times = []
    while _iters_left(times, iters):
        t0 = time.perf_counter()
        report = wl.replay(client, records, rate=400.0, max_workers=8)
        times.append(time.perf_counter() - t0)
        if best is None or report["achieved_rate"] > \
                best["achieved_rate"]:
            best = report
    lat, cache = best["latency"], best["compile_cache"]
    slow = best["slowest"][0] if best["slowest"] else {}
    print(f"# replay: {best['queries']} queries in "
          f"{best['elapsed_seconds']:.2f}s "
          f"({best['achieved_rate']:.0f}/s of {best['offered_rate']:.0f}/s "
          f"offered); p50={lat['p50_ms']:.2f}ms p99={lat['p99_ms']:.2f}ms "
          f"p999={lat['p999_ms']:.2f}ms; "
          f"{best['throttled']} throttled, {best['deadline']} deadline, "
          f"{best['error']} error; compile hit rate "
          f"{(cache['hit_rate'] or 0) * 100:.1f}% "
          f"(steady {(cache['steady_hit_rate'] or 0) * 100:.1f}%); "
          f"slowest {slow.get('wall_ms')}ms trace={slow.get('trace_id')}",
          file=sys.stderr)
    assert best["ok"] == best["queries"], best
    assert cache["steady_hit_rate"] is not None
    return ("replay_queries_per_sec", best["achieved_rate"],
            best["elapsed_seconds"])


def bench_serving_steady(n_rows, iters):
    """Compile-once serving (ISSUE 10): three legs over one fresh-
    constant parameterized mix (3 shapes x skewed draws over the FULL
    key domain, so constants essentially never repeat — the
    million-users `WHERE user_id = ?` traffic ROADMAP 1 names, which
    the pre-PR per-constant fingerprints recompile on every query).

      baseline   auto-parameterization OFF (the pre-PR discipline) on
                 a 60-query slice — recorded so BENCH_NOTES shows what
                 the fix buys (expected: every fresh constant is a
                 fresh fingerprint, hit rate collapses);
      steady     parameterization ON + persistent AOT disk cache, a
                 60-query warmup then the full measured replay —
                 acceptance: steady-state compile-cache hit rate >=99%
                 and CompileObservatory shape-spectrum cardinality
                 bounded (<= pow2 bucket count) despite ~240 distinct
                 constants;
      restart    a SECOND PROCESS builds the same table, points at the
                 same artifact directory, replays the same capture —
                 acceptance: ~0 fresh compiles (disk hits only), the
                 rolling-restart warm start.

    Metric is the parameterized leg's achieved replay throughput."""
    import os as _os
    import random
    import subprocess as _subprocess
    import tempfile

    from ytsaurus_tpu import config as yt_config
    from ytsaurus_tpu.client import connect
    from ytsaurus_tpu.query import workload as wl
    from ytsaurus_tpu.schema import TableSchema

    root = tempfile.mkdtemp(prefix="bench-serving-steady-")
    aot_dir = _os.path.join(root, "aot")

    def build_client(base):
        client = connect(base)
        schema = TableSchema.make(
            [("k", "int64", "ascending"), ("g", "int64"),
             ("v", "int64")], unique_keys=True)
        client.create("table", "//bench/steady",
                      attributes={"schema": schema, "dynamic": True,
                                  "pivot_keys": [[n_rows // 2]]},
                      recursive=True)
        client.mount_table("//bench/steady")
        for lo in range(0, n_rows, 50_000):
            hi = min(lo + 50_000, n_rows)
            client.insert_rows("//bench/steady",
                               [{"k": i, "g": i % 97, "v": i * 3}
                                for i in range(lo, hi)])
        client.freeze_table("//bench/steady")
        return client

    client = build_client(root)
    shapes = [
        "k, v FROM [//bench/steady] WHERE k = {}",
        "g, sum(v) AS s FROM [//bench/steady] WHERE v < {} GROUP BY g",
        "k, v FROM [//bench/steady] WHERE k > {} "
        "ORDER BY k LIMIT 10",
    ]
    # Fresh-constant mix: drawn over the whole key domain (Zipf-ish
    # skew via synthesize_mix), so with n_rows >> count virtually every
    # query carries a constant the fleet has never seen — the traffic
    # shape that makes per-constant fingerprints recompile forever.
    records = wl.synthesize_mix(shapes, count=240, distinct=n_rows,
                                seed=11, interval=0.0)
    capture_path = _os.path.join(root, "capture.json")
    wl.write_capture(capture_path, records)
    records = wl.load_capture(capture_path)

    # Leg 0 — pre-PR baseline: per-constant fingerprints (60-query
    # slice; every fresh constant compiles, so keep the burn bounded).
    yt_config.set_compile_config(
        yt_config.CompileConfig(parameterize=False))
    base_report = wl.replay(client, records[:60], rate=400.0,
                            max_workers=8)
    base_cache = base_report["compile_cache"]

    # Leg 1 — parameterized + persistent artifact tier (the metric).
    # One warmup slice compiles the bounded program set (shape x pow2
    # buckets); the measured replay then serves ~240 distinct constants
    # from it.
    yt_config.set_compile_config(yt_config.CompileConfig(
        parameterize=True, disk_cache_dir=aot_dir))
    from ytsaurus_tpu.query.engine.evaluator import (
        get_compile_observatory,
    )
    obs = get_compile_observatory()
    obs.reset()
    wl.replay(client, records[:60], rate=400.0, max_workers=8)
    best = None
    times = []
    while _iters_left(times, iters):
        t0 = time.perf_counter()
        report = wl.replay(client, records, rate=400.0, max_workers=8)
        times.append(time.perf_counter() - t0)
        if best is None or report["achieved_rate"] > \
                best["achieved_rate"]:
            best = report
    cache = best["compile_cache"]
    steady_rate = cache["steady_hit_rate"] or 0.0
    assert best["ok"] == best["queries"], best
    assert steady_rate >= 0.99, \
        f"steady-state hit rate {steady_rate:.4f} < 0.99"
    # Shape-spectrum acceptance: per fingerprint, the distinct
    # (capacity, binding-shape) programs stay pow2-bounded — 240
    # distinct constants must NOT widen the spectrum.
    spectrum = {r["fingerprint"]: r["shape_count"] for r in obs.top(0)}
    assert spectrum and max(spectrum.values()) <= 8, spectrum

    # Leg 2 — restart warm start: a fresh PROCESS, same artifacts.
    child_src = f"""
import json, sys
from ytsaurus_tpu import config as yt_config
yt_config.set_compile_config(yt_config.CompileConfig(
    parameterize=True, disk_cache_dir={aot_dir!r}))
sys.argv = ["child"]
import bench
client = bench.bench_serving_steady_child({root!r}, {n_rows})
"""
    env = dict(_os.environ, JAX_PLATFORMS=_os.environ.get(
        "JAX_PLATFORMS", "cpu"), BENCH_CHILD="1")
    proc = _subprocess.run(
        [sys.executable, "-c", child_src],
        cwd=_os.path.dirname(_os.path.abspath(__file__)),
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, proc.stderr[-4000:]
    child = json.loads(
        [ln for ln in proc.stdout.splitlines()
         if ln.startswith("{")][-1])
    print(f"# serving_steady: baseline steady hit rate "
          f"{(base_cache['steady_hit_rate'] or 0) * 100:.1f}% "
          f"({base_cache['misses']} compiles) -> parameterized "
          f"{steady_rate * 100:.1f}% ({cache['misses']} misses, "
          f"{cache['fresh_compiles']} fresh); restart leg: "
          f"{child['disk_hits']} disk hits, "
          f"{child['fresh_compiles']} fresh compiles, hit rate "
          f"{(child['hit_rate'] or 0) * 100:.1f}%; "
          f"p99 {best['latency']['p99_ms']:.2f}ms",
          file=sys.stderr)
    assert child["fresh_compiles"] <= 1, child
    assert child["disk_hits"] >= 1, child
    return ("serving_steady_queries_per_sec", best["achieved_rate"],
            best["elapsed_seconds"])


def bench_serving_steady_child(parent_root, n_rows):
    """Restart-warm-start leg of bench_serving_steady, run in a FRESH
    process: rebuild the same table from the same row recipe, replay
    the same capture against the same AOT artifact directory, report
    the compile-cache split as one JSON line."""
    import os as _os
    import tempfile

    from ytsaurus_tpu.client import connect
    from ytsaurus_tpu.query import workload as wl
    from ytsaurus_tpu.schema import TableSchema

    base = tempfile.mkdtemp(prefix="bench-steady-child-")
    client = connect(base)
    schema = TableSchema.make(
        [("k", "int64", "ascending"), ("g", "int64"), ("v", "int64")],
        unique_keys=True)
    client.create("table", "//bench/steady",
                  attributes={"schema": schema, "dynamic": True,
                              "pivot_keys": [[n_rows // 2]]},
                  recursive=True)
    client.mount_table("//bench/steady")
    for lo in range(0, n_rows, 50_000):
        hi = min(lo + 50_000, n_rows)
        client.insert_rows("//bench/steady",
                           [{"k": i, "g": i % 97, "v": i * 3}
                            for i in range(lo, hi)])
    client.freeze_table("//bench/steady")
    records = wl.load_capture(_os.path.join(parent_root,
                                            "capture.json"))
    report = wl.replay(client, records, rate=400.0, max_workers=8)
    cache = report["compile_cache"]
    print(json.dumps({
        "disk_hits": cache["disk_hits"],
        "fresh_compiles": cache["fresh_compiles"],
        "hit_rate": cache["hit_rate"],
        "ok": report["ok"], "queries": report["queries"],
    }), flush=True)
    return client


# Declared per-pool serving SLOs for bench_slo — what the report grades
# p50/p99 against (loose enough for shared CI hosts; the hard
# assertions are the RELATIVE isolation/degradation properties).
_SLO_TARGETS = {
    "prod": {"p50_ms": 100.0, "p99_ms": 500.0},
    "batch": {"p50_ms": 200.0, "p99_ms": 1000.0},
}


def bench_slo(n_rows, iters):
    """Overload-resilient multi-replica serving macro-bench (ISSUE 17):
    the PR 7 open-loop replay mix driven through >= 2 serving replicas
    (each its own cluster + gateway + real HTTP /serving endpoint) via
    the load-aware ReplicaRouter, reporting p50/p99/p999 per pool
    against the declared SLOs.  Five legs:

      baseline   prod + batch mixed at moderate rate; per-pool
                 percentiles recorded (the metric: achieved qps);
      storm      the batch tenant goes greedy (open-loop flood) while
                 prod holds its baseline rate — acceptance: batch p99
                 moves >= 5x its own baseline while prod p99 stays
                 within 1.3x (fair-share isolation), and the brown-out
                 ladder ENGAGES under the storm and DISENGAGES after
                 it drains (rung transitions on /serving);
      join-hot   a THIRD replica built mid-bench joins the router
                 while the mix runs — acceptance: it serves load with
                 ZERO fresh compiles (every program fetched from the
                 cluster AOT artifact store its peers published to);
      control    a fixed chaos-mix replayed fault-free, per-query
                 result digests recorded;
      chaos      the same mix under injected faults (replica death
                 mid-run, routing-scrape failures, artifact-fetch
                 failures) — acceptance: zero lost/duplicated
                 responses, every result digest bit-identical to the
                 fault-free control run."""
    import hashlib
    import os as _os
    import tempfile
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from ytsaurus_tpu import config as yt_config
    from ytsaurus_tpu.chunks.store import FsChunkStore
    from ytsaurus_tpu.client import connect
    from ytsaurus_tpu.config import ServingConfig
    from ytsaurus_tpu.errors import EErrorCode, YtError
    from ytsaurus_tpu.query import workload as wl
    from ytsaurus_tpu.query.engine import aot_cache
    from ytsaurus_tpu.query.routing import ReplicaRouter, RoutedYtClient
    from ytsaurus_tpu.schema import TableSchema
    from ytsaurus_tpu.server.monitoring import MonitoringServer
    from ytsaurus_tpu.utils import failpoints

    root = tempfile.mkdtemp(prefix="bench-slo-")
    # The compile ladder under test: memory -> CLUSTER artifact store
    # (shared blob store, what lets a replica join hot).  The process-
    # global DISK tier stays off — it would hide cluster fetches.
    yt_config.set_compile_config(yt_config.CompileConfig(
        parameterize=True))
    artifact_store = aot_cache.ClusterArtifactStore(
        FsChunkStore(_os.path.join(root, "artifacts")))
    aot_cache.set_cluster_store(artifact_store)

    def serving_config():
        # Tight slots so admission (not raw capacity) shapes latency,
        # and a HARD cap on batch (pool_limits) so the greedy tenant's
        # executing footprint — the thing that contends for CPU with
        # prod — can never exceed 1 slot per replica no matter how
        # idle the rest of the box looks (work-conserving fair share
        # alone would hand it the free slots, and on a shared-CPU host
        # that IS the neighbor's p99).  Deep queue so the storm
        # measures queueing, not rejections; rung-1 threshold above
        # baseline pressure but far below the storm's; rung 2 out of
        # reach so shedding doesn't mask the p99 movement.
        return ServingConfig(
            slots=2, max_queue=10_000, default_pool="prod",
            pools={"prod": 3.0, "batch": 1.0},
            pool_limits={"batch": 1},
            brownout_rung1_seconds=0.4, brownout_rung2_seconds=120.0,
            brownout_min_dwell_seconds=0.5,
            default_staleness_seconds=30.0)

    class _Handle:
        """One replica as the router sees it: a select_rows endpoint
        with a kill switch (simulated replica death) and per-replica
        compile accounting from each query's EXPLAIN ANALYZE stats."""

        def __init__(self, name, client):
            self.name = name
            self.client = client
            self.dead = False
            self.lock = threading.Lock()
            self.served = 0
            self.compile_count = 0
            self.cluster_hits = 0

        def select_rows(self, query, pool=None, timeout=None):
            if self.dead:
                raise YtError(f"replica {self.name} is down",
                              code=EErrorCode.TransportError)
            profile = self.client.select_rows(
                query, pool=pool, timeout=timeout, explain_analyze=True)
            stats = profile.statistics or {}
            with self.lock:
                self.served += 1
                self.compile_count += int(stats.get("compile_count", 0))
                self.cluster_hits += \
                    int(stats.get("compile_cluster_hit", 0))
            return profile.rows

    schema = TableSchema.make(
        [("k", "int64", "ascending"), ("g", "int64"), ("v", "int64")],
        unique_keys=True)

    def make_replica(name):
        client = connect(_os.path.join(root, name))
        client.cluster.serving_config = serving_config()
        client.create("table", "//slo/t",
                      attributes={"schema": schema, "dynamic": True,
                                  "pivot_keys": [[n_rows // 2]]},
                      recursive=True)
        client.mount_table("//slo/t")
        for lo in range(0, n_rows, 50_000):
            hi = min(lo + 50_000, n_rows)
            client.insert_rows("//slo/t",
                               [{"k": i, "g": i % 53, "v": i * 3}
                                for i in range(lo, hi)])
        client.freeze_table("//slo/t")
        monitoring = MonitoringServer()
        monitoring.serving_gateways = [client.cluster.gateway]
        monitoring.start()
        return {"name": name, "client": client,
                "gateway": client.cluster.gateway,
                "monitoring": monitoring,
                "handle": _Handle(name, client)}

    replicas = [make_replica("replica-0"), make_replica("replica-1")]
    router = ReplicaRouter(
        [(r["name"], r["name"], r["monitoring"].address)
         for r in replicas],
        scrape_period=0.2, penalty_seconds=1.0)
    routed = RoutedYtClient(
        router, {r["name"]: r["handle"] for r in replicas})
    router.start()

    shapes = [
        "k, v FROM [//slo/t] WHERE k = {}",
        "g, sum(v) AS s FROM [//slo/t] WHERE v < {} GROUP BY g",
        "k, v FROM [//slo/t] WHERE k > {} ORDER BY k LIMIT 10",
    ]

    def mix(count, pool, seed, rate, start=0.0):
        records = wl.synthesize_mix(shapes, count=count, distinct=64,
                                    seed=seed, pool=pool)
        for i, rec in enumerate(records):
            rec.started_at = start + i / rate
        return records

    def drive(records, timeout=120.0, max_workers=None):
        """Open-loop replay through the routed client: dispatch on each
        record's schedule, never waiting for completions; one result
        slot per record (lost/duplicated responses are structurally
        visible).  The worker pool is sized to the record count so a
        greedy pool's backlog can never starve another pool's DISPATCH
        — starving its admission is the system under test's job."""
        records = sorted(records, key=lambda r: r.started_at)
        results = [None] * len(records)
        if max_workers is None:
            max_workers = len(records) + 4

        def run_one(i, rec):
            t0 = time.perf_counter()
            try:
                rows = routed.select_rows(
                    wl.substitute_literals(rec.query, rec.literals),
                    pool=rec.pool, timeout=timeout)
                outcome, digest = "ok", hashlib.sha1(
                    json.dumps(rows, sort_keys=True,
                               default=str).encode()).hexdigest()
            except YtError as err:
                outcome, digest = wl.outcome_of(err), None
            results[i] = {"pool": rec.pool, "outcome": outcome,
                          "digest": digest,
                          "latency": time.perf_counter() - t0}

        t_start = time.perf_counter()
        with ThreadPoolExecutor(max_workers=max_workers,
                                thread_name_prefix="slo") as pool:
            for i, rec in enumerate(records):
                delay = t_start + rec.started_at - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                pool.submit(run_one, i, rec)
        elapsed = time.perf_counter() - t_start
        return results, elapsed

    def percentiles(results, pool):
        lat = sorted(r["latency"] for r in results
                     if r and r["pool"] == pool and r["outcome"] == "ok")
        if not lat:
            return {"p50_ms": 0.0, "p99_ms": 0.0, "p999_ms": 0.0,
                    "ok": 0}
        def pct(q):
            return round(
                lat[min(int(q * len(lat)), len(lat) - 1)] * 1e3, 3)
        return {"p50_ms": pct(0.50), "p99_ms": pct(0.99),
                "p999_ms": pct(0.999), "ok": len(lat)}

    def brownout_view():
        return {r["name"]:
                r["gateway"].snapshot()["admission"]["brownout"]
                for r in replicas}

    # -- warmup: every shape compiles once per replica (replica-0 first
    # so its publishes seed the artifact store; replica-1's misses then
    # exercise fetch-on-miss before any measured leg).
    warm = wl.synthesize_mix(shapes, count=12, distinct=64, seed=7)
    for r in replicas:
        for rec in warm:
            r["handle"].select_rows(
                wl.substitute_literals(rec.query, rec.literals),
                pool="prod", timeout=30.0)

    # -- calibration: rates scale to THIS host's measured service time
    # (CI boxes span an order of magnitude).  The key design point on
    # a shared-CPU host: the baseline keeps batch's fair-share slots
    # BUSY, so the storm changes only batch's queue depth — its
    # executing footprint (the thing that could slow prod down) is
    # identical in both phases.  That is precisely the isolation
    # fair-share admission promises.
    t_cal = time.perf_counter()
    cal = wl.synthesize_mix(shapes, count=16, distinct=64, seed=9)
    for rec in cal:
        replicas[0]["handle"].select_rows(
            wl.substitute_literals(rec.query, rec.literals),
            pool="prod", timeout=30.0)
    service = (time.perf_counter() - t_cal) / len(cal)
    cap = 1.0 / service            # sequential host capacity, qps
    # Prod's worst-case share under a batch storm is ~cap/2 (batch is
    # hard-capped at 1 of 2 slots per replica); offering prod at
    # cap/4 leaves a 2x margin over calibration noise, so prod never
    # queues structurally in EITHER leg and its p99 measures pure
    # contention — which the design makes identical across legs.
    prod_n = 120
    prod_rate = cap * 0.25
    prod_span = prod_n / prod_rate      # seconds the prod probe runs
    # Batch's real drain rate is NOT derivable from sequential service
    # time (slot caps, cross-replica contention, and scheduler overhead
    # all cut into it) — measure it: burst a cohort through the routed
    # path with prod idle and time the drain.  Everything downstream is
    # sized from this number, so the leg shapes are host-independent.
    burst = mix(max(int(cap * 1.5), 30), "batch", seed=10,
                rate=cap * 50.0)
    burst_results, burst_elapsed = drive(burst)
    batch_drain = len(burst_results) / burst_elapsed    # qps, measured
    # Offered slightly above the measured drain rate FOR PROD'S WHOLE
    # SPAN, so batch's capped executing footprint is saturated in the
    # baseline exactly as it will be under the storm — the storm then
    # moves only batch's own queue, which is the isolation being
    # proven.  (A batch cohort that drains before prod finishes would
    # leave the baseline's tail uncontended and inflate the measured
    # prod move; a grossly over-offered one would pre-build a storm-
    # sized queue and deflate the batch move.)  The burst above ran
    # with prod IDLE; during the legs prod occupies ~prod_rate*service
    # = 0.25 of the core, so batch's effective drain is ~0.75x the
    # measured one — offer against THAT.
    base_batch_rate = batch_drain * 0.75 * 1.10
    base_batch_n = max(int(base_batch_rate * (prod_span + 2.0)), 40)
    storm_rate = cap * 6.0              # the greedy tenant's flood
    # Enough storm queries that the backlog outlives prod's span at
    # the measured drain rate: every prod sample sees the storm, and
    # batch's own queue wait lands near 2x prod_span vs the baseline's
    # ~0.1x — a p99 move of well over 5x by construction, with enough
    # slack that drain-rate measurement noise (which leaks into the
    # baseline's queue growth) can't drag the ratio under the bar.
    storm_batch_n = max(int(batch_drain * prod_span * 2.0), 150)
    batch_cap = cap * 0.25              # nominal share, for reporting

    def settle():
        """Wait for every replica's brown-out ladder to walk back to
        rung 0 (the snapshot read itself drives de-escalation)."""
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            rungs = {name: v["rung"]
                     for name, v in brownout_view().items()}
            if all(r == 0 for r in rungs.values()):
                return rungs
            time.sleep(0.3)
        return rungs

    # -- leg 1: baseline (the metric) ------------------------------------------
    base = None
    times = []
    while _iters_left(times, iters):
        records = mix(prod_n, "prod", seed=21, rate=prod_rate) + \
            mix(base_batch_n, "batch", seed=22,
                rate=base_batch_rate)
        results, elapsed = drive(records)
        times.append(elapsed)
        report = {"results": results, "elapsed": elapsed,
                  "prod": percentiles(results, "prod"),
                  "batch": percentiles(results, "batch")}
        if base is None or elapsed < base["elapsed"]:
            base = report
    lost = [r for r in base["results"] if r is None or
            r["outcome"] != "ok"]
    assert not lost, f"baseline lost/failed {len(lost)} responses"
    baseline_rate = len(base["results"]) / base["elapsed"]

    # -- leg 2: greedy-tenant storm + brown-out ladder -------------------------
    settle()
    engaged_before = sum(v["engaged"] for v in brownout_view().values())
    storm_records = mix(prod_n, "prod", seed=21, rate=prod_rate) + \
        mix(storm_batch_n, "batch", seed=31, rate=storm_rate)
    storm_results, _ = drive(storm_records, timeout=300.0)
    storm_prod = percentiles(storm_results, "prod")
    storm_batch = percentiles(storm_results, "batch")
    print(f"# slo storm: prod {base['prod']} -> {storm_prod} | "
          f"batch {base['batch']} -> {storm_batch}", file=sys.stderr)
    prod_failed = [r for r in storm_results
                   if r and r["pool"] == "prod" and r["outcome"] != "ok"]
    assert not prod_failed, \
        f"prod lost {len(prod_failed)} responses during the storm"
    batch_move = storm_batch["p99_ms"] / max(base["batch"]["p99_ms"],
                                             1e-3)
    prod_move = storm_prod["p99_ms"] / max(base["prod"]["p99_ms"], 1e-3)
    assert batch_move >= 5.0, \
        f"greedy batch p99 moved only {batch_move:.2f}x " \
        f"({base['batch']['p99_ms']} -> {storm_batch['p99_ms']}ms)"
    assert prod_move <= 1.3, \
        f"neighbor prod p99 moved {prod_move:.2f}x " \
        f"({base['prod']['p99_ms']} -> {storm_prod['p99_ms']}ms)"
    after = brownout_view()
    engaged_after = sum(v["engaged"] for v in after.values())
    assert engaged_after > engaged_before, \
        f"brown-out never engaged under the storm: {after}"
    # Disengage on recovery: the storm has drained (drive returned),
    # so after the dwell every replica's ladder must walk back to 0.
    rungs = settle()
    assert all(r == 0 for r in rungs.values()), \
        f"brown-out failed to disengage after recovery: {rungs}"

    # -- leg 3: replica joins hot mid-bench ------------------------------------
    joiner = make_replica("replica-2")
    join_records = mix(120, "prod", seed=41, rate=prod_rate) + \
        mix(50, "batch", seed=42, rate=batch_cap * 0.6)
    join_out = {}

    def run_join_mix():
        join_out["results"], _ = drive(join_records)

    mixer = threading.Thread(target=run_join_mix, daemon=True)
    mixer.start()
    time.sleep(0.8)                        # the mix is mid-flight
    routed.add_replica((joiner["name"], joiner["name"],
                        joiner["monitoring"].address),
                       joiner["handle"])
    replicas.append(joiner)
    mixer.join(timeout=120)
    assert not mixer.is_alive(), "join-hot mix did not complete"
    handle = joiner["handle"]
    assert handle.served > 0, "joining replica was never routed to"
    assert handle.compile_count > 0, \
        "joining replica never loaded a program (mix too small?)"
    fresh = handle.compile_count - handle.cluster_hits
    assert fresh == 0, \
        f"joining replica fresh-compiled {fresh} programs " \
        f"(cluster store should have served them all)"
    join_lost = [r for r in join_out["results"]
                 if r is None or r["outcome"] != "ok"]
    assert not join_lost, \
        f"join-hot leg lost {len(join_lost)} responses"

    # -- legs 4+5: chaos vs fault-free control ---------------------------------
    def chaos_mix():
        return mix(80, "prod", seed=51, rate=prod_rate) + \
            mix(40, "batch", seed=52, rate=batch_cap * 0.5)

    control_results, _ = drive(chaos_mix())
    control = [r["digest"] for r in control_results]
    assert all(r is not None and r["outcome"] == "ok"
               for r in control_results), "control run lost responses"

    failovers_before = router.failovers_n
    by_name = {r["name"]: r for r in replicas}
    victim_cell = []

    def kill_victim():
        time.sleep(1.0)                    # mid-run, not at the edges
        # Kill the replica the router currently FAVORS for prod: pool-
        # aware scoring sends light traffic almost deterministically to
        # the best-scored replica, so killing any OTHER one could sail
        # through the whole leg unpicked and never exercise failover.
        # Favored + dead + monitoring still up reporting an EMPTY queue
        # = traffic keeps landing on the corpse — the failover +
        # quarantine path, not just routing around a pre-flagged peer.
        victim = by_name[router.pick(pool="prod").name]
        victim_cell.append(victim)
        victim["handle"].dead = True       # calls now fail hard...
        # The window spans many scrape periods because the chaos
        # failpoint (`serving.route_scrape=error:p=0.3`) intermittently
        # penalizes the victim into un-pickability; a short window can
        # flakily miss every pick.  Then the endpoint dies too.
        time.sleep(2.0)
        victim["monitoring"].stop()
    killer = threading.Thread(target=kill_victim, daemon=True)
    killer.start()
    with failpoints.active(
            "serving.route_scrape=error:p=0.3;aot.fetch=error:p=0.5",
            seed=17):
        chaos_results, _ = drive(chaos_mix(), timeout=60.0)
    killer.join(timeout=10)
    chaos_lost = [i for i, r in enumerate(chaos_results)
                  if r is None or r["outcome"] != "ok"]
    assert not chaos_lost, \
        f"chaos leg lost {len(chaos_lost)} responses: {chaos_lost[:5]}"
    mismatched = [i for i, r in enumerate(chaos_results)
                  if r["digest"] != control[i]]
    assert not mismatched, \
        f"chaos results diverge from fault-free control at " \
        f"{mismatched[:5]}"
    assert router.failovers_n > failovers_before, \
        "replica death never triggered a failover"

    routing = router.snapshot()
    router.stop()
    victim = victim_cell[0] if victim_cell else None
    for r in replicas:
        if r is not victim:
            r["monitoring"].stop()
    aot_cache.set_cluster_store(None)
    yt_config.set_compile_config(None)

    def grade(pool):
        slo = _SLO_TARGETS[pool]
        got = base[pool]
        return {**got, "slo": slo,
                "met": got["p50_ms"] <= slo["p50_ms"] and
                       got["p99_ms"] <= slo["p99_ms"]}

    print(json.dumps({
        "baseline": {"prod": grade("prod"), "batch": grade("batch"),
                     "achieved_qps": round(baseline_rate, 1)},
        "storm": {"prod": storm_prod, "batch": storm_batch,
                  "batch_p99_move": round(batch_move, 2),
                  "prod_p99_move": round(prod_move, 2),
                  "brownout": after},
        "join_hot": {"served": handle.served,
                     "cluster_hits": handle.cluster_hits,
                     "fresh_compiles": fresh},
        "chaos": {"queries": len(chaos_results), "lost": 0,
                  "mismatched": 0,
                  "failovers": router.failovers_n - failovers_before},
        "artifact_store": artifact_store.snapshot(),
        "routing": {k: v for k, v in routing.items()
                    if k != "replicas"},
    }, indent=2), file=sys.stderr, flush=True)
    return ("slo_baseline_queries_per_sec", baseline_rate,
            base["elapsed"])


def bench_whole_plan(n_rows, iters):
    """Whole-plan fused SPMD execution (ISSUE 12): q1/groupby-class
    plans on the virtual 8-device CPU mesh, three legs per plan —

      stitched-shuffle  CompileConfig.whole_plan OFF, prefer_shuffle
                        (the pre-PR default ladder rung: count program
                        + quota host-sync + exchange program)
      stitched-gather   whole_plan OFF, gather-merge rung
      fused             whole_plan ON: ONE jit(shard_map) program, one
                        final stacked host transfer

    The mesh legs run in a CHILD process (the bench parent is a
    single-device backend; the child forces 8 virtual CPU devices).
    Acceptance: fused ≥2× the BEST stitched rung for both plan classes
    and exactly 1 host sync per fused query (the stitched rungs pay 2).
    Metric is the fused groupby-class throughput."""
    import subprocess as _subprocess

    child_src = f"""
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import json, time
import numpy as np
from ytsaurus_tpu import config as yt_config
from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.parallel.mesh import make_mesh
from ytsaurus_tpu.parallel.distributed import (
    DistributedEvaluator, coordinate_distributed, host_sync_count)
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.query.statistics import QueryStatistics
from ytsaurus_tpu.schema import TableSchema

N = {n_rows}
ITERS = {max(int(iters), 3)}
mesh = make_mesh(8)
rng = np.random.default_rng(1)
per = N // 8

gb_schema = TableSchema.make([("k", "int64", "ascending"),
                              ("g", "int64"), ("v", "int64")])
# Group domain scales with N (~100 rows per group) so smoke-sized runs
# keep the same rows:groups ratio as the default config.
n_groups = max(64, N // 100)
gb_chunks = [ColumnarChunk.from_arrays(gb_schema, {{
    "k": np.arange(per) + s * per,
    "g": rng.integers(0, n_groups, per),
    "v": rng.integers(0, 1000, per)}}) for s in range(8)]
gb_plan = build_query(
    "g, sum(v) AS s, count(*) AS c FROM [//t] GROUP BY g",
    {{"//t": gb_schema}})

q1_schema = TableSchema.make([("rf", "int64"), ("ls", "int64"),
                              ("qty", "double"), ("price", "double")])
q1_chunks = [ColumnarChunk.from_arrays(q1_schema, {{
    "rf": rng.integers(0, 3, per), "ls": rng.integers(0, 2, per),
    "qty": rng.uniform(1, 50, per),
    "price": rng.uniform(1, 1e5, per)}}) for s in range(8)]
q1_plan = build_query(
    "rf, ls, sum(qty) AS sq, sum(price) AS sp, avg(qty) AS aq, "
    "avg(price) AS ap, count(*) AS c FROM [//t] GROUP BY rf, ls",
    {{"//t": q1_schema}})


def leg(plan, chunks, whole, prefer_shuffle=True):
    yt_config.set_compile_config(
        yt_config.CompileConfig(whole_plan=whole))
    de = DistributedEvaluator(mesh)
    stats = QueryStatistics()
    out = coordinate_distributed(plan, mesh, chunks, evaluator=de,
                                 prefer_shuffle=prefer_shuffle,
                                 stats=stats)                  # warm-up
    times = []
    s0 = host_sync_count()
    for _ in range(ITERS):
        t0 = time.perf_counter()
        out = coordinate_distributed(plan, mesh, chunks, evaluator=de,
                                     prefer_shuffle=prefer_shuffle)
        np.asarray(next(iter(out.columns.values())).data[:1])
        times.append(time.perf_counter() - t0)
    return {{"best_s": min(times),
             "syncs_per_query": (host_sync_count() - s0) / ITERS,
             "whole_plan": stats.whole_plan, "rows": out.row_count}}


report = {{}}
for name, plan, chunks in (("groupby", gb_plan, gb_chunks),
                           ("q1", q1_plan, q1_chunks)):
    report[name] = {{
        "stitched_shuffle": leg(plan, chunks, False, True),
        "stitched_gather": leg(plan, chunks, False, False),
        "fused": leg(plan, chunks, True),
    }}
print("REPORT " + json.dumps(report), flush=True)
"""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = _subprocess.run(
        [sys.executable, "-c", child_src],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True, text=True, timeout=3000, env=env)
    assert proc.returncode == 0, proc.stderr[-4000:]
    report = json.loads(
        [ln for ln in proc.stdout.splitlines()
         if ln.startswith("REPORT ")][-1][len("REPORT "):])
    for name, legs in report.items():
        fused = legs["fused"]
        best_stitched = min(legs["stitched_shuffle"]["best_s"],
                            legs["stitched_gather"]["best_s"])
        speedup = best_stitched / fused["best_s"]
        print(f"# whole_plan {name}: stitched-shuffle "
              f"{legs['stitched_shuffle']['best_s']*1e3:.0f}ms "
              f"({legs['stitched_shuffle']['syncs_per_query']:.0f} "
              f"syncs/query), stitched-gather "
              f"{legs['stitched_gather']['best_s']*1e3:.0f}ms, fused "
              f"{fused['best_s']*1e3:.0f}ms "
              f"({fused['syncs_per_query']:.0f} sync/query, "
              f"{n_rows / fused['best_s']:.0f} rows/s) -> "
              f"{speedup:.2f}x vs best stitched rung", file=sys.stderr)
        assert fused["whole_plan"] == 1, name
        assert fused["syncs_per_query"] == 1.0, \
            f"{name}: fused path must host-sync exactly once per query"
        assert legs["stitched_shuffle"]["syncs_per_query"] >= 2.0, name
        assert speedup >= 2.0, \
            (f"{name}: fused {fused['best_s']:.3f}s not >=2x best "
             f"stitched {best_stitched:.3f}s")
    best = report["groupby"]["fused"]["best_s"]
    return "whole_plan_rows_per_sec", n_rows / best, best


def bench_mesh_overhead(n_rows, iters):
    """Mesh telemetry overhead (ISSUE 20): the fused whole-plan rung
    with the in-program telemetry block disarmed vs armed, for the
    round-8 groupby and q1 plan shapes on the virtual 8-device mesh.

    The armed program appends its telemetry lanes (per-shard rows,
    transfer matrices, quota demand) onto the SAME stacked final
    transfer, so arming must cost neither a host sync nor measurable
    wall time.  The ≤1% claim is asserted as a deterministic
    decomposition (the bench_telemetry_overhead discipline — a direct
    A/B on a noisy shared host cannot resolve 1%): exactly 1 host sync
    per query on BOTH legs (the telemetry's whole device cost rides a
    transfer the query already pays), and the per-query host
    decode+publish cost — measured as a per-site microbench — must be
    ≤1% of the disarmed query wall.  The armed/disarmed A/B delta is
    still measured and printed for the record, with a loose 1.5×
    outlier guard against a genuinely broken armed program.  Metric is
    the armed groupby-class throughput."""
    import subprocess as _subprocess

    child_src = f"""
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import json, time
import numpy as np
from ytsaurus_tpu import config as yt_config
from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.parallel.mesh import make_mesh
from ytsaurus_tpu.parallel.distributed import (
    DistributedEvaluator, coordinate_distributed, host_sync_count)
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.query.statistics import QueryStatistics
from ytsaurus_tpu.schema import TableSchema

N = {n_rows}
ITERS = {max(int(iters), 3)}
mesh = make_mesh(8)
rng = np.random.default_rng(1)
per = N // 8

gb_schema = TableSchema.make([("k", "int64", "ascending"),
                              ("g", "int64"), ("v", "int64")])
n_groups = max(64, N // 100)
gb_chunks = [ColumnarChunk.from_arrays(gb_schema, {{
    "k": np.arange(per) + s * per,
    "g": rng.integers(0, n_groups, per),
    "v": rng.integers(0, 1000, per)}}) for s in range(8)]
gb_plan = build_query(
    "g, sum(v) AS s, count(*) AS c FROM [//t] GROUP BY g",
    {{"//t": gb_schema}})

q1_schema = TableSchema.make([("rf", "int64"), ("ls", "int64"),
                              ("qty", "double"), ("price", "double")])
q1_chunks = [ColumnarChunk.from_arrays(q1_schema, {{
    "rf": rng.integers(0, 3, per), "ls": rng.integers(0, 2, per),
    "qty": rng.uniform(1, 50, per),
    "price": rng.uniform(1, 1e5, per)}}) for s in range(8)]
q1_plan = build_query(
    "rf, ls, sum(qty) AS sq, sum(price) AS sp, avg(qty) AS aq, "
    "avg(price) AS ap, count(*) AS c FROM [//t] GROUP BY rf, ls",
    {{"//t": q1_schema}})

yt_config.set_compile_config(yt_config.CompileConfig(whole_plan=True))


def leg(plan, chunks, armed):
    yt_config.set_telemetry_config(
        yt_config.TelemetryConfig(mesh_telemetry=armed))
    de = DistributedEvaluator(mesh)
    stats = QueryStatistics()
    out = coordinate_distributed(plan, mesh, chunks, evaluator=de,
                                 stats=stats)                  # warm-up
    times = []
    s0 = host_sync_count()
    for _ in range(ITERS):
        t0 = time.perf_counter()
        out = coordinate_distributed(plan, mesh, chunks, evaluator=de)
        np.asarray(next(iter(out.columns.values())).data[:1])
        times.append(time.perf_counter() - t0)
    return {{"best_s": min(times),
             "syncs_per_query": (host_sync_count() - s0) / ITERS,
             "whole_plan": stats.whole_plan, "rows": out.row_count,
             "mesh_blocks": len(stats.mesh_blocks),
             "skew": stats.mesh_skew_max}}


report = {{}}
for name, plan, chunks in (("groupby", gb_plan, gb_chunks),
                           ("q1", q1_plan, q1_chunks)):
    report[name] = {{"off": leg(plan, chunks, False),
                     "on": leg(plan, chunks, True)}}

# Per-site microbench of the armed path's ENTIRE host-side addition:
# decode the appended lanes of a representative exchange-shape vector
# (n=8: version + 2x8 row lanes + the 64-cell transfer matrix) and fan
# the block out to stats + observatory + sensors.
from ytsaurus_tpu.parallel import whole_plan as wp
yt_config.set_telemetry_config(yt_config.TelemetryConfig())
vals = np.zeros(3 + 1 + 16 + 64, dtype=np.int64)
vals[3] = wp.MESH_TELEMETRY_VERSION
vals[4:12] = 1000
vals[12:20] = 900
vals[20:] = 100
decode_stats = QueryStatistics()

def decode_once():
    in_rows, out_rows, off = wp._mesh_slices(vals, 3, 8)
    entry = wp._mesh_exchange_entry("shuffle/bench", vals[off: off + 64],
                                    500, 512, 33)
    block = wp._mesh_block(8, in_rows, out_rows, [entry])
    wp._publish_mesh(decode_stats, "bench-fp", None, block)

decode_cost = float("inf")
for _ in range(5):
    t0 = time.perf_counter()
    for _ in range(2000):
        decode_once()
    decode_cost = min(decode_cost, (time.perf_counter() - t0) / 2000)
    decode_stats.mesh_blocks.clear()
report["decode_cost_s"] = decode_cost
print("REPORT " + json.dumps(report), flush=True)
"""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = _subprocess.run(
        [sys.executable, "-c", child_src],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True, text=True, timeout=3000, env=env)
    assert proc.returncode == 0, proc.stderr[-4000:]
    report = json.loads(
        [ln for ln in proc.stdout.splitlines()
         if ln.startswith("REPORT ")][-1][len("REPORT "):])
    decode_cost = report.pop("decode_cost_s")
    print(f"# mesh_overhead decode+publish per query: "
          f"{decode_cost * 1e6:.1f} µs", file=sys.stderr)
    for name, legs in report.items():
        off, on = legs["off"], legs["on"]
        delta = on["best_s"] / off["best_s"] - 1.0
        print(f"# mesh_overhead {name}: disarmed "
              f"{off['best_s']*1e3:.1f}ms, armed {on['best_s']*1e3:.1f}ms "
              f"({delta*100:+.2f}% A/B, for the record), "
              f"{on['syncs_per_query']:.0f} sync/query armed, "
              f"{on['mesh_blocks']} blocks (skew {on['skew']:.3f})",
              file=sys.stderr)
        assert off["whole_plan"] == 1 and on["whole_plan"] == 1, name
        assert off["rows"] == on["rows"], name
        assert off["syncs_per_query"] == 1.0, \
            f"{name}: disarmed fused path must host-sync exactly once"
        assert on["syncs_per_query"] == 1.0, \
            f"{name}: ARMED fused path must still host-sync exactly " \
            f"once — telemetry rides the existing stacked transfer"
        assert on["mesh_blocks"] >= 1 and on["skew"] >= 1.0, \
            f"{name}: armed leg decoded no telemetry block"
        # The ≤1% budget, decomposed: the armed path's host-side
        # addition per query vs the disarmed query wall.
        assert decode_cost <= off["best_s"] * 0.01, \
            (f"{name}: telemetry decode+publish {decode_cost*1e6:.0f}µs "
             f"exceeds 1% of the disarmed query "
             f"({off['best_s']*1e3:.1f}ms)")
        assert on["best_s"] <= off["best_s"] * 1.5 + 0.1, \
            (f"{name}: armed leg {on['best_s']:.4f}s grossly over "
             f"disarmed {off['best_s']:.4f}s — the armed program is "
             f"broken, not noisy")
    best = report["groupby"]["on"]["best_s"]
    return "mesh_overhead_rows_per_sec", n_rows / best, best


def bench_multiway_join(n_rows, iters):
    """Fused multiway join + cost-based planner (ISSUE 14): TPC-H
    Q5/Q7-class 3-way join plans on the virtual 8-device CPU mesh,
    two legs per plan —

      cascade  CompileConfig.whole_plan OFF, the stitched binary
               cascade (`_run_partitioned`: per join a count program +
               quota host sync, a route+probe program + totals host
               sync, an expand program; then the stitched finish)
      fused    whole_plan ON: planner-ordered broadcast/partition joins
               inside ONE jit(shard_map) program — one host sync, the
               exchange/expansion quotas memoized

    Acceptance: fused ≥2× the cascade on both plans, exactly 1 host
    sync per fused query.  Metric is the fused Q5-class throughput
    (fact rows/s)."""
    import subprocess as _subprocess

    child_src = f"""
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import json, time
import numpy as np
from ytsaurus_tpu import config as yt_config
from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.parallel.mesh import make_mesh
from ytsaurus_tpu.parallel.distributed import (
    DistributedEvaluator, coordinate_distributed, host_sync_count)
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.query.statistics import QueryStatistics
from ytsaurus_tpu.schema import TableSchema

N = {n_rows}
ITERS = {max(int(iters), 3)}
mesh = make_mesh(8)
rng = np.random.default_rng(14)
per = N // 8

# TPC-H-class star: lineitem fact, orders (fact-adjacent, too big to
# broadcast -> partition exchange), customer + nation (broadcast dims).
n_orders = max(N // 4, 70_000)      # above broadcast_join_rows
n_cust = 10_000
nations = [f"nation{{i:02d}}" for i in range(25)]
li_schema = TableSchema.make([("l_ok", "int64"), ("l_sk", "int64"),
                              ("price", "double")])
o_schema = TableSchema.make([("o_ok", "int64"), ("o_ck", "int64")])
c_schema = TableSchema.make([("c_ck", "int64"), ("c_nk", "int64")])
n_schema = TableSchema.make([("n_nk", "int64"), ("n_name", "string")])
s_schema = TableSchema.make([("s_sk", "int64"), ("s_nk", "int64")])

li_chunks = [ColumnarChunk.from_arrays(li_schema, {{
    "l_ok": rng.integers(0, n_orders, per),
    "l_sk": rng.integers(0, 1000, per),
    "price": rng.uniform(1, 1e4, per)}}) for s in range(8)]
orders = ColumnarChunk.from_arrays(o_schema, {{
    "o_ok": np.arange(n_orders),
    "o_ck": rng.integers(0, n_cust, n_orders)}})
customer = ColumnarChunk.from_arrays(c_schema, {{
    "c_ck": np.arange(n_cust), "c_nk": rng.integers(0, 25, n_cust)}})
nation = ColumnarChunk.from_rows(
    n_schema, [(i, nations[i]) for i in range(25)])
supplier = ColumnarChunk.from_arrays(s_schema, {{
    "s_sk": np.arange(1000), "s_nk": rng.integers(0, 25, 1000)}})
schemas = {{"//li": li_schema, "//o": o_schema, "//c": c_schema,
           "//n": n_schema, "//s": s_schema}}
foreign = {{"//o": orders, "//c": customer, "//n": nation,
           "//s": supplier}}

# Q5 class: 4-way chain through orders -> customer -> nation.
q5 = build_query(
    "n_name, sum(price) AS rev, count(*) AS c FROM [//li] "
    "JOIN [//o] ON l_ok = o_ok JOIN [//c] ON o_ck = c_ck "
    "JOIN [//n] ON c_nk = n_nk GROUP BY n_name "
    "ORDER BY n_name LIMIT 32", schemas)
# Q7 class: supplier-side 3-way.
q7 = build_query(
    "n_name, sum(price) AS rev FROM [//li] "
    "JOIN [//s] ON l_sk = s_sk JOIN [//n] ON s_nk = n_nk "
    "GROUP BY n_name ORDER BY n_name LIMIT 32", schemas)


from ytsaurus_tpu.parallel.distributed import ShardedTable
table = ShardedTable.from_chunks(mesh, li_chunks)


def leg(plan, mode):
    # cascade   the stitched binary cascade (_run_partitioned: count/
    #           probe/expand programs + 2 host syncs PER join) — the
    #           pre-ISSUE-14 multiway shape the acceptance compares to
    # stitched  whole_plan OFF through the ladder (broadcast-gather
    #           rung when every dim proves unique keys)
    # fused     whole_plan ON: one program, one sync
    yt_config.set_compile_config(
        yt_config.CompileConfig(whole_plan=(mode == "fused")))
    de = DistributedEvaluator(mesh)
    stats = QueryStatistics()

    def run_once(stats=None):
        if mode == "cascade":
            return de.run(plan, table, foreign, shuffle=True)
        return coordinate_distributed(plan, mesh, li_chunks, foreign,
                                      evaluator=de, stats=stats)

    out = run_once(stats)                                    # warm-up
    times = []
    s0 = host_sync_count()
    for _ in range(ITERS):
        t0 = time.perf_counter()
        out = run_once()
        np.asarray(next(iter(out.columns.values())).data[:1])
        times.append(time.perf_counter() - t0)
    return {{"best_s": min(times),
             "syncs_per_query": (host_sync_count() - s0) / ITERS,
             "whole_plan": stats.whole_plan, "rows": out.row_count,
             "join_plan": stats.join_plan}}


report = {{}}
for name, plan in (("q5", q5), ("q7", q7)):
    report[name] = {{"cascade": leg(plan, "cascade"),
                     "stitched": leg(plan, "stitched"),
                     "fused": leg(plan, "fused")}}
print("REPORT " + json.dumps(report), flush=True)
"""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = _subprocess.run(
        [sys.executable, "-c", child_src],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True, text=True, timeout=3000, env=env)
    assert proc.returncode == 0, proc.stderr[-4000:]
    report = json.loads(
        [ln for ln in proc.stdout.splitlines()
         if ln.startswith("REPORT ")][-1][len("REPORT "):])
    for name, legs in report.items():
        fused = legs["fused"]
        cascade = legs["cascade"]
        stitched = legs["stitched"]
        speedup = cascade["best_s"] / fused["best_s"]
        strategies = [e["strategy"] for e in fused["join_plan"] if e]
        print(f"# multiway_join {name}: cascade "
              f"{cascade['best_s']*1e3:.0f}ms "
              f"({cascade['syncs_per_query']:.0f} syncs/query), "
              f"stitched-gather {stitched['best_s']*1e3:.0f}ms "
              f"({stitched['syncs_per_query']:.0f}), fused "
              f"{fused['best_s']*1e3:.0f}ms "
              f"({fused['syncs_per_query']:.0f} sync/query, "
              f"strategies {strategies}, "
              f"{n_rows / fused['best_s']:.0f} rows/s) -> "
              f"{speedup:.2f}x vs stitched cascade", file=sys.stderr)
        assert fused["whole_plan"] == 1, name
        assert fused["syncs_per_query"] == 1.0, \
            f"{name}: fused multiway join must host-sync exactly once"
        assert cascade["syncs_per_query"] >= 3.0, name
        assert fused["rows"] == cascade["rows"] == stitched["rows"], name
        assert speedup >= 2.0, \
            (f"{name}: fused {fused['best_s']:.3f}s not >=2x cascade "
             f"{cascade['best_s']:.3f}s")
    best = report["q5"]["fused"]["best_s"]
    return "multiway_join_rows_per_sec", n_rows / best, best


def bench_scan(n_rows, iters):
    """Versioned MVCC read path (ISSUE 4): snapshot reads over a tablet
    with three flushed version generations (overwrites, deletes, partial
    writes) plus live store churn.  The emitted metric is the WARM
    snapshot-cache path (repeated selects at the current timestamp);
    the cold vectorized merge and the retained pre-PR Python reference
    merge print on stderr with speedups.  n_rows sizes the key space;
    total versions ≈ 1.55×."""
    import tempfile

    import numpy as np

    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    from ytsaurus_tpu.chunks.store import FsChunkStore
    from ytsaurus_tpu.schema import TableSchema
    from ytsaurus_tpu.tablet.tablet import Tablet, versioned_schema

    schema = TableSchema.make([("k", "int64", "ascending"),
                               ("g", "int64"), ("v", "int64")],
                              unique_keys=True)
    tablet = Tablet(schema, FsChunkStore(
        tempfile.mkdtemp(prefix="bench-scan-")))
    vschema = versioned_schema(schema)
    rng = np.random.default_rng(7)

    def publish(arrays, valids):
        chunk = ColumnarChunk.from_arrays(vschema, arrays, valids=valids)
        tablet.chunk_ids.append(tablet.chunk_store.write_chunk(chunk))

    n = n_rows
    keys0 = np.arange(n, dtype=np.int64)
    ones = np.ones(n, dtype=bool)
    publish({"k": keys0, "$timestamp": np.full(n, 100, np.int64),
             "$tombstone": np.zeros(n, dtype=bool),
             "g": keys0 % 1000, "$w:g": ones,
             "v": keys0 * 3, "$w:v": ones},
            valids={})
    # Generation 2: a third of the keys overwritten, a fifth of THOSE
    # deleted (tombstones bound the merge for their keys).
    m1 = max(n // 3, 1)
    k1 = np.sort(rng.choice(n, size=m1, replace=False)).astype(np.int64)
    tomb = np.zeros(m1, dtype=bool)
    tomb[:: 5] = True
    publish({"k": k1, "$timestamp": np.full(m1, 200, np.int64),
             "$tombstone": tomb,
             "g": k1 % 500, "$w:g": ~tomb,
             "v": k1 * 7, "$w:v": ~tomb},
            valids={"g": ~tomb, "v": ~tomb})
    # Generation 3: partial writes — only `v` stated, `g` merges from
    # older generations per column.
    m2 = max(n // 5, 1)
    k2 = np.sort(rng.choice(n, size=m2, replace=False)).astype(np.int64)
    publish({"k": k2, "$timestamp": np.full(m2, 300, np.int64),
             "$tombstone": np.zeros(m2, dtype=bool),
             "g": np.zeros(m2, np.int64),
             "$w:g": np.zeros(m2, dtype=bool),
             "v": k2 * 11, "$w:v": np.ones(m2, dtype=bool)},
            valids={"g": np.zeros(m2, dtype=bool)})
    # Live store churn on top of the sealed chunks.
    for i in range(1024):
        tablet.write_row({"k": int(n + i), "g": i, "v": i}, timestamp=400)

    t0 = time.perf_counter()
    ref = tablet.read_snapshot_reference()
    ref_time = time.perf_counter() - t0
    versions = n + m1 + m2 + 1024

    def timed_read(invalidate):
        times = []
        while _iters_left(times, iters):
            if invalidate:
                tablet._snapshot_cache = None
            t0 = time.perf_counter()
            out = tablet.read_snapshot()
            _sync(out.columns["k"].data)
            times.append(time.perf_counter() - t0)
        return min(times), out

    cold_time, out = timed_read(invalidate=True)
    assert out.row_count == ref.row_count, (out.row_count, ref.row_count)
    tablet.read_snapshot()                        # prime the cache
    warm_time, _ = timed_read(invalidate=False)
    ref_rps = versions / ref_time
    print(f"# scan: warm cache {versions / warm_time:.0f} rows/s "
          f"({warm_time * 1e3:.2f}ms), cold vectorized "
          f"{versions / cold_time:.0f} rows/s ({cold_time * 1e3:.1f}ms), "
          f"reference {ref_rps:.0f} rows/s ({ref_time * 1e3:.0f}ms); "
          f"warm {ref_time / warm_time:.0f}x, cold "
          f"{ref_time / cold_time:.1f}x vs pre-PR merge "
          f"({versions} versions, {out.row_count} visible)",
          file=sys.stderr)
    return "scan_rows_per_sec", versions / warm_time, warm_time


# config -> (fn, default rows on an accelerator, default rows on CPU)
def bench_matview(n_rows, iters):
    """Continuous queries (ISSUE 13): sustained ordered-table ingest
    with an incrementally maintained GROUP BY view (sum/count/avg by a
    97-ary key), exactly-once refresh per micro-batch.

      ingest     the metric: source rows/s through push + incremental
                 refresh (delta-merge into the sorted target), with
                 end-to-end freshness lag (push → committed visibility)
                 reported p50/p99 over the waves;
      steady     fresh-compile count across the measured waves must be
                 ZERO after warmup — one parameterized plan per view,
                 fixed pow2 batch capacity (the ISSUE 13 acceptance);
      restart    (a) in-process daemon-restart analog: a FRESH
                 evaluator + refresher resumes from committed offsets
                 with 0 fresh compiles (AOT disk tier), (b) a fresh
                 CHILD PROCESS builds the same view against the same
                 artifact dir and also refreshes with 0 fresh compiles.

    Correctness is asserted against the full-recompute oracle at the
    end of every leg."""
    import os as _os
    import subprocess as _subprocess
    import tempfile

    from ytsaurus_tpu import config as yt_config
    from ytsaurus_tpu.client import connect
    from ytsaurus_tpu.query.engine.evaluator import (
        Evaluator,
        get_compile_observatory,
    )
    from ytsaurus_tpu.query.views import ViewRefresher, load_view
    from ytsaurus_tpu.schema import TableSchema

    root = tempfile.mkdtemp(prefix="bench-matview-")
    aot_dir = _os.path.join(root, "aot")
    yt_config.set_compile_config(yt_config.CompileConfig(
        parameterize=True, disk_cache_dir=aot_dir))
    batch_rows = 16_384
    wave_rows = max(min(n_rows // 8, 4 * batch_rows), batch_rows)

    def make_rows(lo, n):
        return [{"k": lo + i, "g": (lo + i) % 97,
                 "v": float((lo + i) % 1013)} for i in range(n)]

    client = connect(root)
    schema = TableSchema.make([("k", "int64"), ("g", "int64"),
                               ("v", "double")])
    client.create("table", "//bench/stream", recursive=True,
                  attributes={"schema": schema, "dynamic": True})
    client.mount_table("//bench/stream")
    query = ("g, sum(v) AS s, count(*) AS c, avg(v) AS a "
             "FROM [//bench/stream] GROUP BY g")
    client.create_materialized_view("agg", query,
                                    batch_rows=batch_rows)
    refresher = ViewRefresher(client, load_view(client, "agg"))
    obs = get_compile_observatory()

    # Warmup: full and partial batches cover the (fixed) batch capacity
    # and the merge-combine shapes; everything compiles here (and lands
    # in the AOT disk tier for the restart legs).
    client.push_queue("//bench/stream", make_rows(0, batch_rows))
    refresher.refresh()
    client.push_queue("//bench/stream",
                      make_rows(batch_rows, batch_rows // 3))
    refresher.refresh()
    pushed = batch_rows + batch_rows // 3

    def canon(rows):
        return sorted(tuple((k, round(v, 6) if isinstance(v, float)
                             else v) for k, v in sorted(r.items()))
                      for r in rows)

    def check_oracle():
        got = canon(client.select_rows(
            "g, s, c, a FROM [//sys/views/agg/target]"))
        want = canon(client.select_rows(query))
        assert got == want, "view diverged from the recompute oracle"

    # Measured leg: sustained ingest waves; steady state must be
    # compile-free.
    before = obs.totals()
    waves = []
    ingested = 0
    n_waves = max(4, n_rows // wave_rows)
    t_leg = time.perf_counter()
    while len(waves) < n_waves and _iters_left(waves, n_waves):
        t0 = time.perf_counter()
        client.push_queue("//bench/stream", make_rows(pushed, wave_rows))
        pushed += wave_rows
        ingested += wave_rows
        report = refresher.refresh()
        assert report["lag_rows"] == 0
        waves.append(time.perf_counter() - t0)
    elapsed = time.perf_counter() - t_leg
    after = obs.totals()
    assert after["misses"] == before["misses"], \
        f"steady-state refresh compiled: {before} -> {after}"
    check_oracle()
    lags = sorted(waves)
    p50 = lags[len(lags) // 2]
    p99 = lags[min(len(lags) - 1, int(len(lags) * 0.99))]

    # Restart leg (in-process): a fresh evaluator = an empty in-memory
    # compile cache, i.e. a restarted daemon.  It must resume from the
    # committed offsets and serve every program from the AOT disk tier.
    client.cluster.evaluator = Evaluator()
    restarted = ViewRefresher(client, load_view(client, "agg"))
    before = obs.totals()
    client.push_queue("//bench/stream", make_rows(pushed, wave_rows))
    pushed += wave_rows
    report = restarted.refresh()
    after = obs.totals()
    restart_misses = after["misses"] - before["misses"]
    restart_disk = after["disk_hits"] - before["disk_hits"]
    assert restart_misses == restart_disk, \
        f"restart compiled fresh: {restart_misses} misses, " \
        f"{restart_disk} disk hits"
    assert report["rows_in"] == wave_rows, report
    check_oracle()

    # Restart leg (cross-process): same artifacts, fresh interpreter.
    child_src = f"""
import json, sys
from ytsaurus_tpu import config as yt_config
yt_config.set_compile_config(yt_config.CompileConfig(
    parameterize=True, disk_cache_dir={aot_dir!r}))
sys.argv = ["child"]
import bench
bench.bench_matview_child({batch_rows})
"""
    env = dict(_os.environ, JAX_PLATFORMS=_os.environ.get(
        "JAX_PLATFORMS", "cpu"), BENCH_CHILD="1")
    proc = _subprocess.run(
        [sys.executable, "-c", child_src],
        cwd=_os.path.dirname(_os.path.abspath(__file__)),
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, proc.stderr[-4000:]
    child = json.loads([ln for ln in proc.stdout.splitlines()
                        if ln.startswith("{")][-1])
    assert child["fresh_compiles"] == 0, child
    assert child["disk_hits"] >= 1, child

    rate = (len(waves) * wave_rows) / elapsed
    print(f"# matview: {len(waves)} waves x {wave_rows} rows, "
          f"freshness p50 {p50 * 1e3:.1f}ms p99 {p99 * 1e3:.1f}ms, "
          f"steady fresh compiles 0 (asserted); restart leg "
          f"{restart_misses} misses all from disk; child process "
          f"{child['disk_hits']} disk hits, "
          f"{child['fresh_compiles']} fresh",
          file=sys.stderr)
    return "matview_rows_per_sec", rate, min(waves)


def bench_matview_child(batch_rows):
    """Cross-process restart leg of bench_matview: rebuild an identical
    view in a FRESH interpreter against the SAME AOT artifact directory;
    every program must come back from disk (0 fresh compiles)."""
    import tempfile

    from ytsaurus_tpu.client import connect
    from ytsaurus_tpu.query.engine.evaluator import (
        get_compile_observatory,
    )
    from ytsaurus_tpu.query.views import ViewRefresher, load_view
    from ytsaurus_tpu.schema import TableSchema

    client = connect(tempfile.mkdtemp(prefix="bench-matview-child-"))
    schema = TableSchema.make([("k", "int64"), ("g", "int64"),
                               ("v", "double")])
    client.create("table", "//bench/stream", recursive=True,
                  attributes={"schema": schema, "dynamic": True})
    client.mount_table("//bench/stream")
    client.create_materialized_view(
        "agg", "g, sum(v) AS s, count(*) AS c, avg(v) AS a "
               "FROM [//bench/stream] GROUP BY g",
        batch_rows=batch_rows)
    client.push_queue("//bench/stream", [
        {"k": i, "g": i % 97, "v": float(i % 1013)}
        for i in range(batch_rows + batch_rows // 3)])
    obs = get_compile_observatory()
    obs.reset()
    ViewRefresher(client, load_view(client, "agg")).refresh()
    totals = obs.totals()
    print(json.dumps({
        "disk_hits": totals["disk_hits"],
        "fresh_compiles": totals["misses"] - totals["disk_hits"],
    }), flush=True)


def bench_sanitizer_overhead(n_rows, iters):
    """Concurrency sanitizer (ISSUE 15): the DISABLED path must be a
    plain-lock no-op — `sanitizers.register_lock()` without
    YT_TPU_SANITIZE hands back the raw `threading.Lock`, so its
    per-acquire cost must match a plain lock within noise (asserted
    ≲0.1µs delta) — and the ENABLED path's per-acquire cost is recorded
    (held-set bookkeeping + edge probe; tier-1 pays it suite-wide, so
    the number feeds the 870s-budget arithmetic).  The emitted metric is
    enabled-path acquires/s with one lock held (the edge-probing case,
    i.e. the EXPENSIVE one)."""
    import threading

    from ytsaurus_tpu.utils import sanitizers

    n_round = min(n_rows, 400_000)

    def per_acquire(lock, rounds=7):
        best = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            for _ in range(n_round):
                with lock:
                    pass
            best = min(best, (time.perf_counter() - t0) / n_round)
        return best

    plain_cost = per_acquire(threading.Lock())

    assert not sanitizers.enabled(), \
        "bench must run with the sanitizer DISABLED (unset " \
        "YT_TPU_SANITIZE) to measure the production fast path"
    registered = sanitizers.register_lock("bench.sanitizer._lock")
    assert type(registered) is type(threading.Lock()), \
        "disabled register_lock must return the PLAIN lock, no wrapper"
    disabled_cost = per_acquire(registered)

    san = sanitizers.LockSanitizer()
    inst = sanitizers.InstrumentedLock(san, "bench.inst._lock")
    outer = sanitizers.InstrumentedLock(san, "bench.outer._lock")
    enabled_leaf_cost = per_acquire(inst)
    with outer:                         # one lock held: edge probe runs
        enabled_nested_cost = per_acquire(inst)

    delta = disabled_cost - plain_cost
    print(f"# sanitizer acquire costs: plain {plain_cost * 1e9:.0f} ns, "
          f"disabled-registered {disabled_cost * 1e9:.0f} ns "
          f"(delta {delta * 1e9:+.0f} ns), enabled leaf "
          f"{enabled_leaf_cost * 1e9:.0f} ns, enabled nested "
          f"{enabled_nested_cost * 1e9:.0f} ns", file=sys.stderr)
    assert abs(delta) < 0.1e-6, \
        f"disabled path must be a plain-lock no-op: " \
        f"{delta * 1e9:+.0f} ns/acquire delta vs plain threading.Lock"
    assert san.counters()["edges_observed"] == 1    # outer -> inst

    best = enabled_nested_cost * n_round
    return ("sanitizer_acquires_per_sec", 1.0 / enabled_nested_cost,
            best)


def bench_vector(n_rows, iters):
    """Vector similarity serving (ISSUE 16): the batched NEAREST kernel
    — ONE `(batch, dim) @ (dim, rows)` distance matmul + per-row top-k
    — swept over (dim × k × batch) on the n_rows-vector corpus, plus an
    8-device whole-plan NEAREST leg in a child process (the mesh path:
    per-shard top-k, one gather, exactly one host sync).

    Per-point lines report queries/s and vectors-scanned/s (the batch
    amortization story: batch=64 should scan ~an order of magnitude
    more vectors/s than batch=1 because the matmul reuses the corpus
    plane across the batch dimension).  The emitted metric is
    vectors-scanned/s at the serving sweet spot (dim=256, k=8,
    batch=64)."""
    import subprocess as _subprocess

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ytsaurus_tpu.query.vector import _nearest_jit

    rng = np.random.default_rng(3)
    valid = jnp.ones(n_rows, dtype=bool)
    headline = None
    for dim in (64, 256):
        plane = jnp.asarray(
            rng.standard_normal((n_rows, dim), dtype=np.float32))
        for k in (8, 64):
            for batch in (1, 16, 64):
                q = jnp.asarray(rng.standard_normal(
                    (batch, dim), dtype=np.float32))
                vals, idx = _nearest_jit(plane, valid, q,
                                         metric="l2", k_static=k)
                _sync(vals)              # warm-up / compile
                times = []
                while _iters_left(times, iters):
                    t0 = time.perf_counter()
                    vals, idx = _nearest_jit(plane, valid, q,
                                             metric="l2", k_static=k)
                    _sync(vals)
                    times.append(time.perf_counter() - t0)
                best = min(times)
                qps = batch / best
                scanned = n_rows * batch / best
                print(f"# vector dim={dim} k={k} batch={batch}: "
                      f"{qps:,.0f} queries/s, "
                      f"{scanned:,.0f} vectors-scanned/s",
                      file=sys.stderr)
                if dim == 256 and k == 8 and batch == 64:
                    headline = (scanned, best)

    # 8-device leg: the fused whole-plan NEAREST (distributed tentpole
    # path) in a child with a virtual 8-device CPU mesh.
    n_child = min(n_rows, 200_000)
    child_src = f"""
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import time
import numpy as np
from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.parallel.mesh import make_mesh
from ytsaurus_tpu.parallel.distributed import (
    DistributedEvaluator, ShardedTable, host_sync_count)
from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.schema import TableSchema

DIM = 64
N = {n_child}
per = N // 8
schema = TableSchema.make([("k", "int64"), ("emb", f"vector<float, 64>")])
rng = np.random.default_rng(5)
chunks = []
for s in range(8):
    rows = [dict(k=s * per + i, emb=[float(x) for x in v])
            for i, v in enumerate(rng.standard_normal((per, DIM)))]
    chunks.append(ColumnarChunk.from_rows(schema, rows))
mesh = make_mesh(8)
table = ShardedTable.from_chunks(mesh, chunks)
ev = DistributedEvaluator(mesh)
plan = build_query("k FROM [//t] NEAREST(emb, ?, 8)", {{"//t": schema}},
                   params=[[float(x) for x in rng.standard_normal(DIM)]])
run_whole_plan(ev, plan, table)          # warm-up / compile
s0 = host_sync_count()
t0 = time.perf_counter()
ITERS = 5
for _ in range(ITERS):
    out = run_whole_plan(ev, plan, table)
elapsed = time.perf_counter() - t0
assert host_sync_count() - s0 == ITERS, "fused NEAREST must be 1 sync/query"
assert len(out.to_rows()) == 8
print(f"CHILD {{ITERS / elapsed:.1f}} {{N * ITERS / elapsed:.0f}}")
"""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    proc = _subprocess.run([sys.executable, "-c", child_src],
                           capture_output=True, text=True, env=env,
                           timeout=600)
    for line in proc.stdout.splitlines():
        if line.startswith("CHILD "):
            _, qps8, scanned8 = line.split()
            print(f"# vector spmd-8dev dim=64 k=8 batch=1: "
                  f"{float(qps8):,.1f} queries/s, "
                  f"{float(scanned8):,.0f} vectors-scanned/s "
                  f"(1 host sync/query, asserted)", file=sys.stderr)
            break
    else:
        raise RuntimeError(
            f"vector SPMD child failed:\n{proc.stderr[-2000:]}")

    scanned, best = headline
    return "vector_scan_rows_per_sec", scanned, best


def bench_tiering(n_rows, iters):
    """Adaptive tiered execution (ISSUE 18): a burst of DISTINCT cold
    query shapes served three ways over one resident chunk.

      inline   tiering OFF (the pre-PR discipline): every cold shape
               pays its XLA compile inline on the serving thread —
               cold-shape p50/p99 IS the compile time.
      tiered   tiering ON (hot_threshold=1): cold shapes serve from the
               no-compile interpreter immediately, bit-identically; the
               background compiler promotes each hot fingerprint
               off-thread, after which the SAME keys serve compiled
               (steady-state compiled share asserted >=95%).
      prewarm  restart leg: a FRESH evaluator prewarmed COMPILE-ONLY
               from the recorded shape mix serves the whole burst with
               zero inline compiles (asserted).

    Metric: tiered cold-shape throughput (queries/s through the
    interpreter).  Cold p50/p99 per leg, the p99 drop, background
    promotion latency, and the prewarm report print on stderr."""
    import numpy as _np

    from ytsaurus_tpu import config as _config
    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    from ytsaurus_tpu.query.builder import build_query
    from ytsaurus_tpu.query.engine import evaluator as _ev
    from ytsaurus_tpu.query.engine.prewarm import prewarm_from_capture
    from ytsaurus_tpu.query.profile import get_flight_recorder
    from ytsaurus_tpu.query.statistics import QueryStatistics
    from ytsaurus_tpu.query.workload import WorkloadRecord
    from ytsaurus_tpu.schema import TableSchema

    schema = TableSchema.make([("k", "int64"), ("g", "int64"),
                               ("v", "int64")])
    rows = [{"k": i, "g": i % 97, "v": (i * 31) % 10_000}
            for i in range(n_rows)]
    chunk = ColumnarChunk.from_rows(schema, rows)
    schemas = {"//t": schema}

    # 20 structurally distinct shapes (distinct fingerprints even under
    # literal parameterization): filter-op x column, ORDER BY variants,
    # aggregate x group-key.  All inside the interpreter's coverage.
    shapes = []
    for col in ("k", "v"):
        for op in (">", "<", ">=", "<="):
            shapes.append(f"k, v FROM [//t] WHERE {col} {op} 500 LIMIT 9")
    for col in ("k", "g", "v"):
        for direction in ("asc", "desc"):
            shapes.append(f"k, v FROM [//t] WHERE v > 1 "
                          f"ORDER BY {col} {direction}, k LIMIT 7")
    for key in ("g", "v"):
        for fn in ("sum", "min", "max"):
            shapes.append(f"{key}, {fn}(k) AS a FROM [//t] GROUP BY {key}")
    plans = [build_query(q, schemas) for q in shapes]

    def run_cold_burst(evaluator):
        lat, tiers, compiles = [], [], 0
        for plan in plans:
            stats = QueryStatistics()
            t0 = time.perf_counter()
            evaluator.run_plan(plan, chunk, stats=stats)
            lat.append(time.perf_counter() - t0)
            tiers.append(stats.execution_tier)
            compiles += stats.compile_count
        return lat, tiers, compiles

    def pct(lat, q):
        return sorted(lat)[min(len(lat) - 1, int(q * len(lat)))] * 1e3

    # Leg 1: inline compiles (tiering off).
    _config.set_tiering_config(None)
    inline_lat, inline_tiers, inline_compiles = run_cold_burst(
        _ev.Evaluator())
    assert inline_compiles == len(shapes), inline_compiles
    try:
        # Leg 2: interpreter-first with background promotion.
        _config.set_tiering_config(_config.TieringConfig(
            enabled=True, hot_threshold=1))
        tiered = _ev.Evaluator()
        promotions_before = len(get_flight_recorder().promotions())
        t_cold = time.perf_counter()
        tiered_lat, tiered_tiers, tiered_compiles = run_cold_burst(tiered)
        cold_elapsed = time.perf_counter() - t_cold
        assert tiered_compiles == 0, tiered_compiles
        assert all(t == "interpreted" for t in tiered_tiers), tiered_tiers
        t_promo = time.perf_counter()
        tiered._background.drain(timeout=600)
        promo_wall = time.perf_counter() - t_promo
        events = get_flight_recorder().promotions()[promotions_before:]
        # Steady state: every shape again — all compiled now.
        _steady_lat, steady_tiers, steady_compiles = run_cold_burst(tiered)
        compiled_share = sum(
            t in ("compiled", "promoted-midstream")
            for t in steady_tiers) / len(steady_tiers)
        assert steady_compiles == 0, steady_compiles
        assert compiled_share >= 0.95, compiled_share

        # Leg 3: prewarmed restart — a fresh evaluator, warmed
        # compile-only from the shape mix, serves with 0 inline compiles.
        records = [WorkloadRecord(kind="select", query=q, literals=[])
                   for q in shapes]
        fresh = _ev.Evaluator()
        report = prewarm_from_capture(records, tables={"//t": chunk},
                                      evaluator=fresh)
        assert report["compiled"] + report["aot_hits"] == len(shapes), \
            report
        _pw_lat, pw_tiers, pw_compiles = run_cold_burst(fresh)
        assert pw_compiles == 0, pw_compiles
        assert all(t == "compiled" for t in pw_tiers), pw_tiers
    finally:
        _config.set_tiering_config(None)

    p99_drop = pct(inline_lat, 0.99) / max(pct(tiered_lat, 0.99), 1e-9)
    mean_promo = (sum(e["compile_seconds"] for e in events) /
                  len(events) * 1e3) if events else 0.0
    print(f"# tiering: {len(shapes)} cold shapes x {n_rows} rows; "
          f"inline p50={pct(inline_lat, 0.5):.1f}ms "
          f"p99={pct(inline_lat, 0.99):.1f}ms -> interpreted "
          f"p50={pct(tiered_lat, 0.5):.1f}ms "
          f"p99={pct(tiered_lat, 0.99):.1f}ms "
          f"(cold p99 {p99_drop:.1f}x lower); "
          f"{len(events)} background promotions "
          f"(mean compile {mean_promo:.0f}ms, drained {promo_wall:.2f}s), "
          f"steady compiled share {compiled_share * 100:.0f}%; "
          f"prewarm: {report['compiled']} compiled in "
          f"{report['seconds']:.2f}s, replay 0 inline compiles",
          file=sys.stderr)
    assert p99_drop >= 10.0, f"cold p99 drop {p99_drop:.1f}x < 10x"
    return ("tiering_cold_queries_per_sec", len(shapes) / cold_elapsed,
            cold_elapsed)


# --- per-primitive kernel microbench (ISSUE 19 move c) ----------------------
# tools/kernel_floors.json records rows/s floors per (device, n_rows);
# a measured primitive dipping under its floor fails the config.  Floors
# are written at 0.4x a measured run (YT_TPU_UPDATE_KERNEL_FLOORS=1) so
# machine jitter does not trip the gate; a real engine regression (2.5x+
# slowdown) does.  tests/test_bench_kernels.py asserts the smoke-scale
# floors inside the tier-1 pass.

KERNEL_FLOORS_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "tools",
    "kernel_floors.json")


def _load_kernel_floors():
    try:
        with open(KERNEL_FLOORS_PATH) as f:
            return json.load(f)
    except Exception:
        return {}


def kernel_primitives(n_rows, iters):
    """Time each ops/segments.py backbone primitive; returns
    {name: (rows_per_sec, best_seconds)}.  Shared by the bench config
    and the tier-1 smoke test."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ytsaurus_tpu.ops import segments
    from ytsaurus_tpu.query.engine.joins import _lex_searchsorted
    from ytsaurus_tpu.schema import EValueType

    rng = np.random.default_rng(7)
    nseg = int(min(10_001, max(n_rows // 100, 2)))
    seg_sorted_np = np.sort(rng.integers(0, nseg, n_rows))
    seg_sorted = jnp.asarray(seg_sorted_np, dtype=jnp.int32)
    seg_unsorted = jnp.asarray(rng.permutation(seg_sorted_np),
                               dtype=jnp.int32)
    vals = jnp.asarray(rng.random(n_rows))
    keys64 = jnp.asarray(rng.integers(0, 1 << 60, n_rows, dtype=np.int64))
    valid = jnp.ones(n_rows, dtype=bool)
    starts = jnp.concatenate([jnp.ones(1, dtype=bool),
                              seg_sorted[1:] != seg_sorted[:-1]])
    mask = jnp.asarray(rng.random(n_rows) < 0.5)
    # Encoded join-key planes: (null_rank int8, value) pairs, the format
    # _emit_encoded_keys produces (joins.py).
    ones8 = jnp.ones(n_rows, dtype=jnp.int8)
    f_sorted = jnp.asarray(
        np.sort(rng.integers(1, 1 << 60, n_rows, dtype=np.int64)))
    probe_keys = jnp.asarray(
        rng.integers(1, 1 << 60, n_rows, dtype=np.int64))

    def timed(fn, *args):
        fn_j = jax.jit(fn)
        out = fn_j(*args)                  # warm-up / compile
        _sync(out)
        times = []
        while _iters_left(times, iters):
            t0 = time.perf_counter()
            out = fn_j(*args)
            _sync(out)
            times.append(time.perf_counter() - t0)
        return min(times)

    secs = {}
    secs["segscan_sum"] = timed(
        lambda d, st: segments.segment_scan("sum", d, st), vals, starts)
    secs["group_sum_sorted"] = timed(
        lambda d, sg, v: segments.segment_aggregate(
            "sum", d, v, sg, nseg, EValueType.double, assume_sorted=True),
        vals, seg_sorted, valid)
    secs["group_sum_scatter"] = timed(
        lambda d, sg, v: segments.segment_aggregate(
            "sum", d, v, sg, nseg, EValueType.double),
        vals, seg_unsorted, valid)
    secs["group_min_scatter"] = timed(
        lambda d, sg, v: segments.segment_aggregate(
            "min", d, v, sg, nseg, EValueType.double),
        vals, seg_unsorted, valid)
    secs["radix_rank_u64"] = timed(
        lambda k, v: segments.stable_argsort_u32(
            segments.monotone_u32_words(k, v)), keys64, valid)
    secs["packed_sort_14bit"] = timed(
        lambda sg, v: segments.packed_sort_indices([(sg, v, False, 14)]),
        seg_unsorted, valid)
    secs["hash_group_order"] = timed(
        lambda k, v: segments.hash_group_order([(k, v)], v), keys64, valid)
    secs["lex_probe"] = timed(
        lambda f, q, n8: _lex_searchsorted(
            [(n8, f)], jnp.int64(n_rows), n_rows, [(n8, q)], "left"),
        f_sorted, probe_keys, ones8)
    secs["compact_mask"] = timed(lambda m: segments.compact_mask(m), mask)
    return {name: (n_rows / t, t) for name, t in secs.items()}


def bench_kernels(n_rows, iters):
    """Per-primitive rows/s/core for the segmented-scan / radix / probe
    backbone (ISSUE 19): the floor every macro number multiplies.  The
    config metric is the SLOWEST primitive.  ops/pallas_radix.py is the
    staging ground for moving the rank loop on-chip; these numbers time
    the XLA path."""
    import jax
    platform = jax.devices()[0].platform
    results = kernel_primitives(n_rows, iters)
    floors_doc = _load_kernel_floors()
    entry = floors_doc.get(platform, {}).get(str(n_rows), {})
    failures = []
    for name, (rps, best) in sorted(results.items()):
        floor = entry.get(name)
        status = ""
        if floor is not None:
            status = " (floor %.3g)" % floor
            if rps < floor:
                failures.append((name, rps, floor))
                status += " REGRESSION"
        print("# kernel %-18s %12.1f rows/s  best %8.2fms%s"
              % (name, rps, best * 1e3, status), file=sys.stderr)
    if os.environ.get("YT_TPU_UPDATE_KERNEL_FLOORS"):
        floors_doc.setdefault(platform, {})[str(n_rows)] = {
            name: round(rps * 0.4, 1)
            for name, (rps, _) in sorted(results.items())}
        os.makedirs(os.path.dirname(KERNEL_FLOORS_PATH), exist_ok=True)
        tmp = KERNEL_FLOORS_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(floors_doc, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, KERNEL_FLOORS_PATH)
        print(f"# kernel floors updated: {KERNEL_FLOORS_PATH} "
              f"({platform}:{n_rows})", file=sys.stderr)
    assert not failures, \
        "kernel primitives under recorded floor: %s" % failures
    worst = min(results, key=lambda k: results[k][0])
    return ("kernels_min_rows_per_sec", results[worst][0],
            results[worst][1])



_CONFIGS = {
    "vector": (bench_vector, 4_000_000, 200_000),
    "q1": (bench_q1, 64_000_000, 2_000_000),
    "groupby": (bench_groupby, 64_000_000, 2_000_000),
    "topk": (bench_topk, 64_000_000, 2_000_000),
    "q3": (bench_q3, 4_000_000, 500_000),
    "sort": (bench_sort, 64_000_000, 1_000_000),
    "strings": (bench_strings, 10_000_000, 500_000),
    "window": (bench_window, 2_000_000, 500_000),
    "select": (bench_select, 16_000_000, 1_000_000),
    "serving": (bench_serving, 200_000, 100_000),
    "scan": (bench_scan, 500_000, 100_000),
    "trace_overhead": (bench_trace_overhead, 2_000_000, 500_000),
    "telemetry_overhead": (bench_telemetry_overhead, 200_000, 100_000),
    "replay": (bench_replay, 200_000, 100_000),
    "serving_steady": (bench_serving_steady, 200_000, 100_000),
    "slo": (bench_slo, 100_000, 50_000),
    "whole_plan": (bench_whole_plan, 8_000_000, 1_000_000),
    "mesh_overhead": (bench_mesh_overhead, 8_000_000, 1_000_000),
    "multiway_join": (bench_multiway_join, 4_000_000, 400_000),
    "matview": (bench_matview, 2_000_000, 500_000),
    "sanitizer_overhead": (bench_sanitizer_overhead, 400_000, 400_000),
    "tiering": (bench_tiering, 200_000, 50_000),
    "kernels": (bench_kernels, 64_000_000, 2_000_000),
}


def _emit(metric, rows_per_sec):
    line = {
        "metric": metric,
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
    }
    print(json.dumps(line), flush=True)
    return line


# --- verified-capture persistence -------------------------------------------
# A mid-round tunnel outage must not zero the round's artifact: every
# on-chip (device=tpu) result is persisted here, and a CPU-fallback run
# re-emits the last verified capture (clearly flagged on stderr) instead
# of a meaningless 0.02x CPU number.

VERIFIED_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_VERIFIED.json")


def _load_verified():
    try:
        with open(VERIFIED_PATH) as f:
            return json.load(f)
    except Exception:
        return None


def _git_rev():
    try:
        import subprocess
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or "unknown"
    except Exception:
        return "unknown"


# Source trees whose changes can move benchmark numbers.  A verified
# capture is replayed as the config's primary line ONLY when none of
# these changed between the captured revision and HEAD — a capture from
# an older revision of the measured code must not mask a regression
# (ADVICE r3: stale replay attribution).
_PERF_PATHS = ("bench.py", "ytsaurus_tpu/ops", "ytsaurus_tpu/query",
               "ytsaurus_tpu/models", "ytsaurus_tpu/parallel",
               "ytsaurus_tpu/chunks", "ytsaurus_tpu/utils")


def _capture_current(entry) -> bool:
    """True when the capture measures the same perf-relevant code as the
    WORKING TREE (not just HEAD — uncommitted edits to the measured code
    must invalidate the capture too)."""
    rev = entry.get("rev")
    if not rev or rev == "unknown":
        return False
    try:
        import subprocess
        proc = subprocess.run(
            ["git", "diff", "--quiet", rev, "--", *_PERF_PATHS],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, timeout=10)
        return proc.returncode == 0
    except Exception:
        return False


def _save_verified(platform, name, line, n_rows, best):
    data = _load_verified() or {}
    results = data.setdefault("results", {})
    results[name] = {
        "line": line, "n_rows": n_rows, "best_ms": round(best * 1e3, 2),
        "device": platform, "rev": _git_rev(),
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    data["device"] = platform
    tmp = VERIFIED_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
    os.replace(tmp, VERIFIED_PATH)


def _emit_verified(name, entry):
    # In-band staleness markers: a replayed capture must be
    # distinguishable from a fresh measurement in stdout alone.  Callers
    # gate on _capture_current so the replayed value always measures the
    # same perf-relevant code as HEAD; these fields let the reader audit
    # that.
    line = dict(entry["line"])
    line["replayed_from"] = entry["captured_at"]
    if entry.get("rev"):
        line["captured_rev"] = entry["rev"]
    print(json.dumps(line), flush=True)
    print(f"# config={name} VERIFIED on-chip capture from "
          f"{entry['captured_at']} (n_rows={entry['n_rows']} "
          f"best={entry['best_ms']}ms device={entry['device']}); "
          "current run fell back to CPU", file=sys.stderr)


_METRIC_NAMES = {
    "q1": "tpch_q1_rows_per_sec",
    "groupby": "groupby_rows_per_sec",
    "topk": "topk_rows_per_sec",
    "q3": "tpch_q3_rows_per_sec",
    "sort": "sort_rows_per_sec",
    "strings": "strings_groupby_rows_per_sec",
    "window": "window_rows_per_sec",
    "select": "select_rows_per_sec",
    "serving": "serving_lookup_rows_per_sec",
    "scan": "scan_rows_per_sec",
    "trace_overhead": "trace_overhead_rows_per_sec",
    "telemetry_overhead": "telemetry_overhead_rows_per_sec",
    "replay": "replay_queries_per_sec",
    "serving_steady": "serving_steady_queries_per_sec",
    "slo": "slo_baseline_queries_per_sec",
    "whole_plan": "whole_plan_rows_per_sec",
    "mesh_overhead": "mesh_overhead_rows_per_sec",
    "multiway_join": "multiway_join_rows_per_sec",
    "matview": "matview_rows_per_sec",
    "sanitizer_overhead": "sanitizer_acquires_per_sec",
    "vector": "vector_scan_rows_per_sec",
    "tiering": "tiering_cold_queries_per_sec",
    "kernels": "kernels_min_rows_per_sec",
}


def _run_config(name, args, platform):
    if platform == "cpu" and not args.smoke and args.rows is None:
        verified = _load_verified() or {}
        entry = (verified.get("results") or {}).get(name)
        if entry and entry.get("device") != "cpu" and \
                _capture_current(entry):
            # Tunnel down now, but this config HAS a verified on-chip
            # number for THIS code — re-emit it rather than burning the
            # budget on a CPU run nobody will read.
            _emit_verified(name, entry)
            return
        if entry and entry.get("device") != "cpu":
            print(f"# config={name}: stale on-chip capture "
                  f"(rev {entry.get('rev')}) NOT replayed: perf-relevant "
                  "code changed since; measuring on CPU", file=sys.stderr)
    fn, accel_rows, cpu_rows = _CONFIGS[name]
    default_rows = cpu_rows if platform == "cpu" else accel_rows
    n_rows = args.rows or (100_000 if args.smoke else default_rows)
    metric, rows_per_sec, best = fn(n_rows, args.iters)
    assert metric == _METRIC_NAMES[name]
    line = _emit(metric, rows_per_sec)
    print(f"# config={name} n_rows={n_rows} best={best*1e3:.2f}ms "
          f"device={platform}", file=sys.stderr)
    if platform != "cpu" and not args.smoke and args.rows is None:
        # Only default-config runs are representative enough to replay.
        _save_verified(platform, name, line, n_rows, best)


def main():
    global _DEADLINE
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", choices=sorted(_CONFIGS) + ["all"],
                        default="all",
                        help="default 'all': one JSON line per BASELINE "
                             "config, headline q1 last")
    parser.add_argument("--smoke", action="store_true",
                        help="small row count, CPU-friendly")
    parser.add_argument("--rows", type=int, default=None)
    parser.add_argument("--iters", type=int, default=3)
    parser.add_argument("--budget", type=float,
                        default=float(os.environ.get("BENCH_BUDGET", 420)))
    args = parser.parse_args()
    _DEADLINE = time.monotonic() + args.budget

    config = args.config
    names = ("groupby", "topk", "q3", "sort", "strings", "window",
             "select", "serving", "scan", "q1") \
        if config == "all" else (config,)

    def _emit_fallback(name):
        """Best line available without measuring: a verified capture of
        THIS code if one exists, else an honest zero."""
        entry = ((_load_verified() or {}).get("results") or {}).get(name)
        if entry and entry.get("device") != "cpu" and \
                _capture_current(entry):
            _emit_verified(name, entry)
        else:
            _emit(_METRIC_NAMES[name], 0.0)

    try:
        from ytsaurus_tpu.utils.backend import ensure_backend
        jax = ensure_backend(timeout=180.0)
        platform = jax.devices()[0].platform
    except Exception as exc:
        print(f"# backend initialization failed: {exc!r}", file=sys.stderr)
        for name in names:
            _emit_fallback(name)
        return
    # Cache the probe verdict for the WHOLE bench invocation: every
    # spawned config child inherits it (ensure_backend honors the env)
    # instead of re-probing — a dead tunnel costs one fallback window
    # total, not one per config family (BENCH_r05 probe-hang log).
    os.environ["YT_TPU_PROBE_VERDICT"] = \
        "cpu" if platform == "cpu" else "accel"
    if config == "all":
        _run_all(names, args, platform, _emit_fallback)
        return
    try:
        _run_config(config, args, platform)
    except Exception as exc:
        import traceback
        traceback.print_exc()
        print(f"# bench config={config} failed on {platform}: {exc!r}",
              file=sys.stderr)
        _emit_fallback(config)


def _run_all(names, args, platform, emit_fallback):
    """Each config in its OWN subprocess with a hard timeout: a hung XLA
    compile (the documented v5e 64M-row sort cliff) must not starve the
    later configs or the headline line — every config produces a JSON
    line within the budget no matter what.  The headline q1 runs last
    (the driver parses the final line) with a dedicated time reserve."""
    import subprocess
    q1_reserve = min(180.0, max(90.0, 0.35 * args.budget))
    for idx, name in enumerate(names):
        remaining = _DEADLINE - time.monotonic()
        if remaining < 30.0:
            print(f"# budget exhausted before config={name}; emitting "
                  "fallback line", file=sys.stderr)
            emit_fallback(name)
            continue
        if name == "q1":
            # Never exceed the global budget: a hung q1 child must die
            # early enough for the fallback line to print in-budget.
            child_timeout = max(20.0, remaining - 10.0)
        else:
            left = len([n for n in names[idx:] if n != "q1"])
            child_timeout = max(45.0, (remaining - q1_reserve) / left)
        cmd = [sys.executable, os.path.abspath(__file__),
               "--config", name, "--iters", str(args.iters),
               "--budget", str(max(child_timeout - 20.0, 20.0))]
        if args.smoke:
            cmd.append("--smoke")
        if args.rows:
            cmd.extend(["--rows", str(args.rows)])
        env = dict(os.environ)
        if platform == "cpu":
            env["JAX_PLATFORMS"] = "cpu"    # parent already fell back
        else:
            env.setdefault("BENCH_PROBE_WINDOW", "45")
        try:
            proc = subprocess.run(cmd, timeout=child_timeout, env=env,
                                  capture_output=True, text=True)
            sys.stderr.write(proc.stderr or "")
            lines = [ln for ln in (proc.stdout or "").splitlines()
                     if ln.startswith("{")]
            if proc.returncode == 0 and lines:
                for ln in lines:
                    print(ln, flush=True)
            else:
                print(f"# config={name} child rc={proc.returncode}; "
                      "emitting fallback line", file=sys.stderr)
                emit_fallback(name)
        except subprocess.TimeoutExpired as exc:
            tail = exc.stderr or ""
            if isinstance(tail, bytes):
                tail = tail.decode("utf-8", "replace")
            sys.stderr.write(tail[-500:])
            print(f"# config={name} child TIMED OUT after "
                  f"{child_timeout:.0f}s; emitting fallback line",
                  file=sys.stderr)
            emit_fallback(name)


if __name__ == "__main__":
    main()
