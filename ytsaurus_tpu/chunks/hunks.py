"""Hunks: large values stored out-of-row in separate hunk chunks.

Ref mapping:
  hunks (ytlib/table_client/hunks.h)        → HunkRef in the string
                                              dictionary; payload lives in
                                              its own hunk chunk
  hunk_store (tablet_node/hunk_store.h)     → hunk chunks are plain blobs
                                              in the same chunk store,
                                              id = "hunk-" + content hash
  hunk_chunk_sweeper                        → collect_garbage traces
                                              hunk_chunk_ids from live
                                              chunk metas
  max_inline_hunk_size (TColumnSchema)      → ColumnSchema.max_inline_hunk_size

Design delta (TPU-first): hunk payloads never touch device planes — the
dictionary-encoded string column keeps int32 codes on device either way,
so hunking changes only what the HOST-side vocabulary stores.  Hunk chunks
are content-addressed: flushing or compacting a chunk whose large values
already live in hunks re-hashes the payloads and finds the blobs already
present — compaction never rewrites hunk payloads (the reference gets this
by attaching existing hunk chunks to the new store; we get it from content
addressing).  Refs resolve eagerly at chunk decode; a lazy
chunk-fragment-reader analog is a later optimization.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

import numpy as np

from ytsaurus_tpu.errors import EErrorCode, YtError

HUNK_PREFIX = "hunk-"


@dataclass(frozen=True)
class HunkRef:
    """Out-of-row value pointer (vocab entry stand-in)."""

    hunk_id: str
    length: int


def is_hunk_id(chunk_id: str) -> bool:
    return chunk_id.startswith(HUNK_PREFIX)


def write_hunk(store, payload: bytes) -> str:
    """Store one payload content-addressed; returns the hunk chunk id.
    An existing blob with the same hash is NOT rewritten."""
    hunk_id = HUNK_PREFIX + hashlib.sha256(payload).hexdigest()[:24]
    if not store.exists(hunk_id):
        store.put_blob(hunk_id, payload)
    return hunk_id


def read_hunk(store, ref: HunkRef) -> bytes:
    payload = store.get_blob(ref.hunk_id)
    if len(payload) != ref.length:
        raise YtError(f"Hunk {ref.hunk_id} length {len(payload)} != "
                      f"expected {ref.length}",
                      code=EErrorCode.ChunkFormatError)
    return payload


def hunkify_vocab(store, vocab: np.ndarray,
                  threshold: int) -> tuple[np.ndarray, list[str]]:
    """Move vocab entries >= threshold bytes into hunk chunks.  Returns the
    new vocab (HunkRef entries for moved values) and the hunk ids used."""
    hunk_ids: list[str] = []
    out = vocab
    for i, value in enumerate(vocab):
        if isinstance(value, HunkRef):
            hunk_ids.append(value.hunk_id)
            continue
        if len(value) < threshold:
            continue
        if out is vocab:
            out = vocab.copy()
        hunk_id = write_hunk(store, bytes(value))
        out[i] = HunkRef(hunk_id=hunk_id, length=len(value))
        hunk_ids.append(hunk_id)
    return out, hunk_ids


def resolve_vocab(store, vocab: np.ndarray) -> np.ndarray:
    """Fetch every HunkRef back into an inline bytes entry."""
    out = vocab
    for i, value in enumerate(vocab):
        if isinstance(value, HunkRef):
            if store is None:
                raise YtError("Chunk has hunk refs but no hunk store is "
                              "available to resolve them",
                              code=EErrorCode.ChunkFormatError)
            if out is vocab:
                out = vocab.copy()
            out[i] = read_hunk(store, value)
    return out
