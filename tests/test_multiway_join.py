"""Multiway join fusion + the cost-based planner (ISSUE 14).

Quick tier-1 coverage: the fused-join dual-check over one
representative per strategy/stage shape (CORPUS_QUICK — the 4-way
broadcast+partition+string plan, LEFT broadcast, window-after-join,
cardinality front) against the local evaluator with exactly one steady
host sync; planner units (selectivity order, dependency + LEFT
barriers, broadcast threshold, semi-join pushdown); the stats-drift
recompile; the join degradation ladder; the NDV sketch (accuracy,
merge, bounded payload, decode backfill); EXPLAIN ANALYZE join-plan
rendering; and client-side shard pruning through pushed-down join key
ranges.  The FULL corpus sweep, skew-driven quota overflow escalation,
and the cross-process AOT restart leg run under `slow` so the quick
pass fits the tier-1 870s budget (sibling quick coverage: whole-plan
quota memo + disk-tier tests in test_whole_plan.py).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from ytsaurus_tpu import config as yt_config
from ytsaurus_tpu.chunks import ColumnarChunk
from ytsaurus_tpu.chunks.columnar import concat_chunks
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.query.engine.evaluator import Evaluator
from ytsaurus_tpu.query.statistics import QueryStatistics
from ytsaurus_tpu.schema import TableSchema

FACT = TableSchema.make([
    ("k", "int64", "ascending"), ("ok", "int64"), ("sk", "int64"),
    ("s", "string"), ("v", "int64")])
DIM = TableSchema.make([("d_ok", "int64"), ("d_w", "int64")])
DUP = TableSchema.make([("u_sk", "int64"), ("u_t", "string")])
SDIM = TableSchema.make([("m_s", "string"), ("m_w", "int64")])
SCHEMAS = {"//l": FACT, "//d": DIM, "//u": DUP, "//m": SDIM}

# The dual-check corpus: every strategy mix across the Q5/Q7/Q8-class
# shapes — broadcast (unique int dim), partition (duplicated keys),
# string-key broadcast, LEFT variants, and post-join group/window/
# order/cardinality stages.
CORPUS = [
    # broadcast + group (Q3-class tail)
    "d_w, sum(v) AS sv, count(*) AS c FROM [//l] JOIN [//d] ON ok = d_ok "
    "GROUP BY d_w ORDER BY d_w LIMIT 500",
    # partition (non-unique foreign keys) + group
    "u_t, sum(v) AS sv FROM [//l] JOIN [//u] ON sk = u_sk "
    "GROUP BY u_t ORDER BY u_t LIMIT 500",
    # 3-way mixed broadcast + partition, string group key (Q5-class)
    "m_w, count(*) AS c, sum(v) AS sv FROM [//l] "
    "JOIN [//u] ON sk = u_sk JOIN [//m] ON s = m_s "
    "GROUP BY m_w ORDER BY m_w LIMIT 100",
    # 4-way: broadcast + partition + string broadcast (Q8-class)
    "d_w, m_w, sum(v) AS sv FROM [//l] JOIN [//d] ON ok = d_ok "
    "JOIN [//u] ON sk = u_sk JOIN [//m] ON s = m_s "
    "GROUP BY d_w, m_w ORDER BY d_w, m_w LIMIT 500",
    # LEFT broadcast (string key), bare select
    "k, m_w, v FROM [//l] LEFT JOIN [//m] ON s = m_s WHERE v > 50",
    # LEFT partition join
    "k, u_t FROM [//l] LEFT JOIN [//u] ON sk = u_sk WHERE v > 90",
    # window after join
    "k, d_w, sum(v) OVER (PARTITION BY d_w ORDER BY k) AS rs "
    "FROM [//l] JOIN [//d] ON ok = d_ok ORDER BY k LIMIT 300",
    # cardinality after join (exchange-rows front)
    "d_w, cardinality(s) AS cd FROM [//l] JOIN [//d] ON ok = d_ok "
    "GROUP BY d_w ORDER BY d_w LIMIT 100",
]

# Quick-tier subset: one representative per strategy/stage shape — the
# 4-way plan exercises broadcast + partition + string-broadcast edges
# in one program, plus LEFT broadcast, window-after-join, and the
# cardinality exchange-rows front.  Each corpus query costs a full
# 8-device shard_map compile (~6s on CPU); the 2/3-way and LEFT
# partition variants those subsume run in the `slow` full sweep
# (test_multiway_dual_check_corpus_full).
CORPUS_QUICK = [CORPUS[3], CORPUS[4], CORPUS[6], CORPUS[7]]


@pytest.fixture(autouse=True)
def _fresh_compile_config():
    yield
    yt_config.set_compile_config(None)


@pytest.fixture(scope="module")
def mw_tables(request):
    mesh = request.getfixturevalue("mesh8")
    from ytsaurus_tpu.parallel.distributed import ShardedTable
    rng = np.random.default_rng(37)
    words = [f"w{i:02d}" for i in range(13)]
    chunks = []
    for sh in range(8):
        n = 120 + sh * 9
        rows = []
        for i in range(n):
            rows.append((
                sh * 10_000 + i,
                # ~10% null join keys: they must match nothing (and
                # still surface under LEFT joins).
                int(rng.integers(0, 50)) if rng.uniform() > 0.1 else None,
                int(rng.integers(0, 40)),
                words[int(rng.integers(0, 13))],
                int(rng.integers(0, 100))))
        chunks.append(ColumnarChunk.from_rows(FACT, rows))
    table = ShardedTable.from_chunks(mesh, chunks)
    dim = ColumnarChunk.from_arrays(DIM, {
        "d_ok": np.arange(50), "d_w": np.arange(50) * 3 % 7})
    dup_rows = [(key, f"t{key % 5}")
                for key in range(40) for _ in range(int(rng.integers(0, 4)))]
    dup = ColumnarChunk.from_rows(DUP, dup_rows)
    sdim = ColumnarChunk.from_rows(
        SDIM, [(w, i * 10) for i, w in enumerate(words[:9])])
    foreign = {"//d": dim, "//u": dup, "//m": sdim}
    return mesh, chunks, table, concat_chunks(chunks), foreign


def _canon(rows):
    def norm(v):
        if v is None:
            return (0, 0)
        return (1, round(v, 9) if isinstance(v, float) else v)

    return sorted(tuple((k, norm(v)) for k, v in sorted(r.items()))
                  for r in rows)


def _dual_check(mw_tables, corpus):
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        host_sync_count,
    )
    from ytsaurus_tpu.parallel.whole_plan import can_fuse, run_whole_plan
    mesh, _chunks, table, merged, foreign = mw_tables
    de = DistributedEvaluator(mesh)
    local = Evaluator()
    for query in corpus:
        plan = build_query(query, SCHEMAS)
        assert can_fuse(plan) is None, query
        stats = QueryStatistics()
        got = run_whole_plan(de, plan, table, stats=stats,
                             foreign_chunks=foreign)
        assert stats.whole_plan == 1
        want = local.run_plan(plan, merged, foreign)
        assert _canon(got.to_rows()) == _canon(want.to_rows()), query
        # Steady state (quotas settled): exactly one stacked transfer.
        s0 = host_sync_count()
        got2 = run_whole_plan(de, plan, table, foreign_chunks=foreign)
        assert host_sync_count() - s0 == 1, query
        assert _canon(got2.to_rows()) == _canon(want.to_rows()), query


def test_multiway_dual_check_corpus(mw_tables):
    """Fused multiway joins vs the local evaluator over the quick
    shape-representative corpus, with exactly ONE steady-state host
    sync per fused query."""
    _dual_check(mw_tables, CORPUS_QUICK)


@pytest.mark.slow
def test_multiway_dual_check_corpus_full(mw_tables):
    """The full strategy-mix corpus — minutes-long variant of
    test_multiway_dual_check_corpus."""
    _dual_check(mw_tables, CORPUS)


def test_join_ladder_serves_fused_and_degrades(mw_tables):
    """coordinate_distributed serves join plans off the fused rung; an
    injected all_to_all fault knocks a partition-join plan down the
    ladder bit-identically (a broadcast-only fused join genuinely does
    not touch all_to_all and survives)."""
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        coordinate_distributed,
    )
    from ytsaurus_tpu.utils import failpoints
    mesh, chunks, _table, merged, foreign = mw_tables
    de = DistributedEvaluator(mesh)
    local = Evaluator()
    plan = build_query(CORPUS[1], SCHEMAS)       # partition strategy
    stats = QueryStatistics()
    got = coordinate_distributed(plan, mesh, chunks, foreign,
                                 evaluator=de, stats=stats)
    base = _canon(got.to_rows())
    assert base == _canon(local.run_plan(plan, merged, foreign).to_rows())
    assert stats.whole_plan == 1
    stats = QueryStatistics()
    with failpoints.active("parallel.all_to_all=error:times=1", seed=5):
        got = coordinate_distributed(plan, mesh, chunks, foreign,
                                     evaluator=de, stats=stats)
    assert _canon(got.to_rows()) == base
    assert stats.whole_plan == 0                 # served off-rung
    # Every collective dead → the host coordinator still answers.
    with failpoints.active("parallel.all_to_all=error:times=4;"
                           "parallel.gather=error:times=4", seed=6):
        got = coordinate_distributed(plan, mesh, chunks, foreign,
                                     evaluator=de)
    assert _canon(got.to_rows()) == base


def test_planner_order_dependencies_and_barriers():
    """Greedy selectivity order respects column dependencies and LEFT
    joins pin their position."""
    from ytsaurus_tpu.query import planner
    fact = TableSchema.make([("ok", "int64"), ("sk", "int64"),
                             ("v", "int64")])
    orders = TableSchema.make([("o_ok", "int64"), ("o_ck", "int64")])
    cust = TableSchema.make([("c_ck", "int64"), ("c_n", "int64")])
    supp = TableSchema.make([("s_sk", "int64"), ("s_n", "int64")])
    schemas = {"//l": fact, "//o": orders, "//c": cust, "//s": supp}
    plan = build_query(
        "c_n, s_n, sum(v) AS sv FROM [//l] JOIN [//o] ON ok = o_ok "
        "JOIN [//c] ON o_ck = c_ck JOIN [//s] ON sk = s_sk "
        "GROUP BY c_n, s_n", schemas)
    o_chunk = ColumnarChunk.from_arrays(orders, {
        "o_ok": np.arange(10_000), "o_ck": np.arange(10_000) % 500})
    c_chunk = ColumnarChunk.from_arrays(cust, {
        "c_ck": np.arange(500), "c_n": np.arange(500) % 7})
    s_chunk = ColumnarChunk.from_arrays(supp, {
        "s_sk": np.arange(40), "s_n": np.arange(40) % 7})
    jp = planner.plan_for_chunks(plan, 100_000, {
        "//o": o_chunk, "//c": c_chunk, "//s": s_chunk})
    order = jp.order
    # Most selective available join first: tiny supplier beats orders.
    assert order[0] == 2
    # Dependency: customer (needs o_ck from orders) must follow orders.
    assert order.index(1) > order.index(0)
    # LEFT joins are barriers: nothing reorders across them.
    plan_left = build_query(
        "c_n, s_n, v FROM [//l] JOIN [//o] ON ok = o_ok "
        "LEFT JOIN [//c] ON o_ck = c_ck JOIN [//s] ON sk = s_sk",
        schemas)
    jp2 = planner.plan_for_chunks(plan_left, 100_000, {
        "//o": o_chunk, "//c": c_chunk, "//s": s_chunk})
    assert jp2.order == (0, 1, 2)
    # Planner off: no plan (declared order everywhere).
    yt_config.set_compile_config(
        yt_config.CompileConfig(cost_join_planner=False))
    assert planner.plan_for_chunks(plan, 100_000, {
        "//o": o_chunk, "//c": c_chunk, "//s": s_chunk}) is None


def test_planner_broadcast_threshold_and_pushdown():
    from ytsaurus_tpu.query import planner
    fact = TableSchema.make([("ok", "int64"), ("v", "int64")])
    dim = TableSchema.make([("d_ok", "int64"), ("d_w", "int64")])
    schemas = {"//l": fact, "//d": dim}
    plan = build_query("d_w, sum(v) AS sv FROM [//l] "
                       "JOIN [//d] ON ok = d_ok GROUP BY d_w", schemas)
    chunk = ColumnarChunk.from_arrays(dim, {
        "d_ok": np.arange(100, 200), "d_w": np.arange(100)})
    jp = planner.plan_for_chunks(plan, 10_000, {"//d": chunk})
    d = jp.decisions[0]
    assert d.strategy == "broadcast"
    # The INNER side's key range pushes into the scan stage.
    assert d.pushdown == (("ok", 100, 199),)
    iv = planner.pushdown_intervals(
        plan, {"//d": planner.stats_for_chunk(chunk)})
    assert iv["ok"].lo == 100 and iv["ok"].hi == 199
    # A LEFT join must not push (unmatched rows survive).
    plan_l = build_query("d_w, v FROM [//l] LEFT JOIN [//d] "
                         "ON ok = d_ok", schemas)
    assert planner.pushdown_intervals(
        plan_l, {"//d": planner.stats_for_chunk(chunk)}) == {}
    # Over the broadcast row threshold → partition.
    yt_config.set_compile_config(
        yt_config.CompileConfig(broadcast_join_rows=50))
    jp = planner.plan_for_chunks(plan, 10_000, {"//d": chunk})
    assert jp.decisions[0].strategy == "partition"


@pytest.mark.slow
def test_quota_overflow_escalation_and_memo(request):
    """Skewed join keys overflow the optimistic quotas: the query
    re-runs at the demanded rung (correct results) and the settled
    quotas memoize so the next query runs clean."""
    mesh = request.getfixturevalue("mesh8")
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        ShardedTable,
    )
    from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
    fact = TableSchema.make([("k", "int64", "ascending"),
                             ("ok", "int64"), ("v", "int64")])
    dup = TableSchema.make([("d_ok", "int64"), ("d_t", "int64")])
    rng = np.random.default_rng(11)
    per = 256
    chunks = []
    for sh in range(8):
        # ~90% of rows share ONE join key: the hot (src, dst) cell and
        # the hot device's expansion both overflow the uniform estimate.
        ok = np.where(rng.uniform(size=per) < 0.9, 7,
                      rng.integers(0, 64, per))
        chunks.append(ColumnarChunk.from_arrays(fact, {
            "k": np.arange(per) + sh * per, "ok": ok,
            "v": rng.integers(0, 100, per)}))
    table = ShardedTable.from_chunks(mesh, chunks)
    merged = concat_chunks(chunks)
    dup_chunk = ColumnarChunk.from_rows(
        dup, [(k, k * 10 + r) for k in range(64) for r in range(3)])
    foreign = {"//d": dup_chunk}
    plan = build_query(
        "d_t, count(*) AS c FROM [//l] JOIN [//d] ON ok = d_ok "
        "GROUP BY d_t ORDER BY d_t LIMIT 500",
        {"//l": fact, "//d": dup})
    de = DistributedEvaluator(mesh)
    stats = QueryStatistics()
    got = run_whole_plan(de, plan, table, stats=stats,
                         foreign_chunks=foreign)
    want = Evaluator().run_plan(plan, merged, foreign)
    assert got.to_rows() == want.to_rows()
    assert stats.whole_plan_retries >= 1
    stats2 = QueryStatistics()
    got2 = run_whole_plan(de, plan, table, stats=stats2,
                          foreign_chunks=foreign)
    assert stats2.whole_plan_retries == 0
    assert got2.to_rows() == want.to_rows()


def test_stats_drift_flips_strategy_new_program(request):
    """A foreign table growing past the broadcast threshold flips the
    planner's strategy: the fused program recompiles under a NEW key
    (never serves the stale broadcast program) and results stay right."""
    mesh = request.getfixturevalue("mesh8")
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        ShardedTable,
    )
    from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
    fact = TableSchema.make([("k", "int64", "ascending"),
                             ("ok", "int64"), ("v", "int64")])
    dim = TableSchema.make([("d_ok", "int64"), ("d_w", "int64")])
    rng = np.random.default_rng(23)
    per = 128
    chunks = [ColumnarChunk.from_arrays(fact, {
        "k": np.arange(per) + s * per, "ok": rng.integers(0, 64, per),
        "v": rng.integers(0, 100, per)}) for s in range(8)]
    table = ShardedTable.from_chunks(mesh, chunks)
    merged = concat_chunks(chunks)
    plan = build_query("d_w, sum(v) AS sv FROM [//l] JOIN [//d] "
                       "ON ok = d_ok GROUP BY d_w ORDER BY d_w LIMIT 500",
                       {"//l": fact, "//d": dim})
    yt_config.set_compile_config(
        yt_config.CompileConfig(broadcast_join_rows=100))
    de = DistributedEvaluator(mesh)
    local = Evaluator()
    small = ColumnarChunk.from_arrays(dim, {
        "d_ok": np.arange(64), "d_w": np.arange(64)})
    stats = QueryStatistics()
    got = run_whole_plan(de, plan, table, stats=stats,
                         foreign_chunks={"//d": small})
    assert stats.join_plan[0]["strategy"] == "broadcast"
    assert _canon(got.to_rows()) == _canon(
        local.run_plan(plan, merged, {"//d": small}).to_rows())
    # Stable stats: pure cache hit, zero fresh compiles.
    fc = de.fresh_compiles
    run_whole_plan(de, plan, table, foreign_chunks={"//d": small})
    assert de.fresh_compiles == fc
    # The table grows past the threshold: partition strategy, NEW
    # program (fresh compile), still bit-identical to local.
    grown = ColumnarChunk.from_arrays(dim, {
        "d_ok": np.arange(64).repeat(4),
        "d_w": np.arange(256) % 64})
    stats = QueryStatistics()
    got = run_whole_plan(de, plan, table, stats=stats,
                         foreign_chunks={"//d": grown})
    assert stats.join_plan[0]["strategy"] == "partition"
    assert de.fresh_compiles > fc
    assert _canon(got.to_rows()) == _canon(
        local.run_plan(plan, merged, {"//d": grown}).to_rows())


@pytest.mark.slow
def test_fused_join_cross_process_aot_restart(mw_tables, tmp_path):
    """ISSUE 14 acceptance: compile the fused multiway-join program in
    THIS process; a SECOND process over the same artifact dir serves
    the same plan with 0 fresh SPMD compiles (disk hits only)."""
    from ytsaurus_tpu.parallel.distributed import DistributedEvaluator
    from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
    mesh, _chunks, table, _merged, foreign = mw_tables
    yt_config.set_compile_config(
        yt_config.CompileConfig(disk_cache_dir=str(tmp_path)))
    plan = build_query(CORPUS[2], SCHEMAS)       # mixed strategies
    de = DistributedEvaluator(mesh)
    want = run_whole_plan(de, plan, table, foreign_chunks=foreign)
    assert de.fresh_compiles >= 1
    script = f"""
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
import numpy as np
from ytsaurus_tpu import config as yt_config
yt_config.set_compile_config(yt_config.CompileConfig(
    disk_cache_dir={str(tmp_path)!r}))
from ytsaurus_tpu.chunks import ColumnarChunk
from ytsaurus_tpu.parallel.distributed import DistributedEvaluator, \
    ShardedTable
from ytsaurus_tpu.parallel.mesh import make_mesh
from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.schema import TableSchema

FACT = TableSchema.make([
    ("k", "int64", "ascending"), ("ok", "int64"), ("sk", "int64"),
    ("s", "string"), ("v", "int64")])
DIM = TableSchema.make([("d_ok", "int64"), ("d_w", "int64")])
DUP = TableSchema.make([("u_sk", "int64"), ("u_t", "string")])
SDIM = TableSchema.make([("m_s", "string"), ("m_w", "int64")])
rng = np.random.default_rng(37)
words = [f"w{{i:02d}}" for i in range(13)]
chunks = []
for sh in range(8):
    n = 120 + sh * 9
    rows = []
    for i in range(n):
        rows.append((
            sh * 10_000 + i,
            int(rng.integers(0, 50)) if rng.uniform() > 0.1 else None,
            int(rng.integers(0, 40)),
            words[int(rng.integers(0, 13))],
            int(rng.integers(0, 100))))
    chunks.append(ColumnarChunk.from_rows(FACT, rows))
mesh = make_mesh(8)
table = ShardedTable.from_chunks(mesh, chunks)
dim = ColumnarChunk.from_arrays(DIM, {{
    "d_ok": np.arange(50), "d_w": np.arange(50) * 3 % 7}})
dup_rows = [(key, f"t{{key % 5}}")
            for key in range(40) for _ in range(int(rng.integers(0, 4)))]
dup = ColumnarChunk.from_rows(DUP, dup_rows)
sdim = ColumnarChunk.from_rows(
    SDIM, [(w, i * 10) for i, w in enumerate(words[:9])])
foreign = {{"//d": dim, "//u": dup, "//m": sdim}}
plan = build_query({CORPUS[2]!r},
                   {{"//l": FACT, "//d": DIM, "//u": DUP, "//m": SDIM}})
de = DistributedEvaluator(mesh)
out = run_whole_plan(de, plan, table, foreign_chunks=foreign)
print("CHILD", out.row_count, de.fresh_compiles, de.disk_hits)
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    child = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("CHILD")][0].split()
    rows, fresh, disk = int(child[1]), int(child[2]), int(child[3])
    assert rows == want.row_count
    assert fresh == 0, \
        "restart leg must serve the fused join plan from disk"
    assert disk >= 1


# --- NDV sketch -----------------------------------------------------------


def test_ndv_sketch_estimate_merge_and_bounds():
    from ytsaurus_tpu.chunks.columnar import (
        chunk_column_stats,
        merge_column_stats,
        ndv_estimate,
    )
    schema = TableSchema.make([("k", "int64"), ("s", "string"),
                               ("d", "double")])
    rng = np.random.default_rng(0)
    rows = [(int(rng.integers(0, 1000)),
             f"w{int(rng.integers(0, 50)):03d}",
             float(rng.uniform())) for _ in range(5000)]
    chunk = ColumnarChunk.from_rows(schema, rows)
    stats = chunk_column_stats(chunk)
    exact_k = len({r[0] for r in rows})
    est_k = ndv_estimate(stats["k"]["ndv_sketch"])
    # HLL with 64 registers: ~13% standard error; allow 3 sigma.
    assert abs(est_k - exact_k) / exact_k < 0.4
    assert abs(ndv_estimate(stats["s"]["ndv_sketch"]) - 50) <= 15
    # Merge of two halves == whole (register max is exact for unions).
    a = chunk.slice_rows(0, 2500)
    b = chunk.slice_rows(2500, 5000)
    merged = merge_column_stats(
        [chunk_column_stats(a), chunk_column_stats(b)])
    assert merged["k"]["ndv_sketch"] == stats["k"]["ndv_sketch"]
    assert merged["$row_count"] == 5000
    assert merged["k"]["min"] == stats["k"]["min"]
    # Payload stays fixed-size no matter the data.
    assert len(stats["k"]["ndv_sketch"]) == 64


def test_stats_payload_stays_bounded_with_huge_strings():
    """The PR 5 hunk-externalization regression must not recur: sealing
    stats (now including sketches) into meta never re-inlines data-
    sized payloads — meta stays small for a chunk of multi-KB strings."""
    from ytsaurus_tpu.chunks.encoding import serialize_chunk
    from ytsaurus_tpu.utils.varint import read_varint_u
    schema = TableSchema.make([("k", "int64"), ("blob", "string")])
    rows = [(i, bytes([65 + i % 26]) * 4096) for i in range(64)]
    chunk = ColumnarChunk.from_rows(schema, rows)
    blob = serialize_chunk(chunk)
    meta_len, _pos = read_varint_u(blob, 4)
    # 64 x 4KB values ≈ 256KB of data; the meta header (schema + stats
    # incl. two 64-byte sketches + capped string bounds) stays tiny.
    assert meta_len < 8192, meta_len


def test_read_stats_backfills_missing_sketch(tmp_path):
    """Chunks sealed BEFORE the sketch existed decode once and
    recompute the full payload (the PR 4 read_stats memo discipline)."""
    from ytsaurus_tpu import yson
    from ytsaurus_tpu.chunks.encoding import (
        MAGIC,
        read_chunk_meta,
        serialize_chunk,
    )
    from ytsaurus_tpu.chunks.store import FsChunkStore
    from ytsaurus_tpu.utils.varint import encode_varint_u
    store = FsChunkStore(str(tmp_path))
    schema = TableSchema.make([("k", "int64")])
    chunk = ColumnarChunk.from_rows(schema, [{"k": 5}, {"k": 9}])
    blob = serialize_chunk(chunk)
    meta = read_chunk_meta(blob)
    data_start = meta.pop("_data_start")
    for entry in meta["column_stats"].values():
        if isinstance(entry, dict):
            entry.pop("ndv_sketch", None)       # pre-sketch format
    meta_blob = yson.dumps(meta, binary=True)
    legacy = b"".join([MAGIC, encode_varint_u(len(meta_blob)), meta_blob,
                       blob[data_start:]])
    cid = store.put_blob("ab" + "0" * 30, legacy)
    assert "ndv_sketch" not in \
        store.read_meta(cid)["column_stats"]["k"]
    # Default read: metadata-only consumers ($timestamp, bounds
    # pruning) get the sealed stats with NO chunk decode.
    sealed = store.read_stats(cid)
    assert sealed["k"]["min"] == 5
    assert "ndv_sketch" not in sealed["k"]
    # Planner-fold opt-in: decode-backfill computes the full payload.
    stats = store.read_stats(cid, backfill_sketch=True)
    assert stats["k"].get("ndv_sketch") is not None
    from ytsaurus_tpu.chunks.columnar import ndv_estimate
    assert ndv_estimate(stats["k"]["ndv_sketch"]) >= 1
    # Memoized and upgraded in place: every later reader serves the
    # backfilled payload, the decode happened once.
    assert store.read_stats(cid) is stats
    assert store.read_stats(cid, backfill_sketch=True) is stats


# --- EXPLAIN ANALYZE + client pushdown ------------------------------------


def test_explain_analyze_renders_join_plan():
    from ytsaurus_tpu.query.profile import format_profile_dict
    stats = QueryStatistics(whole_plan=1)
    stats.note_join_stage(0, "//dim", "broadcast", est_rows=1000,
                          actual_rows=950)
    stats.note_join_stage(1, "//orders", "partition", est_rows=5000,
                          actual_rows=7100)
    text = format_profile_dict({"statistics": stats.to_dict()})
    assert "join plan:" in text
    assert "1. //dim [broadcast] est rows 1000 -> actual 950" in text
    assert "2. //orders [partition] est rows 5000 -> actual 7100" in text
    cold = format_profile_dict(
        {"statistics": QueryStatistics().to_dict()})
    assert "join plan" not in cold


def test_client_prunes_shards_via_join_pushdown(tmp_path):
    """End to end through the client: a selective dimension's key range
    (off sealed chunk-stats metadata) prunes source shards whose key
    range cannot join anything — before staging."""
    from ytsaurus_tpu.client import YtClient, YtCluster
    client = YtClient(YtCluster(str(tmp_path / "cluster")))
    fact_schema = TableSchema.make([("ok", "int64"), ("v", "int64")])
    dim_schema = TableSchema.make([("d_ok", "int64"), ("d_w", "int64")])
    # Three fact shards with DISJOINT key ranges; the dim only joins
    # the middle range.
    for lo in (0, 1000, 2000):
        client.write_table("//fact", [
            {"ok": lo + i, "v": i} for i in range(100)],
            schema=fact_schema,
            append=lo > 0)
    client.write_table("//dim", [
        {"d_ok": 1000 + i, "d_w": i} for i in range(100)],
        schema=dim_schema)
    stats_attr = client.get("//fact/@chunk_stats")
    assert len(stats_attr) == 3
    rows = client.select_rows(
        "d_w, sum(v) AS sv FROM [//fact] JOIN [//dim] ON ok = d_ok "
        "GROUP BY d_w ORDER BY d_w LIMIT 500")
    want = {(i, i) for i in range(100)}
    assert {(r["d_w"], r["sv"]) for r in rows} == want
    stats = client.last_query_statistics
    # Two of three fact shards pruned off the pushed-down key range.
    assert stats.shards_pruned == 2
    # A legacy placeholder in the dim's @chunk_stats ({} — sealed
    # before stats existed) makes its key range UNKNOWN: pushdown must
    # stand down entirely (pruning off the remaining chunks' bounds
    # would drop rows joining the legacy chunk).
    dim_stats = client.get("//dim/@chunk_stats")
    client.set("//dim/@chunk_stats", [{}] + list(dim_stats)[1:])
    rows = client.select_rows(
        "d_w, sum(v) AS sv FROM [//fact] JOIN [//dim] ON ok = d_ok "
        "GROUP BY d_w ORDER BY d_w LIMIT 500")
    assert {(r["d_w"], r["sv"]) for r in rows} == want
    assert client.last_query_statistics.shards_pruned == 0
    client.set("//dim/@chunk_stats", dim_stats)
    # Pushdown off → no pruning, same rows.
    yt_config.set_compile_config(
        yt_config.CompileConfig(cost_join_planner=False))
    rows = client.select_rows(
        "d_w, sum(v) AS sv FROM [//fact] JOIN [//dim] ON ok = d_ok "
        "GROUP BY d_w ORDER BY d_w LIMIT 500")
    assert {(r["d_w"], r["sv"]) for r in rows} == want
    assert client.last_query_statistics.shards_pruned == 0
