"""Job isolation environment: per-process resource enforcement for user
jobs.

Ref: server/node/exec_node/job_environment.cpp:359,447 — the reference
offers simple / porto / CRI environments; porto/cgroups enforce memory,
CPU, and process limits per job container.

Redesign for this runtime: container managers need privileges a shared
research box does not grant, so enforcement rides POSIX rlimits applied
in the child between fork and exec (`preexec_fn`) — the same kernel
mechanisms cgroup v1 memory/cpu controllers wrap, scoped per process
group (jobs already run in their own session):

  memory_limit  → RLIMIT_AS   (allocation beyond it fails → job dies)
  cpu_limit     → RLIMIT_CPU  (seconds of CPU → SIGKILL past the hard
                               cap; distinct from wall-clock timeouts)
  max_open_files→ RLIMIT_NOFILE
  nice          → scheduling priority (the cpu.weight analog)

The resulting failure is classified so operators see "memory limit
exceeded", not a bare exit code.
"""

from __future__ import annotations

import signal
from typing import Callable, Optional

MIN_MEMORY_LIMIT = 32 << 20          # below this even /bin/sh won't exec


def limits_from_spec(spec: dict) -> "Optional[dict]":
    """Extract the enforcement keys a job spec may carry (ref user job
    spec memory_limit/cpu_limit)."""
    out = {}
    for key in ("memory_limit", "cpu_limit", "max_open_files", "nice"):
        if spec.get(key) is not None:
            out[key] = spec[key]
    return out or None


def make_preexec(limits: "Optional[dict]") -> "Optional[Callable]":
    """preexec_fn applying the limits in the CHILD (between fork and
    exec) — nothing leaks into the parent server process."""
    if not limits:
        return None
    # Imports resolved in the PARENT: the closure runs between fork and
    # exec, where taking the import lock (possibly held by another
    # parent thread) would deadlock the child.
    import os
    import resource
    memory = limits.get("memory_limit")
    cpu = limits.get("cpu_limit")
    nofile = limits.get("max_open_files")
    nice = limits.get("nice")

    def apply() -> None:
        if memory is not None:
            cap = max(int(memory), MIN_MEMORY_LIMIT)
            resource.setrlimit(resource.RLIMIT_AS, (cap, cap))
        if cpu is not None:
            seconds = max(int(cpu), 1)
            # Soft = SIGXCPU (classifiable), hard = +1s then SIGKILL.
            resource.setrlimit(resource.RLIMIT_CPU,
                               (seconds, seconds + 1))
        if nofile is not None:
            resource.setrlimit(resource.RLIMIT_NOFILE,
                               (int(nofile), int(nofile)))
        if nice is not None:
            os.nice(int(nice))
    return apply


def classify_failure(returncode: int, stderr: bytes,
                     limits: "Optional[dict]") -> "Optional[str]":
    """Human-readable probable cause when a limited job died the way its
    limit kills (ref job proxy's error attribution)."""
    if not limits:
        return None
    if limits.get("cpu_limit") is not None:
        if -returncode == signal.SIGXCPU:
            return "cpu limit exceeded (SIGXCPU)"
        if -returncode == signal.SIGKILL:
            # The hard cap (soft+1s) delivers SIGKILL to jobs that
            # ignore SIGXCPU.
            return "cpu limit exceeded (hard cap SIGKILL)"
    if limits.get("memory_limit") is not None:
        markers = (b"MemoryError", b"Cannot allocate memory",
                   b"std::bad_alloc", b"Killed")
        if returncode != 0 and any(m in stderr for m in markers):
            return "memory limit exceeded (RLIMIT_AS)"
        if -returncode == signal.SIGSEGV:
            return "memory limit exceeded (allocation failed under " \
                   "RLIMIT_AS)"
    return None
