"""Master: the metadata authority — WAL-then-apply mutations + snapshots.

Ref: Hydra's mutation pipeline (server/lib/hydra/hydra_manager.h
CommitMutation → decorated_automaton WAL-append-then-apply, snapshot build/
load in composite_automaton.h).  Single-replica stand-in with the same
durability contract: every mutation is appended (fsync'd) to the changelog
BEFORE applying to the in-memory tree; recovery = load last snapshot +
replay the changelog; snapshots truncate the log.

A real multi-peer deployment replicates the changelog via a quorum before
apply — the apply/recover machinery here is the automaton that would sit
under it.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

from ytsaurus_tpu import yson
from ytsaurus_tpu.cypress.tree import CypressTree
from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.utils.varint import encode_varint_u, read_varint_u


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class Changelog:
    """Length-prefixed YSON records, fsync'd on append (ref: file changelogs,
    server/lib/hydra/changelog.h)."""

    def __init__(self, path: str):
        self.path = path
        self._file = open(path, "ab")
        self._lock = threading.Lock()

    def append(self, record: dict) -> None:
        blob = yson.dumps(record, binary=True)
        with self._lock:
            self._file.write(encode_varint_u(len(blob)) + blob)
            self._file.flush()
            os.fsync(self._file.fileno())

    def close(self) -> None:
        self._file.close()

    @staticmethod
    def read_all(path: str) -> tuple[list[dict], int]:
        """Returns (records, valid_byte_length).  A torn tail write stops the
        scan; the caller MUST truncate to valid_byte_length before appending,
        or post-recovery records land after garbage and vanish on the next
        recovery."""
        records = []
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return [], 0
        pos = 0
        valid = 0
        while pos < len(data):
            try:
                length, pos = read_varint_u(data, pos)
                blob = data[pos:pos + length]
                if len(blob) != length:
                    break              # torn tail write → stop at last good
                records.append(yson.loads(blob))
                pos += length
                valid = pos
            except (ValueError, YtError):
                break
        return records, valid


class Master:
    """Applies named mutations through the WAL; exposes the Cypress tree."""

    SNAPSHOT = "snapshot.yson"
    CHANGELOG = "changelog.log"

    def __init__(self, root_dir: str):
        self.root_dir = root_dir
        os.makedirs(root_dir, exist_ok=True)
        self._lock = threading.RLock()
        self.tree = CypressTree()
        self._recover()
        self.changelog = Changelog(os.path.join(root_dir, self.CHANGELOG))

    # -- mutation pipeline -----------------------------------------------------

    _MUTATIONS = ("create", "remove", "set", "copy", "move", "link")

    def commit_mutation(self, op: str, **args) -> Any:
        """Log, then apply (ref CommitMutation)."""
        if op not in self._MUTATIONS:
            raise YtError(f"Unknown mutation {op!r}")
        with self._lock:
            # Validate BEFORE logging by applying to the live tree; Hydra
            # validates in the mutation handler too — a failed apply after a
            # logged record would poison recovery, so log only after the
            # apply succeeds, holding the lock (single-writer semantics).
            result = self._apply(op, args)
            self.changelog.append({"op": op, "args": args})
            return result

    def _apply(self, op: str, args: dict) -> Any:
        if op == "create":
            return self.tree.create(
                args["path"], args["type"],
                attributes=args.get("attributes"),
                recursive=args.get("recursive", False),
                ignore_existing=args.get("ignore_existing", False))
        if op == "remove":
            return self.tree.remove(args["path"],
                                    recursive=args.get("recursive", True),
                                    force=args.get("force", False))
        if op == "set":
            return self.tree.set(args["path"], args.get("value"))
        if op == "copy":
            return self.tree.copy(args["src"], args["dst"],
                                  recursive=args.get("recursive", False))
        if op == "move":
            return self.tree.move(args["src"], args["dst"],
                                  recursive=args.get("recursive", False))
        if op == "link":
            return self.tree.link(args["target"], args["link"],
                                  recursive=args.get("recursive", False))
        raise AssertionError(op)

    # -- snapshots / recovery --------------------------------------------------

    def build_snapshot(self) -> None:
        """Serialize the tree, truncate the changelog (ref snapshot build)."""
        with self._lock:
            blob = yson.dumps(self.tree.serialize(), binary=True)
            snap_path = os.path.join(self.root_dir, self.SNAPSHOT)
            tmp = snap_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, snap_path)
            _fsync_dir(self.root_dir)      # make the rename durable first
            self.changelog.close()
            log_path = os.path.join(self.root_dir, self.CHANGELOG)
            os.unlink(log_path)
            _fsync_dir(self.root_dir)
            self.changelog = Changelog(log_path)

    def _recover(self) -> None:
        snap_path = os.path.join(self.root_dir, self.SNAPSHOT)
        if os.path.exists(snap_path):
            with open(snap_path, "rb") as f:
                self.tree = CypressTree.deserialize(yson.loads(f.read()))
        log_path = os.path.join(self.root_dir, self.CHANGELOG)
        records, valid_bytes = Changelog.read_all(log_path)
        for record in records:
            try:
                self._apply(record["op"], dict(record["args"]))
            except YtError:
                # Mutations are validated before logging; a failing replay
                # record means it raced a snapshot — skip.
                continue
        # Drop a torn tail so future appends stay recoverable.
        if os.path.exists(log_path) and \
                os.path.getsize(log_path) > valid_bytes:
            with open(log_path, "r+b") as f:
                f.truncate(valid_bytes)
                f.flush()
                os.fsync(f.fileno())
