"""Invariant framework (utils/invariants.py): the debug-build sanitizer
analog — violations raise AT THE SOURCE, and the whole test suite runs
with checks enabled (conftest sets YT_TPU_INVARIANTS=1)."""

import pytest

from ytsaurus_tpu.utils import invariants
from ytsaurus_tpu.utils.invariants import InvariantError


def test_enabled_in_tests():
    assert invariants.enabled()


def test_wal_epoch_regression_detected():
    good = [{"op": "a", "$qe": 1}, {"op": "b", "$qe": 1},
            {"op": "c", "$qe": 3}]
    invariants.check("wal", good)
    bad = good + [{"op": "d", "$qe": 2}]
    with pytest.raises(InvariantError) as err:
        invariants.check("wal", bad)
    assert "epoch regressed" in str(err.value)
    # Untagged (pre-epoch) records read as 0 and must lead the log only.
    invariants.check("wal", [{"op": "x"}, {"op": "y", "$qe": 5}])
    with pytest.raises(InvariantError):
        invariants.check("wal", [{"op": "y", "$qe": 5}, {"op": "x"}])


def test_chunk_capacity_mismatch_detected():
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.schema import TableSchema

    schema = TableSchema.make([("a", "int64")])
    chunk = ColumnarChunk.from_rows(schema, [(1,), (2,)])
    invariants.check("chunks", chunk)       # healthy
    import dataclasses
    broken = dataclasses.replace(chunk, row_count=chunk.capacity + 5)
    with pytest.raises(InvariantError):
        invariants.check("chunks", broken)


def test_disabled_is_noop(monkeypatch):
    monkeypatch.setenv("YT_TPU_INVARIANTS", "0")
    invariants.check("wal", [{"$qe": 9}, {"$qe": 1}])   # no raise
    with pytest.raises(InvariantError):
        monkeypatch.setenv("YT_TPU_INVARIANTS", "1")
        invariants.check("wal", [{"$qe": 9}, {"$qe": 1}])


def test_unknown_domain_rejected():
    with pytest.raises(InvariantError):
        invariants.check("nope", None)


def test_flush_catches_corrupted_store_at_source(tmp_path):
    """A duplicated (key, ts) version in a dynamic store fails the FLUSH
    that would persist it — not some distant read."""
    from ytsaurus_tpu.client import connect
    from ytsaurus_tpu.schema import TableSchema

    cl = connect(str(tmp_path))
    schema = TableSchema.make(
        [("k", "int64", "ascending"), ("v", "int64")], unique_keys=True)
    cl.create("table", "//c/t", recursive=True,
              attributes={"schema": schema, "dynamic": True})
    cl.mount_table("//c/t")
    cl.insert_rows("//c/t", [{"k": 1, "v": 1}])
    (tablet,) = cl._mounted_tablets("//c/t")
    versions = next(iter(tablet.active_store._rows.values()))
    versions.append(versions[-1])          # corrupt: duplicate version
    with pytest.raises(InvariantError) as err:
        tablet.flush()
    assert "duplicate version timestamp" in str(err.value)


def test_tablet_hook_passes_on_live_tablet(tmp_path):
    """The flush/compact hooks run green on a healthy dynamic table (the
    negative cases are unit-level above; every dynamic-table test in the
    suite exercises these hooks implicitly)."""
    from ytsaurus_tpu.client import connect
    from ytsaurus_tpu.schema import TableSchema

    cl = connect(str(tmp_path))
    schema = TableSchema.make(
        [("k", "int64", "ascending"), ("v", "int64")], unique_keys=True)
    cl.create("table", "//i/t", recursive=True,
              attributes={"schema": schema, "dynamic": True})
    cl.mount_table("//i/t")
    cl.insert_rows("//i/t", [{"k": i, "v": i} for i in range(20)])
    (tablet,) = cl._mounted_tablets("//i/t")
    tablet.flush()
    cl.insert_rows("//i/t", [{"k": 5, "v": 50}])
    tablet.compact()
    assert cl.lookup_rows("//i/t", [(5,)]) == [{"k": 5, "v": 50}]