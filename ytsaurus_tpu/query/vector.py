"""Vector similarity serving: batched NEAREST cohorts (ISSUE 16 tentpole).

The query-language surface (`NEAREST(col, ?, k)` desugaring to
`ORDER BY <distance>(col, ?) LIMIT k`) rides the ordinary select
pipeline: the distance emit in `engine/expr.py` is one tiled
`(capacity, dim) @ (dim,)` matmul feeding the existing pow2-bucketed
packed-key top-k, the query vector is a `(dim,)` runtime binding, and
the parameterized fingerprint collapses the vector literal to `?` — so
PR 9's compile-once ladder holds across every distinct query vector,
and PR 10's whole-plan gather distributes it at exactly one host sync.

This module is the SERVING-plane fast path on top: the
millions-of-users shape is many concurrent NEAREST queries against one
table, and executing them one matmul each wastes the MXU's batch
dimension.  `NearestBatcher` mirrors `serving.LookupBatcher`'s
continuous micro-batching — co-admitted NEAREST requests on one
(table, column, metric) coalesce inside a flush window and execute as
ONE batched `(batch, dim) @ (dim, rows)` matmul + per-row top-k, then
each caller scatters its own rows back out.  Batch and k pad to
power-of-two buckets so the program spectrum stays bounded: one
compiled kernel per (capacity, dim, batch-bucket, k-bucket, metric).

Sensors publish under `/query/vector` (catalog-linted); per-pool usage
folds into `query/accounting` as `nearest_*` fields.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ytsaurus_tpu.config import ServingConfig
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.query.accounting import get_accountant
from ytsaurus_tpu.schema import VectorType
from ytsaurus_tpu.utils import sanitizers
from ytsaurus_tpu.utils.profiling import Profiler
from ytsaurus_tpu.utils.tracing import child_span

#: Metric name → (higher-score-is-better kernel tag, result sign).
#: Scores are computed as "bigger is better" so one top_k serves all
#: three metrics; l2/cosine negate back to distances on the way out.
METRICS = ("l2", "cosine", "dot")

_BATCH_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)
_LATENCY_BOUNDS = (0.0002, 0.0005, 0.001, 0.002, 0.005, 0.01, 0.025,
                   0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0)

# Fresh-trace counter: increments ONLY when jax traces a new program
# shape (trace-time side effect), the observable tests/test_parameterize
# asserts flat across distinct query vectors and k within one bucket.
_trace_count = 0


def nearest_trace_count() -> int:
    return _trace_count


def _kernel(plane, valid, queries, *, metric: str, k_static: int):
    """(cap, dim) plane × (B, dim) queries → (B, k_static) top rows.

    THE one batched pass: every distance decomposes over the shared
    `queries @ plane.T` matmul (L2 via the norm trick), scores mask
    invalid rows to -inf, and lax.top_k (ties break to the LOWEST row
    index — the same determinism as the packed-key sort) selects per
    query."""
    global _trace_count
    _trace_count += 1
    q = queries.astype(jnp.float32)              # (B, dim)
    x = plane.astype(jnp.float32)                # (cap, dim)
    dot = q @ x.T                                # (B, cap) — the MXU pass
    if metric == "dot":
        score = dot
    elif metric == "cosine":
        nq = jnp.sqrt((q * q).sum(axis=1))[:, None]
        nx = jnp.sqrt((x * x).sum(axis=1))[None, :]
        denom = nq * nx
        score = -jnp.where(denom > 0.0, 1.0 - dot / denom, 1.0)
    else:  # l2
        nq2 = (q * q).sum(axis=1)[:, None]
        nx2 = (x * x).sum(axis=1)[None, :]
        score = -jnp.sqrt(jnp.maximum(nq2 - 2.0 * dot + nx2, 0.0))
    score = jnp.where(valid[None, :], score, -jnp.inf)
    vals, idx = jax.lax.top_k(score, k_static)
    return vals, idx


_nearest_jit = jax.jit(_kernel, static_argnames=("metric", "k_static"))


def batched_nearest(chunk, column: str, queries: Sequence[Sequence[float]],
                    k: int, metric: str = "l2"):
    """Exhaustive batched nearest-neighbor over one columnar chunk.

    Returns, per query, a list of up to `k` (row_index, measure) pairs
    in rank order — measure is the distance (l2/cosine, ascending) or
    the similarity (dot, descending).  `queries` pad to a pow2 batch
    bucket and `k` to a pow2 k bucket, so the compiled-program spectrum
    is (capacity, dim, batch-bucket, k-bucket, metric)-bounded."""
    from ytsaurus_tpu.chunks.columnar import next_pow2
    if metric not in METRICS:
        raise YtError(f"Unknown NEAREST metric {metric!r}",
                      code=EErrorCode.QueryTypeError)
    col = chunk.columns.get(column)
    if col is None or not isinstance(col.type, VectorType):
        raise YtError(f"Column {column!r} is not a vector column",
                      code=EErrorCode.QueryTypeError)
    dim = col.type.dim
    b = len(queries)
    if b == 0:
        return []
    q_np = np.zeros((next_pow2(b, floor=1), dim), dtype=np.float32)
    for i, q in enumerate(queries):
        arr = np.asarray(q, dtype=np.float32)
        if arr.shape != (dim,):
            raise YtError(
                f"Query vector {i} has shape {arr.shape}, expected ({dim},)",
                code=EErrorCode.QueryTypeError)
        if not np.isfinite(arr).all():
            raise YtError(f"Non-finite component in query vector {i}",
                          code=EErrorCode.QueryTypeError)
        q_np[i] = arr
    n = chunk.row_count
    valid = col.valid & (jnp.arange(col.capacity) < n)
    k_static = min(next_pow2(max(k, 1), floor=1), col.capacity)
    vals, idx = _nearest_jit(col.data, valid, jnp.asarray(q_np),
                             metric=metric, k_static=k_static)
    vals_np = np.asarray(vals)
    idx_np = np.asarray(idx)
    sign = 1.0 if metric == "dot" else -1.0
    out = []
    for i in range(b):
        hits = []
        for j in range(min(k, k_static)):
            if not np.isfinite(vals_np[i, j]):
                break                      # fewer than k valid rows
            hits.append((int(idx_np[i, j]), sign * float(vals_np[i, j])))
        out.append(hits)
    return out


class _NearestBatch:
    """One NEAREST cohort: member query vectors + shared completion
    state (the _Batch shape from serving.py: one event wakes the whole
    cohort; the deadline is the cohort max)."""

    __slots__ = ("queries", "ks", "users", "deadline", "pool", "user",
                 "client", "done", "results", "error")

    def __init__(self, token):
        self.queries: list = []
        self.ks: list[int] = []
        self.users: list = []
        self.deadline = token.deadline
        self.pool = token.pool
        self.user = token.user
        self.client = None
        self.done = threading.Event()
        self.results: "Optional[list]" = None
        self.error: Optional[BaseException] = None

    def join(self, token) -> None:
        if self.deadline is not None:
            self.deadline = None if token.deadline is None \
                else max(self.deadline, token.deadline)

    def flush_token(self):
        from ytsaurus_tpu.query.serving import CancellationToken
        return CancellationToken(self.deadline, pool=self.pool,
                                 user=self.user)


class NearestBatcher:
    """Continuous micro-batching of NEAREST queries (the LookupBatcher
    pattern over the batch dimension of one distance matmul).

    Requests enqueue their query vector into the pending cohort for
    their (table, column, metric, timestamp) and block on the cohort's
    completion event; the flusher thread lets each arriving cohort
    accumulate (growth-stable poll bounded by `flush_window_ms`), then
    executes it as ONE admitted batched `(batch, dim) @ (dim, rows)`
    matmul + per-row top-k over the table snapshot, waking the whole
    cohort with one event.  k is the cohort max's pow2 bucket, so mixed
    k's share the kernel and each member slices its own prefix."""

    _POLL_SECONDS = 0.0002
    _IDLE_EXIT_SECONDS = 30.0

    def __init__(self, config: ServingConfig, admission):
        self.config = config
        self.admission = admission
        # guards: _batches, _flusher, requests_n, batches_n, batched_queries_n
        self._cond = sanitizers.register_condition(
            "vector.NearestBatcher._cond")
        self._batches: "dict[tuple, _NearestBatch]" = {}
        self._flusher: Optional[threading.Thread] = None
        self.requests_n = 0
        self.batches_n = 0
        self.batched_queries_n = 0
        prof = Profiler("/query/vector")
        self.requests = prof.counter("requests")
        self.batches = prof.counter("batches")
        self.batched_queries = prof.counter("batched_queries")
        self.batch_size_hist = prof.histogram("batch_size",
                                              bounds=_BATCH_BOUNDS)
        self.latency_hist = prof.histogram("latency_seconds",
                                           bounds=_LATENCY_BOUNDS)

    # -- request path ----------------------------------------------------------

    def nearest(self, client, path: str, column: str,
                query_vector: Sequence[float], k: int, metric: str,
                timestamp: int, token) -> list:
        """One caller's NEAREST: join the cohort, wait for its flush,
        scatter this member's ranked (row_index, measure) hits."""
        if metric not in METRICS:
            raise YtError(f"Unknown NEAREST metric {metric!r}",
                          code=EErrorCode.QueryTypeError)
        if k <= 0:
            raise YtError("NEAREST expects k >= 1",
                          code=EErrorCode.QueryTypeError)
        t0 = time.monotonic()
        bkey = (path, column, metric, timestamp)
        with self._cond:
            self.requests_n += 1
            self.requests.increment()
            batch = self._batches.get(bkey)
            if batch is None:
                batch = self._batches[bkey] = _NearestBatch(token)
                batch.client = client
            else:
                batch.join(token)
            member = len(batch.queries)
            batch.queries.append(list(query_vector))
            batch.ks.append(int(k))
            batch.users.append(token.user)
            if self._flusher is None or not self._flusher.is_alive():
                self._flusher = threading.Thread(
                    target=self._flusher_loop, daemon=True,
                    name="vector-flusher")
                self._flusher.start()
            self._cond.notify()
        if not batch.done.wait(timeout=token.remaining()):
            raise YtError(
                "deadline exceeded waiting for the NEAREST batch",
                code=EErrorCode.DeadlineExceeded,
                attributes={"table": path})
        if batch.error is not None:
            raise batch.error
        self.latency_hist.record(time.monotonic() - t0)
        return batch.results[member]

    # -- the flusher thread ----------------------------------------------------

    def _flusher_loop(self) -> None:
        while True:
            with self._cond:
                while not self._batches:
                    if not self._cond.wait(
                            timeout=self._IDLE_EXIT_SECONDS) \
                            and not self._batches:
                        self._flusher = None
                        return
            self._accumulate()
            with self._cond:
                taken, self._batches = self._batches, {}
            for (path, column, metric, timestamp), batch in taken.items():
                self._flush(path, column, metric, timestamp, batch)

    def _accumulate(self) -> None:
        window = self.config.flush_window_ms / 1000.0
        if window <= 0:
            return
        deadline = time.monotonic() + window
        prev = -1
        while time.monotonic() < deadline:
            with self._cond:
                n = sum(len(b.queries) for b in self._batches.values())
            if n == prev:
                return
            prev = n
            time.sleep(self._POLL_SECONDS)

    # -- batch execution -------------------------------------------------------

    def _flush(self, path, column, metric, timestamp,
               batch: _NearestBatch) -> None:
        token = batch.flush_token()
        try:
            state = self.admission.admit(token, batch.pool)
        except BaseException as exc:
            self._fail(batch, exc)
            return
        t0 = time.monotonic()
        try:
            with child_span("vector.batch_flush", table=path,
                            cohort=len(batch.queries)):
                self._flush_admitted(path, column, metric, timestamp,
                                     batch, token)
        except BaseException as exc:  # noqa: BLE001 — relayed to waiters
            self._fail(batch, exc)
            if not isinstance(exc, Exception):
                raise
        finally:
            self.admission.release(state, time.monotonic() - t0)

    def _flush_admitted(self, path, column, metric, timestamp,
                        batch: _NearestBatch, token) -> None:
        token.check()
        chunk = self._table_chunk(batch.client, path, timestamp)
        with self._cond:
            self.batches_n += 1
            self.batched_queries_n += len(batch.queries)
        self.batches.increment()
        self.batched_queries.increment(len(batch.queries))
        self.batch_size_hist.record(len(batch.queries))
        k_max = max(batch.ks)
        # ONE batched matmul for the whole cohort; each member slices
        # its own k prefix out of the shared k_max ranking.
        ranked = batched_nearest(chunk, column, batch.queries, k_max,
                                 metric=metric)
        pool = batch.pool or self.config.default_pool
        accountant = get_accountant()
        accountant.observe_nearest_batch(pool, batch.user)
        for user in batch.users:
            accountant.observe_nearest(pool, user,
                                       rows_scanned=chunk.row_count)
        rows = chunk.to_rows()
        results = []
        for member, k in enumerate(batch.ks):
            hits = []
            for row_idx, measure in ranked[member][:k]:
                row = dict(rows[row_idx])
                row["$distance"] = measure
                hits.append(row)
            results.append(hits)
        batch.results = results
        batch.done.set()

    @staticmethod
    def _table_chunk(client, path: str, timestamp: int):
        """The table's visible rowset: concat of per-tablet MVCC
        snapshots (tablets memoize these per flush generation, so
        steady-state flushes reuse device planes)."""
        from ytsaurus_tpu.chunks.columnar import concat_chunks
        tablets = client._mounted_tablets(path)
        return concat_chunks([t.read_snapshot(timestamp)
                              for t in tablets])

    @staticmethod
    def _fail(batch: _NearestBatch, exc: BaseException) -> None:
        batch.error = exc
        batch.done.set()

    def snapshot(self) -> dict:
        return {"requests": self.requests_n,
                "batches": self.batches_n,
                "batched_queries": self.batched_queries_n}
