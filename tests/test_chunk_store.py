"""Chunk serialization + FS store + cache tests (ref: data_node storage)."""

import numpy as np
import pytest

from ytsaurus_tpu import YtError, native
from ytsaurus_tpu.chunks import ColumnarChunk
from ytsaurus_tpu.chunks.encoding import deserialize_chunk, serialize_chunk
from ytsaurus_tpu.chunks.store import ChunkCache, FsChunkStore
from ytsaurus_tpu.schema import TableSchema

SCHEMA = TableSchema.make([
    ("k", "int64", "ascending"), ("u", "uint64"), ("d", "double"),
    ("b", "boolean"), ("s", "string"), ("a", "any")])


def _chunk(n=100, seed=0):
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        rows.append({
            "k": i,
            "u": int(rng.integers(0, 2**63)) * 2 + 1,
            "d": float(rng.uniform(-1, 1)) if i % 7 else None,
            "b": bool(i % 2) if i % 5 else None,
            "s": f"value-{i % 13}" if i % 3 else None,
            "a": {"i": i} if i % 4 == 0 else [1, i],
        })
    return ColumnarChunk.from_rows(SCHEMA, rows)


def test_native_library_builds():
    # The C++ fast path should actually be in use in CI.
    assert native.lib() is not None


def test_native_roundtrips():
    values = np.array([0, -1, 1, 2**62, -(2**62), 127, -128], dtype=np.int64)
    assert (native.varint_decode(native.varint_encode(values), len(values))
            == values).all()
    bools = np.array([True, False] * 33)
    assert (native.bitmap_unpack(native.bitmap_pack(bools), len(bools))
            == bools).all()
    assert (native.delta_decode(native.delta_encode(values)) == values).all()
    c1 = native.checksum(b"hello world")
    assert c1 == native.checksum(b"hello world")
    assert c1 != native.checksum(b"hello worle")


@pytest.mark.parametrize("codec", ["none", "zlib_6", "lzma"])
def test_serialize_roundtrip(codec):
    chunk = _chunk(200)
    blob = serialize_chunk(chunk, codec)
    back = deserialize_chunk(blob)
    assert back.schema == chunk.schema
    assert back.to_rows() == chunk.to_rows()


def test_corruption_detected():
    chunk = _chunk(50)
    blob = bytearray(serialize_chunk(chunk, "none"))
    blob[-10] ^= 0xFF  # flip a bit in the last block
    with pytest.raises(YtError):
        deserialize_chunk(bytes(blob))


def test_fs_store_roundtrip(tmp_path):
    store = FsChunkStore(str(tmp_path))
    chunk = _chunk(64)
    cid = store.write_chunk(chunk)
    assert store.exists(cid)
    assert store.list_chunks() == [cid]
    back = store.read_chunk(cid)
    assert back.to_rows() == chunk.to_rows()
    meta = store.read_meta(cid)
    assert meta["row_count"] == 64
    store.remove_chunk(cid)
    assert not store.exists(cid)
    with pytest.raises(YtError):
        store.read_chunk(cid)


def test_chunk_cache_lru(tmp_path):
    store = FsChunkStore(str(tmp_path))
    ids = [store.write_chunk(_chunk(32, seed=i)) for i in range(4)]
    # Budget fits ~2 decoded chunks.
    one = ChunkCache(store, capacity_bytes=1).get(ids[0])
    size = ChunkCache._chunk_bytes(one)
    cache = ChunkCache(store, capacity_bytes=int(size * 2.5))
    for cid in ids:
        cache.get(cid)
    assert cache.misses == 4
    cache.get(ids[-1])
    assert cache.hits == 1
    cache.get(ids[0])  # evicted earlier → miss again
    assert cache.misses == 5


def test_compression_shrinks_sorted_keys():
    chunk = _chunk(2000)
    raw = serialize_chunk(chunk, "none")
    packed = serialize_chunk(chunk, "zlib_6")
    assert len(packed) < len(raw)


def test_any_str_roundtrips_as_str():
    schema = TableSchema.make([("k", "int64"), ("a", "any")])
    chunk = ColumnarChunk.from_rows(schema, [(1, "text"), (2, {"x": "y"})])
    back = deserialize_chunk(serialize_chunk(chunk, "none"))
    rows = back.to_rows()
    assert rows[0]["a"] == "text"
    assert rows[1]["a"] == {"x": "y"}


def test_bitmap_unpack_bounds_checked():
    with pytest.raises(ValueError):
        native.bitmap_unpack(b"\x01", 1_000_000)


def test_inflated_meta_row_count_rejected():
    schema = TableSchema.make([("k", "int64")])
    chunk = ColumnarChunk.from_rows(schema, [(1,), (2,)])
    blob = serialize_chunk(chunk, "none")
    # Corrupt row_count in the meta by rewriting it through yson.
    from ytsaurus_tpu.chunks.encoding import MAGIC, read_chunk_meta
    from ytsaurus_tpu.utils.varint import encode_varint_u
    from ytsaurus_tpu import yson as y
    meta = read_chunk_meta(blob)
    start = meta.pop("_data_start")
    payload = blob[start:]
    meta["row_count"] = 10_000_000
    meta_blob = y.dumps(meta, binary=True)
    forged = MAGIC + encode_varint_u(len(meta_blob)) + meta_blob + payload
    with pytest.raises(YtError):
        deserialize_chunk(forged)


# --- replicated store ---------------------------------------------------------

def _replicated(tmp_path, n=3, rf=2):
    from ytsaurus_tpu.chunks.replicated import ReplicatedChunkStore
    return ReplicatedChunkStore(
        [str(tmp_path / f"loc{i}") for i in range(n)], replication_factor=rf)


def test_replicated_write_places_rf_copies(tmp_path):
    store = _replicated(tmp_path)
    chunk = _chunk(32)
    cid = store.write_chunk(chunk)
    copies = sum(1 for loc in store.locations if loc.exists(cid))
    assert copies == 2
    assert store.read_chunk(cid).to_rows() == chunk.to_rows()


def test_replicated_read_survives_location_loss(tmp_path):
    import shutil
    store = _replicated(tmp_path)
    chunk = _chunk(32)
    cid = store.write_chunk(chunk)
    # Destroy the first location holding a replica.
    holder = next(loc for loc in store._placement(cid) if loc.exists(cid))
    shutil.rmtree(holder.root)
    import os
    os.makedirs(holder.root, exist_ok=True)
    assert store.read_chunk(cid).to_rows() == chunk.to_rows()
    # Repair-on-read restored the lost replica.
    copies = sum(1 for loc in store.locations if loc.exists(cid))
    assert copies == 2


def test_replicated_total_loss_raises(tmp_path):
    store = _replicated(tmp_path)
    chunk = _chunk(8)
    cid = store.write_chunk(chunk)
    for loc in store.locations:
        loc.remove_chunk(cid)
    with pytest.raises(YtError):
        store.read_chunk(cid)
    assert not store.exists(cid)


def test_replicated_erasure_passthrough(tmp_path):
    store = _replicated(tmp_path)
    chunk = _chunk(64)
    cid = store.write_chunk(chunk, erasure="rs_3_2")
    assert store.exists(cid)
    assert store.read_chunk(cid).to_rows() == chunk.to_rows()


def test_replicated_remove_and_list(tmp_path):
    store = _replicated(tmp_path)
    ids = sorted(store.write_chunk(_chunk(8, seed=i)) for i in range(4))
    assert store.list_chunks() == ids
    for cid in ids:
        store.remove_chunk(cid)
    assert store.list_chunks() == []


def test_replicated_erasure_not_duplicated_on_read(tmp_path):
    store = _replicated(tmp_path)
    chunk = _chunk(64)
    cid = store.write_chunk(chunk, erasure="rs_3_2")
    store.read_chunk(cid)
    # No full plain replica may appear on other locations.
    import os
    plain = sum(1 for loc in store.locations
                if os.path.exists(loc._path(cid)))
    assert plain == 0


def test_replicated_placement_process_stable(tmp_path):
    # sha-based placement must not depend on the hash seed of this process.
    import hashlib
    store = _replicated(tmp_path)
    cid = "deadbeef" * 4
    want = sorted(range(3), key=lambda i: hashlib.sha256(
        f"{cid}:{i}".encode()).digest())
    got = [store.locations.index(s) for s in store._placement(cid)]
    assert got == want


def test_replicated_spilled_write_not_over_replicated(tmp_path):
    import os, stat
    store = _replicated(tmp_path)
    chunk = _chunk(16)
    # Force a spill: make the second placement location unwritable.
    cid_probe = "feedface" * 4
    placement = store._placement(cid_probe)
    os.chmod(placement[1].root, 0o500)
    try:
        cid = store.write_chunk(chunk, chunk_id=cid_probe)
    finally:
        os.chmod(placement[1].root, 0o700)
    copies = sum(1 for loc in store.locations if loc.exists(cid))
    assert copies == 2                      # spilled to the third location
    # Location recovered: a read must NOT add a third copy.
    store.read_chunk(cid)
    copies = sum(1 for loc in store.locations if loc.exists(cid))
    assert copies == 2


def test_replicated_read_survives_unreadable_location(tmp_path):
    import os
    store = _replicated(tmp_path)
    chunk = _chunk(16)
    cid = store.write_chunk(chunk)
    holder = next(loc for loc in store._placement(cid) if loc.exists(cid))
    # Make the file unreadable (EACCES, not FileNotFound).
    path = holder._path(cid)
    os.chmod(path, 0o000)
    try:
        assert store.read_chunk(cid).to_rows() == chunk.to_rows()
    finally:
        os.chmod(path, 0o600)
