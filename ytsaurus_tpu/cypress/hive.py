"""Hive: reliable exactly-once ordered cross-cell messaging.

Ref mapping (server/lib/hive):
  THiveManager::PostMessage (hive_manager.h:130)  → HiveManager.post —
                                                    message lands in a
                                                    WAL-durable outbox
                                                    with a monotone seqno
  mailbox delivery + acks                         → HiveManager.flush —
                                                    replays every message
                                                    past the receiver's
                                                    last-applied seqno,
                                                    then trims the outbox
  exactly-once application (messages apply as     → HiveManager.apply —
  Hydra mutations on the receiving cell)            handler effects and
                                                    the last-applied bump
                                                    ride ONE atomic batch
                                                    mutation, so a replay
                                                    or crash can never
                                                    half-apply a message

Design delta: handlers are declarative — they return cypress tree ops
(create/set/remove) rather than running arbitrary code, which is what
makes the atomic batch possible (the reference gets the same property by
making message application itself an automaton mutation).
"""

from __future__ import annotations

from typing import Callable, Optional

from ytsaurus_tpu.cypress.security import ROOT_USER, authenticated_user
from ytsaurus_tpu.errors import EErrorCode, YtError

HIVE_ROOT = "//sys/hive"


class HiveManager:
    """One per cell (cluster); in-process registry of message handlers."""

    def __init__(self, client, cell_id: str):
        self.client = client
        self.cell_id = cell_id
        self._handlers: dict[str, Callable] = {}
        # Outbox mutation is read-modify-write over a document; concurrent
        # posters (daemon worker threads share one manager) must not lose
        # a message or duplicate a seqno.
        import threading
        self._outbox_lock = threading.Lock()

    def register_handler(self, message_type: str,
                         handler: Callable[[dict], "list | None"]) -> None:
        """handler(payload) → list of (op, args) cypress tree ops
        (op ∈ create/set/remove) applied atomically with the ack."""
        self._handlers[message_type] = handler

    # ------------------------------------------------------------- sending

    def _outbox_path(self, dst_cell: str) -> str:
        return f"{HIVE_ROOT}/{self.cell_id}/outbox/{dst_cell}"

    def _inbox_path(self, src_cell: str) -> str:
        return f"{HIVE_ROOT}/{self.cell_id}/inbox/{src_cell}"

    def post(self, dst_cell: str, message_type: str,
             payload: Optional[dict] = None) -> int:
        """Enqueue a message; durable before this returns (outbox state is
        a WAL mutation).  Returns the message's seqno."""
        path = self._outbox_path(dst_cell)
        with self._outbox_lock, authenticated_user(ROOT_USER):
            if not self.client.exists(path):
                self.client.create("document", path, recursive=True)
                self.client.set(path, {"next_seqno": 1, "messages": []})
            state = dict(self.client.get(path))
            seqno = int(state["next_seqno"])
            state["messages"] = list(state["messages"]) + [{
                "seqno": seqno, "type": message_type,
                "payload": payload or {}}]
            state["next_seqno"] = seqno + 1
            self.client.set(path, state)
        return seqno

    def pending(self, dst_cell: str) -> int:
        path = self._outbox_path(dst_cell)
        if not self.client.exists(path):
            return 0
        return len(self.client.get(path)["messages"])

    def flush(self, dst_hive: "HiveManager") -> int:
        """Deliver every unacked message to the destination cell, in
        order; idempotent (the receiver dedupes by seqno).  Returns the
        number of messages newly applied.  Acked messages trim from the
        outbox."""
        path = self._outbox_path(dst_hive.cell_id)
        if not self.client.exists(path):
            return 0
        state = dict(self.client.get(path))
        messages = sorted(state["messages"], key=lambda m: m["seqno"])
        applied = 0
        for msg in messages:
            if dst_hive.apply(self.cell_id, msg):
                applied += 1
        last = dst_hive.last_applied(self.cell_id)
        # Trim under the outbox lock, re-reading: a concurrent post may
        # have appended past the snapshot taken above.
        with self._outbox_lock, authenticated_user(ROOT_USER):
            state = dict(self.client.get(path))
            remaining = [m for m in state["messages"]
                         if m["seqno"] > last]
            if len(remaining) != len(state["messages"]):
                state["messages"] = remaining
                self.client.set(path, state)
        return applied

    # ----------------------------------------------------------- receiving

    def last_applied(self, src_cell: str) -> int:
        path = self._inbox_path(src_cell)
        if not self.client.exists(path):
            return 0
        return int(self.client.get(path))

    def apply(self, src_cell: str, msg: dict) -> bool:
        """Apply one message exactly once.  Returns False for duplicates;
        raises on seqno gaps (ordered delivery is part of the contract)."""
        seqno = int(msg["seqno"])
        last = self.last_applied(src_cell)
        if seqno <= last:
            return False
        if seqno != last + 1:
            raise YtError(
                f"Hive message gap from {src_cell!r}: got seqno {seqno}, "
                f"expected {last + 1}", code=EErrorCode.Generic)
        handler = self._handlers.get(msg["type"])
        if handler is None:
            raise YtError(f"No hive handler for {msg['type']!r} "
                          f"on cell {self.cell_id!r}",
                          code=EErrorCode.Generic)
        ops = list(handler(dict(msg.get("payload") or {})) or [])
        inbox = self._inbox_path(src_cell)
        with authenticated_user(ROOT_USER):
            if not self.client.exists(inbox):
                self.client.create("document", inbox, recursive=True)
                self.client.set(inbox, 0)
            # Handler effects + the ack bump in ONE WAL record.
            self.client.cluster.master.commit_mutation("batch", ops=(
                [{"op": op, "args": args} for op, args in ops] +
                [{"op": "set", "args": {"path": inbox, "value": seqno}}]))
        return True
