"""Job splitter + controller snapshots / operation revival.

Ref model: job_splitter.h (straggler splits into smaller jobs),
controller operation snapshots + revival (snapshot_builder.cpp,
snapshot_downloader.cpp — redesigned without fork: per-stripe output
chunks + a plan-matched completed set).
"""

import time

import pytest

from ytsaurus_tpu.client import connect
from ytsaurus_tpu.operations.chunk_pools import Stripe, split_stripe
from ytsaurus_tpu.operations.jobs import Job, JobManager, run_command_job
from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.schema import TableSchema


def _chunk(n, start=0):
    return ColumnarChunk.from_arrays(
        TableSchema.make([("x", "int64")]),
        {"x": list(range(start, start + n))})


def test_split_stripe_halves_rows():
    stripe = Stripe()
    stripe.add(_chunk(6), 0, 6)
    stripe.add(_chunk(4, start=6), 0, 4)
    halves = split_stripe(stripe)
    assert len(halves) == 2
    assert halves[0].row_count == 5 and halves[1].row_count == 5
    left = [r["x"] for r in halves[0].materialize().to_rows()]
    right = [r["x"] for r in halves[1].materialize().to_rows()]
    assert left + right == list(range(10))
    # Single-row stripes don't split.
    tiny = Stripe()
    tiny.add(_chunk(1), 0, 1)
    assert len(split_stripe(tiny)) == 1


def test_straggler_splits_into_children():
    manager = JobManager(slots=4, speculation_factor=1.5,
                         min_speculation_seconds=0.3)
    state = {"first": True}

    def slow_then_fast(job):
        if state["first"]:
            state["first"] = False
            return run_command_job(job, "sleep 30; echo late", b"")
        return run_command_job(job, "echo part", b"")

    def splitter(parent):
        return [Job(op_id="op", index=parent.index,
                    run=lambda j: [b"left"], preemptible=True),
                Job(op_id="op", index=parent.index,
                    run=lambda j: [b"right"], preemptible=True)]

    quick = [Job(op_id="op", index=i,
                 run=lambda j: run_command_job(j, "echo q", b""),
                 preemptible=True) for i in range(3)]
    straggler = Job(op_id="op", index=99, run=slow_then_fast,
                    preemptible=True, splitter=splitter)
    t0 = time.monotonic()
    manager.run_all(quick + [straggler], timeout=20)
    assert time.monotonic() - t0 < 15
    assert straggler.state == "completed"
    assert straggler.result == [b"left", b"right"]
    assert straggler.split_children is not None


def test_map_revival_skips_completed_stripes(tmp_path):
    """Simulate a controller crash: operation doc left 'running' with a
    snapshot for stripe 0; revival runs only stripe 1."""
    client = connect(str(tmp_path))
    client.write_table("//in", [{"x": i} for i in range(4)])
    spec = {"command": "cat", "input_table_path": "//in",
            "output_table_path": "//out", "rows_per_job": 2,
            "format": "json"}
    # Forge the crashed operation record + snapshot, exactly as the
    # controller would have written them.
    from ytsaurus_tpu.operations.scheduler import _Snapshot, _clean_spec
    op_id = "deadbeef"
    doc = f"//sys/operations/{op_id}"
    client.create("document", doc, recursive=True)
    client.set(doc + "/@operation_type", "map")
    client.set(doc + "/@spec", _clean_spec(spec))
    client.set(doc + "/@state", "running")
    input_chunk_ids = client.get("//in/@chunk_ids")
    snap = _Snapshot(client, op_id,
                     plan={"input_chunk_ids": list(input_chunk_ids),
                           "stripe_count": 2})
    snap.record(0, [{"x": 0, "marker": "from_snapshot"},
                    {"x": 1, "marker": "from_snapshot"}])
    revived = client.scheduler.revive_operations()
    assert [op.id for op in revived] == [op_id]
    op = revived[0]
    assert op.state == "completed"
    assert op.result["revived_jobs"] == 1
    assert op.result["jobs"] == 1          # only the missing stripe ran
    rows = client.read_table("//out")
    markers = [r.get("marker") for r in rows]
    assert markers[:2] == [b"from_snapshot", b"from_snapshot"]
    assert sorted(r["x"] for r in rows) == [0, 1, 2, 3]
    # Snapshot cleaned up after publish.
    assert not client.exists(doc + "/@snapshot")


def _wait_idle(client, deadline=60.0):
    """Event-based wait: controller settled = no pending/running jobs."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline:
        stats = client.scheduler.job_manager.stats()
        if stats["pending"] == 0 and stats["running"] == 0:
            # One extra beat lets the controller thread run its (would-
            # be) publish after the jobs settle — the window the
            # regression guards.
            time.sleep(0.5)
            stats = client.scheduler.job_manager.stats()
            if stats["pending"] == 0 and stats["running"] == 0:
                return
        time.sleep(0.05)
    raise AssertionError("job manager never settled")


def test_abort_mid_map_keeps_destination_and_snapshot(tmp_path):
    """Aborting a map mid-run must NOT publish partial rows over the
    destination table, and must leave the revival snapshot intact (an
    aborted wait used to fall through to publish + snap.clear)."""
    client = connect(str(tmp_path))
    client.write_table("//in", [{"x": i} for i in range(4)])
    client.write_table("//out", [{"x": 999, "marker": "sentinel"}])
    gate = tmp_path / "gate"
    # Exactly one stripe completes (atomic mkdir wins); the rest block
    # until the abort kills them.
    cmd = (f"mkdir {gate} 2>/dev/null "
           f"&& echo '{{\"x\": 7}}' || sleep 600")
    op = client.scheduler.start_operation("map", {
        "command": cmd, "input_table_path": "//in",
        "output_table_path": "//out", "rows_per_job": 2,
        "format": "json"}, sync=False)
    doc = f"//sys/operations/{op.id}"
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:     # wait for the completed stripe
        if client.exists(doc + "/@snapshot") and \
                (client.get(doc + "/@snapshot").get("completed") or {}):
            break
        time.sleep(0.05)
    else:
        raise AssertionError("no stripe completed before abort")
    client.abort_operation(op.id)
    _wait_idle(client)
    assert op.state == "aborted"
    # Destination untouched: still exactly the sentinel row.
    out = client.read_table("//out")
    assert [r.get("marker") for r in out] == [b"sentinel"]
    # Revival snapshot intact (the completed stripe's record survives).
    snap = client.get(doc + "/@snapshot")
    assert len(snap.get("completed") or {}) >= 1


def test_abort_mid_map_reduce_skips_reduce_phase(tmp_path):
    """An abort landing during the MAP phase of map_reduce must stop the
    reduce phase from running and publishing."""
    client = connect(str(tmp_path))
    client.write_table("//in", [{"x": i} for i in range(4)])
    client.write_table("//mr_out", [{"x": 999, "marker": "sentinel"}])
    gate = tmp_path / "gate"
    map_cmd = (f"mkdir {gate} 2>/dev/null "
               f"&& echo '{{\"x\": 1}}' || sleep 600")
    reduce_ran = tmp_path / "reduce_ran"
    op = client.scheduler.start_operation("map_reduce", {
        "map_command": map_cmd,
        "reduce_command": f"touch {reduce_ran}; cat",
        "input_table_path": "//in", "output_table_path": "//mr_out",
        "reduce_by": "x", "rows_per_job": 2, "format": "json"},
        sync=False)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:     # one map job ran, one blocks
        if gate.exists():
            break
        time.sleep(0.05)
    else:
        raise AssertionError("map phase never started")
    client.abort_operation(op.id)
    _wait_idle(client)
    assert op.state == "aborted"
    assert not reduce_ran.exists()         # reduce phase never launched
    out = client.read_table("//mr_out")
    assert [r.get("marker") for r in out] == [b"sentinel"]


def test_revival_plan_mismatch_restarts(tmp_path):
    """A changed input invalidates the snapshot: everything re-runs."""
    client = connect(str(tmp_path))
    client.write_table("//in", [{"x": i} for i in range(4)])
    from ytsaurus_tpu.operations.scheduler import _Snapshot, _clean_spec
    op_id = "cafebabe"
    doc = f"//sys/operations/{op_id}"
    spec = {"command": "cat", "input_table_path": "//in",
            "output_table_path": "//out", "rows_per_job": 2,
            "format": "json"}
    client.create("document", doc, recursive=True)
    client.set(doc + "/@operation_type", "map")
    client.set(doc + "/@spec", _clean_spec(spec))
    client.set(doc + "/@state", "running")
    snap = _Snapshot(client, op_id,
                     plan={"input_chunk_ids": ["stale-chunk-id"],
                           "stripe_count": 2})
    snap.record(0, [{"x": 777, "marker": "stale"}])
    revived = client.scheduler.revive_operations()
    op = revived[0]
    assert op.state == "completed"
    assert op.result["revived_jobs"] == 0
    assert op.result["jobs"] == 2
    assert sorted(r["x"] for r in client.read_table("//out")) == [0, 1, 2, 3]


def test_crash_between_snapshot_record_and_publish_revives(tmp_path):
    """ISSUE 2: a crash-once failpoint at `scheduler.publish` kills the
    controller AFTER every stripe is snapshot-recorded but BEFORE the
    output publishes.  InjectedCrash pierces the controller's error
    handling (like a real process death), so the operation doc stays
    'running' — and revival must replay purely from the snapshot."""
    from ytsaurus_tpu.utils import failpoints

    client = connect(str(tmp_path))
    client.write_table("//in", [{"x": i} for i in range(4)])
    spec = {"command": "cat", "input_table_path": "//in",
            "output_table_path": "//out", "rows_per_job": 2,
            "format": "json"}
    with failpoints.active("scheduler.publish=crash-once"):
        with pytest.raises(failpoints.InjectedCrash):
            client.scheduler.start_operation("map", spec)
    [op_id] = client.list("//sys/operations")
    doc = f"//sys/operations/{op_id}"
    # The "crashed" controller recorded neither completion nor failure.
    assert client.get(doc + "/@state") == "running"
    # Snapshot records land from worker-thread on_done observers, which
    # may still be in flight when the controller crash unwinds.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if client.exists(doc + "/@snapshot") and len(
                client.get(doc + "/@snapshot").get("completed") or {}) == 2:
            break
        time.sleep(0.05)
    snap = client.get(doc + "/@snapshot")
    assert len(snap.get("completed") or {}) == 2
    assert not client.exists("//out")
    # Simulate the controller process dying: forget the live operation.
    client.scheduler._operations.clear()
    revived = client.scheduler.revive_operations()
    assert [op.id for op in revived] == [op_id]
    op = revived[0]
    assert op.state == "completed"
    assert op.result["revived_jobs"] == 2      # everything from snapshot
    assert op.result["jobs"] == 0              # no stripe re-ran
    assert sorted(r["x"] for r in client.read_table("//out")) == [0, 1, 2, 3]
    assert not client.exists(doc + "/@snapshot")


def test_injected_job_failures_absorbed_by_quarantine(tmp_path):
    """max_failed_job_count (ISSUE 2 hardening): transient job failures
    requeue within the per-job attempt budget instead of failing the
    operation; one past the budget fails it."""
    from ytsaurus_tpu.errors import YtError
    from ytsaurus_tpu.utils import failpoints

    client = connect(str(tmp_path))
    client.write_table("//in", [{"x": i} for i in range(4)])
    spec = {"command": "cat", "input_table_path": "//in",
            "output_table_path": "//out", "rows_per_job": 2,
            "max_failed_job_count": 3, "format": "json"}
    with failpoints.active("jobs.start=error:times=2"):
        op = client.scheduler.start_operation("map", spec)
    assert op.state == "completed"
    assert sorted(r["x"] for r in client.read_table("//out")) == [0, 1, 2, 3]
    # Budget exhausted: with only 1 allowed failure, 2 injected faults on
    # the same job CAN fail the operation — prove failures still surface.
    spec2 = dict(spec, output_table_path="//out2", max_failed_job_count=1,
                 raise_on_failure=True)
    with failpoints.active("jobs.start=error:times=8"):
        with pytest.raises(YtError):
            client.scheduler.start_operation("map", spec2)
