"""Adaptive ordered prefetch: lazy shard staging + feedback-bounded
lookahead in the coordinator.

Ref model: engine_api/coordinator.h:81-90 — scanOrder + prefetch; an
ordered LIMIT must not stage the shards its early exit skips, and a
full scan overlaps shard i+1's staging with shard i's evaluation.
"""

import time

import numpy as np

from tests.harness import evaluate  # noqa: F401  (env pinning via conftest)
from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.client import connect
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.query.coordinator import coordinate_and_execute
from ytsaurus_tpu.query.statistics import QueryStatistics
from ytsaurus_tpu.schema import TableSchema

T = "//t"
SCHEMA = TableSchema.make([("k", "int64", "ascending"), ("v", "int64")])


def _shards(n=8, rows=50):
    out = []
    for s in range(n):
        ks = np.arange(rows) + s * 1000
        out.append(ColumnarChunk.from_arrays(
            SCHEMA, {"k": ks, "v": ks * 2}))
    return out


def test_ordered_limit_touches_at_most_two_shards():
    """The done-criterion: 8 range-ordered shards, ORDER BY key LIMIT —
    only the shard(s) the scan actually read were staged."""
    staged: list[int] = []
    chunks = _shards()

    def supplier(i):
        def make():
            staged.append(i)
            return chunks[i]
        return make

    stats = QueryStatistics()
    plan = build_query(f"k, v FROM [{T}] ORDER BY k ASC LIMIT 5",
                       {T: SCHEMA})
    out = coordinate_and_execute(
        plan, [supplier(i) for i in range(8)],
        merge_shards_below=4_000_000, range_ordered_by=["k"],
        stats=stats)
    rows = out.to_rows()
    assert [r["k"] for r in rows] == [0, 1, 2, 3, 4]
    assert len(set(staged)) <= 2, f"staged shards: {sorted(set(staged))}"
    assert stats.shards_staged <= 2
    assert stats.shards_skipped >= 6


def test_ordered_limit_desc_stages_from_the_far_end():
    staged: list[int] = []
    chunks = _shards()

    def supplier(i):
        def make():
            staged.append(i)
            return chunks[i]
        return make

    stats = QueryStatistics()
    plan = build_query(f"k FROM [{T}] ORDER BY k DESC LIMIT 3",
                       {T: SCHEMA})
    out = coordinate_and_execute(
        plan, [supplier(i) for i in range(8)],
        merge_shards_below=4_000_000, range_ordered_by=["k"],
        stats=stats)
    assert [r["k"] for r in out.to_rows()] == [7049, 7048, 7047]
    assert 7 in staged                    # scanned from the top end
    assert 0 not in staged                # never touched the bottom


def test_lazy_matches_eager_results():
    chunks = _shards(5, 30)
    for query in (
            f"sum(v) AS s FROM [{T}] GROUP BY 1",
            f"k FROM [{T}] WHERE v % 100 = 0 ORDER BY k ASC LIMIT 4",
            f"k FROM [{T}] LIMIT 7"):
        plan = build_query(query, {T: SCHEMA})
        eager = coordinate_and_execute(
            plan, list(chunks), range_ordered_by=["k"]).to_rows()
        lazy = coordinate_and_execute(
            plan, [(lambda c=c: c) for c in chunks],
            range_ordered_by=["k"]).to_rows()

        def canon(rows):
            return sorted(tuple(sorted(r.items())) for r in rows)
        assert canon(lazy) == canon(eager), query


def test_full_scan_overlaps_stage_with_compute():
    """The second done-criterion: with slow staging, the pipelined scan
    beats the serial stage-then-evaluate lower bound."""
    n, delay = 6, 0.2
    chunks = _shards(n, 2000)
    evals = []

    def supplier(i):
        def make():
            time.sleep(delay)             # slow store fetch
            return chunks[i]
        return make

    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    evaluator = Evaluator()
    plan = build_query(f"sum(v) AS s FROM [{T}] GROUP BY 1", {T: SCHEMA})
    # Warm the compile cache so timing measures staging overlap, not XLA.
    coordinate_and_execute(plan, [(lambda c=c: c) for c in chunks],
                           evaluator=evaluator)
    t0 = time.perf_counter()
    out = coordinate_and_execute(plan, [supplier(i) for i in range(n)],
                                 evaluator=evaluator)
    elapsed = time.perf_counter() - t0
    expect = sum(r["k"] * 2 for c in chunks for r in c.to_rows())
    assert out.to_rows()[0]["s"] == expect
    serial_staging = n * delay
    assert elapsed < serial_staging * 0.85, \
        f"no overlap: {elapsed:.2f}s vs serial staging {serial_staging:.2f}s"


def test_client_ordered_limit_stages_few_shards(tmp_path):
    """End-to-end through the client: a resharded sorted dynamic table,
    ORDER BY key LIMIT — the statistics prove the skipped tablets were
    never staged."""
    client = connect(str(tmp_path))
    client.create("table", "//dyn", recursive=True,
                  attributes={"schema": SCHEMA, "dynamic": True})
    client.reshard_table("//dyn", [(100,), (200,), (300,), (400,),
                                   (500,), (600,), (700,)])
    client.mount_table("//dyn")
    client.insert_rows("//dyn", [{"k": i, "v": i} for i in range(800)])
    rows = client.select_rows(
        "k FROM [//dyn] ORDER BY k ASC LIMIT 5")
    assert [r["k"] for r in rows] == [0, 1, 2, 3, 4]
    stats = client.last_query_statistics
    assert stats.shards_staged <= 2, stats.to_dict()
    assert stats.shards_skipped >= 6, stats.to_dict()
    # Full scans still see every row.
    rows = client.select_rows("sum(v) AS s FROM [//dyn] GROUP BY 1")
    assert rows[0]["s"] == sum(range(800))


def test_ordered_tablet_snapshot_pins_a_cut(tmp_path):
    """Deferred ordered-table scans read one commit-timestamp moment:
    rows pushed AFTER the cut is pinned are invisible to every shard's
    supplier, no matter how late it runs."""
    client = connect(str(tmp_path))
    schema = TableSchema.make([("data", "string")])
    client.create("table", "//q", recursive=True,
                  attributes={"schema": schema, "dynamic": True,
                              "ordered": True})
    client.mount_table("//q")
    client.push_queue("//q", [{"data": f"r{i}"} for i in range(5)])
    (tablet,) = client._mounted_tablets("//q")
    cut = client.cluster.transactions.timestamps.generate()
    client.push_queue("//q", [{"data": "late"}])
    snap = tablet.snapshot(cut)
    datas = [r["data"] for r in snap.to_rows()]
    assert len(datas) == 5 and b"late" not in datas
    # Un-pinned snapshot sees everything.
    assert len(tablet.snapshot().to_rows()) == 6
    # Lazy ordered LIMIT scans (the client path) stay correct.
    rows = client.select_rows("data FROM [//q] LIMIT 3")
    assert len(rows) == 3
