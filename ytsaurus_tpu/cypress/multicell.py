"""Multicell Cypress: portal entrances routing subtrees to secondary
master cells.

Ref: yt/yt/server/master/cypress_server/portal_entrance_node.h /
portal_exit_node.h + the cell_master multicell topology (and the
Sequoia direction of moving metadata out of a single master's memory):
the primary cell owns the root namespace; a `portal_entrance` node at
//path delegates everything beneath it to a secondary cell hosting the
portal exit at the SAME path with its own WAL, snapshots, and quota
accounting.  Clients see one namespace — the split happens at path
resolution.

Design deltas (consistent with the rest of the framework):
- A cell is a full framework cluster (own master + chunk plane),
  reached through the same client registry table replication uses for
  remote clusters — no bespoke cell transport.
- Cross-cell lifecycle rides the existing Hive exactly-once mailboxes
  (cypress/hive.py): removing a portal entrance posts an exit-cleanup
  message to the secondary cell, applied atomically with its inbox ack,
  so a crashed primary retries and the exit is dismantled exactly once.
"""

from __future__ import annotations

from typing import Optional

from ytsaurus_tpu.cypress.tree import parse_ypath
from ytsaurus_tpu.errors import EErrorCode, YtError

PORTAL_TYPE = "portal_entrance"
EXIT_CLEANUP = "portal_exit_cleanup"


def portal_prefix(client, path: str, include_self: bool = False
                  ) -> "Optional[tuple[str, dict]]":
    """The longest portal-entrance prefix of `path` (entrance path,
    entrance attributes), or None.  By default only STRICT prefixes
    route (the entrance node itself is primary-cell metadata);
    include_self also routes the entrance path — the read verbs resolve
    an entrance to its exit, like the reference's entrance→exit
    resolution."""
    try:
        tokens, attr = parse_ypath(path)
    except YtError:
        return None
    tree = client.cluster.master.tree
    # An attribute read on the entrance itself (//portal/@x) addresses
    # the ENTRANCE node, never the exit.
    upto = len(tokens) + (1 if include_self and attr is None else 0)
    for i in range(1, upto):
        prefix = "//" + "/".join(tokens[:i])
        node = tree.try_resolve(prefix)
        if node is None:
            return None
        if node.type == PORTAL_TYPE:
            return prefix, dict(node.attributes)
    return None


def cell_client(client, cell_root: str):
    """Secondary-cell client, cached on the primary client's replicator
    registry (the same remote-cluster cache replication uses)."""
    return client.table_replicator.replica_client(cell_root)


def delegate_for(client, path: str, permission: "Optional[str]",
                 include_self: bool = False):
    """Routed-verb front door: resolves the owning cell AND enforces the
    PRIMARY's ACLs at the portal entrance.  The cell executes the call
    under the cell-trust principal (root) — cross-cell requests carry
    the primary's authorization decision, not a per-cell user registry
    (primary principals do not exist in the secondary's //sys/users).
    Wrap the delegated call in as_cell_principal()."""
    hit = portal_prefix(client, path, include_self=include_self)
    if hit is None:
        return None
    entrance, attrs = hit
    if permission is not None:
        client.cluster.security.validate_permission(permission, entrance)
    cell_root = attrs.get("cell_root")
    if not cell_root:
        raise YtError("portal entrance has no @cell_root",
                      code=EErrorCode.ResolveError)
    return cell_client(client, cell_root)


def as_cell_principal():
    """Context for delegated calls: the cell trusts the primary's ACL
    check at the entrance."""
    from ytsaurus_tpu.cypress.security import ROOT_USER, authenticated_user
    return authenticated_user(ROOT_USER)


def reject_under_portal(client, path: str, what: str) -> None:
    """Loud failure for verbs that do not route across portals yet
    (copy/move/link/lock, dynamic-table verbs): acting on the primary
    tree would either miss or SHADOW the secondary's nodes."""
    if portal_prefix(client, path) is not None:
        raise YtError(f"{what} across a portal is not supported yet "
                      f"({path!r} lives on a secondary cell)",
                      code=EErrorCode.QueryUnsupported)


def reject_tx(tx) -> None:
    if tx is not None:
        raise YtError("cross-cell transactions are not supported",
                      code=EErrorCode.QueryUnsupported)


def create_portal(client, path: str, attributes: dict,
                  recursive: bool = False,
                  ignore_existing: bool = False) -> str:
    """Create the entrance on the primary and the exit root on the
    secondary cell (same path), so routed creates find their ancestors."""
    attrs = dict(attributes or {})
    cell_root = attrs.get("cell_root")
    if not cell_root:
        raise YtError("portal_entrance requires @cell_root",
                      code=EErrorCode.ResolveError)
    attrs.setdefault("cell_tag", 1)
    node_id = client.cluster.master.commit_mutation(
        "create", path=path, type=PORTAL_TYPE, attributes=attrs,
        recursive=recursive, ignore_existing=ignore_existing)
    exit_client = cell_client(client, cell_root)
    exit_client.create("map_node", path, recursive=True,
                       ignore_existing=True,
                       attributes={"portal_exit": True})
    return node_id


def portals_under(path: str, node) -> "list[tuple[str, str]]":
    """(entrance path, cell_root) for every portal entrance inside the
    subtree rooted at `node` (including `node` itself)."""
    out: list = []
    stack = [(path, node)]
    while stack:
        prefix, current = stack.pop()
        if current.type == PORTAL_TYPE:
            cell_root = (current.attributes or {}).get("cell_root")
            if cell_root:
                out.append((prefix, cell_root))
            continue                # nothing routable lives beneath it
        for name, child in current.children.items():
            stack.append((f"{prefix}/{name}", child))
    return out


def remove_portal(client, path: str, entrance_attrs: dict,
                  recursive: bool = True, tx=None) -> None:
    """Remove the entrance, then dismantle the exit subtree on the
    secondary via Hive.  Order matters: the PRIMARY removal commits
    first, so a failed/refused primary remove never destroys exit data;
    a crash between the two steps leaks the exit until the next cleanup
    (bounded, and strictly safer than the converse).  Cross-cell
    removal cannot ride a primary transaction — a rollback could not
    restore the exit — so tx is rejected."""
    reject_tx(tx)
    cell_root = entrance_attrs.get("cell_root")
    exit_client = cell_client(client, cell_root)
    with as_cell_principal():
        non_empty = exit_client.exists(path) and exit_client.list(path)
    if not recursive and non_empty:
        raise YtError(f"Cannot remove non-empty portal {path!r} without "
                      "recursive=True", code=EErrorCode.Generic)
    client.cluster.master.commit_mutation("remove", path=path,
                                          recursive=True)
    _dismantle_exit(client, cell_root, path)


def _dismantle_exit(client, cell_root: str, path: str) -> None:
    """Exactly-once exit removal through Hive (durable outbox intent,
    idempotent receiver)."""
    src = hive_of(client)
    dst = hive_of(cell_client(client, cell_root))
    _ensure_cleanup_handler(dst)
    src.post(dst.cell_id, EXIT_CLEANUP, {"path": path})
    src.flush(dst)


def hive_of(client):
    """One HiveManager per cluster, cell id = the cluster root dir."""
    manager = getattr(client, "_hive_manager", None)
    if manager is None:
        from ytsaurus_tpu.cypress.hive import HiveManager
        manager = HiveManager(client, cell_id=_cell_id(client))
        _ensure_cleanup_handler(manager)
        client._hive_manager = manager
    return manager


def _cell_id(client) -> str:
    root = client.cluster.root_dir
    # Cell ids appear in cypress paths: keep them token-safe.
    return "cell-" + "".join(
        c if c.isalnum() else "-" for c in root).strip("-")


def _ensure_cleanup_handler(manager) -> None:
    if EXIT_CLEANUP in manager._handlers:
        return

    def handle(payload: dict):
        path = payload["path"]
        node = manager.client.cluster.master.tree.try_resolve(path)
        if node is None:
            return []               # already gone: idempotent
        # Portals CHAINED inside this exit must dismantle their own
        # (third-cell) exits too, or a recreated chain resurrects stale
        # data there.  This bends Hive's declarative-handler contract
        # (remote posts happen DURING apply, outside the atomic ack
        # batch), which is safe here because dismantles are idempotent:
        # a crash-then-reapply re-posts a cleanup whose receiver finds
        # the path already gone and acks a no-op.
        for nested_path, nested_root in portals_under(path, node):
            _dismantle_exit(manager.client, nested_root, nested_path)
        return [("remove", {"path": path, "recursive": True})]

    manager.register_handler(EXIT_CLEANUP, handle)
