"""Fair-share scheduling math over a pool tree.

Ref shape: library/vector_hdrf/fair_share_update.h (multi-resource
dominant-fairness with piecewise-linear water filling) and the scheduler
strategy (server/scheduler/strategy) — pools carry weight + min-share
guarantees; operations map to pools; the scheduler serves the pool whose
usage is furthest below its fair share.

Redesign: the local job plane has ONE resource (worker slots), so vector
HDRF collapses to scalar progressive filling: min-share guarantees first,
then weight-proportional water filling of the remainder, capped by
demand.  Pool definitions live in Cypress (//sys/pools/<name>/@weight,
@min_share_ratio, @max_running_jobs) like the reference's pool trees.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class PoolState:
    name: str
    weight: float = 1.0
    min_share_ratio: float = 0.0
    max_running_jobs: int | None = None
    # live
    running: int = 0
    pending: int = 0
    fair_share: float = 0.0
    usage: float = 0.0

    @property
    def demand(self) -> int:
        return self.running + self.pending


def compute_fair_shares(pools: "list[PoolState]", total_slots: int) -> None:
    """Progressive filling: guarantee min(min_share, demand), then water-
    fill the remainder proportionally to weight, never past demand.
    Writes .fair_share / .usage on each pool (shares of total_slots)."""
    if total_slots <= 0:
        for p in pools:
            p.fair_share = p.usage = 0.0
        return
    demand = {p.name: min(p.demand / total_slots, 1.0) for p in pools}
    share = {p.name: min(p.min_share_ratio, demand[p.name]) for p in pools}
    budget = 1.0 - sum(share.values())
    # Water filling: raise unsatisfied pools proportionally to weight until
    # the budget is spent or every demand is met.
    for _ in range(32):                       # converges in <= |pools| steps
        unsat = [p for p in pools if share[p.name] < demand[p.name] - 1e-12]
        if not unsat or budget <= 1e-12:
            break
        weights = {p.name: max(p.weight, 0.0) for p in unsat}
        total_weight = sum(weights.values())
        if total_weight <= 0.0:
            # All-zero weights (user-configurable): split the remainder
            # evenly rather than dividing by zero.
            weights = {p.name: 1.0 for p in unsat}
            total_weight = float(len(unsat))
        step = budget / total_weight
        spent = 0.0
        for p in unsat:
            raise_by = min(step * weights[p.name],
                           demand[p.name] - share[p.name])
            share[p.name] += raise_by
            spent += raise_by
        budget -= spent
        if spent <= 1e-12:
            break
    for p in pools:
        p.fair_share = share[p.name]
        p.usage = p.running / total_slots


def pick_pool(pools: "list[PoolState]") -> "PoolState | None":
    """The pool to serve next: lowest usage-to-fair-share ratio among
    pools with pending demand and headroom."""
    best = None
    best_ratio = None
    for p in pools:
        if p.pending <= 0 or p.fair_share <= 0:
            continue
        if p.max_running_jobs is not None and \
                p.running >= p.max_running_jobs:
            continue
        ratio = p.usage / p.fair_share
        if best is None or ratio < best_ratio or \
                (ratio == best_ratio and p.name < best.name):
            best, best_ratio = p, ratio
    return best


def find_preemptable(pools: "list[PoolState]") -> "PoolState | None":
    """A pool running ABOVE fair share while some pool with pending work
    sits below its own (starvation) — its newest job may be preempted.
    Returns the most-over-share pool, or None when fairness holds."""
    starving = any(p.pending > 0 and
                   p.usage < p.fair_share - 1e-9 for p in pools)
    if not starving:
        return None
    over = [p for p in pools if p.running > 0 and
            p.usage > p.fair_share + 1e-9]
    if not over:
        return None
    return max(over, key=lambda p: p.usage - p.fair_share)
