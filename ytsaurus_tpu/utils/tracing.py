"""Distributed trace contexts with sampling, baggage, and a flight
recorder (ISSUE 5 tentpole substrate).

Ref shape: core/tracing/trace_context.h:75 — a TTraceContext carries
(trace id, span id, parent span id, sampled flag, baggage), is propagated
implicitly through fibers and explicitly through RPC headers, and finished
spans go to an exporter (Jaeger in the reference).

Redesign: a `contextvars`-based ambient context (survives asyncio + thread
pools via explicit capture in the RPC layer), spans finished into an
in-process ring buffer that Orchid/monitoring `/traces` read; the wire
encoding is a plain dict injected into the RPC envelope.

Span-site discipline (what keeps an untraced hot path ~free):

  start_span(name)        child of the ambient context, or a SAMPLED
                          fresh root (rate from config.TracingConfig) —
                          legacy entry-point helper.
  child_span(name)        INTERIOR site: child of the ambient context,
                          NULL when there is none (or it is unsampled).
                          This is the probe threaded through the query/
                          operation planes; its disabled fast path is one
                          contextvar read + a singleton return (≲1µs,
                          asserted by `bench.py --config trace_overhead`,
                          mirroring the failpoints fast-path assert).
  start_query_span(name)  ENTRY point (gateway select/lookup, scheduler
                          operation, HTTP proxy): continues the ambient
                          trace when one exists, else roots a new trace
                          subject to `enabled` + `sample_rate` —
                          `force=True` (explain_analyze) always samples.

The collector is a bounded ring with a CURSOR-based drain: the daemon's
TraceExporter consumes each span once while `/traces`, `find()`, and the
flight recorder keep serving from the retained tail.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import random
import threading
import time
from typing import Any, Optional
from ytsaurus_tpu.utils import sanitizers

# Id generation: a per-process random prefix + an atomic counter (the
# `itertools.count` step is GIL-atomic).  uuid4 costs ~16µs per call in
# entropy-starved containers — two per span would dwarf every other cost
# on the sampled path; ids only need uniqueness, not unpredictability.
_ID_PREFIX = int.from_bytes(os.urandom(8), "big")
_ID_COUNTER = itertools.count(int.from_bytes(os.urandom(6), "big"))
_ID_MASK = (1 << 64) - 1


def _new_trace_id() -> str:
    return f"{_ID_PREFIX:016x}{next(_ID_COUNTER) & _ID_MASK:016x}"


def _new_span_id() -> str:
    # Mixed with the process prefix so two processes sharing one trace
    # cannot collide span ids at similar counter values.
    return f"{(_ID_PREFIX ^ (next(_ID_COUNTER) * 0x9E3779B97F4A7C15)) & _ID_MASK:016x}"

_current: contextvars.ContextVar[Optional["TraceContext"]] = \
    contextvars.ContextVar("trace_context", default=None)

# Fast-path mirrors of config.TracingConfig (one module-global read per
# span site, same discipline as utils/failpoints._STATE).
_ENABLED = True
_SAMPLE_RATE = 1.0


def configure(config) -> None:
    """Apply a config.TracingConfig process-wide (None → defaults)."""
    global _ENABLED, _SAMPLE_RATE
    if config is None:
        _ENABLED, _SAMPLE_RATE = True, 1.0
        _collector.set_capacity(4096)
        return
    _ENABLED = bool(config.enabled)
    _SAMPLE_RATE = float(config.sample_rate)
    _collector.set_capacity(int(config.ring_capacity))


def tracing_enabled() -> bool:
    return _ENABLED


class SpanRecord:
    """One finished span (exporter unit)."""

    __slots__ = ("trace_id", "span_id", "parent_span_id", "name", "start",
                 "duration", "tags", "baggage", "seq")

    def __init__(self, ctx: "TraceContext", duration: float):
        self.trace_id = ctx.trace_id
        self.span_id = ctx.span_id
        self.parent_span_id = ctx.parent_span_id
        self.name = ctx.name
        self.start = ctx.start_time
        self.duration = duration
        self.tags = dict(ctx.tags)
        self.baggage = dict(ctx.baggage)
        self.seq = 0                    # stamped by the collector

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__ if k != "seq"}


class SpanCollector:
    """Bounded ring of finished sampled spans with a drain cursor.

    `drain()` hands each span to the exporter exactly once; the ring
    RETAINS everything up to `capacity` so `/traces` and `find()` keep
    serving after an export cycle (the pre-flight-recorder destructive
    drain made a daemon's trace views go empty between scrapes)."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        # guards: _spans, _seq, _drained, _hists, capacity
        self._lock = sanitizers.register_lock(
            "tracing.SpanCollector._lock")
        self._spans: list[SpanRecord] = []
        self._seq = 0                  # spans ever added
        self._drained = 0              # seq consumed by drain()
        self._hists: dict[str, Any] = {}

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self.capacity = max(int(capacity), 1)
            if len(self._spans) > self.capacity:
                del self._spans[:len(self._spans) - self.capacity]

    def add(self, span: SpanRecord) -> None:
        with self._lock:
            self._seq += 1
            span.seq = self._seq
            self._spans.append(span)
            if len(self._spans) > self.capacity:
                del self._spans[:len(self._spans) - self.capacity]
        self._record_duration(span)

    def _record_duration(self, span: SpanRecord) -> None:
        # Span-duration histograms on /metrics (tracing_span_seconds
        # {name=...}); per-name sensor cached — the registry lookup is
        # a lock + dict probe we don't want per span.
        hist = self._hists.get(span.name)
        if hist is None:
            from ytsaurus_tpu.utils.profiling import Profiler
            hist = Profiler("/tracing").with_tags(
                name=span.name).histogram(
                    "span_seconds",
                    bounds=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05,
                            0.1, 0.5, 1.0, 5.0, 30.0))
            # Install under the lock (the lock pass flagged the bare
            # dict write): setdefault keeps the winner if two threads
            # race the first span of a name — the registry already
            # dedups the sensor, so both hists ARE the same object.
            with self._lock:
                hist = self._hists.setdefault(span.name, hist)
        hist.record(span.duration)

    def drain(self) -> list[SpanRecord]:
        """Spans added since the previous drain (cursor advance)."""
        with self._lock:
            fresh = [s for s in self._spans if s.seq > self._drained]
            self._drained = self._seq
            return fresh

    def snapshot(self) -> list[SpanRecord]:
        with self._lock:
            return list(self._spans)

    def find(self, trace_id: str) -> list[SpanRecord]:
        return [s for s in self.snapshot() if s.trace_id == trace_id]


_collector = SpanCollector()


def get_collector() -> SpanCollector:
    return _collector


class TraceContext:
    """One span; use as a context manager to time + activate it."""

    def __init__(self, name: str, *, trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None, sampled: bool = True,
                 baggage: Optional[dict] = None):
        self.name = name
        self.trace_id = trace_id or _new_trace_id()
        self.span_id = _new_span_id()
        self.parent_span_id = parent_span_id
        self.sampled = sampled
        self.baggage: dict[str, Any] = dict(baggage or {})
        self.tags: dict[str, Any] = {}
        self.start_time = 0.0
        self._token = None

    # -- structure -------------------------------------------------------------

    def create_child(self, name: str) -> "TraceContext":
        return TraceContext(name, trace_id=self.trace_id,
                            parent_span_id=self.span_id,
                            sampled=self.sampled, baggage=self.baggage)

    def add_tag(self, key: str, value: Any) -> None:
        self.tags[key] = value

    def set_baggage(self, key: str, value: Any) -> None:
        self.baggage[key] = value

    # -- activation ------------------------------------------------------------

    def __enter__(self) -> "TraceContext":
        self.start_time = time.time()
        self._t0 = time.perf_counter()
        self._token = _current.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        _current.reset(self._token)
        if self.sampled:
            if exc is not None and "error" not in self.tags:
                self.tags["error"] = repr(exc)[:200]
            _collector.add(SpanRecord(self, time.perf_counter() - self._t0))
        return False

    # -- wire ------------------------------------------------------------------

    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "sampled": self.sampled, "baggage": self.baggage}

    @classmethod
    def from_wire(cls, wire: Optional[dict], name: str) -> "TraceContext":
        if not wire:
            return cls(name)
        def _text(v):
            return v.decode() if isinstance(v, bytes) else v
        wire = {(_text(k)): v for k, v in wire.items()}
        return cls(name, trace_id=_text(wire.get("trace_id")),
                   parent_span_id=_text(wire.get("span_id")),
                   sampled=bool(wire.get("sampled", True)),
                   baggage={_text(k): (_text(v) if isinstance(v, bytes)
                                       else v)
                            for k, v in (wire.get("baggage") or {}).items()})


class _NullSpan:
    """The no-op span: what an untraced (or sampled-out) site gets.
    Activation touches NOTHING — not even the contextvar — so nesting
    under it still sees the real ambient context (or None)."""

    __slots__ = ()
    trace_id = None
    span_id = None
    parent_span_id = None
    name = "<null>"
    sampled = False
    tags: dict = {}
    baggage: dict = {}

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def add_tag(self, key, value) -> None:
        pass

    def set_baggage(self, key, value) -> None:
        pass

    def create_child(self, name) -> "_NullSpan":
        return self

    def to_wire(self) -> None:
        return None


NULL_SPAN = _NullSpan()


def current_trace() -> Optional[TraceContext]:
    return _current.get()


def start_span(name: str, **tags) -> "TraceContext | _NullSpan":
    """Child of the ambient context, or a (sampled) fresh root."""
    parent = _current.get()
    if parent is not None:
        if not parent.sampled:
            return NULL_SPAN
        ctx = parent.create_child(name)
        ctx.tags.update(tags)
        return ctx
    if not _ENABLED or (_SAMPLE_RATE < 1.0 and
                        random.random() >= _SAMPLE_RATE):
        return NULL_SPAN
    ctx = TraceContext(name)
    ctx.tags.update(tags)
    return ctx


def child_span(name: str, **tags) -> "TraceContext | _NullSpan":
    """INTERIOR span site: records only under a live sampled trace.
    The no-trace fast path is one contextvar read + a singleton return."""
    parent = _current.get()
    if parent is None or not parent.sampled:
        return NULL_SPAN
    ctx = parent.create_child(name)
    if tags:
        ctx.tags.update(tags)
    return ctx


def start_query_span(name: str, force: bool = False,
                     trace_id: Optional[str] = None,
                     **tags) -> "TraceContext | _NullSpan":
    """ENTRY-point span: continue the ambient trace when one exists
    (an RPC handler running under the caller's propagated context),
    else root a new trace subject to `enabled` + `sample_rate`.
    `force=True` (explain_analyze, explicit X-YT-Trace-Id) always
    samples; `trace_id` pins the root's trace id."""
    parent = _current.get()
    if parent is not None:
        if not (parent.sampled or force):
            return NULL_SPAN
        ctx = TraceContext(name, trace_id=parent.trace_id,
                           parent_span_id=parent.span_id,
                           sampled=True, baggage=parent.baggage)
        ctx.tags.update(tags)
        return ctx
    if not force and (not _ENABLED or (_SAMPLE_RATE < 1.0 and
                                       random.random() >= _SAMPLE_RATE)):
        return NULL_SPAN
    ctx = TraceContext(name, trace_id=trace_id)
    ctx.tags.update(tags)
    return ctx


# -- flight-recorder views -----------------------------------------------------


def trace_summaries(limit: int = 64) -> list[dict]:
    """Recent traces, newest first: one row per trace id with its root
    span name, start time, total span count, and root duration (the
    monitoring `/traces` listing)."""
    spans = _collector.snapshot()
    by_trace: dict[str, list[SpanRecord]] = {}
    for span in spans:
        by_trace.setdefault(span.trace_id, []).append(span)
    out = []
    for trace_id, group in by_trace.items():
        span_ids = {s.span_id for s in group}
        roots = [s for s in group
                 if s.parent_span_id is None or
                 s.parent_span_id not in span_ids]
        root = max(roots, key=lambda s: s.duration) if roots else group[0]
        out.append({"trace_id": trace_id, "root": root.name,
                    "start": root.start, "duration": root.duration,
                    "spans": len(group),
                    "last_seq": max(s.seq for s in group)})
    out.sort(key=lambda r: r["last_seq"], reverse=True)
    for row in out:
        del row["last_seq"]
    return out[:limit]


def _build_tree(spans: "list[SpanRecord]") -> list[dict]:
    nodes = {s.span_id: {**s.to_dict(), "children": []} for s in spans}
    roots = []
    for span in spans:
        node = nodes[span.span_id]
        parent = nodes.get(span.parent_span_id) \
            if span.parent_span_id else None
        if parent is not None:
            parent["children"].append(node)
        else:
            roots.append(node)
    def _sort(items):
        items.sort(key=lambda n: n["start"])
        for item in items:
            _sort(item["children"])
    _sort(roots)
    return roots


def span_tree(trace_id: str) -> list[dict]:
    """Nested span tree of one trace (children under `children`, sorted
    by start time); [] when the trace is unknown/evicted."""
    spans = _collector.find(trace_id)
    return _build_tree(spans) if spans else []


def all_span_trees() -> dict:
    """{trace_id: span tree} for EVERY trace retained in the ring, built
    in one snapshot pass (the orchid `/tracing/traces` producer — same
    retention as the monitoring `/traces/<id>` endpoint, instead of the
    64-most-recent window with a ring scan per trace)."""
    by_trace: dict[str, list[SpanRecord]] = {}
    for span in _collector.snapshot():
        by_trace.setdefault(span.trace_id, []).append(span)
    return {tid: _build_tree(group) for tid, group in by_trace.items()}
