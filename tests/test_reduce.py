"""Reduce + MapReduce controllers.

Ref model: CreateReduceController (controller_agent/controllers/
sorted_controller.cpp:1451) — key-guarantee job slicing over sorted
input; CreateMapReduceController (sort_controller.cpp:5029) — partition
→ hash shuffle → per-partition sort + reduce (partition_sort_job.cpp:43).
"""

import pytest

from ytsaurus_tpu.client import connect
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.operations.reduce_op import (
    iter_groups,
    key_aligned_ranges,
    partition_rows,
    stable_key_hash,
)


@pytest.fixture
def client(tmp_path):
    return connect(str(tmp_path))


# -- slicing / grouping units --------------------------------------------------


def test_key_aligned_ranges_cut_only_on_key_change():
    keys = [(1,), (1,), (2,), (2,), (3,)]
    assert key_aligned_ranges(keys, 2) == [(0, 2), (2, 4), (4, 5)]
    assert key_aligned_ranges(keys, 3) == [(0, 4), (4, 5)]
    assert key_aligned_ranges(keys, 100) == [(0, 5)]
    assert key_aligned_ranges([], 10) == []


def test_key_aligned_ranges_oversized_group_stays_whole():
    keys = [(7,)] * 10 + [(8,)]
    ranges = key_aligned_ranges(keys, 3)
    # The 10-row key group cannot split; it fills one range alone.
    assert ranges == [(0, 10), (10, 11)]


def test_iter_groups():
    rows = [{"k": 1, "v": 10}, {"k": 1, "v": 11}, {"k": 2, "v": 20}]
    groups = list(iter_groups(rows, ["k"]))
    assert [g[0] for g in groups] == [{"k": 1}, {"k": 2}]
    assert [len(g[1]) for g in groups] == [2, 1]
    assert list(iter_groups([], ["k"])) == []


def test_partition_rows_stable_and_complete():
    rows = [{"k": i % 7, "v": i} for i in range(100)]
    parts = partition_rows(rows, ["k"], 4)
    assert sum(len(p) for p in parts) == 100
    # Same key never lands in two partitions.
    for k in range(7):
        hit = [i for i, p in enumerate(parts)
               if any(r["k"] == k for r in p)]
        assert len(hit) == 1
    # Hash is process-stable (documented values guard against drift —
    # revival re-partitions in a fresh process and must agree).
    assert stable_key_hash((1,)) == stable_key_hash((1,))
    assert stable_key_hash((b"a", 1)) != stable_key_hash((b"a", 2))


# -- sorted reduce -------------------------------------------------------------


def _oracle_counts(rows, key="k"):
    out = {}
    for r in rows:
        out[r[key]] = out.get(r[key], 0) + 1
    return out


def test_reduce_python_counts_per_key(client):
    rows = [{"k": i % 13, "v": i} for i in range(997)]
    client.write_table("//in", rows)
    client.run_sort("//in", "//sorted", sort_by=["k"])

    def reducer(key, group):
        return [{"k": key["k"], "n": len(group)}]

    op = client.run_reduce(reducer, "//sorted", "//out", reduce_by="k",
                           job_count=5)
    assert op.state == "completed"
    out = {r["k"]: r["n"] for r in client.read_table("//out")}
    assert out == _oracle_counts(rows)


def test_reduce_key_guarantee_with_tiny_jobs(client):
    """Even with rows_per_job=1, each key group reaches ONE reducer call
    whole (the reference's key guarantee)."""
    rows = [{"k": i % 5, "v": i} for i in range(50)]
    client.write_table("//in", rows)
    client.run_sort("//in", "//sorted", sort_by=["k"])

    def reducer(key, group):
        return [{"k": key["k"], "n": len(group)}]

    op = client.run_reduce(reducer, "//sorted", "//out", reduce_by="k",
                           rows_per_job=1)
    assert op.state == "completed"
    assert op.result["jobs"] == 5          # one aligned stripe per key
    out = {r["k"]: r["n"] for r in client.read_table("//out")}
    assert out == {k: 10 for k in range(5)}


def test_reduce_rejects_unsorted_input(client):
    client.write_table("//in", [{"k": 3}, {"k": 1}])
    with pytest.raises(YtError) as ei:
        client.run_reduce(lambda key, g: [], "//in", "//out", reduce_by="k")
    assert ei.value.find(EErrorCode.SortOrderViolation) is not None


def test_reduce_rejects_wrong_sort_prefix(client):
    client.write_table("//in", [{"k": 1, "s": 2}])
    client.run_sort("//in", "//sorted", sort_by=["s", "k"])
    with pytest.raises(YtError):
        client.run_reduce(lambda key, g: [], "//sorted", "//out",
                          reduce_by="k")


def test_reduce_sort_by_must_extend_reduce_by(client):
    client.write_table("//in", [{"k": 1, "s": 2}])
    client.run_sort("//in", "//sorted", sort_by=["k", "s"])
    with pytest.raises(YtError):
        client.run_reduce(lambda key, g: [], "//sorted", "//out",
                          reduce_by="k", sort_by=["s"])


def test_reduce_secondary_sort_order_within_group(client):
    """sort_by beyond reduce_by orders rows INSIDE each group (ref
    reduce sort_by semantics)."""
    rows = [{"k": i % 3, "s": 100 - i} for i in range(30)]
    client.write_table("//in", rows)
    client.run_sort("//in", "//sorted", sort_by=["k", "s"])

    def reducer(key, group):
        order = [r["s"] for r in group]
        return [{"k": key["k"], "ordered": int(order == sorted(order))}]

    op = client.run_reduce(reducer, "//sorted", "//out", reduce_by="k",
                           sort_by=["k", "s"])
    assert op.state == "completed"
    assert all(r["ordered"] == 1 for r in client.read_table("//out"))


def test_reduce_multiple_sorted_inputs_merge(client):
    a = [{"k": i, "src": 1} for i in range(0, 20, 2)]
    b = [{"k": i, "src": 2} for i in range(0, 20, 3)]
    client.write_table("//a", a)
    client.run_sort("//a", "//sa", sort_by=["k"])
    client.write_table("//b", b)
    client.run_sort("//b", "//sb", sort_by=["k"])

    def reducer(key, group):
        return [{"k": key["k"], "n": len(group)}]

    op = client.run_reduce(reducer, ["//sa", "//sb"], "//out",
                           reduce_by="k")
    assert op.state == "completed"
    oracle = _oracle_counts(a + b)
    assert {r["k"]: r["n"] for r in client.read_table("//out")} == oracle


def test_reduce_shell_command_streams_sorted_groups(client):
    client.write_table("//in", [{"k": i % 4} for i in range(40)])
    client.run_sort("//in", "//sorted", sort_by=["k"])
    op = client.run_reduce("cat", "//sorted", "//out", reduce_by="k",
                           job_count=3)
    assert op.state == "completed"
    out = [r["k"] for r in client.read_table("//out")]
    assert out == sorted(out)          # stripes concatenate in key order
    assert len(out) == 40


def test_reduce_empty_input(client):
    from ytsaurus_tpu.schema import TableSchema
    client.write_table("//in", [],
                       schema=TableSchema.make([("k", "int64")]))
    client.run_sort("//in", "//sorted", sort_by=["k"])
    op = client.run_reduce(lambda key, g: [{"boom": 1}], "//sorted",
                           "//out", reduce_by="k")
    assert op.state == "completed"
    assert op.result["rows"] == 0
    assert client.read_table("//out") == []


# -- map_reduce ----------------------------------------------------------------


def test_map_reduce_word_count(client):
    docs = [{"text": f"w{i % 17} w{i % 5}"} for i in range(300)]
    client.write_table("//docs", docs)

    def mapper(rows):
        for r in rows:
            text = r["text"]
            if isinstance(text, bytes):
                text = text.decode()
            for w in text.split():
                yield {"word": w, "one": 1}

    def reducer(key, group):
        return [{"word": key["word"], "count": sum(r["one"]
                                                   for r in group)}]

    op = client.run_map_reduce(mapper, reducer, "//docs", "//counts",
                               reduce_by="word", partition_count=4)
    assert op.state == "completed"
    assert op.result["partitions"] == 4
    oracle: dict = {}
    for d in docs:
        for w in d["text"].split():
            oracle[w] = oracle.get(w, 0) + 1
    got = {r["word"].decode(): r["count"]
           for r in client.read_table("//counts")}
    assert got == oracle


def test_map_reduce_identity_mapper(client):
    rows = [{"k": i % 6, "v": i} for i in range(120)]
    client.write_table("//in", rows)

    def reducer(key, group):
        return [{"k": key["k"], "total": sum(r["v"] for r in group)}]

    op = client.run_map_reduce(None, reducer, "//in", "//out",
                               reduce_by="k", partition_count=3)
    assert op.state == "completed"
    oracle: dict = {}
    for r in rows:
        oracle[r["k"]] = oracle.get(r["k"], 0) + r["v"]
    assert {r["k"]: r["total"] for r in client.read_table("//out")} == \
        oracle


def test_map_reduce_commands_identity(client):
    rows = [{"k": i % 3, "v": i} for i in range(30)]
    client.write_table("//in", rows)
    op = client.run_map_reduce("cat", "cat", "//in", "//out",
                               reduce_by="k", partition_count=2)
    assert op.state == "completed"
    out = client.read_table("//out")
    assert sorted((r["k"], r["v"]) for r in out) == \
        sorted((r["k"], r["v"]) for r in rows)
    # Each partition's stream is key-sorted before reduce.
    assert op.result["partitions"] == 2


def test_map_reduce_secondary_sort(client):
    rows = [{"k": i % 3, "s": 100 - i} for i in range(60)]
    client.write_table("//in", rows)

    def reducer(key, group):
        order = [r["s"] for r in group]
        return [{"k": key["k"], "ordered": int(order == sorted(order))}]

    op = client.run_map_reduce(None, reducer, "//in", "//out",
                               reduce_by="k", sort_by=["k", "s"],
                               partition_count=2)
    assert op.state == "completed"
    assert all(r["ordered"] == 1 for r in client.read_table("//out"))


# -- revival -------------------------------------------------------------------


def test_reduce_revival_skips_completed_ranges(tmp_path):
    """Forge a crashed reduce: snapshot holds stripe 0's output; revival
    runs only stripe 1 (plan-matched on chunk ids + ranges)."""
    client = connect(str(tmp_path))
    client.write_table("//in", [{"k": i // 2} for i in range(8)])
    client.run_sort("//in", "//sorted", sort_by=["k"])
    spec = {"command": "cat", "input_table_path": "//sorted",
            "output_table_path": "//out", "reduce_by": ["k"],
            "rows_per_job": 4, "format": "json"}
    from ytsaurus_tpu.operations.scheduler import _Snapshot, _clean_spec
    op_id = "feedc0de"
    doc = f"//sys/operations/{op_id}"
    client.create("document", doc, recursive=True)
    client.set(doc + "/@operation_type", "reduce")
    client.set(doc + "/@spec", _clean_spec(spec))
    client.set(doc + "/@state", "running")
    snap = _Snapshot(client, op_id, plan={
        "kind": "reduce",
        "input_chunk_ids": list(client.get("//sorted/@chunk_ids")),
        "ranges": [[0, 4], [4, 8]], "command": "cat"})
    snap.record(0, [{"k": 0, "marker": "snap"}, {"k": 1, "marker": "snap"}])
    revived = client.scheduler.revive_operations()
    assert [op.id for op in revived] == [op_id]
    op = revived[0]
    assert op.state == "completed"
    assert op.result["revived_jobs"] == 1
    rows = client.read_table("//out")
    markers = [r.get("marker") for r in rows]
    assert markers[:2] == [b"snap", b"snap"]
    assert [r["k"] for r in rows[2:]] == [2, 2, 3, 3]
    assert not client.exists(doc + "/@snapshot")


def test_map_reduce_revival_skips_completed_partitions(tmp_path):
    """Forge a crashed map_reduce with partition 0 complete: the map
    phase re-runs (deterministic) and only partition 1 reduces."""
    client = connect(str(tmp_path))
    rows = [{"k": i % 4, "v": i} for i in range(20)]
    client.write_table("//in", rows)
    spec = {"reduce_command": "cat", "input_table_path": "//in",
            "output_table_path": "//out", "reduce_by": ["k"],
            "partition_count": 2, "format": "json"}
    from ytsaurus_tpu.operations.scheduler import _Snapshot, _clean_spec
    op_id = "0ddba11"
    doc = f"//sys/operations/{op_id}"
    client.create("document", doc, recursive=True)
    client.set(doc + "/@operation_type", "map_reduce")
    client.set(doc + "/@spec", _clean_spec(spec))
    client.set(doc + "/@state", "running")
    snap = _Snapshot(client, op_id, plan={
        "kind": "map_reduce",
        "input_chunk_ids": list(client.get("//in/@chunk_ids")),
        "partition_count": 2, "map_command": None,
        "reduce_command": "cat"})
    snap.record(0, [{"marker": "p0"}])
    revived = client.scheduler.revive_operations()
    op = revived[0]
    assert op.state == "completed"
    assert op.result["revived_jobs"] == 1
    out = client.read_table("//out")
    # Partition 0 came from the snapshot; partition 1 re-computed.
    expected_p1 = partition_rows(
        [dict(r) for r in rows], ["k"], 2)[1]
    got_markers = [r for r in out if r.get("marker") == b"p0"]
    assert len(got_markers) == 1
    rest = [(r["k"], r["v"]) for r in out if "marker" not in r or
            r.get("marker") is None]
    assert sorted(rest) == sorted((r["k"], r["v"]) for r in expected_p1)
