"""Inferred guard-discipline pass (ISSUE 15 tentpole): fixture
positives/negatives for the annotation-free inference (lock discovery,
entry-context propagation, thread-entry roots, init-escape, the
`*_locked` convention), the atomicity (check-then-act) lint, the
annotation-drift cross-check, and the reconciliation-graph machinery
the runtime sanitizer's dynamic⊆static gate runs against."""

import textwrap

from tools import analyze
from tools.analyze import guard_inference
from tools.analyze.core import SourceFile


def fixture(rel, source):
    return SourceFile(rel, textwrap.dedent(source))


def run(source, rel="ytsaurus_tpu/fix.py"):
    return guard_inference.run([fixture(rel, source)])


def rules_of(findings):
    return sorted(f.rule for f in findings)


# --- guard inference: the annotation-free core --------------------------------


def test_unguarded_write_flagged_without_annotation():
    findings = run("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._items[k] = v

            def wipe(self):
                self._items = {}
    """)
    assert rules_of(findings) == ["guard-inference"]
    assert findings[0].line == 14
    assert "_items" in findings[0].message


def test_mutator_call_counts_as_write():
    findings = run("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._items[k] = v

            def note(self, v):
                self._items.setdefault(v, []).append(v)
    """)
    assert rules_of(findings) == ["guard-inference"]
    assert "setdefault" in findings[0].message


def test_no_lock_no_findings():
    assert run("""
        class Plain:
            def __init__(self):
                self._n = 0

            def bump(self):
                self._n += 1
    """) == []


def test_unguarded_field_next_to_guarded_one_ok():
    # _stats is never written under the lock: no evidence, no findings —
    # inference never guesses a guard the code doesn't establish.
    assert run("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}
                self._stats = 0

            def put(self, k, v):
                with self._lock:
                    self._items[k] = v

            def bump(self):
                self._stats += 1
    """) == []


# --- entry-context propagation ------------------------------------------------


def test_private_helper_called_under_lock_is_clean():
    assert run("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._put_inner(k, v)

            def _put_inner(self, k, v):
                self._items[k] = v
    """) == []


def test_helper_with_one_unlocked_call_site_flagged():
    # The intersection over call sites is empty: _put_inner cannot
    # assume the lock, so its write is a finding.
    findings = run("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._put_inner(k, v)

            def put_fast(self, k, v):
                self._put_inner(k, v)

            def _put_inner(self, k, v):
                self._items[k] = v
    """)
    assert rules_of(findings) == ["guard-inference"]
    assert "_put_inner" in findings[0].message


def test_thread_entry_root_cannot_assume_locks():
    # _run is referenced as a VALUE (Thread target): even though its
    # only textual reference sits inside the class, it runs on a fresh
    # thread with no locks held.
    findings = run("""
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._thread = threading.Thread(target=self._run)
                self._thread.start()

            def bump(self):
                with self._lock:
                    self._n += 1

            def _run(self):
                while True:
                    self._n += 1
    """)
    assert rules_of(findings) == ["guard-inference"]
    assert "_run" in findings[0].message


def test_executor_submit_is_a_thread_entry_root():
    findings = run("""
        import threading
        from concurrent.futures import ThreadPoolExecutor

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._pool = ThreadPoolExecutor(2)
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

            def kick(self):
                self._pool.submit(self._work)

            def _work(self):
                self._n += 1
    """)
    assert rules_of(findings) == ["guard-inference"]
    assert "_work" in findings[0].message


def test_stored_callback_is_a_thread_entry_root():
    # Bound-method capture via plain ASSIGNMENT (self._cb = self._run)
    # escapes too — the callback can run on any thread later, so _run
    # cannot inherit its direct call sites' locks.
    findings = run("""
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._cb = self._run

            def bump(self):
                with self._lock:
                    self._n += 1
                    self._run()

            def _run(self):
                self._n += 1
    """)
    assert rules_of(findings) == ["guard-inference"]
    assert "_run" in findings[0].message


def test_locked_suffix_convention_assumes_caller_lock():
    assert run("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._items[k] = v

            def evict_locked(self):
                self._items.clear()
    """) == []


# --- __init__ / pre-publication escape ----------------------------------------


def test_init_writes_before_escape_are_exempt():
    assert run("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}
                self._n = 0

            def put(self, k, v):
                with self._lock:
                    self._items[k] = v
                    self._n += 1
    """) == []


def test_init_write_after_thread_start_flagged():
    # The thread is LIVE: the post-start write races with _run.
    findings = run("""
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                threading.Thread(target=self._run).start()
                self._n = 1

            def _run(self):
                with self._lock:
                    self._n += 1
    """)
    assert any(f.rule == "guard-inference" and f.line == 9
               for f in findings), [f.format() for f in findings]


def test_reading_self_attrs_in_init_is_not_an_escape():
    # `tuple(self.DEFAULT)` and `len(self._channels)` read fields —
    # they do not publish the object.
    assert run("""
        import threading

        class Box:
            DEFAULT = (1, 2, 3)

            def __init__(self, bounds=None):
                self.bounds = tuple(bounds or self.DEFAULT)
                self._lock = threading.Lock()
                self._n = len(self.bounds)

            def bump(self):
                with self._lock:
                    self._n += 1
    """) == []


# --- guard-read ---------------------------------------------------------------


def test_unlocked_read_in_locking_method_flagged():
    findings = run("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._items[k] = v

            def peek_and_clear(self):
                with self._lock:
                    self._items.clear()
                return len(self._items)
    """)
    assert rules_of(findings) == ["guard-read"]
    assert findings[0].severity == "warning"


def test_double_checked_lazy_init_read_is_exempt():
    assert run("""
        import threading

        class Lazy:
            def __init__(self):
                self._lock = threading.Lock()
                self._obj = None

            def get(self):
                if self._obj is None:
                    with self._lock:
                        if self._obj is None:
                            self._obj = object()
                return self._obj
    """) == []


def test_lock_free_facade_reads_not_flagged():
    # size() takes no locks and inherits no entry context: lock-free
    # reads from a non-locking method are the sanctioned snapshot idiom.
    assert run("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._items[k] = v

            def size(self):
                return len(self._items)
    """) == []


# --- atomicity (check-then-act) -----------------------------------------------


def test_check_then_act_across_regions_flagged():
    findings = run("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump_if_small(self):
                with self._lock:
                    n = self._n
                if n < 10:
                    with self._lock:
                        self._n = n + 1
    """)
    assert rules_of(findings) == ["atomicity"]
    assert "check-then-act" in findings[0].message


def test_single_region_read_modify_write_ok():
    assert run("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump_if_small(self):
                with self._lock:
                    n = self._n
                    if n < 10:
                        self._n = n + 1
    """) == []


def test_double_checked_second_region_reread_exempt():
    assert run("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._cache = {}

            def get_or_make(self, k):
                with self._lock:
                    hit = self._cache.get(k)
                if hit is not None:
                    return hit
                value = object()
                with self._lock:
                    return self._cache.setdefault(k, value)
    """) == []


def test_reassignment_between_regions_kills_taint():
    # `state` is rebuilt from a non-guarded source before the second
    # region: the write is not acting on the stale read.
    assert run("""
        import threading

        class Conn:
            def __init__(self):
                self._lock = threading.Lock()
                self._conn = None

            def connect(self):
                with self._lock:
                    state = self._conn
                if state is None:
                    state = object()
                    with self._lock:
                        self._conn = state
                return state
    """) == []


# --- annotation drift ---------------------------------------------------------


def test_drift_contradicted_annotation_flagged():
    findings = run("""
        import threading

        class Box:
            def __init__(self):
                self._a = threading.Lock()   # guards: _x
                self._b = threading.Lock()
                self._x = 0

            def bump(self):
                with self._b:
                    self._x += 1
    """)
    assert "guard-drift" in rules_of(findings)
    drift = next(f for f in findings if f.rule == "guard-drift")
    assert "'_b'" in drift.message


def test_drift_stale_annotation_flagged():
    findings = run("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()   # guards: _x, _y
                self._x = 0
                self._y = 0

            def bump(self):
                with self._lock:
                    self._y += 1
    """)
    assert rules_of(findings) == ["guard-drift"]
    assert "stale" in findings[0].message and "_x" in findings[0].message


def test_consistent_annotation_no_drift():
    assert run("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()   # guards: _x
                self._x = 0

            def bump(self):
                with self._lock:
                    self._x += 1
    """) == []


# --- waivers ------------------------------------------------------------------


def test_waiver_with_reason_suppresses_and_bare_waiver_flagged():
    findings = analyze.run_passes([fixture("ytsaurus_tpu/fix.py", """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

            def reset(self):
                # analyze: allow(guard-inference): test-scoped reset, callers quiesce first
                self._n = 0

            def reset2(self):
                self._n = 0   # analyze: allow(guard-inference)
    """)], only=["guards"])
    assert rules_of(findings) == ["guard-inference", "waiver-reason"]


# --- sanitizer registration shapes --------------------------------------------


def test_register_lock_sites_are_inferred_locks_with_site_names():
    f = fixture("ytsaurus_tpu/utils/fix_reg.py", """
        import threading
        from ytsaurus_tpu.utils import sanitizers

        # guards: _GLOBAL
        _LOCK = sanitizers.register_lock("fix._LOCK", hot=False)
        _GLOBAL = None

        class Box:
            def __init__(self):
                self._lock = sanitizers.register_lock("fix.Box._lock")
                self._cond = sanitizers.register_condition(
                    "fix.Box._cond")
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._items[k] = v

            def wipe(self):
                self._items = {}
    """)
    locks = guard_inference.collect_inferred_locks(f)
    by_attr = {l.attr: l for l in locks}
    assert by_attr["_LOCK"].site_name == "fix._LOCK"
    assert by_attr["_lock"].site_name == "fix.Box._lock"
    assert by_attr["_cond"].site_name == "fix.Box._cond"
    site_map = guard_inference.registered_site_map([f])
    assert site_map["fix.Box._lock"] == \
        "ytsaurus_tpu/utils/fix_reg.py::Box._lock"
    # ... and the registered lock still drives inference:
    findings = guard_inference.run([f])
    assert rules_of(findings) == ["guard-inference"]


# --- reconciliation graph -----------------------------------------------------


def test_reconciliation_graph_resolves_cross_file_method_calls():
    """The superset graph's aggressive closure: holding a lock in one
    file while calling a method/constructor/function defined in another
    lock-bearing file produces the edge the runtime sanitizer will
    observe."""
    prof = fixture("ytsaurus_tpu/utils/fix_prof.py", """
        import threading
        from ytsaurus_tpu.utils import sanitizers

        class Registry:
            def __init__(self):
                self._lock = sanitizers.register_lock(
                    "fix_prof.Registry._lock")
                self._sensors = {}

            def fetch(self, name):
                with self._lock:
                    return self._sensors.setdefault(name, object())

        class View:
            def __init__(self, registry):
                self.registry = registry

            def fetch_sensor(self, name):
                return self.registry.fetch(name)
    """)
    user = fixture("ytsaurus_tpu/query/fix_user.py", """
        import threading
        from ytsaurus_tpu.utils import sanitizers

        class Log:
            def __init__(self, view):
                self._lock = sanitizers.register_lock(
                    "fix_user.Log._lock")
                self._view = view
                self._records = []

            def fold(self, record):
                with self._lock:
                    self._records.append(record)
                    self._view.fetch_sensor("records")
    """)
    graph = guard_inference.reconciliation_graph([prof, user])
    assert "ytsaurus_tpu/query/fix_user.py::Log._lock" in graph["locks"]
    assert any(
        a == "ytsaurus_tpu/query/fix_user.py::Log._lock" and
        b == "ytsaurus_tpu/utils/fix_prof.py::Registry._lock"
        for a, b, _site in graph["edges"]), graph["edges"]
    # site_map round-trips both registrations
    assert graph["site_map"]["fix_user.Log._lock"] == \
        "ytsaurus_tpu/query/fix_user.py::Log._lock"


def test_reconciliation_graph_resolves_constructor_calls():
    maker = fixture("ytsaurus_tpu/utils/fix_ctor.py", """
        import threading
        from ytsaurus_tpu.utils import sanitizers

        _LOCK = sanitizers.register_lock("fix_ctor._LOCK", hot=False)
        _GLOBAL = None

        class Widget:
            def __init__(self):
                self._lock = sanitizers.register_lock(
                    "fix_ctor.Widget._lock")
                with self._lock:
                    self._n = 0

        def get_global():
            global _GLOBAL
            with _LOCK:
                if _GLOBAL is None:
                    _GLOBAL = Widget()
                return _GLOBAL
    """)
    graph = guard_inference.reconciliation_graph([maker])
    assert any(
        a.endswith("::_LOCK") and b.endswith("::Widget._lock")
        for a, b, _site in graph["edges"]), graph["edges"]
