"""Device mesh helpers: the framework's "cluster topology".

Where the reference moves rowsets over a TCP bus between tablet nodes
(core/bus/tcp), this framework places table shards on a jax device mesh and
moves data with XLA collectives over ICI (psum / all_gather / all_to_all);
DCN handles cross-slice when meshes span hosts.  SURVEY.md §5 "Distributed
communication backend" describes the mapping.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None,
              axis: str = SHARD_AXIS) -> Mesh:
    """A 1-D mesh over table shards (tablet analog)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"Need {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    # analyze: allow(host-sync): device HANDLES, not array data — no transfer
    return Mesh(np.asarray(devices).reshape(len(devices)), (axis,))


def shard_spec(mesh: Mesh, axis: str = SHARD_AXIS) -> NamedSharding:
    return NamedSharding(mesh, P(axis))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
