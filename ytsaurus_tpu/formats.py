"""Row formats: serialize/parse rowsets as yson / json / dsv / schemaful_dsv.

Ref: yt/yt/client/formats + library/formats — format objects convert between
wire bytes and rows for table IO and job IO.  The same four format names are
accepted by `YtClient.read_table(..., format=)` / `write_table(..., format=)`.
"""

from __future__ import annotations

import json
from typing import Optional, Sequence

from ytsaurus_tpu import yson
from ytsaurus_tpu.errors import EErrorCode, YtError


def _to_jsonable(value):
    if isinstance(value, bytes):
        return value.decode("utf-8", "replace")
    if isinstance(value, dict):
        return {k: _to_jsonable(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_to_jsonable(v) for v in value]
    return value


def _dsv_escape(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\t", "\\t") \
        .replace("\n", "\\n").replace("=", "\\=")


def _dsv_unescape(text: str) -> str:
    out = []
    i = 0
    while i < len(text):
        c = text[i]
        if c == "\\" and i + 1 < len(text):
            nxt = text[i + 1]
            out.append({"t": "\t", "n": "\n", "\\": "\\", "=": "="}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _dsv_split(text: str, sep: str) -> list[str]:
    """Split on unescaped separators (backslash escapes survive)."""
    parts = []
    buf = []
    i = 0
    while i < len(text):
        c = text[i]
        if c == "\\" and i + 1 < len(text):
            buf.append(text[i:i + 2])
            i += 2
        elif c == sep:
            parts.append("".join(buf))
            buf = []
            i += 1
        else:
            buf.append(c)
            i += 1
    parts.append("".join(buf))
    return parts


def _dsv_split_kv(field: str) -> tuple[str, str]:
    """Split key=value on the first UNESCAPED '='."""
    i = 0
    while i < len(field):
        if field[i] == "\\":
            i += 2
        elif field[i] == "=":
            return field[:i], field[i + 1:]
        else:
            i += 1
    return field, ""


def _value_to_text(value) -> str:
    if value is None:
        return ""
    if isinstance(value, bytes):
        return value.decode("utf-8", "replace")
    if isinstance(value, bool):
        return "true" if value else "false"
    return str(value)


def dumps_rows(rows: Sequence[dict], format: str = "yson",
               columns: Optional[Sequence[str]] = None) -> bytes:
    """Serialize rows in the named format (list fragment semantics)."""
    if format == "yson":
        return b";".join(yson.dumps(row) for row in rows) + \
            (b";" if rows else b"")
    if format == "json":
        return b"\n".join(
            json.dumps(_to_jsonable(row), sort_keys=True).encode()
            for row in rows) + (b"\n" if rows else b"")
    if format == "dsv":
        lines = []
        for row in rows:
            fields = [f"{_dsv_escape(k)}={_dsv_escape(_value_to_text(v))}"
                      for k, v in row.items() if v is not None]
            lines.append("\t".join(fields))
        return ("\n".join(lines) + ("\n" if rows else "")).encode()
    if format == "schemaful_dsv":
        if not columns:
            raise YtError("schemaful_dsv requires a column list",
                          code=EErrorCode.QueryUnsupported)
        lines = []
        for row in rows:
            lines.append("\t".join(
                _dsv_escape(_value_to_text(row.get(c))) for c in columns))
        return ("\n".join(lines) + ("\n" if rows else "")).encode()
    raise YtError(f"Unknown format {format!r}",
                  code=EErrorCode.QueryUnsupported)


def loads_rows(data: bytes, format: str = "yson",
               columns: Optional[Sequence[str]] = None) -> list[dict]:
    """Parse rows from the named format."""
    if format == "yson":
        values = yson.loads(data, yson_type="list_fragment")
        for v in values:
            if not isinstance(v, dict):
                raise YtError(f"Expected map rows, got {type(v).__name__}")
        return values
    if format == "json":
        rows = []
        for line in data.splitlines():
            if line.strip():
                rows.append(json.loads(line))
        return rows
    if format == "dsv":
        rows = []
        for line in data.decode().splitlines():
            row = {}
            if line:
                for field in _dsv_split(line, "\t"):
                    if not field:
                        continue
                    key, value = _dsv_split_kv(field)
                    row[_dsv_unescape(key)] = _dsv_unescape(value)
            rows.append(row)
        return rows
    if format == "schemaful_dsv":
        if not columns:
            raise YtError("schemaful_dsv requires a column list",
                          code=EErrorCode.QueryUnsupported)
        rows = []
        for line in data.decode().splitlines():
            parts = line.split("\t")
            if len(parts) != len(columns):
                raise YtError(f"schemaful_dsv row width {len(parts)} != "
                              f"{len(columns)}")
            rows.append({c: _dsv_unescape(p)
                         for c, p in zip(columns, parts)})
        return rows
    raise YtError(f"Unknown format {format!r}",
                  code=EErrorCode.QueryUnsupported)


# --------------------------------------------------------------------- skiff
#
# Skiff (ref client/formats skiff + library/skiff): schema-driven binary row
# format — no per-value tags, so parsing is branch-light and rows are dense.
# Wire per row: uint16 table index, then each schema column in order:
#   optional columns: variant8 tag (0 = null, 1 = value) then the payload
#   int64/uint64:     8-byte LE
#   double:           8-byte LE IEEE
#   boolean:          1 byte
#   string:           uint32 LE length + bytes    ("string32")
#   any:              uint32 LE length + binary YSON ("yson32")

import struct as _struct

from ytsaurus_tpu.schema import EValueType as _EVT


def _skiff_required(col) -> bool:
    return bool(col.required)


def dumps_skiff(rows: Sequence[dict], schema) -> bytes:
    out = bytearray()
    for row in rows:
        out += _struct.pack("<H", 0)             # table index
        for col in schema:
            value = row.get(col.name)
            if not _skiff_required(col):
                if value is None:
                    out.append(0)
                    continue
                out.append(1)
            elif value is None:
                raise YtError(f"Required column {col.name!r} is null",
                              code=EErrorCode.QueryTypeError)
            ty = col.type
            if ty in (_EVT.int64, _EVT.uint64):
                out += _struct.pack("<q" if ty is _EVT.int64 else "<Q",
                                    int(value))
            elif ty is _EVT.double:
                out += _struct.pack("<d", float(value))
            elif ty is _EVT.boolean:
                out.append(1 if value else 0)
            elif ty is _EVT.string:
                data = value.encode() if isinstance(value, str) else \
                    bytes(value)
                out += _struct.pack("<I", len(data)) + data
            elif ty is _EVT.any:
                blob = yson.dumps(value, binary=True)
                out += _struct.pack("<I", len(blob)) + blob
            else:
                raise YtError(f"Skiff cannot encode type {ty.value!r}",
                              code=EErrorCode.QueryUnsupported)
    return bytes(out)


def loads_skiff(data: bytes, schema) -> list[dict]:
    rows: list[dict] = []
    pos = 0
    n = len(data)
    def need(at: int, count: int, what: str) -> None:
        if at + count > n:
            raise YtError(f"Truncated skiff {what} at offset {at}",
                          code=EErrorCode.ChunkFormatError)

    while pos < n:
        need(pos, 2, "row header")
        (_table_index,) = _struct.unpack_from("<H", data, pos)
        pos += 2
        row: dict = {}
        for col in schema:
            if not _skiff_required(col):
                need(pos, 1, f"variant tag of {col.name!r}")
                tag = data[pos]
                pos += 1
                if tag == 0:
                    row[col.name] = None
                    continue
                if tag != 1:
                    raise YtError(f"Bad skiff variant tag {tag}",
                                  code=EErrorCode.ChunkFormatError)
            ty = col.type
            if ty in (_EVT.int64, _EVT.uint64):
                need(pos, 8, col.name)
                (row[col.name],) = _struct.unpack_from(
                    "<q" if ty is _EVT.int64 else "<Q", data, pos)
                pos += 8
            elif ty is _EVT.double:
                need(pos, 8, col.name)
                (row[col.name],) = _struct.unpack_from("<d", data, pos)
                pos += 8
            elif ty is _EVT.boolean:
                need(pos, 1, col.name)
                row[col.name] = bool(data[pos])
                pos += 1
            elif ty in (_EVT.string, _EVT.any):
                need(pos, 4, f"length of {col.name!r}")
                (length,) = _struct.unpack_from("<I", data, pos)
                pos += 4
                need(pos, length, f"payload of {col.name!r}")
                payload = bytes(data[pos:pos + length])
                pos += length
                row[col.name] = payload if ty is _EVT.string \
                    else yson.loads(payload)
            else:
                raise YtError(f"Skiff cannot decode type {ty.value!r}",
                              code=EErrorCode.QueryUnsupported)
        rows.append(row)
    return rows
