"""Distributed sort (range partition → all-to-all → local sort) tests.

Ref behavior: sort_controller.cpp partition/sort tasks; here one shard_map
program per phase on the virtual 8-device mesh.
"""

import numpy as np
import pytest

from ytsaurus_tpu.chunks import ColumnarChunk

# Minutes of 8-device shard_map compiles: excluded from the tier-1 quick
# pass (-m 'not slow'); the all_to_all path stays tier-1-covered by the
# SPMD dual-checks in test_ql_corpus2.py / test_ql_window.py.
pytestmark = pytest.mark.slow
from ytsaurus_tpu.parallel.distributed import ShardedTable
from ytsaurus_tpu.parallel.mesh import make_mesh
from ytsaurus_tpu.parallel.shuffle import sort_table
from ytsaurus_tpu.schema import TableSchema


def _gather_rows(table: ShardedTable):
    """Materialize all rows shard-major (host)."""
    out = []
    cap = table.capacity
    data = {name: np.asarray(col.data) for name, col in table.columns.items()}
    valid = {name: np.asarray(col.valid) for name, col in table.columns.items()}
    for s in range(table.n_shards):
        for i in range(table.row_counts[s]):
            g = s * cap + i
            row = {}
            for name in data:
                row[name] = data[name][g].item() if valid[name][g] else None
            out.append(row)
    return out


SCHEMA = TableSchema.make([("k", "int64"), ("v", "double"), ("tag", "int64")])


def _make_table(mesh, rows_per_shard, seed=0, key_gen=None):
    rng = np.random.default_rng(seed)
    chunks = []
    for s in range(8):
        n = rows_per_shard
        keys = key_gen(rng, s, n) if key_gen else rng.integers(0, 10_000, n)
        chunks.append(ColumnarChunk.from_arrays(
            SCHEMA, {"k": keys, "v": rng.uniform(0, 1, n),
                     "tag": np.full(n, s)}))
    return ShardedTable.from_chunks(mesh, chunks)


def test_sort_random_data():
    mesh = make_mesh(8)
    table = _make_table(mesh, 500)
    before = sorted(r["k"] for r in _gather_rows(table))
    out = sort_table(table, ["k"])
    rows = _gather_rows(out)
    keys = [r["k"] for r in rows]
    assert keys == sorted(keys), "not globally sorted"
    assert keys == before, "rows lost or duplicated"
    assert out.schema.key_column_names == ["k"]


def test_sort_already_sorted_input_skew():
    # Shard i holds the i-th key range already — every row targets one
    # destination, the worst-case transfer skew (quota must adapt).
    mesh = make_mesh(8)
    table = _make_table(
        mesh, 300, key_gen=lambda rng, s, n: s * 1000 + rng.integers(0, 999, n))
    out = sort_table(table, ["k"])
    keys = [r["k"] for r in _gather_rows(out)]
    assert keys == sorted(keys)
    assert len(keys) == 8 * 300


def test_sort_descending():
    mesh = make_mesh(8)
    table = _make_table(mesh, 200)
    out = sort_table(table, ["k"], descending=True)
    keys = [r["k"] for r in _gather_rows(out)]
    assert keys == sorted(keys, reverse=True)


def test_sort_multi_key():
    mesh = make_mesh(8)
    rng = np.random.default_rng(3)
    chunks = []
    for s in range(8):
        n = 100
        chunks.append(ColumnarChunk.from_arrays(
            SCHEMA, {"k": rng.integers(0, 4, n),
                     "v": rng.uniform(0, 1, n),
                     "tag": rng.integers(0, 1000, n)}))
    table = ShardedTable.from_chunks(mesh, chunks)
    out = sort_table(table, ["k", "tag"])
    rows = _gather_rows(out)
    pairs = [(r["k"], r["tag"]) for r in rows]
    assert pairs == sorted(pairs)


def test_sort_with_nulls_first():
    mesh = make_mesh(8)
    schema = TableSchema.make([("k", "int64"), ("p", "int64")])
    chunks = []
    for s in range(8):
        rows = [(None if i % 5 == 0 else i + s * 100, s) for i in range(50)]
        chunks.append(ColumnarChunk.from_rows(schema, rows))
    table = ShardedTable.from_chunks(mesh, chunks)
    out = sort_table(table, ["k"])
    keys = [r["k"] for r in _gather_rows(out)]
    n_null = sum(1 for k in keys if k is None)
    assert n_null == 8 * 10
    assert all(k is None for k in keys[:n_null])
    non_null = keys[n_null:]
    assert non_null == sorted(non_null)


def test_sort_strings():
    mesh = make_mesh(8)
    schema = TableSchema.make([("s", "string"), ("i", "int64")])
    words = ["kiwi", "apple", "fig", "date", "grape", "lime", "pear", "plum"]
    chunks = []
    for s in range(8):
        rows = [(words[(s + i) % 8] + str(i % 3), i) for i in range(40)]
        chunks.append(ColumnarChunk.from_rows(schema, rows))
    table = ShardedTable.from_chunks(mesh, chunks)
    out = sort_table(table, ["s"])
    got = [r["s"] for r in _gather_rows(out)]
    # codes are order-preserving in the unified vocab → decoded bytes sorted
    decoded = [out.columns["s"].dictionary[c] if c is not None else None
               for c in got]
    assert decoded == sorted(decoded)


def test_sort_table_heavy_skew_one_hot_key():
    """One key owns ~50% of all rows: the multi-round exchange must deliver
    a correct global sort without losing rows (VERDICT round-1 item 7)."""
    import numpy as np
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.parallel.distributed import ShardedTable
    from ytsaurus_tpu.parallel.mesh import make_mesh
    from ytsaurus_tpu.parallel.shuffle import sort_table
    from ytsaurus_tpu.schema import TableSchema

    schema = TableSchema.make([("k", "int64"), ("p", "int64")])
    rng = np.random.default_rng(13)
    mesh = make_mesh(8)
    chunks = []
    all_keys = []
    for s in range(8):
        n = 400
        hot = np.full(n // 2, 777)
        rest = rng.integers(0, 10_000, n - n // 2)
        k = np.concatenate([hot, rest])
        rng.shuffle(k)
        all_keys.extend(k.tolist())
        chunks.append(ColumnarChunk.from_arrays(
            schema, {"k": k, "p": np.arange(n) + s * 1000}))
    table = ShardedTable.from_chunks(mesh, chunks)
    out = sort_table(table, ["k"])
    assert out.total_rows == table.total_rows
    # Global order across shard boundaries.
    data = np.asarray(out.columns["k"].data)
    collected = []
    for s in range(8):
        cnt = out.row_counts[s]
        collected.extend(data[s * out.capacity: s * out.capacity + cnt])
    assert collected == sorted(all_keys)


def test_sort_table_single_device_mesh():
    import numpy as np
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.parallel.distributed import ShardedTable
    from ytsaurus_tpu.parallel.mesh import make_mesh
    from ytsaurus_tpu.parallel.shuffle import sort_table
    from ytsaurus_tpu.schema import TableSchema

    schema = TableSchema.make([("k", "int64"), ("v", "int64")])
    rng = np.random.default_rng(3)
    k = rng.integers(0, 1000, 257)
    chunk = ColumnarChunk.from_arrays(
        schema, {"k": k, "v": np.arange(257)})
    mesh = make_mesh(1)
    table = ShardedTable.from_chunks(mesh, [chunk])
    out = sort_table(table, ["k"])
    got = np.asarray(out.columns["k"].data)[:257]
    assert got.tolist() == sorted(k.tolist())
