"""Mesh execution observatory tests (ISSUE 20): the bounded
per-fingerprint roll-up of in-program SPMD telemetry blocks
(parallel/mesh_observatory.py), the balanced-vs-skewed classification
the MESH_SKEW_SLO burns against (fire AND resolve over the ISSUE 6
synthetic-SLI harness), the /mesh monitoring endpoint + orchid twin,
`yt mesh top` formatting, and the satellite-6 fix: SPMD executables
feed the compile observatory's artifact capture so `yt compile-cache
top` shows their FLOPs/bytes."""

import json
import urllib.request

import numpy as np
import pytest

from ytsaurus_tpu import config as yt_config
from ytsaurus_tpu.parallel.mesh_observatory import (
    MESH_SKEW_SLO,
    MeshObservatory,
    get_mesh_observatory,
    memory_analysis_dict,
    peak_bytes,
)


def _block(skew=1.0, xbytes=0, headroom=0.0, watermark=None, drift=0.0,
           shards=8, path="fused"):
    """A telemetry block of the whole_plan._mesh_block shape."""
    block = {"version": 1, "path": path, "shards": shards,
             "in_rows": [10] * shards, "out_rows": [10] * shards,
             "skew": skew, "exchange_bytes": xbytes,
             "exchanges": []}
    if xbytes:
        block["exchanges"] = [{
            "stage": "shuffle/group", "rows": 10 * shards,
            "bytes": xbytes, "demand": 10, "quota": 16,
            "headroom": headroom}]
    if watermark is not None:
        block["memory_watermark_bytes"] = watermark
    if drift:
        block["stages"] = [{"stage": 0, "table": "//d",
                            "strategy": "partition", "est_rows": 100,
                            "actual_rows": 125, "drift": drift}]
    return block


# --- roll-up + classification -------------------------------------------------


def test_rollup_classification_and_top_views():
    obs = MeshObservatory()
    obs.record_execution("fp-a", _block(skew=1.2, xbytes=100))
    # skew 6.0 > mesh_max_imbalance default 4.0 -> classified skewed.
    obs.record_execution("fp-a", _block(skew=6.0, xbytes=50,
                                        headroom=0.8))
    obs.record_execution("fp-b", _block(skew=2.0, watermark=4096,
                                        drift=0.25, path="stitched"))
    assert obs.totals() == {"executions": 3, "balanced": 2, "skewed": 1,
                            "programs": 2, "compiled": 0}
    top = obs.top(by="skew")
    assert [r["fingerprint"] for r in top] == ["fp-a", "fp-b"]
    assert top[0]["skew_max"] == 6.0 and top[0]["skew_last"] == 6.0
    assert top[0]["exchange_bytes"] == 150
    assert top[0]["executions"] == 2 and top[0]["skewed"] == 1
    assert top[0]["quota_headroom"] == 0.8
    assert obs.top(by="memory")[0]["fingerprint"] == "fp-b"
    assert obs.top(by="drift")[0]["drift_max"] == 0.25
    assert obs.top(by="bytes")[0]["fingerprint"] == "fp-a"
    snap = obs.snapshot()
    assert snap["slo"] == MESH_SKEW_SLO
    assert snap["last_blocks"]["fp-a"]["skew"] == 6.0
    assert snap["last_blocks"]["fp-b"]["path"] == "stitched"
    # The ranked rows never carry the raw block (bounded payload).
    assert all("last_block" not in r for r in snap["programs"])


def test_skew_classification_follows_config_threshold():
    """mesh_max_imbalance is the skewed/balanced boundary; a 1-shard
    mesh or an empty output can never classify as skewed."""
    try:
        yt_config.set_telemetry_config(
            yt_config.TelemetryConfig(mesh_max_imbalance=2.0))
        obs = MeshObservatory()
        obs.record_execution("fp", _block(skew=3.0))          # > 2.0
        obs.record_execution("fp", _block(skew=1.5))          # <= 2.0
        obs.record_execution("fp", _block(skew=3.0, shards=1))
        empty = _block(skew=3.0)
        empty["out_rows"] = [0] * 8
        obs.record_execution("fp", empty)
        assert obs.totals()["skewed"] == 1
        assert obs.totals()["balanced"] == 3
    finally:
        yt_config.set_telemetry_config(None)


def test_rollups_are_bounded():
    obs = MeshObservatory()
    for i in range(obs.PROGRAM_CAP + 10):
        obs.record_execution(f"fp{i:04d}", _block())
    assert obs.totals()["programs"] == obs.PROGRAM_CAP
    kept = {r["fingerprint"] for r in obs.top(n=0)}
    assert "fp0000" not in kept               # LRU-evicted
    assert f"fp{obs.PROGRAM_CAP + 9:04d}" in kept
    for i in range(obs.COMPILED_CAP + 5):
        obs.record_compile(("k", i), {"temp_size_in_bytes": i + 1},
                           {"flops": 10.0})
    assert obs.totals()["compiled"] == obs.COMPILED_CAP
    assert obs.memory_for(("k", 0)) is None   # evicted
    assert obs.memory_for(("k", obs.COMPILED_CAP + 4)) == \
        obs.COMPILED_CAP + 5


def test_memory_analysis_normalization():
    class FakeMem:
        temp_size_in_bytes = 100
        argument_size_in_bytes = 40
        output_size_in_bytes = 10
        alias_size_in_bytes = 0
        generated_code_size_in_bytes = 7

    class FakeCompiled:
        def memory_analysis(self):
            return FakeMem()

    mem = memory_analysis_dict(FakeCompiled())
    assert mem["temp_size_in_bytes"] == 100
    assert mem["generated_code_size_in_bytes"] == 7
    # Watermark = live residency: temp + argument + output.
    assert peak_bytes(mem) == 150

    class Broken:
        def memory_analysis(self):
            raise NotImplementedError

    assert memory_analysis_dict(Broken()) is None
    assert peak_bytes(None) is None


# --- MESH_SKEW_SLO burn-rate (satellite 1) ------------------------------------


def test_mesh_skew_slo_burn_fires_and_resolves():
    """The skew SLO over the /query/mesh balanced/skewed counters, on
    the ISSUE 6 synthetic-SLI harness: a healthy baseline stays quiet, a
    skew storm fires the burn-rate alert, recovery resolves it."""
    from ytsaurus_tpu.utils.profiling import MetricsHistory, get_registry
    from ytsaurus_tpu.utils.slo import SloTracker
    obs = MeshObservatory()
    hist = MetricsHistory(registry=get_registry(), fine_capacity=720,
                          coarse_every=4, coarse_capacity=8,
                          sample_period=10.0)
    cfg = yt_config.TelemetryConfig.from_dict(
        {"slos": {"mesh_skew": dict(MESH_SKEW_SLO)}})
    tracker = SloTracker(cfg, history=hist)
    t = 0.0
    for _ in range(60):                     # healthy baseline
        for _ in range(10):
            obs.record_execution("fp", _block(skew=1.1))
        t = hist.sample_once(t + 10.0)
    snap = tracker.evaluate(now=t)
    assert snap["slos"]["mesh_skew"]["firing"] is False
    for _ in range(31):                     # skew storm
        for _ in range(10):
            obs.record_execution("fp", _block(skew=9.0))
        t = hist.sample_once(t + 10.0)
        tracker.evaluate(now=t)
    snap = tracker.evaluate(now=t)
    state = snap["slos"]["mesh_skew"]
    assert state["firing"] is True
    assert state["burn_fast"] > MESH_SKEW_SLO["burn_threshold"]
    assert state["burn_slow"] > MESH_SKEW_SLO["burn_threshold"]
    (alert,) = snap["active_alerts"]
    assert alert["slo"] == "mesh_skew" and alert["state"] == "firing"
    since = alert["since"]
    for _ in range(31):                     # recovery: fast window heals
        for _ in range(10):
            obs.record_execution("fp", _block(skew=1.0))
        t = hist.sample_once(t + 10.0)
        tracker.evaluate(now=t)
    snap = tracker.evaluate(now=t)
    assert snap["slos"]["mesh_skew"]["firing"] is False
    assert snap["active_alerts"] == []
    assert any(a["slo"] == "mesh_skew" and a["state"] == "resolved"
               and a["since"] == since
               for a in snap["resolved_alerts"])


# --- surfaces: /mesh endpoint, orchid, sensors, CLI ---------------------------


def test_monitoring_mesh_endpoint_orchid_and_sensors():
    from ytsaurus_tpu.server.monitoring import MonitoringServer
    from ytsaurus_tpu.server.orchid import default_orchid
    from ytsaurus_tpu.utils.profiling import get_registry
    obs = get_mesh_observatory()
    obs.reset()
    obs.record_execution("fp-end", _block(skew=2.5, xbytes=64,
                                          headroom=0.5))
    try:
        # The sensor family the catalog lint + SLO read.
        collected = get_registry().collect()
        assert collected["/query/mesh/skew_max"] == 2.5
        assert collected["/query/mesh/quota_headroom"] == 0.5
        assert collected["/query/mesh/balanced"] >= 1
        # Orchid twin of the monitoring endpoint.
        tree = default_orchid()
        assert tree.get("/mesh/totals")["executions"] == 1
        assert tree.get("/mesh/last_blocks/fp-end/skew") == 2.5
        server = MonitoringServer()
        server.start()
        try:
            with urllib.request.urlopen(
                    f"http://{server.address}/mesh", timeout=10) as resp:
                body = json.loads(resp.read())
            assert body["totals"]["executions"] == 1
            assert body["last_blocks"]["fp-end"]["exchange_bytes"] == 64
            assert body["slo"]["good_sensor"] == "/query/mesh/balanced"
            assert body["programs"][0]["fingerprint"] == "fp-end"
        finally:
            server.stop()
    finally:
        obs.reset()


def test_mesh_top_cli_formatting():
    from ytsaurus_tpu.cli import _format_mesh_top
    obs = MeshObservatory()
    obs.record_execution("fp-hot", _block(skew=6.5, xbytes=10,
                                          watermark=2048))
    obs.record_execution("fp-wide", _block(skew=1.1, xbytes=9000))
    text = _format_mesh_top(obs.snapshot(), "skew", 20)
    lines = text.splitlines()
    assert lines[0].split() == [
        "fingerprint", "path", "shards", "executions", "skew_max",
        "exchange_bytes", "quota_headroom", "memory_watermark_bytes",
        "drift_max", "skewed"]
    assert lines[1].split()[0] == "fp-hot"       # ranked by skew
    assert "6.500" in lines[1] and "2048" in lines[1]
    assert lines[-1] == ("totals: 2 executions (1 balanced / 1 skewed) "
                         "over 2 programs, 0 compile captures")
    by_bytes = _format_mesh_top(obs.snapshot(), "bytes", 20)
    assert by_bytes.splitlines()[1].split()[0] == "fp-wide"
    # limit clips the ranked rows, not the totals line.
    clipped = _format_mesh_top(obs.snapshot(), "skew", 1)
    assert "fp-wide" not in clipped.splitlines()[1]


# --- satellite 6: SPMD executables feed the compile observatory ---------------


def test_spmd_compile_capture_feeds_compile_cache_top(request):
    """ISSUE 20 fix: `_compile_spmd` threads cost/memory analysis into
    the mesh observatory (always) and — behind capture_artifacts — the
    compile observatory's artifact deque, so fused SPMD programs stop
    showing up blank in `yt compile-cache top`."""
    mesh = request.getfixturevalue("mesh8")
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.cli import _format_compile_top
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        ShardedTable,
    )
    from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
    from ytsaurus_tpu.query.builder import build_query
    from ytsaurus_tpu.query.engine.evaluator import (
        get_compile_observatory,
    )
    from ytsaurus_tpu.schema import TableSchema
    schema = TableSchema.make([("k", "int64", "ascending"),
                               ("v", "int64")])
    chunks = [ColumnarChunk.from_arrays(schema, {
        "k": np.arange(32) + sh * 32,
        "v": np.arange(32) * 2}) for sh in range(8)]
    table = ShardedTable.from_chunks(mesh, chunks)
    obs = get_mesh_observatory()
    compiled_before = obs.totals()["compiled"]
    try:
        yt_config.set_workload_config(
            yt_config.WorkloadConfig(capture_artifacts=True))
        get_compile_observatory().reset()
        de = DistributedEvaluator(mesh)
        plan = build_query("k, v FROM [//t] WHERE v > 10",
                           {"//t": schema})
        run_whole_plan(de, plan, table)
        assert obs.totals()["compiled"] > compiled_before
        artifacts = get_compile_observatory().snapshot()["artifacts"]
        spmd = [a for a in artifacts
                if str(a.get("fingerprint", "")).startswith("spmd/")]
        assert spmd, "SPMD executable must appear in the artifact tier"
        assert spmd[0]["fingerprint"] == "spmd/whole"
        text = _format_compile_top(
            get_compile_observatory().snapshot(), "compiles", 20)
        assert "artifacts:" in text and "spmd/whole" in text
    finally:
        yt_config.set_workload_config(None)
        get_compile_observatory().reset()
