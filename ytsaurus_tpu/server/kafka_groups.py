"""Kafka consumer-group coordinator: the classic 0.9 group membership
protocol (JoinGroup / SyncGroup / Heartbeat / LeaveGroup).

Ref: yt/yt/server/kafka_proxy/group_coordinator.h:14 — the reference
terminates group membership in the proxy so stock Kafka consumers
rebalance against YT queues.  Faithful to the public protocol's
division of labor: the COORDINATOR only runs the membership state
machine (generations, leader election among members, session expiry);
the LEADER CONSUMER computes partition assignments client-side and
ships them through SyncGroup as opaque bytes.  Committed offsets ride
the consumer tables (kafka_proxy.py OffsetCommit), so an assignment
handed to a new member resumes from the group's durable position.

State machine per group (the public GroupMetadata lifecycle):

  Empty → PreparingRebalance → CompletingRebalance → Stable
            ↑__________________________________________|
                    (member join/leave/expiry)

JoinGroup blocks until the join round closes (every known member
rejoined, or the round deadline passes and stragglers are dropped);
SyncGroup blocks followers until the leader ships assignments;
Heartbeat answers REBALANCE_IN_PROGRESS to pull members into the next
round.  A sweeper expires members that stop heartbeating — the death
of one consumer rebalances the survivors.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("kafka_groups")

# Kafka error codes (public protocol).
ERR_NONE = 0
ERR_ILLEGAL_GENERATION = 22
ERR_INCONSISTENT_GROUP_PROTOCOL = 23
ERR_UNKNOWN_MEMBER_ID = 25
ERR_REBALANCE_IN_PROGRESS = 27

EMPTY = "Empty"
PREPARING = "PreparingRebalance"
COMPLETING = "CompletingRebalance"
STABLE = "Stable"

# How long a join round stays open for known members to rejoin once the
# first joiner arrives (the rebalance window).
JOIN_WINDOW_SECONDS = 3.0


@dataclass
class Member:
    member_id: str
    session_timeout: float                     # seconds
    protocols: "list[tuple[str, bytes]]"
    last_heartbeat: float = field(default_factory=time.monotonic)
    assignment: bytes = b""
    rejoined: bool = False                     # in the CURRENT join round


@dataclass
class Group:
    group_id: str
    state: str = EMPTY
    generation: int = 0
    protocol_type: str = ""
    protocol: str = ""
    leader_id: str = ""
    members: "dict[str, Member]" = field(default_factory=dict)
    join_deadline: float = 0.0


class GroupCoordinator:
    """Membership state machines for every group on this proxy."""

    def __init__(self, sweep_interval: float = 0.5):
        self._cond = threading.Condition()
        self._groups: "dict[str, Group]" = {}
        self._stopped = False
        self._sweeper = threading.Thread(
            target=self._sweep_loop, args=(sweep_interval,),
            daemon=True, name="kafka-group-sweeper")
        self._sweeper.start()

    def stop(self) -> None:
        self._stopped = True
        with self._cond:
            self._cond.notify_all()

    # -- join ------------------------------------------------------------------

    def join_group(self, group_id: str, session_timeout_ms: int,
                   member_id: str, protocol_type: str,
                   protocols: "list[tuple[str, bytes]]",
                   timeout: float = 30.0) -> dict:
        """Blocks until the join round closes.  Returns the JoinGroup
        response fields; the leader's `members` list carries everyone's
        subscription metadata for client-side assignment."""
        deadline = time.monotonic() + timeout
        with self._cond:
            group = self._groups.setdefault(group_id, Group(group_id))
            if member_id and member_id not in group.members:
                return {"error": ERR_UNKNOWN_MEMBER_ID}
            if group.members and protocol_type and group.protocol_type \
                    and protocol_type != group.protocol_type:
                return {"error": ERR_INCONSISTENT_GROUP_PROTOCOL}
            if not member_id:
                member_id = f"{group_id}-{uuid.uuid4().hex[:12]}"
            group.protocol_type = protocol_type or group.protocol_type
            member = Member(member_id,
                            max(session_timeout_ms, 1000) / 1000.0,
                            list(protocols))
            group.members[member_id] = member
            if group.state != PREPARING:
                self._begin_rebalance(group)
            # AFTER _begin_rebalance (which clears every rejoined flag):
            # the joiner itself is in the round by definition.
            member.rejoined = True
            member.last_heartbeat = time.monotonic()
            self._cond.notify_all()
            # Wait for the round to close (we, or whoever notices the
            # deadline/completeness first, closes it).
            while group.state == PREPARING and \
                    time.monotonic() < deadline:
                if self._join_round_closable(group):
                    self._close_join_round(group)
                    break
                self._cond.wait(timeout=0.1)
            if group.state == PREPARING:
                return {"error": ERR_REBALANCE_IN_PROGRESS}
            if member_id not in group.members:
                return {"error": ERR_UNKNOWN_MEMBER_ID}   # dropped
            response = {
                "error": ERR_NONE,
                "generation": group.generation,
                "protocol": group.protocol,
                "leader_id": group.leader_id,
                "member_id": member_id,
                "members": [],
            }
            if member_id == group.leader_id:
                chosen = group.protocol
                for mid, m in group.members.items():
                    metadata = b""
                    for name, meta in m.protocols:
                        if name == chosen:
                            metadata = meta
                            break
                    response["members"].append((mid, metadata))
            return response

    def _begin_rebalance(self, group: Group) -> None:
        group.state = PREPARING
        group.join_deadline = time.monotonic() + JOIN_WINDOW_SECONDS
        for member in group.members.values():
            member.rejoined = False
            member.assignment = b""

    def _join_round_closable(self, group: Group) -> bool:
        if all(m.rejoined for m in group.members.values()):
            return True
        return time.monotonic() >= group.join_deadline

    def _close_join_round(self, group: Group) -> None:
        # Stragglers that never rejoined are out of the generation.
        group.members = {mid: m for mid, m in group.members.items()
                        if m.rejoined}
        # The session clock restarts at the round close: a member's
        # time-to-SyncGroup is measured from HERE, not from whenever it
        # happened to enter the round.
        now = time.monotonic()
        for member in group.members.values():
            member.last_heartbeat = now
        if not group.members:
            group.state = EMPTY
            group.generation += 1
            self._cond.notify_all()
            return
        group.generation += 1
        group.leader_id = sorted(group.members)[0]
        group.protocol = self._select_protocol(group)
        group.state = COMPLETING
        logger.info("group %s generation %d: leader %s, %d members",
                    group.group_id, group.generation, group.leader_id,
                    len(group.members))
        self._cond.notify_all()

    def _select_protocol(self, group: Group) -> str:
        """First protocol (in the leader's preference order) every
        member supports — the public coordinator's vote."""
        leader = group.members[group.leader_id]
        for name, _meta in leader.protocols:
            if all(any(n == name for n, _ in m.protocols)
                   for m in group.members.values()):
                return name
        return leader.protocols[0][0] if leader.protocols else ""

    # -- sync ------------------------------------------------------------------

    def sync_group(self, group_id: str, generation: int, member_id: str,
                   assignments: "list[tuple[str, bytes]]",
                   timeout: float = 30.0) -> "tuple[int, bytes]":
        """(error, member_assignment).  The leader ships everyone's
        assignment; followers block until it lands."""
        deadline = time.monotonic() + timeout
        with self._cond:
            group = self._groups.get(group_id)
            if group is None or member_id not in group.members:
                return ERR_UNKNOWN_MEMBER_ID, b""
            if generation != group.generation:
                return ERR_ILLEGAL_GENERATION, b""
            if member_id == group.leader_id and group.state == COMPLETING:
                for mid, blob in assignments:
                    if mid in group.members:
                        group.members[mid].assignment = blob
                group.state = STABLE
                self._cond.notify_all()
            while group.state == COMPLETING and \
                    time.monotonic() < deadline:
                self._cond.wait(timeout=0.1)
            if group.state == PREPARING:
                return ERR_REBALANCE_IN_PROGRESS, b""
            if group.state != STABLE:
                return ERR_REBALANCE_IN_PROGRESS, b""
            if generation != group.generation or \
                    member_id not in group.members:
                return ERR_ILLEGAL_GENERATION, b""
            group.members[member_id].last_heartbeat = time.monotonic()
            return ERR_NONE, group.members[member_id].assignment

    # -- heartbeat / leave -----------------------------------------------------

    def heartbeat(self, group_id: str, generation: int,
                  member_id: str) -> int:
        with self._cond:
            group = self._groups.get(group_id)
            if group is None or member_id not in group.members:
                return ERR_UNKNOWN_MEMBER_ID
            group.members[member_id].last_heartbeat = time.monotonic()
            if group.state == PREPARING:
                return ERR_REBALANCE_IN_PROGRESS   # come rejoin
            if generation != group.generation:
                return ERR_ILLEGAL_GENERATION
            return ERR_NONE

    def leave_group(self, group_id: str, member_id: str) -> int:
        with self._cond:
            group = self._groups.get(group_id)
            if group is None or member_id not in group.members:
                return ERR_UNKNOWN_MEMBER_ID
            del group.members[member_id]
            self._begin_rebalance(group)
            if not group.members:
                group.state = EMPTY
            self._cond.notify_all()
            return ERR_NONE

    # -- expiry ----------------------------------------------------------------

    def _sweep_loop(self, interval: float) -> None:
        while not self._stopped:
            time.sleep(interval)
            now = time.monotonic()
            with self._cond:
                for group in self._groups.values():
                    if group.state == PREPARING:
                        # Mid-round nobody expires (the join window is
                        # short and bounds stragglers); but a round with
                        # no blocked joiner left to close it must not
                        # zombie — the sweeper closes it at deadline.
                        if now >= group.join_deadline:
                            self._close_join_round(group)
                        continue
                    dead = [mid for mid, m in group.members.items()
                            if now - m.last_heartbeat > m.session_timeout]
                    if not dead:
                        continue
                    for mid in dead:
                        logger.info("group %s: member %s expired",
                                    group.group_id, mid)
                        del group.members[mid]
                    if group.members:
                        self._begin_rebalance(group)
                    else:
                        group.state = EMPTY
                    self._cond.notify_all()

    # -- introspection ---------------------------------------------------------

    def describe(self, group_id: str) -> "Optional[dict]":
        with self._cond:
            group = self._groups.get(group_id)
            if group is None:
                return None
            return {"state": group.state,
                    "generation": group.generation,
                    "leader_id": group.leader_id,
                    "members": sorted(group.members)}
