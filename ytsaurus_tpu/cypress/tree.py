"""Cypress: the versioned metadata tree.

Ref: yt/yt/server/master/cypress_server (cypress_manager.h, node_detail.h) +
core/ytree YPath semantics.  Nodes are typed (map_node, table, file,
document, ...), carry attributes, and are addressed by YPath
(`//a/b/@attr`).  Simplifications vs the reference, by design for round 1:
single master cell, exclusive whole-node locks only, no portals/Sequoia.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from ytsaurus_tpu.errors import EErrorCode, YtError

NODE_TYPES = {
    "map_node", "table", "file", "document", "string_node", "int64_node",
    "list_node", "link", "portal_entrance",
}


def parse_ypath(path: str) -> tuple[list[str], Optional[str]]:
    """'//a/b/@attr/x' → (['a','b'], 'attr/x'); '//a/b' → (['a','b'], None)."""
    if not path.startswith("//") and path != "/":
        raise YtError(f"Bad YPath {path!r}: must start with //",
                      code=EErrorCode.ResolveError)
    attr = None
    if "/@" in path:
        path, attr = path.split("/@", 1)
    tokens = [t for t in path[2:].split("/") if t] if path != "/" else []
    return tokens, attr


@dataclass
class CypressNode:
    id: str
    type: str
    attributes: dict = field(default_factory=dict)
    children: dict[str, "CypressNode"] = field(default_factory=dict)
    value: Any = None                  # document/scalar payload

    def to_dict(self, depth: Optional[int] = None) -> Any:
        if self.type == "map_node":
            if depth == 0:
                return {}
            return {name: child.to_dict(None if depth is None else depth - 1)
                    for name, child in self.children.items()}
        if self.type == "document":
            return self.value
        if self.type in ("string_node", "int64_node"):
            return self.value
        return {}

    def serialize(self) -> dict:
        return {
            "id": self.id,
            "type": self.type,
            "attributes": self.attributes,
            "value": self.value,
            "children": {name: child.serialize()
                         for name, child in self.children.items()},
        }

    @classmethod
    def deserialize(cls, data: dict) -> "CypressNode":
        node = cls(id=data["id"], type=data["type"],
                   attributes=dict(data.get("attributes") or {}),
                   value=data.get("value"))
        node.children = {name: cls.deserialize(child)
                         for name, child in (data.get("children") or {}).items()}
        return node


def _clone(node: CypressNode) -> CypressNode:
    import copy as _copy
    cloned = CypressNode(
        id=uuid.uuid4().hex, type=node.type,
        attributes=_copy.deepcopy(node.attributes),
        value=_copy.deepcopy(node.value))
    cloned.children = {name: _clone(child)
                       for name, child in node.children.items()}
    return cloned


class CypressTree:
    def __init__(self):
        self.root = CypressNode(id=uuid.uuid4().hex, type="map_node")

    # -- resolution ------------------------------------------------------------

    def resolve(self, path: str, follow_links: bool = True) -> CypressNode:
        tokens, attr = parse_ypath(path)
        if attr is not None:
            raise YtError(f"Expected a node path, got attribute path {path!r}",
                          code=EErrorCode.ResolveError)
        node = self.root
        for token in tokens:
            child = node.children.get(token)
            if child is None:
                raise YtError(f"Node {path!r} has no child {token!r}",
                              code=EErrorCode.NoSuchNode,
                              attributes={"path": path})
            node = child
            if follow_links and node.type == "link":
                node = self.resolve(node.attributes["target_path"])
        return node

    def try_resolve(self, path: str,
                    follow_links: bool = True) -> Optional[CypressNode]:
        try:
            return self.resolve(path, follow_links=follow_links)
        except YtError:
            return None

    def exists(self, path: str) -> bool:
        tokens, attr = parse_ypath(path)
        node = self.root
        for token in tokens:
            node = node.children.get(token)
            if node is None:
                return False
            if node.type == "link":
                node = self.try_resolve(node.attributes["target_path"])
                if node is None:
                    return False
        if attr is not None:
            return _attr_exists(node, attr)
        return True

    # -- mutations (called through the master WAL) -----------------------------

    def create(self, path: str, node_type: str,
               attributes: Optional[dict] = None, recursive: bool = False,
               ignore_existing: bool = False) -> str:
        if node_type not in NODE_TYPES:
            raise YtError(f"Unknown node type {node_type!r}")
        tokens, attr = parse_ypath(path)
        if attr is not None or not tokens:
            raise YtError(f"Cannot create at {path!r}",
                          code=EErrorCode.ResolveError)
        node = self.root
        for token in tokens[:-1]:
            if node.type != "map_node":
                raise YtError(
                    f"Cannot traverse {node.type} node while creating {path!r}",
                    code=EErrorCode.ResolveError)
            child = node.children.get(token)
            if child is None:
                if not recursive:
                    raise YtError(f"Node {path!r}: missing parent {token!r}",
                                  code=EErrorCode.NoSuchNode)
                child = CypressNode(id=uuid.uuid4().hex, type="map_node")
                node.children[token] = child
            node = child
        name = tokens[-1]
        existing = node.children.get(name)
        if existing is not None:
            if ignore_existing and existing.type == node_type:
                return existing.id
            raise YtError(f"Node {path!r} already exists",
                          code=EErrorCode.AlreadyExists)
        if node.type != "map_node":
            raise YtError(f"Cannot create child under {node.type}",
                          code=EErrorCode.ResolveError)
        new_node = CypressNode(id=uuid.uuid4().hex, type=node_type,
                               attributes=dict(attributes or {}))
        node.children[name] = new_node
        return new_node.id

    def remove(self, path: str, recursive: bool = True,
               force: bool = False) -> None:
        tokens, attr = parse_ypath(path)
        if attr is not None:
            node = self.resolve("//" + "/".join(tokens) if tokens else "/")
            _attr_remove(node, attr)
            return
        if not tokens:
            raise YtError("Cannot remove the root")
        parent = self.root
        for token in tokens[:-1]:
            parent = parent.children.get(token)
            if parent is None:
                if force:
                    return
                raise YtError(f"No such node {path!r}",
                              code=EErrorCode.NoSuchNode)
        name = tokens[-1]
        node = parent.children.get(name)
        if node is None:
            if force:
                return
            raise YtError(f"No such node {path!r}", code=EErrorCode.NoSuchNode)
        if node.children and not recursive:
            raise YtError(f"Node {path!r} is not empty")
        del parent.children[name]

    def set(self, path: str, value: Any) -> None:
        tokens, attr = parse_ypath(path)
        if attr is not None:
            node = self.resolve("//" + "/".join(tokens) if tokens else "/")
            _attr_set(node, attr, value)
            return
        node = self.try_resolve(path)
        if node is None:
            self.create(path, "document", recursive=True)
            node = self.resolve(path)
        if node.type == "map_node" and isinstance(value, dict):
            node.children = {}
            for key, item in value.items():
                self.create(f"{path}/{key}" if path != "/" else f"//{key}",
                            "document")
                self.resolve(f"{path}/{key}").value = item
        else:
            node.value = value

    def copy(self, src_path: str, dst_path: str,
             recursive: bool = False) -> str:
        """Deep-copy a subtree (nodes get fresh ids; attributes copied).
        Copying a link copies the LINK, not its target."""
        node = self.resolve(src_path, follow_links=False)
        cloned = _clone(node)
        self._attach(dst_path, cloned, recursive)
        return cloned.id

    def move(self, src_path: str, dst_path: str,
             recursive: bool = False) -> str:
        """Atomic move: the destination is validated and prepared BEFORE the
        source detaches, so a failing move leaves the tree untouched."""
        node = self.resolve(src_path, follow_links=False)
        attach = self._prepare_attach(dst_path, recursive)
        self.remove(src_path)
        attach(node)
        return node.id

    def link(self, target_path: str, link_path: str,
             recursive: bool = False) -> str:
        """Symlink node storing its target path (resolved on access)."""
        self.resolve(target_path)          # must exist
        return self.create(link_path, "link", recursive=recursive,
                           attributes={"target_path": target_path})

    def _attach(self, path: str, node: CypressNode,
                recursive: bool) -> None:
        self._prepare_attach(path, recursive)(node)

    def _prepare_attach(self, path: str, recursive: bool):
        """Validate + create intermediates; return a closure that attaches a
        node (all failure modes fire BEFORE any caller-side detach)."""
        tokens, attr = parse_ypath(path)
        if attr is not None or not tokens:
            raise YtError(f"Cannot attach at {path!r}",
                          code=EErrorCode.ResolveError)
        parent = self.root
        for token in tokens[:-1]:
            if parent.type != "map_node":
                raise YtError(f"Cannot traverse {parent.type} node")
            child = parent.children.get(token)
            if child is None:
                if not recursive:
                    raise YtError(f"Missing parent {token!r} for {path!r}",
                                  code=EErrorCode.NoSuchNode)
                child = CypressNode(id=uuid.uuid4().hex, type="map_node")
                parent.children[token] = child
            parent = child
        name = tokens[-1]
        if name in parent.children:
            raise YtError(f"Node {path!r} already exists",
                          code=EErrorCode.AlreadyExists)

        def attach(node: CypressNode) -> None:
            parent.children[name] = node
        return attach

    # -- reads -----------------------------------------------------------------

    def get(self, path: str, attributes: Optional[list[str]] = None) -> Any:
        tokens, attr = parse_ypath(path)
        node = self.root
        for token in tokens:
            child = node.children.get(token)
            if child is None:
                raise YtError(f"No such node {path!r}",
                              code=EErrorCode.NoSuchNode)
            node = child
            if node.type == "link":
                node = self.resolve(node.attributes["target_path"])
        if attr is not None:
            return _attr_get(node, attr)
        return node.to_dict()

    def list(self, path: str) -> list[str]:
        node = self.resolve(path)
        if node.type != "map_node":
            raise YtError(f"Cannot list non-map node {path!r}")
        return sorted(node.children)

    # -- persistence -----------------------------------------------------------

    def serialize(self) -> dict:
        return self.root.serialize()

    @classmethod
    def deserialize(cls, data: dict) -> "CypressTree":
        tree = cls()
        tree.root = CypressNode.deserialize(data)
        return tree


_BUILTIN_ATTRS = {"id", "type", "count", "children"}


def _attr_get(node: CypressNode, attr: str):
    parts = attr.split("/")
    name = parts[0]
    if name == "id":
        value: Any = node.id
    elif name == "type":
        value = node.type
    elif name == "count":
        value = len(node.children)
    elif name in node.attributes:
        value = node.attributes[name]
    else:
        raise YtError(f"No such attribute {name!r}",
                      code=EErrorCode.NoSuchNode)
    for part in parts[1:]:
        if isinstance(value, dict) and part in value:
            value = value[part]
        else:
            raise YtError(f"No such attribute path @{attr}",
                          code=EErrorCode.NoSuchNode)
    return value


def _attr_set(node: CypressNode, attr: str, value) -> None:
    parts = attr.split("/")
    if parts[0] in _BUILTIN_ATTRS:
        raise YtError(f"Attribute {parts[0]!r} is read-only")
    target = node.attributes
    for part in parts[:-1]:
        target = target.setdefault(part, {})
        if not isinstance(target, dict):
            raise YtError(f"Attribute path @{attr} is not a map")
    target[parts[-1]] = value


def _attr_remove(node: CypressNode, attr: str) -> None:
    parts = attr.split("/")
    target = node.attributes
    for part in parts[:-1]:
        target = target.get(part)
        if not isinstance(target, dict):
            raise YtError(f"No such attribute @{attr}")
    target.pop(parts[-1], None)


def _attr_exists(node: CypressNode, attr: str) -> bool:
    try:
        _attr_get(node, attr)
        return True
    except YtError:
        return False
