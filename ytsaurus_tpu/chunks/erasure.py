"""Erasure coding: systematic Reed–Solomon and LRC over GF(2^8).

Ref: library/cpp/erasure (codecs RS(6,3), LRC(12,2,2) via ISA-L/Jerasure,
wrapped by yt/yt/library/erasure).  This is an independent numpy
implementation: a systematic generator derived from an extended Vandermonde
matrix; decode selects a full-rank subset of the available rows, so any
recoverable erasure pattern reconstructs.  rs_6_3 matches the reference's
default storage codec shape; lrc_12_2_2 is the production-default family
(README.md:3-7): 12 data parts in two locality groups of 6, one XOR
parity per group (single-part repair reads only its group) plus two
Vandermonde global parities (every 3-erasure pattern and many 4-erasure
patterns reconstruct)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils import failpoints

_FP_DECODE = failpoints.register_site(
    "chunks.erasure.decode",
    error=lambda s: YtError(f"injected erasure decode failure at {s}",
                            code=EErrorCode.ChunkFormatError))

# --- GF(2^8) arithmetic (poly 0x11D, generator 2) ----------------------------

_EXP = np.zeros(512, dtype=np.uint8)
_LOG = np.zeros(256, dtype=np.int32)
_x = 1
for _i in range(255):
    _EXP[_i] = _x
    _LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= 0x11D
for _i in range(255, 512):
    _EXP[_i] = _EXP[_i - 255]


def _gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(_EXP[_LOG[a] + _LOG[b]])


def _gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(256) inverse of 0")
    return int(_EXP[255 - _LOG[a]])


def _gf_matmul_vec(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """(r, k) GF matrix × (k, n) byte planes → (r, n)."""
    r, k = matrix.shape
    out = np.zeros((r, data.shape[1]), dtype=np.uint8)
    for i in range(r):
        acc = np.zeros(data.shape[1], dtype=np.uint8)
        for j in range(k):
            c = int(matrix[i, j])
            if c == 0:
                continue
            # Vectorized GF multiply-by-constant via log tables.
            row = data[j]
            nz = row != 0
            prod = np.zeros_like(row)
            prod[nz] = _EXP[(_LOG[row[nz]] + _LOG[c]) % 255]
            acc ^= prod
        out[i] = acc
    return out


def _gf_gauss_invert(matrix: np.ndarray) -> np.ndarray:
    """Invert a square GF(256) matrix by Gauss-Jordan elimination."""
    n = matrix.shape[0]
    aug = np.concatenate(
        [matrix.astype(np.uint8), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if aug[row, col] != 0:
                pivot = row
                break
        if pivot is None:
            raise YtError("Singular matrix during erasure repair",
                          code=EErrorCode.ChunkFormatError)
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv = _gf_inv(int(aug[col, col]))
        aug[col] = _gf_constant_mul(aug[col], inv)
        for row in range(n):
            if row != col and aug[row, col] != 0:
                factor = int(aug[row, col])
                aug[row] ^= _gf_constant_mul(aug[col], factor)
    return aug[:, n:]


def _gf_constant_mul(row: np.ndarray, c: int) -> np.ndarray:
    if c == 0:
        return np.zeros_like(row)
    nz = row != 0
    out = np.zeros_like(row)
    out[nz] = _EXP[(_LOG[row[nz]] + _LOG[c]) % 255]
    return out


def _gf_pow(a: int, e: int) -> int:
    if e == 0:
        return 1
    if a == 0:
        return 0
    return int(_EXP[(_LOG[a] * e) % 255])


def _systematic_generator(k: int, m: int) -> np.ndarray:
    """(k+m, k) systematic generator: top k rows identity, bottom m parity.

    Vandermonde over distinct evaluation points 0..k+m-1 (any k rows are
    independent), right-multiplied by the inverse of its top k×k block.
    """
    v = np.zeros((k + m, k), dtype=np.uint8)
    for i in range(k + m):
        for j in range(k):
            v[i, j] = _gf_pow(i, j)
    top_inv = _gf_gauss_invert(v[:k].copy())
    return _gf_matrix_mul(v, top_inv)


def _gf_matrix_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    r, k = a.shape
    k2, c = b.shape
    assert k == k2
    out = np.zeros((r, c), dtype=np.uint8)
    for i in range(r):
        for j in range(c):
            acc = 0
            for t in range(k):
                acc ^= _gf_mul(int(a[i, t]), int(b[t, j]))
            out[i, j] = acc
    return out


@dataclass(frozen=True)
class ErasureCodec:
    name: str
    data_parts: int          # k
    parity_parts: int        # m
    generator: np.ndarray    # (k+m, k) systematic
    # Locality groups (LRC): part-index tuples whose members XOR to
    # zero, so any single member rebuilds from the rest of its group.
    # Empty for MDS codes (RS).
    groups: "tuple[tuple[int, ...], ...]" = ()

    @property
    def total_parts(self) -> int:
        return self.data_parts + self.parity_parts

    # -- encode ----------------------------------------------------------------

    def encode(self, blob: bytes) -> list[bytes]:
        """Split into k data parts (padded) + m parity parts.  Part 0 carries
        no length header; callers must remember the original byte length."""
        k = self.data_parts
        part_len = (len(blob) + k - 1) // k
        part_len = max(part_len, 1)
        data = np.frombuffer(
            blob.ljust(k * part_len, b"\0"), dtype=np.uint8).reshape(k, part_len)
        parity = _gf_matmul_vec(self.generator[k:], data)
        return [data[i].tobytes() for i in range(k)] + \
            [parity[i].tobytes() for i in range(self.parity_parts)]

    # -- decode / repair -------------------------------------------------------

    def decode(self, parts: Sequence[Optional[bytes]], size: int) -> bytes:
        """Reconstruct the original blob from a recoverable subset of
        parts.  Row selection is rank-aware: for MDS codes (RS) any k
        parts work; for LRC some k-subsets are dependent (e.g. both
        local parities against erasures concentrated in one group), so
        the decoder picks an invertible row set from EVERYTHING
        available instead of blindly taking the first k."""
        _FP_DECODE.hit()
        return self._data_matrix(parts).reshape(-1).tobytes()[:size]

    def _data_matrix(self, parts: Sequence[Optional[bytes]]) -> np.ndarray:
        k = self.data_parts
        available = [i for i, p in enumerate(parts) if p is not None]
        if available[: k] == list(range(k)):
            return np.stack([np.frombuffer(parts[i], dtype=np.uint8)
                             for i in range(k)])
        use = _select_invertible_rows(self.generator, available, k)
        if use is None:
            raise YtError(
                f"Erasure decode: available parts {available} do not "
                f"span the data (codec {self.name}); unrecoverable "
                "erasure pattern", code=EErrorCode.ChunkFormatError)
        sub = self.generator[use]                        # (k, k)
        inv = _gf_gauss_invert(sub)
        received = np.stack([np.frombuffer(parts[i], dtype=np.uint8)
                             for i in use])
        return _gf_matmul_vec(inv, received)

    def locality_group(self, index: int) -> "Optional[list[int]]":
        """The part indices whose XOR rebuilds `index` (its locality
        group minus `index`); None when the codec has no locality
        structure or the part belongs to no group (global parity)."""
        for group in self.groups:
            if index in group:
                return [m for m in group if m != index]
        return None

    def repair_part(self, parts: Sequence[Optional[bytes]],
                    index: int) -> bytes:
        """Rebuild ONE part.  LRC's locality benefit: a part inside a
        locality group XOR-repairs from the 6 other group members (the
        other group and the global parities may be unavailable); the
        general path reconstructs the data matrix and re-encodes."""
        group = self.locality_group(index)
        if group is not None and all(parts[m] is not None for m in group):
            acc = np.frombuffer(parts[group[0]], dtype=np.uint8).copy()
            for m in group[1:]:
                acc ^= np.frombuffer(parts[m], dtype=np.uint8)
            return acc.tobytes()
        data = self._data_matrix(parts)
        return _gf_matmul_vec(self.generator[index: index + 1],
                              data)[0].tobytes()


def _select_invertible_rows(generator: np.ndarray, available: list,
                            k: int) -> "Optional[list]":
    """Greedy full-rank row selection over GF(2^8): walk the available
    generator rows, keep each row that is independent of those already
    kept (Gaussian reduction), stop at k.  Prefers data rows (identity —
    cheapest) because `available` is index-ordered."""
    chosen: list = []
    basis: list = []            # reduced rows with their pivot columns
    for idx in available:
        row = generator[idx].astype(np.uint8).copy()
        for pivot_col, basis_row in basis:
            if row[pivot_col]:
                factor = row[pivot_col]
                row = row ^ np.array(
                    [_gf_mul(int(factor), int(b)) for b in basis_row],
                    dtype=np.uint8)
        nz = np.nonzero(row)[0]
        if len(nz) == 0:
            continue            # dependent on rows already chosen
        pivot = int(nz[0])
        inv = _gf_inv(int(row[pivot]))
        row = np.array([_gf_mul(inv, int(b)) for b in row],
                       dtype=np.uint8)
        basis.append((pivot, row))
        chosen.append(idx)
        if len(chosen) == k:
            return chosen
    return None


def _lrc_generator() -> np.ndarray:
    """LRC(12,2,2): identity for the 12 data parts, one XOR row per
    locality group of 6 (parts 12, 13), two Vandermonde global parity
    rows over distinct nonzero field elements (parts 14, 15).  Distinct
    alphas make every within-group Vandermonde minor invertible, so all
    3-erasure patterns reconstruct; squaring is a field automorphism, so
    the second global row stays independent."""
    k = 12
    rows = [np.eye(k, dtype=np.uint8)]
    l0 = np.array([1] * 6 + [0] * 6, dtype=np.uint8)
    l1 = np.array([0] * 6 + [1] * 6, dtype=np.uint8)
    alphas = [int(_EXP[i]) for i in range(k)]       # 2^i, all distinct
    g0 = np.array(alphas, dtype=np.uint8)
    g1 = np.array([_gf_mul(a, a) for a in alphas], dtype=np.uint8)
    rows.append(np.stack([l0, l1, g0, g1]))
    return np.vstack(rows)


_CODECS: dict[str, ErasureCodec] = {}


def get_erasure_codec(name: str) -> ErasureCodec:
    codec = _CODECS.get(name)
    if codec is None:
        if name == "rs_6_3":
            codec = ErasureCodec("rs_6_3", 6, 3, _systematic_generator(6, 3))
        elif name == "rs_3_2":
            codec = ErasureCodec("rs_3_2", 3, 2, _systematic_generator(3, 2))
        elif name == "lrc_12_2_2":
            codec = ErasureCodec(
                "lrc_12_2_2", 12, 4, _lrc_generator(),
                groups=(tuple(range(0, 6)) + (12,),
                        tuple(range(6, 12)) + (13,)))
        else:
            raise YtError(f"Unknown erasure codec {name!r}",
                          code=EErrorCode.ChunkFormatError)
        _CODECS[name] = codec
    return codec
