"""WAL backends for the master: local file or quorum-of-N locations.

Ref: Hydra quorum changelogs — mutations are acknowledged by a majority of
changelog replicas before apply (server/lib/hydra/changelog.h + journal
quorum semantics, server/master/journal_server/journal_node.h:19).

Protocol invariants (Viewstamped-Replication-style; ref Hydra changelog
acquisition + VR view change):

- Every record is tagged with the EPOCH of the writer that created it
  (like a Raft entry's term); tags never change when a later writer
  re-replicates the record.
- Remote appends are position-checked AND prev-epoch-checked (the data
  node rejects an append whose stated predecessor epoch differs from its
  own last record's epoch), so a location's log is always a prefix of
  the log of the writer that last appended to it; divergent forks left
  by fenced writers are detected and reset, never silently extended.
- Recovery reads an INTERSECTING set of voting locations (>= n-q+1, so
  it shares a member with every write quorum) and adopts the log with
  the highest (last-record epoch, length) — the VR "most up-to-date"
  rule.  A record acknowledged by any write quorum is therefore visible
  to recovery on at least one voter, and the newest-epoch rule makes
  that voter win against shorter or stale-fork logs.  An UNacknowledged
  tail from the newest epoch may be adopted (it becomes committed
  retroactively, which is sound — no conflicting record was ever
  acknowledged) or discarded if no voter holds it; what can never
  happen is loss of an acknowledged record.
- Recovery re-replicates the adopted log until >= quorum locations hold
  it before acknowledging recovery, so the adopted tail is as durable
  as any acked record by the time the master applies it.

Snapshots are replicated to the journal locations BEFORE the journals are
truncated (build_snapshot), so a total local-disk loss still recovers:
newest quorum snapshot + committed journal tail.
"""

from __future__ import annotations

import os
from typing import Optional

from ytsaurus_tpu.cypress.master import Changelog
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils.invariants import check as _invariant_check
from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("quorum")

# Key under which a record carries the epoch of the writer that created
# it.  Tagged in place (records are master-mutation dicts); records
# written before epoch tagging existed read as epoch 0.
EPOCH_KEY = "$qe"


def record_epoch(record) -> int:
    """Epoch the record was created under (0 for pre-tagging records)."""
    if isinstance(record, dict):
        return int(record.get(EPOCH_KEY, 0))
    return 0


class LocalWal:
    """Single-location WAL: today's fsync'd changelog file.

    A `.init` marker distinguishes "this location has legitimately empty
    history" from "this is a fresh disk that never saw the log" — a fresh
    disk must NOT vote a zero-length prefix in quorum recovery (it would
    truncate acknowledged records)."""

    def __init__(self, path: str):
        self.path = path
        self._log: Optional[Changelog] = None
        self._last_offset: Optional[int] = None
        self.was_initialized = os.path.exists(path + ".init") or \
            os.path.exists(path)

    def _mark_initialized(self) -> None:
        marker = self.path + ".init"
        if not os.path.exists(marker):
            os.makedirs(os.path.dirname(marker) or ".", exist_ok=True)
            with open(marker, "wb") as f:
                f.flush()
                os.fsync(f.fileno())

    def recover(self) -> list[dict]:
        records, valid = Changelog.read_all(self.path)
        self._mark_initialized()
        # Drop a torn tail so future appends stay recoverable.
        if os.path.exists(self.path) and \
                os.path.getsize(self.path) > valid:
            with open(self.path, "r+b") as f:
                f.truncate(valid)
                f.flush()
                os.fsync(f.fileno())
        self._log = Changelog(self.path)
        return records

    def append(self, record: dict) -> None:
        self._last_offset = self._log.append(record)

    def drop_last(self) -> None:
        """Remove exactly the most recent append — O(1), no rewrite.
        Valid only immediately after an append (the offset is not
        tracked across recover/reset)."""
        if self._last_offset is None:
            raise YtError("no append to drop")
        self._log.truncate_to(self._last_offset)
        self._last_offset = None

    def reset(self) -> None:
        """Truncate after a snapshot."""
        self._log.close()
        self._last_offset = None
        if os.path.exists(self.path):
            os.unlink(self.path)
        self._log = Changelog(self.path)

    def close(self) -> None:
        if self._log is not None:
            self._log.close()

    # Snapshot replication is a no-op for a single-location WAL.
    def store_snapshot(self, seq: int, blob: bytes) -> None:
        pass

    def fetch_snapshot(self) -> "tuple[int, bytes] | None":
        return None


class _Replica:
    def __init__(self, channel):
        self.channel = channel
        self.synced_len: Optional[int] = None    # None = unknown/unsynced


class QuorumWal:
    """WAL over one local location + remote journal locations."""

    def __init__(self, local_path: str, journal_name: str,
                 remote_channels: list, quorum: int = 2,
                 bootstrap_from_local: bool = False,
                 lease_ttl: float = 0.0,
                 count_local_ack: bool = True):
        self.local = LocalWal(local_path)
        self.journal_name = journal_name
        self.replicas = [_Replica(ch) for ch in remote_channels]
        self.quorum = quorum
        # >0 under leader election: epoch acquisition also claims the
        # leader lease on each granting location (see LeaderElector).
        self.lease_ttl = lease_ttl
        # count_local_ack=False = REMOTE-ONLY quorum, required under
        # multi-master failover: a successor master recovers with a
        # FRESH local location, so a record acked against "local + k
        # remotes" may sit on only k remotes — the read and write
        # quorums must intersect over the SHARED (remote) locations
        # alone.  The local file still takes every append; it just earns
        # no quorum credit and no recovery vote (it accelerates
        # restart-in-place, like a Hydra follower's local changelog).
        self.count_local_ack = count_local_ack
        # True exactly when this quorum configuration is being adopted for
        # the first time over an existing single-location log: the local
        # history is authoritative and seeds the replicas.
        self.bootstrap_from_local = bootstrap_from_local
        if quorum > 1 + len(self.replicas):
            raise YtError(f"quorum {quorum} unreachable with "
                          f"{1 + len(self.replicas)} locations")
        self._records: list[dict] = []     # committed log (truncated w/ WAL)
        # Latched on the first failed local append: a local log that
        # skipped a record must take NO further appends, or it becomes a
        # holed non-prefix that recovery could adopt (losing the skipped
        # acked record) while still looking like a valid voter.  By
        # never appending past a failure the local log stays a true
        # prefix — shorter, but honest — and keeps its voting rights.
        # Cleared when _realign_local rewrites it whole.
        self._local_broken = False
        self.epoch: int = 0                # 0 = not yet acquired
        import uuid
        self.writer_id: str = uuid.uuid4().hex[:12]

    # -- epoch fencing ---------------------------------------------------------

    def _local_epoch_path(self) -> str:
        return self.local.path + ".epoch"

    def _local_stored_epoch(self) -> int:
        from ytsaurus_tpu.utils.diskio import read_epoch_file
        return read_epoch_file(self._local_epoch_path())[0]

    def _store_local_epoch(self, epoch: int) -> None:
        from ytsaurus_tpu.utils.diskio import write_epoch_file
        write_epoch_file(self._local_epoch_path(), epoch, self.writer_id)

    def _fence_body(self) -> dict:
        return {"epoch": self.epoch or None, "writer": self.writer_id}

    def acquire_epoch(self) -> int:
        """Claim write ownership: epoch = max(stored)+1, granted by a
        MAJORITY of locations (ref Hydra changelog acquisition).  Any
        previous writer's appends are rejected from then on — split-brain
        masters fence each other instead of interleaving one log."""
        observed = [self._local_stored_epoch()]
        for replica in self.replicas:
            try:
                body, _ = replica.channel.call(
                    "data_node", "journal_epoch",
                    {"journal": self.journal_name})
                observed.append(int(body.get("epoch", 0)))
            except YtError:
                pass
        candidate = max(observed) + 1
        self._store_local_epoch(candidate)
        if not self.replicas:
            # Single-location deployment: one process owns the file.
            self.epoch = candidate
            return candidate
        # Grants are counted over the SHARED remote locations only: two
        # candidate masters have disjoint local locations, so quorums
        # counting locals need not intersect.  A STRICT majority of the
        # remote locations must grant — for even remote counts that is
        # n/2+1 (2-of-2 for two journal nodes), so any two successful
        # acquisitions share a granting remote and the later epoch fences
        # the earlier writer there.  The cost is liveness: with two
        # remotes, one dead remote blocks acquisition.  That is the
        # trade the fencing guarantee requires (ceil(n/2) grants would
        # let two candidates win on disjoint halves and commit divergent
        # logs, each using own-local + its granted remote for appends).
        grants = 0
        acquire_body = {"journal": self.journal_name, "epoch": candidate,
                        "writer": self.writer_id}
        if self.lease_ttl > 0:
            acquire_body["lease_ttl"] = self.lease_ttl
        for replica in self.replicas:
            try:
                body, _ = replica.channel.call(
                    "data_node", "journal_acquire", dict(acquire_body),
                    idempotent=False)
                if body.get("granted"):
                    grants += 1
            except YtError:
                pass
        needed = len(self.replicas) // 2 + 1
        if grants < needed:
            raise YtError(
                f"epoch acquisition granted by {grants}/{needed} remote "
                "locations", code=EErrorCode.PeerUnavailable)
        self.epoch = candidate
        return candidate

    # -- replica sync ----------------------------------------------------------

    def _catch_up(self, replica: _Replica, _retry_ok: bool = True) -> bool:
        """Bring one replica to the full committed log; True on success."""
        try:
            if replica.synced_len is None:
                # Length + last-epoch probe; position- and prev-epoch-
                # checked appends guarantee that a location whose last
                # record's epoch matches ours at that position holds a
                # prefix of the committed log, so the pair decides
                # between catch-up and divergence reset.
                body, _ = replica.channel.call(
                    "data_node", "journal_count",
                    {"journal": self.journal_name})
                have = int(body.get("count", 0))
                if self._fork_visible(have, body.get("last_epoch")):
                    # Uncommitted tail or a stale writer's fork from a
                    # previous incarnation; discard and reseed.
                    replica.channel.call(
                        "data_node", "journal_reset",
                        {"journal": self.journal_name,
                         **self._fence_body()}, idempotent=False)
                    have = 0
                replica.synced_len = have
            if replica.synced_len < len(self._records):
                missing = self._records[replica.synced_len:]
                replica.channel.call(
                    "data_node", "journal_append",
                    {"journal": self.journal_name, "records": missing,
                     "position": replica.synced_len,
                     "prev_epoch": record_epoch(
                         self._records[replica.synced_len - 1])
                     if replica.synced_len else 0,
                     **self._fence_body()}, idempotent=False)
                replica.synced_len = len(self._records)
            return True
        except YtError as err:
            replica.synced_len = None
            if err.code == EErrorCode.JournalEpochFenced:
                if _retry_ok and self._maybe_reacquire():
                    return self._catch_up(replica, _retry_ok=False)
                raise self._fenced_error(err)
            if err.code == EErrorCode.JournalDivergence and _retry_ok:
                # The location's tail belongs to another writer's fork
                # (probe raced, or the location predates last-epoch
                # reporting): reset it and reseed in one more pass.
                try:
                    replica.channel.call(
                        "data_node", "journal_reset",
                        {"journal": self.journal_name,
                         **self._fence_body()}, idempotent=False)
                    replica.synced_len = 0
                    return self._catch_up(replica, _retry_ok=False)
                except YtError as reset_err:
                    logger.warning("journal divergence reset failed: %s",
                                   reset_err)
                    return False
            logger.warning("journal replica catch-up failed: %s", err)
            return False

    def _fork_visible(self, have: int, last_epoch) -> bool:
        """True when a location's (count, tail-epoch) probe reveals a log
        that is NOT a prefix of the committed log — a longer log, or an
        equal/shorter one whose tail record carries a different epoch.
        Shared by catch-up (reset + reseed) and orphaned-fence
        re-acquisition (refuse): the fencing argument needs both paths
        to agree on what counts as another writer's fork."""
        return have > len(self._records) or (
            last_epoch is not None and 0 < have and
            int(last_epoch) != record_epoch(self._records[have - 1]))

    # -- write path ------------------------------------------------------------

    def _maybe_reacquire(self) -> bool:
        """Recovery from an ORPHANED fence: a takeover that died between
        acquiring its epoch and reaching quorum leaves a higher epoch
        behind with NO records.  Re-acquire only on POSITIVE evidence: a
        strict majority of remote locations answered the probe and none
        holds records beyond our committed log.  An unreachable replica is
        inconclusive, not absolving — it may be the very location holding
        a new master's records, and a partitioned stale master that
        treated silence as absence would claim a higher epoch and resume
        writing.  Any longer log means a real new master: fail-stop."""
        probed = 0
        for replica in self.replicas:
            try:
                body, _ = replica.channel.call(
                    "data_node", "journal_count",
                    {"journal": self.journal_name})
                probed += 1
                if self._fork_visible(int(body.get("count", 0)),
                                      body.get("last_epoch")):
                    return False        # another writer's fork is visible
            except YtError:
                continue
        if probed < len(self.replicas) // 2 + 1:
            return False
        try:
            self.acquire_epoch()
            logger.warning("re-acquired journal %s after an orphaned "
                           "fence (epoch now %d)", self.journal_name,
                           self.epoch)
            return True
        except YtError:
            return False

    def _fenced_error(self, err: YtError) -> YtError:
        return YtError(
            "WAL writer fenced: a newer master acquired the journal; "
            "this master must stop writing",
            code=EErrorCode.JournalEpochFenced, inner_errors=[err])

    def append(self, record: dict) -> None:
        self._append_attempt(record, _retries=3)

    def _append_attempt(self, payload: dict, _retries: int) -> None:
        position = len(self._records)
        attempt_epoch = self.epoch
        # Tag the record with the writing epoch (a copy — the caller's
        # dict stays clean).  Tags are immutable once the record is
        # committed: later writers re-replicate it with its original
        # epoch, which is what recovery's newest-epoch rule relies on.
        # An IN-FLIGHT record is re-tagged if this writer re-acquires a
        # higher epoch mid-append (orphaned-fence recovery): epochs in
        # any log must be non-decreasing, or a fenced competitor's fork
        # could outrank a log holding acknowledged records.
        record = payload
        if isinstance(record, dict) and EPOCH_KEY not in record:
            record = dict(record)
            record[EPOCH_KEY] = attempt_epoch
        prev_epoch = record_epoch(self._records[-1]) if self._records else 0
        acks = 0
        errors = []
        local_appended = False
        if not self._local_broken:
            try:
                self.local.append(record)
                local_appended = True
                if self.count_local_ack:
                    acks += 1
            except OSError as exc:      # local disk failure
                self._local_broken = True
                errors.append(YtError(f"local WAL append failed: {exc}"))
        else:
            errors.append(YtError(
                "local WAL skipped: broken since an earlier append "
                "failure (awaiting realign)"))
        for replica in self.replicas:
            synced = replica.synced_len == position or \
                self._sync_to(replica, position)
            # _sync_to may have re-acquired a new epoch after an orphaned
            # fence; the in-flight record must carry the new epoch, so
            # restart the whole attempt before extending any log with a
            # stale-tagged record (epochs in a log must not regress).
            if self.epoch != attempt_epoch:
                return self._restart_append(payload, _retries, errors,
                                            local_appended)
            if not synced:
                continue
            try:
                replica.channel.call(
                    "data_node", "journal_append",
                    {"journal": self.journal_name, "records": [record],
                     "position": position, "prev_epoch": prev_epoch,
                     **self._fence_body()},
                    idempotent=False)
                replica.synced_len = position + 1
                acks += 1
            except YtError as err:
                replica.synced_len = None
                errors.append(err)
                if err.code == EErrorCode.JournalEpochFenced:
                    if _retries > 0 and self._maybe_reacquire():
                        return self._restart_append(payload, _retries,
                                                    errors, local_appended)
                    # A newer master owns this journal: fail-stop —
                    # assembling a quorum from the remaining locations
                    # would interleave two writers.
                    raise self._fenced_error(err)
        if acks < self.quorum:
            raise YtError(
                f"WAL append reached {acks}/{self.quorum} locations",
                code=EErrorCode.PeerUnavailable, inner_errors=errors[:3])
        self._records.append(record)
        _invariant_check("wal", self._records[-2:])  # tail: non-decreasing

    def _restart_append(self, payload: dict, retries: int, errors: list,
                        local_appended: bool) -> None:
        """Redo an append after a mid-append epoch re-acquisition: rewind
        every location that may hold the stale-tagged in-flight copy
        (local via an O(1) drop of the one record; replicas via the
        divergence/longer-log reset in catch-up) and retry under the new
        epoch.  Any disk failure here surfaces as YtError so the master's
        poison latch can stop serving a tree that is ahead of its WAL."""
        if retries <= 0:
            raise YtError(
                "WAL append could not settle under a stable epoch",
                code=EErrorCode.PeerUnavailable, inner_errors=errors[:3])
        if local_appended:
            try:
                self.local.drop_last()
            except OSError as exc:
                raise YtError(
                    f"local WAL rewind failed: {exc}",
                    code=EErrorCode.PeerUnavailable,
                    inner_errors=errors[:3])
        for replica in self.replicas:
            replica.synced_len = None
        return self._append_attempt(payload, _retries=retries - 1)

    def _sync_to(self, replica: _Replica, position: int) -> bool:
        """Catch a lagging replica up to `position` committed records."""
        if not self._catch_up(replica):
            return False
        return replica.synced_len == position

    # -- recovery --------------------------------------------------------------

    def recover(self) -> list[dict]:
        local_initialized = self.local.was_initialized
        local_records = self.local.recover()
        if self.bootstrap_from_local:
            # First adoption of this quorum config: local history (possibly
            # written under a local-only WAL) is authoritative.
            self._records = list(local_records)
            self.acquire_epoch()
            for replica in self.replicas:
                replica.synced_len = None
                self._catch_up(replica)
            return list(self._records)
        # Under remote-only quorum the local history holds no vote (a
        # successor's fresh local must not dilute the read quorum, and a
        # stale local must not stretch it).
        lists: list[Optional[list]] = [
            local_records if local_initialized and self.count_local_ack
            else None]
        if not local_initialized and local_records:
            raise YtError("local WAL has records but no init marker")
        for replica in self.replicas:
            try:
                body, _ = replica.channel.call(
                    "data_node", "journal_read",
                    {"journal": self.journal_name})
                if not body.get("initialized", True):
                    # A journal this data node never held must not vote a
                    # zero-length prefix (fresh node disk).
                    lists.append(None)
                    continue
                lists.append(list(body.get("records", [])))
            except YtError as err:
                logger.warning("journal location unreachable in recovery: "
                               "%s", err)
                lists.append(None)
        voting = sum(1 for lst in lists if lst is not None)
        n_voting = len(self.replicas) + (1 if self.count_local_ack else 0)
        # The read set must intersect EVERY write quorum (>= n-q+1
        # voters), or an acknowledged record held by exactly q voters
        # could be invisible to recovery and truncated (ADVICE r3 high:
        # ack on A+B, recovery via B+C used to adopt C's shorter log).
        needed = max(self.quorum, n_voting - self.quorum + 1)
        if voting < needed:
            raise YtError(
                f"cannot recover: {voting}/{needed} initialized WAL "
                "locations reachable (the read set must intersect every "
                "write quorum; a fresh/wiped location cannot vote — "
                "bring more journal owners online)",
                code=EErrorCode.PeerUnavailable)
        # Adopt the most up-to-date log among the voters: highest
        # (last-record epoch, length) — the VR view-change rule.  The
        # intersection guarantee puts every acknowledged record on at
        # least one voter, and no fenced writer's fork can carry a newer
        # epoch than the writer that fenced it, so the chosen log
        # contains every acknowledged record.  Its (possibly unacked)
        # tail is adopted wholesale and re-replicated below.
        def _up_to_date(lst: list) -> "tuple[int, int]":
            return (record_epoch(lst[-1]) if lst else 0, len(lst))

        best = max((lst for lst in lists if lst is not None),
                   key=_up_to_date)
        self._records = list(best)
        committed = len(self._records)
        self._realign_local()
        # Fence any previous writer BEFORE this incarnation writes (ref
        # Hydra changelog acquisition at epoch start).
        self.acquire_epoch()
        # Re-replicate the adopted log until >= quorum locations hold it:
        # an adopted tail held by one voter must be as durable as any
        # acked record before the master applies it.
        holders = 1 if self.count_local_ack else 0   # local just realigned
        for replica, lst in zip(self.replicas, lists[1:]):
            if lst is not None and lst == self._records[:len(lst)]:
                replica.synced_len = len(lst)
            else:
                replica.synced_len = None
            if replica.synced_len != committed:
                self._catch_up(replica)
            if replica.synced_len == committed:
                holders += 1
        if holders < self.quorum:
            raise YtError(
                f"recovered log replicated to only {holders}/{self.quorum} "
                "locations; refusing to serve from an under-replicated "
                "tail", code=EErrorCode.PeerUnavailable)
        _invariant_check("wal", self._records)
        return list(self._records)

    def extend(self, channels: list) -> int:
        """Grow the membership AFTER recovery: seed each new location with
        the full committed log (position-checked appends from 0), then
        adopt the larger quorum.  Seeding first keeps the invariant that
        >= quorum locations hold every committed record — adopting the
        quorum before seeding would make the existing history
        unrecoverable under the new threshold.  Returns the number of
        locations successfully added."""
        added = 0
        for channel in channels:
            replica = _Replica(channel)
            replica.synced_len = None
            self.replicas.append(replica)
            if self._catch_up(replica) and \
                    replica.synced_len == len(self._records):
                added += 1
            else:
                self.replicas.pop()
        if added:
            locations = len(self.replicas) + \
                (1 if self.count_local_ack else 0)
            self.quorum = locations // 2 + 1
        return added

    def _realign_local(self) -> None:
        self.local.reset()
        for record in self._records:
            self.local.append(record)
        self._local_broken = False      # whole again (a full rewrite)

    def reset(self) -> None:
        self.local.reset()
        self._local_broken = False      # empty log is a valid prefix
        self._records = []
        for replica in self.replicas:
            try:
                replica.channel.call(
                    "data_node", "journal_reset",
                    {"journal": self.journal_name, **self._fence_body()},
                    idempotent=False)
                replica.synced_len = 0
            except YtError:
                replica.synced_len = None

    def close(self) -> None:
        self.local.close()

    # -- replicated snapshots --------------------------------------------------

    def store_snapshot(self, seq: int, blob: bytes) -> None:
        """Replicate the snapshot to enough journal locations BEFORE the
        journals are truncated: quorum-1 remotes when the local copy
        counts toward the quorum, a full remote quorum otherwise."""
        acks = 0
        errors = []
        for replica in self.replicas:
            try:
                replica.channel.call(
                    "data_node", "snapshot_put",
                    {"name": self.journal_name, "seq": seq,
                     **self._fence_body()}, [blob],
                    idempotent=False)
                acks += 1
            except YtError as err:
                errors.append(err)
        needed = self.quorum - 1 if self.count_local_ack else self.quorum
        if acks < needed:
            raise YtError(
                f"snapshot replication reached {acks}/{needed} "
                "remote locations", code=EErrorCode.PeerUnavailable,
                inner_errors=errors[:3])

    def fetch_snapshot(self) -> "tuple[int, bytes] | None":
        """Newest snapshot available on any journal location."""
        best: "tuple[int, bytes] | None" = None
        for replica in self.replicas:
            try:
                body, attachments = replica.channel.call(
                    "data_node", "snapshot_get",
                    {"name": self.journal_name})
                if body.get("seq") is None:
                    continue
                seq = int(body["seq"])
                if best is None or seq > best[0]:
                    best = (seq, attachments[0])
            except YtError:
                continue
        return best
