"""Typed wire bodies: YSON with the Python str/bytes distinction preserved.

Binary YSON has one string type; the client API distinguishes text
(attribute values, paths) from binary (row string values).  On the wire,
bytes values are wrapped as {"$b": <raw>}; every unwrapped string decodes
back to str (utf-8).  A literal single-key {"$b": ...} dict is escaped as
{"$$b": ...}.
"""

from __future__ import annotations


def encode_body(value):
    if isinstance(value, bytes):
        return {"$b": value}
    if isinstance(value, dict):
        out = {}
        for k, v in value.items():
            if isinstance(k, str) and k.startswith("$") and len(value) == 1:
                k = "$" + k
            out[k] = encode_body(v)
        return out
    if isinstance(value, (list, tuple)):
        return [encode_body(v) for v in value]
    return value


def decode_body(value):
    if isinstance(value, dict):
        if len(value) == 1:
            ((k, v),) = value.items()
            key = k.decode() if isinstance(k, bytes) else k
            if key == "$b":
                return v if isinstance(v, bytes) else str(v).encode()
            if isinstance(key, str) and key.startswith("$$"):
                return {key[1:]: decode_body(v)}
        return {(k.decode() if isinstance(k, bytes) else k): decode_body(v)
                for k, v in value.items()}
    if isinstance(value, list):
        return [decode_body(v) for v in value]
    if isinstance(value, bytes):
        return value.decode("utf-8")
    return value


def wire_text(v) -> str:
    """Wire value to str (strings arrive as utf-8 str already; bytes from
    legacy peers decode)."""
    return v.decode("utf-8", "replace") if isinstance(v, bytes) else str(v)
