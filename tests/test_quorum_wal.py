"""Quorum WAL unit tests (fake journal channels, no processes).

Covers the Hydra-quorum-changelog semantics the multi-process cluster
relies on: majority-ack appends, refusal below quorum, longest-majority-
prefix recovery, replica realignment.
"""

import pytest

from ytsaurus_tpu.cypress.quorum import EPOCH_KEY, QuorumWal, record_epoch
from ytsaurus_tpu.errors import EErrorCode, YtError


class FakeJournalChannel:
    """In-memory data_node journal endpoint with the REAL position-check
    and prev-epoch-check semantics (a non-contiguous or tail-divergent
    append is rejected, like DataNodeService.journal_append)."""

    def __init__(self):
        self.records = []
        self.snapshots = {}
        self.down = False
        self.epoch = 0
        self.writer = ""

    def _last_epoch(self) -> int:
        return record_epoch(self.records[-1]) if self.records else 0

    def _check(self, body):
        epoch = body.get("epoch")
        if epoch is None:
            return
        writer = body.get("writer") or ""
        if epoch < self.epoch or (epoch == self.epoch and self.writer
                                  and writer != self.writer):
            raise YtError("fenced", code=EErrorCode.JournalEpochFenced,
                          attributes={"stored_epoch": self.epoch})
        if epoch > self.epoch:
            self.epoch, self.writer = epoch, writer

    def call(self, service, method, body=None, attachments=(), **kw):
        if self.down:
            raise YtError("down", code=EErrorCode.TransportError)
        assert service == "data_node"
        if method == "journal_acquire":
            if body["epoch"] <= self.epoch:
                return {"granted": False, "epoch": self.epoch}, []
            self.epoch = body["epoch"]
            self.writer = body.get("writer") or ""
            return {"granted": True, "epoch": self.epoch}, []
        if method == "journal_epoch":
            return {"epoch": self.epoch}, []
        if method == "journal_append":
            self._check(body)
            position = body.get("position")
            if position is not None and position != len(self.records):
                raise YtError("position mismatch",
                              code=EErrorCode.JournalPositionMismatch,
                              attributes={"expected": len(self.records)})
            prev = body.get("prev_epoch")
            if prev is not None and prev != self._last_epoch():
                raise YtError("tail diverged",
                              code=EErrorCode.JournalDivergence)
            self.records.extend(body["records"])
            return {"count": len(self.records)}, []
        if method == "journal_read":
            return {"records": list(self.records)}, []
        if method == "journal_count":
            return {"count": len(self.records),
                    "last_epoch": self._last_epoch()}, []
        if method == "journal_reset":
            self._check(body)
            self.records.clear()
            return {}, []
        if method == "snapshot_put":
            self.snapshots["snap"] = (body["seq"], attachments[0])
            return {}, []
        if method == "snapshot_get":
            if "snap" not in self.snapshots:
                return {"seq": None}, []
            seq, blob = self.snapshots["snap"]
            return {"seq": seq}, [blob]
        raise AssertionError(method)


@pytest.fixture()
def wal3(tmp_path):
    remotes = [FakeJournalChannel(), FakeJournalChannel()]
    wal = QuorumWal(str(tmp_path / "wal.log"), "master_wal", remotes,
                    quorum=2, bootstrap_from_local=True)
    wal.recover()
    return wal, remotes


def test_append_reaches_all_locations(wal3):
    wal, remotes = wal3
    wal.append({"op": "set", "args": {"path": "//a"}})
    assert len(remotes[0].records) == 1
    assert len(remotes[1].records) == 1


def test_append_tolerates_one_location_down(wal3):
    wal, remotes = wal3
    remotes[0].down = True
    wal.append({"op": "set", "args": {"n": 1}})   # local + remote1 = 2/2
    assert len(remotes[1].records) == 1


def test_append_refuses_below_quorum(tmp_path):
    remotes = [FakeJournalChannel(), FakeJournalChannel()]
    wal = QuorumWal(str(tmp_path / "w.log"), "j", remotes, quorum=3,
                    bootstrap_from_local=True)
    wal.recover()
    remotes[0].down = True
    with pytest.raises(YtError) as ei:
        wal.append({"op": "set"})
    assert ei.value.code == EErrorCode.PeerUnavailable


def test_recover_from_remote_majority_after_local_loss(tmp_path):
    remotes = [FakeJournalChannel(), FakeJournalChannel()]
    wal = QuorumWal(str(tmp_path / "w.log"), "j", remotes, quorum=2,
                    bootstrap_from_local=True)
    wal.recover()
    for i in range(5):
        wal.append({"op": "set", "args": {"n": i}})
    wal.close()
    # Local disk dies: a fresh local path, same remotes.
    wal2 = QuorumWal(str(tmp_path / "fresh.log"), "j", remotes, quorum=2)
    records = wal2.recover()
    assert [r["args"]["n"] for r in records] == [0, 1, 2, 3, 4]


def test_recover_discards_unconfirmed_tail(tmp_path):
    remotes = [FakeJournalChannel(), FakeJournalChannel()]
    wal = QuorumWal(str(tmp_path / "w.log"), "j", remotes, quorum=2,
                    bootstrap_from_local=True)
    wal.recover()
    for i in range(3):
        wal.append({"op": "set", "args": {"n": i}})
    # One replica got an extra record the quorum never confirmed.
    remotes[0].records.append({"op": "set", "args": {"n": 99}})
    wal2 = QuorumWal(str(tmp_path / "w.log"), "j", remotes, quorum=2)
    records = wal2.recover()
    assert [r["args"]["n"] for r in records] == [0, 1, 2]
    # Realignment resets the divergent replica to the committed log.
    assert [r["args"]["n"] for r in remotes[0].records] == [0, 1, 2]


def test_recover_catches_up_lagging_replica(tmp_path):
    remotes = [FakeJournalChannel(), FakeJournalChannel()]
    wal = QuorumWal(str(tmp_path / "w.log"), "j", remotes, quorum=2,
                    bootstrap_from_local=True)
    wal.recover()
    for i in range(4):
        wal.append({"op": "set", "args": {"n": i}})
    remotes[1].records = remotes[1].records[:1]     # lagging replica
    wal2 = QuorumWal(str(tmp_path / "w.log"), "j", remotes, quorum=2)
    records = wal2.recover()
    assert len(records) == 4                         # local+r0 confirm all
    assert [r["args"]["n"] for r in remotes[1].records] == [0, 1, 2, 3]


def test_recover_refuses_below_quorum(tmp_path):
    remotes = [FakeJournalChannel(), FakeJournalChannel()]
    wal = QuorumWal(str(tmp_path / "w.log"), "j", remotes, quorum=2,
                    bootstrap_from_local=True)
    wal.recover()
    wal.append({"op": "set"})
    remotes[0].down = True
    remotes[1].down = True
    wal2 = QuorumWal(str(tmp_path / "w.log"), "j", remotes, quorum=2)
    with pytest.raises(YtError):
        wal2.recover()


def test_no_holes_replica_down_then_up(tmp_path):
    """The reviewer's scenario: a replica that missed a record must NOT
    accept later appends (hole) and must not cause loss of a
    quorum-acknowledged record in recovery.  Three remotes: takeover
    needs a strict majority of remote locations, so recovery with one
    remote down requires an odd remote count to stay live."""
    remotes = [FakeJournalChannel(), FakeJournalChannel(),
               FakeJournalChannel()]
    wal = QuorumWal(str(tmp_path / "w.log"), "j", remotes, quorum=2,
                    bootstrap_from_local=True)
    wal.recover()
    remotes[0].down = True
    wal.append({"op": "set", "args": {"n": 1}})     # local + B + C ack
    remotes[0].down = False
    wal.append({"op": "set", "args": {"n": 2}})     # A must catch up first
    # A holds the full prefix, not a holey [r2].
    assert [r["args"]["n"] for r in remotes[0].records] == [1, 2]
    # Recovery with B down: local + A + C still confirm both records and
    # grant the takeover (2-of-3 strict remote majority).
    remotes[1].down = True
    wal2 = QuorumWal(str(tmp_path / "w.log"), "j", remotes, quorum=2)
    records = wal2.recover()
    assert [r["args"]["n"] for r in records] == [1, 2]


def test_unsynced_replica_earns_no_quorum_credit(tmp_path):
    remotes = [FakeJournalChannel(), FakeJournalChannel()]
    wal = QuorumWal(str(tmp_path / "w.log"), "j", remotes, quorum=3,
                    bootstrap_from_local=True)
    wal.recover()
    wal.append({"op": "set", "args": {"n": 1}})
    # A silently loses its log AND rejects catch-up: no ack possible.
    remotes[0].records.clear()
    remotes[0].down = True
    with pytest.raises(YtError):
        wal.append({"op": "set", "args": {"n": 2}})  # 2/3 < quorum 3


def test_snapshot_survives_local_disk_loss(tmp_path):
    from ytsaurus_tpu.cypress.master import Master
    remotes = [FakeJournalChannel(), FakeJournalChannel()]
    m1_dir = tmp_path / "m1"
    wal = QuorumWal(str(m1_dir / "changelog.log"), "j", remotes, quorum=2,
                    bootstrap_from_local=True)
    m1_dir.mkdir()
    m1 = Master(str(m1_dir), wal=wal)
    m1.commit_mutation("create", path="//a", type="map_node")
    m1.commit_mutation("set", path="//a/@x", value=7)
    m1.build_snapshot()
    m1.commit_mutation("set", path="//a/@y", value=8)
    # Total local disk loss: fresh dir, same remote journal locations.
    m2_dir = tmp_path / "m2"
    m2_dir.mkdir()
    wal2 = QuorumWal(str(m2_dir / "changelog.log"), "j", remotes, quorum=2)
    m2 = Master(str(m2_dir), wal=wal2)
    assert m2.tree.get("//a/@x") == 7
    assert m2.tree.get("//a/@y") == 8


class FakeJournalChannelV2(FakeJournalChannel):
    """Adds the initialized-tracking + journal_count surface."""

    def __init__(self):
        super().__init__()
        self.initialized = False

    def call(self, service, method, body=None, attachments=(), **kw):
        if self.down:
            raise YtError("down", code=EErrorCode.TransportError)
        if method == "journal_read":
            return {"records": list(self.records),
                    "initialized": self.initialized}, []
        if method == "journal_count":
            return {"count": len(self.records),
                    "initialized": self.initialized,
                    "last_epoch": self._last_epoch()}, []
        if method == "journal_append":
            self.initialized = True
        if method == "journal_reset":
            self.initialized = True
        return super().call(service, method, body, attachments, **kw)


def test_fresh_remote_journals_cannot_outvote_local_history(tmp_path):
    """Reviewer scenario A: local-only history upgraded to quorum must NOT
    be truncated by the empty (uninitialized) remote journals."""
    path = str(tmp_path / "w.log")
    from ytsaurus_tpu.cypress.quorum import LocalWal
    lw = LocalWal(path)
    lw.recover()
    for i in range(4):
        lw.append({"op": "set", "args": {"n": i}})
    lw.close()
    remotes = [FakeJournalChannelV2(), FakeJournalChannelV2()]
    wal = QuorumWal(path, "j", remotes, quorum=2, bootstrap_from_local=True)
    records = wal.recover()
    assert [r["args"]["n"] for r in records] == [0, 1, 2, 3]
    # Replicas got seeded.
    assert [r["args"]["n"] for r in remotes[0].records] == [0, 1, 2, 3]


def test_wiped_local_cannot_vote_empty_prefix(tmp_path):
    """Reviewer scenario B: a replaced local disk must not outvote a
    replica holding acknowledged records; with only one initialized
    replica reachable, recovery REFUSES instead of losing data."""
    path = str(tmp_path / "w.log")
    remotes = [FakeJournalChannelV2(), FakeJournalChannelV2()]
    wal = QuorumWal(path, "j", remotes, quorum=2,
                    bootstrap_from_local=True)
    wal.recover()
    wal.append({"op": "set", "args": {"n": 1}})
    # Wipe local entirely (changelog + init marker); one replica down.
    import os
    os.unlink(path)
    os.unlink(path + ".init")
    remotes[1].down = True
    fresh = QuorumWal(str(tmp_path / "w2.log"), "j", remotes, quorum=2)
    with pytest.raises(YtError):
        fresh.recover()
    # With both replicas up, the acknowledged record survives.
    remotes[1].down = False
    fresh2 = QuorumWal(str(tmp_path / "w3.log"), "j", remotes, quorum=2)
    assert [r["args"]["n"] for r in fresh2.recover()] == [1]


def test_epoch_fencing_stops_stale_writer(tmp_path):
    """A second master acquiring the journals fences the first: its next
    append fails fast with JournalEpochFenced (fail-stop, no interleaved
    log) — ref Hydra changelog acquisition."""
    remotes = [FakeJournalChannel(), FakeJournalChannel()]
    old = QuorumWal(str(tmp_path / "old.log"), "j", remotes, quorum=2,
                    bootstrap_from_local=True)
    old.recover()
    old.append({"op": "set", "args": {"n": 1}})
    assert old.epoch == 1
    # New master takes over the SAME remote journals.
    new = QuorumWal(str(tmp_path / "new.log"), "j", remotes, quorum=2)
    new.recover()
    assert new.epoch == 2
    assert [r["args"]["n"] for r in new._records] == [1]
    new.append({"op": "set", "args": {"n": 2}})
    # The stale writer is rejected immediately.
    with pytest.raises(YtError) as err:
        old.append({"op": "set", "args": {"n": 99}})
    assert err.value.code == EErrorCode.JournalEpochFenced
    # The log holds ONLY the new master's history.
    assert [r["args"]["n"] for r in remotes[0].records] == [1, 2]


def test_epoch_acquisition_needs_remote_grants(tmp_path):
    remotes = [FakeJournalChannel(), FakeJournalChannel(),
               FakeJournalChannel()]
    # One of THREE replicas down: acquisition still succeeds (2-of-3 is
    # a strict remote majority) and the returning replica learns the
    # epoch from the first append that reaches it.
    remotes[0].down = True
    wal = QuorumWal(str(tmp_path / "w.log"), "j", remotes, quorum=2,
                    bootstrap_from_local=True)
    wal.recover()
    wal.append({"op": "set", "args": {"n": 1}})
    remotes[0].down = False
    wal.append({"op": "set", "args": {"n": 2}})
    assert remotes[0].epoch == wal.epoch
    # Half the remotes down (1 of 2): NOT a strict majority — takeover
    # refused even though one grant is reachable (two candidates on
    # disjoint halves must never both win).
    remotes2 = [FakeJournalChannel(), FakeJournalChannel()]
    remotes2[0].down = True
    wal2 = QuorumWal(str(tmp_path / "w2.log"), "j", remotes2, quorum=2,
                     bootstrap_from_local=True)
    with pytest.raises(YtError):
        wal2.recover()
    # Every replica down: takeover refused.
    remotes3 = [FakeJournalChannel(), FakeJournalChannel()]
    for r in remotes3:
        r.down = True
    wal3 = QuorumWal(str(tmp_path / "w3.log"), "j", remotes3, quorum=2,
                     bootstrap_from_local=True)
    with pytest.raises(YtError):
        wal3.recover()


def test_orphaned_fence_recovers(tmp_path):
    """A takeover that dies between epoch acquisition and writing leaves
    an orphaned higher epoch; the active master re-acquires above it and
    keeps serving instead of latching read-only."""
    remotes = [FakeJournalChannel(), FakeJournalChannel()]
    active = QuorumWal(str(tmp_path / "a.log"), "j", remotes, quorum=2,
                       bootstrap_from_local=True)
    active.recover()
    active.append({"op": "set", "args": {"n": 1}})
    # Orphaned acquisition: epoch bumped, but the candidate never writes.
    for r in remotes:
        r.epoch, r.writer = active.epoch + 1, "dead-candidate"
    active.append({"op": "set", "args": {"n": 2}})      # self-heals
    assert active.epoch > 2
    assert [r["args"]["n"] for r in remotes[0].records] == [1, 2]


def test_stale_divergence_reset_is_fenced(tmp_path):
    """A stale master's catch-up must not journal_reset away the new
    master's committed records (the reset carries the epoch too)."""
    remotes = [FakeJournalChannel(), FakeJournalChannel()]
    old = QuorumWal(str(tmp_path / "old.log"), "j", remotes, quorum=2,
                    bootstrap_from_local=True)
    old.recover()
    old.append({"op": "set", "args": {"n": 1}})
    new = QuorumWal(str(tmp_path / "new.log"), "j", remotes, quorum=2)
    new.recover()
    new.append({"op": "set", "args": {"n": 2}})
    # The stale master believes fewer records exist; its catch-up sees a
    # "longer" remote log and tries to reset it — fenced, and because the
    # new master HAS written, re-acquisition is refused → fail-stop.
    remotes[0].records_longer_than = None
    for r in old.replicas:
        r.synced_len = None
    with pytest.raises(YtError) as err:
        old.append({"op": "set", "args": {"n": 99}})
    assert err.value.code in (EErrorCode.JournalEpochFenced,
                              EErrorCode.PeerUnavailable)
    # New master's records intact on both replicas.
    assert [r["args"]["n"] for r in remotes[0].records] == [1, 2]
    assert [r["args"]["n"] for r in remotes[1].records] == [1, 2]


def test_partitioned_stale_master_cannot_reacquire(tmp_path):
    """ADVICE r2: a fenced stale master that cannot probe a MAJORITY of
    remotes must fail-stop, not re-acquire — the unreachable replica may
    be the very location holding the new master's records."""
    remotes = [FakeJournalChannel(), FakeJournalChannel()]
    old = QuorumWal(str(tmp_path / "old.log"), "j", remotes, quorum=2,
                    bootstrap_from_local=True)
    old.recover()
    old.append({"op": "set", "args": {"n": 1}})
    # A new writer acquired epoch 2 everywhere but its records landed
    # only on replica B — which the stale master cannot reach.
    for r in remotes:
        r.epoch, r.writer = old.epoch + 1, "new-master"
    remotes[1].records.append({"op": "set", "args": {"n": 2}})
    remotes[1].down = True
    # Stale master: append is fenced on A; the reacquire probe reaches
    # only 1/2 remotes (not a majority) -> inconclusive -> fail-stop.
    with pytest.raises(YtError) as err:
        old.append({"op": "set", "args": {"n": 99}})
    assert err.value.code in (EErrorCode.JournalEpochFenced,
                              EErrorCode.PeerUnavailable)
    # The new master's record on B survives untouched.
    assert [r["args"]["n"] for r in remotes[1].records] == [1, 2]


def test_membership_extend_seeds_before_quorum_bump(tmp_path):
    """extend() grows the journal set after recovery: new locations get
    the full committed log first, then the larger quorum applies, so a
    degraded bootstrap membership is never pinned forever."""
    first = [FakeJournalChannel()]
    wal = QuorumWal(str(tmp_path / "w.log"), "j", first, quorum=1,
                    bootstrap_from_local=True)
    wal.recover()
    for i in range(3):
        wal.append({"op": "set", "args": {"n": i}})
    extra = [FakeJournalChannel(), FakeJournalChannel()]
    assert wal.extend(extra) == 2
    assert wal.quorum == 3                      # majority of 4 locations
    for r in extra:
        assert [x["args"]["n"] for x in r.records] == [0, 1, 2]
    wal.append({"op": "set", "args": {"n": 3}})
    assert [x["args"]["n"] for x in extra[0].records] == [0, 1, 2, 3]
    # An unreachable candidate is NOT adopted (no phantom quorum member).
    dead = FakeJournalChannel()
    dead.down = True
    assert wal.extend([dead]) == 0
    assert len(wal.replicas) == 3


def test_remote_only_quorum_survives_leader_loss(tmp_path):
    """Election-mode quorum math: with count_local_ack=False an acked
    record lives on a strict majority of REMOTES, so a successor master
    recovering with a FRESH local location cannot lose it.  (With
    local-credit quorums the same scenario drops the record: ack =
    local + 2-of-3 remotes, but the successor reads only the remotes.)"""
    remotes = [FakeJournalChannelV2(), FakeJournalChannelV2(),
               FakeJournalChannelV2()]
    a = QuorumWal(str(tmp_path / "a.log"), "j", remotes, quorum=2,
                  count_local_ack=False, bootstrap_from_local=True)
    a.recover()
    remotes[2].down = True                   # one remote out
    a.append({"op": "set", "args": {"n": 1}})    # acked: r0 + r1 = 2/2
    # Leader host dies entirely; lagging remote returns.
    remotes[2].down = False
    b = QuorumWal(str(tmp_path / "b.log"), "j", remotes, quorum=2,
                  count_local_ack=False)
    records = b.recover()
    assert [r["args"]["n"] for r in records] == [1]


def test_recover_preserves_acked_record_on_partial_read(tmp_path):
    """ADVICE r3 high: with 3 remotes (quorum 2), a record acked by A+B
    while C lags, followed by leader death and recovery reaching only
    B+C, must NOT truncate the acked record (the old quorum-th-longest
    rule adopted C's shorter log and journal_reset B — destroying the
    only surviving reachable copy)."""
    remotes = [FakeJournalChannelV2(), FakeJournalChannelV2(),
               FakeJournalChannelV2()]          # A, B, C
    a = QuorumWal(str(tmp_path / "a.log"), "j", remotes, quorum=2,
                  count_local_ack=False, bootstrap_from_local=True)
    a.recover()
    a.append({"op": "set", "args": {"n": 1}})       # all three
    remotes[2].down = True                          # C lags
    a.append({"op": "set", "args": {"n": 2}})       # acked: A + B
    # Leader dies; C returns but A becomes unreachable.
    remotes[2].down = False
    remotes[0].down = True
    b = QuorumWal(str(tmp_path / "b.log"), "j", remotes, quorum=2,
                  count_local_ack=False)
    records = b.recover()
    assert [r["args"]["n"] for r in records] == [1, 2]
    # B keeps both records; C is caught up, not the other way round.
    assert [r["args"]["n"] for r in remotes[1].records] == [1, 2]
    assert [r["args"]["n"] for r in remotes[2].records] == [1, 2]


def test_recover_prefers_newest_epoch_over_stale_fork(tmp_path):
    """A fenced writer's unacked fork (older epoch, possibly longer) must
    lose recovery to the newest-epoch log, and the forked location is
    reset + reseeded — records carry epoch tags precisely for this."""
    remotes = [FakeJournalChannelV2(), FakeJournalChannelV2(),
               FakeJournalChannelV2()]          # A, B, C
    w1 = QuorumWal(str(tmp_path / "w1.log"), "j", remotes, quorum=2,
                   count_local_ack=False, bootstrap_from_local=True)
    w1.recover()
    w1.append({"op": "set", "args": {"n": 1}})
    # W1's dying append lands only on A (unacked fork, epoch 1).
    remotes[0].records.append(
        {"op": "set", "args": {"n": 88}, EPOCH_KEY: w1.epoch})
    # W2 takes over with A unreachable, commits its own record (epoch 2).
    remotes[0].down = True
    w2 = QuorumWal(str(tmp_path / "w2.log"), "j", remotes, quorum=2,
                   count_local_ack=False)
    w2.recover()
    w2.append({"op": "set", "args": {"n": 2}})
    # Full recovery with every location reachable: the epoch-2 log wins
    # even though A's fork has equal length; A is reset and reseeded.
    remotes[0].down = False
    w3 = QuorumWal(str(tmp_path / "w3.log"), "j", remotes, quorum=2,
                   count_local_ack=False)
    records = w3.recover()
    assert [r["args"]["n"] for r in records] == [1, 2]
    assert [r["args"]["n"] for r in remotes[0].records] == [1, 2]


def test_append_repairs_equal_length_fork(tmp_path):
    """Steady state: a location holding an equal-length stale-epoch fork
    is detected by the count+tail-epoch probe on the next append and is
    reset + reseeded instead of silently extending the fork."""
    remotes = [FakeJournalChannelV2(), FakeJournalChannelV2(),
               FakeJournalChannelV2()]
    w1 = QuorumWal(str(tmp_path / "w1.log"), "j", remotes, quorum=2,
                   count_local_ack=False, bootstrap_from_local=True)
    w1.recover()
    w1.append({"op": "set", "args": {"n": 1}})
    remotes[0].records.append(
        {"op": "set", "args": {"n": 88}, EPOCH_KEY: w1.epoch})
    remotes[0].down = True
    w2 = QuorumWal(str(tmp_path / "w2.log"), "j", remotes, quorum=2,
                   count_local_ack=False)
    w2.recover()
    w2.append({"op": "set", "args": {"n": 2}})      # B, C at epoch-2 log
    # A returns holding [1, 88(e1)] — same length as the committed log.
    remotes[0].down = False
    w2.append({"op": "set", "args": {"n": 3}})
    assert [r["args"]["n"] for r in remotes[0].records] == [1, 2, 3]


def test_recover_adopts_newest_epoch_unacked_tail(tmp_path):
    """An unacknowledged tail from the NEWEST epoch may be adopted (VR
    semantics: it becomes committed retroactively — sound because no
    conflicting record was ever acknowledged) and recovery re-replicates
    it to a full quorum before returning."""
    remotes = [FakeJournalChannelV2(), FakeJournalChannelV2(),
               FakeJournalChannelV2()]
    w1 = QuorumWal(str(tmp_path / "w1.log"), "j", remotes, quorum=2,
                   count_local_ack=False, bootstrap_from_local=True)
    w1.recover()
    w1.append({"op": "set", "args": {"n": 1}})
    # The writer's dying append reached only A — same (newest) epoch.
    remotes[0].records.append(
        {"op": "set", "args": {"n": 2}, EPOCH_KEY: w1.epoch})
    w2 = QuorumWal(str(tmp_path / "w2.log"), "j", remotes, quorum=2,
                   count_local_ack=False)
    records = w2.recover()
    assert [r["args"]["n"] for r in records] == [1, 2]
    # The adopted tail now lives on a full quorum.
    for r in remotes:
        assert [x["args"]["n"] for x in r.records] == [1, 2]


def test_local_append_failure_never_holes_the_local_log(tmp_path):
    """A transient local-disk append failure must not let later appends
    land past the gap: a holed local log is a non-prefix that recovery
    could adopt (dropping the skipped acked record).  After a failure
    the local location takes no appends until realigned — shorter but
    honest — and recovery still preserves every acked record."""
    path = str(tmp_path / "w.log")
    remotes = [FakeJournalChannelV2(), FakeJournalChannelV2(),
               FakeJournalChannelV2()]
    wal = QuorumWal(path, "j", remotes, quorum=2,
                    bootstrap_from_local=True)
    wal.recover()

    def flaky(record):
        raise OSError("disk error")

    orig = wal.local.append
    wal.local.append = flaky
    wal.append({"op": "set", "args": {"n": 1}})  # acked by the remotes
    wal.local.append = orig
    wal.append({"op": "set", "args": {"n": 2}})  # local must NOT take it
    wal.close()
    from ytsaurus_tpu.cypress.master import Changelog
    records, _ = Changelog.read_all(path)
    assert records == []        # a true (empty) prefix, not [r2]
    # Crash; recovery with one remote down still keeps both records
    # (local's short prefix cannot outvote a remote's full log).
    remotes[2].down = True
    wal2 = QuorumWal(path, "j", remotes, quorum=2)
    assert [r["args"]["n"] for r in wal2.recover()] == [1, 2]
    # And the local location is whole again afterwards.
    records, _ = Changelog.read_all(path)
    assert [r["args"]["n"] for r in records] == [1, 2]


def test_remote_only_quorum_append_needs_remote_majority(tmp_path):
    remotes = [FakeJournalChannelV2(), FakeJournalChannelV2(),
               FakeJournalChannelV2()]
    wal = QuorumWal(str(tmp_path / "w.log"), "j", remotes, quorum=2,
                    count_local_ack=False, bootstrap_from_local=True)
    wal.recover()
    remotes[0].down = True
    remotes[1].down = True
    # Local append alone earns no credit: 1-of-3 remotes < 2.
    with pytest.raises(YtError) as err:
        wal.append({"op": "set", "args": {"n": 1}})
    assert err.value.code == EErrorCode.PeerUnavailable
