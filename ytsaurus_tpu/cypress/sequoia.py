"""Sequoia: Cypress metadata backed by ground dynamic tables.

Ref: yt/yt/server/master/sequoia_server/ + the ground tables under
yt/yt/ytlib/sequoia_client/ and the read path in
yt/yt/server/cypress_proxy/ — the reference's escape from
all-metadata-in-one-master's-RAM: node records move into distributed
dynamic tables ("ground" tables), so the metadata plane scales like any
other table and masters become coordinators over it.

Two slices are realized, exactly as the reference staged them:

slice 1 — RESOLVE: `//sys/sequoia/resolve` maps path → (node id, type);
  `resolve()` is a point lookup instead of a tree walk.  Records store
  the RAW node at each path — a link row carries the link's own id and
  type "link", so link TRAVERSAL stays a resolver-layer concern and
  removing a link's target never invalidates the link's row.

slice 2 — PER-OBJECT RECORDS + the cypress-proxy READ PATH:
  `//sys/sequoia/nodes` (node id → type, attributes, value) and
  `//sys/sequoia/children` ((parent id, child key) → child id) mirror
  the per-object state, and `read_get`/`read_list`/`read_exists`/
  `read_attribute` serve Cypress reads ENTIRELY from the tables — no
  master-tree access — the cypress_proxy/actions.cpp serving model.
  Transaction aborts no longer force a full resync: the master's undo
  replay reports exactly which paths it touched (abort-scoped undo),
  and only those subtrees resynchronize.

`verify()` proves table/tree agreement across all three tables — the
consistency invariant Sequoia's migration hinges on.
"""

from __future__ import annotations

from typing import Iterator, Optional

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.yson import dumps as yson_dumps
from ytsaurus_tpu.yson import loads as yson_loads

RESOLVE_PATH = "//sys/sequoia/resolve"
NODES_PATH = "//sys/sequoia/nodes"
CHILDREN_PATH = "//sys/sequoia/children"

RESOLVE_SCHEMA = TableSchema.make([
    ("path", "string", "ascending"),
    ("node_id", "string"),
    ("node_type", "string"),
    ("revision", "int64"),
], unique_keys=True)

NODES_SCHEMA = TableSchema.make([
    ("node_id", "string", "ascending"),
    ("node_type", "string"),
    ("path", "string"),
    ("attrs", "string"),            # yson map
    ("value", "string"),            # yson payload (documents/scalars)
    ("revision", "int64"),
], unique_keys=True)

CHILDREN_SCHEMA = TableSchema.make([
    ("parent_id", "string", "ascending"),
    ("child_key", "string", "ascending"),
    ("child_id", "string"),
], unique_keys=True)

# Subtree whose mutations must NOT be mirrored (the ground tables' own
# home — mirroring it would recurse through their mount metadata).
_EXCLUDED_ROOT = "//sys/sequoia"


def _excluded(path: str) -> bool:
    return path == _EXCLUDED_ROOT or \
        path.startswith(_EXCLUDED_ROOT + "/")


def _text(value) -> str:
    return value.decode() if isinstance(value, bytes) else value


def _canon(path: str) -> "Optional[str]":
    """Canonical table key for a client-supplied path ('//a//b' and
    '//a/b' address the same node and must share one row)."""
    from ytsaurus_tpu.cypress.tree import parse_ypath
    try:
        tokens, attr = parse_ypath(path)
    except YtError:
        return None
    if attr is not None or not tokens:
        return None
    return "//" + "/".join(tokens)


def _safe_yson(value) -> bytes:
    """YSON-encode, replacing non-encodable leaves with an opaque marker
    (attributes normally arrive through the WAL and ARE encodable; this
    guards in-process clients attaching live objects)."""
    try:
        return yson_dumps(value)
    except TypeError:
        if isinstance(value, dict):
            return yson_dumps({k: yson_loads(_safe_yson(v))
                               for k, v in value.items()})
        return yson_dumps({"$opaque": repr(value)})


def _check_id(node_id: str) -> str:
    """Ids are spliced into QL filters; refuse anything quote-capable."""
    if not node_id or not all(c.isalnum() or c in "-_" for c in node_id):
        raise YtError(f"Malformed node id {node_id!r}",
                      code=EErrorCode.Generic)
    return node_id


class SequoiaResolver:
    """Maintains and serves the ground tables for one cluster."""

    def __init__(self, client):
        self.client = client
        self._revision = 0
        self._enabled = False
        # Host-side mirrors of the tables' key sets: subtree drops become
        # an in-memory prefix scan + exact-key deletes, instead of a
        # table scan under the master mutation lock (and no path text is
        # ever spliced into QL).
        self._paths: set = set()
        self._ids: dict[str, str] = {}          # path → node_id

    # -- lifecycle -------------------------------------------------------------

    def enable(self) -> "SequoiaResolver":
        """Create + mount the ground tables, full-sync them from the
        tree, and subscribe to the mutation stream — atomically under
        the master mutation lock, so no mutation can slip between the
        sync walk and the subscription."""
        for path, schema in ((RESOLVE_PATH, RESOLVE_SCHEMA),
                             (NODES_PATH, NODES_SCHEMA),
                             (CHILDREN_PATH, CHILDREN_SCHEMA)):
            if not self.client.exists(path):
                self.client.create("table", path, recursive=True,
                                   attributes={"schema": schema,
                                               "dynamic": True})
                self.client.mount_table(path)
        master = self.client.cluster.master
        with master.mutation_lock:
            self.full_sync()
            master.add_mutation_listener(self._on_mutation)
        self._enabled = True
        return self

    def _walk_tree(self) -> "Iterator[tuple[str, object, object]]":
        """(path, RAW node, parent node) for every non-excluded tree path
        — THE single walk shared by full_sync and verify.  The parent
        rides along (it is already on the walk's stack), so per-node work
        is O(1) instead of a root-to-parent resolution.  Raw (no link
        following): a link row records the link itself, so target
        mutations never invalidate it and walks cannot loop through
        cyclic links."""
        tree = self.client.cluster.master.tree
        stack = [("/", tree.root)]
        while stack:
            path, node = stack.pop()
            for name, child in list(node.children.items()):
                child_path = f"//{name}" if path == "/" else \
                    f"{path}/{name}"
                if _excluded(child_path):
                    continue
                yield child_path, child, node
                stack.append((child_path, child))

    def _record_rows(self, path: str, node,
                     parent) -> "tuple[dict, dict, dict]":
        """(resolve_row, nodes_row, children_row) for one tree node."""
        _, _, child_key = path.rpartition("/")
        return (
            {"path": path, "node_id": node.id, "node_type": node.type,
             "revision": self._revision},
            {"node_id": node.id, "node_type": node.type, "path": path,
             "attrs": _safe_yson(node.attributes),
             "value": _safe_yson(node.value),
             "revision": self._revision},
            {"parent_id": parent.id if parent is not None else "",
             "child_key": child_key, "child_id": node.id},
        )

    def _parent_node(self, path: str):
        tree = self.client.cluster.master.tree
        parent_path = path.rsplit("/", 1)[0]
        if parent_path in ("", "/"):
            return tree.root
        return tree.try_resolve(parent_path, follow_links=False)

    def full_sync(self) -> int:
        """Rebuild the tables from the live tree (bootstrap, or repair
        after a detected divergence)."""
        resolve_rows, node_rows, child_rows = [], [], []
        for path, node, parent in self._walk_tree():
            r, n, c = self._record_rows(path, node, parent)
            resolve_rows.append(r)
            node_rows.append(n)
            child_rows.append(c)
        for table, key_cols in ((RESOLVE_PATH, ("path",)),
                                (NODES_PATH, ("node_id",)),
                                (CHILDREN_PATH, ("parent_id",
                                                 "child_key"))):
            existing = self.client._select_rows_system(
                f"{', '.join(key_cols)} FROM [{table}]")
            if existing:
                self.client.delete_rows(
                    table, [tuple(_text(r[k]) for k in key_cols)
                            for r in existing])
        if resolve_rows:
            self.client.insert_rows(RESOLVE_PATH, resolve_rows)
            self.client.insert_rows(NODES_PATH, node_rows)
            self.client.insert_rows(CHILDREN_PATH, child_rows)
        self._paths = {r["path"] for r in resolve_rows}
        self._ids = {r["path"]: r["node_id"] for r in resolve_rows}
        return len(resolve_rows)

    # -- incremental maintenance ----------------------------------------------

    def _on_mutation(self, op: str, args: dict, result) -> None:
        try:
            self._apply_mutation(op, args, result)
        except YtError:
            # Upkeep must never block the mutation path; a miss degrades
            # to a stale entry that verify()/full_sync repairs.
            pass

    def _apply_mutation(self, op: str, args: dict, result=None) -> None:
        self._revision += 1
        if op == "create":
            self._upsert(args.get("path"))
        elif op == "remove":
            path = args.get("path")
            if path and "/@" in path:
                self._refresh_record(path.split("/@", 1)[0])
            else:
                self._drop_subtree(path)
        elif op == "set":
            path = args.get("path")
            if path and "/@" in path:
                # Attribute edit: the node's record changes, resolution
                # does not.
                self._refresh_record(path.split("/@", 1)[0])
            elif path:
                # A value set can CREATE the node, and a map_node set
                # replaces its whole child set: resync the subtree.
                self._drop_subtree(path)
                self._upsert_subtree(path)
        elif op in ("copy", "move"):
            if op == "move":
                self._drop_subtree(args.get("src"))
            self._upsert_subtree(args.get("dst"))
        elif op == "link":
            self._upsert(args.get("link"))
        elif op in ("tx_abort", "tx_commit"):
            # Rollback (abort, or commit aborting uncommitted children)
            # edits the tree through undo entries the mutation stream
            # never sees.  The undo replay reports the touched paths —
            # resync exactly those subtrees (abort-scoped undo).
            touched = result if isinstance(result, (list, tuple)) else None
            if touched is None:
                if op == "tx_abort":
                    self.full_sync()        # no scope info: stay correct
                return
            for path in touched:
                self._drop_subtree(path)
                self._upsert_subtree(path)
        elif op == "batch":
            for sub in args.get("ops") or []:
                self._apply_mutation(sub.get("op"), sub.get("args") or {})

    def _skip(self, path: "Optional[str]") -> bool:
        return not path or "/@" in path or _excluded(path)

    def _upsert(self, path: "Optional[str]") -> None:
        path = _canon(path) if path else None
        if self._skip(path):
            return
        node = self.client.cluster.master.tree.try_resolve(
            path, follow_links=False)
        if node is None:
            return
        # Ancestors materialized by recursive creates get records FIRST
        # (their children rows must exist before the child references
        # them in reads).
        parent = path.rsplit("/", 1)[0]
        if parent and parent != "/" and parent not in self._paths:
            self._upsert(parent)
        resolve_row, node_row, child_row = self._record_rows(
            path, node, self._parent_node(path))
        old_id = self._ids.get(path)
        if old_id is not None and old_id != node.id:
            self.client.delete_rows(NODES_PATH, [(old_id,)])
        self.client.insert_rows(RESOLVE_PATH, [resolve_row])
        self.client.insert_rows(NODES_PATH, [node_row])
        self.client.insert_rows(CHILDREN_PATH, [child_row])
        self._paths.add(path)
        self._ids[path] = node.id

    def _refresh_record(self, path: "Optional[str]") -> None:
        """Attribute/value change on an EXISTING node: rewrite its nodes
        row only (resolution and children are untouched)."""
        path = _canon(path) if path else None
        if path is None or _excluded(path):
            return
        node = self.client.cluster.master.tree.try_resolve(
            path, follow_links=False)
        if node is None or path not in self._paths:
            return
        _, node_row, _ = self._record_rows(path, node,
                                           self._parent_node(path))
        self.client.insert_rows(NODES_PATH, [node_row])

    def _upsert_subtree(self, path: "Optional[str]") -> None:
        path = _canon(path) if path else None
        if self._skip(path):
            return
        # RAW node: recursion follows real children only (a link's
        # children are the target's business, recorded at its own path).
        node = self.client.cluster.master.tree.try_resolve(
            path, follow_links=False)
        if node is None:
            return
        self._upsert(path)
        for name in list(node.children):
            self._upsert_subtree(f"{path}/{name}")

    def _drop_subtree(self, path: "Optional[str]") -> None:
        path = _canon(path) if path else None
        if self._skip(path):
            return
        doomed = [p for p in self._paths
                  if p == path or p.startswith(path + "/")]
        if not doomed:
            return
        self.client.delete_rows(RESOLVE_PATH, [(p,) for p in doomed])
        self.client.delete_rows(
            NODES_PATH, [(self._ids[p],) for p in doomed
                         if p in self._ids])
        child_keys = []
        for p in doomed:
            parent_path, _, child_key = p.rpartition("/")
            parent_id = self._ids.get(parent_path) \
                if parent_path not in ("", "/") else \
                self.client.cluster.master.tree.root.id
            if parent_id:
                child_keys.append((parent_id, child_key))
        if child_keys:
            self.client.delete_rows(CHILDREN_PATH, child_keys)
        self._paths.difference_update(doomed)
        for p in doomed:
            self._ids.pop(p, None)

    # -- serving: resolution ---------------------------------------------------

    def resolve(self, path: str) -> "Optional[dict]":
        """Point lookup: {node_id, node_type} or None — the RAW node at
        the path (a link reports type "link"; traversal is the next
        resolver layer).  THE Sequoia win: resolution is a table read,
        not a masters-memory tree walk."""
        path = _canon(path)
        if path is None:
            return None
        (row,) = self.client._lookup_rows_direct(RESOLVE_PATH, [(path,)])
        if row is None:
            return None
        return {"node_id": _text(row["node_id"]),
                "node_type": _text(row["node_type"])}

    # -- serving: the cypress-proxy read path ----------------------------------

    def read_exists(self, path: str) -> bool:
        return self.resolve(path) is not None

    def _node_record(self, node_id: str) -> "Optional[dict]":
        (row,) = self.client._lookup_rows_direct(NODES_PATH, [(node_id,)])
        if row is None:
            return None
        return {"node_type": _text(row["node_type"]),
                "path": _text(row["path"]),
                "attrs": yson_loads(row["attrs"]),
                "value": yson_loads(row["value"])}

    def _children(self, node_id: str) -> "list[tuple[str, str]]":
        rows = self.client._select_rows_system(
            f"child_key, child_id FROM [{CHILDREN_PATH}] "
            f"WHERE parent_id = '{_check_id(node_id)}'")
        return sorted((_text(r["child_key"]), _text(r["child_id"]))
                      for r in rows)

    def read_list(self, path: str) -> "list[str]":
        """Child names, served from the children ground table."""
        res = self.resolve(path)
        if res is None:
            raise YtError(f"No such node {path!r} (sequoia)",
                          code=EErrorCode.ResolveError)
        return [key for key, _ in self._children(res["node_id"])]

    def read_get(self, path: str, depth: "Optional[int]" = None):
        """Cypress get served from the ground tables alone: map nodes
        assemble from children rows, documents/scalars from the value
        column — no master-tree access (cypress_proxy/actions.cpp)."""
        res = self.resolve(path)
        if res is None:
            raise YtError(f"No such node {path!r} (sequoia)",
                          code=EErrorCode.ResolveError)
        return self._assemble(res["node_id"], res["node_type"], depth)

    def _assemble(self, node_id: str, node_type: str,
                  depth: "Optional[int]"):
        if node_type == "map_node":
            if depth == 0:
                return {}
            out = {}
            for key, child_id in self._children(node_id):
                child = self._node_record(child_id)
                if child is None:
                    continue
                out[key] = self._assemble(
                    child_id, child["node_type"],
                    None if depth is None else depth - 1)
            return out
        record = self._node_record(node_id)
        if record is None:
            return {}
        if node_type in ("document", "string_node", "int64_node"):
            return record["value"]
        return {}

    def read_attribute(self, path: str, name: str):
        res = self.resolve(path)
        if res is None:
            raise YtError(f"No such node {path!r} (sequoia)",
                          code=EErrorCode.ResolveError)
        record = self._node_record(res["node_id"])
        if record is None or name not in record["attrs"]:
            raise YtError(f"No attribute {name!r} on {path!r} (sequoia)",
                          code=EErrorCode.ResolveError)
        return record["attrs"][name]

    # -- verification ----------------------------------------------------------

    def verify(self) -> "list[str]":
        """Table/tree agreement check over the FULL namespace and all
        three ground tables; returns divergent paths (empty =
        consistent).  The Sequoia migration invariant, checkable any
        time because both sides coexist."""
        divergent: set = set()
        table_ids: dict[str, str] = {}
        for row in self.client._select_rows_system(
                f"path, node_id FROM [{RESOLVE_PATH}]"):
            table_ids[_text(row["path"])] = _text(row["node_id"])
        node_records: dict[str, dict] = {}
        for row in self.client._select_rows_system(
                f"node_id, node_type, path, attrs, value "
                f"FROM [{NODES_PATH}]"):
            node_records[_text(row["node_id"])] = {
                "node_type": _text(row["node_type"]),
                "path": _text(row["path"]),
                "attrs": row["attrs"], "value": row["value"]}
        children_rows: dict[str, dict[str, str]] = {}
        for row in self.client._select_rows_system(
                f"parent_id, child_key, child_id FROM [{CHILDREN_PATH}]"):
            children_rows.setdefault(_text(row["parent_id"]), {})[
                _text(row["child_key"])] = _text(row["child_id"])

        tree_paths = set()
        tree_ids = set()
        expected_edges: set = set()
        for path, node, parent in self._walk_tree():
            tree_paths.add(path)
            tree_ids.add(node.id)
            child_key = path.rsplit("/", 1)[1]
            expected_edges.add((parent.id, child_key))
            if table_ids.get(path) != node.id:
                divergent.add(path)
                continue
            record = node_records.get(node.id)
            if record is None or record["node_type"] != node.type or \
                    record["attrs"] != _safe_yson(node.attributes) or \
                    record["value"] != _safe_yson(node.value):
                divergent.add(path)
                continue
            if children_rows.get(parent.id, {}).get(child_key) != node.id:
                divergent.add(path)
        divergent.update(p for p in table_ids if p not in tree_paths)
        for node_id, record in node_records.items():
            if node_id not in tree_ids:
                divergent.add(record["path"])
        # Orphan EDGES: a stale children row would make read_list serve a
        # removed child forever if only expected-edge presence were
        # checked.
        for parent_id, by_key in children_rows.items():
            for child_key, child_id in by_key.items():
                if (parent_id, child_key) not in expected_edges:
                    record = node_records.get(child_id)
                    divergent.add(record["path"] if record is not None
                                  else f"<edge {parent_id}/{child_key}>")
        return sorted(divergent)
