"""Vector similarity search (ISSUE 16): the `vector<float, N>` column
type end to end — schema/storage/wire/arrow round-trips with loud
write-path rejection, per-chunk centroid+norm stats (seal, merge,
backfill), NEAREST recall=1.0 against a numpy brute-force oracle
(dot/cosine/l2 × filtered/unfiltered × ties × k>matching-rows),
bit-identical local vs 8-device whole-plan SPMD at exactly one host
sync, the `?` placeholder/params surface, and the serving-plane
NearestBatcher (co-admitted cohort → ONE batched distance matmul).
"""

import threading

import numpy as np
import pytest

from ytsaurus_tpu.chunks.columnar import (
    ColumnarChunk,
    chunk_column_stats,
    concat_chunks,
    merge_column_stats,
)
from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.query.engine.evaluator import Evaluator
from ytsaurus_tpu.schema import TableSchema, VectorType, parse_type

DIM = 8
SCHEMA = TableSchema.make([
    ("k", "int64", "ascending"), ("g", "int64"),
    ("emb", f"vector<float, {DIM}>"), ("v", "int64")])
T = "//t"


def _corpus(n=96, seed=0, null_every=0):
    """Integer-component vectors: f32 distance arithmetic on them is
    exact, so oracle comparisons are == not approx."""
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        emb = None if (null_every and i % null_every == 0) else \
            [float(x) for x in rng.integers(-6, 7, DIM)]
        rows.append({"k": i, "g": i % 5, "emb": emb,
                     "v": int(rng.integers(0, 100))})
    return rows


def _oracle(rows, q, metric, k, pred=lambda r: True):
    """Brute-force numpy ranking (the acceptance oracle): returns the
    kth measure so ties accept ANY row at the cut, plus the expected
    row count min(k, matching)."""
    q = np.asarray(q, dtype=np.float32)
    measures = {}
    for r in rows:
        if r["emb"] is None or not pred(r):
            continue
        e = np.asarray(r["emb"], dtype=np.float32)
        if metric == "dot":
            m = float(e @ q)
        elif metric == "cosine":
            denom = float(np.linalg.norm(e) * np.linalg.norm(q))
            m = 1.0 - float(e @ q) / denom if denom > 0 else 1.0
        else:
            m = float(np.sqrt(((e - q) ** 2).sum()))
        measures[r["k"]] = m
    reverse = metric == "dot"
    ranked = sorted(measures, key=lambda kk: (-measures[kk] if reverse
                                              else measures[kk], kk))
    take = min(k, len(ranked))
    if take == 0:
        return set(), None, 0, measures
    cut = measures[ranked[take - 1]]
    return set(ranked[:take]), cut, take, measures


def _assert_recall(got_ks, rows, q, metric, k, pred=lambda r: True):
    """recall == 1.0 with ties admitted: exactly min(k, matching) rows,
    every one at-or-better than the oracle's kth measure."""
    _top, cut, take, measures = _oracle(rows, q, metric, k, pred)
    assert len(got_ks) == take, (metric, k, got_ks)
    assert len(set(got_ks)) == take, "duplicate rows in top-k"
    for kk in got_ks:
        assert kk in measures, f"row {kk} fails the predicate"
        if metric == "dot":
            assert measures[kk] >= cut
        else:
            assert measures[kk] <= cut


# -- schema + type -------------------------------------------------------------

def test_vector_type_parses_and_interns():
    t1 = parse_type("vector<float, 16>")
    t2 = parse_type("vector<float,16>")
    t3 = parse_type("vector<float, 32>")
    assert isinstance(t1, VectorType) and t1.dim == 16
    assert t1 is t2, "same dim must intern to one object"
    assert t1 is not t3 and t1 != t3
    assert t1.value == "vector<float,16>"
    assert not t1.is_numeric and not t1.is_comparable


def test_vector_schema_survives_rebuild():
    rebuilt = TableSchema.make(
        [(c.name, c.type.value) for c in SCHEMA],
        strict=SCHEMA.strict)
    assert isinstance(rebuilt.get("emb").type, VectorType)
    assert rebuilt.get("emb").type.dim == DIM


def test_vector_key_column_rejected():
    with pytest.raises(YtError, match="key column"):
        TableSchema.make([("emb", "vector<float, 4>", "ascending"),
                          ("v", "int64")])


# -- write-path hardening (satellite 1) ----------------------------------------

@pytest.mark.parametrize("bad,msg", [
    ([1.0, 2.0], "dim mismatch"),                      # wrong dim
    ([[1.0, 2.0], [3.0, 4.0]], "Ragged"),              # nested/ragged
    ([1.0] * (DIM - 1) + [float("nan")], "Non-finite"),
    ([1.0] * (DIM - 1) + [float("inf")], "Non-finite"),
    (["a"] * DIM, "Bad vector value"),
])
def test_write_path_rejects_loudly(bad, msg):
    rows = _corpus(4)
    rows[2]["emb"] = bad
    with pytest.raises(YtError, match=msg):
        ColumnarChunk.from_rows(SCHEMA, rows)


def test_storage_round_trip_with_nulls():
    rows = _corpus(32, seed=1, null_every=7)
    chunk = ColumnarChunk.from_rows(SCHEMA, rows)
    assert chunk.columns["emb"].data.shape == (chunk.capacity, DIM)
    back = chunk.to_rows()
    for want, got in zip(rows, back):
        assert got["emb"] == want["emb"], want["k"]


def test_wire_round_trip_and_non_finite_decode_guard(tmp_path):
    from ytsaurus_tpu.chunks.store import FsChunkStore
    rows = _corpus(48, seed=2, null_every=9)
    chunk = ColumnarChunk.from_rows(SCHEMA, rows)
    store = FsChunkStore(str(tmp_path))
    cid = store.write_chunk(chunk)
    back = store.read_chunk(cid)
    assert back.to_rows() == chunk.to_rows()
    assert np.array_equal(np.asarray(back.columns["emb"].data),
                          np.asarray(chunk.columns["emb"].data))


def test_arrow_round_trip():
    from ytsaurus_tpu.arrow import (
        arrow_ipc_to_rows,
        arrow_schema_to_table_schema,
        chunk_to_arrow,
        chunks_to_arrow_ipc,
    )
    rows = _corpus(24, seed=3, null_every=5)
    chunk = ColumnarChunk.from_rows(SCHEMA, rows)
    table = chunk_to_arrow(chunk)
    assert str(table.schema.field("emb").type).startswith(
        "fixed_size_list")
    back = arrow_ipc_to_rows(chunks_to_arrow_ipc([chunk]))
    for want, got in zip(rows, back):
        assert got["emb"] == want["emb"]
    ts = arrow_schema_to_table_schema(table.schema)
    emb = next(c for c in ts if c.name == "emb")
    assert isinstance(emb.type, VectorType) and emb.type.dim == DIM


# -- per-chunk stats: seal, merge, backfill (satellite 3) ----------------------

def test_vector_stats_sealed_and_exact():
    rows = _corpus(40, seed=4, null_every=11)
    chunk = ColumnarChunk.from_rows(SCHEMA, rows)
    stats = chunk_column_stats(chunk)
    entry = stats["emb"]
    planes = np.array([r["emb"] for r in rows if r["emb"] is not None],
                      dtype=np.float64)
    norms = np.sqrt((planes * planes).sum(axis=1))
    assert entry["vector_dim"] == DIM
    assert entry["count"] == len(planes)
    assert entry["has_null"] is True
    np.testing.assert_allclose(entry["centroid_sum"],
                               planes.sum(axis=0), rtol=1e-6)
    assert entry["norm_min"] == pytest.approx(float(norms.min()))
    assert entry["norm_max"] == pytest.approx(float(norms.max()))


def test_vector_stats_merge_is_exact_fold():
    """Centroid sums ADD across chunks (the reason the stat is a sum,
    not a mean): merged == whole-table stats exactly."""
    rows = _corpus(60, seed=5, null_every=13)
    parts = [ColumnarChunk.from_rows(SCHEMA, rows[i::3])
             for i in range(3)]
    merged = merge_column_stats([chunk_column_stats(c) for c in parts])
    whole = chunk_column_stats(
        ColumnarChunk.from_rows(SCHEMA, rows))["emb"]
    got = merged["emb"]
    assert got["count"] == whole["count"]
    assert got["vector_dim"] == DIM
    np.testing.assert_allclose(got["centroid_sum"],
                               whole["centroid_sum"], rtol=1e-9)
    assert got["norm_min"] == pytest.approx(whole["norm_min"])
    assert got["norm_max"] == pytest.approx(whole["norm_max"])


def test_vector_stats_backfill_via_read_stats(tmp_path):
    """A chunk sealed without stats decode-backfills vector stats
    through ChunkStore.read_stats like every other column."""
    from ytsaurus_tpu.chunks.store import FsChunkStore
    chunk = ColumnarChunk.from_rows(SCHEMA, _corpus(16, seed=6))
    store = FsChunkStore(str(tmp_path))
    cid = store.write_chunk(chunk)
    stats = store.read_stats(cid)
    assert stats["emb"]["vector_dim"] == DIM
    assert stats["emb"]["count"] == 16


# -- NEAREST recall oracle (local evaluator) -----------------------------------

QUERY_VECTORS = [
    [1.0, -2.0, 3.0, 0.0, 5.0, -1.0, 2.0, 4.0],
    [0.0] * DIM,
    [-3.0, -3.0, -3.0, -3.0, 3.0, 3.0, 3.0, 3.0],
]


@pytest.mark.parametrize("metric", ["l2", "cosine", "dot"])
@pytest.mark.parametrize("k", [1, 7, 16])
def test_nearest_recall_unfiltered(metric, k):
    rows = _corpus(96, seed=7, null_every=10)
    chunk = ColumnarChunk.from_rows(SCHEMA, rows)
    ev = Evaluator()
    for q in QUERY_VECTORS:
        plan = build_query(
            f"SELECT k FROM [{T}] NEAREST(emb, ?, {k}, '{metric}')",
            {T: SCHEMA}, params=[q])
        got = [r["k"] for r in ev.run_plan(plan, chunk).to_rows()]
        _assert_recall(got, rows, q, metric, k)


@pytest.mark.parametrize("metric", ["l2", "dot"])
def test_nearest_recall_filtered(metric):
    """The predicate fuses BEFORE the distance pass: filtered-out rows
    can never displace matching rows from the top-k."""
    rows = _corpus(96, seed=8, null_every=10)
    chunk = ColumnarChunk.from_rows(SCHEMA, rows)
    ev = Evaluator()
    q = QUERY_VECTORS[0]
    plan = build_query(
        f"SELECT k FROM [{T}] WHERE g = 2 AND v < 70 "
        f"NEAREST(emb, ?, 8, '{metric}')",
        {T: SCHEMA}, params=[q])
    got = [r["k"] for r in ev.run_plan(plan, chunk).to_rows()]
    _assert_recall(got, rows, q, metric, 8,
                   pred=lambda r: r["g"] == 2 and r["v"] < 70)


def test_nearest_k_exceeds_matching_rows():
    rows = _corpus(64, seed=9)
    chunk = ColumnarChunk.from_rows(SCHEMA, rows)
    plan = build_query(
        f"SELECT k FROM [{T}] WHERE g = 3 NEAREST(emb, ?, 50)",
        {T: SCHEMA}, params=[QUERY_VECTORS[0]])
    got = [r["k"] for r in Evaluator().run_plan(plan, chunk).to_rows()]
    matching = [r for r in rows if r["g"] == 3]
    assert len(got) == len(matching)
    _assert_recall(got, rows, QUERY_VECTORS[0], "l2", 50,
                   pred=lambda r: r["g"] == 3)


def test_nearest_ties_admit_any_tied_row():
    """Duplicate vectors at the k cut: every returned row must be
    at-or-under the cut distance (set equality is NOT required)."""
    rows = []
    for i in range(12):
        rows.append({"k": i, "g": 0,
                     "emb": [float(i % 3)] * DIM, "v": 0})
    chunk = ColumnarChunk.from_rows(SCHEMA, rows)
    q = [0.0] * DIM
    plan = build_query(f"SELECT k FROM [{T}] NEAREST(emb, ?, 5)",
                       {T: SCHEMA}, params=[q])
    got = [r["k"] for r in Evaluator().run_plan(plan, chunk).to_rows()]
    _assert_recall(got, rows, q, "l2", 5)


def test_nearest_order_by_distance_equivalent():
    """The sugared and unsugared spellings produce identical rows."""
    rows = _corpus(48, seed=10)
    chunk = ColumnarChunk.from_rows(SCHEMA, rows)
    q = QUERY_VECTORS[2]
    ev = Evaluator()
    a = ev.run_plan(build_query(
        f"SELECT k FROM [{T}] NEAREST(emb, ?, 6)",
        {T: SCHEMA}, params=[q]), chunk).to_rows()
    b = ev.run_plan(build_query(
        f"SELECT k FROM [{T}] ORDER BY l2_distance(emb, ?) LIMIT 6",
        {T: SCHEMA}, params=[q]), chunk).to_rows()
    assert a == b


# -- params surface ------------------------------------------------------------

def test_params_arity_mismatch_is_loud():
    with pytest.raises(YtError, match="[Pp]laceholder"):
        build_query(f"SELECT k FROM [{T}] NEAREST(emb, ?, 4)",
                    {T: SCHEMA}, params=[])
    with pytest.raises(YtError, match="[Pp]laceholder|param"):
        build_query(f"SELECT k FROM [{T}] NEAREST(emb, ?, 4)",
                    {T: SCHEMA}, params=[[1.0] * DIM, [2.0] * DIM])
    with pytest.raises(YtError, match="[Uu]nbound"):
        build_query(f"SELECT k FROM [{T}] NEAREST(emb, ?, 4)",
                    {T: SCHEMA})


def test_nearest_surface_validation():
    with pytest.raises(YtError, match="dim"):
        build_query(f"SELECT k FROM [{T}] NEAREST(emb, ?, 4)",
                    {T: SCHEMA}, params=[[1.0, 2.0]])
    with pytest.raises(YtError, match="metric"):
        build_query(f"SELECT k FROM [{T}] NEAREST(emb, ?, 4, 'bogus')",
                    {T: SCHEMA}, params=[[1.0] * DIM])
    with pytest.raises(YtError):
        build_query(f"SELECT k FROM [{T}] NEAREST(emb, ?, 0)",
                    {T: SCHEMA}, params=[[1.0] * DIM])
    with pytest.raises(YtError, match="ORDER BY|LIMIT"):
        build_query(
            f"SELECT k FROM [{T}] NEAREST(emb, ?, 4) ORDER BY k",
            {T: SCHEMA}, params=[[1.0] * DIM])


def test_vector_column_guards():
    """Raw vectors have no total order / equality surface: comparisons,
    GROUP BY and ORDER BY on them are loud type errors."""
    for q in [f"SELECT k FROM [{T}] WHERE emb = emb",
              f"SELECT k, count(*) AS c FROM [{T}] GROUP BY emb",
              f"SELECT k FROM [{T}] ORDER BY emb LIMIT 3"]:
        with pytest.raises(YtError):
            build_query(q, {T: SCHEMA})


# -- distributed: whole-plan SPMD, one host sync (tentpole acceptance) ---------

@pytest.fixture(scope="module")
def vtable8(request):
    mesh = request.getfixturevalue("mesh8")
    from ytsaurus_tpu.parallel.distributed import ShardedTable
    chunks, all_rows = [], []
    for sh in range(8):
        rows = _corpus(40 + sh * 7, seed=20 + sh,
                       null_every=13 if sh % 2 else 0)
        for r in rows:
            r["k"] += sh * 10_000
        all_rows.extend(rows)
        chunks.append(ColumnarChunk.from_rows(SCHEMA, rows))
    return mesh, ShardedTable.from_chunks(mesh, chunks), \
        concat_chunks(chunks), all_rows


@pytest.mark.parametrize("metric", ["l2", "cosine", "dot"])
def test_nearest_spmd_bit_identical_one_sync(vtable8, metric):
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        host_sync_count,
    )
    from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
    from ytsaurus_tpu.query.statistics import QueryStatistics
    mesh, table, merged, all_rows = vtable8
    de = DistributedEvaluator(mesh)
    local = Evaluator()
    q = QUERY_VECTORS[0]
    plan = build_query(
        f"SELECT k FROM [{T}] NEAREST(emb, ?, 9, '{metric}')",
        {T: SCHEMA}, params=[q])
    stats = QueryStatistics()
    s0 = host_sync_count()
    got = run_whole_plan(de, plan, table, stats=stats)
    assert host_sync_count() - s0 == 1, \
        "fused NEAREST must cost exactly one host sync"
    assert stats.whole_plan == 1
    want = local.run_plan(plan, merged)
    assert got.to_rows() == want.to_rows(), \
        "distributed top-k must be bit-identical to local"
    _assert_recall([r["k"] for r in got.to_rows()],
                   all_rows, q, metric, 9)


def test_nearest_spmd_filtered(vtable8):
    from ytsaurus_tpu.parallel.distributed import DistributedEvaluator
    from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
    mesh, table, merged, all_rows = vtable8
    q = QUERY_VECTORS[2]
    plan = build_query(
        f"SELECT k, g FROM [{T}] WHERE g != 1 NEAREST(emb, ?, 12)",
        {T: SCHEMA}, params=[q])
    got = run_whole_plan(DistributedEvaluator(mesh), plan, table)
    want = Evaluator().run_plan(plan, merged)
    assert got.to_rows() == want.to_rows()
    _assert_recall([r["k"] for r in got.to_rows()], all_rows, q, "l2",
                   12, pred=lambda r: r["g"] != 1)


# -- serving: co-admitted cohort = ONE batched matmul (tentpole) ---------------

@pytest.fixture
def vclient(tmp_path):
    from ytsaurus_tpu.client import YtClient, YtCluster
    client = YtClient(YtCluster(str(tmp_path / "cluster")))
    client.create("map_node", "//home", recursive=True,
                  ignore_existing=True)
    client.create("table", "//home/vec", attributes={
        "schema": [
            {"name": "k", "type": "int64", "sort_order": "ascending"},
            {"name": "g", "type": "int64"},
            {"name": "emb", "type": f"vector<float, {DIM}>"},
            {"name": "v", "type": "int64"},
        ],
        "dynamic": True})
    client.mount_table("//home/vec")
    rows = _corpus(80, seed=30)
    client.insert_rows("//home/vec", rows)
    return client, rows


def test_nearest_rows_client_api(vclient):
    client, rows = vclient
    q = QUERY_VECTORS[0]
    out = client.nearest_rows("//home/vec", "emb", q, 5, metric="l2")
    _assert_recall([r["k"] for r in out], rows, q, "l2", 5)
    # $distance rides each row, ascending for l2.
    ds = [r["$distance"] for r in out]
    assert ds == sorted(ds)
    # dot returns similarity, descending.
    out = client.nearest_rows("//home/vec", "emb", q, 5, metric="dot")
    ds = [r["$distance"] for r in out]
    assert ds == sorted(ds, reverse=True)
    _assert_recall([r["k"] for r in out], rows, q, "dot", 5)


def test_cohort_shares_one_batched_matmul(vclient):
    """THE serving acceptance: N co-admitted NEAREST queries on one
    (table, column, metric) execute as ONE batched flush — the batcher
    counts one batch, and the jitted kernel does not re-trace for the
    co-batched queries (they ride the batch dimension of one matmul)."""
    from ytsaurus_tpu.query import vector as vmod
    client, rows = vclient
    gateway = client.cluster.gateway
    batcher = gateway.nearest_batcher
    # Widen the coalescing window so all workers land in one cohort
    # deterministically (the default 2ms window is a latency tuning,
    # not a correctness bound).
    old_window = gateway.config.flush_window_ms
    gateway.config.flush_window_ms = 200.0
    try:
        # Warm one flush so the kernel for this (capacity, batch-bucket,
        # k-bucket) is already traced, then assert the cohort run adds
        # exactly one batch and zero fresh traces for its members.
        client.nearest_rows("//home/vec", "emb", QUERY_VECTORS[1], 3)
        rng = np.random.default_rng(31)
        queries = [[float(x) for x in rng.integers(-6, 7, DIM)]
                   for _ in range(8)]
        b0 = batcher.batches_n
        t0 = vmod.nearest_trace_count()
        results = [None] * len(queries)
        barrier = threading.Barrier(len(queries))

        def work(i):
            barrier.wait()
            results[i] = client.nearest_rows("//home/vec", "emb",
                                             queries[i], 3)

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(len(queries))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert batcher.batches_n - b0 == 1, \
            "co-admitted cohort must flush as ONE batch"
        assert vmod.nearest_trace_count() - t0 <= 1, \
            "cohort members must share one compiled kernel"
        for i, q in enumerate(queries):
            _assert_recall([r["k"] for r in results[i]], rows, q,
                           "l2", 3)
    finally:
        gateway.config.flush_window_ms = old_window


def test_mixed_k_cohort_each_member_gets_its_k(vclient):
    client, rows = vclient
    gateway = client.cluster.gateway
    old_window = gateway.config.flush_window_ms
    gateway.config.flush_window_ms = 200.0
    try:
        ks = [1, 3, 7, 2]
        results = [None] * len(ks)
        barrier = threading.Barrier(len(ks))

        def work(i):
            barrier.wait()
            results[i] = client.nearest_rows(
                "//home/vec", "emb", QUERY_VECTORS[0], ks[i])

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(len(ks))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, k in enumerate(ks):
            _assert_recall([r["k"] for r in results[i]], rows,
                           QUERY_VECTORS[0], "l2", k)
    finally:
        gateway.config.flush_window_ms = old_window


def test_nearest_accounting_folds(vclient):
    from ytsaurus_tpu.query.accounting import get_accountant
    client, _rows = vclient
    before = get_accountant().totals()
    client.nearest_rows("//home/vec", "emb", QUERY_VECTORS[0], 4)
    after = get_accountant().totals()
    assert after["nearest_queries"] - before["nearest_queries"] == 1
    assert after["nearest_batches"] - before["nearest_batches"] == 1
    assert after["nearest_rows_scanned"] > \
        before["nearest_rows_scanned"]


def test_nearest_rejects_bad_inputs(vclient):
    client, _rows = vclient
    with pytest.raises(YtError, match="metric"):
        client.nearest_rows("//home/vec", "emb", QUERY_VECTORS[0], 3,
                            metric="manhattan")
    with pytest.raises(YtError, match="k >= 1"):
        client.nearest_rows("//home/vec", "emb", QUERY_VECTORS[0], 0)
    with pytest.raises(YtError, match="shape"):
        client.nearest_rows("//home/vec", "emb", [1.0, 2.0], 3)
    with pytest.raises(YtError, match="Non-finite"):
        client.nearest_rows("//home/vec", "emb",
                            [float("nan")] * DIM, 3)
    with pytest.raises(YtError, match="not a vector"):
        client.nearest_rows("//home/vec", "v", QUERY_VECTORS[0], 3)


def test_select_rows_params_through_client(vclient):
    client, rows = vclient
    q = QUERY_VECTORS[0]
    out = client.select_rows(
        "SELECT k FROM [//home/vec] NEAREST(emb, ?, 6)", params=[q])
    _assert_recall([r["k"] for r in out], rows, q, "l2", 6)
