"""HTTP proxy: the REST surface (`/api/v4/<command>`) over the driver.

Ref shape: server/http_proxy (api.h, context.h) — a stateless daemon that
authenticates the request, resolves the command against the driver
registry, parses parameters from headers/query/body, streams table data
in wire formats, and forwards to the cluster.

Redesign: stdlib ThreadingHTTPServer bridging to the primary over the RPC
plane (RemoteYtClient), one handler per command call:

  POST /api/v4/select_rows   {"query": "..."}            → JSON rows
  PUT  /api/v4/write_table?path=//t  (body = format rows)
  GET  /api/v4/read_table?path=//t&format=json           → format rows
  GET  /api/v4/get?path=//home/@x                        → JSON value
  GET  /ping | /hosts | /api | /api/v4

The authenticated principal comes from `X-YT-User` (the reference reads
auth tokens; local clusters run unauthenticated with user stamping).
Parameters merge: query string < `X-YT-Parameters` header (JSON) < JSON
body — later wins, matching the reference's precedence.
"""

from __future__ import annotations

import collections
import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ytsaurus_tpu.driver import COMMANDS
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("HttpProxy")

_FORMAT_CONTENT_TYPES = {
    "json": "application/json",
    "yson": "application/x-yt-yson-binary",
    "dsv": "text/tab-separated-values",
    "schemaful_dsv": "text/tab-separated-values",
}


def _json_default(value):
    if isinstance(value, bytes):
        return value.decode("utf-8", "replace")
    return str(value)


class HttpProxy:
    """Serves the REST API against a client (RemoteYtClient or YtClient)."""

    def __init__(self, client_factory, host: str = "127.0.0.1",
                 port: int = 0):
        """client_factory(user) → client executing as that principal."""
        self._client_factory = client_factory
        self._clients: "collections.OrderedDict[str, object]" = \
            collections.OrderedDict()
        self._clients_lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _run(self):
                try:
                    outer._handle(self)
                except (ConnectionError, BrokenPipeError):
                    pass
                except Exception as exc:   # noqa: BLE001 — wire boundary
                    logger.exception("proxy request failed")
                    try:
                        outer._reply_error(self, YtError(repr(exc)))
                    except (ConnectionError, BrokenPipeError):
                        pass

            do_GET = do_POST = do_PUT = _run

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="http-proxy")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    # -- request handling ------------------------------------------------------

    _MAX_CLIENTS = 64

    def _client(self, user: str):
        with self._clients_lock:
            client = self._clients.get(user)
            if client is not None:
                # LRU touch.
                self._clients.pop(user)
                self._clients[user] = client
                return client
            # X-YT-User is caller-supplied: bound the cache or unique
            # user strings leak one connection each.
            while len(self._clients) >= self._MAX_CLIENTS:
                _, evicted = self._clients.popitem(last=False)
                try:
                    evicted.close()
                except Exception:   # noqa: BLE001 — eviction best-effort
                    pass
            client = self._clients[user] = self._client_factory(user)
            return client

    def _handle(self, request) -> None:
        parsed = urllib.parse.urlsplit(request.path)
        path = parsed.path.rstrip("/") or "/"
        # ALWAYS drain the request body first: replying while unread body
        # bytes sit on a keep-alive connection corrupts the next request.
        length = int(request.headers.get("Content-Length") or 0)
        raw_body = request.rfile.read(length) if length else b""
        if path == "/ping":
            self._reply(request, 200, b"", "text/plain")
            return
        if path in ("/api", "/api/v4"):
            body = json.dumps(sorted(COMMANDS)).encode()
            self._reply(request, 200, body, "application/json")
            return
        if path == "/hosts":
            body = json.dumps([self.address]).encode()
            self._reply(request, 200, body, "application/json")
            return
        if not path.startswith("/api/v4/"):
            self._reply(request, 404, b"not found", "text/plain")
            return
        command = path[len("/api/v4/"):]
        if command not in COMMANDS:
            self._reply_error(request, YtError(
                f"Unknown command {command!r}",
                code=EErrorCode.NoSuchMethod), status=404)
            return
        user = request.headers.get("X-YT-User", "root")
        params, data_body = self._parse_parameters(request, parsed,
                                                   raw_body)
        # Serving-plane deadline: X-YT-Timeout (seconds) maps onto the
        # query gateway's deadline for lookup/select commands.
        header_timeout = request.headers.get("X-YT-Timeout")
        if header_timeout and command in ("select_rows", "lookup_rows"):
            try:
                params.setdefault("timeout", float(header_timeout))
            except ValueError:
                pass
        # Distributed tracing over HTTP (ISSUE 5): an X-YT-Trace-Id
        # header pins (and force-samples) the query's trace id, so the
        # caller can fetch the span tree from /traces/<id> afterwards;
        # the id is echoed on the response either way the trace rooted.
        trace_header = request.headers.get("X-YT-Trace-Id")
        from ytsaurus_tpu.utils.tracing import NULL_SPAN, start_query_span
        span = NULL_SPAN
        if command in ("select_rows", "lookup_rows"):
            span = start_query_span(f"http.{command}",
                                    force=trace_header is not None,
                                    trace_id=trace_header or None,
                                    user=user)
        if span.trace_id:
            request.yt_trace_id = span.trace_id
        try:
            with span:
                result = self._execute(command, params, data_body, user)
        except YtError as err:
            self._reply_error(request, err)
            return
        self._reply_result(request, command, params, result)

    @staticmethod
    def _parse_parameters(request, parsed, body: bytes) -> tuple[dict, bytes]:
        params: dict = {}
        for key, value in urllib.parse.parse_qsl(parsed.query):
            try:
                params[key] = json.loads(value)
            except ValueError:
                params[key] = value
        header = request.headers.get("X-YT-Parameters")
        if header:
            params.update(json.loads(header))
        content_type = (request.headers.get("Content-Type") or "").split(
            ";")[0].strip()
        data_body = b""
        if body:
            if content_type == "application/json" and \
                    request.command == "POST":
                try:
                    params.update(json.loads(body))
                except ValueError:
                    data_body = body
            else:
                data_body = body       # table payload (write_table etc.)
        return params, data_body

    def _execute(self, command: str, params: dict, data_body: bytes,
                 user: str):
        client = self._client(user)
        descriptor = COMMANDS[command]
        if command == "write_table" and "rows" not in params:
            # Raw table payload in the request body (PUT/POST with a
            # format); JSON parameter rows take the registry path instead.
            params.setdefault("format", "json")
            return client.write_table(
                params["path"], data_body, format=params["format"],
                append=bool(params.get("append", False)))
        if command == "read_table":
            params.setdefault("format", "json")
        kwargs = dict(params)
        # The remote client mirrors driver commands as methods where the
        # shapes differ; everything else goes through the registry.
        if hasattr(client, "_execute"):
            return client._execute(command, kwargs, idempotent=not
                                   descriptor.is_mutating)
        from ytsaurus_tpu.driver import Driver
        return Driver(client).execute(command, kwargs)

    def _reply_result(self, request, command: str, params: dict,
                      result) -> None:
        if isinstance(result, bytes):
            fmt = params.get("format", "json")
            ctype = _FORMAT_CONTENT_TYPES.get(fmt,
                                              "application/octet-stream")
            self._reply(request, 200, result, ctype)
            return
        body = json.dumps({"value": result}, default=_json_default).encode()
        self._reply(request, 200, body, "application/json")

    def _reply_error(self, request, err: YtError,
                     status: int = 400) -> None:
        from ytsaurus_tpu.errors import retry_after_hint
        retry_after = None
        if err.contains(EErrorCode.RequestThrottled):
            # Admission rejection → 429 + Retry-After, the HTTP shape of
            # the serving plane's retry_after hint.
            status = 429
            retry_after = retry_after_hint(err)
        elif err.contains(EErrorCode.DeadlineExceeded):
            status = 504
        body = json.dumps(err.to_dict(), default=_json_default).encode()
        request.send_response(status)
        request.send_header("Content-Type", "application/json")
        trace_id = getattr(request, "yt_trace_id", None)
        if trace_id:
            request.send_header("X-YT-Trace-Id", trace_id)
        if retry_after is not None:
            request.send_header("Retry-After", f"{retry_after:.3f}")
        request.send_header("X-YT-Error", json.dumps(
            {"code": err.code, "message": err.message},
            default=_json_default)[:1024])
        request.send_header("Content-Length", str(len(body)))
        request.end_headers()
        request.wfile.write(body)

    @staticmethod
    def _reply(request, status: int, body: bytes, ctype: str) -> None:
        request.send_response(status)
        request.send_header("Content-Type", ctype)
        trace_id = getattr(request, "yt_trace_id", None)
        if trace_id:
            request.send_header("X-YT-Trace-Id", trace_id)
        request.send_header("Content-Length", str(len(body)))
        request.end_headers()
        request.wfile.write(body)
