"""Tiled stable LSD radix sort for large rowsets.

Why: one-pass variadic sort (jax.lax.sort over all key words at once)
drags every operand through an O(n log^2 n) compare-exchange network whose
depth grows with the FULL row count — past ~8M rows on v5e the warm-up
never completes (the round-2 "sort cliff").  The TPU-shaped replacement
keeps every sort network TILE-sized and does the global movement with
histogram arithmetic:

  per 8-bit digit pass:
    1. batched per-tile stable sort by (digit, position) — ONE u32
       composite key, network depth log^2(TILE) not log^2(n), vectorized
       across tiles on the VPU;
    2. per-tile bin offsets via batched searchsorted over the sorted
       digits (a (tiles, 256) table — tiny);
    3. global stable rank for every output slot from exclusive cumsums of
       that table, inverted with a vectorized binary search (log(tiles)
       gather sweeps over the cumulative table);
    4. one contiguous-run gather moves the payload planes.

No data-dependent shapes, no giant network, no scatter (TPU scatters with
duplicate indices serialize; the one permutation scatter variant is kept
behind engine="scatter" for measurement, using unique_indices=True).

Reference analog: the Sort operation's partition tree + k-way heap merge
(yt/yt/server/controller_agent/controllers/sort_controller.cpp:459,
yt/yt/ytlib/table_client/partition_sort_reader.h:20) — re-expressed as
counting-rank movement instead of comparison merges, which is what a
batch-synchronous vector machine wants.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

# Tile size for the per-tile sort networks: the composite key is
# (digit << LOG_TILE) | position, so RADIX_BITS + LOG_TILE must be <= 32.
RADIX_TILE = int(os.environ.get("YT_TPU_RADIX_TILE", 2048))
RADIX_BITS = 8
_B = 1 << RADIX_BITS


def _exclusive(x, axis):
    return jnp.cumsum(x, axis=axis) - x


def radix_pass(digit: jax.Array, payloads: list[jax.Array],
               engine: str = "gather") -> list[jax.Array]:
    """One stable ascending partition by `digit` (u32 values < 256).

    digit and each payload are (N,) with N % RADIX_TILE == 0; returns the
    payloads reordered by a stable counting sort on digit."""
    n = digit.shape[0]
    if n == 0:
        return list(payloads)
    tile = min(RADIX_TILE, n)
    nt = n // tile
    log_tile = tile.bit_length() - 1
    assert tile == 1 << log_tile and n == nt * tile
    assert RADIX_BITS + log_tile <= 32

    d2 = digit.reshape(nt, tile).astype(jnp.uint32)
    pos = jnp.arange(tile, dtype=jnp.uint32)
    composite = (d2 << np.uint32(log_tile)) | pos[None, :]
    operands = (composite,) + tuple(p.reshape(nt, tile) for p in payloads)
    # The composite key is unique within a tile, so a non-stable sort is
    # stable by construction (and cheaper).
    sorted_ops = jax.lax.sort(operands, dimension=1, num_keys=1,
                              is_stable=False)
    d_sorted = (sorted_ops[0] >> np.uint32(log_tile)).astype(jnp.int32)
    pay_sorted = [p.reshape(n) for p in sorted_ops[1:]]

    # local_start[t, b] = first position of digit b inside tile t.
    bins = jnp.arange(_B, dtype=jnp.int32)
    local_start = jax.vmap(
        lambda row: jnp.searchsorted(row, bins, side="left"))(d_sorted)
    local_start = local_start.astype(jnp.int32)                 # (nt, B)
    ends = jnp.concatenate(
        [local_start[:, 1:], jnp.full((nt, 1), tile, jnp.int32)], axis=1)
    counts = ends - local_start                                 # (nt, B)

    per_bin = counts.sum(axis=0)                                # (B,)
    bin_start = _exclusive(per_bin, 0)                          # (B,)
    tile_excl = _exclusive(counts, 0)                           # (nt, B)

    if engine == "scatter":
        # dest of tile t's bin-b run = bin_start[b] + rows of b in earlier
        # tiles; every element's destination is unique (a permutation).
        run_start = bin_start[None, :] + tile_excl              # (nt, B)
        rs = jnp.take_along_axis(run_start, d_sorted, axis=1)
        ls = jnp.take_along_axis(local_start, d_sorted, axis=1)
        dest = (rs + (pos[None, :].astype(jnp.int32) - ls)).reshape(n)
        return [jnp.zeros(n, p.dtype).at[dest].set(
                    p, unique_indices=True, mode="drop")
                for p in pay_sorted]

    # engine == "gather": invert the permutation by rank arithmetic.
    # For output slot j: which bin, which tile, which local row?
    j = jnp.arange(n, dtype=jnp.int32)
    b = jnp.clip(jnp.searchsorted(bin_start, j, side="right") - 1, 0,
                 _B - 1).astype(jnp.int32)
    k = j - bin_start[b]                       # rank of j within its bin
    # Vectorized binary search over the per-bin inclusive tile cumsums:
    # t(j) = first tile whose inclusive count exceeds k.
    ccounts = (tile_excl + counts).T.reshape(-1)     # (B*nt,) row-major b
    lo = jnp.zeros(n, jnp.int32)
    hi = jnp.full(n, nt, jnp.int32)
    for _ in range(max(nt.bit_length(), 1)):
        mid = (lo + hi) >> 1
        go_right = ccounts[b * nt + jnp.minimum(mid, nt - 1)] <= k
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right, hi, mid)
    t = jnp.clip(lo, 0, nt - 1)
    prev = jnp.where(t > 0, ccounts[b * nt + jnp.maximum(t - 1, 0)], 0)
    r = k - prev                               # rank within tile t's run
    src = t * tile + local_start.reshape(-1)[t * _B + b] + r
    return [p[src] for p in pay_sorted]


def _pad_to_tile(x: jax.Array, n_pad: int, fill) -> jax.Array:
    if n_pad == 0:
        return x
    return jnp.concatenate([x, jnp.full(n_pad, fill, x.dtype)])


def radix_argsort_u32(words: list[jax.Array],
                      word_bits: "list[int] | None" = None,
                      engine: str = "gather") -> jax.Array:
    """Stable ascending argsort over u32 key words (major word first) via
    LSD radix passes.  `word_bits[k]` bounds the significant LOW bits of
    word k (higher bits must be zero) — digit passes above the bound are
    skipped, so a packed 12-bit key costs 2 byte passes, not 4.

    engine: "gather" | "scatter" (ops above) | "pallas" (counting pass
    as a Pallas TPU kernel + permutation scatter, ops/pallas_radix.py).

    Pad rows (to the tile multiple) carry all-ones keys and sort last;
    ties against real all-ones rows resolve to the real rows first by
    stability (pad payload indices are appended after)."""
    n = words[0].shape[0]
    if n == 0:
        # A forced engine must not die on an empty rowset (tile math
        # degenerates); the identity permutation is the sorted order.
        return jnp.arange(0, dtype=jnp.uint32)
    if word_bits is None:
        word_bits = [32] * len(words)
    if engine == "pallas":
        from ytsaurus_tpu.ops.pallas_radix import (
            PALLAS_BITS,
            PALLAS_TILE,
            radix_pass_pallas,
        )
        pass_bits = PALLAS_BITS
        tile = PALLAS_TILE
        pass_fn = lambda d, p: radix_pass_pallas(d, p, PALLAS_BITS)  # noqa: E731
    else:
        pass_bits = RADIX_BITS
        tile = min(RADIX_TILE, 1 << max(n - 1, 1).bit_length())
        pass_fn = lambda d, p: radix_pass(d, p, engine=engine)  # noqa: E731
    padded = ((n + tile - 1) // tile) * tile
    n_pad = padded - n
    perm = jnp.arange(padded, dtype=jnp.uint32)
    mask = np.uint32((1 << pass_bits) - 1)
    for word, bits in zip(reversed(words), reversed(word_bits)):
        if bits <= 0:
            continue
        # Pad keys sort last: all-ones is the maximum in every pass.
        fill = np.uint32((1 << min(bits, 32)) - 1)
        wpad = _pad_to_tile(word.astype(jnp.uint32), n_pad, fill)
        for shift in range(0, min(bits, 32), pass_bits):
            digit = (jnp.take(wpad, perm) >> np.uint32(shift)) & mask
            (perm,) = pass_fn(digit, [perm])
    return perm[:n]
