"""Workload recorder + replay harness and the compilation observatory
(ISSUE 8): query normalization (literal hoisting), the bounded/sampled/
rotated workload log with versioned capture export/import, recording
through the select/lookup planes, per-fingerprint compile telemetry
(miss causes, shape spectrum, evictions, artifacts), the pow2
capacity-bucket satellite in EXPLAIN ANALYZE, pool-sensor/observatory
reconciliation under concurrent mixed-pool traffic, the
recompilation-storm SLO (fires AND resolves), open-loop replay
reporting (p50/p99/p999 + steady-state hit rate + slowest trace ids),
the /workload + /compile monitoring endpoints, and the CLI surfaces.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from ytsaurus_tpu import config as yt_config
from ytsaurus_tpu.errors import EErrorCode, ThrottledError, YtError
from ytsaurus_tpu.query import workload as wl
from ytsaurus_tpu.query.lexer import tokenize
from ytsaurus_tpu.schema import TableSchema


@pytest.fixture(autouse=True)
def _workload_defaults():
    """Every test starts from a fresh workload log + observatory and
    leaves the process-wide configs restored."""
    wl.get_workload_log().clear()
    from ytsaurus_tpu.query.engine.evaluator import (
        get_compile_observatory,
    )
    yield
    yt_config.set_workload_config(None)
    wl.configure(None)
    get_compile_observatory().reset()


@pytest.fixture(scope="module")
def client(tmp_path_factory):
    from ytsaurus_tpu.client import connect
    from ytsaurus_tpu.config import ServingConfig
    c = connect(str(tmp_path_factory.mktemp("workload-cluster")))
    # Two REAL admission pools so mixed-pool traffic lands on distinct
    # `pool=` sensor arms (the reconciliation satellite's setting).
    c.cluster.serving_config = ServingConfig(
        pools={"default": 1.0, "other": 1.0})
    schema = TableSchema.make(
        [("k", "int64", "ascending"), ("v", "int64")], unique_keys=True)
    c.create("table", "//wl/t",
             attributes={"schema": schema, "dynamic": True},
             recursive=True)
    c.mount_table("//wl/t")
    c.insert_rows("//wl/t", [{"k": i, "v": i * 2} for i in range(100)])
    return c


def _fresh_evaluator_inputs(n_rows=100):
    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    schema = TableSchema.make([("k", "int64"), ("v", "int64")])
    chunk = ColumnarChunk.from_arrays(schema, {
        "k": np.arange(n_rows, dtype=np.int64),
        "v": np.arange(n_rows, dtype=np.int64)})
    return schema, chunk


def _plan(query, schema):
    from ytsaurus_tpu.query.builder import build_query
    return build_query(query, {"//t": schema})


# -- query normalization -------------------------------------------------------

def test_normalize_hoists_literals_and_round_trips():
    q = ("k, v FROM [//some/table] WHERE k = 42 AND s = 'a\"b' "
         "AND d < 1.5 AND k IN (1, 2, 3)")
    normalized, literals = wl.normalize_query(q)
    assert normalized.count("?") == len(literals) == 6
    assert [kind for kind, _v in literals] == \
        ["int64", "string", "double", "int64", "int64", "int64"]
    assert "42" not in normalized and "a\"b" not in normalized
    back = wl.substitute_literals(normalized, literals)
    assert [(t.kind, t.value) for t in tokenize(back)] == \
        [(t.kind, t.value) for t in tokenize(q)]


def test_normalize_is_literal_invariant():
    a = wl.normalize_query("v FROM [//t] WHERE k = 1 AND s = 'x'")
    b = wl.normalize_query("v FROM [//t] WHERE k = 999 AND s = 'yyy'")
    assert a[0] == b[0]
    assert wl.query_fingerprint(a[0]) == wl.query_fingerprint(b[0])
    c = wl.normalize_query("v FROM [//t] WHERE k > 1 AND s = 'x'")
    assert wl.query_fingerprint(c[0]) != wl.query_fingerprint(a[0])


def test_substitute_mismatch_fails_loudly():
    with pytest.raises(YtError):
        wl.substitute_literals("k = ? AND v = ?", [("int64", 1)])


# -- the bounded log -----------------------------------------------------------

def test_log_is_bounded_and_sampled():
    log = wl.WorkloadLog(yt_config.WorkloadConfig(capacity=8))
    for i in range(20):
        log.observe(wl.WorkloadRecord(query=f"q{i}"))
    assert log.recorded_n == 20 and len(log.records()) == 8
    dropped = wl.WorkloadLog(yt_config.WorkloadConfig(sample_rate=0.0))
    assert not dropped.observe(wl.WorkloadRecord(query="q"))
    assert dropped.sampled_out_n == 1 and not dropped.records()
    off = wl.WorkloadLog(yt_config.WorkloadConfig(enabled=False))
    assert not off.observe_select("k FROM [//t]")


def test_fingerprint_rollup_is_bounded():
    log = wl.WorkloadLog(yt_config.WorkloadConfig(
        fingerprint_capacity=2))
    for i in range(4):
        log.observe(wl.WorkloadRecord(query=f"shape{i}",
                                      fingerprint=f"fp{i}"))
    assert len(log.fingerprints(top=0)) == 2
    assert log.fingerprints_dropped_n == 2


def test_disk_log_rotates_with_versioned_headers(tmp_path):
    cfg = yt_config.WorkloadConfig(log_dir=str(tmp_path),
                                   rotate_bytes=4096, max_files=2)
    log = wl.WorkloadLog(cfg)
    for i in range(40):
        log.observe(wl.WorkloadRecord(query="k FROM [//t] WHERE k = ?",
                                      literals=[["int64", i]],
                                      wall_time=0.001 * i))
    base = tmp_path / wl.WorkloadLog.LOG_NAME
    assert base.exists() and (tmp_path / (wl.WorkloadLog.LOG_NAME +
                                          ".1")).exists()
    header = json.loads(base.read_text().splitlines()[0])
    assert header["workload_schema"] == wl.WORKLOAD_SCHEMA_VERSION
    records = log.read_disk_log()
    assert records and all(r.query == "k FROM [//t] WHERE k = ?"
                           for r in records)
    # A version-tampered file refuses to load.
    lines = base.read_text().splitlines()
    base.write_text("\n".join([json.dumps({"workload_schema": 999}),
                               *lines[1:]]) + "\n")
    with pytest.raises(YtError, match="incompatible"):
        log.read_disk_log()


def test_capture_roundtrip_and_version_check(tmp_path):
    log = wl.WorkloadLog(yt_config.WorkloadConfig())
    for i in range(5):
        log.observe(wl.WorkloadRecord(query="v FROM [//t] WHERE k = ?",
                                      literals=[["int64", i]],
                                      pool="p", outcome="ok"))
    path = tmp_path / "capture.json"
    assert log.export_capture(str(path)) == 5
    records = wl.load_capture(str(path))
    assert len(records) == 5
    assert records[3].literals == [["int64", 3]]
    assert records[3].pool == "p"
    # Incompatible schema version fails loudly BEFORE anything replays
    # (the versioned workload-log satellite).
    payload = json.loads(path.read_text())
    payload["workload_schema"] = wl.WORKLOAD_SCHEMA_VERSION + 1
    path.write_text(json.dumps(payload))
    with pytest.raises(YtError, match="incompatible"):
        wl.load_capture(str(path))
    with pytest.raises(YtError):
        wl.load_capture(str(tmp_path / "missing.json"))


def test_planner_feedback_ledger_roundtrip(tmp_path):
    """ISSUE 20 satellite: the per-query est-vs-actual join drift rides
    the workload record (schema v2, `join_est_error`), observe_select
    derives it from the join plan, the fingerprint roll-up keeps the
    max, the capture round-trips it bit-exactly — and a v1 capture
    refuses to load loudly."""
    from ytsaurus_tpu.query.statistics import QueryStatistics
    assert wl.WORKLOAD_SCHEMA_VERSION == 2
    log = wl.WorkloadLog(yt_config.WorkloadConfig())
    stats = QueryStatistics()
    stats.note_join_stage(0, "//dim", "broadcast",
                          est_rows=100, actual_rows=150)
    stats.note_join_stage(1, "//dim2", "partition",
                          est_rows=80, actual_rows=80)
    assert log.observe_select(
        "g, name FROM [//t] JOIN [//dim] ON g = dk WHERE v > 5",
        stats=stats)
    rec = log.records()[-1]
    assert rec.join_est_error == 0.3333       # |150 - 100| / 150
    # A later execution of the same fingerprint with a better estimate
    # must not shrink the recorded worst case.
    stats2 = QueryStatistics()
    stats2.note_join_stage(0, "//dim", "broadcast",
                           est_rows=150, actual_rows=150)
    assert log.observe_select(
        "g, name FROM [//t] JOIN [//dim] ON g = dk WHERE v > 9",
        stats=stats2)
    (entry,) = log.fingerprints(top=0)
    assert entry["count"] == 2
    assert entry["join_est_error_max"] == 0.3333
    path = tmp_path / "capture.json"
    assert log.export_capture(str(path)) == 2
    records = wl.load_capture(str(path))
    assert [r.join_est_error for r in records] == [0.3333, 0.0]
    # A capture written by the v1 schema (no drift ledger) refuses to
    # load — silently defaulting the field would poison the planner
    # feedback it exists to provide.
    payload = json.loads(path.read_text())
    payload["workload_schema"] = 1
    path.write_text(json.dumps(payload))
    with pytest.raises(YtError, match="incompatible"):
        wl.load_capture(str(path))


# -- recording through the planes ----------------------------------------------

def test_select_folds_workload_record(client):
    log = wl.get_workload_log()
    client.select_rows("k, v FROM [//wl/t] WHERE k < 7")
    rec = log.records()[-1]
    assert rec.kind == "select" and rec.outcome == "ok"
    assert rec.query == "k, v FROM [//wl/t] WHERE k < ?"
    assert rec.literals == [["int64", 7]]
    assert rec.pool == "default" and rec.wall_time > 0
    assert rec.capacity_buckets, "pow2 buckets must ride the record"
    assert rec.trace_id, "sampled select must carry its trace id"
    rollup = log.fingerprints()
    assert rollup[0]["count"] >= 1 and rollup[0]["ok"] >= 1


def test_throttled_select_records_outcome(client):
    from ytsaurus_tpu.utils import failpoints
    log = wl.get_workload_log()
    with failpoints.active("serving.admit=error", seed=1):
        with pytest.raises(ThrottledError):
            client.select_rows("k FROM [//wl/t]")
    rec = log.records()[-1]
    assert rec.outcome == "throttled"
    assert log.fingerprints(top=0)[0]["throttled"] >= 1 or any(
        e["throttled"] >= 1 for e in log.fingerprints(top=0))


def test_lookup_folds_workload_record(client):
    log = wl.get_workload_log()
    rows = client.lookup_rows("//wl/t", [(3,), (5,)])
    assert rows[0]["v"] == 6
    recs = [r for r in log.records() if r.kind == "lookup"]
    assert recs, "gateway lookups must fold into the workload log"
    rec = recs[-1]
    assert rec.table == "//wl/t" and rec.keys == 2
    assert [tuple(lit[1]) for lit in rec.literals] == [(3,), (5,)]
    assert rec.outcome == "ok"


def test_explain_analyze_reports_capacity_buckets(client):
    """ISSUE 8 satellite: the pow2 capacity bucket each program
    compiled against is visible PER QUERY, so bucket churn (a
    shape-spectrum leak) shows up in EXPLAIN ANALYZE, not just in
    aggregate."""
    schema = TableSchema.make(
        [("k", "int64", "ascending"), ("v", "int64")], unique_keys=True)
    client.create("table", "//wl/buckets",
                  attributes={"schema": schema, "dynamic": True},
                  recursive=True)
    client.mount_table("//wl/buckets")
    client.insert_rows("//wl/buckets",
                       [{"k": i, "v": i} for i in range(10)])
    p1 = client.select_rows("k FROM [//wl/buckets] WHERE v >= 0",
                            explain_analyze=True)
    buckets1 = p1.statistics["capacity_buckets"]
    assert buckets1 == [128]
    client.insert_rows("//wl/buckets",
                       [{"k": i, "v": i} for i in range(10, 200)])
    p2 = client.select_rows("k FROM [//wl/buckets] WHERE v >= 0",
                            explain_analyze=True)
    buckets2 = p2.statistics["capacity_buckets"]
    assert buckets2 and buckets2 != buckets1, "bucket churn invisible"
    assert "capacity buckets" in p2.format()


# -- compilation observatory ---------------------------------------------------

def test_observatory_miss_causes_and_eviction():
    from ytsaurus_tpu.query.engine.evaluator import (
        Evaluator,
        get_compile_observatory,
    )
    from ytsaurus_tpu.query.statistics import QueryStatistics
    obs = get_compile_observatory()
    obs.reset()
    yt_config.set_workload_config(
        yt_config.WorkloadConfig(compile_cache_capacity=1))
    schema, small = _fresh_evaluator_inputs(100)
    _schema, big = _fresh_evaluator_inputs(500)
    plan_a = _plan("k, v FROM [//t] WHERE v < 5", schema)
    plan_b = _plan("k, sum(v) AS s FROM [//t] GROUP BY k", schema)
    ev = Evaluator()
    stats = QueryStatistics()
    ev.run_plan(plan_a, small, stats=stats)   # never-seen shape
    assert stats.compile_new_fingerprint == 1
    ev.run_plan(plan_a, big, stats=stats)     # same shape, new bucket
    assert stats.compile_new_shape == 1
    ev.run_plan(plan_b, small, stats=stats)   # evicts plan_a programs
    stats2 = QueryStatistics()
    ev.run_plan(plan_a, small, stats=stats2)  # re-miss on evicted key
    assert stats2.compile_evicted == 1
    totals = obs.totals()
    assert totals["misses"] == 4 and totals["evictions"] == 3
    top = obs.top(5)
    assert top[0]["compile_seconds"] > 0
    fp_a = [r for r in top if r["compiles"] == 3][0]
    assert fp_a["shape_count"] == 2 and fp_a["evictions"] >= 1
    assert fp_a["last_miss_cause"] == "eviction"
    # The slow-query-log rendering names the cause (satellite).
    from ytsaurus_tpu.query.profile import format_profile_dict
    text = format_profile_dict({"statistics": stats2.to_dict()})
    assert "evicted 1" in text


def test_observatory_captures_artifacts_behind_flag():
    from ytsaurus_tpu.query.engine.evaluator import (
        Evaluator,
        get_compile_observatory,
    )
    obs = get_compile_observatory()
    obs.reset()
    yt_config.set_workload_config(
        yt_config.WorkloadConfig(capture_artifacts=True,
                                 artifact_capacity=4))
    schema, chunk = _fresh_evaluator_inputs(64)
    Evaluator().run_plan(_plan("k FROM [//t] WHERE v < 3", schema),
                         chunk)
    arts = obs.artifacts()
    assert len(arts) == 1
    assert arts[0]["hlo"], "HLO text must be captured"
    assert arts[0]["compile_seconds"] > 0
    assert arts[0]["flops"] is not None
    # /compile payload carries artifact metadata without the HLO blob.
    snap = obs.snapshot()
    assert snap["artifacts"] and "hlo" not in snap["artifacts"][0]
    # Default config captures nothing.
    yt_config.set_workload_config(None)
    obs.reset()
    schema, chunk = _fresh_evaluator_inputs(32)
    Evaluator().run_plan(_plan("k FROM [//t] WHERE v < 9", schema),
                         chunk)
    assert not obs.artifacts()


def _compile_sensor_totals():
    from ytsaurus_tpu.utils.profiling import get_registry
    registry = get_registry()
    totals = {"hits": 0.0, "misses": 0.0}
    with registry._lock:
        items = list(registry._sensors.items())
    for (name, _tags), sensor in items:
        if name == "/query/compile_cache/hits":
            totals["hits"] += sensor.get()
        elif name == "/query/compile_cache/misses":
            totals["misses"] += sensor.get()
    return totals


def test_pool_sensors_reconcile_with_observatory(client):
    """ISSUE 8 satellite: per-pool `query_compile_cache_{hits,misses}`
    sensors reconcile EXACTLY with the observatory's per-fingerprint
    totals under concurrent mixed-pool replay traffic — both count the
    same dispatch events, or per-pool SLO math silently drifts."""
    from ytsaurus_tpu.query.engine.evaluator import (
        get_compile_observatory,
    )
    obs = get_compile_observatory()
    before_sensors = _compile_sensor_totals()
    before_obs = obs.totals()
    errors = []

    def worker(seed, pool):
        try:
            for i in range(6):
                client.select_rows(
                    f"k, v FROM [//wl/t] WHERE k < {10 + (seed + i) % 4}",
                    pool=pool)
        except Exception as exc:   # noqa: BLE001 — surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(s, pool),
                                daemon=True)
               for s, pool in enumerate(["default", "default", "other",
                                         "other"])]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    after_sensors = _compile_sensor_totals()
    after_obs = obs.totals()
    d_sensor_hits = after_sensors["hits"] - before_sensors["hits"]
    d_sensor_misses = after_sensors["misses"] - before_sensors["misses"]
    d_obs_hits = after_obs["hits"] - before_obs["hits"]
    d_obs_misses = after_obs["misses"] - before_obs["misses"]
    assert d_sensor_hits + d_sensor_misses == 24
    assert (d_sensor_hits, d_sensor_misses) == (d_obs_hits,
                                                d_obs_misses)
    # Both tag arms really took traffic (mixed-pool, not one pool).
    from ytsaurus_tpu.utils.profiling import get_registry
    with get_registry()._lock:
        pool_arms = {dict(tags).get("pool")
                     for (name, tags), _s in
                     get_registry()._sensors.items()
                     if name == "/query/compile_cache/hits"}
    assert {"default", "other"} <= pool_arms
    # Per-fingerprint rows sum to the same totals (delta-free check on
    # the observatory's own books).
    rows = obs.top(0)
    assert sum(r["compiles"] for r in rows) == after_obs["misses"]
    assert sum(r["hits"] for r in rows) == after_obs["hits"]


def test_recompilation_storm_slo_fires_and_resolves():
    """ISSUE 8 acceptance: a synthetic recompilation storm fires the
    compile-burn SLO alert over the PR 6 history rings and the alert
    resolves once the cache serves hits again."""
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    from ytsaurus_tpu.utils.profiling import MetricsHistory, get_registry
    from ytsaurus_tpu.utils.slo import SloTracker
    slo = dict(wl.COMPILE_STORM_SLO, fast_window=60.0,
               slow_window=300.0)
    tcfg = yt_config.TelemetryConfig.from_dict(
        {"slos": {"compile_storm": slo}})
    history = MetricsHistory(registry=get_registry())
    tracker = SloTracker(tcfg, history=history)
    schema, chunk = _fresh_evaluator_inputs(64)
    ev = Evaluator()
    # Distinct plan SHAPES (conjunct count varies): since ISSUE 10's
    # auto-parameterization, plans differing only in literal values
    # share one fingerprint and can no longer storm — exactly the fix
    # this SLO was built to watch land.
    plans = [_plan("k FROM [//t] WHERE " +
                   " AND ".join(f"v < {100 + j}" for j in range(i + 1)),
                   schema)
             for i in range(6)]
    # Warm one dispatch BEFORE the baseline sample: the compile-cache
    # counters are created lazily, and a series needs a pre-storm point
    # for window deltas to exist at all.
    ev.run_plan(_plan("k FROM [//t] WHERE v < 99", schema), chunk)
    t0 = 1_000_000.0
    history.sample_once(t0)
    for plan in plans:                      # storm: all misses
        ev.run_plan(plan, chunk)
    history.sample_once(t0 + 400.0)
    snap = tracker.evaluate(now=t0 + 400.0)
    assert snap["slos"]["compile_storm"]["firing"]
    assert [a["slo"] for a in snap["active_alerts"]] == ["compile_storm"]
    for _ in range(4):                      # recovery: all hits
        for plan in plans:
            ev.run_plan(plan, chunk)
    history.sample_once(t0 + 800.0)
    snap = tracker.evaluate(now=t0 + 800.0)
    assert not snap["slos"]["compile_storm"]["firing"]
    assert not snap["active_alerts"]
    assert [a["slo"] for a in snap["resolved_alerts"]] == \
        ["compile_storm"]


# -- replay --------------------------------------------------------------------

def test_replay_reports_latency_hit_rate_and_traces(client, tmp_path):
    log = wl.get_workload_log()
    for i in range(12):
        client.select_rows(
            f"k, v FROM [//wl/t] WHERE k < {5 + i % 3}")
    client.lookup_rows("//wl/t", [(1,), (2,)])
    path = tmp_path / "cap.json"
    log.export_capture(str(path))
    records = wl.load_capture(str(path))
    assert len(records) >= 13
    report = wl.replay(client, records, rate=300.0, max_workers=4)
    assert report["queries"] == len(records)
    assert report["ok"] == report["queries"]
    assert report["error"] == report["throttled"] == \
        report["deadline"] == 0
    lat = report["latency"]
    assert 0 < lat["p50_ms"] <= lat["p99_ms"] <= lat["p999_ms"] <= \
        lat["max_ms"]
    cache = report["compile_cache"]
    # Every shape was compiled during recording: the replay itself is
    # all hits — the steady-state discipline ROADMAP 1 will gate on.
    assert cache["hit_rate"] == 1.0
    assert cache["steady_hit_rate"] == 1.0
    # Drive-by satellite: slowest queries embed their trace ids so a
    # bad run is diagnosable via /traces without re-running.
    assert report["slowest"]
    slowest = report["slowest"][0]
    assert slowest["trace_id"]
    from ytsaurus_tpu.utils.tracing import span_tree
    assert span_tree(slowest["trace_id"]), \
        "slowest trace id must resolve in /traces"


def test_replay_paces_by_recorded_spacing():
    schema_recs = wl.synthesize_mix(["x FROM [//t] WHERE x = {}"],
                                    count=8, interval=0.05, seed=3)

    seen = []

    class FakeClient:
        def select_rows(self, query, pool=None, timeout=None,
                        explain_analyze=False):
            seen.append(query)
            return {"trace_id": None,
                    "statistics": {"cache_hits": 1, "compile_count": 0},
                    "wall_time": 0.0}

    import time as _time
    t0 = _time.perf_counter()
    report = wl.replay(FakeClient(), schema_recs, speed=4.0)
    elapsed = _time.perf_counter() - t0
    assert len(seen) == 8 and report["ok"] == 8
    # 7 gaps x 50ms / speed 4 ~= 87ms of pacing.
    assert elapsed >= 0.07
    assert report["offered_rate"] == pytest.approx(80.0, rel=0.01)
    assert report["compile_cache"]["hit_rate"] == 1.0
    with pytest.raises(YtError):
        wl.replay(FakeClient(), [])


def test_synthesize_mix_shapes():
    records = wl.synthesize_mix(
        ["v FROM [//t] WHERE k = {}",
         "g, sum(v) AS s FROM [//t] WHERE v < {} GROUP BY g"],
        count=20, distinct=4, seed=1)
    assert len(records) == 20
    fps = {r.fingerprint for r in records}
    assert len(fps) == 2, "one fingerprint per SHAPE, not per literal"
    q = wl.substitute_literals(records[0].query, records[0].literals)
    tokenize(q)   # reconstructed text must lex


# -- endpoints + CLI -----------------------------------------------------------

def test_monitoring_endpoints_round_trip(client):
    from ytsaurus_tpu.server.monitoring import MonitoringServer
    client.select_rows("k FROM [//wl/t] WHERE v < 4")
    server = MonitoringServer(port=0)
    server.start()
    try:
        with urllib.request.urlopen(
                f"http://{server.address}/workload?limit=16") as resp:
            workload = json.loads(resp.read())
        assert workload["schema_version"] == wl.WORKLOAD_SCHEMA_VERSION
        assert workload["records"] and workload["fingerprints"]
        with urllib.request.urlopen(
                f"http://{server.address}/compile?top=5") as resp:
            compile_view = json.loads(resp.read())
        assert "totals" in compile_view
        assert len(compile_view["fingerprints"]) <= 5
    finally:
        server.stop()


def test_orchid_mounts():
    tree = __import__("ytsaurus_tpu.server.orchid",
                      fromlist=["default_orchid"]).default_orchid()
    assert "recorded" in tree.get("/workload")
    assert "totals" in tree.get("/compile")


def test_cli_compile_cache_top(client, capsys):
    from ytsaurus_tpu.cli import run
    client.select_rows("k FROM [//wl/t] WHERE v < 2")
    assert run(["compile-cache", "top", "--limit", "5"],
               client=client) == 0
    out = capsys.readouterr().out
    assert "fingerprint" in out and "compile_seconds" in out
    assert "totals:" in out


def test_cli_workload_and_replay(client, tmp_path, capsys):
    from ytsaurus_tpu.cli import run
    client.select_rows("k, v FROM [//wl/t] WHERE k < 9")
    cap = str(tmp_path / "cli-cap.json")
    assert run(["workload", "export", "--out", cap],
               client=client) == 0
    written = json.loads(capsys.readouterr().out)
    assert written["written"] >= 1
    assert run(["replay", "--capture", cap, "--rate", "200",
                "--json"], client=client) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["ok"] == report["queries"] >= 1
    assert "p999_ms" in report["latency"]
    # Pretty rendering names the trace ids.
    assert run(["replay", "--capture", cap, "--rate", "200"],
               client=client) == 0
    pretty = capsys.readouterr().out
    assert "trace=" in pretty and "p999" in pretty
    # `yt workload show` renders the fingerprint roll-up.
    assert run(["workload", "show"], client=client) == 0
    assert "fingerprint" in capsys.readouterr().out
    # An incompatible capture is refused loudly.
    payload = json.loads(open(cap).read())
    payload["workload_schema"] = 999
    bad = str(tmp_path / "bad-cap.json")
    open(bad, "w").write(json.dumps(payload))
    assert run(["replay", "--capture", bad], client=client) == 1
    assert "incompatible" in capsys.readouterr().err
