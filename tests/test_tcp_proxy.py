"""TCP proxy: leader-following byte router (ref yt/yt/server/tcp_proxy).
"""

import pytest

import jax

jax.config.update("jax_platforms", "cpu")

from ytsaurus_tpu.remote_client import connect_remote  # noqa: E402
from ytsaurus_tpu.server.tcp_proxy import TcpProxy  # noqa: E402


def test_tcp_proxy_routes_thin_client(tmp_path):
    from ytsaurus_tpu.environment import LocalCluster

    with LocalCluster(str(tmp_path / "c"), n_nodes=1,
                      replication_factor=1) as cluster:
        proxy = TcpProxy([cluster.primary_address]).start()
        try:
            # The client speaks to the PROXY address only.
            cl = connect_remote(proxy.address)
            cl.create("document", "//via/proxy", recursive=True)
            cl.set("//via/proxy", {"ok": True})
            assert cl.get("//via/proxy") == {"ok": True}
            cl.write_table("//via/t", [{"x": i} for i in range(50)])
            assert len(cl.read_table("//via/t")) == 50
            assert proxy.stats["connections"] >= 1
            assert set(proxy.stats["routed_to"]) == \
                {cluster.primary_address}
        finally:
            proxy.stop()


@pytest.mark.slow   # ~12s; tier-1 keeps proxy routing coverage via
# test_tcp_proxy_routes_thin_client, and leader failover via the
# election/clock failover suites
def test_tcp_proxy_follows_leader(tmp_path):
    from ytsaurus_tpu.environment import LocalCluster

    with LocalCluster(str(tmp_path / "e"), n_nodes=3, n_masters=2,
                      lease_ttl=3.0) as cluster:
        leader = cluster.leader_index(timeout=60)
        proxy = TcpProxy(list(cluster.master_addresses)).start()
        try:
            cl = connect_remote(proxy.address)
            cl.create("document", "//lf/a", recursive=True)
            assert proxy.stats["routed_to"] == {
                cluster.master_addresses[leader]:
                    proxy.stats["connections"]}
            # Kill the leader: NEW connections route to the successor.
            cluster.kill_leader()
            new_leader = cluster.leader_index(timeout=60)
            assert new_leader != leader
            cl2 = connect_remote(proxy.address)
            assert cl2.exists("//lf/a")
            assert cluster.master_addresses[new_leader] in \
                proxy.stats["routed_to"]
        finally:
            proxy.stop()
