"""Typed sensors on a tag tree + Prometheus-format export.

Ref shape: library/profiling (TProfiler: counters/gauges/summaries/
histograms registered under a tag tree, per-CPU sharded) and
library/profiling/solomon/exporter.h:25 (pull endpoint scraped by the
monitoring system, Prometheus-compatible rendering).

Redesign: one process-wide `ProfilerRegistry`; a `Profiler` is a (prefix,
tags) view onto it.  Sensors are lock-striped rather than per-CPU — host
Python threads, not fibers, are the concurrency unit here.  Rendering is
Prometheus text exposition (the de-facto pull format); the HTTP endpoint
lives on each daemon's monitoring server (`server/monitoring.py`).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Optional


def _escape_label_value(value) -> str:
    """Prometheus exposition escaping for label values: backslash,
    double quote, and newline must be escaped or the scrape line is
    grammatically invalid (the exposition-validator test enforces it)."""
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _format_tags(tags: dict[str, str]) -> str:
    if not tags:
        return ""
    inner = ",".join(
        f'{_sanitize(str(k))}="{_escape_label_value(v)}"'
        for k, v in sorted(tags.items()))
    return "{" + inner + "}"


def _sanitize(name: str) -> str:
    return name.strip("/").replace("/", "_").replace("-", "_").replace(".", "_")


class Counter:
    """Monotone counter."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def increment(self, delta: float = 1.0) -> None:
        with self._lock:
            self._value += delta

    def get(self) -> float:
        return self._value

    def samples(self):
        yield "counter", "", self._value


class Gauge:
    """Last-set value."""

    def __init__(self):
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = float(value)

    def get(self) -> float:
        return self._value

    def samples(self):
        yield "gauge", "", self._value


class Summary:
    """Count/sum/min/max/last of observed values."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.last = 0.0

    def record(self, value: float) -> None:
        with self._lock:
            self.count += 1
            self.sum += value
            self.min = min(self.min, value)
            self.max = max(self.max, value)
            self.last = value

    def samples(self):
        yield "summary", ".sum", self.sum
        yield "summary", ".count", self.count
        if self.count:
            yield "summary", ".min", self.min
            yield "summary", ".max", self.max


class Histogram:
    """Fixed-bucket histogram (upper bounds; +Inf implicit)."""

    DEFAULT_BOUNDS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                      30.0, 60.0)

    def __init__(self, bounds=None):
        self.bounds = tuple(bounds or self.DEFAULT_BOUNDS)
        self._lock = threading.Lock()
        self.buckets = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0

    def record(self, value: float) -> None:
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self.buckets[idx] += 1
            self.count += 1
            self.sum += value

    def samples(self):
        cumulative = 0
        for bound, n in zip(self.bounds, self.buckets):
            cumulative += n
            yield "histogram", f'.bucket{{le="{bound}"}}', cumulative
        yield "histogram", '.bucket{le="+Inf"}', self.count
        yield "histogram", ".sum", self.sum
        yield "histogram", ".count", self.count


class Timer:
    """Context manager recording elapsed seconds into a Summary/Histogram."""

    def __init__(self, sensor):
        self._sensor = sensor

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._sensor.record(time.perf_counter() - self._t0)
        return False


class ProfilerRegistry:
    """All sensors of one process, keyed by (name, frozen tags)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sensors: dict[tuple, object] = {}

    def _get(self, name: str, tags: dict, factory):
        key = (name, tuple(sorted(tags.items())))
        with self._lock:
            sensor = self._sensors.get(key)
            if sensor is None:
                sensor = self._sensors[key] = factory()
            return sensor

    def render_prometheus(self) -> str:
        """Text exposition format, stable ordering."""
        lines = []
        with self._lock:
            items = sorted(self._sensors.items(),
                           key=lambda kv: (kv[0][0], kv[0][1]))
        for (name, tags), sensor in items:
            metric = _sanitize(name)
            tag_str = _format_tags(dict(tags))
            for _kind, suffix, value in sensor.samples():
                if suffix.startswith(".bucket"):
                    # merge histogram le-tag with sensor tags
                    le = suffix[len(".bucket"):]
                    base = tag_str[:-1] + "," + le[1:] if tag_str \
                        else le
                    lines.append(f"{metric}_bucket{base} {value}")
                else:
                    lines.append(
                        f"{metric}{suffix.replace('.', '_')}{tag_str} "
                        f"{value}")
        return "\n".join(lines) + ("\n" if lines else "")

    def collect(self) -> dict:
        """Live snapshot as a plain dict (Orchid's data source)."""
        out = {}
        with self._lock:
            items = list(self._sensors.items())
        for (name, tags), sensor in items:
            entry = {suffix or "value": value
                     for _k, suffix, value in sensor.samples()
                     if not suffix.startswith(".bucket")}
            key = name + _format_tags(dict(tags))
            out[key] = entry if len(entry) > 1 else next(iter(entry.values()))
        return out


_global_registry = ProfilerRegistry()


def get_registry() -> ProfilerRegistry:
    return _global_registry


class Profiler:
    """A (prefix, tags) view: `Profiler('/query', {'pool': 'prod'})`.

    Ref TProfiler semantics: `.with_tags()` refines, sensor getters
    create-or-fetch.
    """

    def __init__(self, prefix: str = "", tags: Optional[dict] = None,
                 registry: Optional[ProfilerRegistry] = None):
        self.prefix = prefix
        self.tags = dict(tags or {})
        self.registry = registry or _global_registry

    def with_prefix(self, prefix: str) -> "Profiler":
        return Profiler(self.prefix + prefix, self.tags, self.registry)

    def with_tags(self, **tags) -> "Profiler":
        return Profiler(self.prefix, {**self.tags, **tags}, self.registry)

    def _name(self, name: str) -> str:
        return f"{self.prefix}/{name}" if self.prefix else name

    def counter(self, name: str) -> Counter:
        return self.registry._get(self._name(name), self.tags, Counter)

    def gauge(self, name: str) -> Gauge:
        return self.registry._get(self._name(name), self.tags, Gauge)

    def summary(self, name: str) -> Summary:
        return self.registry._get(self._name(name), self.tags, Summary)

    def histogram(self, name: str, bounds=None) -> Histogram:
        return self.registry._get(self._name(name), self.tags,
                                  lambda: Histogram(bounds))

    def timer(self, name: str) -> Timer:
        return Timer(self.summary(name))
