"""Continuous sampling CPU profiler + trace export.

Ref mapping:
  continuous profiler  → SamplingProfiler
    (library/ytprof/cpu_profiler.h — the reference samples stacks on a
     timer signal into pprof profiles; here the sampler walks
     sys._current_frames() on a daemon thread, the cross-platform
     Python analog of the SIGPROF stack walker)
  Jaeger trace export  → TraceExporter
    (library/tracing/jaeger/tracer.h:91 — the reference batches
     finished spans and flushes them to a Jaeger agent; here batches
     drain the span collector to a pluggable sink on a flush interval —
     a JSONL file sink stands in for the agent socket)

Both are always-on-capable: sampling costs one frame walk per interval
across all threads (~tens of µs), and the aggregated profile is served
live through Orchid as collapsed stacks (the flamegraph input format),
so an operator can pull a profile from a running daemon without
restarting anything.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import Callable, Optional

from ytsaurus_tpu.utils.tracing import get_collector


class SamplingProfiler:
    """Statistical CPU profiler over sys._current_frames()."""

    def __init__(self, interval: float = 0.01, max_depth: int = 24,
                 max_entries: int = 4096):
        self.interval = interval
        self.max_depth = max_depth
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._samples: "dict[str, int]" = {}     # collapsed stack → hits
        self._total = 0
        self._idle = 0                           # blocked-wait samples
        self._stop = threading.Event()
        self._thread: "Optional[threading.Thread]" = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "SamplingProfiler":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cpu-profiler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        me = threading.get_ident()
        while not self._stop.wait(self.interval):
            self.sample_once(exclude_thread=me)

    # -- sampling --------------------------------------------------------------

    # Leaves parked in these stdlib files are blocking waits, not CPU:
    # a daemon's many idle threads (RPC workers, background loops) would
    # otherwise dominate every profile with Event.wait frames.  The
    # reference's SIGPROF sampler gets this for free (it only fires on
    # CPU time); this is the frame-walker's approximation.
    _WAIT_FILES = ("threading.py", "selectors.py", "socket.py", "ssl.py",
                   "queue.py", "socketserver.py")

    def sample_once(self, exclude_thread: "Optional[int]" = None) -> None:
        frames = sys._current_frames()
        stacks = []
        idle = 0
        for thread_id, frame in frames.items():
            if thread_id == exclude_thread:
                continue
            leaf = frame.f_code
            leaf_file = leaf.co_filename.rsplit("/", 1)[-1]
            if leaf_file in self._WAIT_FILES or leaf.co_name == "sleep":
                idle += 1
                continue
            parts = []
            depth = 0
            while frame is not None and depth < self.max_depth:
                code = frame.f_code
                parts.append(f"{code.co_name} "
                             f"({code.co_filename.rsplit('/', 1)[-1]}:"
                             f"{frame.f_lineno})")
                frame = frame.f_back
                depth += 1
            stacks.append(";".join(reversed(parts)))
        with self._lock:
            self._idle += idle
            for stack in stacks:
                if stack in self._samples or \
                        len(self._samples) < self.max_entries:
                    self._samples[stack] = \
                        self._samples.get(stack, 0) + 1
                else:
                    # Past the entry cap every sample still lands
                    # SOMEWHERE, or hotspot shares would dilute over
                    # time (hits/total with silently dropped hits).
                    self._samples["(other)"] = \
                        self._samples.get("(other)", 0) + 1
            self._total += len(stacks)

    # -- reporting -------------------------------------------------------------

    def collapsed(self, top: int = 50) -> "list[str]":
        """Collapsed-stack lines `stack count` — flamegraph.pl input."""
        with self._lock:
            items = sorted(self._samples.items(), key=lambda kv: -kv[1])
        return [f"{stack} {count}" for stack, count in items[:top]]

    def hotspots(self, top: int = 15) -> "list[dict]":
        """Per-FRAME aggregation: where do samples actually land."""
        leaf_hits: "dict[str, int]" = {}
        with self._lock:
            total = max(self._total, 1)
            for stack, count in self._samples.items():
                leaf = stack.rsplit(";", 1)[-1]
                leaf_hits[leaf] = leaf_hits.get(leaf, 0) + count
        out = sorted(leaf_hits.items(), key=lambda kv: -kv[1])[:top]
        return [{"frame": frame, "samples": hits,
                 "share": round(hits / total, 4)}
                for frame, hits in out]

    def state(self) -> dict:
        with self._lock:
            return {"total_samples": self._total,
                    "idle_samples": self._idle,
                    "distinct_stacks": len(self._samples),
                    "interval": self.interval}

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._total = 0
            self._idle = 0


class TraceExporter:
    """Flushes finished spans from the collector to a sink in batches
    (the Jaeger-agent flush loop, ref jaeger/tracer.h:91)."""

    def __init__(self, sink: "Callable[[list[dict]], None]",
                 flush_interval: float = 2.0, collector=None,
                 recent_capacity: int = 64):
        from collections import deque
        self.sink = sink
        self.flush_interval = flush_interval
        self.collector = collector or get_collector()
        self.stats = {"batches": 0, "spans": 0}
        # Draining the shared collector would starve live-inspection
        # endpoints (/tracing/recent_spans): the exporter keeps its own
        # recent tail so those can serve from HERE when export is on.
        self.recent: "deque[dict]" = deque(maxlen=recent_capacity)
        self._flush_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: "Optional[threading.Thread]" = None

    def start(self) -> "TraceExporter":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="trace-exporter")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.flush_once()                   # drain the tail

    def _loop(self) -> None:
        while not self._stop.wait(self.flush_interval):
            try:
                self.flush_once()
            except Exception:   # noqa: BLE001 — export must not crash
                pass

    def flush_once(self) -> int:
        # stop() flushes the tail on the CALLER's thread while the loop
        # may be mid-flush: serialize, or stats/sink writes interleave.
        with self._flush_lock:
            spans = self.collector.drain()
            if not spans:
                return 0
            batch = [s.to_dict() for s in spans]
            self.sink(batch)
            self.recent.extend(batch)
            self.stats["batches"] += 1
            self.stats["spans"] += len(batch)
            return len(batch)


def jsonl_sink(path: str,
               max_bytes: int = 64 << 20) -> "Callable[[list[dict]], None]":
    """File sink: one JSON span per line (the agent-socket stand-in;
    ingestable by anything that reads OTLP/Jaeger-style JSON).  Rotates
    to `<path>.1` past max_bytes — an always-on exporter must not fill
    the daemon's volume."""
    import os
    lock = threading.Lock()

    def sink(batch: "list[dict]") -> None:
        with lock:
            try:
                if os.path.getsize(path) > max_bytes:
                    os.replace(path, path + ".1")
            except OSError:
                pass
            with open(path, "a") as f:
                for span in batch:
                    f.write(json.dumps(span, default=repr) + "\n")
    return sink
