"""Driver: string command name + parameters → typed client call.

Ref: the reference driver command registry (client/driver/driver.cpp:121) —
one table of command descriptors shared by every protocol front end (CLI,
HTTP proxy).  `execute(command, parameters)` dispatches onto YtClient; the
registry doubles as the machine-readable API surface list.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from ytsaurus_tpu.errors import EErrorCode, YtError


@dataclass(frozen=True)
class CommandDescriptor:
    name: str
    required: tuple[str, ...]
    optional: tuple[str, ...]
    is_mutating: bool
    invoke: Callable


def _d(name, required, optional, mutating, invoke):
    return CommandDescriptor(name=name, required=tuple(required),
                             optional=tuple(optional), is_mutating=mutating,
                             invoke=invoke)


def _select_rows_command(cl, p: dict):
    """select_rows with the EXPLAIN ANALYZE shape: explain_analyze=True
    returns the ExecutionProfile as a plain dict (wire/JSON safe — this
    registry feeds the RPC driver service and the HTTP proxy)."""
    kwargs = {k: p[k] for k in ("timeout", "pool", "params") if k in p}
    if p.get("explain_analyze"):
        profile = cl.select_rows(p["query"], explain_analyze=True,
                                 **kwargs)
        return profile.to_dict() if hasattr(profile, "to_dict") \
            else profile
    return cl.select_rows(p["query"], **kwargs)


def _registry() -> dict[str, CommandDescriptor]:
    c: dict[str, CommandDescriptor] = {}
    for d in [
        # cypress
        _d("create", ("type", "path"), ("attributes", "recursive",
                                        "ignore_existing", "tx"), True,
           lambda cl, p: cl.create(p["type"], p["path"],
                                   attributes=p.get("attributes"),
                                   recursive=p.get("recursive", False),
                                   ignore_existing=p.get("ignore_existing",
                                                         False),
                                   tx=p.get("tx"))),
        _d("get", ("path",), ("tx",), False,
           lambda cl, p: cl.get(p["path"], tx=p.get("tx"))),
        _d("set", ("path", "value"), ("tx",), True,
           lambda cl, p: cl.set(p["path"], p["value"], tx=p.get("tx"))),
        _d("exists", ("path",), (), False,
           lambda cl, p: cl.exists(p["path"])),
        _d("list", ("path",), (), False, lambda cl, p: cl.list(p["path"])),
        _d("remove", ("path",), ("recursive", "force", "tx"), True,
           lambda cl, p: cl.remove(p["path"],
                                   recursive=p.get("recursive", True),
                                   force=p.get("force", False),
                                   tx=p.get("tx"))),
        # master transactions + locks (ref: start_tx/lock driver commands)
        _d("start_tx", (), ("parent",), True,
           lambda cl, p: cl.start_tx(parent=p.get("parent"))),
        _d("commit_tx", ("tx",), (), True,
           lambda cl, p: cl.commit_tx(p["tx"])),
        _d("abort_tx", ("tx",), (), True,
           lambda cl, p: cl.abort_tx(p["tx"])),
        _d("lock", ("path", "tx"), ("mode",), True,
           lambda cl, p: cl.lock(p["path"],
                                 mode=p.get("mode", "exclusive"),
                                 tx=p["tx"])),
        # security (ref: security_client commands)
        _d("create_user", ("name",), (), True,
           lambda cl, p: cl.cluster.security.create_user(p["name"])),
        _d("create_group", ("name",), ("members",), True,
           lambda cl, p: cl.cluster.security.create_group(
               p["name"], members=p.get("members"))),
        _d("create_account", ("name",), ("resource_limits",), True,
           lambda cl, p: cl.cluster.security.create_account(
               p["name"], resource_limits=p.get("resource_limits"))),
        _d("add_member", ("group", "member"), (), True,
           lambda cl, p: cl.cluster.security.add_member(p["group"],
                                                        p["member"])),
        _d("remove_member", ("group", "member"), (), True,
           lambda cl, p: cl.cluster.security.remove_member(p["group"],
                                                           p["member"])),
        _d("check_permission", ("user", "permission", "path"), (), False,
           lambda cl, p: {"action": "allow"
                          if cl.cluster.security.check_permission(
                              p["user"], p["permission"], p["path"])
                          else "deny"}),
        _d("copy", ("source_path", "destination_path"), ("recursive",), True,
           lambda cl, p: cl.copy(p["source_path"], p["destination_path"],
                                 recursive=p.get("recursive", False))),
        _d("move", ("source_path", "destination_path"), ("recursive",), True,
           lambda cl, p: cl.move(p["source_path"], p["destination_path"],
                                 recursive=p.get("recursive", False))),
        _d("link", ("target_path", "link_path"), ("recursive",), True,
           lambda cl, p: cl.link(p["target_path"], p["link_path"],
                                 recursive=p.get("recursive", False))),
        # static tables
        _d("write_table", ("path", "rows"), ("append", "schema", "format"),
           True,
           lambda cl, p: cl.write_table(p["path"], p["rows"],
                                        append=p.get("append", False),
                                        schema=p.get("schema"),
                                        format=p.get("format"))),
        _d("read_table", ("path",), ("format",), False,
           lambda cl, p: cl.read_table(p["path"], format=p.get("format"))),
        # dynamic tables
        _d("mount_table", ("path",), (), True,
           lambda cl, p: cl.mount_table(p["path"])),
        _d("unmount_table", ("path",), (), True,
           lambda cl, p: cl.unmount_table(p["path"])),
        _d("freeze_table", ("path",), (), True,
           lambda cl, p: cl.freeze_table(p["path"])),
        _d("reshard_table", ("path", "pivot_keys"), (), True,
           lambda cl, p: cl.reshard_table(p["path"], p["pivot_keys"])),
        _d("insert_rows", ("path", "rows"), ("update",), True,
           lambda cl, p: cl.insert_rows(p["path"], p["rows"],
                                        update=p.get("update", False))),
        _d("delete_rows", ("path", "keys"), (), True,
           lambda cl, p: cl.delete_rows(p["path"], p["keys"])),
        _d("lookup_rows", ("path", "keys"),
           ("column_names", "timestamp", "timeout", "pool"), False,
           lambda cl, p: cl.lookup_rows(
               p["path"], p["keys"],
               **({"timestamp": p["timestamp"]} if "timestamp" in p else {}),
               **({"timeout": p["timeout"]} if "timeout" in p else {}),
               **({"pool": p["pool"]} if "pool" in p else {}),
               column_names=p.get("column_names"))),
        _d("select_rows", ("query",),
           ("timeout", "pool", "explain_analyze", "params"), False,
           lambda cl, p: _select_rows_command(cl, p)),
        _d("nearest_rows", ("path", "column", "query_vector", "k"),
           ("metric", "timestamp", "timeout", "pool"), False,
           lambda cl, p: cl.nearest_rows(
               p["path"], p["column"], p["query_vector"], int(p["k"]),
               metric=p.get("metric", "l2"),
               **({"timestamp": p["timestamp"]} if "timestamp" in p
                  else {}),
               **({"timeout": p["timeout"]} if "timeout" in p else {}),
               **({"pool": p["pool"]} if "pool" in p else {}))),
        _d("trim_rows", ("path", "trimmed_row_count"), (), True,
           lambda cl, p: cl.trim_rows(p["path"], p["trimmed_row_count"])),
        _d("push_queue", ("path", "rows"), (), True,
           lambda cl, p: cl.push_queue(p["path"], p["rows"])),
        _d("pull_queue", ("path", "offset"), ("limit",), False,
           lambda cl, p: cl.pull_queue(p["path"], p["offset"],
                                       limit=p.get("limit"))),
        _d("compact_table", ("path",), (), True,
           lambda cl, p: cl.compact_table(p["path"])),
        _d("collect_garbage", (), (), True,
           lambda cl, p: cl.collect_garbage()),
        # operations
        _d("sort", ("input_table_path", "output_table_path", "sort_by"), (),
           True,
           lambda cl, p: cl.run_sort(p["input_table_path"],
                                     p["output_table_path"],
                                     p["sort_by"]).id),
        _d("merge", ("input_table_paths", "output_table_path"), ("mode",),
           True,
           lambda cl, p: cl.run_merge(p["input_table_paths"],
                                      p["output_table_path"],
                                      mode=p.get("mode", "unordered")).id),
        _d("erase", ("table_path",), (), True,
           lambda cl, p: cl.run_erase(p["table_path"]).id),
        _d("map", ("command", "input_table_path", "output_table_path"),
           ("format", "pool", "job_count", "ordered"), True,
           lambda cl, p: cl.run_map(
               p["command"], p["input_table_path"],
               p["output_table_path"],
               **{k: p[k] for k in ("format", "pool", "job_count",
                                    "ordered") if k in p}).id),
        _d("get_operation", ("operation_id",), (), False,
           lambda cl, p: (lambda op: {"id": op.id, "state": op.state,
                                      "type": op.type})(
               cl.scheduler.get_operation(p["operation_id"]))),
        # queue consumers (ref queue_client + queue_agent verbs)
        _d("register_queue_consumer", ("queue_path", "consumer_path"),
           ("vital",), True,
           lambda cl, p: cl.register_queue_consumer(
               p["queue_path"], p["consumer_path"],
               vital=p.get("vital", True))),
        _d("unregister_queue_consumer", ("queue_path", "consumer_path"), (),
           True,
           lambda cl, p: cl.unregister_queue_consumer(
               p["queue_path"], p["consumer_path"])),
        _d("advance_consumer", ("consumer_path", "queue_path", "new_offset"),
           ("old_offset",), True,
           lambda cl, p: cl.advance_consumer(
               p["consumer_path"], p["queue_path"], p["new_offset"],
               old_offset=p.get("old_offset"))),
        _d("pull_consumer", ("consumer_path", "queue_path"), ("limit",),
           False,
           lambda cl, p: (lambda rows, off: {"rows": rows,
                                             "next_offset": off})(
               *cl.pull_consumer(p["consumer_path"], p["queue_path"],
                                 limit=p.get("limit")))),
        # materialized views (ISSUE 13: continuous queries)
        _d("create_materialized_view", ("name", "query"),
           ("source", "target", "pool", "batch_rows"), True,
           lambda cl, p: cl.create_materialized_view(
               p["name"], p["query"], source=p.get("source"),
               target=p.get("target"), pool=p.get("pool", "views"),
               batch_rows=p.get("batch_rows"))),
        _d("list_views", (), (), False, lambda cl, p: cl.list_views()),
        _d("get_view", ("name",), (), False,
           lambda cl, p: cl.get_view(p["name"])),
        _d("pause_view", ("name",), (), True,
           lambda cl, p: cl.pause_view(p["name"])),
        _d("resume_view", ("name",), (), True,
           lambda cl, p: cl.resume_view(p["name"])),
        _d("remove_view", ("name",), ("drop_target",), True,
           lambda cl, p: cl.remove_view(
               p["name"], drop_target=p.get("drop_target", False))),
        _d("refresh_view", ("name",), ("max_batches",), True,
           lambda cl, p: cl.refresh_view(
               p["name"], max_batches=p.get("max_batches", 0))),
        # query tracker (ref server/query_tracker verbs)
        _d("start_query", ("query",), ("engine", "annotations"), True,
           lambda cl, p: cl.query_tracker.start_query(
               p["query"], engine=p.get("engine", "ql"),
               annotations=p.get("annotations"))),
        _d("get_query", ("query_id",), (), False,
           lambda cl, p: cl.query_tracker.get_query(p["query_id"])),
        _d("list_queries", (), ("state", "engine"), False,
           lambda cl, p: cl.query_tracker.list_queries(
               state=p.get("state"), engine=p.get("engine"))),
        _d("read_query_result", ("query_id",), (), False,
           lambda cl, p: cl.query_tracker.read_query_result(p["query_id"])),
        _d("abort_query", ("query_id",), (), True,
           lambda cl, p: cl.query_tracker.abort_query(p["query_id"])),
    ]:
        c[d.name] = d
    return c


COMMANDS = _registry()


class Driver:
    """Executes named commands against a client (ref IDriver::Execute)."""

    def __init__(self, client):
        self.client = client

    def execute(self, command: str, parameters: Optional[dict] = None) -> Any:
        descriptor = COMMANDS.get(command)
        if descriptor is None:
            raise YtError(f"Unknown command {command!r}",
                          code=EErrorCode.Generic,
                          attributes={"available": sorted(COMMANDS)})
        parameters = dict(parameters or {})
        missing = [name for name in descriptor.required
                   if name not in parameters]
        if missing:
            raise YtError(
                f"Command {command!r} is missing parameters {missing}",
                code=EErrorCode.Generic)
        unknown = set(parameters) - set(descriptor.required) \
            - set(descriptor.optional)
        if unknown:
            raise YtError(
                f"Command {command!r} got unknown parameters "
                f"{sorted(unknown)}", code=EErrorCode.Generic)
        return descriptor.invoke(self.client, parameters)
