"""Encoded-plane kernel execution (ISSUE 19): the dual-check corpus.

String predicates, string GROUP BY, and string ORDER BY execute on
dict CODES (query/engine/expr.py `_bind_string_literal_cmp`); the
decoded remap-table path stays behind `encoded_predicates=False` as the
bit-identity oracle.  Every corpus leg here compares the encoded,
donation-armed engine (the shipping default) against the fully
conservative oracle (decoded predicates, donation off) and requires
EXACT row identity — values, validity, order where the query orders.

Corpus axes: dict-heavy (few words, many rows), null-heavy (70% null
strings), high-cardinality (~900 distinct values), and mixed-vocab
(two chunks with different vocabularies concatenated through
`unify_dictionaries`).  Legs: local evaluator, the interpreter tier,
and fused 8-device SPMD.  Satellite regressions ride along: the
("strlit", op, vocab-digest) compile-cache fragmentation note, the
identical-vocab `unify_dictionaries` fast path, and the sealed-layout
ORDER BY sort skip vs its unsealed oracle.
"""

import dataclasses

import numpy as np
import pytest

from ytsaurus_tpu import config as yt_config
from ytsaurus_tpu.chunks.columnar import (
    ColumnarChunk,
    concat_chunks,
    unify_dictionaries,
)
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.query.engine import interp, lowering
from ytsaurus_tpu.query.engine.evaluator import Evaluator
from ytsaurus_tpu.query.statistics import QueryStatistics
from ytsaurus_tpu.schema import EValueType, TableSchema

SCHEMA = TableSchema.make([("k", "int64"), ("v", "int64"),
                           ("s", "string")])

WORDS = [b"alpha", b"beta", b"gamma", b"delta", b"eps", b"zeta"]


@pytest.fixture(autouse=True)
def _fresh_compile_config():
    yield
    yt_config.set_compile_config(None)


def _rows(n, words, null_every=9, seed=3):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        s = None if (null_every and i % null_every == 0) \
            else words[int(rng.randint(0, len(words)))]
        out.append({"k": i, "v": int(rng.randint(-100, 100)), "s": s})
    return out


def _dict_heavy():
    return ColumnarChunk.from_rows(SCHEMA, _rows(3000, WORDS))


def _null_heavy():
    rng = np.random.RandomState(11)
    rows = []
    for i in range(1500):
        s = WORDS[int(rng.randint(0, len(WORDS)))] \
            if rng.randint(0, 10) >= 7 else None
        rows.append({"k": i, "v": int(rng.randint(0, 50)), "s": s})
    return ColumnarChunk.from_rows(SCHEMA, rows)


def _high_card():
    words = [f"u{i:04d}".encode() for i in range(900)] + [b"alpha"]
    return ColumnarChunk.from_rows(SCHEMA, _rows(1200, words, seed=5))


def _mixed_vocab():
    """Two chunks whose vocabularies only partially overlap; the concat
    runs them through `unify_dictionaries`, so codes here are POST-unify
    remaps — the leg that catches any stale-code pairing."""
    a = ColumnarChunk.from_rows(
        SCHEMA, _rows(800, [b"alpha", b"beta", b"mix_a"], seed=7))
    b = ColumnarChunk.from_rows(
        SCHEMA, _rows(800, [b"beta", b"gamma", b"mix_b"], seed=13))
    return concat_chunks([a, b])


TABLES = {
    "dict_heavy": _dict_heavy,
    "null_heavy": _null_heavy,
    "high_card": _high_card,
    "mixed_vocab": _mixed_vocab,
}

# Every encoded-plane shape: equality / inequality / IN (present and
# absent literals), order-preserving range compares, string GROUP BY,
# string ORDER BY, and an empty result off an absent literal.
CORPUS = [
    "k, s from t where s = 'alpha'",
    "k from t where s != 'beta'",
    "k, s from t where s in ('alpha', 'gamma', 'zzz')",
    "k from t where s > 'b'",
    "k, v from t where s between 'a' and 'bz'",
    "s, count(*) as c, sum(v) as sv from t group by s",
    "k, s from t order by s, k limit 50",
    "k from t where s = 'zzz'",
]

# Cheap subset for the expensive legs (interp is cheap but SPMD and the
# extra tables each pay full compiles).
CORPUS_QUICK = [CORPUS[0], CORPUS[2], CORPUS[5], CORPUS[6]]


def _canon(rows):
    def norm(v):
        return (0, b"") if v is None else (1, v)

    return sorted(tuple((k, norm(v)) for k, v in sorted(r.items()))
                  for r in rows)


def _run(query, chunk, *, encoded, donate):
    yt_config.set_compile_config(yt_config.CompileConfig(
        encoded_predicates=encoded, donate_buffers=donate))
    try:
        plan = build_query("select " + query, {"t": SCHEMA})
        stats = QueryStatistics()
        got = Evaluator().run_plan(plan, chunk, stats=stats)
        return plan, got.to_rows(), stats
    finally:
        yt_config.set_compile_config(None)


@pytest.mark.parametrize("table", sorted(TABLES))
def test_dual_check_local(table):
    """Encoded + donation-armed vs decoded + donation-off oracle: exact
    rows on every corpus query, positional where the query orders."""
    chunk = TABLES[table]()
    queries = CORPUS if table in ("dict_heavy", "mixed_vocab") \
        else CORPUS_QUICK
    for query in queries:
        plan, got, _ = _run(query, chunk, encoded=True, donate=True)
        _, want, _ = _run(query, chunk, encoded=False, donate=False)
        if plan.order is not None:
            assert got == want, query
        assert _canon(got) == _canon(want), query


def _decode(planes, count, output):
    """Planes -> row tuples, None for invalid slots (the tier-agnostic
    comparison form, same as test_tiering)."""
    cols = []
    for (d, v), out in zip(planes, output):
        d, v = np.asarray(d), np.asarray(v)
        vals = []
        for i in range(count):
            if not v[i]:
                vals.append(None)
            elif out.type is EValueType.string:
                vals.append(bytes(out.vocab[int(d[i])]))
            elif out.type is EValueType.boolean:
                vals.append(bool(d[i]))
            elif out.type is EValueType.double:
                vals.append(float(d[i]))
            else:
                vals.append(int(d[i]))
        cols.append(vals)
    return list(zip(*cols)) if cols else []


@pytest.mark.parametrize("table", sorted(TABLES))
def test_dual_check_interp_tier(table):
    """The interpreter tier's numpy twin of the code-space compare must
    stay bit-identical to the compiled encoded path — tier promotion
    mid-stream must never change a query's answer."""
    chunk = TABLES[table]()
    for query in CORPUS:
        plan = build_query("select " + query, {"t": SCHEMA})
        if not interp.covers(plan):
            continue
        iq = interp.try_prepare(plan, chunk)
        assert iq is not None, query
        planes_i, count_i = iq.execute(chunk)
        prepared = lowering.prepare(plan, chunk)
        columns = {name: (col.data, col.valid)
                   for name, col in chunk.columns.items()}
        planes_c, count_c = prepared.run(columns, chunk.row_valid,
                                         tuple(prepared.bindings))
        assert _decode(planes_i, count_i, iq.output) == \
            _decode(planes_c, int(count_c), prepared.output), query


def test_dual_check_spmd(mesh8):
    """Fused 8-device SPMD with per-shard vocab skew (distributed unify)
    vs the decoded local oracle."""
    from ytsaurus_tpu.parallel.distributed import ShardedTable
    from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
    from ytsaurus_tpu.parallel.distributed import DistributedEvaluator
    rng = np.random.RandomState(17)
    chunks = []
    for sh in range(8):
        words = WORDS[sh % 3:] + [f"shard{sh}".encode()]
        rows = [{"k": sh * 1000 + i, "v": int(rng.randint(0, 500)),
                 "s": words[int(rng.randint(0, len(words)))]}
                for i in range(120 + sh * 7)]
        chunks.append(ColumnarChunk.from_rows(SCHEMA, rows))
    table = ShardedTable.from_chunks(mesh8, chunks)
    merged = concat_chunks(chunks)
    de = DistributedEvaluator(mesh8)
    for query in ["s, count(*) as c, sum(v) as sv from [//t] "
                  "group by s order by s limit 100",
                  "k, s from [//t] where s in ('alpha', 'shard3')"]:
        plan = build_query("select " + query, {"//t": SCHEMA})
        got = run_whole_plan(de, plan, table)
        yt_config.set_compile_config(yt_config.CompileConfig(
            encoded_predicates=False, donate_buffers=False))
        try:
            want = Evaluator().run_plan(plan, merged)
        finally:
            yt_config.set_compile_config(None)
        assert _canon(got.to_rows()) == _canon(want.to_rows()), query


# -- satellite regressions -----------------------------------------------------

def test_strlit_note_fragments_compile_cache():
    """Satellite 2: the bound code is only meaningful against one vocab
    generation, so the vocab content digest must fold into
    structure_key — two content-distinct vocabs may never share a cached
    program for the same query text."""
    plan = build_query("select k from t where s = 'alpha'",
                       {"t": SCHEMA})
    chunk_a = TABLES["dict_heavy"]()
    chunk_b = ColumnarChunk.from_rows(
        SCHEMA, _rows(3000, [b"alpha", b"other"]))

    def strlit_notes(prepared):
        def walk(node):
            if isinstance(node, tuple):
                if node[:1] == ("strlit",):
                    yield node
                for item in node:
                    yield from walk(item)
        return list(walk(prepared.structure_key))

    notes_a = strlit_notes(lowering.prepare(plan, chunk_a))
    notes_b = strlit_notes(lowering.prepare(plan, chunk_b))
    assert notes_a and notes_b
    assert notes_a != notes_b                     # digest fragments
    # Content-identical vocab in a DIFFERENT array object: same key
    # (the digest is content-addressed, not identity-addressed).
    chunk_a2 = ColumnarChunk.from_rows(SCHEMA, _rows(3000, WORDS))
    assert strlit_notes(lowering.prepare(plan, chunk_a2)) == notes_a


def test_unify_dictionaries_identity_fast_path():
    """Satellite 1: columns that already share one vocabulary (by
    identity, or by content in distinct arrays) come back untouched —
    no merged vocab, no device gathers."""
    chunk = TABLES["dict_heavy"]()
    col = chunk.columns["s"]
    out, vocab = unify_dictionaries([col, col])
    assert out[0] is col and out[1] is col
    assert [bytes(w) for w in vocab] == \
        [bytes(w) for w in col.dictionary]
    # Content-equal vocab in a different array object.
    col2 = dataclasses.replace(
        col, dictionary=np.array(list(col.dictionary), dtype=object))
    assert col2.dictionary is not col.dictionary
    out2, _ = unify_dictionaries([col, col2])
    assert out2[0] is col and out2[1] is col2
    # Different content still merges.
    other = ColumnarChunk.from_rows(
        SCHEMA, _rows(100, [b"alpha", b"qq"])).columns["s"]
    out3, vocab3 = unify_dictionaries([col, other])
    assert out3[0] is not col
    assert b"qq" in {bytes(w) for w in vocab3}


def test_kernel_sensors_and_explain_line():
    """Satellite 6: /query/kernels counters book per dispatch, the
    statistics carry execution_encoding, and EXPLAIN ANALYZE renders
    the `execution: encoded|decoded` line."""
    from ytsaurus_tpu.query.engine import evaluator as ev_mod
    from ytsaurus_tpu.query.profile import format_profile_dict
    chunk = TABLES["dict_heavy"]()
    e0 = ev_mod._encoded_scans_counter.get()
    d0 = ev_mod._decoded_fallbacks_counter.get()
    b0 = ev_mod._donated_buffers_counter.get()
    _, _, stats = _run("k from t where s = 'alpha'", chunk,
                       encoded=True, donate=True)
    assert ev_mod._encoded_scans_counter.get() == e0 + 1
    assert ev_mod._donated_buffers_counter.get() > b0
    assert stats.execution_encoding == "encoded"
    assert "execution: encoded" in \
        format_profile_dict({"statistics": stats.to_dict()})
    _, _, stats_d = _run("k from t where s = 'alpha'", chunk,
                         encoded=False, donate=False)
    assert ev_mod._decoded_fallbacks_counter.get() == d0 + 1
    assert stats_d.execution_encoding == "decoded"
    assert "execution: decoded" in \
        format_profile_dict({"statistics": stats_d.to_dict()})
    # Donation off: the arming counter stays put.
    b1 = ev_mod._donated_buffers_counter.get()
    _run("k from t where s = 'alpha'", chunk, encoded=True,
         donate=False)
    assert ev_mod._donated_buffers_counter.get() == b1


def test_sealed_layout_skips_order_by_sort():
    """Layout sealing: a chunk sealed `sorted_by=("k",)` compiles
    ORDER BY k with the packed-key sort elided (the ("presorted", n)
    structure note), and the skipped program returns exactly the rows
    the unsealed oracle sorts for — including with a WHERE interleaved
    (compact_mask is stable)."""
    rows = _rows(1000, WORDS)                      # k already ascending
    unsealed = ColumnarChunk.from_rows(SCHEMA, rows)
    sealed = dataclasses.replace(unsealed, sorted_by=("k",))

    def notes(prepared):
        return [t for t in prepared.structure_key
                if isinstance(t, tuple) and t[:1] == ("presorted",)]

    plan = build_query("select k, v, s from t order by k limit 40",
                       {"t": SCHEMA})
    assert notes(lowering.prepare(plan, sealed)) == [("presorted", 1)]
    assert notes(lowering.prepare(plan, unsealed)) == []
    # Descending, or a non-prefix column, must NOT skip.
    desc = build_query("select k from t order by k desc limit 4",
                       {"t": SCHEMA})
    assert notes(lowering.prepare(desc, sealed)) == []
    off_key = build_query("select k from t order by v limit 4",
                          {"t": SCHEMA})
    assert notes(lowering.prepare(off_key, sealed)) == []

    for query in ["select k, v, s from t order by k limit 40",
                  "select k, s from t where s != 'beta' and v > -50 "
                  "order by k limit 35"]:
        qplan = build_query(query, {"t": SCHEMA})
        got = Evaluator().run_plan(qplan, sealed).to_rows()
        want = Evaluator().run_plan(qplan, unsealed).to_rows()
        assert got == want, query
